"""Block-table-aware paged attention — the fourth op class.

The serving engine's paged KV cache (``serving/kvcache.py``) stores
each slot's KV as a chain of physical blocks named by a block table;
until this op class existed every decode step materialized the full
logical view ``pool[table] -> [S, T, h, dh]`` through ``decode_gather``
before attention ran, so HBM traffic and peak memory scaled with the
padded table capacity ``T``, not the tokens actually live in a chain.
``paged_attention`` attends THROUGH the table instead: online-softmax
block by block, one physical block (or a small group) in flight at a
time, the gathered view never built.

Calling convention (all backends)::

    call(q, pool_k, pool_v, table, pos, block_step=None,
         interpret=None) -> ctx

    q       [S, W, h, dh]   query window (W=1 for plain decode,
                            W=k+1 for the speculative verify window)
    pool_k  [num_blocks, B, h, dh]   the physical K pool (one layer)
    pool_v  [num_blocks, B, h, dh]   the physical V pool
    table   [S, NB] int32   per-slot block chain (block 0 = trash)
    pos     [S, W]  int32   absolute position of each query; key token
                            ``j`` participates iff ``j <= pos`` — the
                            same write-before-attend mask the gather
                            spelling applies, so trash-block garbage,
                            bucket padding and CoW tails all carry
                            exactly zero attention weight
    ctx     [S, W, h, dh]   in ``q.dtype``

Numerics conventions match the flash kernels (f32 scores via
``preferred_element_type``, ``NEG_INF`` masking, f32 ``(m, l, acc)``
online-softmax state, one normalization at the end with the
``l == 0 -> 1`` guard, output cast to the input dtype).  The blocked
reassociation means results differ from the dense gather+softmax
spelling within ``ORACLE_TOL["paged_attention", ...]``; within one
backend the op is bit-exact run to run.  Token position ``nb*B + b``
of slot ``s`` lives at ``(table[s, nb], b)`` — block 0 never needs
zeroing because its token positions in an unused table entry are
always ``> pos``.

Backends:

* ``xla_ref`` — a ``lax.scan`` over table entries, gathering
  ``block_step`` physical blocks per step (``[S, block_step*B, h,
  dh]`` in flight — the tuned block-iteration geometry,
  ``tune.paged_attention_config``).  The universal numerics reference.
* ``pallas_tpu`` — ``PrefetchScalarGridSpec`` scalar prefetch (the
  ``pallas_gather.py`` spelling): the table feeds the K/V BlockSpec
  index maps, so each sequential grid step DMAs exactly one physical
  block into VMEM while ``(m, l, acc)`` carry in VMEM scratch.
  Registered available on real TPU only (off-TPU the interpret-mode
  grid would replace one fused XLA loop with a per-block Python loop);
  the oracle suite still covers the kernel logic on CPU by forcing
  ``interpret=True``.
* ``triton`` — the GPU decomposition of ``triton_attention.py``: a
  parallel grid over independent slots, the block-chain reduction as a
  ``lax.fori_loop`` inside the kernel with ``pl.load`` +
  ``pl.dslice`` dynamic block fetches.  Interpret-verified on CPU.

``serving/batched_decode.py`` routes here when ``PADDLE_TPU_PAGED_ATTN``
is on (the default); ``=0`` restores the gather+flash spelling
bit-exact (docs/serving.md "Paged KV cache").
"""

import jax
import jax.numpy as jnp

from .registry import register_kernel
from .triton_attention import _default_interpret, _gpu_available
from .xla_ref import NEG_INF


def _normalize_block_step(block_step, nb, w=1):
    if block_step is None:
        # measured default (tune_paged_attention owns the per-workload
        # override): single-token decode (W=1) is fastest streaming one
        # block per step and that is also where the memory win lives;
        # multi-token windows (the speculative verify, W=k+1) pay the
        # scan's sequential dispatch W times over and win by consuming
        # the whole chain in one wide step instead
        block_step = 1 if w == 1 else nb
    return max(1, min(int(block_step), nb))


# -- xla_ref: the block-scan oracle ------------------------------------------

def paged_attention_ref(q, pool_k, pool_v, table, pos, block_step=None,
                        interpret=None):
    """The oracle spelling: ``lax.scan`` over the block chain with
    online-softmax carry — per step only ``block_step`` physical blocks
    are gathered (``[S, block_step*B, h, dh]``), never the ``T``-wide
    view.  ``interpret`` is accepted for signature parity and ignored
    (no Pallas here)."""
    del interpret
    S, W, h, dh = q.shape
    B = pool_k.shape[1]
    NB = table.shape[1]
    T = NB * B
    bs = _normalize_block_step(block_step, NB, W)
    pad = (-NB) % bs
    tbl = table.astype(jnp.int32)
    if pad:
        # pad the chain with trash-block entries; their token positions
        # (>= T) are unconditionally masked below
        tbl = jnp.concatenate(
            [tbl, jnp.zeros((S, pad), jnp.int32)], axis=1)
    scale = 1.0 / float(dh) ** 0.5
    off = jnp.arange(bs * B, dtype=jnp.int32)

    if (NB + pad) // bs == 1:
        # one step consumes the whole chain: skip the scan and its
        # renormalization carry — a single masked softmax over the
        # one gathered [S, bs*B, h, dh] group (same NEG_INF masking,
        # same l==0 guard; this is what the scan would compute, minus
        # the dead alpha/acc-renorm work of a length-1 carry)
        kb = pool_k[tbl].reshape(S, (NB + pad) * B, h, dh)
        vb = pool_v[tbl].reshape(S, (NB + pad) * B, h, dh)
        s = jnp.einsum("swhd,sthd->swht", q, kb,
                       preferred_element_type=jnp.float32) * scale
        keep = ((off[None, None, None, :] <= pos[:, :, None, None])
                & (off < T)[None, None, None, :])
        s = jnp.where(keep, s, NEG_INF)
        p = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
        l = jnp.sum(p, axis=-1)
        l_safe = jnp.where(l == 0.0, 1.0, l)
        ctx = jnp.einsum("swht,sthd->swhd", p, vb.astype(jnp.float32))
        return (ctx / l_safe[..., None]).astype(q.dtype)

    def step(carry, i):
        m, l, acc = carry
        blk = jax.lax.dynamic_slice_in_dim(tbl, i * bs, bs, 1)  # [S, bs]
        kb = pool_k[blk].reshape(S, bs * B, h, dh)
        vb = pool_v[blk].reshape(S, bs * B, h, dh)
        tok = i * (bs * B) + off                                # [bs*B]
        s = jnp.einsum("swhd,sthd->swht", q, kb,
                       preferred_element_type=jnp.float32) * scale
        keep = ((tok[None, None, None, :] <= pos[:, :, None, None])
                & (tok < T)[None, None, None, :])
        s = jnp.where(keep, s, NEG_INF)
        m2 = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m2)
        p = jnp.exp(s - m2[..., None])
        l2 = l * alpha + jnp.sum(p, axis=-1)
        acc2 = acc * alpha[..., None] + jnp.einsum(
            "swht,sthd->swhd", p, vb.astype(jnp.float32))
        return (m2, l2, acc2), None

    m0 = jnp.full((S, W, h), NEG_INF, jnp.float32)
    l0 = jnp.zeros((S, W, h), jnp.float32)
    a0 = jnp.zeros((S, W, h, dh), jnp.float32)
    nsteps = (NB + pad) // bs
    (m, l, acc), _ = jax.lax.scan(
        step, (m0, l0, a0), jnp.arange(nsteps, dtype=jnp.int32))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    return (acc / l_safe[..., None]).astype(q.dtype)


# -- pallas_tpu: scalar-prefetch block streaming -----------------------------

def paged_attention_pallas(q, pool_k, pool_v, table, pos, block_step=None,
                           interpret=None):
    """``PrefetchScalarGridSpec`` kernel: grid ``(S, NB)``, the block
    TABLE is the scalar-prefetch argument consumed by the K/V BlockSpec
    index maps, so grid step ``(s, nb)`` streams physical block
    ``table[s, nb]`` into VMEM.  TPU grids run sequentially, so the
    online-softmax state carries across ``nb`` steps in VMEM scratch
    and the output writes once at the last step.  ``block_step`` is
    accepted for signature parity and ignored — this spelling streams
    exactly one block per grid step by construction."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    del block_step
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    S, W, h, dh = q.shape
    B = pool_k.shape[1]
    NB = table.shape[1]
    T = NB * B
    scale = 1.0 / float(dh) ** 0.5

    def kernel(tbl, q_ref, k_ref, v_ref, pos_ref, o_ref,
               m_ref, l_ref, acc_ref):
        del tbl  # consumed by the index maps, not the body
        nb = pl.program_id(1)

        @pl.when(nb == 0)
        def _init():
            m_ref[...] = jnp.full_like(m_ref, NEG_INF)
            l_ref[...] = jnp.zeros_like(l_ref)
            acc_ref[...] = jnp.zeros_like(acc_ref)

        qw = q_ref[0]                                      # [W, h, dh]
        kb = k_ref[0]                                      # [B, h, dh]
        vb = v_ref[0]
        pw = pos_ref[0]                                    # [W]
        s = jnp.einsum("whd,bhd->whb", qw, kb,
                       preferred_element_type=jnp.float32) * scale
        tok = nb * B + jax.lax.broadcasted_iota(jnp.int32, (1, 1, B), 2)
        keep = tok <= pw[:, None, None]
        s = jnp.where(keep, s, NEG_INF)
        m = m_ref[...]
        m2 = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m2)
        p = jnp.exp(s - m2[..., None])
        m_ref[...] = m2
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1)
        acc_ref[...] = acc_ref[...] * alpha[..., None] + jnp.einsum(
            "whb,bhd->whd", p, vb.astype(jnp.float32))

        @pl.when(nb == NB - 1)
        def _finish():
            l = l_ref[...]
            l_safe = jnp.where(l == 0.0, 1.0, l)
            o_ref[...] = (acc_ref[...]
                          / l_safe[..., None]).astype(o_ref.dtype)[None]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(S, NB),
        in_specs=[
            pl.BlockSpec((1, W, h, dh), lambda s, nb, tbl: (s, 0, 0, 0)),
            pl.BlockSpec((1, B, h, dh),
                         lambda s, nb, tbl: (tbl[s, nb], 0, 0, 0)),
            pl.BlockSpec((1, B, h, dh),
                         lambda s, nb, tbl: (tbl[s, nb], 0, 0, 0)),
            pl.BlockSpec((1, W), lambda s, nb, tbl: (s, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, W, h, dh), lambda s, nb, tbl: (s, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((W, h), jnp.float32),
            pltpu.VMEM((W, h), jnp.float32),
            pltpu.VMEM((W, h, dh), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, W, h, dh), q.dtype),
        compiler_params=dict(
            mosaic=dict(dimension_semantics=("arbitrary", "arbitrary"))),
        interpret=bool(interpret),
    )(table.astype(jnp.int32), q, pool_k, pool_v, pos.astype(jnp.int32))


def _tpu_available():
    try:
        backend = jax.default_backend()
    except Exception as e:  # noqa: BLE001
        return False, f"jax backend probe failed: {e}"
    if backend == "tpu":
        return True, ""
    return False, (f"not on TPU (platform {backend!r}); the block-scan "
                   f"XLA oracle is the efficient spelling here")


# -- triton: parallel slots, fori_loop block chain ---------------------------

def paged_attention_triton(q, pool_k, pool_v, table, pos, block_step=None,
                           interpret=None):
    """GPU-style decomposition (``triton_attention.py`` structure): the
    grid covers only independent cells (one slot each — slots share
    nothing), and the block-chain reduction runs INSIDE the kernel as a
    ``lax.fori_loop`` whose body ``pl.load``s the physical block the
    table names via a dynamic ``pl.dslice``.  ``block_step`` is
    accepted for signature parity and ignored — the loop consumes one
    physical block per iteration."""
    import jax.experimental.pallas as pl

    del block_step
    interpret = _default_interpret(interpret)
    S, W, h, dh = q.shape
    num_blocks, B = pool_k.shape[0], pool_k.shape[1]
    NB = table.shape[1]
    scale = 1.0 / float(dh) ** 0.5

    def kernel(q_ref, k_ref, v_ref, tbl_ref, pos_ref, o_ref):
        qw = q_ref[0]                                      # [W, h, dh]
        pw = pos_ref[0]                                    # [W]

        def body(nb, carry):
            m, l, acc = carry
            blk = pl.load(tbl_ref, (pl.dslice(0, 1),
                                    pl.dslice(nb, 1)))[0, 0]
            kb = pl.load(k_ref, (pl.dslice(blk, 1), slice(None),
                                 slice(None), slice(None)))[0]
            vb = pl.load(v_ref, (pl.dslice(blk, 1), slice(None),
                                 slice(None), slice(None)))[0]
            s = jnp.einsum("whd,bhd->whb", qw, kb,
                           preferred_element_type=jnp.float32) * scale
            tok = nb * B + jax.lax.broadcasted_iota(
                jnp.int32, (1, 1, B), 2)
            s = jnp.where(tok <= pw[:, None, None], s, NEG_INF)
            m2 = jnp.maximum(m, jnp.max(s, axis=-1))
            alpha = jnp.exp(m - m2)
            p = jnp.exp(s - m2[..., None])
            l2 = l * alpha + jnp.sum(p, axis=-1)
            acc2 = acc * alpha[..., None] + jnp.einsum(
                "whb,bhd->whd", p, vb.astype(jnp.float32))
            return m2, l2, acc2

        m0 = jnp.full((W, h), NEG_INF, jnp.float32)
        l0 = jnp.zeros((W, h), jnp.float32)
        a0 = jnp.zeros((W, h, dh), jnp.float32)
        m, l, acc = jax.lax.fori_loop(0, NB, body, (m0, l0, a0))
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[...] = (acc / l_safe[..., None]).astype(o_ref.dtype)[None]

    return pl.pallas_call(
        kernel,
        grid=(S,),
        in_specs=[
            pl.BlockSpec((1, W, h, dh), lambda s: (s, 0, 0, 0)),
            pl.BlockSpec((num_blocks, B, h, dh), lambda s: (0, 0, 0, 0)),
            pl.BlockSpec((num_blocks, B, h, dh), lambda s: (0, 0, 0, 0)),
            pl.BlockSpec((1, NB), lambda s: (s, 0)),
            pl.BlockSpec((1, W), lambda s: (s, 0)),
        ],
        out_specs=pl.BlockSpec((1, W, h, dh), lambda s: (s, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((S, W, h, dh), q.dtype),
        interpret=bool(interpret),
    )(q, pool_k, pool_v, table.astype(jnp.int32), pos.astype(jnp.int32))


# -- registration ------------------------------------------------------------

class _PagedXlaRef:
    call = staticmethod(paged_attention_ref)


class _PagedPallasTpu:
    call = staticmethod(paged_attention_pallas)


class _PagedTriton:
    call = staticmethod(paged_attention_triton)


register_kernel("paged_attention", "xla_ref", _PagedXlaRef)
register_kernel("paged_attention", "pallas_tpu", _PagedPallasTpu,
                available=_tpu_available)
register_kernel("paged_attention", "triton", _PagedTriton,
                available=_gpu_available)
