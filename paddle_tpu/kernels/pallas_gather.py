"""Paged decode gather as a Pallas TPU kernel — the ``pallas_tpu``
backend for the ``decode_gather`` op class.

The serving engine's decode step gathers each slot's logical KV
sequence through its block table: ``pool[table]`` (see
``serving/batched_decode.py``).  On CPU/GPU that advanced-indexing
spelling lowers to an efficient XLA gather (the ``xla_ref`` backend);
on TPU a row gather lowers poorly — the TPU-native spelling is a
``PrefetchScalarGridSpec`` kernel where the block TABLE is a scalar-
prefetch argument consumed by the input BlockSpec's index map, so each
grid cell's DMA fetches exactly the physical block the table names
(pallas_guide.md "PrefetchScalarGridSpec").  The kernel body is a pure
copy: a gather moves bits, it does not compute, so this backend is
BIT-EXACT vs the oracle in every dtype (``ORACLE_TOL`` pins 0.0).

Registered available only on real TPU — off-TPU the interpret-mode
kernel would replace one fast XLA gather with a slow per-block Python
loop; the oracle suite still exercises the kernel logic on CPU by
forcing ``interpret=True`` directly."""

import jax
import jax.numpy as jnp

from .registry import register_kernel


def decode_gather(pool, table, interpret=None):
    """``pool [num_blocks, B, h, dh]``, ``table [S, NB]`` int32 ->
    ``[S, NB*B, h, dh]``: slot ``s``'s logical view is the
    concatenation of its table's physical blocks."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    S, NB = table.shape
    _, B, h, dh = pool.shape

    def kernel(tbl, in_ref, out_ref):
        del tbl  # consumed by the index maps, not the body
        out_ref[0, 0] = in_ref[0]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(S, NB),
        in_specs=[pl.BlockSpec(
            (1, B, h, dh), lambda s, nb, tbl: (tbl[s, nb], 0, 0, 0))],
        out_specs=pl.BlockSpec(
            (1, 1, B, h, dh), lambda s, nb, tbl: (s, nb, 0, 0, 0)),
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, NB, B, h, dh), pool.dtype),
        interpret=bool(interpret),
    )(table.astype(jnp.int32), pool)
    return out.reshape(S, NB * B, h, dh)


def _tpu_available():
    try:
        backend = jax.default_backend()
    except Exception as e:  # noqa: BLE001
        return False, f"jax backend probe failed: {e}"
    if backend == "tpu":
        return True, ""
    return False, (f"not on TPU (platform {backend!r}); the XLA gather "
                   f"is the efficient spelling here")


class _GatherPallasTpu:
    call = staticmethod(decode_gather)


register_kernel("decode_gather", "pallas_tpu", _GatherPallasTpu,
                available=_tpu_available)
