"""Flash attention lowered GPU-style — the ``triton`` registry backend.

Same block schedule as the Mosaic kernels (``ops/pallas_attention.py``:
online softmax over k blocks, causal block skip, backward recomputed
from the narrow lse residual), re-lowered for the GPU execution model:

* TPU grids run SEQUENTIALLY, so the TPU kernels put the k axis in the
  grid and carry softmax state in VMEM scratch across grid steps.  GPU
  grids are PARALLEL — each program id is an independent CTA — so here
  the grid covers only independent work (one (batch*head, q-block) or
  (batch*head, k-block) cell) and the reduction loop runs INSIDE the
  kernel body (``lax.fori_loop`` with the online-softmax state as loop
  carry, k/v blocks loaded per iteration with ``pl.load`` +
  ``pl.dslice``).  This is the standard Triton flash decomposition
  (triton_guide.md), written as Pallas so jax's Triton backend lowers
  it on GPU and the interpreter runs the identical logic in CPU tests.
* Causal cells above the diagonal are skipped by bounding the loop
  (``hi = ceil(((j+1)*bq) / bk)`` clamped), and the iota mask runs on
  every visited block — per-sub-tile mask elision (the TPU DIAG_W
  machinery) buys little on GPU where the mask is a fused vector op.
* Backward = dq kernel (q-block grid, loop over k) + dk/dv kernel
  (k-block grid, loop over q); ``delta = rowsum(do*o)`` inside, the
  optional lse cotangent folded in exactly like the TPU kernels.

Layout: the packed-by-transpose core ``q/k/v [b*h, t, d]``; lse is the
2-D ``[b*h, t]`` f32 residual (same contract — ``FLASH_BWD_RESIDUALS``
— as the Mosaic kernels, so memory_optimize name policies treat both
identically).  Registered available only where a GPU backend exists;
CPU oracle tests run these kernels under ``interpret=True``.
"""

import functools

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from ..analysis.jaxpr_tools import KERNEL_RESIDUAL_TAG
from ..ops.pallas_attention import _pick_block
from .registry import register_kernel

NEG_INF = -1e30

# GPU SRAM is ~100x smaller than the problem; the canonical Triton
# flash tile is 64-128 square.  Caller block hints are honored but
# capped here — "the same block schedule" means the same loop
# structure and skip predicate, not the same 1024-wide VMEM tiles.
MAX_BLOCK = 128


def _blocks(t_q, t_k, block_q, block_k):
    bq = _pick_block(t_q, min(int(block_q or MAX_BLOCK), MAX_BLOCK))
    bk = _pick_block(t_k, min(int(block_k or MAX_BLOCK), MAX_BLOCK))
    return bq, bk


def _causal_hi(j, block_q, block_k, nk):
    """Number of k blocks a causal q block ``j`` touches (the TPU
    kernels' ``last_kb`` clamp, as a loop bound)."""
    return jnp.minimum(((j + 1) * block_q - 1) // block_k + 1, nk)


def _mask(s, q0, k0, wq, wk):
    q_pos = q0 + jax.lax.broadcasted_iota(jnp.int32, (wq, wk), 0)
    k_pos = k0 + jax.lax.broadcasted_iota(jnp.int32, (wq, wk), 1)
    return jnp.where(q_pos >= k_pos, s, NEG_INF)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale,
                causal, block_q, block_k, nk):
    import jax.experimental.pallas as pl

    j = pl.program_id(1)
    q = q_ref[0]                                        # [bq, d]
    d = q.shape[-1]

    def body(kb, carry):
        m, l, acc = carry
        cols = (pl.dslice(0, 1), pl.dslice(kb * block_k, block_k),
                slice(None))
        kb_t = pl.load(k_ref, cols)[0]
        vb_t = pl.load(v_ref, cols)[0]
        s = jax.lax.dot_general(
            q, kb_t, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _mask(s, j * block_q, kb * block_k, block_q, block_k)
        m2 = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m2)
        p = jnp.exp(s - m2[:, None])
        l2 = l * alpha + jnp.sum(p, axis=-1)
        acc2 = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(vb_t.dtype), vb_t, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m2, l2, acc2

    hi = _causal_hi(j, block_q, block_k, nk) if causal else nk
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, hi, body, (m0, l0, acc0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l_safe)


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    import jax.experimental.pallas as pl

    bh, t_q, d = q.shape
    t_k = k.shape[1]
    block_q, block_k = _blocks(t_q, t_k, block_q, block_k)
    nk = t_k // block_k
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, nk=nk),
        grid=(bh, t_q // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, t_k, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, t_k, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_q), lambda i, j: (i, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, t_q), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


def _bwd_dq_kernel(*refs, sm_scale, causal, block_q, block_k, nk,
                   has_dlse):
    import jax.experimental.pallas as pl

    if has_dlse:
        (q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dlse_ref,
         dq_ref) = refs
    else:
        q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dq_ref = refs
        dlse_ref = None
    j = pl.program_id(1)
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0]                                    # [bq]
    d = q.shape[-1]
    delta = jnp.sum(do.astype(jnp.float32) * o_ref[0].astype(jnp.float32),
                    axis=-1)
    if dlse_ref is not None:
        delta = delta - dlse_ref[0]

    def body(kb, dq):
        cols = (pl.dslice(0, 1), pl.dslice(kb * block_k, block_k),
                slice(None))
        kb_t = pl.load(k_ref, cols)[0]
        vb_t = pl.load(v_ref, cols)[0]
        s = jax.lax.dot_general(
            q, kb_t, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _mask(s, j * block_q, kb * block_k, block_q, block_k)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(
            do, vb_t, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None]) * sm_scale).astype(kb_t.dtype)
        return dq + jax.lax.dot_general(
            ds, kb_t, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    hi = _causal_hi(j, block_q, block_k, nk) if causal else nk
    dq = jax.lax.fori_loop(0, hi, body,
                           jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, sm_scale, causal, block_q, block_k, nq,
                    has_dlse):
    import jax.experimental.pallas as pl

    if has_dlse:
        (k_ref, v_ref, q_ref, do_ref, o_ref, lse_ref, dlse_ref,
         dk_ref, dv_ref) = refs
    else:
        (k_ref, v_ref, q_ref, do_ref, o_ref, lse_ref,
         dk_ref, dv_ref) = refs
        dlse_ref = None
    kb = pl.program_id(1)
    k = k_ref[0]
    v = v_ref[0]
    d = k.shape[-1]

    def body(jq, carry):
        dk, dv = carry
        rows = (pl.dslice(0, 1), pl.dslice(jq * block_q, block_q),
                slice(None))
        lrows = (pl.dslice(0, 1), pl.dslice(jq * block_q, block_q))
        qb = pl.load(q_ref, rows)[0]
        dob = pl.load(do_ref, rows)[0]
        ob = pl.load(o_ref, rows)[0]
        lse = pl.load(lse_ref, lrows)[0]
        delta = jnp.sum(
            dob.astype(jnp.float32) * ob.astype(jnp.float32), axis=-1)
        if dlse_ref is not None:
            delta = delta - pl.load(dlse_ref, lrows)[0]
        s = jax.lax.dot_general(
            qb, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _mask(s, jq * block_q, kb * block_k, block_q, block_k)
        p = jnp.exp(s - lse[:, None])
        dv2 = dv + jax.lax.dot_general(
            p.astype(dob.dtype), dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            dob, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None]) * sm_scale).astype(qb.dtype)
        dk2 = dk + jax.lax.dot_general(
            ds, qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk2, dv2

    # causal: q block jq touches k block kb iff its last row reaches the
    # block diagonal — start the loop there, skip the rest entirely
    lo = (kb * block_k) // block_q if causal else 0
    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(lo, nq, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, sm_scale, causal, block_q, block_k,
               interpret, dlse=None):
    import jax.experimental.pallas as pl

    bh, t_q, d = q.shape
    t_k = k.shape[1]
    block_q, block_k = _blocks(t_q, t_k, block_q, block_k)
    nq = t_q // block_q
    nk = t_k // block_k
    has_dlse = dlse is not None

    qspec = pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0))
    kfull = pl.BlockSpec((1, t_k, d), lambda i, j: (i, 0, 0))
    lspec = pl.BlockSpec((1, block_q), lambda i, j: (i, j))
    dq_specs = [qspec, kfull, kfull, qspec, qspec, lspec]
    dq_args = [q, k, v, do, o, lse]
    if has_dlse:
        dq_specs.append(lspec)
        dq_args.append(dlse)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale,
                          causal=causal, block_q=block_q,
                          block_k=block_k, nk=nk, has_dlse=has_dlse),
        grid=(bh, nq),
        in_specs=dq_specs,
        out_specs=[qspec],
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype)],
        interpret=interpret,
    )(*dq_args)[0]

    kspec = pl.BlockSpec((1, block_k, d), lambda i, kb: (i, kb, 0))
    qfull = pl.BlockSpec((1, t_q, d), lambda i, kb: (i, 0, 0))
    lfull = pl.BlockSpec((1, t_q), lambda i, kb: (i, 0))
    dkv_specs = [kspec, kspec, qfull, qfull, qfull, lfull]
    dkv_args = [k, v, q, do, o, lse]
    if has_dlse:
        dkv_specs.append(lfull)
        dkv_args.append(dlse)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale,
                          causal=causal, block_q=block_q,
                          block_k=block_k, nq=nq, has_dlse=has_dlse),
        grid=(bh, nk),
        in_specs=dkv_specs,
        out_specs=[kspec, kspec],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        interpret=interpret,
    )(*dkv_args)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _triton_core(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    o, _ = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k,
                      interpret)
    return o


def _triton_core_fwd(q, k, v, sm_scale, causal, block_q, block_k,
                     interpret):
    o, lse = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k,
                        interpret)
    # the FLASH_BWD_RESIDUALS contract, backend-invariant
    o = checkpoint_name(o, KERNEL_RESIDUAL_TAG)
    lse = checkpoint_name(lse, KERNEL_RESIDUAL_TAG)
    return o, (q, k, v, o, lse)


def _triton_core_bwd(sm_scale, causal, block_q, block_k, interpret, res,
                     do):
    q, k, v, o, lse = res
    return _flash_bwd(q, k, v, o, lse, do, sm_scale, causal, block_q,
                      block_k, interpret)


_triton_core.defvjp(_triton_core_fwd, _triton_core_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _triton_core_lse(q, k, v, sm_scale, causal, block_q, block_k,
                     interpret):
    return _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k,
                      interpret)


def _triton_core_lse_fwd(q, k, v, sm_scale, causal, block_q, block_k,
                         interpret):
    o, lse = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k,
                        interpret)
    o = checkpoint_name(o, KERNEL_RESIDUAL_TAG)
    lse = checkpoint_name(lse, KERNEL_RESIDUAL_TAG)
    return (o, lse), (q, k, v, o, lse)


def _triton_core_lse_bwd(sm_scale, causal, block_q, block_k, interpret,
                         res, cts):
    q, k, v, o, lse = res
    do, dlse = cts
    return _flash_bwd(q, k, v, o, lse, do, sm_scale, causal, block_q,
                      block_k, interpret,
                      dlse=dlse.astype(jnp.float32))


_triton_core_lse.defvjp(_triton_core_lse_fwd, _triton_core_lse_bwd)


def _default_interpret(interpret):
    if interpret is None:
        return jax.default_backend() not in ("gpu", "cuda", "rocm")
    return bool(interpret)


def flash_attention(q, k, v, causal=False, sm_scale=None, block_q=None,
                    block_k=None, interpret=None):
    """4-D entry (``[b, t, h, d]``): pack by transpose (cheap on GPU —
    a layout change, not the TPU's 8%-of-step tax) and run the core."""
    interpret = _default_interpret(interpret)
    b, t_q, h, d = q.shape
    t_k = k.shape[1]
    sm_scale = d ** -0.5 if sm_scale is None else sm_scale

    def pack(x, t):
        return jnp.swapaxes(x, 1, 2).reshape(b * h, t, x.shape[-1])

    o = _triton_core(pack(q, t_q), pack(k, t_k), pack(v, t_k),
                     float(sm_scale), bool(causal),
                     block_q and int(block_q), block_k and int(block_k),
                     interpret)
    return jnp.swapaxes(o.reshape(b, h, t_q, d), 1, 2)


def flash_attention_with_lse(q, k, v, causal=False, sm_scale=None,
                             block_q=None, block_k=None, interpret=None):
    interpret = _default_interpret(interpret)
    b, t_q, h, d = q.shape
    t_k = k.shape[1]
    sm_scale = d ** -0.5 if sm_scale is None else sm_scale

    def pack(x, t):
        return jnp.swapaxes(x, 1, 2).reshape(b * h, t, x.shape[-1])

    o, lse = _triton_core_lse(
        pack(q, t_q), pack(k, t_k), pack(v, t_k), float(sm_scale),
        bool(causal), block_q and int(block_q),
        block_k and int(block_k), interpret)
    return (jnp.swapaxes(o.reshape(b, h, t_q, d), 1, 2),
            lse.reshape(b, h, t_q))


def flash_attention_packed(q, k, v, n_head, causal=False, sm_scale=None,
                           block_q=None, block_k=None, interpret=None):
    """Packed layout ``[b, t, h*d]``: the head split is a reshape +
    transpose here (no Mosaic lane-slice constraint), so every head
    width is supported."""
    b, t, hd = q.shape
    if hd % n_head:
        raise ValueError(
            f"feature dim {hd} not divisible by n_head {n_head}")
    d = hd // n_head
    r4 = lambda x: x.reshape(b, x.shape[1], n_head, d)
    o = flash_attention(r4(q), r4(k), r4(v), causal=causal,
                        sm_scale=sm_scale, block_q=block_q,
                        block_k=block_k, interpret=interpret)
    return o.reshape(b, t, hd)


def _gpu_available():
    try:
        backend = jax.default_backend()
    except Exception as e:  # noqa: BLE001
        return False, f"jax backend probe failed: {e}"
    if backend in ("gpu", "cuda", "rocm"):
        return True, ""
    return False, (f"no GPU on this host (platform {backend!r}); "
                   f"CPU tests run these kernels with interpret=True")


class _FlashTriton:
    call = staticmethod(flash_attention)
    call_with_lse = staticmethod(flash_attention_with_lse)
    call_packed = staticmethod(flash_attention_packed)


register_kernel("flash_attention", "triton", _FlashTriton,
                available=_gpu_available)
