"""Multi-backend kernel registry (docs/kernels.md).

The paper's framework survived a hardware transition because op
SEMANTICS were separated from op IMPLEMENTATION — layer-graph ops were
re-lowered per device.  This module is that separation for the fused
kernels: each op CLASS (flash attention fwd/bwd, the fused CE/LSE head,
the paged serving decode gather) registers up to three backends and
every call site resolves through ONE selection path:

* ``pallas_tpu`` — the Mosaic kernels (``ops/pallas_attention.py``,
  ``ops/pallas_ce.py``).  Native on TPU; off-TPU they run in Pallas
  interpret mode (slow, but the exact kernel logic — the CPU test
  path).
* ``triton`` — the same block schedules lowered GPU-style
  (``kernels/triton_attention.py`` / ``triton_ce.py``: parallel grid
  over independent blocks, the reduction loop INSIDE the kernel body —
  TPU grids are sequential with carried scratch, GPU grids are not).
  Available only where a GPU exists; elsewhere it skips with a reason.
* ``xla_ref`` — the shape-complete pure-XLA reference
  (``kernels/xla_ref.py``): causal/non-causal, d_head 64/128, packed
  layouts, lse outputs, grads through the same custom-vjp algebra.
  Always available, and the universal numerics ORACLE every other
  backend is tested against (``tests/test_kernels.py``,
  ``python -m paddle_tpu --kernels-selftest``).

Selection precedence (the registry unit suite pins this):

1. explicit ``backend=`` argument at the call site (or a tuner-forced
   backend inside :func:`forced_backend`) — unknown raises
   ``ValueError``, registered-but-unavailable raises
   :class:`KernelUnavailable` with the reason;
2. per-op env ``PADDLE_TPU_KERNEL_BACKEND_<OP>`` (op class upper-cased,
   e.g. ``PADDLE_TPU_KERNEL_BACKEND_FLASH_ATTENTION=xla_ref``) — same
   strictness as an explicit argument;
3. global env ``PADDLE_TPU_KERNEL_BACKEND=auto|pallas_tpu|triton|
   xla_ref`` — unavailable/unregistered degrades to auto with the
   fallback counted (``kernels.env_fallbacks``) so a fleet-wide env pin
   never crashes the one op that lacks the backend;
4. ``auto`` — the per-platform preference order (:data:`AUTO_ORDER`):
   first registered AND available backend wins.

Every resolution is recorded (``selected_backends()``); the Executor
snapshots the record per compile into ``last_step_cost
["kernel_backends"]``, the attribution workload key gains a ``|kb=``
token, and bench rows / trainer JSONL carry it — tuner cache entries
and the learned-cost-model corpus are keyed by WHICH kernel ran, not
just the platform.
"""

import contextlib
import os
import threading

from ..observability import metrics as _obs

__all__ = [
    "BACKENDS", "AUTO_ORDER", "KernelUnavailable", "register_kernel",
    "get_kernel", "resolve", "resolve_name", "available_backends",
    "registered_op_classes", "selected_backends", "reset_selected",
    "forced_backend", "timed_run", "timed_run_active",
    "TIMED_RUN_ENV", "GLOBAL_ENV",
]

BACKENDS = ("pallas_tpu", "triton", "xla_ref")

GLOBAL_ENV = "PADDLE_TPU_KERNEL_BACKEND"
TIMED_RUN_ENV = "PADDLE_TPU_TIMED_RUN"

# per-platform auto preference.  CPU deliberately prefers the Mosaic
# kernels in interpret mode: a CPU process is a CI/test process and
# exercising the REAL kernel logic is the point (every pre-registry
# test ran this way).  Timed CPU runs are the exception — bench
# declares its flagship sections timed-run regions so interpret-mode
# kernels are flagged as a lint error on the row
# (jaxpr.kernel-backend); the operator routes such runs with
# PADDLE_TPU_KERNEL_BACKEND=xla_ref (docs/kernels.md).
AUTO_ORDER = {
    "tpu": ("pallas_tpu", "xla_ref"),
    "gpu": ("triton", "xla_ref"),
    "cuda": ("triton", "xla_ref"),
    "rocm": ("triton", "xla_ref"),
    "cpu": ("pallas_tpu", "xla_ref"),
}
_DEFAULT_ORDER = ("xla_ref",)


class KernelUnavailable(RuntimeError):
    """An explicitly requested backend is registered for the op class
    but not available on this host (e.g. ``triton`` with no GPU).
    ``.reason`` carries the availability probe's explanation — test
    suites turn it into a skip, resolution fallbacks record it."""

    def __init__(self, op_class, backend, reason):
        super().__init__(
            f"kernel backend {backend!r} for op {op_class!r} is "
            f"unavailable on this host: {reason}")
        self.op_class = op_class
        self.backend = backend
        self.reason = reason


class _Kernel:
    __slots__ = ("op_class", "backend", "impl", "_available")

    def __init__(self, op_class, backend, impl, available):
        self.op_class = op_class
        self.backend = backend
        self.impl = impl
        self._available = available

    def availability(self):
        """(ok, reason) — ``reason`` explains an unavailable backend or
        annotates an available one (e.g. "interpret mode off-TPU")."""
        if self._available is None:
            return True, ""
        try:
            out = self._available()
        except Exception as e:  # noqa: BLE001 — a probe crash = absent
            return False, f"availability probe failed: {e}"
        if isinstance(out, tuple):
            return bool(out[0]), str(out[1] or "")
        return bool(out), ""


_KERNELS = {}  # {op_class: {backend: _Kernel}}
_SELECTED = {}  # {op_class: backend} — most recent resolutions
_SEL_LOCK = threading.Lock()
_FORCED = []  # [(op_class_or_None, backend)] — tuner/test hook stack


def register_kernel(op_class, backend, impl, available=None):
    """Register ``impl`` (an opaque namespace of callables — each op
    class defines its own calling convention, see the op modules) as
    ``op_class``'s ``backend`` implementation.  ``available`` is an
    optional zero-arg probe returning ``bool`` or ``(bool, reason)``."""
    if backend not in BACKENDS:
        raise ValueError(
            f"unknown backend {backend!r} (valid: {BACKENDS})")
    per_op = _KERNELS.setdefault(op_class, {})
    if backend in per_op:
        raise ValueError(
            f"kernel {op_class!r}/{backend!r} registered twice")
    per_op[backend] = _Kernel(op_class, backend, impl, available)
    return impl


def registered_op_classes():
    return sorted(_KERNELS)


def get_kernel(op_class, backend):
    """The registered ``_Kernel`` or None (no resolution, no checks —
    introspection only)."""
    return _KERNELS.get(op_class, {}).get(backend)


def available_backends(op_class):
    """``[(backend, ok, reason)]`` for every registered backend of the
    op class, in ``BACKENDS`` order — the selftest/oracle enumeration."""
    per_op = _KERNELS.get(op_class, {})
    out = []
    for b in BACKENDS:
        k = per_op.get(b)
        if k is None:
            continue
        ok, reason = k.availability()
        out.append((b, ok, reason))
    return out


def _platform():
    try:
        import jax

        return jax.default_backend()
    except Exception:  # backendless callers (pure-unit tests)
        return "cpu"


def _env_value(op_class):
    """(value, source) from the env layers: per-op wins over global.
    Empty/unset values fall through; names are validated by resolve."""
    per_op = os.environ.get(
        f"{GLOBAL_ENV}_{op_class.upper()}", "").strip().lower()
    if per_op:
        return per_op, "env_op"
    glob = os.environ.get(GLOBAL_ENV, "").strip().lower()
    if glob:
        return glob, "env"
    return None, "auto"


def _auto_resolve(op_class, platform):
    order = AUTO_ORDER.get(platform, _DEFAULT_ORDER)
    per_op = _KERNELS.get(op_class, {})
    reasons = []
    for b in order:
        k = per_op.get(b)
        if k is None:
            reasons.append(f"{b}: not registered")
            continue
        ok, reason = k.availability()
        if ok:
            return k
        reasons.append(f"{b}: {reason or 'unavailable'}")
    raise KernelUnavailable(
        op_class, "auto",
        f"no backend available on platform {platform!r} "
        f"({'; '.join(reasons) or 'none registered'})")


def _validate(name):
    if name not in BACKENDS and name != "auto":
        raise ValueError(
            f"unknown kernel backend {name!r} (valid: auto, "
            f"{', '.join(BACKENDS)})")


def resolve(op_class, backend=None, platform=None):
    """Resolve the backend for one op-class call site at trace time.

    Returns the chosen ``_Kernel``.  Precedence: explicit ``backend``
    arg > tuner-forced > per-op env > global env > auto (see module
    docstring).  Explicit/per-op requests are strict (unknown
    raises ``ValueError``, unavailable raises
    :class:`KernelUnavailable`); a global-env or tuner-forced request
    that this op cannot serve degrades to auto with
    ``kernels.env_fallbacks`` counted.  The resolution is recorded in
    :func:`selected_backends`."""
    if op_class not in _KERNELS:
        raise KeyError(f"no kernels registered for op {op_class!r}")
    platform = platform or _platform()
    source = "arg"
    strict = True
    name = backend
    if name is None and _FORCED:
        for scope, forced in reversed(_FORCED):
            if scope is None or scope == op_class:
                name, source, strict = forced, "forced", False
                break
    if name is None:
        name, source = _env_value(op_class)
        strict = source == "env_op"
    if name is not None:
        name = str(name).strip().lower()
        _validate(name)
    if name is None or name == "auto":
        kernel = _auto_resolve(op_class, platform)
    else:
        kernel = _KERNELS[op_class].get(name)
        ok, reason = (kernel.availability() if kernel is not None
                      else (False, "not registered for this op"))
        if not ok:
            if strict:
                raise KernelUnavailable(op_class, name,
                                        reason or "unavailable")
            # non-strict sources (global env, tuned/forced configs)
            # degrade to auto: a fleet-wide pin must never crash the
            # one op that lacks the backend
            _obs.get_registry().counter(
                "kernels.env_fallbacks",
                help="kernel backend requests that fell back to auto "
                     "(requested backend unavailable for the op)").inc()
            kernel = _auto_resolve(op_class, platform)
    with _SEL_LOCK:
        _SELECTED[op_class] = kernel.backend
    _obs.get_registry().counter(
        "kernels.resolved",
        help="kernel registry resolutions (per traced call site)").inc()
    return kernel


def resolve_name(op_class, backend=None, platform=None):
    """:func:`resolve`, returning just the backend name."""
    return resolve(op_class, backend=backend, platform=platform).backend


def selected_backends():
    """Snapshot of the most recent resolution per op class — the
    Executor folds this into ``last_step_cost["kernel_backends"]`` per
    compile (it resets the record before tracing)."""
    with _SEL_LOCK:
        return dict(_SELECTED)


def reset_selected():
    with _SEL_LOCK:
        _SELECTED.clear()


@contextlib.contextmanager
def forced_backend(backend, op_class=None):
    """Force resolution to ``backend`` inside the context (all op
    classes, or one) — how the autotuner measures a backend candidate
    and how tests pin routing without env mutation.  Non-strict: an op
    the backend cannot serve falls back to auto (counted), so forcing
    ``triton`` on a CPU host measures what auto would actually run.
    Explicit ``backend=`` call-site arguments still win."""
    if backend is not None:
        _validate(str(backend).strip().lower())
    _FORCED.append((op_class, None if backend is None
                    else str(backend).strip().lower()))
    try:
        yield
    finally:
        _FORCED.pop()


def pallas_tpu_availability():
    """The shared availability probe of the Mosaic (``pallas_tpu``)
    kernel backends: native on TPU; AVAILABLE everywhere else too, in
    Pallas interpret mode (the CPU test path) — the reason string
    annotates the cost so timed runs know to route elsewhere."""
    try:
        import jax

        backend = jax.default_backend()
    except Exception as e:  # noqa: BLE001
        return False, f"jax backend probe failed: {e}"
    if backend == "tpu":
        return True, ""
    return True, (f"interpret mode on platform {backend!r} — exact "
                  f"kernel logic, orders of magnitude slower than "
                  f"hardware (timed runs should route xla_ref)")


def timed_run_active():
    """True inside a declared timed-run region — the
    ``jaxpr.kernel-backend`` analysis check only flags interpret-mode
    kernels there (a CPU test compile is SUPPOSED to interpret)."""
    return os.environ.get(TIMED_RUN_ENV, "").lower() in (
        "1", "true", "yes")


@contextlib.contextmanager
def timed_run():
    """Declare a timed-run region (bench.py wraps its flagship
    sections): compiles inside it lint interpret-mode Pallas kernels as
    errors — an interpreted kernel in a timed row is a benchmarking
    bug, not a measurement (docs/kernels.md)."""
    old = os.environ.get(TIMED_RUN_ENV)
    os.environ[TIMED_RUN_ENV] = "1"
    try:
        yield
    finally:
        if old is None:
            os.environ.pop(TIMED_RUN_ENV, None)
        else:
            os.environ[TIMED_RUN_ENV] = old
