"""``python -m paddle_tpu --kernels-selftest`` — the multi-backend
kernel registry's CI gate (tools/tier1.sh, docs/kernels.md).

What it proves on THIS host, accelerator or not:

1. registry resolution — every op class resolves under auto, the
   override precedence holds (explicit arg > per-op env > global env >
   auto), unknown backends raise, unavailable explicit backends raise
   with a reason, a global-env pin an op cannot serve degrades to auto;
2. oracle parity — every backend AVAILABLE here (plus the GPU/TPU
   kernels force-run in interpret mode, so the kernel logic itself is
   exercised even on a CPU-only host) matches the xla_ref oracle
   within the documented ``ORACLE_TOL`` bounds, f32 + bf16, causal +
   non-causal, d_head 64/128, grads through the custom-vjp — and is
   BIT-EXACT run-to-run within itself;
3. paged-attention parity — every runnable backend of the
   ``paged_attention`` op class (interpret-forced where unavailable)
   matches a dense gather+softmax reference within ``ORACLE_TOL``
   over ragged chains (fully-cached one-token prefill, a CoW fork,
   trash-block garbage), is bit-exact run-to-run, and the
   ``PADDLE_TPU_PAGED_ATTN`` kill switch provably toggles which
   spelling the serving decode chunk compiles;
4. the xla_ref acceptance bar — ``PADDLE_TPU_KERNEL_BACKEND=xla_ref``
   runs the full GPT trainer path under EVERY memory_optimize policy
   with ZERO Pallas calls in the traced jaxpr and a finite loss;
5. the timed-run lint — a timed-run region compiled with interpret-mode
   kernels plants a ``jaxpr.kernel-backend`` error and the same region
   routed to xla_ref compiles clean.
"""

import os

import numpy as np


def _rel_err(a, ref):
    import jax.numpy as jnp

    a = jnp.asarray(a, jnp.float32)
    ref = jnp.asarray(ref, jnp.float32)
    scale = float(jnp.max(jnp.abs(ref))) or 1.0
    return float(jnp.max(jnp.abs(a - ref))) / scale


def _check_registry(failures):
    import jax

    from . import (KernelUnavailable, available_backends, forced_backend,
                   registered_op_classes, resolve_name)

    ops = registered_op_classes()
    print(f"registry: op classes {ops} on platform "
          f"{jax.default_backend()!r}")
    if sorted(ops) != ["decode_gather", "flash_attention", "fused_ce",
                       "paged_attention"]:
        failures.append(f"unexpected op classes: {ops}")
    for op in ops:
        auto = resolve_name(op)
        rows = available_backends(op)
        print(f"  {op}: auto -> {auto}; "
              + "; ".join(f"{b}={'ok' if ok else 'SKIP'}"
                          + (f" ({r})" if r and not ok else "")
                          for b, ok, r in rows))
    # precedence: explicit arg wins over env
    os.environ["PADDLE_TPU_KERNEL_BACKEND"] = "xla_ref"
    try:
        if resolve_name("flash_attention") != "xla_ref":
            failures.append("global env did not route flash to xla_ref")
        if resolve_name("flash_attention", "pallas_tpu") != "pallas_tpu":
            failures.append("explicit arg did not beat global env")
        os.environ["PADDLE_TPU_KERNEL_BACKEND_FLASH_ATTENTION"] = \
            "pallas_tpu"
        if resolve_name("flash_attention") != "pallas_tpu":
            failures.append("per-op env did not beat global env")
        if resolve_name("fused_ce") != "xla_ref":
            failures.append("per-op env leaked across op classes")
    finally:
        os.environ.pop("PADDLE_TPU_KERNEL_BACKEND", None)
        os.environ.pop("PADDLE_TPU_KERNEL_BACKEND_FLASH_ATTENTION", None)
    # unknown raises
    try:
        resolve_name("flash_attention", "cuda_graphs")
        failures.append("unknown backend did not raise")
    except ValueError:
        pass
    # explicitly requesting an unavailable backend raises with a reason
    unavailable = [b for b, ok, _ in
                   available_backends("flash_attention") if not ok]
    for b in unavailable:
        try:
            resolve_name("flash_attention", b)
            failures.append(f"unavailable backend {b} did not raise")
        except KernelUnavailable as e:
            if not e.reason:
                failures.append(f"unavailable backend {b} has no reason")
    # a global-env pin an op cannot serve degrades to auto (triton has
    # no decode_gather registration anywhere)
    os.environ["PADDLE_TPU_KERNEL_BACKEND"] = "triton"
    try:
        name = resolve_name("decode_gather")
        if name not in ("pallas_tpu", "xla_ref"):
            failures.append(
                f"global-env fallback resolved decode_gather to {name}")
    finally:
        os.environ.pop("PADDLE_TPU_KERNEL_BACKEND", None)
    # the tuner's forced hook routes without env mutation
    with forced_backend("xla_ref"):
        if resolve_name("fused_ce") != "xla_ref":
            failures.append("forced_backend did not route fused_ce")
    print("registry precedence ok")


def _flash_impls():
    """(name, fn(q4, k4, v4, causal) -> o) for every backend whose
    kernel logic can run on this host — available ones as the registry
    would run them, plus interpret-forced Mosaic/triton kernels on
    hosts where they are 'unavailable' (the logic is still the thing
    under test)."""
    from . import available_backends, get_kernel

    avail = {b: ok for b, ok, _ in available_backends("flash_attention")}
    out = []
    for b, ok in avail.items():
        if b == "xla_ref":
            continue
        impl = get_kernel("flash_attention", b).impl
        # explicit 64-wide blocks: at the t=128 parity shapes the
        # default (1024-capped) blocks compile a degenerate
        # single-block kernel in which the cross-block online-softmax
        # carry — the thing under test — is dead code
        if ok:
            # off-TPU the available Mosaic backend IS interpret mode —
            # the kernel logic is what runs either way
            out.append((b, lambda q, k, v, c, i=impl: i.call(
                q, k, v, causal=c, block_q=64, block_k=64)))
        elif b == "triton":
            out.append((b + "(interpret)",
                        lambda q, k, v, c, i=impl: i.call(
                            q, k, v, causal=c, block_q=64, block_k=64,
                            interpret=True)))
    return out


def _check_oracle(failures):
    import jax
    import jax.numpy as jnp

    from . import get_kernel, oracle_tol

    oracle = get_kernel("flash_attention", "xla_ref").impl
    rng = np.random.default_rng(11)
    impls = _flash_impls()
    print(f"oracle parity (flash): backends "
          f"{[n for n, _ in impls]} vs xla_ref")
    for dt in (jnp.float32, jnp.bfloat16):
        dt_name = str(jnp.dtype(dt))
        for causal in (False, True):
            for d in (64, 128):
                b, t, h = 2, 128, 2
                q, k, v = (jnp.asarray(
                    rng.normal(size=(b, t, h, d)) * 0.5, dt)
                    for _ in range(3))
                ref = oracle.call(q, k, v, causal=causal)
                for name, fn in impls:
                    err = _rel_err(fn(q, k, v, causal), ref)
                    tol = oracle_tol("flash_attention", dt_name, "fwd")
                    if err > tol:
                        failures.append(
                            f"flash {name} {dt_name} causal={causal} "
                            f"d={d}: fwd err {err:.2e} > {tol}")
    # grads through the custom-vjp, f32 + bf16
    for dt in (jnp.float32, jnp.bfloat16):
        dt_name = str(jnp.dtype(dt))
        b, t, h, d = 1, 128, 2, 64
        q, k, v = (jnp.asarray(rng.normal(size=(b, t, h, d)) * 0.5, dt)
                   for _ in range(3))
        wgt = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)

        def make_loss(fn):
            return lambda q, k, v: jnp.sum(
                fn(q, k, v, True).astype(jnp.float32) * wgt)

        g_ref = jax.grad(make_loss(
            lambda q, k, v, c: oracle.call(q, k, v, causal=c)),
            (0, 1, 2))(q, k, v)
        for name, fn in impls:
            gs = jax.grad(make_loss(fn), (0, 1, 2))(q, k, v)
            tol = oracle_tol("flash_attention", dt_name, "grad")
            for which, a, r in zip("qkv", gs, g_ref):
                err = _rel_err(a, r)
                if err > tol:
                    failures.append(
                        f"flash {name} {dt_name} d{which}: grad err "
                        f"{err:.2e} > {tol}")
    print("flash parity ok")

    # fused CE: available backends + interpret-forced triton vs oracle
    from . import available_backends

    ce_oracle = get_kernel("fused_ce", "xla_ref").impl
    ce_impls = []
    for bk, ok, _ in available_backends("fused_ce"):
        if bk == "xla_ref":
            continue
        impl = get_kernel("fused_ce", bk).impl
        # explicit small blocks: the default caps would compile a
        # single-vocab-tile kernel at the parity shape — the online
        # carry across vocab tiles must actually run
        blks = dict(block_n=64, block_v=128, block_v_fwd=128)
        if ok:
            ce_impls.append((bk, lambda x, w, y, i=impl: i.call(
                x, w, y, **blks)))
        elif bk == "triton":
            ce_impls.append((bk + "(interpret)",
                             lambda x, w, y, i=impl: i.call(
                                 x, w, y, interpret=True, **blks)))
    for dt in (jnp.float32, jnp.bfloat16):
        dt_name = str(jnp.dtype(dt))
        n, dm, vocab = 128, 64, 512
        x = jnp.asarray(rng.normal(size=(n, dm)) * 0.3, dt)
        w = jnp.asarray(rng.normal(size=(dm, vocab)) * 0.05, dt)
        y = jnp.asarray(rng.integers(0, vocab, (n,)), jnp.int32)
        ref = ce_oracle.call(x, w, y)
        for name, fn in ce_impls:
            err = _rel_err(fn(x, w, y), ref)
            tol = oracle_tol("fused_ce", dt_name, "fwd")
            if err > tol:
                failures.append(f"ce {name} {dt_name}: fwd err "
                                f"{err:.2e} > {tol}")
        gvec = jnp.asarray(rng.normal(size=(n,)), jnp.float32)
        g_ref = jax.grad(lambda x, w: jnp.sum(
            ce_oracle.call(x, w, y) * gvec), (0, 1))(x, w)
        for name, fn in ce_impls:
            gs = jax.grad(lambda x, w, f=fn: jnp.sum(
                f(x, w, y) * gvec), (0, 1))(x, w)
            tol = oracle_tol("fused_ce", dt_name, "grad")
            for which, a, r in zip(("x", "w"), gs, g_ref):
                err = _rel_err(a, r)
                if err > tol:
                    failures.append(f"ce {name} {dt_name} d{which}: "
                                    f"grad err {err:.2e} > {tol}")
    print("ce parity ok")

    # decode gather: bit-exact in every dtype (it moves bits)
    from .pallas_gather import decode_gather as pallas_decode_gather

    gather_oracle = get_kernel("decode_gather", "xla_ref").impl
    pool = jnp.asarray(rng.normal(size=(7, 4, 2, 8)), jnp.float32)
    table = jnp.asarray(rng.integers(0, 7, (3, 5)), jnp.int32)
    ref = gather_oracle.call(pool, table)
    got = pallas_decode_gather(pool, table, interpret=True)
    if not bool(jnp.array_equal(ref, got)):
        failures.append("decode_gather pallas(interpret) not bit-exact")
    print("gather parity ok (bit-exact)")

    # run-to-run bit-exactness WITHIN a backend: one compiled fn, same
    # inputs, twice -> identical bits
    import jax as _jax

    q, k, v = (jnp.asarray(rng.normal(size=(1, 128, 2, 64)) * 0.5,
                           jnp.float32) for _ in range(3))
    for name, fn in impls + [("xla_ref", lambda q, k, v, c:
                              oracle.call(q, k, v, causal=c))]:
        jf = _jax.jit(lambda q, k, v, f=fn: f(q, k, v, True))
        a, b2 = jf(q, k, v), jf(q, k, v)
        if not bool(jnp.array_equal(a, b2)):
            failures.append(f"flash {name}: not bit-exact run-to-run")
    print("run-to-run bit-exactness ok")


def _paged_impls():
    """(name, fn(q, pk, pv, table, pos) -> ctx) for every backend whose
    paged-attention logic can run on this host — available ones as the
    registry would run them, plus the GPU/TPU kernels force-run in
    interpret mode (the blocked online-softmax logic is the thing under
    test, accelerator or not)."""
    from . import available_backends, get_kernel

    out = []
    for b, ok, _ in available_backends("paged_attention"):
        impl = get_kernel("paged_attention", b).impl
        if b == "xla_ref":
            # the oracle itself re-runs at several block_steps (None =
            # the W-aware default, including the one-step no-scan
            # path): the cross-block carry must not depend on the
            # iteration grouping
            for bs in (None, 1, 3):
                out.append((f"xla_ref(bs={bs or 'auto'})",
                            lambda q, k, v, t, p, i=impl, s=bs: i.call(
                                q, k, v, t, p, block_step=s)))
        elif ok:
            out.append((b, lambda q, k, v, t, p, i=impl: i.call(
                q, k, v, t, p)))
        else:
            out.append((b + "(interpret)",
                        lambda q, k, v, t, p, i=impl: i.call(
                            q, k, v, t, p, interpret=True)))
    return out


def _paged_dense_ref(q, pool_k, pool_v, table, pos):
    """The independent spelling the kernels must match: materialize the
    gathered [S, T, h, dh] view (exactly what the paged kernel exists
    to avoid), dense-mask past ``pos``, one softmax — all f32."""
    import jax.numpy as jnp

    from .xla_ref import NEG_INF, decode_gather

    S, NB = table.shape
    B = pool_k.shape[1]
    dh = q.shape[-1]
    kg = decode_gather(pool_k, table).astype(jnp.float32)
    vg = decode_gather(pool_v, table).astype(jnp.float32)
    s = jnp.einsum("swhd,sthd->swht", q.astype(jnp.float32), kg)
    s = s / jnp.sqrt(jnp.float32(dh))
    tok = jnp.arange(NB * B, dtype=jnp.int32)
    keep = tok[None, None, None, :] <= pos[:, :, None, None]
    s = jnp.where(keep, s, NEG_INF)
    p = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    return jnp.einsum("swht,sthd->swhd", p, vg).astype(q.dtype)


def _check_paged_oracle(failures):
    import jax
    import jax.numpy as jnp

    from . import oracle_tol

    rng = np.random.default_rng(23)
    impls = _paged_impls()
    print(f"oracle parity (paged attention): backends "
          f"{[n for n, _ in impls]} vs dense gather+softmax")
    S, NB, B, h, dh = 4, 3, 4, 2, 16
    num_blocks = 1 + S * NB
    # ragged chains: a full slot, a mid-chain decode, a fully-cached
    # one-token prefill (pos = plen-1 with plen < capacity), and a CoW
    # fork — slot 3 shares slot 0's first block id, diverges after
    table = np.arange(1, 1 + S * NB, dtype=np.int32).reshape(S, NB)
    table[3, 0] = table[0, 0]
    table[2, 2] = 0          # unused tail -> trash block (masked)
    pos_cases = (
        ("decode", 1, np.array([[NB * B - 1], [5], [7], [9]], np.int32)),
        ("cached-prefill", 1, np.array([[3], [0], [6], [4]], np.int32)),
        ("verify-window", 3,
         np.array([[4, 5, 6], [1, 2, 3], [5, 6, 7], [8, 9, 10]],
                  np.int32)),
    )
    for dt in (jnp.float32, jnp.bfloat16):
        dt_name = str(jnp.dtype(dt))
        pool_k = jnp.asarray(
            rng.normal(size=(num_blocks, B, h, dh)) * 0.5, dt)
        pool_v = jnp.asarray(
            rng.normal(size=(num_blocks, B, h, dh)) * 0.5, dt)
        # trash block 0 holds garbage, as in the live engine: masking,
        # not zeroing, must keep it out of every context
        pool_k = pool_k.at[0].set(1e3)
        pool_v = pool_v.at[0].set(1e3)
        tol = oracle_tol("paged_attention", dt_name, "fwd")
        for case, W, pos in pos_cases:
            q = jnp.asarray(rng.normal(size=(S, W, h, dh)) * 0.5, dt)
            tbl = jnp.asarray(table)
            p = jnp.asarray(pos)
            ref = _paged_dense_ref(q, pool_k, pool_v, tbl, p)
            for name, fn in impls:
                err = _rel_err(fn(q, pool_k, pool_v, tbl, p), ref)
                if err > tol:
                    failures.append(
                        f"paged {name} {dt_name} {case}: fwd err "
                        f"{err:.2e} > {tol}")
    print("paged parity ok (incl. trash-block masking, CoW fork)")

    # run-to-run bit-exactness WITHIN a backend
    q = jnp.asarray(rng.normal(size=(S, 1, h, dh)) * 0.5, jnp.float32)
    pool_k = jnp.asarray(
        rng.normal(size=(num_blocks, B, h, dh)), jnp.float32)
    pool_v = jnp.asarray(
        rng.normal(size=(num_blocks, B, h, dh)), jnp.float32)
    tbl = jnp.asarray(table)
    p = jnp.asarray([[5], [7], [9], [11]], np.int32)
    for name, fn in impls:
        jf = jax.jit(fn)
        a, b2 = jf(q, pool_k, pool_v, tbl, p), jf(q, pool_k, pool_v,
                                                  tbl, p)
        if not bool(jnp.array_equal(a, b2)):
            failures.append(f"paged {name}: not bit-exact run-to-run")
    print("paged run-to-run bit-exactness ok")

    # the PADDLE_TPU_PAGED_ATTN kill switch: =0 compiles the serving
    # decode step through decode_gather (the pre-paged spelling,
    # bit-exact with itself across compiles), =1 through the paged
    # kernel; both spellings agree numerically
    import paddle_tpu as pt
    from paddle_tpu.models import transformer
    from paddle_tpu.serving import batched_decode as _bd

    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        transformer.build(vocab_size=64, n_layer=1, n_head=2,
                          d_model=32, max_len=16, dropout_rate=0.0)
    scope = pt.core.scope.Scope()
    pt.core.scope._scope_stack.append(scope)
    try:
        exe = pt.Executor()
        exe.run(startup, scope=scope)
        params = transformer.extract_params(program=main, scope=scope)
    finally:
        pt.core.scope._scope_stack.pop()
    pdev = {k: jnp.asarray(v) for k, v in params.items()}
    S2, NB2, B2 = 2, 4, 4
    nb2 = 1 + S2 * NB2
    pk = (jnp.asarray(rng.normal(size=(nb2, B2, 2, 16)) * 0.1,
                      jnp.float32),)
    pv = (jnp.asarray(rng.normal(size=(nb2, B2, 2, 16)) * 0.1,
                      jnp.float32),)
    tok = jnp.asarray([3, 5], jnp.int32)
    t = jnp.asarray([6, 9], jnp.int32)
    tbl2 = jnp.asarray(1 + np.arange(S2 * NB2).reshape(S2, NB2),
                       np.int32)
    prev = os.environ.get("PADDLE_TPU_PAGED_ATTN")
    try:
        outs = {}
        for env in ("0", "1"):
            os.environ["PADDLE_TPU_PAGED_ATTN"] = env
            fn = _bd.make_decode_chunk(1, 2, 32, 2, donate=False)
            # the compiled module keeps op metadata (source_file /
            # named_scope op_name); the StableHLO dump does not
            text = fn.lower(pdev, pk, pv, tok, t, tbl2).compile() \
                     .as_text()
            spelled = ("decode_gather" in text if env == "0"
                       else "paged_attention" in text)
            if not spelled:
                failures.append(
                    f"PADDLE_TPU_PAGED_ATTN={env}: expected spelling "
                    f"absent from the lowered decode chunk")
            outs[env] = fn(pdev, pk, pv, tok, t, tbl2)
            again = fn(pdev, pk, pv, tok, t, tbl2)
            for a, b2_ in zip(jax.tree_util.tree_leaves(outs[env]),
                              jax.tree_util.tree_leaves(again)):
                if not bool(jnp.array_equal(a, b2_)):
                    failures.append(
                        f"PADDLE_TPU_PAGED_ATTN={env}: decode chunk "
                        f"not bit-exact across calls")
                    break
        # outputs are (pool_k', pool_v', last', pos', toks): greedy
        # token equality is the spelling-equivalence bar (float pools
        # may differ in reassociation low bits between the spellings)
        toks0, toks1 = outs["0"][4], outs["1"][4]
        if not bool(jnp.array_equal(toks0, toks1)):
            failures.append(
                "kill switch: paged vs gather decode chunks sampled "
                "different tokens")
    finally:
        if prev is None:
            os.environ.pop("PADDLE_TPU_PAGED_ATTN", None)
        else:
            os.environ["PADDLE_TPU_PAGED_ATTN"] = prev
    print("kill switch ok: =0 compiles decode_gather, =1 compiles "
          "paged_attention, same tokens")


def _check_xla_ref_trainer(failures):
    import jax

    import paddle_tpu as pt
    from paddle_tpu.analysis.jaxpr_tools import walk_report
    from paddle_tpu.models import transformer

    os.environ["PADDLE_TPU_KERNEL_BACKEND"] = "xla_ref"
    try:
        for policy in (None, "selective", "offload", "compact", "full"):
            pt.core.unique_name.reset()
            main, startup = pt.Program(), pt.Program()
            main.random_seed = 7
            with pt.program_guard(main, startup):
                outs = transformer.build(
                    vocab_size=128, n_layer=3, n_head=2, d_model=32,
                    max_len=64, dropout_rate=0.0, dtype="float32",
                    fused_head=True)
                if policy:
                    pt.memory_optimize(main, policy=policy)
            scope = pt.core.scope.Scope()
            pt.core.scope._scope_stack.append(scope)
            try:
                exe = pt.Executor()
                exe.run(startup, scope=scope)
                rng = np.random.default_rng(3)
                toks = rng.integers(0, 128, (2, 64)).astype(np.int64)
                feed = {"tokens": toks, "labels": np.roll(toks, -1, 1)}
                loss = exe.run(main, feed=feed,
                               fetch_list=[outs["avg_cost"]],
                               scope=scope)[0]
                if not np.isfinite(np.asarray(loss)).all():
                    failures.append(
                        f"xla_ref trainer: non-finite loss at "
                        f"policy={policy}")
                kb = (exe.last_step_cost or {}).get(
                    "kernel_backends") or {}
                if kb.get("flash_attention") != "xla_ref" or \
                        kb.get("fused_ce") != "xla_ref":
                    failures.append(
                        f"xla_ref trainer: backends {kb} at "
                        f"policy={policy}")
                state_names = tuple(sorted(
                    v.name for v in main.persistable_vars()
                    if scope.find_var(v.name) is not None))
                step, _ = exe.lower(
                    main, ["labels", "tokens"],
                    [outs["avg_cost"].name], state_names)
                state = {n: scope.get(n) for n in state_names}
                state[pt.core.scope.RNG_VAR] = scope.get(
                    pt.core.scope.RNG_VAR)
                rep = walk_report(jax.make_jaxpr(step)(state, toks,
                                                       toks))
                if rep["pallas_total"] != 0:
                    failures.append(
                        f"xla_ref trainer: {rep['pallas_total']} pallas "
                        f"calls in jaxpr at policy={policy}")
                print(f"xla_ref trainer policy={policy}: loss "
                      f"{float(np.asarray(loss).ravel()[0]):.4f}, "
                      f"pallas calls 0")
            finally:
                pt.core.scope._scope_stack.pop()
    finally:
        os.environ.pop("PADDLE_TPU_KERNEL_BACKEND", None)


def _check_timed_run_lint(failures):
    import paddle_tpu as pt
    from paddle_tpu.models import transformer

    from . import timed_run

    def compile_step(backend_env):
        pt.core.unique_name.reset()
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            outs = transformer.build(
                vocab_size=128, n_layer=2, n_head=2, d_model=32,
                max_len=16, dropout_rate=0.0, dtype="float32",
                fused_head=True)
        scope = pt.core.scope.Scope()
        pt.core.scope._scope_stack.append(scope)
        try:
            if backend_env:
                os.environ["PADDLE_TPU_KERNEL_BACKEND"] = backend_env
            exe = pt.Executor()
            with timed_run():
                exe.run(startup, scope=scope)
                toks = np.zeros((2, 16), np.int64)
                exe.run(main, feed={"tokens": toks, "labels": toks},
                        fetch_list=[outs["avg_cost"]], scope=scope)
            return exe.last_step_cost or {}
        finally:
            os.environ.pop("PADDLE_TPU_KERNEL_BACKEND", None)
            pt.core.scope._scope_stack.pop()

    import jax

    if jax.default_backend() == "tpu":
        print("timed-run lint: on TPU, interpret planting n/a — skipped")
        return
    planted = compile_step(None)  # auto on CPU = interpret kernels
    if not planted.get("interpret_in_timed_run"):
        failures.append(
            f"timed-run lint did not fire on interpret kernels "
            f"(lint_checks={planted.get('lint_checks')})")
    else:
        print("timed-run lint: planted interpret-mode kernels detected")
    clean = compile_step("xla_ref")
    if clean.get("interpret_in_timed_run"):
        failures.append("timed-run lint fired on an xla_ref-routed run")
    else:
        print("timed-run lint: xla_ref-routed region compiles clean")


def run_selftest():
    failures = []
    for check in (_check_registry, _check_oracle, _check_paged_oracle,
                  _check_xla_ref_trainer, _check_timed_run_lint):
        try:
            check(failures)
        except Exception as e:  # noqa: BLE001 — report, don't crash CI
            import traceback

            traceback.print_exc()
            failures.append(f"{check.__name__}: {type(e).__name__}: {e}")
    for f in failures:
        print(f"FAILURE: {f}")
    print("kernels selftest " + ("FAILED" if failures else "PASSED"))
    return 1 if failures else 0
