"""Fused CE/LSE head lowered GPU-style — the ``triton`` registry
backend for the ``fused_ce`` op class.

Same schedule as ``ops/pallas_ce.py`` (vocab-tiled online softmax, the
label logit picked by an iota==label select, backward recomputed from
the saved per-row lse), re-lowered for parallel GPU grids: the grid
covers independent row blocks (fwd/dx) or vocab blocks (dW) and the
reduction loop runs INSIDE the kernel (``lax.fori_loop`` + ``pl.load``
vocab/row tiles) instead of carrying scratch across sequential grid
steps.  See ``kernels/triton_attention.py`` for the execution-model
rationale; registered available only where a GPU exists, and CPU
oracle tests run the identical logic under ``interpret=True``."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name

from ..analysis.jaxpr_tools import KERNEL_RESIDUAL_TAG
from ..ops.pallas_attention import _pick_block
from .registry import register_kernel
from .triton_attention import _gpu_available

MAX_BLOCK_N = 128
MAX_BLOCK_V = 1024


def _blocks(n, v, block_n, block_v):
    bn = _pick_block(n, min(int(block_n or MAX_BLOCK_N), MAX_BLOCK_N))
    bv = _pick_block(v, min(int(block_v or MAX_BLOCK_V), MAX_BLOCK_V))
    return bn, bv


def _ce_fwd_kernel(x_ref, w_ref, y_ref, loss_ref, lse_ref, *, block_v,
                   nv):
    import jax.experimental.pallas as pl

    x = x_ref[...]                                      # [bn, d]
    y = y_ref[...]                                      # [bn, 1]
    bn = x.shape[0]

    def body(jv, carry):
        m, l, picked = carry
        wb = pl.load(w_ref, (slice(None), pl.dslice(jv * block_v,
                                                    block_v)))
        s = jax.lax.dot_general(
            x, wb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bn, bv]
        m2 = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m2)
        p = jnp.exp(s - m2[:, None])
        l2 = l * alpha + jnp.sum(p, axis=-1)
        col = jv * block_v + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        picked2 = picked + jnp.sum(
            jnp.where(col == y, s, 0.0), axis=-1)
        return m2, l2, picked2

    m0 = jnp.full((bn,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((bn,), jnp.float32)
    pick0 = jnp.zeros((bn,), jnp.float32)
    m, l, picked = jax.lax.fori_loop(0, nv, body, (m0, l0, pick0))
    lse = m + jnp.log(l)
    lse_ref[...] = lse[:, None]
    loss_ref[...] = (lse - picked)[:, None]


def _ce_dx_kernel(x_ref, w_ref, y_ref, lse_ref, geff_ref, gpick_ref,
                  dx_ref, *, block_v, nv):
    import jax.experimental.pallas as pl

    x = x_ref[...]
    y = y_ref[...]
    lse = lse_ref[...]                                  # [bn, 1]
    geff = geff_ref[...]
    gpick = gpick_ref[...]
    d = x.shape[1]

    def body(jv, dx):
        wb = pl.load(w_ref, (slice(None), pl.dslice(jv * block_v,
                                                    block_v)))
        s = jax.lax.dot_general(
            x, wb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        p = jnp.exp(s - lse)
        col = jv * block_v + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        onehot = (col == y).astype(jnp.float32)
        ds = (p * geff - onehot * gpick).astype(wb.dtype)
        return dx + jax.lax.dot_general(
            ds, wb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)

    dx = jax.lax.fori_loop(
        0, nv, body, jnp.zeros((x.shape[0], d), jnp.float32))
    dx_ref[...] = dx.astype(dx_ref.dtype)


def _ce_dw_kernel(x_ref, w_ref, y_ref, lse_ref, geff_ref, gpick_ref,
                  dw_ref, *, block_n, block_v, nn):
    import jax.experimental.pallas as pl

    jv = pl.program_id(0)
    wb = w_ref[...]                                     # [d, bv]
    d = wb.shape[0]

    def body(jn, dw):
        rows = pl.dslice(jn * block_n, block_n)
        x = pl.load(x_ref, (rows, slice(None)))
        y = pl.load(y_ref, (rows, slice(None)))
        lse = pl.load(lse_ref, (rows, slice(None)))
        geff = pl.load(geff_ref, (rows, slice(None)))
        gpick = pl.load(gpick_ref, (rows, slice(None)))
        s = jax.lax.dot_general(
            x, wb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        p = jnp.exp(s - lse)
        col = jv * block_v + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        onehot = (col == y).astype(jnp.float32)
        ds = (p * geff - onehot * gpick).astype(x.dtype)
        return dw + jax.lax.dot_general(
            x, ds, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dw = jax.lax.fori_loop(
        0, nn, body, jnp.zeros((d, wb.shape[1]), jnp.float32))
    dw_ref[...] = dw.astype(dw_ref.dtype)


def _ce_fwd(x, w, y, block_n, block_v, interpret):
    import jax.experimental.pallas as pl

    n, d = x.shape
    v = w.shape[1]
    bn, bv = _blocks(n, v, block_n, block_v)
    nv = v // bv
    y2 = y.reshape(n, 1)
    loss, lse = pl.pallas_call(
        functools.partial(_ce_fwd_kernel, block_v=bv, nv=nv),
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((d, v), lambda i: (0, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x, w, y2)
    return loss[:, 0], lse[:, 0]


def _ce_bwd(x, w, y, lse, g_eff, g_pick, block_n, block_v, interpret):
    import jax.experimental.pallas as pl

    n, d = x.shape
    v = w.shape[1]
    bn, bv = _blocks(n, v, block_n, block_v)
    nn_ = n // bn
    nv = v // bv
    y2 = y.reshape(n, 1)
    lse2 = lse.reshape(n, 1)
    geff2 = g_eff.astype(jnp.float32).reshape(n, 1)
    gpick2 = g_pick.astype(jnp.float32).reshape(n, 1)

    rstat = pl.BlockSpec((bn, 1), lambda i: (i, 0))
    dx = pl.pallas_call(
        functools.partial(_ce_dx_kernel, block_v=bv, nv=nv),
        grid=(nn_,),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((d, v), lambda i: (0, 0)),
            rstat, rstat, rstat, rstat,
        ],
        out_specs=[pl.BlockSpec((bn, d), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((n, d), x.dtype)],
        interpret=interpret,
    )(x, w, y2, lse2, geff2, gpick2)[0]

    cstat = pl.BlockSpec((n, 1), lambda jv: (0, 0))
    dw = pl.pallas_call(
        functools.partial(_ce_dw_kernel, block_n=bn, block_v=bv,
                          nn=nn_),
        grid=(nv,),
        in_specs=[
            pl.BlockSpec((n, d), lambda jv: (0, 0)),
            pl.BlockSpec((d, bv), lambda jv: (0, jv)),
            cstat, cstat, cstat, cstat,
        ],
        out_specs=[pl.BlockSpec((d, bv), lambda jv: (0, jv))],
        out_shape=[jax.ShapeDtypeStruct((d, v), w.dtype)],
        interpret=interpret,
    )(x, w, y2, lse2, geff2, gpick2)[0]
    return dx, dw


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _tce_core(x, w, y, blocks, interpret):
    loss, _ = _ce_fwd(x, w, y, blocks[0], blocks[1], interpret)
    return loss


def _tce_core_fwd(x, w, y, blocks, interpret):
    loss, lse = _ce_fwd(x, w, y, blocks[0], blocks[1], interpret)
    lse = checkpoint_name(lse, KERNEL_RESIDUAL_TAG)
    return loss, (x, w, y, lse)


def _tce_core_bwd(blocks, interpret, res, g):
    x, w, y, lse = res
    g = g.astype(jnp.float32)
    dx, dw = _ce_bwd(x, w, y, lse, g, g, blocks[0], blocks[1],
                     interpret)
    return dx, dw, np.zeros(y.shape, jax.dtypes.float0)


_tce_core.defvjp(_tce_core_fwd, _tce_core_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _tce_core_lse(x, w, y, blocks, interpret):
    return _ce_fwd(x, w, y, blocks[0], blocks[1], interpret)


def _tce_core_lse_fwd(x, w, y, blocks, interpret):
    loss, lse = _ce_fwd(x, w, y, blocks[0], blocks[1], interpret)
    lse = checkpoint_name(lse, KERNEL_RESIDUAL_TAG)
    return (loss, lse), (x, w, y, lse)


def _tce_core_lse_bwd(blocks, interpret, res, cts):
    x, w, y, lse = res
    g, glse = cts
    g = g.astype(jnp.float32)
    glse = glse.astype(jnp.float32)
    # loss = lse - picked: total logits cotangent p*(g+glse) - onehot*g
    # (kernels/xla_ref.py derivation)
    dx, dw = _ce_bwd(x, w, y, lse, g + glse, g, blocks[0], blocks[1],
                     interpret)
    return dx, dw, np.zeros(y.shape, jax.dtypes.float0)


_tce_core_lse.defvjp(_tce_core_lse_fwd, _tce_core_lse_bwd)


def _default_interpret(interpret):
    if interpret is None:
        return jax.default_backend() not in ("gpu", "cuda", "rocm")
    return bool(interpret)


def fused_softmax_ce_head(x, w, labels, block_n=None, block_v=None,
                          block_v_fwd=None, interpret=None):
    """``x [n, d]``, ``w [d, v]``, ``labels [n]`` -> NLL ``[n]`` f32.
    ``block_v_fwd`` is accepted for signature parity (the in-kernel
    loop uses one vocab tile width)."""
    del block_v_fwd
    interpret = _default_interpret(interpret)
    return _tce_core(x, w, labels.astype(jnp.int32),
                     (block_n and int(block_n), block_v and int(block_v)),
                     interpret)


def fused_softmax_ce_head_with_lse(x, w, labels, block_n=None,
                                   block_v=None, block_v_fwd=None,
                                   interpret=None):
    del block_v_fwd
    interpret = _default_interpret(interpret)
    return _tce_core_lse(
        x, w, labels.astype(jnp.int32),
        (block_n and int(block_n), block_v and int(block_v)), interpret)


class _CeTriton:
    call = staticmethod(fused_softmax_ce_head)
    call_with_lse = staticmethod(fused_softmax_ce_head_with_lse)


register_kernel("fused_ce", "triton", _CeTriton,
                available=_gpu_available)
