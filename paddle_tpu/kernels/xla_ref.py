"""Pure-XLA reference backend — the universal numerics oracle.

Shape-complete, first-class implementations of every registered op
class: flash attention (causal/non-causal, any d_head, packed layouts,
lse outputs), the fused CE/LSE head, and the paged decode gather.  No
``pallas_call`` ever appears in a program routed here
(``PADDLE_TPU_KERNEL_BACKEND=xla_ref`` runs the full GPT trainer path —
every ``memory_optimize`` policy — with zero Pallas calls in the
jaxpr; the kernels selftest asserts it).

These are not test stubs: attention and the CE head carry the SAME
custom-VJP algebra as the Mosaic kernels (backward recomputed from the
saved ``(q, k, v, o, lse)`` / ``(x, w, y, lse)`` residual sets, tagged
``KERNEL_RESIDUAL_TAG`` so the offload name-policy keeps them), so the
memory_optimize contracts hold under this backend too — only the O(t^2)
probability matrix materializes, which is exactly what makes this the
oracle spelling: every sum is a single dense reduction with no tiling
reassociation.  Tolerances for the other backends against this one are
pinned in ``ORACLE_TOL`` (docs/kernels.md).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name

from ..analysis.jaxpr_tools import KERNEL_RESIDUAL_TAG
from .registry import register_kernel

NEG_INF = -1e30

# The cross-backend numerics contract (docs/kernels.md "Oracle
# contract"): max |backend - xla_ref| / max|xla_ref|, per op class and
# dtype, forward and grads.  Within one backend the contract is
# BIT-EXACT run-to-run (same compiled fn, same inputs -> identical
# bits; the oracle suite asserts both).  The bounds are set by the
# tiling reassociation the blocked backends introduce (f32) plus input
# rounding (bf16) — an O(1) logic/masking bug clears them by orders of
# magnitude.
ORACLE_TOL = {
    ("flash_attention", "float32"): {"fwd": 2e-4, "grad": 1e-3},
    ("flash_attention", "bfloat16"): {"fwd": 2e-2, "grad": 5e-2},
    ("fused_ce", "float32"): {"fwd": 2e-4, "grad": 1e-3},
    ("fused_ce", "bfloat16"): {"fwd": 2e-2, "grad": 5e-2},
    # a gather moves bits, it does not compute: exact in every dtype
    ("decode_gather", "float32"): {"fwd": 0.0, "grad": 0.0},
    ("decode_gather", "bfloat16"): {"fwd": 0.0, "grad": 0.0},
    # paged attention is inference-only (no VJP): fwd bounds match
    # flash_attention — the same blocked online-softmax reassociation
    # against the same dense-softmax reference, per block chain
    ("paged_attention", "float32"): {"fwd": 2e-4, "grad": None},
    ("paged_attention", "bfloat16"): {"fwd": 2e-2, "grad": None},
}


def oracle_tol(op_class, dtype, kind="fwd"):
    """The documented tolerance for comparing ``op_class`` outputs in
    ``dtype`` against this backend (``kind``: "fwd" | "grad")."""
    key = (op_class, str(jnp.dtype(dtype)))
    if key not in ORACLE_TOL:
        raise KeyError(f"no oracle tolerance documented for {key}")
    return ORACLE_TOL[key][kind]


# -- flash attention ---------------------------------------------------------

def _attn_fwd(q, k, v, sm_scale, causal):
    """Dense forward on [b, t, h, d]: returns (o [b, t_q, h, d] in the
    input dtype, lse [b, h, t_q] f32).  Same numerics conventions as the
    kernels: f32 scores/softmax state, NEG_INF causal mask, output
    normalized once at the end."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if causal:
        t_q, t_k = s.shape[-2:]
        mask = jnp.arange(t_q)[:, None] >= jnp.arange(t_k)[None, :]
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    l_bqh = jnp.moveaxis(l_safe[..., 0], -1, 1)          # [b, q, h]
    o = (acc / l_bqh[..., None]).astype(q.dtype)
    lse = (m + jnp.log(l_safe))[..., 0]                  # [b, h, q]
    return o, lse


def _attn_bwd_math(q, k, v, o, lse, do, sm_scale, causal, dlse=None):
    """Backward recomputed from the flash residual contract
    ``(q, k, v, o, lse)`` — the same ds/delta algebra as the Mosaic
    backward kernels, spelled dense."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if causal:
        t_q, t_k = s.shape[-2:]
        mask = jnp.arange(t_q)[:, None] >= jnp.arange(t_k)[None, :]
        s = jnp.where(mask, s, NEG_INF)
    p = jnp.exp(s - lse[..., None])                      # [b, h, q, k]
    delta = jnp.einsum("bqhd,bqhd->bhq", do.astype(jnp.float32),
                       o.astype(jnp.float32))
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)
    dp = jnp.einsum("bqhd,bkhd->bhqk", do, v,
                    preferred_element_type=jnp.float32)
    ds = p * (dp - delta[..., None]) * sm_scale
    dq = jnp.einsum("bhqk,bkhd->bqhd", ds,
                    k.astype(jnp.float32)).astype(q.dtype)
    dk = jnp.einsum("bhqk,bqhd->bkhd", ds,
                    q.astype(jnp.float32)).astype(k.dtype)
    dv = jnp.einsum("bhqk,bqhd->bkhd", p,
                    do.astype(jnp.float32)).astype(v.dtype)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _attn_core(q, k, v, sm_scale, causal):
    o, _ = _attn_fwd(q, k, v, sm_scale, causal)
    return o


def _attn_core_fwd(q, k, v, sm_scale, causal):
    o, lse = _attn_fwd(q, k, v, sm_scale, causal)
    # the flash residual contract, backend-invariant: a name-policy
    # checkpoint (memory_optimize offload) keeps these instead of
    # re-running the forward in the backward pass
    o = checkpoint_name(o, KERNEL_RESIDUAL_TAG)
    lse = checkpoint_name(lse, KERNEL_RESIDUAL_TAG)
    return o, (q, k, v, o, lse)


def _attn_core_bwd(sm_scale, causal, res, do):
    q, k, v, o, lse = res
    return _attn_bwd_math(q, k, v, o, lse, do, sm_scale, causal)


_attn_core.defvjp(_attn_core_fwd, _attn_core_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _attn_core_lse(q, k, v, sm_scale, causal):
    return _attn_fwd(q, k, v, sm_scale, causal)


def _attn_core_lse_fwd(q, k, v, sm_scale, causal):
    o, lse = _attn_fwd(q, k, v, sm_scale, causal)
    o = checkpoint_name(o, KERNEL_RESIDUAL_TAG)
    lse = checkpoint_name(lse, KERNEL_RESIDUAL_TAG)
    return (o, lse), (q, k, v, o, lse)


def _attn_core_lse_bwd(sm_scale, causal, res, cts):
    q, k, v, o, lse = res
    do, dlse = cts
    return _attn_bwd_math(q, k, v, o, lse, do, sm_scale, causal,
                          dlse=dlse)


_attn_core_lse.defvjp(_attn_core_lse_fwd, _attn_core_lse_bwd)


def flash_attention(q, k, v, causal=False, sm_scale=None, block_q=None,
                    block_k=None, interpret=None):
    """The 4-D entry point (``q/k/v [b, t, h, d]``).  Block sizes and
    ``interpret`` are accepted for signature parity with the kernel
    backends and ignored — XLA owns the tiling here."""
    del block_q, block_k, interpret
    d = q.shape[-1]
    sm_scale = d ** -0.5 if sm_scale is None else sm_scale
    return _attn_core(q, k, v, float(sm_scale), bool(causal))


def flash_attention_with_lse(q, k, v, causal=False, sm_scale=None,
                             block_q=None, block_k=None, interpret=None):
    """Returns ``(o [b, t, h, d], lse [b, h, t])``, differentiable
    through both — the ring-attention merge building block."""
    del block_q, block_k, interpret
    d = q.shape[-1]
    sm_scale = d ** -0.5 if sm_scale is None else sm_scale
    return _attn_core_lse(q, k, v, float(sm_scale), bool(causal))


def flash_attention_packed(q, k, v, n_head, causal=False, sm_scale=None,
                           block_q=None, block_k=None, interpret=None):
    """The packed layout (``[b, t, h*d]``) is shape-complete here for
    ANY head width: the head split is a free reshape (no data movement
    in XLA's row-major layout), so no geometry restriction applies."""
    del block_q, block_k, interpret
    b, t, hd = q.shape
    if hd % n_head:
        raise ValueError(
            f"feature dim {hd} not divisible by n_head {n_head}")
    d = hd // n_head
    sm_scale = d ** -0.5 if sm_scale is None else sm_scale
    r4 = lambda x: x.reshape(b, x.shape[1], n_head, d)
    o = _attn_core(r4(q), r4(k), r4(v), float(sm_scale), bool(causal))
    return o.reshape(b, t, hd)


# -- fused CE / LSE head -----------------------------------------------------

def _ce_fwd(x, w, y):
    """Dense forward on ``x [n, d]``, ``w [d, v]``, ``y [n]`` int32:
    returns (loss [n] f32, lse [n] f32).  The [n, v] logits materialize
    — that is the point of the oracle spelling."""
    s = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)              # [n, v]
    m = jnp.max(s, axis=-1, keepdims=True)
    l = jnp.sum(jnp.exp(s - m), axis=-1, keepdims=True)
    lse = (m + jnp.log(l))[:, 0]
    # out-of-range labels (ignore_index) produce finite garbage the
    # caller masks, exactly like the kernel's iota==label pick
    yc = jnp.clip(y, 0, s.shape[1] - 1)
    picked = jnp.take_along_axis(s, yc[:, None], axis=-1)[:, 0]
    in_range = (y >= 0) & (y < s.shape[1])
    picked = jnp.where(in_range, picked, 0.0)
    return lse - picked, lse


def _ce_bwd_math(x, w, y, lse, g_eff, g_pick):
    """ds = p * g_eff - onehot * g_pick, then dx/dW — the kernel's
    backward algebra dense.  ``g_eff`` multiplies the softmax term
    (g + glse for the lse variant), ``g_pick`` the picked-logit term
    (always g)."""
    s = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    p = jnp.exp(s - lse[:, None])
    col = jnp.arange(s.shape[1], dtype=jnp.int32)[None, :]
    onehot = (col == y[:, None]).astype(jnp.float32)
    ds = p * g_eff[:, None] - onehot * g_pick[:, None]
    dx = jax.lax.dot_general(
        ds, w.astype(jnp.float32), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32).astype(x.dtype)
    dw = jax.lax.dot_general(
        x.astype(jnp.float32), ds, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(w.dtype)
    return dx, dw


@jax.custom_vjp
def _ce_core(x, w, y):
    loss, _ = _ce_fwd(x, w, y)
    return loss


def _ce_core_fwd(x, w, y):
    loss, lse = _ce_fwd(x, w, y)
    lse = checkpoint_name(lse, KERNEL_RESIDUAL_TAG)
    return loss, (x, w, y, lse)


def _ce_core_bwd(res, g):
    x, w, y, lse = res
    g = g.astype(jnp.float32)
    dx, dw = _ce_bwd_math(x, w, y, lse, g, g)
    return dx, dw, np.zeros(y.shape, jax.dtypes.float0)


_ce_core.defvjp(_ce_core_fwd, _ce_core_bwd)


@jax.custom_vjp
def _ce_core_lse(x, w, y):
    return _ce_fwd(x, w, y)


def _ce_core_lse_fwd(x, w, y):
    loss, lse = _ce_fwd(x, w, y)
    lse = checkpoint_name(lse, KERNEL_RESIDUAL_TAG)
    return (loss, lse), (x, w, y, lse)


def _ce_core_lse_bwd(res, cts):
    x, w, y, lse = res
    g, glse = cts
    g = g.astype(jnp.float32)
    glse = glse.astype(jnp.float32)
    # loss = lse - picked: the total logits cotangent is
    # p*(g + glse) - onehot*g (one fused ds — algebraically identical
    # to pallas_ce's run-with-g'=g+glse plus rank-1 onehot correction)
    dx, dw = _ce_bwd_math(x, w, y, lse, g + glse, g)
    return dx, dw, np.zeros(y.shape, jax.dtypes.float0)


_ce_core_lse.defvjp(_ce_core_lse_fwd, _ce_core_lse_bwd)


def fused_softmax_ce_head(x, w, labels, block_n=None, block_v=None,
                          block_v_fwd=None, interpret=None):
    """``x [n, d]``, ``w [d, v]``, ``labels [n]`` -> NLL ``[n]`` f32.
    Block args are accepted for signature parity and ignored."""
    del block_n, block_v, block_v_fwd, interpret
    return _ce_core(x, w, labels.astype(jnp.int32))


def fused_softmax_ce_head_with_lse(x, w, labels, block_n=None,
                                   block_v=None, block_v_fwd=None,
                                   interpret=None):
    del block_n, block_v, block_v_fwd, interpret
    return _ce_core_lse(x, w, labels.astype(jnp.int32))


# -- paged decode gather -----------------------------------------------------

def decode_gather(pool, table):
    """``pool [num_blocks, B, h, dh]``, ``table [S, NB]`` int32 ->
    each slot's logical KV view ``[S, NB*B, h, dh]`` — the advanced-
    indexing spelling (an XLA gather) that MATERIALIZES the per-slot
    view in HBM.  Since the ``paged_attention`` op class landed this is
    the kill-switch / oracle spelling (``PADDLE_TPU_PAGED_ATTN=0``) and
    the parity reference the selftest checks the blocked kernels
    against; the serving hot path streams pool blocks through
    ``paged_attention`` instead and never builds this view.  The
    ``named_scope`` keys HLO attribution: every op XLA fuses out of
    this gather lands in the ``decode_gather`` class, so serving
    benches can put a number on exactly the traffic the paged kernel
    deletes."""
    S, NB = table.shape
    B = pool.shape[1]
    with jax.named_scope("decode_gather"):
        return pool[table].reshape(S, NB * B, pool.shape[2],
                                   pool.shape[3])


# -- registration ------------------------------------------------------------

class _FlashXlaRef:
    call = staticmethod(flash_attention)
    call_with_lse = staticmethod(flash_attention_with_lse)
    call_packed = staticmethod(flash_attention_packed)


class _CeXlaRef:
    call = staticmethod(fused_softmax_ce_head)
    call_with_lse = staticmethod(fused_softmax_ce_head_with_lse)


class _GatherXlaRef:
    call = staticmethod(decode_gather)


register_kernel("flash_attention", "xla_ref", _FlashXlaRef)
register_kernel("fused_ce", "xla_ref", _CeXlaRef)
register_kernel("decode_gather", "xla_ref", _GatherXlaRef)
