"""paddle_tpu.kernels — the multi-backend kernel registry
(docs/kernels.md, ROADMAP item 2).

One op, three targets, one numerics oracle: every fused op class
(``flash_attention``, ``fused_ce``, ``decode_gather``,
``paged_attention``) resolves through
:mod:`.registry` to one of ``pallas_tpu`` (the Mosaic kernels — native
on TPU, interpret mode in CPU tests), ``triton`` (the same block
schedules lowered GPU-style — :mod:`.triton_attention` /
:mod:`.triton_ce`), or ``xla_ref`` (:mod:`.xla_ref` — the shape-
complete pure-XLA reference every backend is tested against, with the
documented cross-backend tolerances in ``ORACLE_TOL``).

Selection: ``PADDLE_TPU_KERNEL_BACKEND=auto|pallas_tpu|triton|xla_ref``
(global), ``PADDLE_TPU_KERNEL_BACKEND_<OP>`` (per op class), explicit
``backend=`` call-site arguments, or the tuned winner's persisted
kernel choice — precedence and fallback semantics in
:mod:`.registry`.  CI: ``python -m paddle_tpu --kernels-selftest``
(tools/tier1.sh) and ``tests/test_kernels.py``.
"""

from . import registry  # must load first: backend modules register into it
from .registry import (
    AUTO_ORDER, BACKENDS, GLOBAL_ENV, TIMED_RUN_ENV, KernelUnavailable,
    available_backends, forced_backend, get_kernel,
    registered_op_classes, reset_selected, resolve, resolve_name,
    selected_backends, timed_run, timed_run_active)
from .xla_ref import ORACLE_TOL, oracle_tol
from . import xla_ref  # registers the oracle backend
from . import triton_attention, triton_ce  # register the GPU backends
from . import pallas_gather  # registers the TPU decode gather
from . import paged_attention  # registers the paged-attention op class

__all__ = [
    "AUTO_ORDER", "BACKENDS", "GLOBAL_ENV", "TIMED_RUN_ENV",
    "KernelUnavailable", "ORACLE_TOL", "available_backends",
    "forced_backend", "get_kernel", "oracle_tol",
    "registered_op_classes", "reset_selected", "resolve",
    "resolve_name", "selected_backends", "timed_run",
    "timed_run_active",
]

# The pallas_tpu flash/CE backends register from the op modules
# themselves (they own the kernels).  Importing them here makes a bare
# ``import paddle_tpu.kernels`` self-sufficient; inside the package's
# own import cycle they may arrive partially initialized, in which case
# their bottom-of-module registration still runs when the outer import
# completes.
try:  # noqa: SIM105
    from ..ops import pallas_attention as _pa  # noqa: F401
    from ..ops import pallas_ce as _pce  # noqa: F401
except ImportError:  # pragma: no cover — mid-bootstrap partial import
    pass
