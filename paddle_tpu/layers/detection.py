"""Detection layers wrapping the detection op group (reference ops:
prior_box_op, iou_similarity_op, bipartite_match_op, roi_pool_op,
detection_output)."""

from .layer_helper import LayerHelper

__all__ = ["prior_box", "iou_similarity", "bipartite_match", "roi_pool",
           "detection_output", "multibox_loss"]


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variances=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None):
    helper = LayerHelper("prior_box", name=name)
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    p = len(min_sizes) * len(ars) + len(max_sizes or [])
    h, w = input.shape[2], input.shape[3]
    boxes = helper.create_tmp_variable("float32", [h, w, p, 4], stop_gradient=True)
    var = helper.create_tmp_variable("float32", [h, w, p, 4], stop_gradient=True)
    helper.append_op(
        type="prior_box",
        inputs={"Input": [input.name], "Image": [image.name]},
        outputs={"Boxes": [boxes.name], "Variances": [var.name]},
        attrs={
            "min_sizes": list(min_sizes),
            "max_sizes": list(max_sizes or []),
            "aspect_ratios": list(aspect_ratios),
            "variances": list(variances),
            "flip": flip,
            "clip": clip,
            "step_w": steps[0],
            "step_h": steps[1],
            "offset": offset,
        },
    )
    return boxes, var


def iou_similarity(x, y, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    out = helper.create_tmp_variable("float32", [x.shape[0], y.shape[0]], stop_gradient=True)
    helper.append_op(
        type="iou_similarity", inputs={"X": [x.name], "Y": [y.name]},
        outputs={"Out": [out.name]},
    )
    return out


def bipartite_match(dist_matrix, name=None):
    helper = LayerHelper("bipartite_match", name=name)
    m = dist_matrix.shape[1]
    idx = helper.create_tmp_variable("int32", [1, m], stop_gradient=True)
    dist = helper.create_tmp_variable("float32", [1, m], stop_gradient=True)
    helper.append_op(
        type="bipartite_match",
        inputs={"DistMat": [dist_matrix.name]},
        outputs={"ColToRowMatchIndices": [idx.name], "ColToRowMatchDist": [dist.name]},
    )
    return idx, dist


def roi_pool(input, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0,
             name=None):
    helper = LayerHelper("roi_pool", name=name)
    c = input.shape[1]
    r = rois.shape[0]
    out = helper.create_tmp_variable(input.dtype, [r, c, pooled_height, pooled_width])
    argmax = helper.create_tmp_variable("int64", [r, c, pooled_height, pooled_width], stop_gradient=True)
    helper.append_op(
        type="roi_pool",
        inputs={"X": [input.name], "ROIs": [rois.name]},
        outputs={"Out": [out.name], "Argmax": [argmax.name]},
        attrs={
            "pooled_height": pooled_height,
            "pooled_width": pooled_width,
            "spatial_scale": spatial_scale,
        },
    )
    return out


def detection_output(loc, scores, prior_box, background_label=0,
                     nms_threshold=0.45, nms_top_k=400, keep_top_k=200,
                     score_threshold=0.01, name=None):
    helper = LayerHelper("detection_output", name=name)
    out = helper.create_tmp_variable("float32", [scores.shape[0], keep_top_k, 6], stop_gradient=True)
    helper.append_op(
        type="detection_output",
        inputs={"Loc": [loc.name], "Conf": [scores.name], "PriorBox": [prior_box.name]},
        outputs={"Out": [out.name]},
        attrs={
            "background_label": background_label,
            "nms_threshold": nms_threshold,
            "nms_top_k": nms_top_k,
            "keep_top_k": keep_top_k,
            "score_threshold": score_threshold,
        },
    )
    return out


def multibox_loss(loc, conf, prior_box, gt_box, gt_label,
                  overlap_threshold=0.5, neg_pos_ratio=3.0,
                  background_label=0, name=None):
    """SSD training loss (reference gserver MultiBoxLossLayer.cpp): IoU
    matching, smooth-L1 on matched location offsets, softmax CE on
    confidences with hard negative mining.  loc [b, P, 4], conf [b, P, C],
    prior_box [P, 4] or [2, P, 4], gt_box [b, G, 4], gt_label [b, G]
    (< 0 = padding).  Returns the per-image loss [b, 1]."""
    helper = LayerHelper("multibox_loss", name=name)
    out = helper.create_tmp_variable(loc.dtype, [loc.shape[0], 1])
    helper.append_op(
        type="multibox_loss",
        inputs={"Loc": [loc.name], "Conf": [conf.name],
                "PriorBox": [prior_box.name], "GtBox": [gt_box.name],
                "GtLabel": [gt_label.name]},
        outputs={"Loss": [out.name]},
        attrs={"overlap_threshold": overlap_threshold,
               "neg_pos_ratio": neg_pos_ratio,
               "background_label": background_label},
    )
    return out
