from .io import data, sparse_data
from .tensor import *  # noqa: F401,F403
from .nn import *  # noqa: F401,F403
from .ops import *  # noqa: F401,F403
from .control_flow import *  # noqa: F401,F403
from .detection import *  # noqa: F401,F403
from .device import get_places
from . import io
from . import tensor
from . import nn
from . import ops
from . import control_flow
from . import detection
from . import device
