"""Data layers (reference: fluid/layers/io.py ``data``)."""

from ..core.program import default_main_program, default_startup_program


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         main_program=None, stop_gradient=True):
    """Declare an input variable.

    ``append_batch_size=True`` prepends a batch dim of -1 (resolved at feed
    time from the actual minibatch, like the reference's -1 dim).  With
    ``lod_level > 0`` the variable is a padded sequence batch and its shadow
    ``<name>@LENGTH`` int32 var is created alongside (the LoD replacement).
    ``lod_level == 2`` declares a NESTED sequence batch [b, s, t, ...]
    (reference ``lod_tensor.h:58`` two-level LoD /
    ``Argument.subSequenceStartPositions``): ``@LENGTH`` [b] counts
    sub-sequences per sample and the additional shadow ``@SUBLENGTH``
    [b, s] counts items per sub-sequence.
    """
    prog = main_program or default_main_program()
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    var = prog.global_block().create_var(
        name=name,
        shape=shape,
        dtype=dtype,
        lod_level=lod_level,
        is_data=True,
        stop_gradient=stop_gradient,
    )
    if lod_level > 0:
        var.length_var()
    if lod_level > 1:
        var.sub_length_var()
    return var
