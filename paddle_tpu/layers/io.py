"""Data layers (reference: fluid/layers/io.py ``data``)."""

from ..core.program import (IDS_SUFFIX, VALS_SUFFIX, default_main_program,
                            default_startup_program)


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         main_program=None, stop_gradient=True):
    """Declare an input variable.

    ``append_batch_size=True`` prepends a batch dim of -1 (resolved at feed
    time from the actual minibatch, like the reference's -1 dim).  With
    ``lod_level > 0`` the variable is a padded sequence batch and its shadow
    ``<name>@LENGTH`` int32 var is created alongside (the LoD replacement).
    ``lod_level == 2`` declares a NESTED sequence batch [b, s, t, ...]
    (reference ``lod_tensor.h:58`` two-level LoD /
    ``Argument.subSequenceStartPositions``): ``@LENGTH`` [b] counts
    sub-sequences per sample and the additional shadow ``@SUBLENGTH``
    [b, s] counts items per sub-sequence.
    """
    prog = main_program or default_main_program()
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    var = prog.global_block().create_var(
        name=name,
        shape=shape,
        dtype=dtype,
        lod_level=lod_level,
        is_data=True,
        stop_gradient=stop_gradient,
    )
    if lod_level > 0:
        var.length_var()
    if lod_level > 1:
        var.sub_length_var()
    return var


def sparse_data(name, dim, dtype="float32", lod_level=0, main_program=None):
    """Declare a NATIVE sparse input slot of vocabulary size ``dim``
    (reference ``sparse_binary_vector``/``sparse_float_vector`` slots,
    PyDataProvider2.py:90-156, assembled as sparse Arguments by
    PyDataProvider2.cpp:195 — never densified).

    TPU re-design: the slot feeds as two padded shadow arrays —
    ``<name>@IDS`` int64 [b, nnz] (0-padded) and ``<name>@VALS``
    [b, nnz] (0.0-padded; all-ones for binary slots) — and ``fc`` on the
    returned handle lowers to the ``sparse_fc`` op, a weighted
    gather-sum ``sum_i vals_i * W[ids_i]`` whose cost is O(nnz), not
    O(dim).  Zero-valued padding makes the sum exact without a count.
    The handle variable itself (declared shape [-1, dim]) is symbolic:
    it is never fed and never materialized.

    ``lod_level=1`` declares a sequence of sparse vectors: the shadow
    arrays gain a time axis ([b, t, nnz]) and ``<name>@LENGTH`` carries
    the sequence lengths as usual.
    """
    prog = main_program or default_main_program()
    shape = [-1, int(dim)]
    if lod_level:
        shape = [-1, -1, int(dim)]
    block = prog.global_block()
    var = block.create_var(
        name=name, shape=shape, dtype=dtype, lod_level=lod_level,
        is_data=True, stop_gradient=True,
    )
    var.sparse_slot = True
    inner = [-1, -1] if lod_level else [-1]
    block.create_var(name=name + IDS_SUFFIX, shape=inner + [-1],
                     dtype="int64", is_data=True, stop_gradient=True)
    block.create_var(name=name + VALS_SUFFIX, shape=inner + [-1],
                     dtype=dtype, is_data=True, stop_gradient=True)
    if lod_level > 0:
        var.length_var()
    return var
