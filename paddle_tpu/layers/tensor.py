"""Tensor layers (reference: fluid/layers/tensor.py — create_tensor, cast,
concat, sums, assign, fill_constant, ones, zeros …)."""

from ..core.program import default_main_program
from ..core import unique_name
from .layer_helper import LayerHelper

__all__ = [
    "create_tensor",
    "create_parameter",
    "cast",
    "concat",
    "sums",
    "assign",
    "fill_constant",
    "fill_constant_batch_size_like",
    "ones",
    "zeros",
    "reshape",
    "transpose",
    "split",
    "expand",
    "gather",
    "scatter",
    "pad",
    "crop",
    "argmax",
    "argmin",
    "shape",
    "increment",
    "one_hot",
]


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.main_block.create_var(
        name=name or unique_name.generate("create_tensor"),
        dtype=dtype,
        persistable=persistable,
    )


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    helper = LayerHelper("create_parameter", name=name)
    return helper.create_parameter(
        attr, shape, dtype, suffix="b" if is_bias else "w",
        default_initializer=default_initializer,
    )


def cast(x, dtype):
    helper = LayerHelper("cast")
    out = helper.create_tmp_variable(dtype, x.shape, lod_level=x.lod_level)
    helper.append_op(
        type="cast", inputs={"X": [x.name]}, outputs={"Out": [out.name]},
        attrs={"out_dtype": str(dtype)},
    )
    return out


def concat(input, axis=0):
    helper = LayerHelper("concat")
    shape = list(input[0].shape)
    shape[axis] = sum(v.shape[axis] for v in input) if all(
        v.shape[axis] >= 0 for v in input
    ) else -1
    out = helper.create_tmp_variable(input[0].dtype, shape)
    helper.append_op(
        type="concat", inputs={"X": input}, outputs={"Out": [out.name]},
        attrs={"axis": axis},
    )
    return out


def sums(input, out=None):
    helper = LayerHelper("sums")
    if out is None:
        out = helper.create_tmp_variable(input[0].dtype, input[0].shape)
    helper.append_op(type="sum", inputs={"X": input}, outputs={"Out": [out.name]})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if output is None:
        output = helper.create_tmp_variable(input.dtype, input.shape)
    helper.append_op(
        type="assign", inputs={"X": [input.name]}, outputs={"Out": [output.name]}
    )
    return output


def fill_constant(shape, dtype="float32", value=0.0, out=None):
    helper = LayerHelper("fill_constant")
    if out is None:
        out = helper.create_tmp_variable(dtype, shape, stop_gradient=True)
    helper.append_op(
        type="fill_constant",
        outputs={"Out": [out.name]},
        attrs={"shape": list(shape), "dtype": str(dtype), "value": float(value)},
    )
    return out


def fill_constant_batch_size_like(
    input, shape, dtype="float32", value=0.0, input_dim_idx=0, output_dim_idx=0
):
    helper = LayerHelper("fill_constant_batch_size_like")
    out = helper.create_tmp_variable(dtype, shape, stop_gradient=True)
    helper.append_op(
        type="fill_constant_batch_size_like",
        inputs={"Input": [input.name]},
        outputs={"Out": [out.name]},
        attrs={
            "shape": list(shape),
            "dtype": str(dtype),
            "value": float(value),
            "input_dim_idx": input_dim_idx,
            "output_dim_idx": output_dim_idx,
        },
    )
    return out


def ones(shape, dtype="float32"):
    return fill_constant(shape, dtype, 1.0)


def zeros(shape, dtype="float32"):
    return fill_constant(shape, dtype, 0.0)


def reshape(x, shape):
    helper = LayerHelper("reshape")
    # 0 = copy dim from input (Paddle reshape convention)
    out_shape = [
        x.shape[i] if s == 0 and i < len(x.shape) else s
        for i, s in enumerate(shape)
    ]
    out = helper.create_tmp_variable(x.dtype, out_shape)
    helper.append_op(
        type="reshape", inputs={"X": [x.name]}, outputs={"Out": [out.name]},
        attrs={"shape": list(shape)},
    )
    return out


def transpose(x, perm):
    helper = LayerHelper("transpose")
    shape = [x.shape[i] for i in perm]
    out = helper.create_tmp_variable(x.dtype, shape)
    helper.append_op(
        type="transpose", inputs={"X": [x.name]}, outputs={"Out": [out.name]},
        attrs={"axis": list(perm)},
    )
    return out


def split(input, num_or_sections, dim=0):
    helper = LayerHelper("split")
    if isinstance(num_or_sections, int):
        num, sections = num_or_sections, []
        sizes = [input.shape[dim] // num] * num if input.shape[dim] >= 0 else [-1] * num
    else:
        num, sections = 0, list(num_or_sections)
        sizes = sections
    outs = []
    for s in sizes:
        shape = list(input.shape)
        shape[dim] = s
        outs.append(helper.create_tmp_variable(input.dtype, shape))
    helper.append_op(
        type="split",
        inputs={"X": [input.name]},
        outputs={"Out": outs},
        attrs={"num": num if not sections else 0, "sections": sections, "axis": dim},
    )
    return outs


def expand(x, expand_times):
    helper = LayerHelper("expand")
    shape = [
        (s * t if s >= 0 else -1) for s, t in zip(x.shape, expand_times)
    ]
    out = helper.create_tmp_variable(x.dtype, shape)
    helper.append_op(
        type="expand", inputs={"X": [x.name]}, outputs={"Out": [out.name]},
        attrs={"expand_times": list(expand_times)},
    )
    return out


def gather(input, index):
    helper = LayerHelper("gather")
    shape = list(index.shape[:1]) + list(input.shape[1:])
    out = helper.create_tmp_variable(input.dtype, shape)
    helper.append_op(
        type="gather",
        inputs={"X": [input.name], "Index": [index.name]},
        outputs={"Out": [out.name]},
    )
    return out


def scatter(input, index, updates, overwrite=True):
    helper = LayerHelper("scatter")
    out = helper.create_tmp_variable(input.dtype, input.shape)
    helper.append_op(
        type="scatter",
        inputs={"X": [input.name], "Ids": [index.name], "Updates": [updates.name]},
        outputs={"Out": [out.name]},
        attrs={"overwrite": overwrite},
    )
    return out


def pad(x, paddings, pad_value=0.0):
    helper = LayerHelper("pad")
    shape = [
        (s + paddings[2 * i] + paddings[2 * i + 1]) if s >= 0 else -1
        for i, s in enumerate(x.shape)
    ]
    out = helper.create_tmp_variable(x.dtype, shape)
    helper.append_op(
        type="pad", inputs={"X": [x.name]}, outputs={"Out": [out.name]},
        attrs={"paddings": list(paddings), "pad_value": float(pad_value)},
    )
    return out


def crop(x, shape=None, offsets=None, y=None):
    helper = LayerHelper("crop")
    tgt = list(y.shape) if y is not None else list(shape)
    out = helper.create_tmp_variable(x.dtype, tgt)
    inputs = {"X": [x.name]}
    if y is not None:
        inputs["Y"] = [y.name]
    helper.append_op(
        type="crop", inputs=inputs, outputs={"Out": [out.name]},
        attrs={"offsets": list(offsets or []), "shape": list(shape or [])},
    )
    return out


def argmax(x, axis=-1):
    helper = LayerHelper("arg_max")
    shape = [s for i, s in enumerate(x.shape) if i != (axis % len(x.shape))]
    out = helper.create_tmp_variable("int64", shape, stop_gradient=True)
    helper.append_op(
        type="arg_max", inputs={"X": [x.name]}, outputs={"Out": [out.name]},
        attrs={"axis": axis},
    )
    return out


def argmin(x, axis=-1):
    helper = LayerHelper("arg_min")
    shape = [s for i, s in enumerate(x.shape) if i != (axis % len(x.shape))]
    out = helper.create_tmp_variable("int64", shape, stop_gradient=True)
    helper.append_op(
        type="arg_min", inputs={"X": [x.name]}, outputs={"Out": [out.name]},
        attrs={"axis": axis},
    )
    return out


def shape(input):
    helper = LayerHelper("shape")
    out = helper.create_tmp_variable("int64", [len(input.shape)], stop_gradient=True)
    helper.append_op(
        type="shape", inputs={"Input": [input.name]}, outputs={"Out": [out.name]}
    )
    return out


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    out = x if in_place else helper.create_tmp_variable(x.dtype, x.shape)
    helper.append_op(
        type="increment", inputs={"X": [x.name]}, outputs={"Out": [out.name]},
        attrs={"step": float(value)},
    )
    return out


def one_hot(input, depth):
    helper = LayerHelper("one_hot")
    shape = list(input.shape)
    if shape and shape[-1] == 1:
        shape = shape[:-1]
    out = helper.create_tmp_variable("float32", shape + [depth])
    helper.append_op(
        type="one_hot", inputs={"X": [input.name]}, outputs={"Out": [out.name]},
        attrs={"depth": depth},
    )
    return out
