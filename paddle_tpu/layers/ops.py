"""Auto-generated thin op wrappers (reference: fluid/layers/ops.py, produced
by layer_function_generator.py from OpProtos).  Each wrapper creates an
output temp var and appends the op."""

from .layer_helper import LayerHelper

_UNARY_OPS = [
    "sigmoid", "logsigmoid", "exp", "relu", "tanh", "tanh_shrink", "sqrt",
    "abs", "ceil", "floor", "round", "reciprocal", "log", "square",
    "softplus", "softsign", "brelu", "leaky_relu", "soft_relu", "elu",
    "relu6", "pow", "stanh", "hard_shrink", "softshrink", "thresholded_relu",
    "hard_sigmoid", "swish", "sign", "assign_value",
]

_BINARY_OPS = [
    "elementwise_add", "elementwise_sub", "elementwise_mul", "elementwise_div",
    "elementwise_max", "elementwise_min", "elementwise_pow",
    "less_than", "less_equal", "greater_than", "greater_equal", "equal",
    "not_equal", "logical_and", "logical_or", "logical_xor",
]

__all__ = list(_UNARY_OPS) + list(_BINARY_OPS) + ["logical_not", "uniform_random", "gaussian_random"]


def _make_unary(op_type):
    def f(x, **attrs):
        helper = LayerHelper(op_type)
        out = helper.create_tmp_variable(x.dtype, list(x.shape), lod_level=x.lod_level)
        helper.append_op(
            type=op_type, inputs={"X": [x.name]}, outputs={"Out": [out.name]},
            attrs=attrs,
        )
        return out

    f.__name__ = op_type
    return f


def _make_binary(op_type):
    bool_out = op_type.split("_")[0] in (
        "less", "greater", "equal", "not", "logical"
    ) or op_type in ("equal", "not_equal")

    def f(x, y, axis=-1, **attrs):
        helper = LayerHelper(op_type)
        dtype = "bool" if bool_out else x.dtype
        shape = list(x.shape) if len(x.shape) >= len(y.shape) else list(y.shape)
        out = helper.create_tmp_variable(dtype, shape)
        a = dict(attrs)
        if op_type.startswith("elementwise"):
            a["axis"] = axis
        helper.append_op(
            type=op_type, inputs={"X": [x.name], "Y": [y.name]},
            outputs={"Out": [out.name]}, attrs=a,
        )
        return out

    f.__name__ = op_type
    return f


for _op in _UNARY_OPS:
    globals()[_op] = _make_unary(_op)
for _op in _BINARY_OPS:
    globals()[_op] = _make_binary(_op)


def logical_not(x):
    helper = LayerHelper("logical_not")
    out = helper.create_tmp_variable("bool", list(x.shape))
    helper.append_op(
        type="logical_not", inputs={"X": [x.name]}, outputs={"Out": [out.name]}
    )
    return out


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random")
    out = helper.create_tmp_variable(dtype, list(shape), stop_gradient=True)
    helper.append_op(
        type="uniform_random", outputs={"Out": [out.name]},
        attrs={"shape": list(shape), "dtype": str(dtype), "min": min, "max": max,
               "seed": seed},
    )
    return out


def gaussian_random(shape, dtype="float32", mean=0.0, std=1.0, seed=0):
    helper = LayerHelper("gaussian_random")
    out = helper.create_tmp_variable(dtype, list(shape), stop_gradient=True)
    helper.append_op(
        type="gaussian_random", outputs={"Out": [out.name]},
        attrs={"shape": list(shape), "dtype": str(dtype), "mean": mean, "std": std,
               "seed": seed},
    )
    return out
