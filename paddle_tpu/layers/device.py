"""Device layers (reference: fluid/layers/device.py:26 get_places — feeds
parallel_do's PLACE_LIST).  On TPU the analog of a place list is the device
mesh; parallelism is sharding, not scattering, so this returns the devices
for introspection only."""

import jax


def get_places(device_count=None, device_type=None):
    devs = jax.devices()
    if device_type == "CPU":
        devs = [d for d in devs if d.platform == "cpu"]
    elif device_type in ("TPU", "GPU", "CUDA"):
        devs = [d for d in devs if d.platform != "cpu"]
    if device_count:
        devs = devs[:device_count]
    return devs
