"""LayerHelper — shared plumbing for layer functions (reference:
python/paddle/v2/fluid/layer_helper.py): create parameters in the startup
program (with initializer ops) and main program, create temporaries, append
bias/activation ops."""

import numpy as np

from ..core.program import default_main_program, default_startup_program, Variable
from ..core import unique_name
from ..param_attr import ParamAttr
from .. import initializer as init_mod


class LayerHelper:
    def __init__(self, layer_type, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        self.name = name or unique_name.generate(layer_type)
        self.main_program = kwargs.get("main_program") or default_main_program()
        self.startup_program = (
            kwargs.get("startup_program") or default_startup_program()
        )

    @property
    def main_block(self):
        return self.main_program.current_block()

    def append_op(self, **kwargs):
        return self.main_block.append_op(**kwargs)

    def create_tmp_variable(self, dtype, shape=None, lod_level=0, stop_gradient=False):
        return self.main_block.create_var(
            name=unique_name.generate(f"{self.name}.tmp"),
            dtype=dtype,
            shape=shape or (),
            lod_level=lod_level,
            stop_gradient=stop_gradient,
        )

    def create_parameter(
        self, attr, shape, dtype, suffix="w", default_initializer=None
    ):
        attr = ParamAttr.to_attr(attr)
        if attr is None:
            return None
        name = attr.name or f"{self.name}.{suffix}"
        # Explicitly-named parameters are shared across layers (the reference
        # reuses the variable when two layers name the same ParamAttr, e.g.
        # word2vec's shared embedding table).
        gb = self.main_program.global_block()
        if attr.name is not None and attr.name in gb.vars:
            from ..core.program import Parameter
            from ..core.dtypes import convert_dtype

            existing = gb.vars[attr.name]
            if not isinstance(existing, Parameter):
                raise ValueError(
                    f"param_attr name {attr.name!r} collides with a "
                    f"non-parameter variable"
                )
            if tuple(existing.shape) != tuple(shape):
                raise ValueError(
                    f"shared parameter {attr.name!r} reused with shape "
                    f"{tuple(shape)} != existing {tuple(existing.shape)}"
                )
            if existing.dtype != convert_dtype(dtype):
                raise ValueError(
                    f"shared parameter {attr.name!r} reused with dtype "
                    f"{dtype} != existing {existing.dtype.name}"
                )
            return existing
        init = attr.initializer or default_initializer
        if init is None:
            if suffix == "b":
                init = init_mod.Constant(0.0)
            else:
                init = init_mod.Xavier()
        # main-program parameter (referenced by compute ops)
        param = self.main_program.global_block().create_parameter(
            name=name,
            shape=shape,
            dtype=dtype,
            trainable=attr.trainable,
            regularizer=attr.regularizer,
            gradient_clip_attr=attr.gradient_clip,
            optimize_attr={"learning_rate": attr.learning_rate},
            initializer=init,
        )
        # startup-program twin + its init op
        sb = self.startup_program.global_block()
        if name not in sb.vars:
            svar = sb.create_var(
                name=name, shape=shape, dtype=dtype, persistable=True
            )
            init(svar, sb)
        return param

    def create_global_variable(
        self, shape, dtype, name=None, persistable=True, initializer=None,
        stop_gradient=True,
    ):
        """Non-trainable persistable state (BN stats, metric accumulators,
        LR counters)."""
        name = name or unique_name.generate(f"{self.name}.global")
        var = self.main_program.global_block().create_var(
            name=name, shape=shape, dtype=dtype, persistable=persistable,
            stop_gradient=stop_gradient,
        )
        if initializer is not None:
            sb = self.startup_program.global_block()
            if name not in sb.vars:
                svar = sb.create_var(
                    name=name, shape=shape, dtype=dtype, persistable=True
                )
                initializer(svar, sb)
        return var

    # -- composite helpers -------------------------------------------------
    def input_dtype(self, x):
        return x.dtype

    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        bias_attr = self.kwargs.get("bias_attr")
        if bias_attr is False:
            return input_var
        size = list(input_var.shape[dim_start:dim_end])
        b = self.create_parameter(
            ParamAttr.to_attr(bias_attr), shape=size, dtype=input_var.dtype,
            suffix="b", default_initializer=init_mod.Constant(0.0),
        )
        if b is None:
            return input_var
        out = self.create_tmp_variable(input_var.dtype, input_var.shape)
        self.append_op(
            type="elementwise_add",
            inputs={"X": [input_var.name], "Y": [b.name]},
            outputs={"Out": [out.name]},
            attrs={"axis": dim_start},
        )
        return out

    def append_activation(self, input_var):
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        act = dict(act)
        act_type = act.pop("type")
        out = self.create_tmp_variable(input_var.dtype, input_var.shape)
        self.append_op(
            type=act_type,
            inputs={"X": [input_var.name]},
            outputs={"Out": [out.name]},
            attrs=act,
        )
        return out


def seq_length(x):
    """The Length input for sequence-aware ops: the shadow ``@LENGTH`` var
    if x is a sequence (lod_level > 0), else None."""
    if getattr(x, "lod_level", 0) and x.lod_level > 0:
        return x.length_var()
    return None
