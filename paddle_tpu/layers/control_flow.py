"""Control-flow layers.

Reference: fluid/layers/control_flow.py (16 constructs incl. While:583,
StaticRNN, array ops, less_than, increment).  Sub-blocks are recorded in the
program and lowered to lax.while_loop / lax.scan by the control-flow ops
(ops/control_flow_ops.py) — structured, compiled control flow instead of
interpreter re-entry.
"""

import contextlib

from ..core.program import default_main_program
from ..core import unique_name
from .layer_helper import LayerHelper, seq_length
from . import tensor as tensor_layers

__all__ = [
    "While",
    "StaticRNN",
    "DynamicRNN",
    "array_write",
    "array_read",
    "array_length",
    "create_array",
    "increment",
    "less_than",
    "max_sequence_len",
    "ParallelDo",
]

from .ops import less_than  # re-export (compare layer lives in ops)
from .tensor import increment


def create_array(dtype, max_len, shape):
    """A preallocated tensor array [max_len, ...] — the LoDTensorArray
    analog with static capacity."""
    return tensor_layers.fill_constant([max_len] + list(shape), dtype, 0.0)


def array_write(x, i, array):
    helper = LayerHelper("array_write")
    helper.append_op(
        type="array_write",
        inputs={"X": [x.name], "I": [i.name], "Array": [array.name]},
        outputs={"Out": [array.name]},
    )
    return array


def array_read(array, i):
    helper = LayerHelper("array_read")
    out = helper.create_tmp_variable(array.dtype, list(array.shape[1:]))
    helper.append_op(
        type="array_read",
        inputs={"Array": [array.name], "I": [i.name]},
        outputs={"Out": [out.name]},
    )
    return out


def array_length(array):
    helper = LayerHelper("array_length")
    out = helper.create_tmp_variable("int64", [1], stop_gradient=True)
    helper.append_op(
        type="array_length", inputs={"Array": [array.name]},
        outputs={"Out": [out.name]},
    )
    return out


def max_sequence_len(x):
    """Max length of a padded sequence batch (max_sequence_len_op analog)."""
    helper = LayerHelper("max_sequence_len")
    ln = seq_length(x)
    out = helper.create_tmp_variable("int32", [1], stop_gradient=True)
    helper.append_op(
        type="reduce_max", inputs={"X": [ln.name]}, outputs={"Out": [out.name]},
        attrs={"reduce_all": True, "keep_dim": True},
    )
    return out


class While:
    """while-loop construct (control_flow.py:583).

    with While(cond).block():
        ...ops...
        # update cond inside the block
    Carried state = condition + every var written in the block that existed
    before it; shapes must stay constant (XLA).
    """

    def __init__(self, cond, name=None):
        self.helper = LayerHelper("while", name=name)
        self.cond_var = cond

    @contextlib.contextmanager
    def block(self):
        prog = self.helper.main_program
        parent = prog.current_block()
        sub = prog.create_block()
        yield
        prog.rollback()
        parent.append_op(
            type="while",
            inputs={"Condition": [self.cond_var.name]},
            outputs={},
            attrs={"sub_block": sub.idx},
        )


class StaticRNN:
    """Scan-based RNN builder (control_flow.py StaticRNN): step inputs are
    time-slices of sequence tensors; memories are loop-carried."""

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self._x_outer = []
        self._x_inner = []
        self._init_outer = []
        self._state_names = []
        self._out_names = []
        self._outputs = []
        self._sub = None
        self._parent = None
        self._length_name = None

    def set_sequence_length(self, length_var):
        """Freeze carried states past each sample's length (the LoD
        semantics: padded steps do not advance memories)."""
        self._length_name = length_var.name

    @contextlib.contextmanager
    def step(self):
        prog = self.helper.main_program
        self._parent = prog.current_block()
        self._sub = prog.create_block()
        yield
        prog.rollback()
        attrs = {
            "sub_block": self._sub.idx,
            "x_names": self._x_inner,
            "state_names": self._state_names,
            "out_names": self._out_names,
            "reverse": False,
        }
        if self._length_name is not None:
            attrs["length_name"] = self._length_name
        self._parent.append_op(
            type="scan_block",
            inputs={"X": self._x_outer, "Init": self._init_outer},
            outputs={"Out": [o.name for o in self._outputs]},
            attrs=attrs,
        )

    def step_input(self, x):
        """x: [b, t, ...] sequence var; returns the per-step slice [b, ...]."""
        inner = self._sub.create_var(
            name=unique_name.generate(f"{self.helper.name}.step_in"),
            dtype=x.dtype,
            shape=[x.shape[0]] + list(x.shape[2:]),
        )
        self._x_outer.append(x.name)
        self._x_inner.append(inner.name)
        return inner

    def memory(self, init):
        """Loop-carried state initialized from ``init`` [b, d]."""
        mem = self._sub.create_var(
            name=unique_name.generate(f"{self.helper.name}.mem"),
            dtype=init.dtype,
            shape=list(init.shape),
        )
        self._init_outer.append(init.name)
        self._state_names.append(mem.name)
        return mem

    def update_memory(self, mem, new_val):
        self._sub.append_op(
            type="assign", inputs={"X": [new_val.name]}, outputs={"Out": [mem.name]}
        )

    def step_output(self, o):
        self._out_names.append(o.name)
        outer = self._parent.create_var(
            name=unique_name.generate(f"{self.helper.name}.out"),
            dtype=o.dtype,
            shape=[o.shape[0], -1] + list(o.shape[1:]),
        )
        self._outputs.append(outer)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def __call__(self):
        if len(self._outputs) == 1:
            return self._outputs[0]
        return self._outputs


# DynamicRNN in the reference sorts by length into a rank table
# (lod_rank_table_op) and shrinks the batch each step; on TPU padded+masked
# scan (StaticRNN over padded batch, mask from @LENGTH) is the efficient
# equivalent, so DynamicRNN is StaticRNN with automatic masking.
DynamicRNN = StaticRNN


class ParallelDo:
    """Reference parallel_do (fluid/layers/control_flow.py ParallelDo):
    scatter over places, run block per place, gather.  On TPU the same
    program is SPMD-sharded over the mesh, so this construct records its
    block and lowers to inline execution; pair it with
    paddle_tpu.parallel.data_parallel() for actual multi-chip running."""

    def __init__(self, places=None, name=None):
        self.helper = LayerHelper("parallel_do", name=name)
        self._inputs = []

    @contextlib.contextmanager
    def do(self):
        prog = self.helper.main_program
        parent = prog.current_block()
        sub = prog.create_block()
        yield
        prog.rollback()
        parent.append_op(
            type="parallel_do",
            inputs={"X": self._inputs},
            outputs={},
            attrs={"sub_block": sub.idx},
        )

    def read_input(self, x):
        self._inputs.append(x.name)
        return x

    def write_output(self, o):
        return o
