"""Neural-net layers (reference: python/paddle/v2/fluid/layers/nn.py — fc:70,
embedding:191, dynamic_lstm:250, conv2d:913, batch_norm:1251, …).  Each layer
creates parameters through LayerHelper and appends ops; sequence-aware layers
wire the shadow ``@LENGTH`` variables automatically (the LoD replacement)."""

import numpy as np

from ..core.program import IDS_SUFFIX, VALS_SUFFIX, Variable
from ..param_attr import ParamAttr
from .. import initializer as init_mod
from .layer_helper import LayerHelper, seq_length

__all__ = [
    "link_sequence",
    "fc",
    "embedding",
    "dynamic_lstm",
    "dynamic_lstmp",
    "dynamic_gru",
    "gru_unit",
    "lstm_unit",
    "conv2d",
    "conv2d_transpose",
    "conv3d",
    "pool3d",
    "pool2d",
    "batch_norm",
    "layer_norm",
    "dropout",
    "cross_entropy",
    "square_error_cost",
    "accuracy",
    "auc",
    "chunk_eval",
    "sequence_conv",
    "sequence_pool",
    "sequence_first_step",
    "sequence_last_step",
    "sequence_expand",
    "sequence_reshape",
    "sequence_softmax",
    "sequence_reverse",
    "softmax",
    "softmax_with_cross_entropy",
    "fused_softmax_ce_head",
    "sigmoid_cross_entropy_with_logits",
    "smooth_l1",
    "matmul",
    "mul",
    "flash_attention",
    "flash_attention_packed",
    "multi_head_attention",
    "nested_sequence_pool",
    "nested_sequence_expand",
    "nested_sequence_slice",
    "sub_nested_seq",
    "nested_rnn",
    "topk",
    "warpctc",
    "ctc_greedy_decoder",
    "edit_distance",
    "l1_norm",
    "prelu",
    "bilinear_tensor_product",
    "l2_normalize",
    "im2sequence",
    "nce",
    "hsigmoid",
    "selective_fc",
    "row_conv",
    "multiplex",
    "linear_chain_crf",
    "crf_decoding",
    "cos_sim",
    "mean",
    "scale",
    "reduce_sum",
    "reduce_mean",
    "reduce_max",
    "reduce_min",
    "reduce_prod",
    "clip",
    "clip_by_norm",
    "beam_search",
    "beam_search_decode",
    "lrn",
    "maxout",
    "spp",
]


def _ntuple(v, n):
    # mirror ops/nn_ops.py _pair: sequences pass through, any scalar
    # (python or numpy int) broadcasts
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v,) * n


def _conv_osize(i, k, s, p, d=1):
    """Conv output extent (floor mode); -1 stays dynamic."""
    if i < 0:
        return -1
    eff = (k - 1) * d + 1
    return (i + 2 * p - eff) // s + 1


def _pool_osize(i, k, s, p, ceil_mode=False, global_pooling=False):
    if global_pooling:
        return 1
    if i < 0:
        return -1
    num = i + 2 * p - k
    return (num + s - 1) // s + 1 if ceil_mode else num // s + 1


def _seq_inputs(inputs, x):
    ln = seq_length(x)
    if ln is not None:
        inputs["Length"] = [ln.name]
    return ln


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None, **kwargs):
    helper = LayerHelper("fc", bias_attr=bias_attr, act=act, name=name, **kwargs)
    inputs = input if isinstance(input, (list, tuple)) else [input]
    mul_results = []
    for i, x in enumerate(inputs):
        suffix = "w" if len(inputs) == 1 else f"w_{i}"
        if getattr(x, "sparse_slot", False):
            # native sparse input slot: weighted gather-sum, O(nnz) not
            # O(dim) — the fc-over-sparse-Argument path (layers.sparse_data)
            w = helper.create_parameter(
                param_attr, shape=[x.shape[-1], size], dtype=x.dtype,
                suffix=suffix,
            )
            out_shape = list(x.shape[:-1]) + [size]
            tmp = helper.create_tmp_variable(
                x.dtype, out_shape, lod_level=x.lod_level)
            helper.append_op(
                type="sparse_fc",
                inputs={"Ids": [x.name + IDS_SUFFIX],
                        "Vals": [x.name + VALS_SUFFIX], "W": [w.name]},
                outputs={"Out": [tmp.name]},
            )
            _link_length(tmp, x)
            mul_results.append(tmp)
            continue
        in_dim = int(np.prod(x.shape[num_flatten_dims:]))
        # one weight per input (duplicable W slot); w_0, w_1... when several
        w = helper.create_parameter(
            param_attr, shape=[in_dim, size], dtype=x.dtype,
            suffix=suffix,
        )
        out_shape = list(x.shape[:num_flatten_dims]) + [size]
        tmp = helper.create_tmp_variable(x.dtype, out_shape, lod_level=x.lod_level)
        helper.append_op(
            type="mul",
            inputs={"X": [x.name], "Y": [w.name]},
            outputs={"Out": [tmp.name]},
            attrs={"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1},
        )
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_tmp_variable(
            mul_results[0].dtype, mul_results[0].shape,
            lod_level=mul_results[0].lod_level,
        )
        helper.append_op(
            type="sum", inputs={"X": mul_results}, outputs={"Out": [pre_bias.name]}
        )
    pre_act = helper.append_bias_op(pre_bias, dim_start=len(pre_bias.shape) - 1)
    out = helper.append_activation(pre_act)
    out.lod_level = inputs[0].lod_level
    return out


def embedding(input, size, is_sparse=False, padding_idx=None, param_attr=None,
              dtype="float32", name=None):
    helper = LayerHelper("embedding", name=name)
    w = helper.create_parameter(
        param_attr, shape=list(size), dtype=dtype, suffix="w",
        default_initializer=init_mod.Uniform(-0.05, 0.05),
    )
    ishape = list(input.shape)
    if ishape and ishape[-1] == 1:
        ishape = ishape[:-1]
    out = helper.create_tmp_variable(dtype, ishape + [size[1]], lod_level=input.lod_level)
    helper.append_op(
        type="lookup_table",
        inputs={"W": [w.name], "Ids": [input.name]},
        outputs={"Out": [out.name]},
        attrs={
            "is_sparse": is_sparse,
            "padding_idx": -1 if padding_idx is None else padding_idx,
        },
    )
    if input.lod_level > 0:
        # propagate sequence lengths to the embedded output
        out.block.vars[out.name + "@LENGTH"] = input.length_var()
        out.lod_level = input.lod_level
    return out


def _link_length(out, src):
    """Make ``out`` share ``src``'s sequence-length variable."""
    if getattr(src, "lod_level", 0) > 0:
        out.block.vars.setdefault(out.name + "@LENGTH", src.length_var())
        out.lod_level = src.lod_level
    return out


def link_sequence(out, src):
    """Public helper: mark ``out`` as a sequence batch sharing ``src``'s
    lengths (useful after shape-preserving layers like fc with
    num_flatten_dims=2)."""
    return _link_length(out, src)


def dynamic_lstm(input, size, use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", param_attr=None, bias_attr=None,
                 name=None):
    """LSTM over a padded sequence batch [b, t, 4d] (input pre-projected to
    4*hidden, reference dynamic_lstm nn.py:250).  size = 4*hidden."""
    helper = LayerHelper("lstm", name=name)
    d = size // 4
    weight = helper.create_parameter(param_attr, shape=[d, 4 * d], dtype=input.dtype)
    bias_size = 7 * d if use_peepholes else 4 * d
    bias = helper.create_parameter(
        ParamAttr.to_attr(bias_attr) or ParamAttr(), shape=[1, bias_size],
        dtype=input.dtype, suffix="b", default_initializer=init_mod.Constant(0.0),
    )
    hidden = helper.create_tmp_variable(
        input.dtype, list(input.shape[:2]) + [d], lod_level=input.lod_level
    )
    cell = helper.create_tmp_variable(
        input.dtype, list(input.shape[:2]) + [d], lod_level=input.lod_level
    )
    inputs = {"Input": [input.name], "Weight": [weight.name], "Bias": [bias.name]}
    _seq_inputs(inputs, input)
    helper.append_op(
        type="lstm",
        inputs=inputs,
        outputs={"Hidden": [hidden.name], "Cell": [cell.name]},
        attrs={
            "use_peepholes": use_peepholes,
            "is_reverse": is_reverse,
            "gate_activation": gate_activation,
            "cell_activation": cell_activation,
            "candidate_activation": candidate_activation,
        },
    )
    _link_length(hidden, input)
    _link_length(cell, input)
    return hidden, cell


def dynamic_lstmp(input, size, proj_size, use_peepholes=True, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  param_attr=None, bias_attr=None, name=None):
    helper = LayerHelper("lstmp", name=name)
    d = size // 4
    weight = helper.create_parameter(
        param_attr, shape=[proj_size, 4 * d], dtype=input.dtype
    )
    proj_weight = helper.create_parameter(
        param_attr, shape=[d, proj_size], dtype=input.dtype, suffix="proj_w"
    )
    bias_size = 7 * d if use_peepholes else 4 * d
    bias = helper.create_parameter(
        ParamAttr.to_attr(bias_attr) or ParamAttr(), shape=[1, bias_size],
        dtype=input.dtype, suffix="b", default_initializer=init_mod.Constant(0.0),
    )
    proj = helper.create_tmp_variable(
        input.dtype, list(input.shape[:2]) + [proj_size], lod_level=input.lod_level
    )
    inputs = {
        "Input": [input.name],
        "Weight": [weight.name],
        "ProjWeight": [proj_weight.name],
        "Bias": [bias.name],
    }
    _seq_inputs(inputs, input)
    helper.append_op(
        type="lstmp",
        inputs=inputs,
        outputs={"Projection": [proj.name]},
        attrs={
            "use_peepholes": use_peepholes,
            "is_reverse": is_reverse,
            "gate_activation": gate_activation,
            "cell_activation": cell_activation,
            "candidate_activation": candidate_activation,
            "proj_activation": proj_activation,
        },
    )
    return _link_length(proj, input)


def dynamic_gru(input, size, is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", param_attr=None, bias_attr=None,
                h_0=None, name=None):
    """GRU over padded batch [b, t, 3d]; size = hidden d."""
    helper = LayerHelper("gru", name=name)
    d = size
    weight = helper.create_parameter(param_attr, shape=[d, 3 * d], dtype=input.dtype)
    bias = helper.create_parameter(
        ParamAttr.to_attr(bias_attr) or ParamAttr(), shape=[1, 3 * d],
        dtype=input.dtype, suffix="b", default_initializer=init_mod.Constant(0.0),
    )
    hidden = helper.create_tmp_variable(
        input.dtype, list(input.shape[:2]) + [d], lod_level=input.lod_level
    )
    inputs = {"Input": [input.name], "Weight": [weight.name], "Bias": [bias.name]}
    if h_0 is not None:
        inputs["H0"] = [h_0.name]
    _seq_inputs(inputs, input)
    helper.append_op(
        type="gru",
        inputs=inputs,
        outputs={"Hidden": [hidden.name]},
        attrs={
            "is_reverse": is_reverse,
            "gate_activation": gate_activation,
            "activation": candidate_activation,
        },
    )
    return _link_length(hidden, input)


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid"):
    """One GRU step (nn.py gru_unit); size = 3*hidden_dim."""
    helper = LayerHelper("gru_unit")
    d = size // 3
    weight = helper.create_parameter(param_attr, shape=[d, 3 * d], dtype=input.dtype)
    bias = helper.create_parameter(
        ParamAttr.to_attr(bias_attr) or ParamAttr(), shape=[1, 3 * d],
        dtype=input.dtype, suffix="b", default_initializer=init_mod.Constant(0.0),
    )
    out = helper.create_tmp_variable(input.dtype, list(hidden.shape))
    helper.append_op(
        type="gru_unit",
        inputs={
            "Input": [input.name],
            "HiddenPrev": [hidden.name],
            "Weight": [weight.name],
            "Bias": [bias.name],
        },
        outputs={"Hidden": [out.name]},
        attrs={"activation": activation, "gate_activation": gate_activation},
    )
    return out


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None):
    """One LSTM step with its own input projection (nn.py lstm_unit)."""
    d = cell_t_prev.shape[-1]
    gates = fc([x_t, hidden_t_prev], size=4 * d, param_attr=param_attr,
               bias_attr=bias_attr if bias_attr is not None else ParamAttr())
    helper = LayerHelper("lstm_unit")
    c = helper.create_tmp_variable(x_t.dtype, list(cell_t_prev.shape))
    h = helper.create_tmp_variable(x_t.dtype, list(cell_t_prev.shape))
    helper.append_op(
        type="lstm_unit",
        inputs={"X": [gates.name], "C_prev": [cell_t_prev.name]},
        outputs={"C": [c.name], "H": [h.name]},
        attrs={"forget_bias": float(forget_bias)},
    )
    return h, c


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("conv2d", bias_attr=bias_attr, act=act, name=name)
    filter_size = _ntuple(filter_size, 2)
    stride, padding = _ntuple(stride, 2), _ntuple(padding, 2)
    dilation = _ntuple(dilation, 2)
    cin = input.shape[1]
    w = helper.create_parameter(
        param_attr,
        shape=[num_filters, cin // groups, filter_size[0], filter_size[1]],
        dtype=input.dtype,
        default_initializer=init_mod.MSRA(uniform=False),
    )
    oh = _conv_osize(input.shape[2], filter_size[0], stride[0], padding[0], dilation[0])
    ow = _conv_osize(input.shape[3], filter_size[1], stride[1], padding[1], dilation[1])
    pre_bias = helper.create_tmp_variable(
        input.dtype, [input.shape[0], num_filters, oh, ow]
    )
    helper.append_op(
        type="conv2d",
        inputs={"Input": [input.name], "Filter": [w.name]},
        outputs={"Output": [pre_bias.name]},
        attrs={
            "strides": list(stride),
            "paddings": list(padding),
            "dilations": list(dilation),
            "groups": groups,
        },
    )
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None, name=None):
    """5-D (NCDHW) convolution (reference conv_op.cc conv3d)."""
    helper = LayerHelper("conv3d", bias_attr=bias_attr, act=act, name=name)
    filter_size = _ntuple(filter_size, 3)
    stride, padding = _ntuple(stride, 3), _ntuple(padding, 3)
    dilation = _ntuple(dilation, 3)
    cin = input.shape[1]
    w = helper.create_parameter(
        param_attr,
        shape=[num_filters, cin // groups, *filter_size],
        dtype=input.dtype,
        default_initializer=init_mod.MSRA(uniform=False),
    )
    spatial = [
        _conv_osize(input.shape[2 + i], filter_size[i], stride[i],
                    padding[i], dilation[i])
        for i in range(3)
    ]
    pre_bias = helper.create_tmp_variable(
        input.dtype, [input.shape[0], num_filters, *spatial]
    )
    helper.append_op(
        type="conv3d",
        inputs={"Input": [input.name], "Filter": [w.name]},
        outputs={"Output": [pre_bias.name]},
        attrs={
            "strides": list(stride),
            "paddings": list(padding),
            "dilations": list(dilation),
            "groups": groups,
        },
    )
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def pool3d(input, pool_size=2, pool_type="max", pool_stride=1, pool_padding=0,
           global_pooling=False, ceil_mode=False, name=None):
    """5-D (NCDHW) pooling (reference pool_op.cc pool3d)."""
    helper = LayerHelper("pool3d", name=name)
    k = _ntuple(pool_size, 3)
    s, p = _ntuple(pool_stride, 3), _ntuple(pool_padding, 3)
    spatial = [
        _pool_osize(input.shape[2 + i], k[i], s[i], p[i], ceil_mode,
                    global_pooling)
        for i in range(3)
    ]
    out = helper.create_tmp_variable(
        input.dtype, [input.shape[0], input.shape[1], *spatial]
    )
    helper.append_op(
        type="pool3d",
        inputs={"X": [input.name]},
        outputs={"Out": [out.name]},
        attrs={"ksize": list(k), "strides": list(s), "paddings": list(p),
               "pooling_type": pool_type, "global_pooling": global_pooling,
               "ceil_mode": ceil_mode},
    )
    return out


def conv2d_transpose(input, num_filters, filter_size, stride=1, padding=0,
                     dilation=1, param_attr=None, bias_attr=None, act=None,
                     name=None):
    helper = LayerHelper("conv2d_transpose", bias_attr=bias_attr, act=act, name=name)
    filter_size = _ntuple(filter_size, 2)
    stride, padding = _ntuple(stride, 2), _ntuple(padding, 2)
    dilation = _ntuple(dilation, 2)
    cin = input.shape[1]
    w = helper.create_parameter(
        param_attr, shape=[cin, num_filters, filter_size[0], filter_size[1]],
        dtype=input.dtype,
    )

    def osize(i, k, s, p, d):
        # transpose-conv output extent (inverse of _conv_osize)
        if i < 0:
            return -1
        eff = (k - 1) * d + 1
        return (i - 1) * s - 2 * p + eff

    oh = osize(input.shape[2], filter_size[0], stride[0], padding[0], dilation[0])
    ow = osize(input.shape[3], filter_size[1], stride[1], padding[1], dilation[1])
    pre_bias = helper.create_tmp_variable(
        input.dtype, [input.shape[0], num_filters, oh, ow]
    )
    helper.append_op(
        type="conv2d_transpose",
        inputs={"Input": [input.name], "Filter": [w.name]},
        outputs={"Output": [pre_bias.name]},
        attrs={"strides": list(stride), "paddings": list(padding),
               "dilations": list(dilation)},
    )
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def pool2d(input, pool_size=2, pool_type="max", pool_stride=1, pool_padding=0,
           global_pooling=False, ceil_mode=False, name=None):
    helper = LayerHelper("pool2d", name=name)
    k = _ntuple(pool_size, 2)
    s, p = _ntuple(pool_stride, 2), _ntuple(pool_padding, 2)
    oh = _pool_osize(input.shape[2], k[0], s[0], p[0], ceil_mode,
                     global_pooling)
    ow = _pool_osize(input.shape[3], k[1], s[1], p[1], ceil_mode,
                     global_pooling)
    out = helper.create_tmp_variable(input.dtype, [input.shape[0], input.shape[1], oh, ow])
    helper.append_op(
        type="pool2d",
        inputs={"X": [input.name]},
        outputs={"Out": [out.name]},
        attrs={
            "ksize": list(k),
            "strides": list(s),
            "paddings": list(p),
            "pooling_type": pool_type,
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
        },
    )
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW", name=None):
    helper = LayerHelper("batch_norm", act=act, name=name)
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    dtype = "float32"  # stats and affine params in f32 even for bf16 activations
    scale = helper.create_parameter(
        param_attr, shape=[c], dtype=dtype, suffix="scale",
        default_initializer=init_mod.Constant(1.0),
    )
    bias = helper.create_parameter(
        ParamAttr.to_attr(bias_attr) or ParamAttr(), shape=[c], dtype=dtype,
        suffix="offset", default_initializer=init_mod.Constant(0.0),
    )
    mean = helper.create_global_variable(
        shape=[c], dtype=dtype, name=f"{helper.name}.mean",
        initializer=init_mod.Constant(0.0),
    )
    variance = helper.create_global_variable(
        shape=[c], dtype=dtype, name=f"{helper.name}.variance",
        initializer=init_mod.Constant(1.0),
    )
    saved_mean = helper.create_tmp_variable(dtype, [c], stop_gradient=True)
    saved_var = helper.create_tmp_variable(dtype, [c], stop_gradient=True)
    out = helper.create_tmp_variable(input.dtype, list(input.shape))
    helper.append_op(
        type="batch_norm",
        inputs={
            "X": [input.name],
            "Scale": [scale.name],
            "Bias": [bias.name],
            "Mean": [mean.name],
            "Variance": [variance.name],
        },
        outputs={
            "Y": [out.name],
            "MeanOut": [mean.name],
            "VarianceOut": [variance.name],
            "SavedMean": [saved_mean.name],
            "SavedVariance": [saved_var.name],
        },
        attrs={
            "momentum": momentum,
            "epsilon": epsilon,
            "is_test": is_test,
            "data_layout": data_layout,
        },
    )
    return helper.append_activation(out)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1, epsilon=1e-5,
               param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("layer_norm", act=act, name=name)
    norm_shape = [int(np.prod(input.shape[begin_norm_axis:]))]
    inputs = {"X": [input.name]}
    if scale:
        s = helper.create_parameter(
            param_attr, shape=norm_shape, dtype=input.dtype, suffix="scale",
            default_initializer=init_mod.Constant(1.0),
        )
        inputs["Scale"] = [s.name]
    if shift:
        b = helper.create_parameter(
            ParamAttr.to_attr(bias_attr) or ParamAttr(), shape=norm_shape,
            dtype=input.dtype, suffix="bias",
            default_initializer=init_mod.Constant(0.0),
        )
        inputs["Bias"] = [b.name]
    out = helper.create_tmp_variable(input.dtype, list(input.shape))
    mean = helper.create_tmp_variable("float32", list(input.shape[:begin_norm_axis]), stop_gradient=True)
    var = helper.create_tmp_variable("float32", list(input.shape[:begin_norm_axis]), stop_gradient=True)
    helper.append_op(
        type="layer_norm",
        inputs=inputs,
        outputs={"Y": [out.name], "Mean": [mean.name], "Variance": [var.name]},
        attrs={"begin_norm_axis": begin_norm_axis, "epsilon": epsilon},
    )
    return helper.append_activation(out)


def dropout(x, dropout_prob, is_test=False, seed=0, name=None):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_tmp_variable(x.dtype, list(x.shape), lod_level=x.lod_level)
    mask = helper.create_tmp_variable(x.dtype, list(x.shape), stop_gradient=True)
    helper.append_op(
        type="dropout",
        inputs={"X": [x.name]},
        outputs={"Out": [out.name], "Mask": [mask.name]},
        attrs={
            "dropout_prob": dropout_prob,
            "is_test": is_test,
            "seed": seed,
            "fix_seed": bool(seed),
        },
    )
    return _link_length(out, x)


def cross_entropy(input, label, soft_label=False):
    helper = LayerHelper("cross_entropy")
    out = helper.create_tmp_variable(input.dtype, list(input.shape[:-1]) + [1])
    helper.append_op(
        type="cross_entropy",
        inputs={"X": [input.name], "Label": [label.name]},
        outputs={"Y": [out.name]},
        attrs={"soft_label": soft_label},
    )
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False):
    helper = LayerHelper("softmax_with_cross_entropy")
    softmax_out = helper.create_tmp_variable(logits.dtype, list(logits.shape))
    loss = helper.create_tmp_variable(logits.dtype, list(logits.shape[:-1]) + [1])
    helper.append_op(
        type="softmax_with_cross_entropy",
        inputs={"Logits": [logits.name], "Label": [label.name]},
        outputs={"Softmax": [softmax_out.name], "Loss": [loss.name]},
        attrs={"soft_label": soft_label},
    )
    return loss


def fused_softmax_ce_head(input, label, size, param_attr=None, name=None,
                          block_n=512, block_v=1024, block_v_fwd=2048,
                          backend=None):
    """Fused LM-head loss: projection [d -> size] + softmax cross-entropy
    in one Pallas kernel that never materializes ``[..., size]`` logits in
    HBM (``ops/pallas_ce.py``).  Replaces the composed
    ``fc(bias_attr=False) + softmax_with_cross_entropy`` head (the
    reference's ``softmax_with_cross_entropy_op.cc`` path) for large
    vocabularies.  Returns per-position loss ``[..., 1]`` float32; rows
    with out-of-range labels (ignore_index) must be masked by the caller,
    exactly like the composed path."""
    helper = LayerHelper("fused_softmax_ce_head", name=name)
    in_dim = int(input.shape[-1])
    w = helper.create_parameter(
        param_attr, shape=[in_dim, size], dtype=input.dtype, suffix="w")
    loss = helper.create_tmp_variable(
        "float32", list(input.shape[:-1]) + [1])
    attrs = {"block_n": block_n, "block_v": block_v,
             "block_v_fwd": block_v_fwd}
    if backend:
        # kernel-registry routing pin (docs/kernels.md)
        attrs["backend"] = str(backend)
    helper.append_op(
        type="fused_softmax_ce_head",
        inputs={"X": [input.name], "W": [w.name], "Label": [label.name]},
        outputs={"Loss": [loss.name]},
        attrs=attrs,
    )
    return loss


def sigmoid_cross_entropy_with_logits(x, label):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits")
    out = helper.create_tmp_variable(x.dtype, list(x.shape))
    helper.append_op(
        type="sigmoid_cross_entropy_with_logits",
        inputs={"X": [x.name], "Label": [label.name]},
        outputs={"Out": [out.name]},
    )
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=1.0):
    helper = LayerHelper("smooth_l1")
    out = helper.create_tmp_variable(x.dtype, [x.shape[0], 1])
    diff = helper.create_tmp_variable(x.dtype, list(x.shape), stop_gradient=True)
    inputs = {"X": [x.name], "Y": [y.name]}
    if inside_weight is not None:
        inputs["InsideWeight"] = [inside_weight.name]
    if outside_weight is not None:
        inputs["OutsideWeight"] = [outside_weight.name]
    helper.append_op(
        type="smooth_l1_loss",
        inputs=inputs,
        outputs={"Out": [out.name], "Diff": [diff.name]},
        attrs={"sigma": sigma},
    )
    return out


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost")
    minus_out = helper.create_tmp_variable(input.dtype, list(input.shape))
    helper.append_op(
        type="elementwise_sub",
        inputs={"X": [input.name], "Y": [label.name]},
        outputs={"Out": [minus_out.name]},
    )
    out = helper.create_tmp_variable(input.dtype, list(input.shape))
    helper.append_op(
        type="square", inputs={"X": [minus_out.name]}, outputs={"Out": [out.name]}
    )
    return out


def accuracy(input, label, k=1, correct=None, total=None):
    helper = LayerHelper("accuracy")
    topk_out = helper.create_tmp_variable(input.dtype, [input.shape[0], k])
    topk_indices = helper.create_tmp_variable("int64", [input.shape[0], k], stop_gradient=True)
    helper.append_op(
        type="top_k",
        inputs={"X": [input.name]},
        outputs={"Out": [topk_out.name], "Indices": [topk_indices.name]},
        attrs={"k": k},
    )
    acc_out = helper.create_tmp_variable("float32", [1], stop_gradient=True)
    correct = correct or helper.create_tmp_variable("int32", [1], stop_gradient=True)
    total = total or helper.create_tmp_variable("int32", [1], stop_gradient=True)
    helper.append_op(
        type="accuracy",
        inputs={
            "Out": [topk_out.name],
            "Indices": [topk_indices.name],
            "Label": [label.name],
        },
        outputs={
            "Accuracy": [acc_out.name],
            "Correct": [correct.name],
            "Total": [total.name],
        },
    )
    return acc_out


def auc(input, label, curve="ROC", num_thresholds=200):
    helper = LayerHelper("auc")
    out = helper.create_tmp_variable("float32", [1], stop_gradient=True)
    helper.append_op(
        type="auc",
        inputs={"Out": [input.name], "Label": [label.name]},
        outputs={"AUC": [out.name]},
        attrs={"curve": curve, "num_thresholds": num_thresholds},
    )
    return out


def chunk_eval(input, label, chunk_scheme="IOB", num_chunk_types=1,
               excluded_chunk_types=None):
    helper = LayerHelper("chunk_eval")
    outs = {
        n: helper.create_tmp_variable(
            "float32" if i < 3 else "int64", [1], stop_gradient=True
        )
        for i, n in enumerate(
            ["Precision", "Recall", "F1-Score", "NumInferChunks",
             "NumLabelChunks", "NumCorrectChunks"]
        )
    }
    inputs = {"Inference": [input.name], "Label": [label.name]}
    _seq_inputs(inputs, input)
    helper.append_op(
        type="chunk_eval",
        inputs=inputs,
        outputs={k: [v.name] for k, v in outs.items()},
        attrs={"chunk_scheme": chunk_scheme, "num_chunk_types": num_chunk_types,
               "excluded_chunk_types": tuple(excluded_chunk_types or ())},
    )
    return (
        outs["Precision"], outs["Recall"], outs["F1-Score"],
        outs["NumInferChunks"], outs["NumLabelChunks"], outs["NumCorrectChunks"],
    )


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=None, param_attr=None, bias_attr=None, act=None,
                  name=None):
    helper = LayerHelper("sequence_conv", bias_attr=bias_attr, act=act, name=name)
    d = input.shape[-1]
    w = helper.create_parameter(
        param_attr, shape=[filter_size * d, num_filters], dtype=input.dtype
    )
    out = helper.create_tmp_variable(
        input.dtype, list(input.shape[:2]) + [num_filters], lod_level=input.lod_level
    )
    inputs = {"X": [input.name], "Filter": [w.name]}
    _seq_inputs(inputs, input)
    helper.append_op(
        type="sequence_conv",
        inputs=inputs,
        outputs={"Out": [out.name]},
        attrs={"contextLength": filter_size, "contextStart": -(filter_size // 2)},
    )
    _link_length(out, input)
    pre_act = helper.append_bias_op(out, dim_start=len(out.shape) - 1)
    return helper.append_activation(pre_act)


def sequence_pool(input, pool_type):
    helper = LayerHelper("sequence_pool")
    out = helper.create_tmp_variable(input.dtype, [input.shape[0]] + list(input.shape[2:]))
    inputs = {"X": [input.name]}
    _seq_inputs(inputs, input)
    helper.append_op(
        type="sequence_pool",
        inputs=inputs,
        outputs={"Out": [out.name]},
        attrs={"pooltype": pool_type.upper()},
    )
    return out


def sequence_first_step(input):
    return sequence_pool(input, "first")


def sequence_last_step(input):
    return sequence_pool(input, "last")


def sequence_expand(x, y, name=None):
    helper = LayerHelper("sequence_expand", name=name)
    t = y.shape[1] if len(y.shape) > 1 else -1
    out = helper.create_tmp_variable(
        x.dtype, [x.shape[0], t] + list(x.shape[1:]), lod_level=1
    )
    inputs = {"X": [x.name], "Y": [y.name]}
    yl = seq_length(y)
    if yl is not None:
        inputs["YLength"] = [yl.name]
        out.block.vars[out.name + "@LENGTH"] = yl
    helper.append_op(type="sequence_expand", inputs=inputs, outputs={"Out": [out.name]})
    return out


def sequence_reshape(input, new_dim):
    helper = LayerHelper("sequence_reshape")
    b, t, d = input.shape
    new_t = t * d // new_dim if t >= 0 else -1
    out = helper.create_tmp_variable(input.dtype, [b, new_t, new_dim], lod_level=1)
    inputs = {"X": [input.name]}
    _seq_inputs(inputs, input)
    helper.append_op(
        type="sequence_reshape",
        inputs=inputs,
        outputs={"Out": [out.name], "OutLength": [out.length_var().name]},
        attrs={"new_dim": new_dim},
    )
    return out


def sequence_softmax(x, name=None):
    helper = LayerHelper("sequence_softmax", name=name)
    out = helper.create_tmp_variable(x.dtype, list(x.shape), lod_level=x.lod_level)
    inputs = {"X": [x.name]}
    _seq_inputs(inputs, x)
    helper.append_op(
        type="sequence_softmax", inputs=inputs, outputs={"Out": [out.name]}
    )
    return _link_length(out, x)


def flash_attention_packed(q, k, v, n_head, causal=False, sm_scale=None,
                           block_q=None, block_k=None, backend=None,
                           name=None):
    """Fused attention on the raw projection layout: q/k/v [b, t, h*d]
    (what the QKV matmuls emit) -> [b, t, h*d] (what the out-projection
    consumes).  No [b,t,h,d]<->[bh,t,d] pack/unpack transposes exist —
    heads are lane slices in the kernel's block index maps
    (ops/pallas_attention.py).  Requires d_head % 128 == 0, d_head == 64
    with even n_head (two heads per lane slice), or n_head 1.
    ``block_q``/``block_k`` override the kernel tile sizes (the MFU tuning
    knob bench.py exposes as BENCH_GPT_BLOCK_Q/K)."""
    helper = LayerHelper("flash_attention_packed", name=name)
    out = helper.create_tmp_variable(q.dtype, q.shape)
    attrs = {"n_head": int(n_head), "causal": bool(causal),
             "sm_scale": 0.0 if sm_scale is None else float(sm_scale)}
    if backend:
        # kernel-registry routing (docs/kernels.md): pin this op to one
        # backend; unset resolves env overrides then the platform auto
        # order at trace time
        attrs["backend"] = str(backend)
    if block_q:
        attrs["block_q"] = int(block_q)
    if block_k:
        attrs["block_k"] = int(block_k)
    helper.append_op(
        type="flash_attention_packed",
        inputs={"Q": [q.name], "K": [k.name], "V": [v.name]},
        outputs={"Out": [out.name]},
        attrs=attrs,
    )
    return out


def softmax(x, name=None):
    helper = LayerHelper("softmax", name=name)
    out = helper.create_tmp_variable(x.dtype, list(x.shape))
    helper.append_op(type="softmax", inputs={"X": [x.name]}, outputs={"Out": [out.name]})
    return out


def flash_attention(q, k, v, causal=False, sm_scale=None, block_q=None,
                    block_k=None, backend=None, name=None):
    """Fused blockwise attention (registry-routed: Pallas TPU kernel,
    triton lowering, or the pure-XLA reference — docs/kernels.md).
    q [b, t_q, h, d], k/v [b, t_k, h, d] -> [b, t_q, h, d].
    ``block_q``/``block_k`` tune the kernel tiles (kernel defaults when
    omitted); ``backend`` pins the kernel backend for this op."""
    helper = LayerHelper("flash_attention", name=name)
    out = helper.create_tmp_variable(q.dtype, q.shape)
    attrs = {"causal": bool(causal),
             "sm_scale": 0.0 if sm_scale is None else float(sm_scale)}
    if backend:
        attrs["backend"] = str(backend)
    if block_q:
        attrs["block_q"] = int(block_q)
    if block_k:
        attrs["block_k"] = int(block_k)
    helper.append_op(
        type="flash_attention",
        inputs={"Q": [q.name], "K": [k.name], "V": [v.name]},
        outputs={"Out": [out.name]},
        attrs=attrs,
    )
    return out


def multi_head_attention(queries, keys, values, d_model, n_head,
                         dropout_rate=0.0, causal=False, is_test=False,
                         param_attr=None, block_q=None, block_k=None,
                         packed=None, backend=None, name=None):
    """Multi-head attention block: QKV projections -> fused flash
    attention (Pallas TPU kernel) -> output projection.

    The reference composes attention from fc + softmax
    (``trainer_config_helpers/networks.py simple_attention``); this is the
    modern multi-head form with the O(t) HBM-traffic kernel.  Inputs are
    ``[batch, time, dim]``; ``d_model`` must divide by ``n_head``.

    Kernel geometry is TUNABLE (docs/autotune.md): when the caller
    passes no explicit ``block_q``/``block_k``/``packed``, the autotune
    cache is consulted for this shape's measured winner
    (``tune.attention_config``; ``PADDLE_TPU_TUNE=0`` kills the lookup
    and a cache miss keeps today's defaults).  Explicit arguments always
    win.  ``packed`` forces the head routing: True = the transpose-free
    packed kernel (geometry permitting), False = the 4-D path, None =
    tuned/auto.
    """
    if d_model % n_head:
        raise ValueError(f"d_model {d_model} not divisible by n_head {n_head}")
    from .tensor import reshape
    from ..param_attr import ParamAttr

    def _proj_attr(suffix):
        # each projection needs its OWN parameter: a shared named attr
        # would silently tie Q/K/V/out weights together (create_parameter
        # reuses same-named params), so suffix any user-provided name.
        attr = ParamAttr.to_attr(param_attr)
        if attr is not None and attr.name is not None:
            import copy

            attr = copy.copy(attr)
            attr.name = f"{attr.name}_{suffix}"
        return attr

    b, tq = queries.shape[0], queries.shape[1]
    tk = keys.shape[1]
    dh = d_model // n_head
    if (block_q is None and block_k is None and backend is None
            and causal and tq == tk):
        # no explicit geometry: consult the autotune cache for this
        # shape's measured winner (None on miss/kill-switch — defaults)
        from ..tune import attention_config

        tuned = attention_config(tq, dh, n_head, queries.dtype,
                                 causal=causal)
        if tuned:
            block_q = tuned.get("block_q")
            block_k = tuned.get("block_k")
            if packed is None:
                packed = tuned.get("packed")
            # a tuned winner persists its kernel choice; re-resolve it
            # on THIS host now, non-strictly — the attr would reach
            # resolve() as an explicit (strict) request at trace time,
            # and a cached choice the host cannot serve (shared tune
            # cache, probe change) must degrade to auto instead of
            # crashing a user who never asked for a backend
            backend = tuned.get("backend")
            if backend:
                from ..kernels import resolve as _kresolve

                try:
                    _kresolve("flash_attention", backend)
                except Exception:  # unavailable/unknown tuned choice
                    backend = None
            if tuned.get("diag_w"):
                # the winner was MEASURED at this sub-tile width; the
                # kernels read the module global at trace time
                # (process-wide — last tuned build wins; the
                # PADDLE_TPU_DIAG_W env pin beats the cache)
                from ..ops.pallas_attention import apply_tuned_diag_w

                apply_tuned_diag_w(tuned["diag_w"])
    q = fc(queries, d_model, num_flatten_dims=2, param_attr=_proj_attr("q"),
           name=None if name is None else name + "_q")
    k = fc(keys, d_model, num_flatten_dims=2, param_attr=_proj_attr("k"),
           name=None if name is None else name + "_k")
    v = fc(values, d_model, num_flatten_dims=2, param_attr=_proj_attr("v"),
           name=None if name is None else name + "_v")
    from ..ops.pallas_attention import packed_sub_heads

    use_packed = packed_sub_heads(n_head, dh) is not None
    if packed is not None:
        use_packed = use_packed and bool(packed)
    if use_packed:
        # packable head geometry (d_head % 128 == 0, d_head == 64 with
        # even n_head — two heads per lane slice — or n_head == 1): the
        # packed kernel takes the projection outputs as-is and no head
        # pack/unpack transposes exist (8% of flagship device time on
        # the 4-D path — RESULTS.md round 4/5)
        ctx = flash_attention_packed(q, k, v, n_head, causal=causal,
                                     sm_scale=1.0 / float(dh) ** 0.5,
                                     block_q=block_q, block_k=block_k,
                                     backend=backend)
    else:
        qh = reshape(q, [b, tq, n_head, dh])
        kh = reshape(k, [b, tk, n_head, dh])
        vh = reshape(v, [b, tk, n_head, dh])
        ctx = flash_attention(qh, kh, vh, causal=causal,
                              sm_scale=1.0 / float(dh) ** 0.5,
                              block_q=block_q, block_k=block_k,
                              backend=backend)
        ctx = reshape(ctx, [b, tq, d_model])
    out = fc(ctx, d_model, num_flatten_dims=2, param_attr=_proj_attr("out"),
             name=None if name is None else name + "_out")
    if dropout_rate:
        out = dropout(out, dropout_rate, is_test=is_test)
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    helper = LayerHelper("matmul", name=name)
    xs = list(x.shape)
    ys = list(y.shape)
    if transpose_x:
        xs[-1], xs[-2] = xs[-2], xs[-1]
    if transpose_y:
        ys[-1], ys[-2] = ys[-2], ys[-1]
    out_shape = xs[:-1] + ys[-1:]
    out = helper.create_tmp_variable(x.dtype, out_shape)
    helper.append_op(
        type="matmul",
        inputs={"X": [x.name], "Y": [y.name]},
        outputs={"Out": [out.name]},
        attrs={"transpose_X": transpose_x, "transpose_Y": transpose_y},
    )
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1):
    helper = LayerHelper("mul")
    out_shape = list(x.shape[:x_num_col_dims]) + list(y.shape[y_num_col_dims:])
    out = helper.create_tmp_variable(x.dtype, out_shape)
    helper.append_op(
        type="mul",
        inputs={"X": [x.name], "Y": [y.name]},
        outputs={"Out": [out.name]},
        attrs={"x_num_col_dims": x_num_col_dims, "y_num_col_dims": y_num_col_dims},
    )
    return out


def topk(input, k):
    helper = LayerHelper("top_k")
    vals = helper.create_tmp_variable(input.dtype, list(input.shape[:-1]) + [k])
    idx = helper.create_tmp_variable("int64", list(input.shape[:-1]) + [k], stop_gradient=True)
    helper.append_op(
        type="top_k",
        inputs={"X": [input.name]},
        outputs={"Out": [vals.name], "Indices": [idx.name]},
        attrs={"k": k},
    )
    return vals, idx


def warpctc(input, label, blank=0, norm_by_times=False):
    helper = LayerHelper("warpctc")
    loss = helper.create_tmp_variable(input.dtype, [input.shape[0], 1])
    inputs = {"Logits": [input.name], "Label": [label.name]}
    il = seq_length(input)
    ll = seq_length(label)
    if il is not None:
        inputs["LogitsLength"] = [il.name]
    if ll is not None:
        inputs["LabelLength"] = [ll.name]
    helper.append_op(
        type="warpctc",
        inputs=inputs,
        outputs={"Loss": [loss.name]},
        attrs={"blank": blank, "norm_by_times": norm_by_times},
    )
    return loss


def ctc_greedy_decoder(input, blank):
    helper = LayerHelper("ctc_greedy_decoder")
    # input: [b, t, V] probs -> argmax ids -> collapse
    ids = helper.create_tmp_variable("int64", list(input.shape[:2]), stop_gradient=True)
    helper.append_op(
        type="arg_max", inputs={"X": [input.name]}, outputs={"Out": [ids.name]},
        attrs={"axis": -1},
    )
    out = helper.create_tmp_variable("int64", list(input.shape[:2]), lod_level=1, stop_gradient=True)
    inputs = {"Input": [ids.name]}
    il = seq_length(input)
    if il is not None:
        inputs["Length"] = [il.name]
    helper.append_op(
        type="ctc_align",
        inputs=inputs,
        outputs={"Output": [out.name], "OutputLength": [out.length_var().name]},
        attrs={"blank": blank, "merge_repeated": True},
    )
    return out


def edit_distance(input, label, normalized=False, ignored_tokens=None):
    helper = LayerHelper("edit_distance")
    hyp, ref = input, label
    if ignored_tokens:
        for var in (hyp, ref):
            pass  # handled by sequence_erase below
        new_hyp = helper.create_tmp_variable(hyp.dtype, list(hyp.shape), lod_level=1, stop_gradient=True)
        inputs = {"X": [hyp.name]}
        hl = seq_length(hyp)
        if hl is not None:
            inputs["Length"] = [hl.name]
        helper.append_op(
            type="sequence_erase", inputs=inputs,
            outputs={"Out": [new_hyp.name], "OutLength": [new_hyp.length_var().name]},
            attrs={"tokens": list(ignored_tokens)},
        )
        hyp = new_hyp
        new_ref = helper.create_tmp_variable(ref.dtype, list(ref.shape), lod_level=1, stop_gradient=True)
        inputs = {"X": [ref.name]}
        rl = seq_length(ref)
        if rl is not None:
            inputs["Length"] = [rl.name]
        helper.append_op(
            type="sequence_erase", inputs=inputs,
            outputs={"Out": [new_ref.name], "OutLength": [new_ref.length_var().name]},
            attrs={"tokens": list(ignored_tokens)},
        )
        ref = new_ref
    out = helper.create_tmp_variable("float32", [input.shape[0], 1], stop_gradient=True)
    seq_num = helper.create_tmp_variable("int64", [1], stop_gradient=True)
    inputs = {"Hyps": [hyp.name], "Refs": [ref.name]}
    hl, rl = seq_length(hyp), seq_length(ref)
    if hl is not None:
        inputs["HypsLength"] = [hl.name]
    if rl is not None:
        inputs["RefsLength"] = [rl.name]
    helper.append_op(
        type="edit_distance",
        inputs=inputs,
        outputs={"Out": [out.name], "SequenceNum": [seq_num.name]},
        attrs={"normalized": normalized},
    )
    return out, seq_num


def l1_norm(x, name=None):
    helper = LayerHelper("l1_norm", name=name)
    out = helper.create_tmp_variable(x.dtype, [1])
    helper.append_op(type="l1_norm", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]})
    return out


def prelu(x, param_attr=None, name=None):
    """Parametric ReLU with a learnable scalar alpha (reference
    prelu_op.cc)."""
    helper = LayerHelper("prelu", name=name)
    alpha = helper.create_parameter(
        param_attr, shape=[1], dtype=x.dtype, suffix="alpha",
        default_initializer=init_mod.Constant(0.25),
    )
    out = helper.create_tmp_variable(x.dtype, list(x.shape))
    helper.append_op(
        type="prelu",
        inputs={"X": [x.name], "Alpha": [alpha.name]},
        outputs={"Out": [out.name]},
    )
    return out


def bilinear_tensor_product(x, y, size, param_attr=None, bias_attr=None,
                            act=None, name=None):
    """out[b,i] = x[b] @ W[i] @ y[b] + bias[i] (reference
    bilinear_tensor_product_op.h:30)."""
    helper = LayerHelper("bilinear_tensor_product", bias_attr=bias_attr,
                         act=act, name=name)
    w = helper.create_parameter(
        param_attr, shape=[size, x.shape[-1], y.shape[-1]], dtype=x.dtype,
    )
    out = helper.create_tmp_variable(x.dtype, [x.shape[0], size])
    inputs = {"X": [x.name], "Y": [y.name], "Weight": [w.name]}
    if bias_attr is not False:
        b = helper.create_parameter(
            ParamAttr.to_attr(bias_attr) or ParamAttr(), shape=[size],
            dtype=x.dtype, suffix="b",
            default_initializer=init_mod.Constant(0.0),
        )
        inputs["Bias"] = [b.name]
    helper.append_op(
        type="bilinear_tensor_product",
        inputs=inputs,
        outputs={"Out": [out.name]},
    )
    return helper.append_activation(out)


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", name=name)
    square = helper.create_tmp_variable(x.dtype, list(x.shape))
    helper.append_op(type="square", inputs={"X": [x.name]}, outputs={"Out": [square.name]})
    ssum = helper.create_tmp_variable(x.dtype, [s if i != axis % len(x.shape) else 1 for i, s in enumerate(x.shape)])
    helper.append_op(
        type="reduce_sum", inputs={"X": [square.name]}, outputs={"Out": [ssum.name]},
        attrs={"dim": axis, "keep_dim": True},
    )
    eps = helper.create_tmp_variable(x.dtype, [1])
    helper.append_op(
        type="fill_constant", outputs={"Out": [eps.name]},
        attrs={"shape": [1], "dtype": str(x.dtype.name), "value": float(epsilon)},
    )
    maxed = helper.create_tmp_variable(x.dtype, ssum.shape)
    helper.append_op(
        type="elementwise_max", inputs={"X": [ssum.name], "Y": [eps.name]},
        outputs={"Out": [maxed.name]},
    )
    rsq = helper.create_tmp_variable(x.dtype, ssum.shape)
    helper.append_op(type="sqrt", inputs={"X": [maxed.name]}, outputs={"Out": [rsq.name]})
    out = helper.create_tmp_variable(x.dtype, list(x.shape))
    helper.append_op(
        type="elementwise_div", inputs={"X": [x.name], "Y": [rsq.name]},
        outputs={"Out": [out.name]}, attrs={"axis": 0},
    )
    return out


def im2sequence(input, filter_size=1, stride=1, padding=0, name=None):
    helper = LayerHelper("im2sequence", name=name)
    k = (filter_size, filter_size) if isinstance(filter_size, int) else tuple(filter_size)
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    p = (padding,) * 4 if isinstance(padding, int) else tuple(padding)
    n, c, h, w = input.shape
    oh = (h + p[0] + p[2] - k[0]) // s[0] + 1 if h >= 0 else -1
    ow = (w + p[1] + p[3] - k[1]) // s[1] + 1 if w >= 0 else -1
    t = oh * ow if oh >= 0 and ow >= 0 else -1
    out = helper.create_tmp_variable(input.dtype, [n, t, c * k[0] * k[1]], lod_level=1)
    helper.append_op(
        type="im2sequence",
        inputs={"X": [input.name]},
        outputs={"Out": [out.name]},
        attrs={"kernels": list(k), "strides": list(s), "paddings": list(p)},
    )
    return out


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None):
    """Hierarchical sigmoid over a complete binary tree — large-vocab
    classification at O(log C) cost (reference
    ``paddle/gserver/layers/HierarchicalSigmoidLayer.cpp:1``, config
    helper ``hsigmoid`` in trainer_config_helpers/layers.py)."""
    helper = LayerHelper("hsigmoid", name=name)
    dim = input.shape[1]
    w = helper.create_parameter(
        param_attr, shape=[num_classes - 1, dim], dtype=input.dtype)
    inputs = {"X": [input.name], "W": [w.name], "Label": [label.name]}
    if bias_attr is not False:
        b = helper.create_parameter(
            ParamAttr.to_attr(bias_attr) or ParamAttr(),
            shape=[num_classes - 1], dtype=input.dtype, suffix="b",
            default_initializer=init_mod.Constant(0.0),
        )
        inputs["Bias"] = [b.name]
    max_len = max(1, (2 * num_classes - 1).bit_length() - 1)
    cost = helper.create_tmp_variable(input.dtype, [input.shape[0], 1])
    pre_out = helper.create_tmp_variable(
        input.dtype, [input.shape[0], max_len], stop_gradient=True)
    helper.append_op(
        type="hierarchical_sigmoid",
        inputs=inputs,
        outputs={"Out": [cost.name], "PreOut": [pre_out.name]},
        attrs={"num_classes": num_classes},
    )
    return cost


def selective_fc(input, size, select=None, param_attr=None, bias_attr=None,
                 act=None, name=None):
    """Fully-connected layer that evaluates only the selected output
    columns per sample (reference
    ``paddle/gserver/layers/SelectiveFcLayer.cpp:1``; weight stored one
    row per output neuron, as there).  ``select`` is an int tensor
    [batch, s] of column ids (entries < 0 are padding); omit it for a
    plain full fc pass."""
    helper = LayerHelper("selective_fc", bias_attr=bias_attr, act=act,
                         name=name)
    dim = input.shape[1]
    w = helper.create_parameter(param_attr, shape=[size, dim],
                                dtype=input.dtype)
    inputs = {"X": [input.name], "W": [w.name]}
    if bias_attr is not False:
        b = helper.create_parameter(
            ParamAttr.to_attr(bias_attr) or ParamAttr(), shape=[size],
            dtype=input.dtype, suffix="b",
            default_initializer=init_mod.Constant(0.0),
        )
        inputs["Bias"] = [b.name]
    out_cols = select.shape[1] if select is not None else size
    if select is not None:
        inputs["Select"] = [select.name]
    out = helper.create_tmp_variable(input.dtype, [input.shape[0], out_cols])
    helper.append_op(
        type="selective_fc", inputs=inputs, outputs={"Out": [out.name]},
    )
    return helper.append_activation(out)


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=10, name=None):
    helper = LayerHelper("nce", name=name)
    dim = input.shape[1]
    w = helper.create_parameter(param_attr, shape=[num_total_classes, dim], dtype=input.dtype)
    b = helper.create_parameter(
        ParamAttr.to_attr(bias_attr) or ParamAttr(), shape=[num_total_classes],
        dtype=input.dtype, suffix="b", default_initializer=init_mod.Constant(0.0),
    )
    cost = helper.create_tmp_variable(input.dtype, [input.shape[0], 1])
    sample_logits = helper.create_tmp_variable(input.dtype, [input.shape[0], num_neg_samples + 1], stop_gradient=True)
    sample_labels = helper.create_tmp_variable("int64", [input.shape[0], num_neg_samples + 1], stop_gradient=True)
    inputs = {"Input": [input.name], "Label": [label.name], "Weight": [w.name], "Bias": [b.name]}
    if sample_weight is not None:
        inputs["SampleWeight"] = [sample_weight.name]
    helper.append_op(
        type="nce",
        inputs=inputs,
        outputs={
            "Cost": [cost.name],
            "SampleLogits": [sample_logits.name],
            "SampleLabels": [sample_labels.name],
        },
        attrs={
            "num_neg_samples": num_neg_samples,
            "num_total_classes": num_total_classes,
        },
    )
    return cost


def row_conv(input, future_context_size, param_attr=None, act=None, name=None):
    helper = LayerHelper("row_conv", act=act, name=name)
    d = input.shape[-1]
    w = helper.create_parameter(
        param_attr, shape=[future_context_size + 1, d], dtype=input.dtype
    )
    out = helper.create_tmp_variable(input.dtype, list(input.shape), lod_level=input.lod_level)
    inputs = {"X": [input.name], "Filter": [w.name]}
    _seq_inputs(inputs, input)
    helper.append_op(type="row_conv", inputs=inputs, outputs={"Out": [out.name]})
    _link_length(out, input)
    return helper.append_activation(out)


def multiplex(inputs, index):
    helper = LayerHelper("multiplex")
    out = helper.create_tmp_variable(inputs[0].dtype, list(inputs[0].shape))
    helper.append_op(
        type="multiplex",
        inputs={"X": inputs, "Ids": [index.name]},
        outputs={"Out": [out.name]},
    )
    return out


def linear_chain_crf(input, label, param_attr=None):
    helper = LayerHelper("linear_chain_crf")
    num_tags = input.shape[-1]
    transition = helper.create_parameter(
        param_attr, shape=[num_tags + 2, num_tags], dtype="float32",
        suffix="transition", default_initializer=init_mod.Uniform(-0.1, 0.1),
    )
    b = input.shape[0]
    ll = helper.create_tmp_variable(input.dtype, [b, 1])
    emission_exps = helper.create_tmp_variable(input.dtype, list(input.shape), stop_gradient=True)
    transition_exps = helper.create_tmp_variable("float32", [num_tags + 2, num_tags], stop_gradient=True)
    alpha = helper.create_tmp_variable(input.dtype, list(input.shape), stop_gradient=True)
    inputs = {"Emission": [input.name], "Transition": [transition.name], "Label": [label.name]}
    _seq_inputs(inputs, input)
    helper.append_op(
        type="linear_chain_crf",
        inputs=inputs,
        outputs={
            "LogLikelihood": [ll.name],
            "EmissionExps": [emission_exps.name],
            "TransitionExps": [transition_exps.name],
            "Alpha": [alpha.name],
        },
    )
    return ll


def crf_decoding(input, param_attr=None, label=None):
    helper = LayerHelper("crf_decoding")
    attr = ParamAttr.to_attr(param_attr)
    transition = helper.main_program.global_block().var(attr.name)
    out = helper.create_tmp_variable("int64", list(input.shape[:2]), stop_gradient=True)
    inputs = {"Emission": [input.name], "Transition": [transition.name]}
    if label is not None:
        inputs["Label"] = [label.name]
    _seq_inputs(inputs, input)
    helper.append_op(
        type="crf_decoding", inputs=inputs, outputs={"ViterbiPath": [out.name]}
    )
    return out


def cos_sim(X, Y):
    helper = LayerHelper("cos_sim")
    out = helper.create_tmp_variable(X.dtype, [X.shape[0], 1])
    xnorm = helper.create_tmp_variable(X.dtype, [X.shape[0], 1], stop_gradient=True)
    ynorm = helper.create_tmp_variable(X.dtype, [Y.shape[0], 1], stop_gradient=True)
    helper.append_op(
        type="cos_sim",
        inputs={"X": [X.name], "Y": [Y.name]},
        outputs={"Out": [out.name], "XNorm": [xnorm.name], "YNorm": [ynorm.name]},
    )
    return out


def mean(x, name=None):
    helper = LayerHelper("mean", name=name)
    out = helper.create_tmp_variable(x.dtype, [1])
    helper.append_op(type="mean", inputs={"X": [x.name]}, outputs={"Out": [out.name]})
    return out


def scale(x, scale=1.0, bias=0.0, name=None):
    helper = LayerHelper("scale", name=name)
    out = helper.create_tmp_variable(x.dtype, list(x.shape), lod_level=x.lod_level)
    helper.append_op(
        type="scale", inputs={"X": [x.name]}, outputs={"Out": [out.name]},
        attrs={"scale": float(scale), "bias": float(bias)},
    )
    return _link_length(out, x)


def _reduce_layer(op_type):
    def f(input, dim=None, keep_dim=False, name=None):
        helper = LayerHelper(op_type, name=name)
        if dim is None:
            shape = [1]
        else:
            dims = dim if isinstance(dim, (list, tuple)) else [dim]
            dims = [d % len(input.shape) for d in dims]
            shape = [
                (1 if i in dims and keep_dim else s)
                for i, s in enumerate(input.shape)
                if keep_dim or i not in dims
            ] or [1]
        out = helper.create_tmp_variable(input.dtype, shape)
        attrs = {"keep_dim": keep_dim, "reduce_all": dim is None}
        if dim is not None:
            attrs["dim"] = dim
        helper.append_op(
            type=op_type, inputs={"X": [input.name]}, outputs={"Out": [out.name]},
            attrs=attrs,
        )
        return out

    f.__name__ = op_type
    return f


reduce_sum = _reduce_layer("reduce_sum")
reduce_mean = _reduce_layer("reduce_mean")
reduce_max = _reduce_layer("reduce_max")
reduce_min = _reduce_layer("reduce_min")
reduce_prod = _reduce_layer("reduce_prod")


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", name=name)
    out = helper.create_tmp_variable(x.dtype, list(x.shape))
    helper.append_op(
        type="clip", inputs={"X": [x.name]}, outputs={"Out": [out.name]},
        attrs={"min": float(min), "max": float(max)},
    )
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", name=name)
    out = helper.create_tmp_variable(x.dtype, list(x.shape))
    helper.append_op(
        type="clip_by_norm", inputs={"X": [x.name]}, outputs={"Out": [out.name]},
        attrs={"max_norm": float(max_norm)},
    )
    return out


def beam_search(pre_ids, pre_scores, scores, beam_size, end_id):
    helper = LayerHelper("beam_search")
    b, k = pre_ids.shape[0], beam_size
    sel_ids = helper.create_tmp_variable("int64", [b, k], stop_gradient=True)
    sel_scores = helper.create_tmp_variable("float32", [b, k], stop_gradient=True)
    parent = helper.create_tmp_variable("int64", [b, k], stop_gradient=True)
    helper.append_op(
        type="beam_search",
        inputs={
            "PreIds": [pre_ids.name],
            "PreScores": [pre_scores.name],
            "Scores": [scores.name],
        },
        outputs={
            "SelectedIds": [sel_ids.name],
            "SelectedScores": [sel_scores.name],
            "ParentIdx": [parent.name],
        },
        attrs={"beam_size": beam_size, "end_id": end_id},
    )
    return sel_ids, sel_scores, parent


def beam_search_decode(ids, parent_idx, scores=None, end_id=1, name=None):
    """Backtrack stacked per-step beams [T, b, k] into sentences
    [b, k, T] (+ final scores) — reference beam_search_decode_op.cc via
    fluid layers/control_flow.py beam_search_decode."""
    helper = LayerHelper("beam_search_decode", name=name)
    t, b, k = ids.shape[0], ids.shape[1], ids.shape[2]
    sent = helper.create_tmp_variable("int64", [b, k, t], stop_gradient=True)
    outputs = {"SentenceIds": [sent.name]}
    inputs = {"Ids": [ids.name], "ParentIdx": [parent_idx.name]}
    out_scores = None
    if scores is not None:
        inputs["Scores"] = [scores.name]
        out_scores = helper.create_tmp_variable("float32", [b, k],
                                                stop_gradient=True)
        outputs["SentenceScores"] = [out_scores.name]
    helper.append_op(
        type="beam_search_decode",
        inputs=inputs,
        outputs=outputs,
        attrs={"end_id": end_id},
    )
    return (sent, out_scores) if scores is not None else sent


def lrn(input, n=5, k=2.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", name=name)
    out = helper.create_tmp_variable(input.dtype, list(input.shape))
    mid = helper.create_tmp_variable(input.dtype, list(input.shape), stop_gradient=True)
    helper.append_op(
        type="lrn", inputs={"X": [input.name]},
        outputs={"Out": [out.name], "MidOut": [mid.name]},
        attrs={"n": n, "k": k, "alpha": alpha, "beta": beta},
    )
    return out


def maxout(x, groups, name=None):
    helper = LayerHelper("maxout", name=name)
    n, c, h, w = x.shape
    out = helper.create_tmp_variable(x.dtype, [n, c // groups, h, w])
    helper.append_op(
        type="maxout", inputs={"X": [x.name]}, outputs={"Out": [out.name]},
        attrs={"groups": groups},
    )
    return out


def spp(input, pyramid_height=3, pool_type="max", name=None):
    helper = LayerHelper("spp", name=name)
    c = input.shape[1]
    total = sum((2 ** l) ** 2 for l in range(pyramid_height))
    out = helper.create_tmp_variable(input.dtype, [input.shape[0], c * total])
    helper.append_op(
        type="spp", inputs={"X": [input.name]}, outputs={"Out": [out.name]},
        attrs={"pyramid_height": pyramid_height, "pooling_type": pool_type},
    )
    return out


def sequence_reverse(x, name=None):
    """Length-aware reversal along the (outer) time axis: element t of
    each sequence swaps with element len-1-t; padding stays in place.
    For a nested (lod 2) input the OUTER subsequence order is reversed
    and the @SUBLENGTH shadow is permuted to match.  The v1
    ``recurrent_group(reverse=True)`` support (reference
    ``trainer_config_helpers/layers.py:347``)."""
    helper = LayerHelper("sequence_reverse", name=name)
    inputs = {"X": [x.name]}
    ln = seq_length(x)
    if ln is not None:
        inputs["Length"] = [ln.name]
    out = helper.create_tmp_variable(x.dtype, list(x.shape),
                                     lod_level=x.lod_level)
    helper.append_op(type="sequence_reverse", inputs=inputs,
                     outputs={"Out": [out.name]})
    _link_length(out, x)
    if getattr(x, "lod_level", 0) >= 2:
        sub = x.sub_length_var()
        sub_rev = helper.create_tmp_variable(sub.dtype, list(sub.shape))
        helper.append_op(
            type="sequence_reverse",
            inputs={"X": [sub.name], "Length": [x.length_var().name]},
            outputs={"Out": [sub_rev.name]})
        out.block.vars[out.name + "@SUBLENGTH"] = sub_rev
    return out


# -- 2-level (nested) sequence layers ----------------------------------------
def _nested_inputs(inputs, x):
    """Wire Length + SubLength for a nested (lod_level 2) input."""
    if getattr(x, "lod_level", 0) > 0:
        inputs["Length"] = [x.length_var().name]
    if getattr(x, "lod_level", 0) > 1:
        inputs["SubLength"] = [x.sub_length_var().name]


def _link_nested(out, length_var, sub_length_var):
    """Mark ``out`` as a nested sequence carrying the given shadows."""
    out.lod_level = 2
    out.block.vars[out.name + "@LENGTH"] = length_var
    out.block.vars[out.name + "@SUBLENGTH"] = sub_length_var
    return out


def nested_sequence_pool(input, pool_type):
    """Pool the INNER level of a [b, s, t, ...] nested batch ->
    [b, s, ...] 1-level sequence (lengths = the outer level's)."""
    helper = LayerHelper("nested_sequence_pool")
    out = helper.create_tmp_variable(
        input.dtype, [input.shape[0], input.shape[1]] + list(input.shape[3:]))
    inputs = {"X": [input.name]}
    _nested_inputs(inputs, input)
    helper.append_op(
        type="nested_sequence_pool", inputs=inputs,
        outputs={"Out": [out.name]},
        attrs={"pooltype": pool_type.upper()},
    )
    out.lod_level = 1
    out.block.vars[out.name + "@LENGTH"] = input.length_var()
    return out


def nested_sequence_expand(x, y):
    """Expand per-sub-seq values x [b, s, ...] over nested y's inner
    level -> [b, s, t, ...] (masked broadcast)."""
    helper = LayerHelper("nested_sequence_expand")
    t = y.shape[2]
    out = helper.create_tmp_variable(
        x.dtype, list(x.shape[:2]) + [t] + list(x.shape[2:]))
    inputs = {"X": [x.name], "Y": [y.name]}
    _nested_inputs(inputs, y)
    helper.append_op(type="nested_sequence_expand", inputs=inputs,
                     outputs={"Out": [out.name]})
    return _link_nested(out, y.length_var(), y.sub_length_var())


def nested_sequence_slice(input, offset, size):
    """Keep sub-sequences [offset, offset+size) of each sample."""
    helper = LayerHelper("nested_sequence_slice")
    out = helper.create_tmp_variable(input.dtype, list(input.shape))
    out_len = helper.create_tmp_variable("int32", [input.shape[0]],
                                         stop_gradient=True)
    out_sub = helper.create_tmp_variable(
        "int32", [input.shape[0], input.shape[1]], stop_gradient=True)
    inputs = {"X": [input.name], "Offset": [offset.name],
              "Size": [size.name]}
    _nested_inputs(inputs, input)
    helper.append_op(
        type="nested_sequence_slice", inputs=inputs,
        outputs={"Out": [out.name], "OutLength": [out_len.name],
                 "OutSubLength": [out_sub.name]})
    return _link_nested(out, out_len, out_sub)


def sub_nested_seq(input, selected_indices):
    """Select sub-sequences by per-sample indices (reference
    SubNestedSequenceLayer.cpp); negative indices = padding."""
    helper = LayerHelper("sub_nested_seq")
    k = selected_indices.shape[1]
    out = helper.create_tmp_variable(
        input.dtype, [input.shape[0], k] + list(input.shape[2:]))
    out_len = helper.create_tmp_variable("int32", [input.shape[0]],
                                         stop_gradient=True)
    out_sub = helper.create_tmp_variable("int32", [input.shape[0], k],
                                         stop_gradient=True)
    inputs = {"X": [input.name], "Indices": [selected_indices.name]}
    _nested_inputs(inputs, input)
    helper.append_op(
        type="sub_nested_seq", inputs=inputs,
        outputs={"Out": [out.name], "OutLength": [out_len.name],
                 "OutSubLength": [out_sub.name]})
    return _link_nested(out, out_len, out_sub)


def nested_rnn(input, size, param_attr=None, bias_attr=None, h_0=None,
               gate_activation="sigmoid", candidate_activation="tanh",
               name=None):
    """Hierarchical GRU over a nested batch [b, s, t, 3d] (input
    pre-projected to gates, the dynamic_gru convention): the inner RNN
    runs each sub-sequence booted from the outer state; the outer state
    advances to the last valid inner hidden.  Returns
    (inner_hiddens [b, s, t, d], outer_states [b, s, d]); outer_states
    is a 1-level sequence over the outer lengths."""
    helper = LayerHelper("nested_rnn", name=name)
    d = size
    weight = helper.create_parameter(param_attr, shape=[d, 3 * d],
                                     dtype=input.dtype)
    bias = helper.create_parameter(
        ParamAttr.to_attr(bias_attr) or ParamAttr(), shape=[1, 3 * d],
        dtype=input.dtype, suffix="b",
        default_initializer=init_mod.Constant(0.0),
    )
    hidden = helper.create_tmp_variable(
        input.dtype, list(input.shape[:3]) + [d])
    outer = helper.create_tmp_variable(
        input.dtype, list(input.shape[:2]) + [d])
    inputs = {"Input": [input.name], "Weight": [weight.name],
              "Bias": [bias.name]}
    if h_0 is not None:
        inputs["H0"] = [h_0.name]
    _nested_inputs(inputs, input)
    helper.append_op(
        type="nested_rnn", inputs=inputs,
        outputs={"Hidden": [hidden.name], "OuterHidden": [outer.name]},
        attrs={"gate_activation": gate_activation,
               "activation": candidate_activation},
    )
    if getattr(input, "lod_level", 0) > 1:
        _link_nested(hidden, input.length_var(), input.sub_length_var())
        outer.lod_level = 1
        outer.block.vars[outer.name + "@LENGTH"] = input.length_var()
    return hidden, outer
