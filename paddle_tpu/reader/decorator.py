"""Reader decorators (reference: python/paddle/v2/reader/decorator.py)."""

import itertools
import random
import queue as queue_mod
import threading

__all__ = [
    "map_readers", "buffered", "compose", "chain", "shuffle", "firstn",
    "xmap_readers", "batch", "prefetch_to_device", "resumable",
    "ResumableReader",
]


def map_readers(func, *readers):
    def reader():
        rs = [r() for r in readers]
        for items in zip(*rs):
            yield func(*items)

    return reader


def shuffle(reader, buf_size):
    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            random.shuffle(buf)
            for b in buf:
                yield b

    return data_reader


def chain(*readers):
    def reader():
        for r in readers:
            yield from r()

    return reader


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, check_alignment=True):
    def make_tuple(x):
        if isinstance(x, tuple):
            return x
        return (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(map(make_tuple, outputs), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                if any(o is None for o in outputs):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned"
                    )
                yield sum(map(make_tuple, outputs), ())

    return reader


class _End:
    pass


def _pipeline(reader, size, transform=None):
    """Shared producer-thread machinery for buffered/prefetch_to_device:
    bounded queue, optional per-item transform on the producer thread,
    producer errors re-raised on the consumer side, and early consumer
    exit (break/close) releases the producer instead of leaking it."""

    def data_reader():
        r = reader()
        q = queue_mod.Queue(maxsize=size)
        err = []
        stop = threading.Event()

        def offer(item):
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue_mod.Full:
                    continue
            return False

        def fill():
            try:
                for d in r:
                    if transform is not None:
                        d = transform(d)
                    if not offer(d):
                        return
            except BaseException as e:  # re-raised on the consumer side
                err.append(e)
            finally:
                offer(_End)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        try:
            while True:
                e = q.get()
                if e is _End:
                    break
                yield e
        finally:
            stop.set()  # unblock the producer if we exit early
        if err:
            raise err[0]

    return data_reader


def buffered(reader, size):
    """Prefetch into a bounded queue on a daemon thread — the analog of the
    reference's double-buffered PyDataProvider2 pool."""
    return _pipeline(reader, size)


def firstn(reader, n):
    def data_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return data_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader with worker threads (decorator.py
    xmap_readers)."""

    end = object()

    def data_reader():
        in_q = queue_mod.Queue(buffer_size)
        out_q = queue_mod.Queue(buffer_size)

        def read_worker():
            for i, d in enumerate(reader()):
                in_q.put((i, d))
            for _ in range(process_num):
                in_q.put(end)

        def map_worker():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    return
                i, d = item
                out_q.put((i, mapper(d)))

        threading.Thread(target=read_worker, daemon=True).start()
        workers = [
            threading.Thread(target=map_worker, daemon=True)
            for _ in range(process_num)
        ]
        for w in workers:
            w.start()
        finished = 0
        pending = {}
        next_idx = 0
        while finished < process_num:
            item = out_q.get()
            if item is end:
                finished += 1
                continue
            if not order:
                yield item[1]
            else:
                pending[item[0]] = item[1]
                while next_idx in pending:
                    yield pending.pop(next_idx)
                    next_idx += 1
        if order:
            for i in sorted(pending):
                yield pending[i]

    return data_reader


class ResumableReader:
    """Position-tracking reader wrapper — the input-pipeline half of a
    full-state checkpoint (``resilience.checkpoint``).

    Wraps a reader *factory* (a callable returning an iterable, the v2
    convention).  Each iteration counts the items it hands out
    (``items``) and completed iterations (``epochs``); ``state()``
    snapshots the cursor and ``set_state()`` arms the NEXT iteration to
    resume from it.

    Two resume strategies, picked automatically:

    * if the underlying factory object carries its own
      ``state()``/``set_state()`` pair (e.g. a file reader snapshotting
      a byte offset), it is delegated to — O(1) resume;
    * otherwise the next iteration FAST-FORWARDS by re-drawing and
      discarding ``items`` leading items — correct for any
      deterministic reader, O(position) in reader work but zero
      training compute.

        r = resumable(my_batched_reader)
        for b in r():
            train(b)                 # killed here...
        ckpt["reader_state"] = r.state()
        # ...later, a fresh process:
        r = resumable(my_batched_reader)
        r.set_state(ckpt["reader_state"])
        for b in r():                # continues at the next unseen batch
            train(b)
    """

    def __init__(self, reader):
        self._factory = reader
        self._skip = 0   # items the next iteration fast-forwards past
        self._base = 0   # position already restored inside the factory
        self.items = 0   # current-epoch position (incl. restored items)
        self.epochs = 0  # completed iterations

    def state(self):
        """Snapshot the cursor: the position count, plus the underlying
        factory's own ``state()`` when it has one."""
        out = {"items": self.items, "epochs": self.epochs}
        if hasattr(self._factory, "state"):
            out["underlying"] = self._factory.state()
        return out

    def set_state(self, state):
        """Arm the next iteration to resume from ``state`` (a dict from
        ``state()``, or any mapping with an ``items`` count)."""
        if "underlying" in state and hasattr(self._factory, "set_state"):
            self._factory.set_state(state["underlying"])
            self._skip, self._base = 0, int(state.get("items", 0))
        else:
            self._skip, self._base = int(state.get("items", 0)), 0
        self.epochs = int(state.get("epochs", 0))

    def __call__(self):
        skip, self._skip = self._skip, 0
        base, self._base = self._base, 0

        def gen():
            it = iter(self._factory())
            self.items = base
            for _ in range(skip):
                try:
                    next(it)
                except StopIteration:
                    return
                self.items += 1
            for item in it:
                self.items += 1
                yield item
            self.epochs += 1

        return gen()


def resumable(reader):
    """Wrap a reader factory so its position can be checkpointed and
    restored (see ``ResumableReader``)."""
    return ResumableReader(reader)


def batch(reader, batch_size, drop_last=True):
    """Group samples into minibatches (reference: paddle/v2/minibatch.py).
    ``drop_last`` defaults True for TPU: a ragged final batch would trigger
    a recompile for one step."""

    def batch_reader():
        b = []
        for instance in reader():
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader


def prefetch_to_device(reader, size=2, feed_converter=None, sharding=None):
    """Overlap host->device transfer with compute: batches are converted
    (optionally via ``feed_converter``, e.g. ``DataFeeder.feed``) and
    ``jax.device_put`` AHEAD of consumption on a daemon thread, so the
    training loop always finds the next batch already device-resident
    (the TPU-era equivalent of the reference's GPU double-buffering in
    MultiGradientMachine's data pipeline).

    ``sharding``: an optional ``jax.sharding.NamedSharding`` (applied to
    every array), or a dict ``feed_name -> NamedSharding`` for dict
    batches (names missing from the dict use the plain default put).
    With it, prefetched batches land PRE-SHARDED across the mesh — e.g.
    batch-split over ``dp`` — from the producer thread, instead of
    replicated-then-resharded on step entry (the Executor accepts
    device-resident feeds as-is, ``core/executor.py``).

        feeder = pt.DataFeeder(model["feed"])
        for feed in prefetch_to_device(batched_reader, 2, feeder.feed)():
            exe.run(feed=feed, fetch_list=[cost])   # no h2d stall
    """
    import jax

    def put(v, name=None):
        sh = (sharding.get(name) if isinstance(sharding, dict)
              else sharding)
        return jax.device_put(v) if sh is None else jax.device_put(v, sh)

    def put_on_device(item):
        if feed_converter is not None:
            item = feed_converter(item)
        if isinstance(item, dict):
            return {k: put(v, k) for k, v in item.items()}
        if isinstance(item, tuple) and hasattr(item, "_fields"):
            return type(item)(*(put(v) for v in item))
        if isinstance(item, (list, tuple)):
            return type(item)(put(v) for v in item)
        return put(item)

    return _pipeline(reader, size, transform=put_on_device)
