"""Reader decorators (reference: python/paddle/v2/reader/decorator.py)."""

import itertools
import random
import queue as queue_mod
import threading

__all__ = [
    "map_readers", "buffered", "compose", "chain", "shuffle", "firstn",
    "xmap_readers", "batch",
]


def map_readers(func, *readers):
    def reader():
        rs = [r() for r in readers]
        for items in zip(*rs):
            yield func(*items)

    return reader


def shuffle(reader, buf_size):
    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            random.shuffle(buf)
            for b in buf:
                yield b

    return data_reader


def chain(*readers):
    def reader():
        for r in readers:
            yield from r()

    return reader


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, check_alignment=True):
    def make_tuple(x):
        if isinstance(x, tuple):
            return x
        return (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(map(make_tuple, outputs), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                if any(o is None for o in outputs):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned"
                    )
                yield sum(map(make_tuple, outputs), ())

    return reader


def buffered(reader, size):
    """Prefetch into a bounded queue on a daemon thread — the analog of the
    reference's double-buffered PyDataProvider2 pool."""

    class _End:
        pass

    def data_reader():
        r = reader()
        q = queue_mod.Queue(maxsize=size)

        def fill():
            try:
                for d in r:
                    q.put(d)
            finally:
                q.put(_End)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                break
            yield e

    return data_reader


def firstn(reader, n):
    def data_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return data_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader with worker threads (decorator.py
    xmap_readers)."""

    end = object()

    def data_reader():
        in_q = queue_mod.Queue(buffer_size)
        out_q = queue_mod.Queue(buffer_size)

        def read_worker():
            for i, d in enumerate(reader()):
                in_q.put((i, d))
            for _ in range(process_num):
                in_q.put(end)

        def map_worker():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    return
                i, d = item
                out_q.put((i, mapper(d)))

        threading.Thread(target=read_worker, daemon=True).start()
        workers = [
            threading.Thread(target=map_worker, daemon=True)
            for _ in range(process_num)
        ]
        for w in workers:
            w.start()
        finished = 0
        pending = {}
        next_idx = 0
        while finished < process_num:
            item = out_q.get()
            if item is end:
                finished += 1
                continue
            if not order:
                yield item[1]
            else:
                pending[item[0]] = item[1]
                while next_idx in pending:
                    yield pending.pop(next_idx)
                    next_idx += 1
        if order:
            for i in sorted(pending):
                yield pending[i]

    return data_reader


def batch(reader, batch_size, drop_last=True):
    """Group samples into minibatches (reference: paddle/v2/minibatch.py).
    ``drop_last`` defaults True for TPU: a ragged final batch would trigger
    a recompile for one step."""

    def batch_reader():
        b = []
        for instance in reader():
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader
