"""Reader creators (reference: python/paddle/v2/reader/creator.py —
np_array, text_file, recordio, cloud_reader via Go master)."""

import numpy as np

__all__ = ["np_array", "text_file", "recordio", "cloud_reader"]


def np_array(x):
    def reader():
        for e in np.asarray(x):
            yield e

    return reader


def text_file(path):
    def reader():
        with open(path) as f:
            for line in f:
                yield line.rstrip("\n")

    return reader


def recordio(paths, buf_size=100, num_threads=0, shuffle_seed=-1):
    """Read recordio files written by paddle_tpu.dataset.common.convert
    (reference creator.py:60).  With ``num_threads > 0`` the native
    multithreaded prefetching Loader decodes chunks off the main thread
    (PyDataProvider2's background-feed pattern, now in C++)."""
    import pickle

    from .. import native
    from ..native import recordio as rio

    if isinstance(paths, str):
        paths = paths.split(",")

    if num_threads > 0 and native.available():
        def reader():
            with native.Loader(paths, num_threads=num_threads,
                               queue_cap=max(buf_size, 16),
                               shuffle_seed=shuffle_seed) as loader:
                for rec in loader:
                    yield pickle.loads(rec)

        return reader

    def reader():
        for p in paths:
            for rec in rio.reader(p):
                yield pickle.loads(rec)

    return reader


def cloud_reader(paths, etcd_endpoints=None, timeout_sec=5, buf_size=64):
    """Elastic dataset reader backed by the distributed master service
    (reference creator.py:91 cloud_reader → Go master).  Pulls task chunks
    from paddle_tpu.distributed.master.MasterClient."""
    import pickle

    from ..distributed.master import MasterClient

    def reader():
        client = MasterClient(etcd_endpoints, timeout_sec=timeout_sec)
        client.set_dataset(paths)
        while True:
            rec = client.next_record()
            if rec is None:
                break
            yield pickle.loads(rec)

    return reader
