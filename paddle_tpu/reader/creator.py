"""Reader creators (reference: python/paddle/v2/reader/creator.py —
np_array, text_file, recordio, cloud_reader via Go master)."""

import numpy as np

__all__ = ["np_array", "text_file", "recordio", "cloud_reader"]


def np_array(x):
    def reader():
        for e in np.asarray(x):
            yield e

    return reader


def text_file(path):
    def reader():
        with open(path) as f:
            for line in f:
                yield line.rstrip("\n")

    return reader


def recordio(paths, buf_size=100):
    """Read record files written by paddle_tpu.dataset.common.convert (a
    simple length-prefixed record format standing in for RecordIO)."""
    from ..dataset.common import read_records
    import pickle

    if isinstance(paths, str):
        paths = paths.split(",")

    def reader():
        for p in paths:
            for rec in read_records(p):
                yield pickle.loads(rec)

    return reader


def cloud_reader(paths, etcd_endpoints=None, timeout_sec=5, buf_size=64):
    """Elastic dataset reader backed by the distributed master service
    (reference creator.py:91 cloud_reader → Go master).  Pulls task chunks
    from paddle_tpu.distributed.master.MasterClient."""
    import pickle

    from ..distributed.master import MasterClient

    def reader():
        client = MasterClient(etcd_endpoints, timeout_sec=timeout_sec)
        client.set_dataset(paths)
        while True:
            rec = client.next_record()
            if rec is None:
                break
            yield pickle.loads(rec)

    return reader
