"""PyDataProvider2 ``@provider`` protocol shim (reference:
python/paddle/trainer/PyDataProvider2.py — the v1 in-process data-feed
decorator: ``@provider(input_types=...)`` over a
``process(settings, filename)`` generator, with init_hook settings,
CACHE_PASS_IN_MEM, and typed slots).

TPU-native re-design: the decorated generator becomes an ordinary
composable reader factory (``reader/__init__.py`` protocol) —
``process(file_list)`` returns a no-arg reader yielding converted rows
that ``DataFeeder`` pads/batches.  Sparse slots stay SPARSE end to end
(reference PyDataProvider2.cpp:195 assembles sparse Arguments; here each
slot becomes a :class:`SparseRow` of (ids, vals) that the feeder pads to
``<name>@IDS``/``<name>@VALS`` arrays and ``sparse_fc`` consumes as a
weighted gather-sum) — a 10M-dim CTR slot never materializes a dense
row.  ``SparseRow.todense()`` exists for the dense-var fallback; the DCN
sparse-update path lives in ``parallel/sparse.py``.
"""

import functools

import numpy as np

__all__ = [
    "provider", "CacheType", "SequenceType", "DataType", "InputType",
    "SparseRow",
    "dense_vector", "dense_vector_sequence", "dense_array",
    "sparse_binary_vector", "sparse_binary_vector_sequence",
    "sparse_float_vector", "sparse_float_vector_sequence",
    "integer_value", "integer_value_sequence", "integer_sequence",
]


class SparseRow:
    """One sample of one sparse slot: ``ids`` [nnz] int64, ``vals`` [nnz]
    float32 (all-ones for binary slots), ``dim`` the declared vocabulary.
    The feeder pads batches of these to ``@IDS``/``@VALS`` arrays; nothing
    of size ``dim`` is ever allocated on the host."""

    __slots__ = ["ids", "vals", "dim"]

    def __init__(self, ids, vals, dim):
        self.ids = np.asarray(ids, np.int64).reshape(-1)
        self.vals = (np.ones(self.ids.shape[0], np.float32) if vals is None
                     else np.asarray(vals, np.float32).reshape(-1))
        if self.vals.shape != self.ids.shape:
            raise ValueError(
                f"sparse slot ids/vals length mismatch: {self.ids.shape[0]}"
                f" vs {self.vals.shape[0]}")
        self.dim = int(dim)

    @property
    def nnz(self):
        return self.ids.shape[0]

    def todense(self):
        out = np.zeros(self.dim, np.float32)
        # duplicate ids ACCUMULATE — matching sparse_fc's gather-sum, so
        # the dense and native spellings of the same slot agree exactly
        np.add.at(out, self.ids, self.vals)
        return out

    def __repr__(self):
        return f"SparseRow(nnz={self.nnz}, dim={self.dim})"


class SequenceType:
    NO_SEQUENCE = 0
    SEQUENCE = 1
    SUB_SEQUENCE = 2


class DataType:
    Dense = 0
    SparseNonValue = 1
    SparseValue = 2
    Index = 3


class CacheType:
    NO_CACHE = 0
    CACHE_PASS_IN_MEM = 1


class InputType:
    """Typed slot declaration (reference PyDataProvider2.py:63)."""

    __slots__ = ["dim", "seq_type", "type"]

    def __init__(self, dim, seq_type, tp):
        self.dim = dim
        self.seq_type = seq_type
        self.type = tp

    def __repr__(self):
        return (f"InputType(dim={self.dim}, seq_type={self.seq_type}, "
                f"type={self.type})")


def dense_vector(dim, seq_type=SequenceType.NO_SEQUENCE):
    return InputType(dim, seq_type, DataType.Dense)


def dense_vector_sequence(dim):
    return dense_vector(dim, SequenceType.SEQUENCE)


dense_array = dense_vector


def sparse_binary_vector(dim, seq_type=SequenceType.NO_SEQUENCE):
    return InputType(dim, seq_type, DataType.SparseNonValue)


def sparse_binary_vector_sequence(dim):
    return sparse_binary_vector(dim, SequenceType.SEQUENCE)


def sparse_float_vector(dim, seq_type=SequenceType.NO_SEQUENCE):
    return InputType(dim, seq_type, DataType.SparseValue)


def sparse_float_vector_sequence(dim):
    return sparse_float_vector(dim, SequenceType.SEQUENCE)


def integer_value(value_range, seq_type=SequenceType.NO_SEQUENCE):
    return InputType(value_range, seq_type, DataType.Index)


def integer_value_sequence(value_range):
    return integer_value(value_range, SequenceType.SEQUENCE)


integer_sequence = integer_value_sequence


class _Settings:
    """Attribute bag passed to init_hook and the process generator."""

    def __init__(self):
        self.input_types = None
        self.logger = None


def _convert_slot(value, itype):
    """One slot of one row -> numpy (sparse slots -> SparseRow)."""
    if itype is None:
        return np.asarray(value)
    if itype.type == DataType.Index:
        if itype.seq_type == SequenceType.NO_SEQUENCE:
            return np.asarray(value, np.int64).reshape(())
        return np.asarray(value, np.int64)
    if itype.type == DataType.Dense:
        return np.asarray(value, np.float32)

    def sparsify(v):
        if itype.type == DataType.SparseNonValue:
            return SparseRow(v, None, itype.dim)
        pairs = list(v)
        return SparseRow([i for i, _ in pairs], [val for _, val in pairs],
                         itype.dim)

    if itype.seq_type == SequenceType.NO_SEQUENCE:
        return sparsify(value)
    return [sparsify(v) for v in value]


def provider(input_types=None, should_shuffle=None, pool_size=-1,
             min_pool_size=-1, can_over_batch_size=True, calc_batch_size=None,
             cache=CacheType.NO_CACHE, check=False, check_fail_continue=False,
             init_hook=None, **outter_kwargs):
    """Decorator: ``@provider(input_types=[...])`` over
    ``def process(settings, filename): yield slot0, slot1, ...``.

    The decorated function becomes a reader factory:
    ``process(file_list, **hook_kwargs)`` -> no-arg reader of converted
    rows.  ``settings.input_types`` order defines the slot order; dict
    yields are reordered to it when input_types is a dict.  Unknown
    reference knobs (pool_size etc. — trainer-internal scheduling) are
    accepted and ignored.
    """

    def _wrapper(generator):
        @functools.wraps(generator)
        def create(file_list=None, **kwargs):
            settings = _Settings()
            settings.input_types = input_types
            files = ([file_list] if isinstance(file_list, str)
                     else list(file_list or [None]))
            if init_hook is not None:
                init_hook(settings, file_list=files, **dict(outter_kwargs,
                                                            **kwargs))
            types = settings.input_types
            if isinstance(types, dict):
                names = list(types.keys())
                tlist = [types[n] for n in names]
            else:
                names = None
                tlist = list(types) if types else None

            cache_box = {"rows": None}

            def convert_row(row):
                if isinstance(row, dict):
                    row = tuple(row[n] for n in names)
                if not isinstance(row, (tuple, list)):
                    row = (row,)
                if tlist is None:
                    return tuple(np.asarray(v) for v in row)
                return tuple(
                    _convert_slot(v, t) for v, t in zip(row, tlist)
                )

            def reader():
                if cache_box["rows"] is not None:
                    yield from cache_box["rows"]
                    return
                mem = [] if cache == CacheType.CACHE_PASS_IN_MEM else None
                for fname in files:
                    for row in generator(settings, fname):
                        out = convert_row(row)
                        if mem is not None:
                            mem.append(out)
                        yield out
                if mem is not None:
                    cache_box["rows"] = mem

            return reader

        create.origin = generator
        create.input_types = input_types
        return create

    return _wrapper
