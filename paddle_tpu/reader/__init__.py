"""Composable data readers.

Reference: python/paddle/v2/reader — a reader is a no-arg callable returning
an iterable of samples; decorators compose them (decorator.py: map_readers,
shuffle, batched/batch, buffered, compose, chain, firstn, xmap_readers,
pipe_reader; creator.py: np_array, text_file, recordio, cloud_reader).
Identical protocol here — it is pure Python and already the right shape for
feeding an async device pipeline.
"""

from .decorator import (
    map_readers,
    buffered,
    compose,
    chain,
    shuffle,
    firstn,
    xmap_readers,
    batch,
    prefetch_to_device,
    resumable,
    ResumableReader,
)
from . import creator
from . import provider

__all__ = [
    "map_readers", "buffered", "compose", "chain", "shuffle", "firstn",
    "xmap_readers", "batch", "prefetch_to_device", "resumable",
    "ResumableReader", "creator", "provider",
]
