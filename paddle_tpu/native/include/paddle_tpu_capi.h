/* C inference API (reference: paddle/capi — gradient_machine.h, matrix.h).
 *
 * Load a model exported by paddle_tpu.io.save_inference_model and run
 * forward passes from C/C++.  Link against libpaddle_tpu_capi.so (which
 * embeds a Python interpreter driving the XLA-compiled engine).
 *
 * Minimal usage:
 *   pt_init("/path/containing/paddle_tpu");
 *   void* h = pt_engine_create("/path/to/exported_model");
 *   const float* out; const int64_t* shape; int32_t rank;
 *   pt_engine_run(h, names, datas, shapes, ranks, n_inputs, 0,
 *                 &out, &shape, &rank);
 *   pt_engine_destroy(h);
 */
#ifndef PADDLE_TPU_CAPI_H_
#define PADDLE_TPU_CAPI_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* Initialize the embedded runtime; extra_pythonpath (nullable) is
 * prepended to sys.path so the paddle_tpu package can be found.
 * Returns 0 on success. */
int pt_init(const char* extra_pythonpath);

/* Last error message (valid until the next failing call). */
const char* pt_last_error(void);

/* Load an exported inference model directory; NULL on failure. */
void* pt_engine_create(const char* model_dir);

/* ---- model introspection (reference capi/gradient_machine.h +
 * capi/matrix.h ergonomics): enumerate the exported program's feed and
 * fetch surface.  Returned strings/arrays are owned by the handle. ---- */
int32_t pt_engine_num_inputs(void* handle);
const char* pt_engine_input_name(void* handle, int32_t i);
/* Declared input shape; -1 marks a dynamic (batch) dimension. */
int pt_engine_input_shape(void* handle, int32_t i, const int64_t** shape,
                          int32_t* rank);
int32_t pt_engine_num_outputs(void* handle);
const char* pt_engine_output_name(void* handle, int32_t i);

/* Run one forward pass, computing and caching EVERY fetch target.
 *   names[i]   feed variable name
 *   datas[i]   float32 buffer, row-major
 *   shapes[i]  dimensions, ranks[i] entries
 * Read results back per target with pt_engine_output.  Returns 0 on
 * success. */
int pt_engine_run_all(void* handle, const char** names, const float** datas,
                      const int64_t** shapes, const int32_t* ranks,
                      int32_t n_inputs);

/* Dtype-tagged variant: dtypes[i] names input i's element type —
 * "float32", "float64", "int64" or "int32" (NULL entry = float32).
 * The int paths are how word-id / sequence models are fed (the
 * reference paddle_ivector, capi/vector.h + sequence Arguments). */
int pt_engine_run_all_typed(void* handle, const char** names,
                            const void** datas, const char** dtypes,
                            const int64_t** shapes, const int32_t* ranks,
                            int32_t n_inputs);

/* Read cached fetch target ``i`` of the last run.  Output pointers are
 * owned by the handle and valid until the next run/destroy. */
int pt_engine_output(void* handle, int32_t i, const float** out_data,
                     const int64_t** out_shape, int32_t* out_rank);

/* Back-compat single-output form: pt_engine_run_all + pt_engine_output. */
int pt_engine_run(void* handle, const char** names, const float** datas,
                  const int64_t** shapes, const int32_t* ranks,
                  int32_t n_inputs, int32_t out_index,
                  const float** out_data, const int64_t** out_shape,
                  int32_t* out_rank);

void pt_engine_destroy(void* handle);

/* No-op (the runtime stays resident for process lifetime, like the
 * reference capi). */
void pt_shutdown(void);

#ifdef __cplusplus
}
#endif

#endif /* PADDLE_TPU_CAPI_H_ */
