// capi.cc — C inference API.
//
// Reference: paddle/capi (gradient_machine.h / matrix.h, ~2k LoC) lets C
// programs load an exported model and run forward passes; the legacy engine
// itself embeds CPython for data providers (paddle/utils/PythonUtil.h).
// Here the same pattern: the C ABI embeds a Python interpreter and drives
// paddle_tpu.inference.InferenceEngine, so C/C++ services get TPU inference
// through one stable ABI with no Python in their own code.
//
//   pt_init(pythonpath)                         -- once per process
//   h  = pt_engine_create("/path/to/model")     -- load exported model
//   pt_engine_run(h, names, datas, shapes, ranks, n_inputs, out_index,
//                 &out_data, &out_shape, &out_rank)
//   pt_engine_destroy(h);  pt_shutdown()
//
// All outputs are float32 copies owned by the handle (valid until the next
// run or destroy).  Errors: functions return NULL/-1; pt_last_error() gives
// the Python traceback.

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

std::string g_error;
std::mutex g_mu;

void capture_py_error() {
  PyObject *type, *value, *tb;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  g_error = "python error";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      g_error = PyUnicode_AsUTF8(s);
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

struct Engine {
  PyObject* engine = nullptr;            // paddle_tpu.inference.InferenceEngine
  // introspection (filled at create): reference capi exposes the
  // gradient machine's argument names/shapes (capi/gradient_machine.h,
  // capi/matrix.h); here the exported program's feed/fetch surface
  std::vector<std::string> input_names;
  std::vector<std::vector<int64_t>> input_shapes;  // -1 = dynamic dim
  std::vector<std::string> output_names;
  // last run's result (ALL fetch targets); conversion to float buffers
  // happens LAZILY per requested index so legacy single-output callers
  // don't pay for targets they never read
  PyObject* last_result = nullptr;
  std::vector<bool> converted;
  std::vector<std::vector<float>> out_data;
  std::vector<std::vector<int64_t>> out_shape;
};

// Convert cached fetch target i (GIL must be held).  Returns false and
// sets g_error on failure.
bool convert_output(Engine* eng, int32_t i) {
  if (eng->converted[i]) return true;
  bool ok = false;
  PyObject* np = PyImport_ImportModule("numpy");
  PyObject* item = np ? PySequence_GetItem(eng->last_result, i) : nullptr;
  PyObject* arr = item ? PyObject_CallMethod(np, "asarray", "Os", item,
                                             "float32")
                       : nullptr;
  if (arr) {
    PyObject* shape = PyObject_GetAttrString(arr, "shape");
    PyObject* flat = PyObject_CallMethod(arr, "flatten", nullptr);
    PyObject* lst =
        flat ? PyObject_CallMethod(flat, "tolist", nullptr) : nullptr;
    if (shape && lst) {
      Py_ssize_t rank = PyTuple_Size(shape);
      eng->out_shape[i].resize(rank);
      for (Py_ssize_t d = 0; d < rank; d++) {
        eng->out_shape[i][d] = PyLong_AsLongLong(PyTuple_GET_ITEM(shape, d));
      }
      Py_ssize_t numel = PyList_Size(lst);
      eng->out_data[i].resize(numel);
      for (Py_ssize_t j = 0; j < numel; j++) {
        eng->out_data[i][j] =
            static_cast<float>(PyFloat_AsDouble(PyList_GET_ITEM(lst, j)));
      }
      eng->converted[i] = true;
      ok = true;
    }
    Py_XDECREF(lst);
    Py_XDECREF(flat);
    Py_XDECREF(shape);
    Py_DECREF(arr);
  }
  Py_XDECREF(item);
  Py_XDECREF(np);
  if (!ok) capture_py_error();
  return ok;
}

// Fill Engine::input_*/output_* from the python engine object.
bool load_introspection(Engine* eng) {
  PyObject* feed_vars = PyObject_GetAttrString(eng->engine, "feed_vars");
  PyObject* fetch_vars = PyObject_GetAttrString(eng->engine, "fetch_vars");
  bool ok = feed_vars && fetch_vars;
  if (ok) {
    Py_ssize_t n = PySequence_Size(feed_vars);
    for (Py_ssize_t i = 0; ok && i < n; i++) {
      PyObject* v = PySequence_GetItem(feed_vars, i);
      PyObject* name = v ? PyObject_GetAttrString(v, "name") : nullptr;
      PyObject* shape = v ? PyObject_GetAttrString(v, "shape") : nullptr;
      if (name && shape) {
        eng->input_names.emplace_back(PyUnicode_AsUTF8(name));
        std::vector<int64_t> dims;
        Py_ssize_t rank = PySequence_Size(shape);
        for (Py_ssize_t d = 0; d < rank; d++) {
          PyObject* e = PySequence_GetItem(shape, d);
          dims.push_back(e ? PyLong_AsLongLong(e) : -1);
          Py_XDECREF(e);
        }
        eng->input_shapes.push_back(std::move(dims));
      } else {
        ok = false;
      }
      Py_XDECREF(shape);
      Py_XDECREF(name);
      Py_XDECREF(v);
    }
    Py_ssize_t m = ok ? PySequence_Size(fetch_vars) : 0;
    for (Py_ssize_t i = 0; ok && i < m; i++) {
      PyObject* v = PySequence_GetItem(fetch_vars, i);
      PyObject* name = v ? PyObject_GetAttrString(v, "name") : nullptr;
      if (name) eng->output_names.emplace_back(PyUnicode_AsUTF8(name));
      else ok = false;
      Py_XDECREF(name);
      Py_XDECREF(v);
    }
  }
  Py_XDECREF(fetch_vars);
  Py_XDECREF(feed_vars);
  return ok;
}

bool g_we_initialized = false;
PyThreadState* g_saved_tstate = nullptr;

}  // namespace

extern "C" {

const char* pt_last_error() { return g_error.c_str(); }

// Initialize the embedded interpreter.  extra_pythonpath may be NULL.
int pt_init(const char* extra_pythonpath) {
  std::lock_guard<std::mutex> lock(g_mu);
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    g_we_initialized = true;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = 0;
  if (extra_pythonpath && *extra_pythonpath) {
    PyObject* sys_path = PySys_GetObject("path");  // borrowed
    PyObject* p = PyUnicode_FromString(extra_pythonpath);
    if (!sys_path || !p || PyList_Insert(sys_path, 0, p) != 0) {
      capture_py_error();
      rc = -1;
    }
    Py_XDECREF(p);
  }
  // PADDLE_TPU_PLATFORM overrides the jax backend (some platform plugins
  // ignore the JAX_PLATFORMS env var; jax.config.update is authoritative)
  const char* platform = getenv("PADDLE_TPU_PLATFORM");
  if (rc == 0 && platform && *platform) {
    std::string code =
        std::string("import jax\n"
                    "jax.config.update('jax_platforms', '") + platform + "')\n";
    if (PyRun_SimpleString(code.c_str()) != 0) {
      g_error = "failed to set jax platform";
      rc = -1;
    }
  }
  PyGILState_Release(gil);
  if (g_we_initialized && !g_saved_tstate) {
    // Py_InitializeEx leaves the initializing thread owning the GIL even
    // after the matching PyGILState_Release; drop it so other threads'
    // PyGILState_Ensure (pt_engine_*) can acquire it.
    g_saved_tstate = PyEval_SaveThread();
  }
  return rc;
}

void* pt_engine_create(const char* model_dir) {
  PyGILState_STATE gil = PyGILState_Ensure();
  Engine* eng = nullptr;
  PyObject* mod = PyImport_ImportModule("paddle_tpu.inference");
  if (!mod) {
    capture_py_error();
    PyGILState_Release(gil);
    return nullptr;
  }
  PyObject* cls = PyObject_GetAttrString(mod, "InferenceEngine");
  PyObject* obj =
      cls ? PyObject_CallFunction(cls, "s", model_dir) : nullptr;
  if (!obj) capture_py_error();
  if (obj) {
    eng = new Engine();
    eng->engine = obj;
    if (!load_introspection(eng)) {
      capture_py_error();
      Py_DECREF(obj);
      delete eng;
      eng = nullptr;
    }
  }
  Py_XDECREF(cls);
  Py_DECREF(mod);
  PyGILState_Release(gil);
  return eng;
}

// ---- introspection (reference capi/gradient_machine.h + matrix.h) ----
int32_t pt_engine_num_inputs(void* handle) {
  return static_cast<int32_t>(
      static_cast<Engine*>(handle)->input_names.size());
}

const char* pt_engine_input_name(void* handle, int32_t i) {
  auto* eng = static_cast<Engine*>(handle);
  if (i < 0 || i >= static_cast<int32_t>(eng->input_names.size()))
    return nullptr;
  return eng->input_names[i].c_str();
}

int pt_engine_input_shape(void* handle, int32_t i, const int64_t** shape,
                          int32_t* rank) {
  auto* eng = static_cast<Engine*>(handle);
  if (i < 0 || i >= static_cast<int32_t>(eng->input_shapes.size()))
    return -1;
  *shape = eng->input_shapes[i].data();
  *rank = static_cast<int32_t>(eng->input_shapes[i].size());
  return 0;
}

int32_t pt_engine_num_outputs(void* handle) {
  return static_cast<int32_t>(
      static_cast<Engine*>(handle)->output_names.size());
}

const char* pt_engine_output_name(void* handle, int32_t i) {
  auto* eng = static_cast<Engine*>(handle);
  if (i < 0 || i >= static_cast<int32_t>(eng->output_names.size()))
    return nullptr;
  return eng->output_names[i].c_str();
}

// Read one cached output of the last pt_engine_run/pt_engine_run_all
// (converted lazily on first read).
int pt_engine_output(void* handle, int32_t i, const float** out_data,
                     const int64_t** out_shape, int32_t* out_rank) {
  auto* eng = static_cast<Engine*>(handle);
  if (!eng->last_result ||
      i < 0 || i >= static_cast<int32_t>(eng->out_data.size())) {
    g_error = "output index out of range (run the engine first)";
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  bool ok = convert_output(eng, i);
  PyGILState_Release(gil);
  if (!ok) return -1;
  *out_data = eng->out_data[i].data();
  *out_shape = eng->out_shape[i].data();
  *out_rank = static_cast<int32_t>(eng->out_shape[i].size());
  return 0;
}

// Run inference, caching EVERY fetch target (read them back with
// Shared run core for pt_engine_run_all{,_typed}: build the feed dict,
// call InferenceEngine.run, cache EVERY fetch target on the handle
// (read back per index with pt_engine_output).  dtypes may be null (all float32) or name each
// input's element type: "float32" (default), "float64", "int64",
// "int32" — the int paths are the reference `paddle_ivector` analog
// (capi/vector.h:30), how word-id / sequence models are served.
static int run_all_impl(void* handle, const char** names,
                        const void** datas, const char** dtypes,
                        const int64_t** shapes, const int32_t* ranks,
                        int32_t n_inputs) {
  auto* eng = static_cast<Engine*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* np = nullptr;
  PyObject* feed = nullptr;
  PyObject* result = nullptr;
  // invalidate the previous run's cache up front: a FAILED run must not
  // leave pt_engine_output silently serving stale results
  Py_XDECREF(eng->last_result);
  eng->last_result = nullptr;
  eng->converted.clear();
  eng->out_data.clear();
  eng->out_shape.clear();
  do {
    np = PyImport_ImportModule("numpy");
    if (!np) break;
    feed = PyDict_New();
    if (!feed) break;
    bool feed_ok = true;
    for (int32_t i = 0; i < n_inputs && feed_ok; i++) {
      int64_t numel = 1;
      for (int32_t d = 0; d < ranks[i]; d++) numel *= shapes[i][d];
      const char* dt = (dtypes && dtypes[i]) ? dtypes[i] : "float32";
      // build a flat python list then reshape via numpy (avoids needing
      // the numpy C API headers)
      PyObject* lst = PyList_New(numel);
      if (!lst) { feed_ok = false; break; }
      if (std::strcmp(dt, "int64") == 0) {
        const int64_t* p = static_cast<const int64_t*>(datas[i]);
        for (int64_t j = 0; j < numel; j++)
          PyList_SET_ITEM(lst, j, PyLong_FromLongLong(p[j]));
      } else if (std::strcmp(dt, "int32") == 0) {
        const int32_t* p = static_cast<const int32_t*>(datas[i]);
        for (int64_t j = 0; j < numel; j++)
          PyList_SET_ITEM(lst, j, PyLong_FromLong(p[j]));
      } else if (std::strcmp(dt, "float64") == 0) {
        const double* p = static_cast<const double*>(datas[i]);
        for (int64_t j = 0; j < numel; j++)
          PyList_SET_ITEM(lst, j, PyFloat_FromDouble(p[j]));
      } else if (std::strcmp(dt, "float32") == 0) {
        const float* p = static_cast<const float*>(datas[i]);
        for (int64_t j = 0; j < numel; j++)
          PyList_SET_ITEM(lst, j, PyFloat_FromDouble(p[j]));
      } else {
        {
          std::lock_guard<std::mutex> lock(g_mu);
          g_error = std::string("unsupported input dtype: ") + dt;
        }
        Py_DECREF(lst);
        Py_XDECREF(feed);
        Py_XDECREF(np);
        PyGILState_Release(gil);
        return -1;
      }
      PyObject* shape = PyTuple_New(ranks[i]);
      for (int32_t d = 0; d < ranks[i]; d++) {
        PyTuple_SET_ITEM(shape, d, PyLong_FromLongLong(shapes[i][d]));
      }
      PyObject* arr = PyObject_CallMethod(np, "asarray", "Os", lst, dt);
      PyObject* reshaped =
          arr ? PyObject_CallMethod(arr, "reshape", "O", shape) : nullptr;
      if (!reshaped) feed_ok = false;
      else PyDict_SetItemString(feed, names[i], reshaped);
      Py_XDECREF(reshaped);
      Py_XDECREF(arr);
      Py_DECREF(shape);
      Py_DECREF(lst);
    }
    if (!feed_ok) break;
    result = PyObject_CallMethod(eng->engine, "run", "O", feed);
    if (!result) break;
    Py_ssize_t n_out = PySequence_Size(result);
    if (n_out < 0) break;  // non-sequence run() result: clean rc=-1
    eng->last_result = result;  // cache was invalidated at entry
    result = nullptr;  // ownership moved to the handle
    eng->converted.assign(n_out, false);
    eng->out_data.assign(n_out, {});
    eng->out_shape.assign(n_out, {});
    rc = 0;
  } while (false);
  if (rc != 0) capture_py_error();
  Py_XDECREF(result);
  Py_XDECREF(feed);
  Py_XDECREF(np);
  PyGILState_Release(gil);
  return rc;
}

// Run inference on float32 inputs, caching every fetch target (read
// them back with pt_engine_output).  names[i]: feed name; datas[i]:
// float32 buffer; shapes[i]: dims (ranks[i] entries).  Returns 0 on
// success.
int pt_engine_run_all(void* handle, const char** names, const float** datas,
                      const int64_t** shapes, const int32_t* ranks,
                      int32_t n_inputs) {
  return run_all_impl(handle, names,
                      reinterpret_cast<const void**>(datas), nullptr,
                      shapes, ranks, n_inputs);
}

// Dtype-tagged variant: ints for word-id/sequence models (the reference
// paddle_ivector path, capi/vector.h:30 + arguments.h sequence ids).
int pt_engine_run_all_typed(void* handle, const char** names,
                            const void** datas, const char** dtypes,
                            const int64_t** shapes, const int32_t* ranks,
                            int32_t n_inputs) {
  return run_all_impl(handle, names, datas, dtypes, shapes, ranks,
                      n_inputs);
}

// Back-compat single-output form: run, then hand back fetch out_index.
int pt_engine_run(void* handle, const char** names, const float** datas,
                  const int64_t** shapes, const int32_t* ranks,
                  int32_t n_inputs, int32_t out_index,
                  const float** out_data, const int64_t** out_shape,
                  int32_t* out_rank) {
  int rc = pt_engine_run_all(handle, names, datas, shapes, ranks, n_inputs);
  if (rc != 0) return rc;
  return pt_engine_output(handle, out_index, out_data, out_shape, out_rank);
}

void pt_engine_destroy(void* handle) {
  auto* eng = static_cast<Engine*>(handle);
  if (!eng) return;
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_XDECREF(eng->last_result);
  Py_XDECREF(eng->engine);
  PyGILState_Release(gil);
  delete eng;
}

void pt_shutdown() {
  // Finalizing an interpreter that loaded jax/XLA can hang on backend
  // threads; matching the reference capi (which never unloads), shutdown
  // is a no-op and the OS reclaims at process exit.
}

}  // extern "C"
