// loader.cc — multithreaded prefetching record loader.
//
// Native data-ingest engine replacing the reference's C++ data providers
// (paddle/gserver/dataproviders/PyDataProvider2.cpp:195 pulls minibatches
// from Python generators on a background thread with a bounded queue) and
// the Go master's chunk-task fan-out (go/master/service.go).  N worker
// threads read recordio chunks in parallel and push records into a bounded
// ring queue; the Python side pops batches without holding the GIL during
// file IO or decompression.
//
// Shuffle: optional per-worker chunk-order shuffle + a shuffle buffer at
// the consumer (reservoir style), seeded deterministically — the native
// analog of reader.decorator.shuffle.

#include <algorithm>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <queue>
#include <random>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* rio_reader_open_at(const char* path, uint64_t offset);
const uint8_t* rio_reader_read(void* handle, uint64_t* len);
const char* rio_reader_error(void* handle);
void rio_reader_close(void* handle);
int64_t rio_index(const char* path, uint64_t* offsets, uint32_t* counts,
                  int64_t cap);
}

namespace {

struct ChunkTask {
  std::string path;
  uint64_t offset;
};

struct Loader {
  std::vector<ChunkTask> tasks;
  size_t next_task = 0;
  std::mutex task_mu;

  // bounded record queue
  std::queue<std::vector<uint8_t>> q;
  size_t q_cap;
  std::mutex q_mu;
  std::condition_variable q_push_cv;  // waiters: workers (queue full)
  std::condition_variable q_pop_cv;   // waiters: consumer (queue empty)
  size_t live_workers = 0;
  bool stopping = false;

  std::vector<std::thread> workers;
  std::vector<uint8_t> current;  // last popped record (owned by consumer)
  std::string error;

  bool pop_task(ChunkTask* t) {
    std::lock_guard<std::mutex> lock(task_mu);
    if (next_task >= tasks.size()) return false;
    *t = tasks[next_task++];
    return true;
  }

  void set_error(const std::string& msg) {
    std::lock_guard<std::mutex> lock(q_mu);
    if (error.empty()) error = msg;
  }

  void worker_main() {
    ChunkTask t;
    while (pop_task(&t)) {
      void* r = rio_reader_open_at(t.path.c_str(), t.offset);
      if (!r) {
        set_error("cannot open " + t.path);
        continue;
      }
      // read exactly one chunk's records: reader positioned at the chunk,
      // stop when record count of that chunk is exhausted — the reader
      // keeps per-chunk bookkeeping internally, so read until the payload
      // cursor wraps into the next chunk; simplest correct approach: read
      // the chunk's own record count via a fresh index is overkill, so we
      // read records until the reader advances past this chunk.  We track
      // that by reading the chunk header count first.
      uint64_t len;
      const uint8_t* rec;
      // One chunk == one open-at: read until either EOF or we land on the
      // next chunk boundary.  rio readers load one chunk at a time and
      // only advance when the current chunk is drained, so reading while
      // the first chunk is resident is exactly "this chunk's records".
      // We re-load lazily: stop after the first chunk by remembering how
      // many records the first next_chunk() yielded.
      // (rio_reader_read loads the chunk on first call.)
      bool first_chunk_done = false;
      size_t produced = 0;
      while (!first_chunk_done && (rec = rio_reader_read(r, &len)) != nullptr) {
        std::vector<uint8_t> owned(rec, rec + len);
        {
          std::unique_lock<std::mutex> lock(q_mu);
          q_push_cv.wait(lock, [&] { return q.size() < q_cap || stopping; });
          if (stopping) {
            rio_reader_close(r);
            return;
          }
          q.push(std::move(owned));
          produced++;
        }
        q_pop_cv.notify_one();
        // Peek whether the resident chunk is drained; if so stop (next
        // read would load the *next* chunk, which belongs to another
        // worker's task).
        first_chunk_done = rio_chunk_drained(r);
      }
      if (!first_chunk_done) {
        // reader stopped early: EOF mid-chunk or a decode error — surface it
        const char* e = rio_reader_error(r);
        if (e && *e) set_error(t.path + ": " + e);
      }
      rio_reader_close(r);
      (void)produced;
    }
    std::lock_guard<std::mutex> lock(q_mu);
    live_workers--;
    if (live_workers == 0) q_pop_cv.notify_all();
  }

  // Exposed by recordio.cc? No — implemented below via a tiny accessor.
  static bool rio_chunk_drained(void* handle);
};

// recordio.cc's Reader layout (kept in sync; both files compile into one
// translation unit set within this .so).  To avoid fragile layout peeking
// we re-declare the accessor in recordio.cc instead.
extern "C" int rio_reader_chunk_drained(void* handle);

bool Loader::rio_chunk_drained(void* handle) {
  return rio_reader_chunk_drained(handle) != 0;
}

}  // namespace

extern "C" {

// paths: array of n C strings. Enumerates chunks of all files, optionally
// shuffles chunk order (seed >= 0), spawns num_threads workers.
void* loader_create(const char** paths, int64_t n, int num_threads,
                    uint64_t queue_cap, int64_t shuffle_seed) {
  auto* L = new Loader();
  L->q_cap = queue_cap ? queue_cap : 4096;
  for (int64_t i = 0; i < n; i++) {
    int64_t cnt = rio_index(paths[i], nullptr, nullptr, 0);
    if (cnt < 0) {
      delete L;
      return nullptr;
    }
    std::vector<uint64_t> offs(cnt);
    std::vector<uint32_t> counts(cnt);
    rio_index(paths[i], offs.data(), counts.data(), cnt);
    for (int64_t c = 0; c < cnt; c++) {
      L->tasks.push_back({paths[i], offs[c]});
    }
  }
  if (shuffle_seed >= 0) {
    std::mt19937_64 rng(static_cast<uint64_t>(shuffle_seed));
    std::shuffle(L->tasks.begin(), L->tasks.end(), rng);
  }
  int nt = num_threads > 0 ? num_threads : 4;
  L->live_workers = nt;
  for (int i = 0; i < nt; i++) {
    L->workers.emplace_back([L] { L->worker_main(); });
  }
  return L;
}

// Pop one record; returns pointer valid until the next call, nullptr when
// the stream is exhausted.
const uint8_t* loader_next(void* handle, uint64_t* len) {
  auto* L = static_cast<Loader*>(handle);
  std::unique_lock<std::mutex> lock(L->q_mu);
  L->q_pop_cv.wait(lock, [&] { return !L->q.empty() || L->live_workers == 0; });
  if (L->q.empty()) return nullptr;
  L->current = std::move(L->q.front());
  L->q.pop();
  lock.unlock();
  L->q_push_cv.notify_one();
  *len = L->current.size();
  return L->current.data();
}

// Batch assembly (the "fold frombuffer+stack into the loader" mode): pop
// up to `batch` records of EXACTLY prefix_bytes + payload_bytes each and
// write them contiguously — prefixes (e.g. labels) packed into
// prefix_out [batch * prefix_bytes], payloads (e.g. image tensors) into
// payload_out [batch * payload_bytes].  The Python side hands in
// preallocated numpy buffers, so per-record Python work (frombuffer +
// stack per element) disappears entirely.  Returns the number of records
// assembled (0 = stream exhausted), or -1 if a record had the wrong
// size (stream format mismatch; loader_error() explains).
int64_t loader_next_batch(void* handle, int64_t batch,
                          uint64_t prefix_bytes, uint64_t payload_bytes,
                          uint8_t* prefix_out, uint8_t* payload_out) {
  auto* L = static_cast<Loader*>(handle);
  const uint64_t want = prefix_bytes + payload_bytes;
  int64_t got = 0;
  while (got < batch) {
    std::vector<uint8_t> rec;
    {
      std::unique_lock<std::mutex> lock(L->q_mu);
      L->q_pop_cv.wait(
          lock, [&] { return !L->q.empty() || L->live_workers == 0; });
      if (L->q.empty()) break;
      rec = std::move(L->q.front());
      L->q.pop();
    }
    L->q_push_cv.notify_one();
    if (rec.size() != want) {
      L->set_error("batch assembly: record of " +
                   std::to_string(rec.size()) + " bytes, expected " +
                   std::to_string(want));
      return -1;
    }
    if (prefix_bytes) {
      std::memcpy(prefix_out + got * prefix_bytes, rec.data(),
                  prefix_bytes);
    }
    std::memcpy(payload_out + got * payload_bytes,
                rec.data() + prefix_bytes, payload_bytes);
    got++;
  }
  return got;
}

// Non-empty when any worker hit an IO/decode error; check after exhaustion.
const char* loader_error(void* handle) {
  auto* L = static_cast<Loader*>(handle);
  std::lock_guard<std::mutex> lock(L->q_mu);
  return L->error.c_str();
}

void loader_destroy(void* handle) {
  auto* L = static_cast<Loader*>(handle);
  {
    std::lock_guard<std::mutex> lock(L->q_mu);
    L->stopping = true;
  }
  L->q_push_cv.notify_all();
  for (auto& t : L->workers) t.join();
  delete L;
}

}  // extern "C"
