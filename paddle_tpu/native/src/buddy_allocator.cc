// buddy_allocator.cc — power-of-two buddy allocator over one mmap'd arena.
//
// Native memory-management layer mirroring the capability of the reference's
// fluid allocator (paddle/memory/detail/buddy_allocator.{h,cc} over system
// allocators, exposed as memory::Alloc/Free/Used — paddle/memory/memory.h:36).
// On TPU the device heap belongs to XLA/PJRT, so this arena serves the
// *host* side: staging buffers for the data loader and feed pipeline, where
// steady-state training must not churn malloc.
//
// Flat C ABI for ctypes.

#include <sys/mman.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <vector>

namespace {

constexpr int kMinOrder = 6;  // 64-byte blocks (cacheline)

struct Buddy {
  uint8_t* base = nullptr;
  size_t arena_size = 0;
  int max_order = 0;
  // free_lists[o] holds offsets of free blocks of size 1<<o
  std::vector<std::vector<size_t>> free_lists;
  // order of the block allocated at offset (or -1)
  std::vector<int8_t> alloc_order;  // indexed by offset >> kMinOrder
  size_t used = 0;
  std::mutex mu;

  explicit Buddy(size_t size) {
    // round up to power of two
    int order = kMinOrder;
    while ((size_t(1) << order) < size) order++;
    arena_size = size_t(1) << order;
    max_order = order;
    base = static_cast<uint8_t*>(mmap(nullptr, arena_size,
                                      PROT_READ | PROT_WRITE,
                                      MAP_PRIVATE | MAP_ANONYMOUS, -1, 0));
    if (base == MAP_FAILED) {
      base = nullptr;
      return;
    }
    free_lists.resize(max_order + 1);
    free_lists[max_order].push_back(0);
    alloc_order.assign(arena_size >> kMinOrder, -1);
  }

  ~Buddy() {
    if (base) munmap(base, arena_size);
  }

  static int order_for(size_t n) {
    int o = kMinOrder;
    while ((size_t(1) << o) < n) o++;
    return o;
  }

  void* alloc(size_t n) {
    if (n == 0 || !base) return nullptr;
    int want = order_for(n);
    if (want > max_order) return nullptr;
    std::lock_guard<std::mutex> lock(mu);
    int o = want;
    while (o <= max_order && free_lists[o].empty()) o++;
    if (o > max_order) return nullptr;  // out of arena
    size_t off = free_lists[o].back();
    free_lists[o].pop_back();
    // split down to the wanted order, pushing buddies back
    while (o > want) {
      o--;
      free_lists[o].push_back(off + (size_t(1) << o));
    }
    alloc_order[off >> kMinOrder] = static_cast<int8_t>(want);
    used += size_t(1) << want;
    return base + off;
  }

  bool free(void* p) {
    if (!p) return true;
    size_t off = static_cast<uint8_t*>(p) - base;
    if (off >= arena_size) return false;
    std::lock_guard<std::mutex> lock(mu);
    int o = alloc_order[off >> kMinOrder];
    if (o < 0) return false;  // double free / bad pointer
    alloc_order[off >> kMinOrder] = -1;
    used -= size_t(1) << o;
    // coalesce with buddy while possible
    while (o < max_order) {
      size_t buddy = off ^ (size_t(1) << o);
      auto& fl = free_lists[o];
      bool merged = false;
      for (size_t i = 0; i < fl.size(); i++) {
        if (fl[i] == buddy) {
          fl[i] = fl.back();
          fl.pop_back();
          off = off < buddy ? off : buddy;
          o++;
          merged = true;
          break;
        }
      }
      if (!merged) break;
    }
    free_lists[o].push_back(off);
    return true;
  }
};

}  // namespace

extern "C" {

void* buddy_create(uint64_t arena_bytes) {
  auto* b = new Buddy(arena_bytes);
  if (!b->base) {
    delete b;
    return nullptr;
  }
  return b;
}

void* buddy_alloc(void* handle, uint64_t n) {
  return static_cast<Buddy*>(handle)->alloc(n);
}

int buddy_free(void* handle, void* p) {
  return static_cast<Buddy*>(handle)->free(p) ? 0 : -1;
}

uint64_t buddy_used(void* handle) {
  return static_cast<Buddy*>(handle)->used;
}

uint64_t buddy_capacity(void* handle) {
  return static_cast<Buddy*>(handle)->arena_size;
}

void buddy_destroy(void* handle) { delete static_cast<Buddy*>(handle); }

}  // extern "C"
