// recordio.cc — chunked record file format with CRC32 + optional zlib
// compression.
//
// TPU-native rebuild of the reference's RecordIO data path: the Go master
// partitions datasets into RecordIO chunks and hands them out as tasks
// (reference go/master/service.go:106 partition), and the v2 reader layer
// creates readers over recordio files (reference
// python/paddle/v2/reader/creator.py:60).  This is the native (C++) storage
// layer under paddle_tpu.reader / paddle_tpu.distributed.master.
//
// File layout:
//   File  := Chunk*
//   Chunk := Header Payload
//   Header (little-endian):
//     u32 magic       0x50545243 ("CRTP")
//     u32 compressor  0 = none, 1 = zlib
//     u32 crc32       of the *stored* (possibly compressed) payload bytes
//     u32 num_records
//     u64 raw_len     uncompressed payload length
//     u64 stored_len  stored payload length
//   Payload (after decompression) := { u32 record_len, bytes }*
//
// Exposed as a flat C ABI consumed via ctypes (no pybind11 in this image).

#include <zlib.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x50545243u;  // "CRTP"

#pragma pack(push, 1)
struct ChunkHeader {
  uint32_t magic;
  uint32_t compressor;
  uint32_t crc;
  uint32_t num_records;
  uint64_t raw_len;
  uint64_t stored_len;
};
#pragma pack(pop)

struct Writer {
  FILE* f = nullptr;
  int compressor = 0;
  size_t max_chunk_bytes = 0;
  uint32_t num_records = 0;
  std::vector<uint8_t> buf;  // raw payload being accumulated
  std::string error;

  bool flush_chunk() {
    if (num_records == 0) return true;
    std::vector<uint8_t> stored;
    const std::vector<uint8_t>* out = &buf;
    if (compressor == 1) {
      uLongf bound = compressBound(buf.size());
      stored.resize(bound);
      if (compress2(stored.data(), &bound, buf.data(), buf.size(),
                    Z_DEFAULT_COMPRESSION) != Z_OK) {
        error = "zlib compress failed";
        return false;
      }
      stored.resize(bound);
      out = &stored;
    }
    ChunkHeader h;
    h.magic = kMagic;
    h.compressor = static_cast<uint32_t>(compressor);
    h.crc = crc32(0, out->data(), out->size());
    h.num_records = num_records;
    h.raw_len = buf.size();
    h.stored_len = out->size();
    if (fwrite(&h, sizeof(h), 1, f) != 1 ||
        (!out->empty() && fwrite(out->data(), 1, out->size(), f) != out->size())) {
      error = "short write";
      return false;
    }
    buf.clear();
    num_records = 0;
    return true;
  }
};

struct Reader {
  FILE* f = nullptr;
  std::vector<uint8_t> payload;  // decompressed current chunk
  size_t pos = 0;                // cursor into payload
  uint32_t remaining = 0;        // records left in current chunk
  std::string error;

  // Load the next chunk; returns false at EOF or error.
  bool next_chunk() {
    ChunkHeader h;
    size_t n = fread(&h, 1, sizeof(h), f);
    if (n == 0) return false;  // clean EOF
    if (n != sizeof(h) || h.magic != kMagic) {
      error = "corrupt chunk header";
      return false;
    }
    std::vector<uint8_t> stored(h.stored_len);
    if (fread(stored.data(), 1, stored.size(), f) != stored.size()) {
      error = "truncated chunk payload";
      return false;
    }
    if (crc32(0, stored.data(), stored.size()) != h.crc) {
      error = "chunk crc mismatch";
      return false;
    }
    if (h.compressor == 1) {
      payload.resize(h.raw_len);
      uLongf raw = h.raw_len;
      if (uncompress(payload.data(), &raw, stored.data(), stored.size()) !=
              Z_OK ||
          raw != h.raw_len) {
        error = "zlib uncompress failed";
        return false;
      }
    } else {
      payload = std::move(stored);
    }
    pos = 0;
    remaining = h.num_records;
    return true;
  }
};

}  // namespace

extern "C" {

// ---------------------------------------------------------------- writer
void* rio_writer_open(const char* path, int compressor,
                      uint64_t max_chunk_bytes) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  auto* w = new Writer();
  w->f = f;
  w->compressor = compressor;
  w->max_chunk_bytes = max_chunk_bytes ? max_chunk_bytes : (1u << 20);
  return w;
}

int rio_writer_write(void* handle, const uint8_t* data, uint64_t len) {
  auto* w = static_cast<Writer*>(handle);
  if (len > UINT32_MAX) {
    w->error = "record larger than 4 GiB";
    return -1;
  }
  uint32_t len32 = static_cast<uint32_t>(len);
  const uint8_t* p = reinterpret_cast<const uint8_t*>(&len32);
  w->buf.insert(w->buf.end(), p, p + sizeof(len32));
  w->buf.insert(w->buf.end(), data, data + len);
  w->num_records++;
  if (w->buf.size() >= w->max_chunk_bytes) {
    if (!w->flush_chunk()) return -1;
  }
  return 0;
}

int rio_writer_close(void* handle) {
  auto* w = static_cast<Writer*>(handle);
  int rc = w->flush_chunk() ? 0 : -1;
  fclose(w->f);
  delete w;
  return rc;
}

// ---------------------------------------------------------------- reader
void* rio_reader_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  auto* r = new Reader();
  r->f = f;
  return r;
}

// Returns pointer to the record bytes (valid until the next call) and sets
// *len.  Returns nullptr at EOF or error (check rio_reader_error).
const uint8_t* rio_reader_read(void* handle, uint64_t* len) {
  auto* r = static_cast<Reader*>(handle);
  while (r->remaining == 0) {
    if (!r->next_chunk()) return nullptr;
  }
  if (r->pos + 4 > r->payload.size()) {
    r->error = "corrupt record length";
    return nullptr;
  }
  uint32_t rec_len;
  memcpy(&rec_len, r->payload.data() + r->pos, 4);
  r->pos += 4;
  if (r->pos + rec_len > r->payload.size()) {
    r->error = "corrupt record payload";
    return nullptr;
  }
  const uint8_t* out = r->payload.data() + r->pos;
  r->pos += rec_len;
  r->remaining--;
  *len = rec_len;
  return out;
}

const char* rio_reader_error(void* handle) {
  return static_cast<Reader*>(handle)->error.c_str();
}

// 1 when the currently-resident chunk has been fully consumed (the next
// read would load a new chunk).  Lets the loader treat "one chunk" as one
// unit of work (go/master task granularity).
int rio_reader_chunk_drained(void* handle) {
  return static_cast<Reader*>(handle)->remaining == 0 ? 1 : 0;
}

void rio_reader_close(void* handle) {
  auto* r = static_cast<Reader*>(handle);
  fclose(r->f);
  delete r;
}

// ------------------------------------------------------- chunk indexing
// Scan chunk boundaries so a dataset master can partition a file into
// chunk-granular tasks (go/master/service.go partition analog).  Fills up
// to cap (offset, num_records) pairs; returns total chunk count, or -1.
int64_t rio_index(const char* path, uint64_t* offsets, uint32_t* counts,
                  int64_t cap) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  int64_t n = 0;
  for (;;) {
    long off = ftell(f);
    ChunkHeader h;
    size_t got = fread(&h, 1, sizeof(h), f);
    if (got == 0) break;
    if (got != sizeof(h) || h.magic != kMagic) {
      fclose(f);
      return -1;
    }
    if (n < cap) {
      offsets[n] = static_cast<uint64_t>(off);
      counts[n] = h.num_records;
    }
    n++;
    if (fseek(f, static_cast<long>(h.stored_len), SEEK_CUR) != 0) {
      fclose(f);
      return -1;
    }
  }
  fclose(f);
  return n;
}

// Open a reader positioned at a specific chunk offset (task execution).
void* rio_reader_open_at(const char* path, uint64_t offset) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  if (fseek(f, static_cast<long>(offset), SEEK_SET) != 0) {
    fclose(f);
    return nullptr;
  }
  auto* r = new Reader();
  r->f = f;
  return r;
}

}  // extern "C"
