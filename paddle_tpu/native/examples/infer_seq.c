/* Serve an NLP (word-id input) model through the C API — the reference
 * capi/examples pattern for sequence models (paddle_ivector inputs,
 * capi/vector.h): feed int64 token ids with pt_engine_run_all_typed,
 * read back float32 outputs per fetch target.
 *
 * Usage: infer_seq <model_dir> <pythonpath> <t> id0 id1 ... id{t-1}
 * Prints each output as "out<i>: v0 v1 ..." one line per fetch target.
 */
#include <stdio.h>
#include <stdlib.h>

#include "paddle_tpu_capi.h"

int main(int argc, char** argv) {
  if (argc < 4) {
    fprintf(stderr, "usage: %s <model_dir> <pythonpath> <t> ids...\n",
            argv[0]);
    return 2;
  }
  const char* model_dir = argv[1];
  const char* pythonpath = argv[2];
  int64_t t = atoll(argv[3]);
  if (argc != 4 + (int)t) {
    fprintf(stderr, "expected %lld ids\n", (long long)t);
    return 2;
  }
  int64_t* ids = malloc(sizeof(int64_t) * t);
  for (int64_t j = 0; j < t; j++) ids[j] = atoll(argv[4 + j]);

  if (pt_init(pythonpath) != 0) {
    fprintf(stderr, "pt_init failed: %s\n", pt_last_error());
    return 1;
  }
  void* h = pt_engine_create(model_dir);
  if (!h) {
    fprintf(stderr, "pt_engine_create failed: %s\n", pt_last_error());
    return 1;
  }

  /* one int64 sequence input, batch of 1: [1, t] */
  const char* names[1];
  names[0] = pt_engine_input_name(h, 0);
  const void* datas[1] = {ids};
  const char* dtypes[1] = {"int64"};
  int64_t shape0[2];
  shape0[0] = 1;
  shape0[1] = t;
  const int64_t* shapes[1] = {shape0};
  int32_t ranks[1] = {2};
  if (pt_engine_run_all_typed(h, names, datas, dtypes, shapes, ranks, 1)
      != 0) {
    fprintf(stderr, "run failed: %s\n", pt_last_error());
    return 1;
  }
  int32_t n_out = pt_engine_num_outputs(h);
  for (int32_t i = 0; i < n_out; i++) {
    const float* out;
    const int64_t* oshape;
    int32_t orank;
    if (pt_engine_output(h, i, &out, &oshape, &orank) != 0) {
      fprintf(stderr, "output %d failed: %s\n", i, pt_last_error());
      return 1;
    }
    int64_t numel = 1;
    for (int32_t d = 0; d < orank; d++) numel *= oshape[d];
    printf("out%d:", i);
    for (int64_t j = 0; j < numel; j++) printf(" %.6f", out[j]);
    printf("\n");
  }
  pt_engine_destroy(h);
  pt_shutdown();
  free(ids);
  return 0;
}
