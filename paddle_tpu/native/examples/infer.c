/* Example C consumer of the paddle_tpu C inference API (reference:
 * paddle/capi/examples/model_inference).  Loads an exported model dir and
 * runs one batch of float32 inputs read as argv:
 *
 *   ./infer <pythonpath> <model_dir> <feed_name> <d0> <d1> v0 v1 ...
 *
 * Prints the flat output values, one per line.
 */
#include <stdio.h>
#include <stdlib.h>

#include "paddle_tpu_capi.h"

int main(int argc, char** argv) {
  if (argc < 6) {
    fprintf(stderr, "usage: %s pythonpath model_dir feed d0 d1 v...\n",
            argv[0]);
    return 2;
  }
  if (pt_init(argv[1]) != 0) {
    fprintf(stderr, "init failed: %s\n", pt_last_error());
    return 1;
  }
  void* h = pt_engine_create(argv[2]);
  if (!h) {
    fprintf(stderr, "load failed: %s\n", pt_last_error());
    return 1;
  }
  int64_t shape[2] = {atoll(argv[4]), atoll(argv[5])};
  int64_t numel = shape[0] * shape[1];
  if (argc - 6 != numel) {
    fprintf(stderr, "expected %lld values\n", (long long)numel);
    return 2;
  }
  float* data = (float*)malloc(sizeof(float) * numel);
  for (int64_t i = 0; i < numel; i++) data[i] = (float)atof(argv[6 + i]);

  /* introspection: enumerate the model's feed/fetch surface */
  for (int32_t i = 0; i < pt_engine_num_inputs(h); i++) {
    const int64_t* ishape;
    int32_t irank;
    pt_engine_input_shape(h, i, &ishape, &irank);
    fprintf(stderr, "input %d: %s rank=%d first_dim=%lld\n", i,
            pt_engine_input_name(h, i), irank,
            irank ? (long long)ishape[0] : -1);
  }
  for (int32_t i = 0; i < pt_engine_num_outputs(h); i++) {
    fprintf(stderr, "output %d: %s\n", i, pt_engine_output_name(h, i));
  }

  const char* names[1] = {argv[3]};
  const float* datas[1] = {data};
  const int64_t* shapes[1] = {shape};
  int32_t ranks[1] = {2};

  if (pt_engine_run_all(h, names, datas, shapes, ranks, 1) != 0) {
    fprintf(stderr, "run failed: %s\n", pt_last_error());
    return 1;
  }
  /* every fetch target, tagged by index */
  for (int32_t oi = 0; oi < pt_engine_num_outputs(h); oi++) {
    const float* out;
    const int64_t* out_shape;
    int32_t out_rank;
    if (pt_engine_output(h, oi, &out, &out_shape, &out_rank) != 0) {
      fprintf(stderr, "output %d failed: %s\n", oi, pt_last_error());
      return 1;
    }
    int64_t n = 1;
    for (int32_t d = 0; d < out_rank; d++) n *= out_shape[d];
    for (int64_t i = 0; i < n; i++) printf("%d %f\n", oi, out[i]);
  }

  pt_engine_destroy(h);
  pt_shutdown();
  free(data);
  return 0;
}
