"""recordio — Python API over the native chunked record format.

Reference capability: RecordIO files are the cluster dataset interchange
format — ``python/paddle/v2/reader/creator.py:60 recordio`` reads them and
``go/master/service.go:106 partition`` splits them into chunk tasks.  The
native implementation lives in ``src/recordio.cc``; a pure-Python
implementation of the same on-disk format is provided as fallback and as a
cross-check in tests.
"""

import ctypes
import os
import struct
import zlib

from . import available, lib

MAGIC = 0x50545243
_HEADER = struct.Struct("<IIIIQQ")  # magic, compressor, crc, nrec, raw, stored


class Writer:
    """Append records to a recordio file.

    compressor: 0 = none, 1 = zlib."""

    def __init__(self, path, compressor=0, max_chunk_bytes=1 << 20,
                 use_native=None):
        self.path = os.fspath(path)
        use_native = available() if use_native is None else use_native
        self._native = None
        if use_native:
            self._lib = lib()
            self._native = self._lib.rio_writer_open(
                self.path.encode(), compressor, max_chunk_bytes
            )
            if not self._native:
                raise IOError(f"cannot open {path} for writing")
        else:
            self._f = open(self.path, "wb")
            self._compressor = compressor
            self._max = max_chunk_bytes
            self._buf = bytearray()
            self._nrec = 0

    def write(self, record: bytes):
        if self._native:
            buf = (ctypes.c_uint8 * len(record)).from_buffer_copy(record)
            if self._lib.rio_writer_write(self._native, buf, len(record)) != 0:
                raise IOError("recordio write failed")
            return
        self._buf += struct.pack("<I", len(record)) + record
        self._nrec += 1
        if len(self._buf) >= self._max:
            self._flush()

    def _flush(self):
        if self._nrec == 0:
            return
        raw = bytes(self._buf)
        stored = zlib.compress(raw) if self._compressor == 1 else raw
        crc = zlib.crc32(stored) & 0xFFFFFFFF
        self._f.write(_HEADER.pack(MAGIC, self._compressor, crc, self._nrec,
                                   len(raw), len(stored)))
        self._f.write(stored)
        self._buf = bytearray()
        self._nrec = 0

    def close(self):
        if self._native:
            rc = self._lib.rio_writer_close(self._native)
            self._native = None
            if rc != 0:
                raise IOError("recordio close failed")
        elif getattr(self, "_f", None):
            self._flush()
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _py_read_chunks(f):
    while True:
        hdr = f.read(_HEADER.size)
        if not hdr:
            return
        if len(hdr) < _HEADER.size:
            raise IOError("truncated recordio chunk header")
        magic, comp, crc, nrec, raw_len, stored_len = _HEADER.unpack(hdr)
        if magic != MAGIC:
            raise IOError("corrupt recordio chunk header")
        stored = f.read(stored_len)
        if len(stored) != stored_len:
            raise IOError("truncated recordio chunk")
        if zlib.crc32(stored) & 0xFFFFFFFF != crc:
            raise IOError("recordio crc mismatch")
        payload = zlib.decompress(stored) if comp == 1 else stored
        pos = 0
        for _ in range(nrec):
            (ln,) = struct.unpack_from("<I", payload, pos)
            pos += 4
            yield payload[pos:pos + ln]
            pos += ln


def reader(path, use_native=None):
    """Generator over records of a recordio file."""
    use_native = available() if use_native is None else use_native
    path = os.fspath(path)
    if not use_native:
        with open(path, "rb") as f:
            yield from _py_read_chunks(f)
        return
    l = lib()
    h = l.rio_reader_open(path.encode())
    if not h:
        raise IOError(f"cannot open {path}")
    try:
        n = ctypes.c_uint64()
        while True:
            p = l.rio_reader_read(h, ctypes.byref(n))
            if not p:
                err = l.rio_reader_error(h)
                if err:
                    raise IOError(f"recordio: {err.decode()}")
                return
            yield ctypes.string_at(p, n.value)
    finally:
        l.rio_reader_close(h)


def index(path):
    """[(chunk_offset, num_records)] — the master's partition unit
    (go/master/service.go:106)."""
    path = os.fspath(path)
    if not available():
        out = []
        with open(path, "rb") as f:
            while True:
                off = f.tell()
                hdr = f.read(_HEADER.size)
                if not hdr:
                    return out
                if len(hdr) < _HEADER.size:
                    raise IOError("truncated recordio chunk header")
                magic, _, _, nrec, _, stored_len = _HEADER.unpack(hdr)
                if magic != MAGIC:
                    raise IOError("corrupt recordio chunk header")
                out.append((off, nrec))
                f.seek(stored_len, os.SEEK_CUR)
        return out
    l = lib()
    cnt = l.rio_index(path.encode(), None, None, 0)
    if cnt < 0:
        raise IOError(f"cannot index {path}")
    offs = (ctypes.c_uint64 * cnt)()
    counts = (ctypes.c_uint32 * cnt)()
    l.rio_index(path.encode(), offs, counts, cnt)
    return list(zip(offs, counts))


def read_chunk(path, offset):
    """Records of the single chunk at ``offset`` (task execution)."""
    if not available():
        with open(path, "rb") as f:
            f.seek(offset)
            hdr = f.read(_HEADER.size)
            if len(hdr) < _HEADER.size:
                raise IOError("truncated recordio chunk header")
            magic, comp, crc, nrec, raw_len, stored_len = _HEADER.unpack(hdr)
            if magic != MAGIC:
                raise IOError("corrupt recordio chunk header")
            stored = f.read(stored_len)
            if len(stored) != stored_len:
                raise IOError("truncated recordio chunk")
            if zlib.crc32(stored) & 0xFFFFFFFF != crc:
                raise IOError("recordio crc mismatch")
            payload = zlib.decompress(stored) if comp == 1 else stored
            pos = 0
            for _ in range(nrec):
                (ln,) = struct.unpack_from("<I", payload, pos)
                pos += 4
                yield payload[pos:pos + ln]
                pos += ln
        return
    l = lib()
    h = l.rio_reader_open_at(os.fspath(path).encode(), offset)
    if not h:
        raise IOError(f"cannot open {path}@{offset}")
    try:
        n = ctypes.c_uint64()
        while True:
            p = l.rio_reader_read(h, ctypes.byref(n))
            if not p:
                err = l.rio_reader_error(h)
                if err:
                    raise IOError(f"recordio: {err.decode()}")
                return
            yield ctypes.string_at(p, n.value)
            if l.rio_reader_chunk_drained(h):
                return
    finally:
        l.rio_reader_close(h)
