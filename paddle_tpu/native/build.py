"""Build driver for the native C++ runtime library.

Compiles ``src/*.cc`` into one shared object with g++ (no pybind11 in the
image — the ABI is flat C consumed via ctypes).  Rebuilds only when source
hashes change; the result is cached under ``_build/``.
"""

import hashlib
import os
import subprocess
import sysconfig

_HERE = os.path.dirname(os.path.abspath(__file__))
SRC_DIR = os.path.join(_HERE, "src")
BUILD_DIR = os.path.join(_HERE, "_build")
LIB_BASENAME = "libpaddle_tpu_native.so"

CXXFLAGS = ["-O2", "-std=c++17", "-fPIC", "-shared", "-pthread", "-Wall"]
LDLIBS = ["-lz"]


CAPI_SOURCES = {"capi.cc"}  # built separately (needs Python headers)


def _sources():
    return sorted(
        os.path.join(SRC_DIR, f)
        for f in os.listdir(SRC_DIR)
        if f.endswith(".cc") and f not in CAPI_SOURCES
    )


def _digest(sources):
    h = hashlib.sha256()
    for s in sources:
        with open(s, "rb") as f:
            h.update(f.read())
    h.update(" ".join(CXXFLAGS + LDLIBS).encode())
    return h.hexdigest()[:16]


def build(force=False):
    """Compile (if stale) and return the path to the shared library, or
    None when no C++ toolchain is available (pure-Python fallbacks take
    over)."""
    sources = _sources()
    if not sources:
        return None
    os.makedirs(BUILD_DIR, exist_ok=True)
    stamp = os.path.join(BUILD_DIR, "stamp")
    lib = os.path.join(BUILD_DIR, LIB_BASENAME)
    digest = _digest(sources)
    if not force and os.path.exists(lib) and os.path.exists(stamp):
        with open(stamp) as f:
            if f.read().strip() == digest:
                return lib
    cxx = os.environ.get("CXX", "g++")
    cmd = [cxx] + CXXFLAGS + sources + ["-o", lib] + LDLIBS
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        err = getattr(e, "stderr", str(e))
        raise RuntimeError(f"native build failed:\n{err}") from e
    with open(stamp, "w") as f:
        f.write(digest)
    return lib


def build_capi(force=False):
    """Compile the C inference API (embeds CPython) into
    libpaddle_tpu_capi.so; returns its path."""
    src = os.path.join(SRC_DIR, "capi.cc")
    os.makedirs(BUILD_DIR, exist_ok=True)
    lib = os.path.join(BUILD_DIR, "libpaddle_tpu_capi.so")
    stamp = os.path.join(BUILD_DIR, "capi.stamp")
    h = hashlib.sha256()
    with open(src, "rb") as f:
        h.update(f.read())
    digest = h.hexdigest()[:16]
    if not force and os.path.exists(lib) and os.path.exists(stamp):
        with open(stamp) as f:
            if f.read().strip() == digest:
                return lib
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR")
    pyver = sysconfig.get_config_var("LDVERSION") or sysconfig.get_config_var(
        "VERSION")
    cxx = os.environ.get("CXX", "g++")
    cmd = [cxx] + CXXFLAGS + [f"-I{inc}", src, "-o", lib,
                              f"-L{libdir}", f"-lpython{pyver}"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        err = getattr(e, "stderr", str(e))
        raise RuntimeError(f"capi build failed:\n{err}") from e
    with open(stamp, "w") as f:
        f.write(digest)
    return lib


if __name__ == "__main__":
    print(build(force=True))
    print(build_capi(force=True))
