"""paddle_tpu.native — C++ runtime components (ctypes bindings).

The reference implements its runtime in native code (allocator
``paddle/memory``, data providers ``paddle/gserver/dataproviders``, RecordIO
chunk partitioning in ``go/master``).  On TPU the *compute* path is XLA, but
the host-side runtime around it is native here too:

* ``recordio``   — chunked, CRC-checked record file format (writer/reader/
                   chunk index) — storage layer for datasets and the
                   distributed master's task partitioning.
* ``Loader``     — multithreaded prefetching record loader with a bounded
                   queue (the PyDataProvider2 background-thread pattern,
                   without the GIL in the IO path).
* ``BuddyAllocator`` — power-of-two buddy arena for host staging buffers
                   (paddle/memory/detail/buddy_allocator analog).

If no C++ toolchain is available the recordio format falls back to a pure-
Python implementation (same on-disk bytes); Loader/BuddyAllocator then
raise on construction.
"""

import ctypes
import os

from . import build as _build

_lib = None
_lib_err = None


def _load():
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    try:
        path = _build.build()
        lib = ctypes.CDLL(path)
    except Exception as e:  # pragma: no cover - toolchain-less environments
        _lib_err = e
        return None

    lib.rio_writer_open.restype = ctypes.c_void_p
    lib.rio_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                    ctypes.c_uint64]
    lib.rio_writer_write.restype = ctypes.c_int
    lib.rio_writer_write.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_uint8),
                                     ctypes.c_uint64]
    lib.rio_writer_close.restype = ctypes.c_int
    lib.rio_writer_close.argtypes = [ctypes.c_void_p]

    lib.rio_reader_open.restype = ctypes.c_void_p
    lib.rio_reader_open.argtypes = [ctypes.c_char_p]
    lib.rio_reader_open_at.restype = ctypes.c_void_p
    lib.rio_reader_open_at.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.rio_reader_read.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.rio_reader_read.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_uint64)]
    lib.rio_reader_error.restype = ctypes.c_char_p
    lib.rio_reader_error.argtypes = [ctypes.c_void_p]
    lib.rio_reader_close.argtypes = [ctypes.c_void_p]
    lib.rio_reader_chunk_drained.restype = ctypes.c_int
    lib.rio_reader_chunk_drained.argtypes = [ctypes.c_void_p]
    lib.rio_index.restype = ctypes.c_int64
    lib.rio_index.argtypes = [ctypes.c_char_p,
                              ctypes.POINTER(ctypes.c_uint64),
                              ctypes.POINTER(ctypes.c_uint32),
                              ctypes.c_int64]

    lib.loader_create.restype = ctypes.c_void_p
    lib.loader_create.argtypes = [ctypes.POINTER(ctypes.c_char_p),
                                  ctypes.c_int64, ctypes.c_int,
                                  ctypes.c_uint64, ctypes.c_int64]
    lib.loader_next.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.loader_next.argtypes = [ctypes.c_void_p,
                                ctypes.POINTER(ctypes.c_uint64)]
    lib.loader_next_batch.restype = ctypes.c_int64
    lib.loader_next_batch.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                      ctypes.c_uint64, ctypes.c_uint64,
                                      ctypes.c_void_p, ctypes.c_void_p]
    lib.loader_error.restype = ctypes.c_char_p
    lib.loader_error.argtypes = [ctypes.c_void_p]
    lib.loader_destroy.argtypes = [ctypes.c_void_p]

    lib.buddy_create.restype = ctypes.c_void_p
    lib.buddy_create.argtypes = [ctypes.c_uint64]
    lib.buddy_alloc.restype = ctypes.c_void_p
    lib.buddy_alloc.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.buddy_free.restype = ctypes.c_int
    lib.buddy_free.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.buddy_used.restype = ctypes.c_uint64
    lib.buddy_used.argtypes = [ctypes.c_void_p]
    lib.buddy_capacity.restype = ctypes.c_uint64
    lib.buddy_capacity.argtypes = [ctypes.c_void_p]
    lib.buddy_destroy.argtypes = [ctypes.c_void_p]

    _lib = lib
    return _lib


def available():
    return _load() is not None


def lib():
    l = _load()
    if l is None:
        raise RuntimeError(f"native library unavailable: {_lib_err}")
    return l


# ---------------------------------------------------------------- loader
class Loader:
    """Multithreaded prefetching reader over recordio files.

    Iterates raw record bytes; deterministic chunk-order shuffle when
    ``shuffle_seed >= 0``."""

    def __init__(self, paths, num_threads=4, queue_cap=4096,
                 shuffle_seed=-1):
        if isinstance(paths, (str, os.PathLike)):
            paths = [paths]
        self._lib = lib()
        arr = (ctypes.c_char_p * len(paths))(
            *[os.fspath(p).encode() for p in paths]
        )
        self._h = self._lib.loader_create(
            arr, len(paths), num_threads, queue_cap, shuffle_seed
        )
        if not self._h:
            raise IOError(f"loader_create failed for {paths}")

    def __iter__(self):
        n = ctypes.c_uint64()
        while True:
            p = self._lib.loader_next(self._h, ctypes.byref(n))
            if not p:
                err = self._lib.loader_error(self._h)
                if err:
                    raise IOError(f"loader: {err.decode()}")
                return
            yield ctypes.string_at(p, n.value)

    def next_batch(self, batch_size, prefix_bytes, payload_bytes,
                   prefix_dtype="uint8", payload_dtype="uint8"):
        """Assemble up to ``batch_size`` fixed-size records C-side (the
        batch-assembly mode): every record must be exactly
        ``prefix_bytes + payload_bytes``; prefixes (labels) and payloads
        (tensors) are memcpy'd contiguously into fresh numpy buffers —
        no per-record Python work.  Returns ``(prefix, payload)`` arrays
        of ``n`` rows (n < batch_size at end of stream), or ``None``
        when exhausted.  Raises on malformed records or IO errors."""
        import numpy as np

        prefix = np.empty((batch_size, prefix_bytes), np.uint8)
        payload = np.empty((batch_size, payload_bytes), np.uint8)
        n = self._lib.loader_next_batch(
            self._h, batch_size, prefix_bytes, payload_bytes,
            prefix.ctypes.data_as(ctypes.c_void_p),
            payload.ctypes.data_as(ctypes.c_void_p))
        if n < 0:
            err = self._lib.loader_error(self._h)
            raise IOError(f"loader batch: {err.decode() if err else '?'}")
        if n == 0:
            err = self._lib.loader_error(self._h)
            if err:
                raise IOError(f"loader: {err.decode()}")
            return None
        pre = prefix[:n]
        pay = payload[:n]
        if prefix_dtype != "uint8":
            pre = pre.view(prefix_dtype)
        if payload_dtype != "uint8":
            pay = pay.view(payload_dtype)
        if n < batch_size:
            # A partial batch may mean end-of-stream OR a worker died
            # mid-stream.  Surface a pending error NOW (callers often
            # treat a short batch as clean EOS and never call again),
            # but don't discard the n good records: they ride on the
            # exception as ``err.partial``.
            err = self._lib.loader_error(self._h)
            if err:
                e = IOError(f"loader: {err.decode()}")
                e.partial = (pre, pay)
                raise e
        return pre, pay

    def close(self):
        if self._h:
            self._lib.loader_destroy(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------- buddy allocator
class BuddyAllocator:
    """Power-of-two buddy arena over mmap'd host memory."""

    def __init__(self, arena_bytes=64 << 20):
        self._lib = lib()
        self._h = self._lib.buddy_create(arena_bytes)
        if not self._h:
            raise MemoryError("buddy_create failed")

    def alloc(self, n):
        p = self._lib.buddy_alloc(self._h, n)
        if not p:
            raise MemoryError(f"buddy arena exhausted allocating {n} bytes")
        return p

    def free(self, p):
        if self._lib.buddy_free(self._h, p) != 0:
            raise ValueError("bad pointer passed to buddy_free")

    @property
    def used(self):
        return self._lib.buddy_used(self._h)

    @property
    def capacity(self):
        return self._lib.buddy_capacity(self._h)

    def buffer(self, n):
        """A Python memoryview over a fresh allocation (for staging)."""
        p = self.alloc(n)
        return p, (ctypes.c_uint8 * n).from_address(p)

    def destroy(self):
        if self._h:
            self._lib.buddy_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.destroy()
        except Exception:
            pass
