"""paddle_tpu — a TPU-native deep-learning framework.

A ground-up rebuild of the capabilities of PaddlePaddle ~v0.11 (the Fluid
program-as-data core, the layer/op library, the v2 data pipeline, the
distributed pserver/master generation) designed TPU-first on JAX/XLA:

* A *Program* is still data (blocks of ops over named variables, mirroring
  the capability of ``framework.proto`` ProgramDesc — see reference
  ``paddle/framework/framework.proto:148``), but instead of a per-op C++
  interpreter (reference ``paddle/framework/executor.cc:79``) the Executor
  lowers a whole program to ONE pure function ``(state, feed) -> (state',
  fetches)`` and hands it to XLA via ``jax.jit``.  Everything fuses; there is
  no per-op dispatch at runtime.
* Autodiff is ``jax.grad`` over the traced forward prefix (the analog of
  ``append_backward`` / ``backward.cc:415 MakeBlockBackward``), surfaced
  through the same ``<param>@GRAD`` variable convention so optimizer ops,
  regularizers and clippers stay ordinary ops in the program.
* Variable-length sequences (the reference's LoD system,
  ``paddle/framework/lod_tensor.h``) are dense padded tensors + explicit
  length/segment metadata, with mask-aware sequence ops — the static-shape
  form XLA wants.
* Multi-device execution is a ``jax.sharding.Mesh`` + sharding annotations,
  replacing MultiGradientMachine ring merge, parallel_do and the NCCL ops
  with ICI collectives inserted by XLA.
"""

from . import core
from .core import (
    Program,
    Variable,
    Executor,
    Scope,
    global_scope,
    default_main_program,
    default_startup_program,
    program_guard,
    CPUPlace,
    TPUPlace,
    unique_name,
)
from . import initializer
from .param_attr import ParamAttr
from . import learning_rate_decay
from . import layers
from . import ops
from . import nets
from . import optimizer
from . import regularizer
from . import clip
from . import backward
from .backward import append_backward
from . import io
from . import evaluator
from . import metrics
from . import reader
from . import dataset
from . import data_feeder
from .data_feeder import DataFeeder
from . import parallel
from . import observability
from . import analysis
from . import tune
from . import resilience
from . import serving
from . import profiler
from . import trainer
from . import models
from . import inference
from . import distributed
from . import flags
from .flags import FLAGS
from . import memory_optimization_transpiler
from .memory_optimization_transpiler import (
    gradient_accumulation, memory_optimize, release_memory)
from . import checkgrad
from .checkgrad import check_gradients
from . import compat
from . import image
from . import net_drawer
from . import parameters
from . import plot
from . import native

__version__ = "0.1.0"
