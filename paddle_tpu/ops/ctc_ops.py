"""CTC loss — the warpctc replacement.

Reference: ``warpctc_op.cc`` dynamically loads Baidu's warp-ctc CUDA library
(``platform/dynload/warpctc``); gradient computed by the library.  TPU-native
form: the forward-backward recursion in log space as a ``lax.scan`` over
time; the gradient falls out of JAX AD through the scan (same asymptotics as
warpctc's analytic gradient, and XLA fuses the per-step algebra).  Inputs are
padded dense [b, T, V] logits + [b, L] labels with explicit lengths — the
reference's LoD packing is unnecessary.
"""

import jax
import jax.numpy as jnp

from ..core.registry import register_op

_NEG_INF = -1e30


def ctc_loss_dense(logits, logit_lengths, labels, label_lengths, blank=0):
    """Negative log-likelihood per batch row.

    logits [b, T, V] (unnormalized), labels [b, L] int32 (no blanks).
    Standard alpha recursion over the expanded label sequence
    (blank, l1, blank, l2, ..., blank) of length 2L+1.
    """
    b, t, v = logits.shape
    l = labels.shape[1]
    s = 2 * l + 1
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)

    # expanded sequence: even positions blank, odd positions labels
    exp_labels = jnp.full((b, s), blank, dtype=jnp.int32)
    exp_labels = exp_labels.at[:, 1::2].set(labels.astype(jnp.int32))
    # allow skip from s-2 to s when labels differ (standard CTC transition)
    prev2 = jnp.concatenate(
        [jnp.full((b, 2), -1, jnp.int32), exp_labels[:, :-2]], axis=1
    )
    can_skip = jnp.logical_and(
        jnp.arange(s)[None, :] % 2 == 1, exp_labels != prev2
    )

    alpha0 = jnp.full((b, s), _NEG_INF)
    alpha0 = alpha0.at[:, 0].set(logp[:, 0, blank])
    has_label = label_lengths > 0
    first_lbl = jnp.take_along_axis(
        logp[:, 0, :], exp_labels[:, 1:2], axis=1
    ).reshape(-1)
    alpha0 = alpha0.at[:, 1].set(jnp.where(has_label, first_lbl, _NEG_INF))

    def logaddexp3(a, b_, c):
        m = jnp.maximum(jnp.maximum(a, b_), c)
        m = jnp.maximum(m, _NEG_INF)
        return m + jnp.log(
            jnp.exp(a - m) + jnp.exp(b_ - m) + jnp.exp(c - m)
        )

    def step(alpha, tt):
        stay = alpha
        move1 = jnp.concatenate(
            [jnp.full((b, 1), _NEG_INF), alpha[:, :-1]], axis=1
        )
        move2 = jnp.concatenate(
            [jnp.full((b, 2), _NEG_INF), alpha[:, :-2]], axis=1
        )
        move2 = jnp.where(can_skip, move2, _NEG_INF)
        merged = logaddexp3(stay, move1, move2)
        emit = jnp.take_along_axis(logp[:, tt, :], exp_labels, axis=1)
        new_alpha = merged + emit
        # freeze rows whose time is exhausted
        active = (tt < logit_lengths)[:, None]
        return jnp.where(active, new_alpha, alpha), None

    alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, t))
    # final: sum of last two valid positions (label_len*2 and label_len*2-1)
    last = 2 * label_lengths.astype(jnp.int32)
    a_last = jnp.take_along_axis(alpha, last[:, None], axis=1).reshape(-1)
    a_prev = jnp.take_along_axis(
        alpha, jnp.maximum(last - 1, 0)[:, None], axis=1
    ).reshape(-1)
    a_prev = jnp.where(label_lengths > 0, a_prev, _NEG_INF)
    m = jnp.maximum(a_last, a_prev)
    ll = m + jnp.log(jnp.exp(a_last - m) + jnp.exp(a_prev - m))
    return -ll


@register_op("warpctc")
def warpctc(Logits, Label, LogitsLength=None, LabelLength=None,
            blank=0, norm_by_times=False, **_):
    b, t, v = Logits.shape
    logit_len = (
        LogitsLength.astype(jnp.int32)
        if LogitsLength is not None
        else jnp.full((b,), t, jnp.int32)
    )
    lbl = Label
    if lbl.ndim == 3 and lbl.shape[-1] == 1:
        lbl = lbl.reshape(lbl.shape[:-1])
    label_len = (
        LabelLength.astype(jnp.int32)
        if LabelLength is not None
        else jnp.full((b,), lbl.shape[1], jnp.int32)
    )
    loss = ctc_loss_dense(Logits, logit_len, lbl, label_len, blank)
    if norm_by_times:
        loss = loss / jnp.maximum(logit_len.astype(jnp.float32), 1.0)
    return {"Loss": loss[:, None].astype(Logits.dtype)}
