"""Optimizer update ops.

Reference: ``sgd_op, momentum_op, adam_op, adamax_op, adagrad_op, adadelta_op,
decayed_adagrad_op, rmsprop_op, ftrl_op, proximal_{gd,adagrad}_op`` — each a
standalone op so the same update rule can run trainer-side or pserver-side
(``recv_op.cc:100``).  Same shape here: pure functions (Param, Grad, state…)
-> (ParamOut, state…); the Executor routes ParamOut back into the persistable
state, giving XLA an in-place donated update."""

import jax.numpy as jnp

from ..core.registry import register_op


def _f32(x):
    return x.astype(jnp.float32)


@register_op("sgd")
def sgd(Param, Grad, LearningRate, **_):
    lr = _f32(LearningRate).reshape(())
    out = _f32(Param) - lr * _f32(Grad)
    return {"ParamOut": out.astype(Param.dtype)}


@register_op("momentum")
def momentum(Param, Grad, Velocity, LearningRate, mu=0.9, use_nesterov=False, **_):
    lr = _f32(LearningRate).reshape(())
    v = mu * _f32(Velocity) + _f32(Grad)
    if use_nesterov:
        p = _f32(Param) - (_f32(Grad) + mu * v) * lr
    else:
        p = _f32(Param) - lr * v
    return {"ParamOut": p.astype(Param.dtype), "VelocityOut": v.astype(Velocity.dtype)}


@register_op("adagrad")
def adagrad(Param, Grad, Moment, LearningRate, epsilon=1e-6, **_):
    lr = _f32(LearningRate).reshape(())
    g = _f32(Grad)
    m = _f32(Moment) + g * g
    p = _f32(Param) - lr * g / (jnp.sqrt(m) + epsilon)
    return {"ParamOut": p.astype(Param.dtype), "MomentOut": m.astype(Moment.dtype)}


@register_op("adam")
def adam(
    Param, Grad, Moment1, Moment2, LearningRate, Beta1Pow, Beta2Pow,
    beta1=0.9, beta2=0.999, epsilon=1e-8, **_,
):
    lr = _f32(LearningRate).reshape(())
    g = _f32(Grad)
    m1 = beta1 * _f32(Moment1) + (1 - beta1) * g
    m2 = beta2 * _f32(Moment2) + (1 - beta2) * g * g
    b1p = _f32(Beta1Pow).reshape(())
    b2p = _f32(Beta2Pow).reshape(())
    lr_t = lr * jnp.sqrt(1 - b2p * beta2) / (1 - b1p * beta1)
    p = _f32(Param) - lr_t * m1 / (jnp.sqrt(m2) + epsilon)
    return {
        "ParamOut": p.astype(Param.dtype),
        "Moment1Out": m1.astype(Moment1.dtype),
        "Moment2Out": m2.astype(Moment2.dtype),
        "Beta1PowOut": (b1p * beta1).reshape(Beta1Pow.shape).astype(Beta1Pow.dtype),
        "Beta2PowOut": (b2p * beta2).reshape(Beta2Pow.shape).astype(Beta2Pow.dtype),
    }


@register_op("adamax")
def adamax(
    Param, Grad, Moment, InfNorm, LearningRate, Beta1Pow,
    beta1=0.9, beta2=0.999, epsilon=1e-8, **_,
):
    lr = _f32(LearningRate).reshape(())
    g = _f32(Grad)
    m = beta1 * _f32(Moment) + (1 - beta1) * g
    u = jnp.maximum(beta2 * _f32(InfNorm), jnp.abs(g))
    b1p = _f32(Beta1Pow).reshape(()) * beta1
    p = _f32(Param) - (lr / (1 - b1p)) * m / (u + epsilon)
    return {
        "ParamOut": p.astype(Param.dtype),
        "MomentOut": m.astype(Moment.dtype),
        "InfNormOut": u.astype(InfNorm.dtype),
        "Beta1PowOut": b1p.reshape(Beta1Pow.shape).astype(Beta1Pow.dtype),
    }


@register_op("adadelta")
def adadelta(Param, Grad, AvgSquaredGrad, AvgSquaredUpdate, rho=0.95, epsilon=1e-6, **_):
    g = _f32(Grad)
    asg = rho * _f32(AvgSquaredGrad) + (1 - rho) * g * g
    update = -jnp.sqrt((_f32(AvgSquaredUpdate) + epsilon) / (asg + epsilon)) * g
    asu = rho * _f32(AvgSquaredUpdate) + (1 - rho) * update * update
    p = _f32(Param) + update
    return {
        "ParamOut": p.astype(Param.dtype),
        "AvgSquaredGradOut": asg.astype(AvgSquaredGrad.dtype),
        "AvgSquaredUpdateOut": asu.astype(AvgSquaredUpdate.dtype),
    }


@register_op("decayed_adagrad")
def decayed_adagrad(Param, Grad, Moment, LearningRate, decay=0.95, epsilon=1e-6, **_):
    lr = _f32(LearningRate).reshape(())
    g = _f32(Grad)
    m = decay * _f32(Moment) + (1 - decay) * g * g
    p = _f32(Param) - lr * g / (jnp.sqrt(m) + epsilon)
    return {"ParamOut": p.astype(Param.dtype), "MomentOut": m.astype(Moment.dtype)}


@register_op("rmsprop")
def rmsprop(Param, Grad, MeanSquare, Moment, LearningRate, epsilon=1e-10, decay=0.9, momentum=0.0, **_):
    lr = _f32(LearningRate).reshape(())
    g = _f32(Grad)
    ms = decay * _f32(MeanSquare) + (1 - decay) * g * g
    mom = momentum * _f32(Moment) + lr * g / jnp.sqrt(ms + epsilon)
    p = _f32(Param) - mom
    return {
        "ParamOut": p.astype(Param.dtype),
        "MeanSquareOut": ms.astype(MeanSquare.dtype),
        "MomentOut": mom.astype(Moment.dtype),
    }


@register_op("ftrl")
def ftrl(Param, Grad, SquaredAccumulator, LinearAccumulator, LearningRate,
         l1=0.0, l2=0.0, lr_power=-0.5, **_):
    lr = _f32(LearningRate).reshape(())
    g = _f32(Grad)
    sq = _f32(SquaredAccumulator)
    lin = _f32(LinearAccumulator)
    new_sq = sq + g * g
    if lr_power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq)) / lr
    else:
        sigma = (jnp.power(new_sq, -lr_power) - jnp.power(sq, -lr_power)) / lr
    new_lin = lin + g - sigma * _f32(Param)
    if lr_power == -0.5:
        denom = jnp.sqrt(new_sq) / lr + 2 * l2
    else:
        denom = jnp.power(new_sq, -lr_power) / lr + 2 * l2
    pre = jnp.clip(new_lin, -l1, l1) - new_lin
    p = pre / denom
    return {
        "ParamOut": p.astype(Param.dtype),
        "SquaredAccumOut": new_sq.astype(SquaredAccumulator.dtype),
        "LinearAccumOut": new_lin.astype(LinearAccumulator.dtype),
    }


@register_op("proximal_gd")
def proximal_gd(Param, Grad, LearningRate, l1=0.0, l2=0.0, **_):
    lr = _f32(LearningRate).reshape(())
    prox = _f32(Param) - lr * _f32(Grad)
    if l1 > 0:
        p = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0) / (1.0 + lr * l2)
    else:
        p = prox / (1.0 + lr * l2)
    return {"ParamOut": p.astype(Param.dtype)}


@register_op("proximal_adagrad")
def proximal_adagrad(Param, Grad, Moment, LearningRate, l1=0.0, l2=0.0, **_):
    g = _f32(Grad)
    m = _f32(Moment) + g * g
    lr = _f32(LearningRate).reshape(()) / jnp.sqrt(m)
    prox = _f32(Param) - lr * g
    if l1 > 0:
        p = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0) / (1.0 + lr * l2)
    else:
        p = prox / (1.0 + lr * l2)
    return {"ParamOut": p.astype(Param.dtype), "MomentOut": m.astype(Moment.dtype)}
