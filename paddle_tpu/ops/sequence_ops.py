"""Sequence ops — the TPU-native replacement for the reference LoD system.

The reference stores ragged batches as packed tensors + LoD offset vectors
(``lod_tensor.h:58``) and every sequence op walks offsets (e.g.
``sequence_pooling.cc``, ``hl_cuda_sequence.cu``).  XLA wants static shapes,
so here a "sequence batch" is a padded dense tensor ``[batch, max_len, ...]``
plus an int32 ``Length`` [batch] (the shadow ``<name>@LENGTH`` variable) and
ops are mask-aware.  No padding *waste* survives compilation where it
matters: masked lanes vectorize on the VPU, and bucketing in the DataFeeder
keeps max_len tight (SURVEY §5 long-context notes).
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op


def time_mask(Length, max_len, dtype=jnp.float32):
    """[batch, max_len] 1/0 mask from lengths."""
    return (jnp.arange(max_len)[None, :] < Length[:, None]).astype(dtype)


def _mask_for(X, Length):
    m = time_mask(Length, X.shape[1], X.dtype)
    return m.reshape(m.shape + (1,) * (X.ndim - 2))


@register_op("sequence_pool")
def sequence_pool(X, Length=None, pooltype="SUM", **_):
    b, t = X.shape[0], X.shape[1]
    if Length is None:
        Length = jnp.full((b,), t, dtype=jnp.int32)
    m = _mask_for(X, Length)
    lens = Length.astype(jnp.float32).reshape((b,) + (1,) * (X.ndim - 2))
    pt = pooltype.upper()
    if pt == "SUM":
        out = jnp.sum(X * m, axis=1)
    elif pt == "AVERAGE":
        out = jnp.sum(X * m, axis=1) / jnp.maximum(lens, 1.0)
    elif pt == "SQRT":
        out = jnp.sum(X * m, axis=1) / jnp.sqrt(jnp.maximum(lens, 1.0))
    elif pt == "MAX":
        neg = jnp.asarray(-1e38, X.dtype)
        out = jnp.max(jnp.where(m > 0, X, neg), axis=1)
    elif pt == "LAST":
        idx = jnp.maximum(Length - 1, 0).astype(jnp.int32)
        out = jnp.take_along_axis(
            X, idx.reshape((b, 1) + (1,) * (X.ndim - 2)), axis=1
        ).squeeze(1)
    elif pt == "FIRST":
        out = X[:, 0]
    else:
        raise ValueError(f"unknown pooltype {pooltype}")
    return {"Out": out}


@register_op("sequence_softmax")
def sequence_softmax(X, Length=None, **_):
    # X: [batch, max_len] (scores per timestep)
    if Length is None:
        return {"Out": jax.nn.softmax(X, axis=1)}
    m = time_mask(Length, X.shape[1], jnp.bool_)
    neg = jnp.asarray(-1e38, X.dtype)
    sm = jax.nn.softmax(jnp.where(m, X, neg), axis=1)
    return {"Out": jnp.where(m, sm, 0.0)}


@register_op("sequence_conv")
def sequence_conv(X, Filter, Length=None, contextLength=3, contextStart=None, **_):
    """Context-window projection (sequence_conv_op + math/context_project).
    X [b,t,d], Filter [contextLength*d, out]; rows outside the sequence are
    zero (reference pads with zeros unless a padding-trainable matrix is
    given)."""
    b, t, d = X.shape
    start = contextStart if contextStart is not None else -((contextLength - 1) // 2)
    if Length is not None:
        X = X * _mask_for(X, Length)
    cols = []
    for i in range(contextLength):
        off = start + i
        shifted = jnp.roll(X, -off, axis=1)
        if off > 0:
            mask = (jnp.arange(t) < t - off)[None, :, None]
        elif off < 0:
            mask = (jnp.arange(t) >= -off)[None, :, None]
        else:
            mask = None
        cols.append(jnp.where(mask, shifted, 0.0) if mask is not None else shifted)
    ctx = jnp.concatenate(cols, axis=-1)  # [b,t,ctx*d]
    out = jnp.einsum("btc,co->bto", ctx, Filter.astype(X.dtype))
    if Length is not None:
        out = out * _mask_for(out, Length)
    return {"Out": out}


@register_op("sequence_concat")
def sequence_concat(X, Length=None, axis=1, **_):
    """Concatenate sequences per batch item along time (axis=1 semantics of
    reference's level-0 concat): result lengths add."""
    xs = X if isinstance(X, (list, tuple)) else [X]
    lens = Length if isinstance(Length, (list, tuple)) else ([Length] if Length is not None else None)
    if axis != 1 or lens is None:
        return {"Out": jnp.concatenate(xs, axis=axis)}
    b = xs[0].shape[0]
    total = sum(x.shape[1] for x in xs)
    feat = xs[0].shape[2:]
    out = jnp.zeros((b, total) + feat, xs[0].dtype)
    out_len = jnp.zeros((b,), jnp.int32)
    pos = jnp.zeros((b,), jnp.int32)
    for x, ln in zip(xs, lens):
        t = x.shape[1]
        idx = pos[:, None] + jnp.arange(t)[None, :]
        valid = time_mask(ln, t, jnp.bool_)
        idx = jnp.where(valid, idx, total)  # out-of-range drops
        outpad = jnp.concatenate([out, jnp.zeros((b, 1) + feat, out.dtype)], axis=1)
        bidx = jnp.arange(b)[:, None].repeat(t, 1)
        outpad = outpad.at[bidx, idx].set(jnp.where(valid.reshape(valid.shape + (1,) * len(feat)), x, outpad[bidx, idx]))
        out = outpad[:, :total]
        pos = pos + ln.astype(jnp.int32)
        out_len = out_len + ln.astype(jnp.int32)
    return {"Out": out, "OutLength": out_len}


@register_op("sequence_expand")
def sequence_expand(X, Y=None, YLength=None, **_):
    """Reference sequence_expand_op: broadcast each batch item's vector
    across its target sequence's timesteps.  X [b, d] (or [b,1,d]),
    out [b, max_len_y, d] masked by YLength."""
    if Y is None:
        # YLength is a tracer under jit, so the time dim cannot come from it
        raise ValueError("sequence_expand requires the Y input (its static "
                         "max_len defines the output time dimension)")
    x = X if X.ndim == 3 else X[:, None, :]
    t = Y.shape[1]
    out = jnp.broadcast_to(x, (x.shape[0], t) + x.shape[2:])
    if YLength is not None:
        out = out * _mask_for(out, YLength)
    return {"Out": out}


@register_op("sequence_slice")
def sequence_slice(X, Offset, SeqLength, **_):
    """Per-sequence slice (sequence_slice_op.cc): take [offset, offset+len)
    from each row; output stays padded to X's max_len."""
    b, t = X.shape[0], X.shape[1]
    off = Offset.reshape(-1).astype(jnp.int32)
    ln = SeqLength.reshape(-1).astype(jnp.int32)
    idx = off[:, None] + jnp.arange(t)[None, :]
    idx = jnp.clip(idx, 0, t - 1)
    out = jnp.take_along_axis(X, idx.reshape((b, t) + (1,) * (X.ndim - 2)), axis=1)
    out = out * _mask_for(out, ln)
    return {"Out": out, "OutLength": ln}


@register_op("sequence_reverse")
def sequence_reverse(X, Length=None, **_):
    """Length-aware per-sequence reversal (the v1 ``reverse=`` group
    support; reference semantics: RecurrentGradientMachine reversed
    groups, ``trainer_config_helpers/layers.py:347``):
    ``out[b, t] = x[b, len_b - 1 - t]`` for ``t < len_b``, padding stays
    in place — so right-padded layouts remain right-padded and masking
    conventions survive a round trip."""
    b, t = X.shape[0], X.shape[1]
    if Length is None:
        return {"Out": X[:, ::-1]}
    ln = Length.reshape(-1, 1).astype(jnp.int32)
    idx = jnp.arange(t)[None, :]
    ridx = jnp.where(idx < ln, ln - 1 - idx, idx)
    out = jnp.take_along_axis(
        X, ridx.reshape((b, t) + (1,) * (X.ndim - 2)), axis=1)
    return {"Out": out}


@register_op("sequence_erase", nondiff=True)
def sequence_erase(X, Length=None, tokens=(), **_):
    """Remove given token ids, compacting each sequence left
    (sequence_erase_op.cc).  X int [b, t]."""
    b, t = X.shape
    keep = jnp.ones_like(X, dtype=jnp.bool_)
    for tok in tokens:
        keep = jnp.logical_and(keep, X != tok)
    if Length is not None:
        keep = jnp.logical_and(keep, time_mask(Length, t, jnp.bool_))
    # stable compaction: sort by (not keep) preserving order
    order = jnp.argsort(jnp.where(keep, 0, 1) * t + jnp.arange(t)[None, :], axis=1)
    gathered = jnp.take_along_axis(X, order, axis=1)
    new_len = jnp.sum(keep, axis=1).astype(jnp.int32)
    out = jnp.where(time_mask(new_len, t, jnp.bool_), gathered, 0)
    return {"Out": out, "OutLength": new_len}


@register_op("sequence_reshape")
def sequence_reshape(X, Length=None, new_dim=0, **_):
    b, t, d = X.shape
    factor = d / new_dim
    new_t = int(t * d // new_dim)
    out = X.reshape(b, new_t, new_dim)
    new_len = None
    if Length is not None:
        new_len = (Length.astype(jnp.float32) * factor).astype(jnp.int32)
    return {"Out": out, "OutLength": new_len if new_len is not None else jnp.full((b,), new_t, jnp.int32)}


@register_op("sequence_scale")
def sequence_scale(X, Scales, Length=None, **_):
    """Per-sequence scaling (math/sequence_scale, used by warpctc grad)."""
    out = X * Scales.reshape((-1,) + (1,) * (X.ndim - 1))
    return {"Out": out}


@register_op("edit_distance", nondiff=True)
def edit_distance(Hyps, Refs, HypsLength=None, RefsLength=None, normalized=False, **_):
    """Levenshtein distance per batch row (edit_distance_op.cc).  Hyps/Refs
    int [b, t]; computed with a lax.scan DP over the reference axis."""
    b, th = Hyps.shape
    tr = Refs.shape[1]
    hlen = HypsLength if HypsLength is not None else jnp.full((b,), th, jnp.int32)
    rlen = RefsLength if RefsLength is not None else jnp.full((b,), tr, jnp.int32)

    def per_row(hyp, ref, hl, rl):
        # dp over prefix lengths; row i = distance(hyp[:i], ref[:j]) rolled by scan over i
        init = jnp.arange(tr + 1, dtype=jnp.int32)  # distance(empty, ref[:j])
        # clamp to rl: positions beyond rl should mirror rl (we mask at the end)
        def step(prev_row, i):
            ins = prev_row[0] + 1  # j=0 column: distance(hyp[:i+1], empty)

            def inner(carry, j):
                left = carry  # dp[i+1][j]
                sub_cost = jnp.where(hyp[i] == ref[j], 0, 1)
                val = jnp.minimum(
                    jnp.minimum(prev_row[j + 1] + 1, left + 1),
                    prev_row[j] + sub_cost,
                )
                # beyond valid hyp length, copy previous row (no-op)
                val = jnp.where(i < hl, val, prev_row[j + 1])
                return val, val

            _, rest = jax.lax.scan(inner, jnp.where(i < hl, ins, prev_row[0]), jnp.arange(tr))
            first = jnp.where(i < hl, ins, prev_row[0])
            row = jnp.concatenate([first[None], rest])
            return row, None

        final, _ = jax.lax.scan(step, init, jnp.arange(th))
        return final[rl]

    dist = jax.vmap(per_row)(Hyps, Refs, hlen, rlen).astype(jnp.float32)
    if normalized:
        dist = dist / jnp.maximum(rlen.astype(jnp.float32), 1.0)
    return {"Out": dist[:, None], "SequenceNum": jnp.asarray([b], jnp.int32)}


@register_op("ctc_align", nondiff=True)
def ctc_align(Input, Length=None, blank=0, merge_repeated=True, **_):
    """CTC greedy decode alignment (ctc_align_op.cc): collapse repeats then
    remove blanks.  Input int [b, t] of argmax ids."""
    b, t = Input.shape
    x = Input
    if merge_repeated:
        prev = jnp.concatenate([jnp.full((b, 1), -1, x.dtype), x[:, :-1]], axis=1)
        keep = x != prev
    else:
        keep = jnp.ones_like(x, dtype=jnp.bool_)
    keep = jnp.logical_and(keep, x != blank)
    if Length is not None:
        keep = jnp.logical_and(keep, time_mask(Length, t, jnp.bool_))
    order = jnp.argsort(jnp.where(keep, 0, 1) * t + jnp.arange(t)[None, :], axis=1)
    gathered = jnp.take_along_axis(x, order, axis=1)
    new_len = jnp.sum(keep, axis=1).astype(jnp.int32)
    out = jnp.where(time_mask(new_len, t, jnp.bool_), gathered, 0)
    return {"Output": out, "OutputLength": new_len}


# -- 2-level (nested) sequences ----------------------------------------------
# The reference's sequence type is recursively nested (lod_tensor.h:58:
# LoD = vector of levels; Argument.subSequenceStartPositions, Argument.h:84).
# TPU-native form: [b, s, t, ...] padded dense + Length [b] (sub-seqs per
# sample) + SubLength [b, s] (items per sub-seq).


def _nested_masks(X, Length, SubLength):
    b, s, t = X.shape[0], X.shape[1], X.shape[2]
    if Length is None:
        Length = jnp.full((b,), s, jnp.int32)
    if SubLength is None:
        SubLength = jnp.full((b, s), t, jnp.int32)
    outer = (jnp.arange(s)[None, :] < Length[:, None])            # [b, s]
    inner = (jnp.arange(t)[None, None, :] < SubLength[:, :, None])  # [b,s,t]
    inner = inner & outer[:, :, None]
    return Length, SubLength, outer, inner


@register_op("nested_sequence_pool")
def nested_sequence_pool(X, Length=None, SubLength=None, pooltype="SUM", **_):
    """Pool the INNER level of a nested batch: [b, s, t, ...] ->
    [b, s, ...] (a 1-level sequence whose lengths are the outer Length).
    The per-sub-seq semantics match sequence_pool (reference
    SequencePoolLayer at the sub-sequence level /
    sequence_pool with lod_level 2)."""
    Length, SubLength, outer, inner = _nested_masks(X, Length, SubLength)
    b, s, t = X.shape[:3]
    m = inner.astype(X.dtype).reshape(inner.shape + (1,) * (X.ndim - 3))
    # outer-padded slots count as EMPTY sub-seqs even when SubLength was
    # defaulted (MAX's lens>0 guard must zero them like every pooltype)
    SubLength = jnp.where(outer, SubLength, 0)
    lens = SubLength.astype(jnp.float32).reshape(
        (b, s) + (1,) * (X.ndim - 3))
    pt = pooltype.upper()
    if pt == "SUM":
        out = jnp.sum(X * m, axis=2)
    elif pt == "AVERAGE":
        out = jnp.sum(X * m, axis=2) / jnp.maximum(lens, 1.0)
    elif pt == "SQRT":
        out = jnp.sum(X * m, axis=2) / jnp.sqrt(jnp.maximum(lens, 1.0))
    elif pt == "MAX":
        neg = jnp.asarray(-1e38, X.dtype)
        out = jnp.max(jnp.where(m > 0, X, neg), axis=2)
        out = jnp.where(lens > 0, out, jnp.zeros_like(out))
    elif pt == "LAST":
        idx = jnp.maximum(SubLength - 1, 0).astype(jnp.int32)
        out = jnp.take_along_axis(
            X, idx.reshape((b, s, 1) + (1,) * (X.ndim - 3)), axis=2
        ).squeeze(2)
        out = out * outer.astype(X.dtype).reshape(
            (b, s) + (1,) * (X.ndim - 3))
    elif pt == "FIRST":
        out = X[:, :, 0] * outer.astype(X.dtype).reshape(
            (b, s) + (1,) * (X.ndim - 3))
    else:
        raise ValueError(f"unknown pooltype {pooltype}")
    return {"Out": out}


@register_op("nested_sequence_expand")
def nested_sequence_expand(X, Y, Length=None, SubLength=None, **_):
    """Expand a per-sub-seq tensor [b, s, ...] over Y's inner level:
    out[b, s, t] = X[b, s] for t < SubLength[b, s], else 0 (the
    sub-sequence-level SequenceExpandLayer)."""
    _, _, _, inner = _nested_masks(Y, Length, SubLength)
    t = Y.shape[2]
    out = jnp.broadcast_to(
        X[:, :, None], X.shape[:2] + (t,) + X.shape[2:])
    m = inner.astype(X.dtype).reshape(inner.shape + (1,) * (X.ndim - 2))
    return {"Out": out * m}


@register_op("nested_sequence_slice")
def nested_sequence_slice(X, Offset, Size, Length=None, SubLength=None, **_):
    """Per-sample sub-sequence range selection: sample b keeps sub-seqs
    [Offset[b], Offset[b]+Size[b]) — nested analog of sequence_slice
    (reference SequenceSliceLayer on the outer level).  Output stays
    [b, s, t, ...] with OutLength=Size and sub-lengths gathered."""
    b, s = X.shape[:2]
    Offset = Offset.reshape(b).astype(jnp.int32)
    Size = Size.reshape(b).astype(jnp.int32)
    pos = jnp.arange(s)[None, :] + Offset[:, None]       # [b, s]
    # a slot is valid only when inside the requested range AND the
    # sample's REAL sub-sequence count (out-of-range requests yield
    # fewer sub-seqs, never a silently duplicated clamp or phantom
    # padded slots — the reference SequenceSliceLayer bounds-checks)
    if Length is None:
        Length = jnp.full((b,), s, jnp.int32)
    valid = ((jnp.arange(s)[None, :] < Size[:, None])
             & (pos < Length[:, None]))
    pos = jnp.where(valid, pos, 0)
    idx = pos.reshape((b, s) + (1,) * (X.ndim - 2))
    out = jnp.take_along_axis(X, jnp.broadcast_to(idx, (b, s) + X.shape[2:]),
                              axis=1)
    vm = valid.reshape((b, s) + (1,) * (X.ndim - 2)).astype(X.dtype)
    out = out * vm
    _, SubLength, _, _ = _nested_masks(X, Length, SubLength)
    sub = jnp.take_along_axis(SubLength, pos, axis=1) * valid
    return {"Out": out,
            "OutLength": jnp.sum(valid, axis=1).astype(jnp.int32),
            "OutSubLength": sub.astype(jnp.int32)}


@register_op("sub_nested_seq")
def sub_nested_seq(X, Indices, Length=None, SubLength=None, **_):
    """Select sub-sequences by per-sample indices (reference
    SubNestedSequenceLayer.cpp): Indices [b, k] picks sentences; negative
    indices are padding and produce empty sub-seqs.  Output
    [b, k, t, ...] + OutLength [b] (count of valid picks) +
    OutSubLength [b, k]."""
    b, s = X.shape[:2]
    k = Indices.shape[1]
    idx = Indices.astype(jnp.int32)
    if Length is None:
        Length = jnp.full((b,), s, jnp.int32)
    # bounds-check like the reference SubNestedSequenceLayer: an index
    # outside the sample's real sub-sequence count is padding, not data
    valid = (idx >= 0) & (idx < Length[:, None])
    safe = jnp.where(valid, idx, 0)
    gi = safe.reshape((b, k) + (1,) * (X.ndim - 2))
    out = jnp.take_along_axis(
        X, jnp.broadcast_to(gi, (b, k) + X.shape[2:]), axis=1)
    vm = valid.reshape((b, k) + (1,) * (X.ndim - 2)).astype(X.dtype)
    out = out * vm
    _, SubLength, _, _ = _nested_masks(X, Length, SubLength)
    sub = jnp.take_along_axis(SubLength, safe, axis=1) * valid
    return {"Out": out,
            "OutLength": jnp.sum(valid, axis=1).astype(jnp.int32),
            "OutSubLength": sub.astype(jnp.int32)}
