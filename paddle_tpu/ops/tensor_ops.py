"""Tensor manipulation & fill ops (reference "Data/misc" group, SURVEY §2.2):
fill/assign/reshape/transpose/split/concat/expand/gather/scatter/pad/crop/
multiplex/increment/lookup_table …"""

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op
from ..core.dtypes import convert_dtype


@register_op("fill_constant")
def fill_constant(shape=(), dtype="float32", value=0.0, **_):
    return {"Out": jnp.full(tuple(shape), value, dtype=convert_dtype(dtype))}


@register_op("fill_constant_batch_size_like")
def fill_constant_batch_size_like(
    Input, shape=(), dtype="float32", value=0.0, input_dim_idx=0, output_dim_idx=0, **_
):
    shape = list(shape)
    shape[output_dim_idx] = Input.shape[input_dim_idx]
    return {"Out": jnp.full(tuple(shape), value, dtype=convert_dtype(dtype))}


@register_op("fill_zeros_like")
def fill_zeros_like(X, **_):
    return {"Out": jnp.zeros_like(X)}


@register_op("assign")
def assign(X, **_):
    return {"Out": X}


@register_op("assign_value")
def assign_value(shape=(), dtype="float32", values=(), **_):
    arr = np.asarray(values, dtype=convert_dtype(dtype)).reshape(tuple(shape))
    return {"Out": jnp.asarray(arr)}


@register_op("shape")
def shape_op(Input, **_):
    return {"Out": jnp.asarray(Input.shape, dtype=jnp.int32)}


@register_op("reshape")
def reshape(X, shape=(), **_):
    # Paddle convention: 0 means "copy this dim from the input".
    shape = [int(X.shape[i]) if int(s) == 0 else int(s)
             for i, s in enumerate(shape)]
    return {"Out": X.reshape(tuple(shape))}


@register_op("transpose")
def transpose(X, axis=(), **_):
    return {"Out": jnp.transpose(X, tuple(axis))}


@register_op("split")
def split(X, num=0, sections=(), axis=0, **_):
    if sections:
        idx = np.cumsum(sections)[:-1].tolist()
        outs = jnp.split(X, idx, axis=axis)
    else:
        outs = jnp.split(X, num, axis=axis)
    return {"Out": list(outs)}


@register_op("concat")
def concat(X, axis=0, **_):
    xs = X if isinstance(X, (list, tuple)) else [X]
    return {"Out": jnp.concatenate(xs, axis=axis)}


@register_op("expand")
def expand(X, expand_times=(), **_):
    return {"Out": jnp.tile(X, tuple(expand_times))}


@register_op("gather")
def gather(X, Index, **_):
    return {"Out": jnp.take(X, Index.astype(jnp.int32), axis=0)}


@register_op("scatter")
def scatter(X, Ids, Updates, overwrite=True, **_):
    ids = Ids.astype(jnp.int32)
    if overwrite:
        return {"Out": X.at[ids].set(Updates)}
    return {"Out": X.at[ids].add(Updates)}


@register_op("pad")
def pad(X, paddings=(), pad_value=0.0, **_):
    pads = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(X.ndim)]
    return {"Out": jnp.pad(X, pads, constant_values=pad_value)}


@register_op("crop")
def crop(X, Y=None, offsets=(), shape=(), **_):
    tgt = Y.shape if Y is not None else tuple(shape)
    off = list(offsets) if offsets else [0] * X.ndim
    # -1 extends to the end of the dim (build-time-unknown batch axes)
    tgt = tuple(X.shape[i] - off[i] if s == -1 else s
                for i, s in enumerate(tgt))
    slices = tuple(slice(o, o + s) for o, s in zip(off, tgt))
    return {"Out": X[slices]}


@register_op("multiplex")
def multiplex(Ids, X, **_):
    xs = jnp.stack(X if isinstance(X, (list, tuple)) else [X], axis=0)
    ids = Ids.reshape(-1).astype(jnp.int32)
    rows = jnp.arange(xs.shape[1])
    return {"Out": xs[ids, rows]}


@register_op("increment")
def increment(X, step=1.0, **_):
    return {"Out": X + jnp.asarray(step, dtype=X.dtype)}


@register_op("one_hot")
def one_hot(X, depth=0, **_):
    ids = X.reshape(X.shape[:-1]) if X.shape and X.shape[-1] == 1 else X
    return {"Out": jax.nn.one_hot(ids.astype(jnp.int32), depth)}


@register_op("lookup_table")
def lookup_table(W, Ids, padding_idx=-1, is_sparse=False, **_):
    """Embedding lookup (reference lookup_table_op.cc).  Ids may be [...,1]
    (fluid convention).  ``is_sparse`` is advisory here: gradients flow as
    dense arrays single-host; the distributed embedding service (parallel/
    sparse) row-shards instead — SelectedRows' job (selected_rows.h)."""
    ids = Ids
    if ids.shape and ids.shape[-1] == 1:
        ids = ids.reshape(ids.shape[:-1])
    ids = ids.astype(jnp.int32)
    out = jnp.take(W, ids, axis=0)
    if padding_idx is not None and padding_idx >= 0:
        mask = (ids != padding_idx)[..., None]
        out = jnp.where(mask, out, jnp.zeros_like(out))
    return {"Out": out}


@register_op("sparse_fc")
def sparse_fc(Ids, Vals, W, **_):
    """Weighted gather-sum over a sparse input slot: ``Out[..., :] =
    sum_n Vals[..., n] * W[Ids[..., n], :]`` — the TPU lowering of the
    reference's fc-over-sparse-Argument matmul (sparse row vector times
    dense matrix, math/SparseMatrix.cpp).  Ids are 0-padded, Vals
    0.0-padded, so padding contributes exactly zero; duplicate ids sum.
    Cost is O(nnz * size); nothing of height ``dim`` is touched beyond
    the gathered rows, and the backward is a scatter-add of outer
    products (the SelectedRows gradient, compressed for the DCN path by
    parallel/sparse.sparse_rows_from_grad)."""
    ids = Ids.astype(jnp.int32)
    rows = jnp.take(W, ids, axis=0)  # [..., n, size]
    out = jnp.sum(rows * Vals[..., None].astype(W.dtype), axis=-2)
    return {"Out": out}


@register_op("embedding_grad_rows")
def embedding_grad_rows(Grad, Ids, table_height=0, **_):
    """Helper exposing the SelectedRows idea: scatter-add token grads into a
    dense table of zeros (used by the sparse pserver path's tests)."""
    ids = Ids.reshape(-1).astype(jnp.int32)
    g = Grad.reshape((ids.shape[0], -1))
    table = jnp.zeros((table_height, g.shape[1]), dtype=Grad.dtype)
    return {"Out": table.at[ids].add(g)}


@register_op("top_k")
def top_k(X, k=1, **_):
    vals, idx = jax.lax.top_k(X, k)
    return {"Out": vals, "Indices": idx.astype(jnp.int32)}


@register_op("arg_max", nondiff=True)
def arg_max(X, axis=-1, **_):
    return {"Out": jnp.argmax(X, axis=axis).astype(jnp.int32)}


@register_op("arg_min", nondiff=True)
def arg_min(X, axis=-1, **_):
    return {"Out": jnp.argmin(X, axis=axis).astype(jnp.int32)}


@register_op("is_empty", nondiff=True)
def is_empty(X, **_):
    return {"Out": jnp.asarray(int(np.prod(X.shape)) == 0)}


@register_op("isfinite", nondiff=True)
def isfinite(X, **_):
    xs = X if isinstance(X, (list, tuple)) else [X]
    ok = jnp.asarray(True)
    for x in xs:
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(x.astype(jnp.float32))))
    return {"Out": ok}


@register_op("scale_sub_region")
def scale_sub_region(X, Indices, value=1.0, **_):
    """Scale the values inside a per-sample sub-region of a [N, C, H, W]
    feature map (reference ``paddle/gserver/layers/ScaleSubRegionLayer.cpp:1``).
    Indices [N, 6] int = (c1, c2, h1, h2, w1, w2), 1-based inclusive like
    the reference config."""
    n, c, h, w = X.shape
    idx = Indices.astype(jnp.int32)

    def axis_mask(lo, hi, dim):
        r = jnp.arange(dim)[None, :]
        return jnp.logical_and(r >= lo[:, None] - 1, r <= hi[:, None] - 1)

    mc = axis_mask(idx[:, 0], idx[:, 1], c)[:, :, None, None]
    mh = axis_mask(idx[:, 2], idx[:, 3], h)[:, None, :, None]
    mw = axis_mask(idx[:, 4], idx[:, 5], w)[:, None, None, :]
    region = jnp.logical_and(jnp.logical_and(mc, mh), mw)
    return {"Out": jnp.where(region, X * value, X)}
