"""GEMM / elementwise / reduction ops.

Reference groups (SURVEY §2.2): ``mul_op``, ``matmul_op`` (cuBLAS via
``operators/math/math_function``), ``elementwise_*_op`` with the axis
broadcast rule (``elementwise_op_function.h``), ``reduce_op``, ``sum_op``,
``scale/sign/clip/cast/minus`` etc.  All become single jnp/lax calls that XLA
maps straight onto the MXU (dots) and VPU (elementwise) — matmuls
accumulate in float32 via ``preferred_element_type`` so bfloat16 inputs keep
MXU-native speed without losing accumulation precision.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op
from ..core.dtypes import convert_dtype


def _acc_type(x):
    if x.dtype in (jnp.bfloat16, jnp.float16):
        return jnp.float32
    return None


def _broadcast_y(X, Y, axis):
    """Reference broadcast rule (elementwise_op_function.h): Y's dims align
    with X's dims starting at ``axis`` (default -1 = align trailing)."""
    if Y.ndim == 0 or X.shape == Y.shape:
        return Y
    ax = axis if axis >= 0 else X.ndim - Y.ndim
    # trim trailing size-1 dims like the reference does
    yshape = list(Y.shape)
    while yshape and yshape[-1] == 1 and len(yshape) > X.ndim - ax:
        yshape.pop()
    newshape = [1] * X.ndim
    newshape[ax : ax + len(yshape)] = yshape
    return Y.reshape(newshape)


def _register_elementwise(name, fn):
    @register_op("elementwise_" + name)
    def _op(X, Y, axis=-1, **_):
        return {"Out": fn(X, _broadcast_y(X, Y, axis))}

    _op.__name__ = "elementwise_" + name
    return _op


_register_elementwise("add", jnp.add)
_register_elementwise("sub", jnp.subtract)
_register_elementwise("mul", jnp.multiply)
_register_elementwise("div", jnp.divide)
_register_elementwise("max", jnp.maximum)
_register_elementwise("min", jnp.minimum)
_register_elementwise("pow", jnp.power)


@register_op("mul")
def mul(X, Y, x_num_col_dims=1, y_num_col_dims=1, **_):
    """Flattening matmul (reference mul_op.cc): X collapses to 2-D at
    x_num_col_dims, Y at y_num_col_dims; result regains X's leading dims."""
    x2 = X.reshape((int(np.prod(X.shape[:x_num_col_dims])), -1))
    y2 = Y.reshape((int(np.prod(Y.shape[:y_num_col_dims])), -1))
    out = jnp.dot(x2, y2, preferred_element_type=_acc_type(X))
    if out.dtype != X.dtype:
        out = out.astype(X.dtype)
    out_shape = X.shape[:x_num_col_dims] + Y.shape[y_num_col_dims:]
    return {"Out": out.reshape(out_shape)}


@register_op("matmul")
def matmul(X, Y, transpose_X=False, transpose_Y=False, alpha=1.0, **_):
    x = jnp.swapaxes(X, -1, -2) if transpose_X and X.ndim >= 2 else X
    y = jnp.swapaxes(Y, -1, -2) if transpose_Y and Y.ndim >= 2 else Y
    out = jnp.matmul(x, y, preferred_element_type=_acc_type(x))
    if out.dtype != X.dtype:
        out = out.astype(X.dtype)
    if alpha != 1.0:
        out = out * alpha
    return {"Out": out}


@register_op("sum")
def sum_op(X, **_):
    xs = X if isinstance(X, (list, tuple)) else [X]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": out}


@register_op("scale")
def scale(X, scale=1.0, bias=0.0, bias_after_scale=True, **_):
    if bias_after_scale:
        return {"Out": X * scale + bias}
    return {"Out": (X + bias) * scale}


@register_op("minus")
def minus(X, Y, **_):
    return {"Out": X - Y}


@register_op("sign")
def sign(X, **_):
    return {"Out": jnp.sign(X)}


@register_op("clip")
def clip(X, min=-1.0, max=1.0, **_):
    return {"Out": jnp.clip(X, min, max)}


@register_op("clip_by_norm")
def clip_by_norm(X, max_norm=1.0, **_):
    norm = jnp.sqrt(jnp.sum(jnp.square(X.astype(jnp.float32))))
    factor = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12), 1.0)
    return {"Out": (X * factor.astype(X.dtype))}


@register_op("cast")
def cast(X, out_dtype="float32", **_):
    return {"Out": X.astype(convert_dtype(out_dtype))}


def _reduce(fn, X, dim, keep_dim, reduce_all):
    if reduce_all or dim is None:
        axis = None
    else:
        axis = tuple(dim) if isinstance(dim, (list, tuple)) else (dim,)
    return fn(X, axis=axis, keepdims=keep_dim)


def _register_reduce(name, fn):
    @register_op("reduce_" + name)
    def _op(X, dim=None, keep_dim=False, reduce_all=False, **_):
        return {"Out": _reduce(fn, X, dim, keep_dim, reduce_all)}

    return _op


_register_reduce("sum", jnp.sum)
_register_reduce("mean", jnp.mean)
_register_reduce("max", jnp.max)
_register_reduce("min", jnp.min)
_register_reduce("prod", jnp.prod)


@register_op("mean")
def mean(X, **_):
    return {"Out": jnp.mean(X).reshape(1)}


@register_op("squared_l2_norm")
def squared_l2_norm(X, **_):
    return {"Out": jnp.sum(jnp.square(X)).reshape(1)}


@register_op("l1_norm")
def l1_norm(X, **_):
    # reference l1_norm_op.h: Out = sum(|X|)
    return {"Out": jnp.sum(jnp.abs(X)).reshape(1)}


@register_op("bilinear_tensor_product")
def bilinear_tensor_product(X, Y, Weight, Bias=None, **_):
    # reference bilinear_tensor_product_op.h:30: out[b,i] =
    # x[b,:] @ W[i,:,:] @ y[b,:] (+ bias[i]); one einsum on the MXU
    # replaces the per-output-channel gemm loop.
    out = jnp.einsum("bj,ijk,bk->bi", X, Weight.astype(X.dtype), Y)
    if Bias is not None:
        out = out + Bias.astype(X.dtype)
    return {"Out": out}


@register_op("squared_l2_distance")
def squared_l2_distance(X, Y, **_):
    d = X - _broadcast_y(X, Y, -1)
    sub = d.reshape((d.shape[0], -1))
    return {"sub_result": sub, "Out": jnp.sum(jnp.square(sub), axis=1, keepdims=True)}


@register_op("cos_sim")
def cos_sim(X, Y, **_):
    # Y may have batch 1 (broadcast against all rows of X), cos_sim_op.cc
    if Y.shape[0] == 1 and X.shape[0] != 1:
        Y = jnp.broadcast_to(Y, X.shape)
    xn = jnp.sqrt(jnp.sum(jnp.square(X), axis=1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(Y), axis=1, keepdims=True))
    out = jnp.sum(X * Y, axis=1, keepdims=True) / jnp.maximum(xn * yn, 1e-12)
    return {"Out": out, "XNorm": xn, "YNorm": yn}


@register_op("dot")
def dot(X, Y, **_):
    return {"Out": jnp.sum(X * Y, axis=-1, keepdims=True)}


@register_op("norm")
def norm(X, Input=None, epsilon=1e-10, **_):
    # reference norm_op: l2-normalize across channel dim (NCHW dim 1),
    # optionally scaled by a learnable per-channel Scale input.
    sq = jnp.sum(jnp.square(X), axis=1, keepdims=True)
    out = X / jnp.sqrt(sq + epsilon)
    if Input is not None:
        out = out * Input.reshape((1, -1) + (1,) * (X.ndim - 2))
    return {"Out": out}


@register_op("maxout")
def maxout(X, groups=2, **_):
    n, c, h, w = X.shape
    return {"Out": jnp.max(X.reshape(n, c // groups, groups, h, w), axis=2)}


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _identity_clip_grad(x, lo, hi):
    return x


def _icg_fwd(x, lo, hi):
    return x, None


def _icg_bwd(lo, hi, _res, g):
    return (jnp.clip(g, lo, hi),)


_identity_clip_grad.defvjp(_icg_fwd, _icg_bwd)


@register_op("error_clip")
def error_clip(X, max=1.0, min=None, **_):
    # reference fluid/clip.py ErrorClipByValue: identity forward, the
    # BACKPROPAGATED error through this point is clipped to [min, max] —
    # realized as a custom-VJP identity (jax.grad sees the clipped
    # cotangent exactly where the reference's backward rewrite clipped).
    lo = -abs(float(max)) if min is None else float(min)
    return {"Out": _identity_clip_grad(X, lo, float(max))}


@register_op("selective_fc")
def selective_fc(X, W, Bias=None, Select=None, **_):
    """Selective fully-connected: compute only the selected output columns
    per sample — the reference's large-output-layer capability
    (``paddle/gserver/layers/SelectiveFcLayer.cpp:1``; weight stored
    transposed there too, one row per output neuron).

    X [b,d]; W [k,d] (row-major by output neuron); Bias [k];
    Select [b,s] int ids, entries < 0 are padding.  With Select, Out is
    [b,s] (padded positions 0); without, a plain full fc Out [b,k].
    """
    if Select is None:
        out = X @ W.T
        if Bias is not None:
            out = out + Bias.reshape(1, -1)
        return {"Out": out}
    sel = Select.astype(jnp.int32)
    valid = sel >= 0
    idx = jnp.maximum(sel, 0)
    rows = W[idx]  # [b, s, d]
    out = jnp.einsum("bsd,bd->bs", rows, X)
    if Bias is not None:
        out = out + Bias.reshape(-1)[idx]
    return {"Out": jnp.where(valid, out, 0.0)}
