"""Random ops.

Reference: ``uniform_random_op``, ``gaussian_random_op``, ``dropout_op``
(cuRAND / std::mt19937 with per-op ``seed`` attrs).  TPU-native randomness is
functional: every random op derives a deterministic PRNG key either from its
``seed`` attr (startup-program initializers — reproducible like the
reference's seeded Philox) or from the executor's per-step key stream
(dropout etc., which must differ step to step)."""

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from ..core.dtypes import convert_dtype


def _seed_key(seed, ctx):
    if isinstance(seed, tuple):
        base, ctr = seed
        return jax.random.fold_in(jax.random.PRNGKey(base), ctr)
    if seed:
        return jax.random.PRNGKey(int(seed))
    if ctx is not None:
        return ctx.next_op_key()
    return jax.random.PRNGKey(0)


@register_op("uniform_random")
def uniform_random(shape=(), dtype="float32", min=-1.0, max=1.0, seed=0, _ctx=None, **_):
    key = _seed_key(seed, _ctx)
    return {
        "Out": jax.random.uniform(
            key, tuple(shape), dtype=jnp.float32, minval=min, maxval=max
        ).astype(convert_dtype(dtype))
    }


@register_op("gaussian_random")
def gaussian_random(shape=(), dtype="float32", mean=0.0, std=1.0, seed=0, _ctx=None, **_):
    key = _seed_key(seed, _ctx)
    out = mean + std * jax.random.normal(key, tuple(shape), dtype=jnp.float32)
    return {"Out": out.astype(convert_dtype(dtype))}


@register_op("truncated_gaussian_random")
def truncated_gaussian_random(
    shape=(), dtype="float32", mean=0.0, std=1.0, seed=0, _ctx=None, **_
):
    key = _seed_key(seed, _ctx)
    out = mean + std * jax.random.truncated_normal(
        key, -2.0, 2.0, tuple(shape), dtype=jnp.float32
    )
    return {"Out": out.astype(convert_dtype(dtype))}


@register_op("dropout", stateful_rng=True)
def dropout(X, dropout_prob=0.5, is_test=False, seed=0, fix_seed=False, _key=None, **_):
    # v0.11 semantics (dropout_op.h): train -> out = x * mask (no rescale);
    # test -> out = x * (1 - p) so train/test magnitudes agree.
    if is_test:
        return {"Out": X * (1.0 - dropout_prob), "Mask": jnp.ones_like(X)}
    if dropout_prob == 0.0:
        return {"Out": X, "Mask": jnp.ones_like(X)}
    key = jax.random.PRNGKey(int(seed)) if fix_seed else _key
    keep = 1.0 - dropout_prob
    mask = jax.random.bernoulli(key, keep, X.shape).astype(X.dtype)
    return {"Out": X * mask, "Mask": mask}


@register_op("random_crop", stateful_rng=True)
def random_crop(X, shape=(), _key=None, **_):
    out_shape = tuple(shape)
    starts = []
    key = _key if _key is not None else jax.random.PRNGKey(0)
    for i, (full, crop) in enumerate(zip(X.shape, out_shape)):
        key, sub = jax.random.split(key)
        starts.append(
            jax.random.randint(sub, (), 0, full - crop + 1) if full > crop else 0
        )
    out = jax.lax.dynamic_slice(X, [jnp.asarray(s) for s in starts], out_shape)
    return {"Out": out}


@register_op("sampling_id", stateful_rng=True, nondiff=True)
def sampling_id(X, _key=None, **_):
    """Sample one id per row from the row's probability distribution
    (``paddle/gserver/layers/SamplingIdLayer.cpp:1``).  X [b, k] of
    probabilities (rows need not be exactly normalized)."""
    key = _key if _key is not None else jax.random.PRNGKey(0)
    logits = jnp.log(jnp.maximum(X, 1e-20))
    ids = jax.random.categorical(key, logits, axis=-1)
    return {"Out": ids.astype(jnp.int32)}
