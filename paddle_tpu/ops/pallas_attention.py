"""Flash attention as a Pallas TPU kernel.

The reference has no fused attention (attention is composed from fc +
softmax in ``trainer_config_helpers/networks.py simple_attention``); on TPU
the fused blockwise kernel is the difference between O(t^2) HBM traffic and
O(t) — this is the hot-op Pallas path of the framework (pallas_guide.md
patterns: grid over (batch*heads, q-blocks), online softmax in VMEM,
custom VJP with recompute backward).

Layout: q [b, t_q, h, d], k/v [b, t_k, h, d] (same as parallel.ring_attention,
whose per-device inner block this kernel accelerates).

Forward: Pallas kernel, grid (batch*head, q-blocks, k-blocks) with the
k axis innermost; online-softmax state carried in VMEM scratch; causal
k-blocks above the diagonal are skipped, and the mask select runs only on
blocks straddling the diagonal.  Backward: custom_vjp into two Pallas
kernels — dq (q-major grid) and dk/dv (k-major grid) — recomputing p from
the saved lane-replicated lse, also with causal block skip.
delta = rowsum(do*o) is computed inside the kernels.  HBM residuals are
O(t) rows (lse is stored 2-D [bh, t] — 4 B/row; the in-kernel softmax
state uses 128-lane scratch tiles); VMEM stays O(block^2).

MXU feeds stay in the input dtype: bf16 q/k/v/do go straight into the
dots with f32 accumulation (bf16 input is 2x the f32 MXU rate on v5e);
only softmax state (m/l/lse/p pre-cast) is f32.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30
LSE_LANES = 128  # Mosaic min lane tile (in-kernel m/l scratch width);
# lse ITSELF is stored narrow: [bq, 1] kernel outputs, 2-D [bh, t] residuals


def _pick_block(t, cap):
    """Largest divisor of t that is <= cap (TPU-friendly when t is a
    multiple of 128; always exact so no masking is needed)."""
    b = min(t, cap)
    while t % b:
        b -= 1
    return b


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr,
                      acc_scr, *, sm_scale, causal, block_q, block_k, nk):
    """One (batch*head, q-block, k-block) grid cell.  The k-block axis is
    the INNERMOST grid dimension (TPU grids run sequentially), so the
    online-softmax state lives in VMEM scratch carried across k steps —
    VMEM holds only O(block_q*d + block_k*d), never the full K/V (a
    whole-K/V block spec OOMs scoped vmem at t ~ 16k)."""
    import jax.experimental.pallas as pl

    j = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr[...], NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr[...])
        acc_scr[...] = jnp.zeros_like(acc_scr[...])

    if causal:
        # causal block skip: k blocks strictly above the diagonal touch
        # no unmasked entries — skip their compute entirely (halves the
        # causal forward's work).  Clamp to nk-1: cross-attention with
        # t_q > t_k has q blocks whose diagonal lies beyond the last k
        # block, and the finalize step must still fire for them.
        last_kb = jnp.minimum(((j + 1) * block_q - 1) // block_k, nk - 1)
        needed = kb <= last_kb
    else:
        last_kb = nk - 1
        needed = None

    def _block(masked):
        # MXU feeds stay in the INPUT dtype (bf16 in = 2x the f32 MXU
        # rate); only the softmax state is f32.  Same convention as the
        # public TPU flash kernels.
        q = q_ref[0]          # [bq, d]
        k = k_ref[0]          # [bk, d]
        v = v_ref[0]
        bq = q.shape[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # [bq, bk] f32
        if masked:
            q_pos = j * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)

        # m/l are lane-replicated [bq, 128] (Mosaic min lane tile)
        m_prev = m_scr[...]
        l_prev = l_scr[...]
        m2 = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m2)
        p = jnp.exp(s - m2[:, :1])
        l2 = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc2 = acc_scr[...] * alpha[:, :1] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = m2
        l_scr[...] = l2
        acc_scr[...] = acc2

    if needed is None:
        _block(False)
    else:
        # the mask only bites on blocks straddling the diagonal; blocks
        # fully below it skip the iota/compare/select VPU passes
        unmasked = j * block_q >= (kb + 1) * block_k - 1
        pl.when(jnp.logical_and(needed, unmasked))(lambda: _block(False))
        pl.when(jnp.logical_and(needed, jnp.logical_not(unmasked)))(
            lambda: _block(True))

    @pl.when(kb == last_kb)
    def _finalize():
        l_fin = l_scr[...]
        l_safe = jnp.where(l_fin == 0.0, 1.0, l_fin)
        o_ref[0] = (acc_scr[...] / l_safe[:, :1]).astype(o_ref.dtype)
        # narrow [bq, 1] store (Mosaic masked store) — the residual /
        # ring-merge layout, 4 B/row instead of a 512 B replicated tile
        lse_ref[0] = (m_scr[...] + jnp.log(l_safe))[:, :1]


def _packed_geom(q, k, n_head):
    """Shapes + block-index maps for the two supported layouts.

    ``n_head=None``: q/k/v are [b*h, t, d] (the packed-by-transpose layout
    the 4-D public API produces).  ``n_head=h``: q/k/v are [b, t, h*d] —
    the RAW projection output.  Heads live in the lane dimension, so each
    grid cell's block is a 128-aligned lane slice selected by the INDEX
    MAP ((i // h, ·, i % h) block coords) and no [b,t,h,d]<->[bh,t,d]
    transpose ever exists.  (A 4-D h-sliced BlockSpec is rejected by the
    Mosaic tiling rules — see RESULTS.md round 4; the lane-slice form is
    the legal spelling of the same thing, requiring d % 128 == 0.)

    Returns (bh, t_q, t_k, d, qix, kix) where qix/kix map (grid cell,
    q-or-k block index) -> block coords for q-shaped / k-shaped arrays.
    """
    if n_head is None:
        bh, t_q, d = q.shape
        t_k = k.shape[1]

        def qix(i, blk):
            return (i, blk, 0)

        return bh, t_q, t_k, d, qix, qix
    h = n_head
    b, t_q, hd = q.shape
    t_k = k.shape[1]
    d = hd // h

    def pix(i, blk):
        return (i // h, blk, i % h)

    return b * h, t_q, t_k, d, pix, pix


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret,
               n_head=None):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, t_q, t_k, d, qix, kix = _packed_geom(q, k, n_head)
    block_q = _pick_block(t_q, block_q)
    block_k = _pick_block(t_k, block_k)
    nk = t_k // block_k

    kernel = functools.partial(
        _flash_fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, nk=nk,
    )
    scratch = [
        pltpu.VMEM((block_q, LSE_LANES), jnp.float32),  # m
        pltpu.VMEM((block_q, LSE_LANES), jnp.float32),  # l
        pltpu.VMEM((block_q, d), jnp.float32),          # acc
    ]
    # lse stays [bh, t_q, 1] in BOTH layouts: it is a per-token scalar
    # (1.5 MB at the flagship shape) so writing it row-major-by-(b,h)
    # costs nothing — grid cell i owns row i = b_idx*h + h_idx, and the
    # backward kernels read it back with the same (i, j, 0) map.  Only
    # the O(t*d) tensors need the lane-slice maps to dodge transposes.
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, t_q // block_q, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kb: qix(i, j)),
            pl.BlockSpec((1, block_k, d), lambda i, j, kb: kix(i, kb)),
            pl.BlockSpec((1, block_k, d), lambda i, j, kb: kix(i, kb)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kb: qix(i, j)),
            pl.BlockSpec((1, block_q, 1), lambda i, j, kb: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, t_q, 1), jnp.float32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
    )(q, k, v)
    # lse leaves the kernel [bh, t_q, 1] but is squeezed to 2-D [bh, t_q]
    # immediately: a trailing size-1 dim gets tile-padded back to 128
    # lanes by XLA's T(8,128) layout (402 MB/layer at t=16k bs8 — exactly
    # the lane-replicated waste again, just hidden in padding).  The 2-D
    # form is compact; backward re-expands it transiently.
    return o, lse[:, :, 0]


def _bwd_dq_kernel(*refs, sm_scale, causal, block_q, block_k, nk,
                   has_dlse):
    """dq: grid (bh, q-blocks, k-blocks), k innermost; accumulate in VMEM.
    delta = rowsum(do*o) is computed here (kb==0); an lse cotangent (from
    callers that consume lse, e.g. ring-attention merges) folds in as
    ds = p * (dp - delta + dlse) * scale."""
    import jax.experimental.pallas as pl

    if has_dlse:
        (q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dlse_ref,
         dq_ref, dq_scr, delta_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
         dq_ref, dq_scr, delta_scr) = refs
        dlse_ref = None

    j = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr[...])
        d_row = jnp.sum(
            do_ref[0].astype(jnp.float32) * o_ref[0].astype(jnp.float32),
            axis=-1, keepdims=True)
        if dlse_ref is not None:
            d_row = d_row - dlse_ref[0][:, :1]
        delta_scr[...] = jnp.broadcast_to(d_row, delta_scr.shape)

    if causal:
        # clamped like the forward: cross-attention t_q > t_k must still
        # finalize the q blocks past the last k block
        last_kb = jnp.minimum(((j + 1) * block_q - 1) // block_k, nk - 1)
    else:
        last_kb = nk - 1

    def _block(masked):
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]      # [bq, 1] narrow residual block
        delta = delta_scr[...]
        bq = q.shape[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if masked:
            q_pos = j * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, :1])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, :1]) * sm_scale).astype(k.dtype)
        dq_scr[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        unmasked = j * block_q >= (kb + 1) * block_k - 1
        on = kb <= last_kb
        pl.when(jnp.logical_and(on, unmasked))(lambda: _block(False))
        pl.when(jnp.logical_and(on, jnp.logical_not(unmasked)))(
            lambda: _block(True))
    else:
        _block(False)

    @pl.when(kb == last_kb)
    def _finalize():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, sm_scale, causal, block_q, block_k, nq,
                    has_dlse):
    """dk/dv: grid (bh, k-blocks, q-blocks), q innermost."""
    import jax.experimental.pallas as pl

    if has_dlse:
        (k_ref, v_ref, q_ref, do_ref, o_ref, lse_ref, dlse_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
    else:
        (k_ref, v_ref, q_ref, do_ref, o_ref, lse_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
        dlse_ref = None

    kb = pl.program_id(1)
    jq = pl.program_id(2)

    @pl.when(jq == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr[...])
        dv_scr[...] = jnp.zeros_like(dv_scr[...])

    def _block(masked):
        k = k_ref[0]
        v = v_ref[0]
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]
        delta = jnp.sum(
            do.astype(jnp.float32) * o_ref[0].astype(jnp.float32),
            axis=-1, keepdims=True)
        if dlse_ref is not None:
            delta = delta - dlse_ref[0][:, :1]
        bq = q.shape[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if masked:
            q_pos = jq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, :1])
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, :1]) * sm_scale).astype(q.dtype)
        dk_scr[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # q block jq touches k block kb iff its last row is at/below the
        # block diagonal: (jq+1)*bq - 1 >= kb*bk
        on = jq >= (kb * block_k) // block_q
        unmasked = jq * block_q >= (kb + 1) * block_k - 1
        pl.when(jnp.logical_and(on, unmasked))(lambda: _block(False))
        pl.when(jnp.logical_and(on, jnp.logical_not(unmasked)))(
            lambda: _block(True))
    else:
        _block(False)

    @pl.when(jq == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_fused_kernel(*refs, sm_scale, causal, block_q, block_k, nq,
                      has_dlse):
    """Single-pass backward: grid (bh, k-blocks, q-blocks), q innermost.
    Computes the s/p tile ONCE per (k, q) block pair (the split dq + dkv
    kernels each recompute it — 7 block matmuls per pair vs 5 here) and
    emits dk/dv via VMEM accumulators plus dq as per-k-block partials
    ``dq_part[kb]`` that the caller reduces over kb.  Used when the
    partial buffer is small (nk grows with t; the split kernels remain
    the long-context path)."""
    import jax.experimental.pallas as pl

    if has_dlse:
        (k_ref, v_ref, q_ref, do_ref, o_ref, lse_ref, dlse_ref,
         dqp_ref, dk_ref, dv_ref, dk_scr, dv_scr) = refs
    else:
        (k_ref, v_ref, q_ref, do_ref, o_ref, lse_ref,
         dqp_ref, dk_ref, dv_ref, dk_scr, dv_scr) = refs
        dlse_ref = None

    kb = pl.program_id(1)
    jq = pl.program_id(2)

    @pl.when(jq == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr[...])
        dv_scr[...] = jnp.zeros_like(dv_scr[...])

    def _block(masked):
        k = k_ref[0]
        v = v_ref[0]
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]
        delta = jnp.sum(
            do.astype(jnp.float32) * o_ref[0].astype(jnp.float32),
            axis=-1, keepdims=True)
        if dlse_ref is not None:
            delta = delta - dlse_ref[0][:, :1]
        bq = q.shape[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if masked:
            q_pos = jq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, :1])
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, :1]) * sm_scale).astype(q.dtype)
        dk_scr[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dqp_ref[0, 0] = jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dqp_ref.dtype)

    if causal:
        on = jq >= (kb * block_k) // block_q
        unmasked = jq * block_q >= (kb + 1) * block_k - 1
        pl.when(jnp.logical_and(on, unmasked))(lambda: _block(False))
        pl.when(jnp.logical_and(on, jnp.logical_not(unmasked)))(
            lambda: _block(True))

        # skipped cells still own their dq_part block — zero it so the
        # caller's reduce over kb sees no garbage
        @pl.when(jnp.logical_not(on))
        def _zero():
            dqp_ref[0, 0] = jnp.zeros_like(dqp_ref[0, 0])
    else:
        _block(False)

    @pl.when(jq == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


# fused-backward dq partials budget: [nk, bh, t, d] must stay under this
# (past it — long t — the split dq/dkv kernels take over)
FUSED_BWD_PARTIAL_BYTES = 512 << 20


def _flash_bwd_fused(q, k, v, o, lse, do, sm_scale, causal, block_q,
                     block_k, interpret, dlse=None, n_head=None):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, t_q, t_k, d, qix, kix = _packed_geom(q, k, n_head)
    block_q = _pick_block(t_q, block_q)
    block_k = _pick_block(t_k, block_k)
    nq = t_q // block_q
    nk = t_k // block_k
    has_dlse = dlse is not None

    kspec = pl.BlockSpec((1, block_k, d), lambda i, kb, jq: kix(i, kb))
    qspec = pl.BlockSpec((1, block_q, d), lambda i, kb, jq: qix(i, jq))
    qstat = pl.BlockSpec((1, block_q, 1), lambda i, kb, jq: (i, jq, 0))
    in_specs = [kspec, kspec, qspec, qspec, qspec, qstat]
    args = [k, v, q, do, o, lse]
    if has_dlse:
        in_specs.append(qstat)
        args.append(dlse)
    if n_head is None:
        dqp_spec = pl.BlockSpec((1, 1, block_q, d),
                                lambda i, kb, jq: (kb, i, jq, 0))
        dqp_shape = jax.ShapeDtypeStruct((nk, bh, t_q, d), q.dtype)
    else:
        h = n_head
        dqp_spec = pl.BlockSpec((1, 1, block_q, d),
                                lambda i, kb, jq: (kb, i // h, jq, i % h))
        dqp_shape = jax.ShapeDtypeStruct((nk,) + q.shape, q.dtype)
    dq_part, dk, dv = pl.pallas_call(
        functools.partial(_bwd_fused_kernel, sm_scale=sm_scale,
                          causal=causal, block_q=block_q, block_k=block_k,
                          nq=nq, has_dlse=has_dlse),
        grid=(bh, nk, nq),
        in_specs=in_specs,
        out_specs=[dqp_spec, kspec, kspec],
        out_shape=[
            dqp_shape,
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(*args)
    dq = jnp.sum(dq_part.astype(jnp.float32), axis=0).astype(q.dtype)
    return dq, dk, dv


def _flash_bwd(q, k, v, o, lse, do, sm_scale, causal, block_q, block_k,
               interpret, dlse=None, n_head=None):
    """Pallas backward.  Short/medium t: one fused kernel (s recomputed
    once per block pair, dq as per-k-block partials).  Long t (partials
    over budget): dq kernel (q-major) + dk/dv kernel (k-major), both with
    causal block skip; O(block^2) VMEM.  ``lse`` and the optional ``dlse``
    (the cotangent of the returned lse, for callers that consume it —
    ring-attention merges) arrive in the narrow [bh, t_q, 1] residual
    layout in BOTH q/k/v layouts (packed mode keeps lse row-major by
    (b, h) — see the forward's lse note)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, t_q, t_k, d, qix, kix = _packed_geom(q, k, n_head)
    block_q = _pick_block(t_q, block_q)
    block_k = _pick_block(t_k, block_k)
    nq = t_q // block_q
    nk = t_k // block_k
    has_dlse = dlse is not None

    part_bytes = nk * bh * t_q * d * q.dtype.itemsize
    if part_bytes <= FUSED_BWD_PARTIAL_BYTES:
        return _flash_bwd_fused(q, k, v, o, lse, do, sm_scale, causal,
                                block_q, block_k, interpret, dlse=dlse,
                                n_head=n_head)

    qspec = pl.BlockSpec((1, block_q, d), lambda i, j, kb: qix(i, j))
    kspec = pl.BlockSpec((1, block_k, d), lambda i, j, kb: kix(i, kb))
    qstat = pl.BlockSpec((1, block_q, 1), lambda i, j, kb: (i, j, 0))
    dq_in_specs = [qspec, kspec, kspec, qspec, qspec, qstat]
    dq_args = [q, k, v, do, o, lse]
    if has_dlse:
        dq_in_specs.append(qstat)
        dq_args.append(dlse)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, nk=nk,
                          has_dlse=has_dlse),
        grid=(bh, nq, nk),
        in_specs=dq_in_specs,
        out_specs=[qspec],
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32),
                        pltpu.VMEM((block_q, LSE_LANES), jnp.float32)],
        interpret=interpret,
    )(*dq_args)[0]

    kspec2 = pl.BlockSpec((1, block_k, d), lambda i, kb, jq: kix(i, kb))
    qspec2 = pl.BlockSpec((1, block_q, d), lambda i, kb, jq: qix(i, jq))
    qstat2 = pl.BlockSpec((1, block_q, 1), lambda i, kb, jq: (i, jq, 0))
    dkv_in_specs = [kspec2, kspec2, qspec2, qspec2, qspec2, qstat2]
    dkv_args = [k, v, q, do, o, lse]
    if has_dlse:
        dkv_in_specs.append(qstat2)
        dkv_args.append(dlse)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale,
                          causal=causal, block_q=block_q, block_k=block_k,
                          nq=nq, has_dlse=has_dlse),
        grid=(bh, nk, nq),
        in_specs=dkv_in_specs,
        out_specs=[kspec2, kspec2],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(*dkv_args)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_core(q, k, v, sm_scale, causal, block_q, block_k, interpret,
                n_head=None):
    o, _ = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret,
                      n_head=n_head)
    return o


def _flash_core_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret,
                    n_head=None):
    o, lse = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k,
                        interpret, n_head=n_head)
    return o, (q, k, v, o, lse)


def _flash_core_bwd(sm_scale, causal, block_q, block_k, interpret, n_head,
                    res, do):
    q, k, v, o, lse = res
    return _flash_bwd(q, k, v, o, lse[:, :, None], do, sm_scale, causal,
                      block_q, block_k, interpret, n_head=n_head)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention(q, k, v, causal=False, sm_scale=None, block_q=1024,
                    block_k=1024, interpret=None):
    """Fused attention.  q [b, t_q, h, d], k/v [b, t_k, h, d] ->
    [b, t_q, h, d].  Differentiable (custom VJP).  ``interpret=None``
    auto-selects Pallas interpreter mode off-TPU so the same code path runs
    in CPU tests."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, t_q, h, d = q.shape
    t_k = k.shape[1]
    sm_scale = d ** -0.5 if sm_scale is None else sm_scale

    def pack(x, t):
        return jnp.swapaxes(x, 1, 2).reshape(b * h, t, x.shape[-1])

    o = _flash_core(
        pack(q, t_q), pack(k, t_k), pack(v, t_k),
        float(sm_scale), bool(causal), int(block_q), int(block_k),
        bool(interpret), None,
    )
    return jnp.swapaxes(o.reshape(b, h, t_q, d), 1, 2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_core_lse(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    o, lse = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k,
                        interpret)
    return o, lse


def _flash_core_lse_fwd(q, k, v, sm_scale, causal, block_q, block_k,
                        interpret):
    o, lse = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k,
                        interpret)
    return (o, lse), (q, k, v, o, lse)


def _flash_core_lse_bwd(sm_scale, causal, block_q, block_k, interpret,
                        res, cts):
    q, k, v, o, lse = res
    do, dlse = cts
    return _flash_bwd(q, k, v, o, lse[:, :, None], do, sm_scale, causal,
                      block_q, block_k, interpret,
                      dlse=dlse.astype(jnp.float32)[:, :, None])


_flash_core_lse.defvjp(_flash_core_lse_fwd, _flash_core_lse_bwd)


def flash_attention_with_lse(q, k, v, causal=False, sm_scale=None,
                             block_q=1024, block_k=1024, interpret=None):
    """flash_attention that ALSO returns the per-row logsumexp
    (o [b, t, h, d], lse [b, h, t]) — the building block for composing
    partial attentions with online-softmax merges (ring attention).
    Fully differentiable including through lse."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, t_q, h, d = q.shape
    t_k = k.shape[1]
    sm_scale = d ** -0.5 if sm_scale is None else sm_scale

    def pack(x, t):
        return jnp.swapaxes(x, 1, 2).reshape(b * h, t, x.shape[-1])

    o, lse = _flash_core_lse(
        pack(q, t_q), pack(k, t_k), pack(v, t_k),
        float(sm_scale), bool(causal), int(block_q), int(block_k),
        bool(interpret),
    )
    return (jnp.swapaxes(o.reshape(b, h, t_q, d), 1, 2),
            lse.reshape(b, h, t_q))



def flash_attention_packed(q, k, v, n_head, causal=False, sm_scale=None,
                           block_q=1024, block_k=1024, interpret=None):
    """Fused attention on the RAW projection layout: q/k/v [b, t, h*d]
    (heads concatenated in the feature dim, exactly what the QKV matmuls
    emit) -> o [b, t, h*d] (exactly what the output projection consumes).

    Numerically identical to ``flash_attention`` on the reshaped 4-D view,
    but the [b,t,h,d]<->[b*h,t,d] pack/unpack transposes — 23 ms/step on
    the GPT flagship, 8% of device time (RESULTS.md round 4) — never
    exist: each head is a 128-aligned lane slice selected by the kernels'
    block index maps.  Requires ``d_head % 128 == 0`` (the Mosaic lane
    tile) unless ``n_head == 1``; callers with other head widths use
    ``flash_attention``."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, t_q, hd = q.shape
    if hd % n_head:
        raise ValueError(f"feature dim {hd} not divisible by n_head {n_head}")
    d = hd // n_head
    if n_head > 1 and d % 128 and not interpret:
        # interpret mode has no Mosaic tiling rules — CPU tests exercise
        # small head widths through the identical code path
        raise ValueError(
            f"flash_attention_packed needs d_head % 128 == 0 (lane-aligned "
            f"head slices), got d_head={d}; use flash_attention for other "
            f"head widths")
    sm_scale = d ** -0.5 if sm_scale is None else sm_scale
    return _flash_core(
        q, k, v, float(sm_scale), bool(causal), int(block_q), int(block_k),
        bool(interpret), int(n_head))


def attention_reference(q, k, v, causal=False, sm_scale=None):
    """Dense reference implementation (for tests and tiny shapes)."""
    d = q.shape[-1]
    sm_scale = d ** -0.5 if sm_scale is None else sm_scale
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * sm_scale
    if causal:
        t_q, t_k = logits.shape[-2:]
        mask = jnp.arange(t_q)[:, None] >= jnp.arange(t_k)[None, :]
        logits = jnp.where(mask, logits, NEG_INF)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


# -- op registration ---------------------------------------------------------
from ..core.registry import register_op


@register_op("flash_attention")
def flash_attention_op(Q, K, V, causal=False, sm_scale=0.0, **_):
    scale = None if not sm_scale else float(sm_scale)
    return {"Out": flash_attention(Q, K, V, causal=causal, sm_scale=scale)}


def _tp_axis(_ctx):
    """(mesh, tp_size) when the executor runs under a mesh with a 'tp'
    axis — the signal for the ops to enter their shard_map paths."""
    mesh = getattr(getattr(_ctx, "executor", None), "mesh", None)
    if mesh is None or "tp" not in mesh.axis_names:
        return None, 1
    return mesh, int(mesh.shape["tp"])


@register_op("flash_attention_packed")
def flash_attention_packed_op(Q, K, V, n_head=None, causal=False,
                              sm_scale=0.0, _ctx=None, **_):
    if n_head is None:
        # no safe default: 1 would silently softmax across the whole
        # concatenated h*d feature dim as a single head
        raise ValueError("flash_attention_packed op requires the n_head attr")
    n_head = int(n_head)
    scale = None if not sm_scale else float(sm_scale)
    mesh, tp = _tp_axis(_ctx)
    if tp > 1 and n_head % tp == 0:
        # Head-sharded tensor parallelism: the packed feature dim IS the
        # head dim, so a 'tp' shard of [b, t, h*d] holds h/tp whole
        # heads and attention needs NO cross-shard communication — each
        # shard runs the kernel on its local heads (the shard_map-over-
        # heads recipe; GSPMD cannot partition an opaque custom call, so
        # without this it would all-gather the tp-sharded activations).
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        db = "dp" if "dp" in mesh.axis_names else None
        spec = P(db, None, "tp")

        def local(q, k, v):
            return flash_attention_packed(
                q, k, v, n_head // tp, causal=causal, sm_scale=scale)

        out = shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                        out_specs=spec, check_rep=False)(Q, K, V)
        return {"Out": out}
    return {"Out": flash_attention_packed(
        Q, K, V, n_head, causal=causal, sm_scale=scale)}
