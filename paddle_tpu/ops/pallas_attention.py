"""Flash attention as a Pallas TPU kernel.

The reference has no fused attention (attention is composed from fc +
softmax in ``trainer_config_helpers/networks.py simple_attention``); on TPU
the fused blockwise kernel is the difference between O(t^2) HBM traffic and
O(t) — this is the hot-op Pallas path of the framework (pallas_guide.md
patterns: grid over (batch*heads, q-blocks), online softmax in VMEM,
custom VJP with recompute backward).

Layout: q [b, t_q, h, d], k/v [b, t_k, h, d] (same as parallel.ring_attention,
whose per-device inner block this kernel accelerates).

Forward: Pallas kernel, one grid cell per (batch*head, q-block); inner
fori_loop streams K/V blocks through VMEM with online softmax.
Backward: custom_vjp — blockwise recompute in plain JAX (XLA fuses the
einsums onto the MXU; memory stays O(t * block)).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30
LSE_LANES = 128  # Mosaic min lane tile; lse vectors are lane-replicated


def _pick_block(t, cap):
    """Largest divisor of t that is <= cap (TPU-friendly when t is a
    multiple of 128; always exact so no masking is needed)."""
    b = min(t, cap)
    while t % b:
        b -= 1
    return b


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale,
                      causal, block_q, block_k, t_k):
    import jax.experimental.pallas as pl

    q = q_ref[0].astype(jnp.float32)  # [bq, d]
    bq, d = q.shape
    j = pl.program_id(1)
    q_pos = j * block_q + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    nk = t_k // block_k

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # [bq, bk]
        if causal:
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m2 = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m2)
        p = jnp.exp(s - m2[:, None])
        l2 = l * alpha + jnp.sum(p, axis=-1)
        acc2 = acc * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m2, l2, acc2

    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, nk, body, (m0, l0, acc0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # lse is replicated across a 128-lane trailing dim: Mosaic requires the
    # last two block dims be (8k, 128m) tiles, so a [bq] vector per grid
    # cell is stored as [bq, 128] (the official TPU flash kernels do the
    # same); the wrapper slices lane 0 back out.
    lse_ref[0] = jnp.broadcast_to(
        (m + jnp.log(l_safe))[:, None], (bq, LSE_LANES)
    )


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    import jax.experimental.pallas as pl

    bh, t_q, d = q.shape
    t_k = k.shape[1]
    block_q = _pick_block(t_q, block_q)
    block_k = _pick_block(t_k, block_k)

    kernel = functools.partial(
        _flash_fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, t_k=t_k,
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, t_q // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, t_k, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, t_k, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_q, LSE_LANES), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t_q, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t_q, LSE_LANES), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse[:, :, 0]


def _flash_bwd(q, k, v, o, lse, do, sm_scale, causal, block_k):
    """Blockwise backward from saved lse (plain JAX; scan over K/V blocks
    keeps memory O(t*block) while XLA runs the einsums on the MXU)."""
    bh, t_q, d = q.shape
    t_k = k.shape[1]
    block_k = _pick_block(t_k, block_k)
    nk = t_k // block_k

    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    delta = jnp.sum(dof * o.astype(jnp.float32), axis=-1)  # [bh, tq]
    q_pos = jnp.arange(t_q)[:, None]

    kb = jnp.swapaxes(k.reshape(bh, nk, block_k, d), 0, 1)
    vb = jnp.swapaxes(v.reshape(bh, nk, block_k, d), 0, 1)

    def body(dq_acc, blk):
        kk, vv, idx = blk
        kkf = kk.astype(jnp.float32)
        vvf = vv.astype(jnp.float32)
        s = jnp.einsum("bqd,bkd->bqk", qf, kkf,
                       preferred_element_type=jnp.float32) * sm_scale
        if causal:
            k_pos = idx * block_k + jnp.arange(block_k)[None, :]
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, :, None])  # [bh, tq, bk]
        dv = jnp.einsum("bqk,bqd->bkd", p, dof,
                        preferred_element_type=jnp.float32)
        dp = jnp.einsum("bqd,bkd->bqk", dof, vvf,
                        preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, :, None]) * sm_scale
        dq_acc = dq_acc + jnp.einsum("bqk,bkd->bqd", ds, kkf,
                                     preferred_element_type=jnp.float32)
        dk = jnp.einsum("bqk,bqd->bkd", ds, qf,
                        preferred_element_type=jnp.float32)
        return dq_acc, (dk, dv)

    dq0 = jnp.zeros((bh, t_q, d), jnp.float32)
    dq, (dks, dvs) = jax.lax.scan(body, dq0, (kb, vb, jnp.arange(nk)))
    dk = jnp.swapaxes(dks, 0, 1).reshape(bh, t_k, d)
    dv = jnp.swapaxes(dvs, 0, 1).reshape(bh, t_k, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_core(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    o, _ = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret)
    return o


def _flash_core_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    o, lse = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret)
    return o, (q, k, v, o, lse)


def _flash_core_bwd(sm_scale, causal, block_q, block_k, interpret, res, do):
    q, k, v, o, lse = res
    return _flash_bwd(q, k, v, o, lse, do, sm_scale, causal, block_k)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention(q, k, v, causal=False, sm_scale=None, block_q=256,
                    block_k=256, interpret=None):
    """Fused attention.  q [b, t_q, h, d], k/v [b, t_k, h, d] ->
    [b, t_q, h, d].  Differentiable (custom VJP).  ``interpret=None``
    auto-selects Pallas interpreter mode off-TPU so the same code path runs
    in CPU tests."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, t_q, h, d = q.shape
    t_k = k.shape[1]
    sm_scale = d ** -0.5 if sm_scale is None else sm_scale

    def pack(x, t):
        return jnp.swapaxes(x, 1, 2).reshape(b * h, t, x.shape[-1])

    o = _flash_core(
        pack(q, t_q), pack(k, t_k), pack(v, t_k),
        float(sm_scale), bool(causal), int(block_q), int(block_k),
        bool(interpret),
    )
    return jnp.swapaxes(o.reshape(b, h, t_q, d), 1, 2)


def attention_reference(q, k, v, causal=False, sm_scale=None):
    """Dense reference implementation (for tests and tiny shapes)."""
    d = q.shape[-1]
    sm_scale = d ** -0.5 if sm_scale is None else sm_scale
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * sm_scale
    if causal:
        t_q, t_k = logits.shape[-2:]
        mask = jnp.arange(t_q)[:, None] >= jnp.arange(t_k)[None, :]
        logits = jnp.where(mask, logits, NEG_INF)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


# -- op registration ---------------------------------------------------------
from ..core.registry import register_op


@register_op("flash_attention")
def flash_attention_op(Q, K, V, causal=False, sm_scale=0.0, **_):
    scale = None if not sm_scale else float(sm_scale)
    return {"Out": flash_attention(Q, K, V, causal=causal, sm_scale=scale)}
