"""Flash attention as a Pallas TPU kernel.

The reference has no fused attention (attention is composed from fc +
softmax in ``trainer_config_helpers/networks.py simple_attention``); on TPU
the fused blockwise kernel is the difference between O(t^2) HBM traffic and
O(t) — this is the hot-op Pallas path of the framework (pallas_guide.md
patterns: grid over (batch*heads, q-blocks), online softmax in VMEM,
custom VJP with recompute backward).

Layout: q [b, t_q, h, d], k/v [b, t_k, h, d] (same as parallel.ring_attention,
whose per-device inner block this kernel accelerates).

Forward: Pallas kernel, grid (batch*head, q-blocks, k-blocks) with the
k axis innermost; online-softmax state carried in VMEM scratch; causal
k-blocks above the diagonal are skipped, and the mask select runs only on
blocks straddling the diagonal.  Backward: custom_vjp into two Pallas
kernels — dq (q-major grid) and dk/dv (k-major grid) — recomputing p from
the saved lane-replicated lse, also with causal block skip.
delta = rowsum(do*o) is computed inside the kernels.  HBM residuals are
O(t) rows (lse is stored 2-D [bh, t] — 4 B/row; the in-kernel softmax
state uses 128-lane scratch tiles); VMEM stays O(block^2).

MXU feeds stay in the input dtype: bf16 q/k/v/do go straight into the
dots with f32 accumulation (bf16 input is 2x the f32 MXU rate on v5e);
only softmax state (m/l/lse/p pre-cast) is f32.
"""

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name

from ..analysis.jaxpr_tools import KERNEL_RESIDUAL_TAG

# The backward residual contract, pinned by tests/test_memory_engine.py:
# the custom VJP recomputes p from EXACTLY these five arrays and closes
# over nothing else.  q/k/v are upstream projection outputs (saved once,
# shared with the matmul residuals), o is the kernel's own output, lse is
# the narrow 2-D [b*h, t] softmax statistic.  Anything beyond this set
# (a saved p tile, a delta row, a replicated lse) multiplies per-layer
# residual memory at long context — at the t=16k flagship every extra
# bf16 [b, t, d] residual is 144 MB/layer.
FLASH_BWD_RESIDUALS = ("q", "k", "v", "o", "lse")

NEG_INF = -1e30
LSE_LANES = 128  # Mosaic min lane tile (in-kernel m/l scratch width);
# lse ITSELF is stored narrow: [bq, 1] kernel outputs, 2-D [bh, t] residuals

# causal diagonal sub-tile width: straddling (diagonal) blocks are computed
# as a static grid of (DIAG_W x DIAG_W) sub-tiles and sub-tiles entirely
# above the diagonal are NEVER computed — the forward waste of a causal
# block pair drops from ~block/2 masked columns per row-block (~20% of all
# flops at t=4096 with 1024 blocks) to the DIAG_W-wide band along the
# diagonal (~w/t).  256 keeps the sub-dots MXU-shaped ([256, d] x [d, 256])
# and the unroll at <= 16 regions per straddling cell.  A process-wide
# TUNABLE: PADDLE_TPU_DIAG_W pins it (the env knob wins over everything),
# and the autotune engine (paddle_tpu.tune, docs/autotune.md) sets the
# module global while measuring a candidate / applying a tuned winner
# (apply_tuned_diag_w) — the kernels read it at trace time, so fwd and
# all three bwd kernels always agree within one compile.
_DIAG_W_ENV = int(os.environ.get("PADDLE_TPU_DIAG_W", "0") or 0)
DIAG_W = _DIAG_W_ENV or 256


def apply_tuned_diag_w(width):
    """Apply a tuned causal sub-tile width process-wide (the autotune
    hot path / search loop).  The PADDLE_TPU_DIAG_W env pin always
    wins; returns the width actually in effect."""
    global DIAG_W
    if width and not _DIAG_W_ENV:
        DIAG_W = int(width)
    return DIAG_W


def _pick_block(t, cap):
    """Largest divisor of t that is <= cap (TPU-friendly when t is a
    multiple of 128; always exact so no masking is needed)."""
    b = min(t, cap)
    while t % b:
        b -= 1
    return b


def packed_sub_heads(n_head, d_head):
    """How many heads one 128-lane slice of the packed layout carries.

    Returns 1 (one lane-aligned head per slice), 2 (two d=64 heads packed
    per slice), or None when the geometry has no packed spelling and
    callers must use the 4-D ``flash_attention`` path.  This is THE
    geometry decision: tests pin it per (n_head, d_head)."""
    if n_head == 1:
        return 1
    if d_head % 128 == 0:
        return 1
    if d_head == 64 and n_head % 2 == 0:
        return 2
    return None


def _diag_subtile_live(j, kb, qs, ks, block_q, block_k, wq, wk):
    """Sub-tile (qs, ks) of straddling cell (j, kb) intersects the allowed
    causal region (q_pos >= k_pos) — its first k column is at or below the
    sub-tile's last q row.  Works on both Python ints (flop accounting)
    and traced program ids (the kernel's pl.when predicates)."""
    row_last = j * block_q + (qs + 1) * wq - 1
    col0 = kb * block_k + ks * wk
    return col0 <= row_last


def _diag_subtile_needs_mask(j, kb, qs, ks, block_q, block_k, wq, wk):
    """The diagonal passes through sub-tile (qs, ks): its last k column is
    past the sub-tile's first q row, so the iota/select must run."""
    row0 = j * block_q + qs * wq
    col_last = kb * block_k + (ks + 1) * wk - 1
    return col_last > row0


def causal_flash_flops(t_q, t_k, d, block_q=1024, block_k=1024,
                       diag_w=None, per_head=True):
    """MXU flops the causal forward kernel SCHEDULES for one (batch, head),
    by simulating exactly the kernel's block/sub-tile skip logic
    (``_diag_subtile_live`` is shared with the forward AND all three
    backward kernels, so this accounting IS the grid-shape assertion; the
    backward schedules the same (row, col) coverage with 5-7 dots per
    pair instead of 2).  Returns ``(scheduled, useful)`` where useful
    counts only unmasked (q_pos >= k_pos) score entries; both in flops of
    the two forward block dots (q@k^T and p@v: 4*d per score entry)."""
    block_q = _pick_block(t_q, block_q)
    block_k = _pick_block(t_k, block_k)
    wq = _pick_block(block_q, diag_w or DIAG_W)
    wk = _pick_block(block_k, diag_w or DIAG_W)
    nq, nk = t_q // block_q, t_k // block_k
    scheduled = 0
    for j in range(nq):
        last_kb = min(((j + 1) * block_q - 1) // block_k, nk - 1)
        for kb in range(last_kb + 1):
            if j * block_q >= (kb + 1) * block_k - 1:
                scheduled += block_q * block_k  # fully unmasked cell
                continue
            for qs in range(block_q // wq):
                for ks in range(block_k // wk):
                    if _diag_subtile_live(j, kb, qs, ks, block_q,
                                          block_k, wq, wk):
                        scheduled += wq * wk
    useful = sum(min(r + 1, t_k) for r in range(t_q))
    return 4 * d * scheduled, 4 * d * useful


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr,
                      acc_scr, *, sm_scale, causal, block_q, block_k, nk,
                      sub_heads):
    """One (batch*head-slice, q-block, k-block) grid cell.  The k-block
    axis is the INNERMOST grid dimension (TPU grids run sequentially), so
    the online-softmax state lives in VMEM scratch carried across k steps
    — VMEM holds only O(block_q*d + block_k*d), never the full K/V (a
    whole-K/V block spec OOMs scoped vmem at t ~ 16k).

    ``sub_heads`` (S): heads carried per 128-lane feature slice.  S=1 is
    the lane-aligned layout (d_head % 128 == 0); S=2 packs two d=64 heads
    per slice — each sub-head is an independent attention over its own
    64-lane half (separate softmax state in the leading scratch axis), so
    d_head=64 models get the transpose-free packed path too.  The 64-lane
    value sub-slices are plain static lane slices (interpret mode and
    Mosaic's masked vector loads both handle them).

    Causal straddling (diagonal) cells run TRIANGULAR: a static grid of
    DIAG_W-wide sub-tiles in which sub-tiles entirely above the diagonal
    are never computed (``_diag_subtile_live``) and the iota/select mask
    runs only on sub-tiles the diagonal actually crosses — the masked
    half-block flops of the old full-tile + select spelling do not exist.
    """
    import jax.experimental.pallas as pl

    j = pl.program_id(1)
    kb = pl.program_id(2)
    S = sub_heads
    d = q_ref.shape[-1] // S
    wq = _pick_block(block_q, DIAG_W)
    wk = _pick_block(block_k, DIAG_W)

    @pl.when(kb == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr[...], NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr[...])
        acc_scr[...] = jnp.zeros_like(acc_scr[...])

    if causal:
        # causal block skip: k blocks strictly above the diagonal touch
        # no unmasked entries — skip their compute entirely (halves the
        # causal forward's work).  Clamp to nk-1: cross-attention with
        # t_q > t_k has q blocks whose diagonal lies beyond the last k
        # block, and the finalize step must still fire for them.
        last_kb = jnp.minimum(((j + 1) * block_q - 1) // block_k, nk - 1)
        needed = kb <= last_kb
    else:
        last_kb = nk - 1
        needed = None

    def _update(sh, rows, s, v_sub):
        """One online-softmax state update for sub-head ``sh``, q rows
        ``rows`` (a static slice) and score tile ``s``."""
        m_prev = m_scr[sh, rows]
        l_prev = l_scr[sh, rows]
        m2 = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m2)
        p = jnp.exp(s - m2[:, :1])
        l2 = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc2 = acc_scr[sh, rows] * alpha[:, :1] + jax.lax.dot_general(
            p.astype(v_sub.dtype), v_sub, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[sh, rows] = m2
        l_scr[sh, rows] = l2
        acc_scr[sh, rows] = acc2

    def _score(q_sub, k_sub):
        # MXU feeds stay in the INPUT dtype (bf16 in = 2x the f32 MXU
        # rate); only the softmax state is f32.  Same convention as the
        # public TPU flash kernels.
        return jax.lax.dot_general(
            q_sub, k_sub, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale

    def _full_block():
        for sh in range(S):
            sl = slice(sh * d, (sh + 1) * d)
            s = _score(q_ref[0][:, sl], k_ref[0][:, sl])
            _update(sh, slice(None), s, v_ref[0][:, sl])

    def _diag_block():
        # triangular straddling cell: only sub-tiles intersecting the
        # allowed q_pos >= k_pos region are computed
        for sh in range(S):
            sl = slice(sh * d, (sh + 1) * d)
            q = q_ref[0][:, sl]
            k = k_ref[0][:, sl]
            v = v_ref[0][:, sl]
            for qs in range(block_q // wq):
                rows = slice(qs * wq, (qs + 1) * wq)
                for ks in range(block_k // wk):
                    cols = slice(ks * wk, (ks + 1) * wk)

                    def _sub(masked, rows=rows, cols=cols, qs=qs, ks=ks,
                             sh=sh, q=q, k=k, v=v):
                        s = _score(q[rows], k[cols])
                        if masked:
                            q_pos = (j * block_q + qs * wq
                                     + jax.lax.broadcasted_iota(
                                         jnp.int32, (wq, wk), 0))
                            k_pos = (kb * block_k + ks * wk
                                     + jax.lax.broadcasted_iota(
                                         jnp.int32, (wq, wk), 1))
                            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
                        _update(sh, rows, s, v[cols])

                    live = _diag_subtile_live(j, kb, qs, ks, block_q,
                                              block_k, wq, wk)
                    crossing = _diag_subtile_needs_mask(
                        j, kb, qs, ks, block_q, block_k, wq, wk)
                    pl.when(jnp.logical_and(live, crossing))(
                        lambda _s=_sub: _s(True))
                    pl.when(jnp.logical_and(
                        live, jnp.logical_not(crossing)))(
                        lambda _s=_sub: _s(False))

    if needed is None:
        _full_block()
    else:
        # the diagonal only crosses blocks straddling it; blocks fully
        # below run the plain full-tile dot with no iota/select at all
        unmasked = j * block_q >= (kb + 1) * block_k - 1
        pl.when(jnp.logical_and(needed, unmasked))(_full_block)
        pl.when(jnp.logical_and(needed, jnp.logical_not(unmasked)))(
            _diag_block)

    @pl.when(kb == last_kb)
    def _finalize():
        lses = []
        outs = []
        for sh in range(S):
            l_fin = l_scr[sh]
            l_safe = jnp.where(l_fin == 0.0, 1.0, l_fin)
            outs.append((acc_scr[sh] / l_safe[:, :1]).astype(o_ref.dtype))
            # narrow [bq, 1] store (Mosaic masked store) — the residual /
            # ring-merge layout, 4 B/row instead of a 512 B replicated tile
            lses.append((m_scr[sh] + jnp.log(l_safe))[:, :1])
        o_ref[0] = outs[0] if S == 1 else jnp.concatenate(outs, axis=-1)
        lse_ref[...] = jnp.stack(lses)


def _packed_geom(q, k, n_head, sub_heads=1):
    """Shapes + block-index maps for the supported layouts.

    ``n_head=None``: q/k/v are [b*h, t, d] (the packed-by-transpose layout
    the 4-D public API produces).  ``n_head=h``: q/k/v are [b, t, h*d] —
    the RAW projection output.  Heads live in the lane dimension, so each
    grid cell's block is a 128-aligned lane slice selected by the INDEX
    MAP ((i // n_slices, ·, i % n_slices) block coords) and no
    [b,t,h,d]<->[bh,t,d] transpose ever exists.  (A 4-D h-sliced BlockSpec
    is rejected by the Mosaic tiling rules — see RESULTS.md round 4; the
    lane-slice form is the legal spelling of the same thing.)

    ``sub_heads`` (S): heads per 128-lane slice — 1 for d_head % 128 == 0,
    2 for d_head == 64 (two heads packed per slice; the kernels run S
    independent softmax states over the 64-lane halves).  The grid's
    leading axis then has b * h / S cells over h / S slices.

    Returns (bh_cells, t_q, t_k, width, qix, kix) where ``width`` is the
    feature-slice width each block spec carries (S * d_head) and qix/kix
    map (grid cell, q-or-k block index) -> block coords.
    """
    if n_head is None:
        bh, t_q, d = q.shape
        t_k = k.shape[1]

        def qix(i, blk):
            return (i, blk, 0)

        return bh, t_q, t_k, d, qix, qix
    h = n_head
    S = sub_heads
    b, t_q, hd = q.shape
    t_k = k.shape[1]
    width = (hd // h) * S
    n_slices = h // S

    def pix(i, blk):
        return (i // n_slices, blk, i % n_slices)

    return b * (h // S), t_q, t_k, width, pix, pix


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret,
               n_head=None, sub_heads=1):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    S = sub_heads
    bh, t_q, t_k, width, qix, kix = _packed_geom(q, k, n_head, S)
    block_q = _pick_block(t_q, block_q)
    block_k = _pick_block(t_k, block_k)
    nk = t_k // block_k

    kernel = functools.partial(
        _flash_fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, nk=nk, sub_heads=S,
    )
    scratch = [
        pltpu.VMEM((S, block_q, LSE_LANES), jnp.float32),  # m
        pltpu.VMEM((S, block_q, LSE_LANES), jnp.float32),  # l
        pltpu.VMEM((S, block_q, width // S), jnp.float32),  # acc
    ]
    # lse stays [b*h, t_q, 1] in ALL layouts: it is a per-token scalar
    # (1.5 MB at the flagship shape) so writing it row-major-by-(b,h)
    # costs nothing — grid cell i owns rows [i*S, (i+1)*S), and the
    # backward kernels read it back with the same (i, j, 0) map.  Only
    # the O(t*d) tensors need the lane-slice maps to dodge transposes.
    n_lse_rows = bh * S
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, t_q // block_q, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, width), lambda i, j, kb: qix(i, j)),
            pl.BlockSpec((1, block_k, width), lambda i, j, kb: kix(i, kb)),
            pl.BlockSpec((1, block_k, width), lambda i, j, kb: kix(i, kb)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, width), lambda i, j, kb: qix(i, j)),
            pl.BlockSpec((S, block_q, 1), lambda i, j, kb: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((n_lse_rows, t_q, 1), jnp.float32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
    )(q, k, v)
    # lse leaves the kernel [b*h, t_q, 1] but is squeezed to 2-D [b*h, t_q]
    # immediately: a trailing size-1 dim gets tile-padded back to 128
    # lanes by XLA's T(8,128) layout (402 MB/layer at t=16k bs8 — exactly
    # the lane-replicated waste again, just hidden in padding).  The 2-D
    # form is compact; backward re-expands it transiently.
    return o, lse[:, :, 0]


def _bwd_dq_kernel(*refs, sm_scale, causal, block_q, block_k, nk,
                   has_dlse, sub_heads):
    """dq: grid (bh, q-blocks, k-blocks), k innermost; accumulate in VMEM.
    delta = rowsum(do*o) is computed here (kb==0); an lse cotangent (from
    callers that consume lse, e.g. ring-attention merges) folds in as
    ds = p * (dp - delta + dlse) * scale.  ``sub_heads`` > 1: each
    128-lane slice carries S independent d=64 heads (see the forward
    kernel) — per-sub-head score/delta math, one concatenated dq store."""
    import jax.experimental.pallas as pl

    if has_dlse:
        (q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dlse_ref,
         dq_ref, dq_scr, delta_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
         dq_ref, dq_scr, delta_scr) = refs
        dlse_ref = None

    j = pl.program_id(1)
    kb = pl.program_id(2)
    S = sub_heads
    d = q_ref.shape[-1] // S

    @pl.when(kb == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr[...])
        for sh in range(S):
            sl = slice(sh * d, (sh + 1) * d)
            d_row = jnp.sum(
                do_ref[0][:, sl].astype(jnp.float32)
                * o_ref[0][:, sl].astype(jnp.float32),
                axis=-1, keepdims=True)
            if dlse_ref is not None:
                d_row = d_row - dlse_ref[sh][:, :1]
            delta_scr[sh] = jnp.broadcast_to(d_row, delta_scr.shape[1:])

    if causal:
        # clamped like the forward: cross-attention t_q > t_k must still
        # finalize the q blocks past the last k block
        last_kb = jnp.minimum(((j + 1) * block_q - 1) // block_k, nk - 1)
    else:
        last_kb = nk - 1

    wq = _pick_block(block_q, DIAG_W)
    wk = _pick_block(block_k, DIAG_W)

    def _sub(sh, rows, cols, q, k, v, do, masked, q0, k0):
        """One (q-rows, k-cols) sub-tile of the dq math for sub-head sh;
        ``q0``/``k0`` are the tile's absolute start positions."""
        lse = lse_ref[sh]
        delta = delta_scr[sh]
        s = jax.lax.dot_general(
            q[rows], k[cols], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if masked:
            shape = (s.shape[0], s.shape[1])
            q_pos = q0 + jax.lax.broadcasted_iota(jnp.int32, shape, 0)
            k_pos = k0 + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[rows][:, :1])
        dp = jax.lax.dot_general(
            do[rows], v[cols], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[rows][:, :1]) * sm_scale).astype(k.dtype)
        dq_scr[sh, rows] += jax.lax.dot_general(
            ds, k[cols], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    def _block(masked):
        for sh in range(S):
            sl = slice(sh * d, (sh + 1) * d)
            _sub(sh, slice(None), slice(None), q_ref[0][:, sl],
                 k_ref[0][:, sl], v_ref[0][:, sl], do_ref[0][:, sl],
                 masked, j * block_q, kb * block_k)

    def _diag_block():
        # triangular straddling cell (same skip predicate as the forward):
        # sub-tiles entirely above the diagonal are never computed
        for sh in range(S):
            sl = slice(sh * d, (sh + 1) * d)
            q = q_ref[0][:, sl]
            k = k_ref[0][:, sl]
            v = v_ref[0][:, sl]
            do = do_ref[0][:, sl]
            for qs in range(block_q // wq):
                rows = slice(qs * wq, (qs + 1) * wq)
                for ks in range(block_k // wk):
                    cols = slice(ks * wk, (ks + 1) * wk)

                    def _go(masked, sh=sh, rows=rows, cols=cols, qs=qs,
                            ks=ks, q=q, k=k, v=v, do=do):
                        _sub(sh, rows, cols, q, k, v, do, masked,
                             j * block_q + qs * wq,
                             kb * block_k + ks * wk)

                    live = _diag_subtile_live(j, kb, qs, ks, block_q,
                                              block_k, wq, wk)
                    crossing = _diag_subtile_needs_mask(
                        j, kb, qs, ks, block_q, block_k, wq, wk)
                    pl.when(jnp.logical_and(live, crossing))(
                        lambda _g=_go: _g(True))
                    pl.when(jnp.logical_and(
                        live, jnp.logical_not(crossing)))(
                        lambda _g=_go: _g(False))

    if causal:
        unmasked = j * block_q >= (kb + 1) * block_k - 1
        on = kb <= last_kb
        pl.when(jnp.logical_and(on, unmasked))(lambda: _block(False))
        pl.when(jnp.logical_and(on, jnp.logical_not(unmasked)))(
            _diag_block)
    else:
        _block(False)

    @pl.when(kb == last_kb)
    def _finalize():
        if S == 1:
            dq_ref[0] = dq_scr[0].astype(dq_ref.dtype)
        else:
            dq_ref[0] = jnp.concatenate(
                [dq_scr[sh] for sh in range(S)], axis=-1
            ).astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, sm_scale, causal, block_q, block_k, nq,
                    has_dlse, sub_heads):
    """dk/dv: grid (bh, k-blocks, q-blocks), q innermost."""
    import jax.experimental.pallas as pl

    if has_dlse:
        (k_ref, v_ref, q_ref, do_ref, o_ref, lse_ref, dlse_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
    else:
        (k_ref, v_ref, q_ref, do_ref, o_ref, lse_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
        dlse_ref = None

    kb = pl.program_id(1)
    jq = pl.program_id(2)
    S = sub_heads
    d = q_ref.shape[-1] // S

    @pl.when(jq == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr[...])
        dv_scr[...] = jnp.zeros_like(dv_scr[...])

    wq = _pick_block(block_q, DIAG_W)
    wk = _pick_block(block_k, DIAG_W)

    def _delta(sh, rows, do, o):
        """delta = rowsum(do*o) for one sub-head's q rows — computed once
        per (sub-head, row group), NOT per k sub-tile."""
        d_row = jnp.sum(
            do[rows].astype(jnp.float32) * o[rows].astype(jnp.float32),
            axis=-1, keepdims=True)
        if dlse_ref is not None:
            d_row = d_row - dlse_ref[sh][rows][:, :1]
        return d_row

    def _sub(sh, rows, cols, k, v, q, do, delta, masked, q0, k0):
        """One (q-rows, k-cols) sub-tile of the dk/dv math: accumulates
        into the k-row slices of the scratch accumulators."""
        lse = lse_ref[sh]
        s = jax.lax.dot_general(
            q[rows], k[cols], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if masked:
            shape = (s.shape[0], s.shape[1])
            q_pos = q0 + jax.lax.broadcasted_iota(jnp.int32, shape, 0)
            k_pos = k0 + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[rows][:, :1])
        dv_scr[sh, cols] += jax.lax.dot_general(
            p.astype(do.dtype), do[rows], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do[rows], v[cols], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, :1]) * sm_scale).astype(q.dtype)
        dk_scr[sh, cols] += jax.lax.dot_general(
            ds, q[rows], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    def _views(sh):
        sl = slice(sh * d, (sh + 1) * d)
        return (k_ref[0][:, sl], v_ref[0][:, sl], q_ref[0][:, sl],
                do_ref[0][:, sl], o_ref[0][:, sl])

    def _block(masked):
        for sh in range(S):
            k, v, q, do, o = _views(sh)
            delta = _delta(sh, slice(None), do, o)
            _sub(sh, slice(None), slice(None), k, v, q, do, delta, masked,
                 jq * block_q, kb * block_k)

    def _diag_block():
        for sh in range(S):
            k, v, q, do, o = _views(sh)
            for qs in range(block_q // wq):
                rows = slice(qs * wq, (qs + 1) * wq)
                delta = _delta(sh, rows, do, o)
                for ks in range(block_k // wk):
                    cols = slice(ks * wk, (ks + 1) * wk)

                    def _go(masked, sh=sh, rows=rows, cols=cols, qs=qs,
                            ks=ks, k=k, v=v, q=q, do=do, delta=delta):
                        _sub(sh, rows, cols, k, v, q, do, delta, masked,
                             jq * block_q + qs * wq,
                             kb * block_k + ks * wk)

                    live = _diag_subtile_live(jq, kb, qs, ks, block_q,
                                              block_k, wq, wk)
                    crossing = _diag_subtile_needs_mask(
                        jq, kb, qs, ks, block_q, block_k, wq, wk)
                    pl.when(jnp.logical_and(live, crossing))(
                        lambda _g=_go: _g(True))
                    pl.when(jnp.logical_and(
                        live, jnp.logical_not(crossing)))(
                        lambda _g=_go: _g(False))

    if causal:
        # q block jq touches k block kb iff its last row is at/below the
        # block diagonal: (jq+1)*bq - 1 >= kb*bk
        on = jq >= (kb * block_k) // block_q
        unmasked = jq * block_q >= (kb + 1) * block_k - 1
        pl.when(jnp.logical_and(on, unmasked))(lambda: _block(False))
        pl.when(jnp.logical_and(on, jnp.logical_not(unmasked)))(
            _diag_block)
    else:
        _block(False)

    @pl.when(jq == nq - 1)
    def _finalize():
        if S == 1:
            dk_ref[0] = dk_scr[0].astype(dk_ref.dtype)
            dv_ref[0] = dv_scr[0].astype(dv_ref.dtype)
        else:
            dk_ref[0] = jnp.concatenate(
                [dk_scr[sh] for sh in range(S)], axis=-1
            ).astype(dk_ref.dtype)
            dv_ref[0] = jnp.concatenate(
                [dv_scr[sh] for sh in range(S)], axis=-1
            ).astype(dv_ref.dtype)


def _bwd_fused_kernel(*refs, sm_scale, causal, block_q, block_k, nq,
                      has_dlse, sub_heads):
    """Single-pass backward: grid (bh, k-blocks, q-blocks), q innermost.
    Computes the s/p tile ONCE per (k, q) block pair (the split dq + dkv
    kernels each recompute it — 7 block matmuls per pair vs 5 here) and
    emits dk/dv via VMEM accumulators plus dq as per-k-block partials
    ``dq_part[kb]`` that the caller reduces over kb.  Used when the
    partial buffer is small (nk grows with t; the split kernels remain
    the long-context path)."""
    import jax.experimental.pallas as pl

    if has_dlse:
        (k_ref, v_ref, q_ref, do_ref, o_ref, lse_ref, dlse_ref,
         dqp_ref, dk_ref, dv_ref, dk_scr, dv_scr, dqp_scr) = refs
    else:
        (k_ref, v_ref, q_ref, do_ref, o_ref, lse_ref,
         dqp_ref, dk_ref, dv_ref, dk_scr, dv_scr, dqp_scr) = refs
        dlse_ref = None

    kb = pl.program_id(1)
    jq = pl.program_id(2)
    S = sub_heads
    d = q_ref.shape[-1] // S
    wq = _pick_block(block_q, DIAG_W)
    wk = _pick_block(block_k, DIAG_W)

    @pl.when(jq == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr[...])
        dv_scr[...] = jnp.zeros_like(dv_scr[...])

    def _views(sh):
        sl = slice(sh * d, (sh + 1) * d)
        return (k_ref[0][:, sl], v_ref[0][:, sl], q_ref[0][:, sl],
                do_ref[0][:, sl], o_ref[0][:, sl])

    def _block(masked):
        dqps = []
        for sh in range(S):
            k, v, q, do, o = _views(sh)
            lse = lse_ref[sh]
            delta = jnp.sum(
                do.astype(jnp.float32) * o.astype(jnp.float32),
                axis=-1, keepdims=True)
            if dlse_ref is not None:
                delta = delta - dlse_ref[sh][:, :1]
            bq = q.shape[0]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * sm_scale
            if masked:
                q_pos = jq * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, block_k), 0)
                k_pos = kb * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, block_k), 1)
                s = jnp.where(q_pos >= k_pos, s, NEG_INF)
            p = jnp.exp(s - lse[:, :1])
            dv_scr[sh] += jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            ds = (p * (dp - delta[:, :1]) * sm_scale).astype(q.dtype)
            dk_scr[sh] += jax.lax.dot_general(
                ds, q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dqps.append(jax.lax.dot_general(
                ds, k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))
        dqp_ref[0, 0] = (
            dqps[0] if S == 1 else jnp.concatenate(dqps, axis=-1)
        ).astype(dqp_ref.dtype)

    def _diag_block():
        # triangular straddling cell: dq partials accumulate in the f32
        # dqp scratch across live sub-tiles (skipped sub-tiles leave
        # their zeros), then one store; dk/dv accumulate into the k-row
        # slices of their scratches exactly like the split kernel
        dqp_scr[...] = jnp.zeros_like(dqp_scr[...])
        for sh in range(S):
            k, v, q, do, o = _views(sh)
            for qs in range(block_q // wq):
                rows = slice(qs * wq, (qs + 1) * wq)
                # delta once per (sub-head, row group), not per k sub-tile
                delta0 = jnp.sum(
                    do[rows].astype(jnp.float32)
                    * o[rows].astype(jnp.float32),
                    axis=-1, keepdims=True)
                if dlse_ref is not None:
                    delta0 = delta0 - dlse_ref[sh][rows][:, :1]
                for ks in range(block_k // wk):
                    cols = slice(ks * wk, (ks + 1) * wk)

                    def _go(masked, sh=sh, rows=rows, cols=cols, qs=qs,
                            ks=ks, k=k, v=v, q=q, do=do, delta=delta0):
                        lse = lse_ref[sh]
                        s = jax.lax.dot_general(
                            q[rows], k[cols], (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
                        if masked:
                            shape = (s.shape[0], s.shape[1])
                            q_pos = (jq * block_q + qs * wq
                                     + jax.lax.broadcasted_iota(
                                         jnp.int32, shape, 0))
                            k_pos = (kb * block_k + ks * wk
                                     + jax.lax.broadcasted_iota(
                                         jnp.int32, shape, 1))
                            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
                        p = jnp.exp(s - lse[rows][:, :1])
                        dv_scr[sh, cols] += jax.lax.dot_general(
                            p.astype(do.dtype), do[rows],
                            (((0,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
                        dp = jax.lax.dot_general(
                            do[rows], v[cols], (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
                        ds = (p * (dp - delta[:, :1]) * sm_scale).astype(
                            q.dtype)
                        dk_scr[sh, cols] += jax.lax.dot_general(
                            ds, q[rows], (((0,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
                        dqp_scr[sh, rows] += jax.lax.dot_general(
                            ds, k[cols], (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)

                    live = _diag_subtile_live(jq, kb, qs, ks, block_q,
                                              block_k, wq, wk)
                    crossing = _diag_subtile_needs_mask(
                        jq, kb, qs, ks, block_q, block_k, wq, wk)
                    pl.when(jnp.logical_and(live, crossing))(
                        lambda _g=_go: _g(True))
                    pl.when(jnp.logical_and(
                        live, jnp.logical_not(crossing)))(
                        lambda _g=_go: _g(False))
        dqp_ref[0, 0] = (
            dqp_scr[0] if S == 1 else jnp.concatenate(
                [dqp_scr[sh] for sh in range(S)], axis=-1)
        ).astype(dqp_ref.dtype)

    if causal:
        on = jq >= (kb * block_k) // block_q
        unmasked = jq * block_q >= (kb + 1) * block_k - 1
        pl.when(jnp.logical_and(on, unmasked))(lambda: _block(False))
        pl.when(jnp.logical_and(on, jnp.logical_not(unmasked)))(
            _diag_block)

        # skipped cells still own their dq_part block — zero it so the
        # caller's reduce over kb sees no garbage
        @pl.when(jnp.logical_not(on))
        def _zero():
            dqp_ref[0, 0] = jnp.zeros_like(dqp_ref[0, 0])
    else:
        _block(False)

    @pl.when(jq == nq - 1)
    def _finalize():
        if S == 1:
            dk_ref[0] = dk_scr[0].astype(dk_ref.dtype)
            dv_ref[0] = dv_scr[0].astype(dv_ref.dtype)
        else:
            dk_ref[0] = jnp.concatenate(
                [dk_scr[sh] for sh in range(S)], axis=-1
            ).astype(dk_ref.dtype)
            dv_ref[0] = jnp.concatenate(
                [dv_scr[sh] for sh in range(S)], axis=-1
            ).astype(dv_ref.dtype)


# fused-backward dq partials budget: [nk, bh, t, d] must stay under this
# (past it — long t — the split dq/dkv kernels take over)
FUSED_BWD_PARTIAL_BYTES = 512 << 20


def _flash_bwd_fused(q, k, v, o, lse, do, sm_scale, causal, block_q,
                     block_k, interpret, dlse=None, n_head=None,
                     sub_heads=1):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    S = sub_heads
    bh, t_q, t_k, width, qix, kix = _packed_geom(q, k, n_head, S)
    d_sub = width // S
    block_q = _pick_block(t_q, block_q)
    block_k = _pick_block(t_k, block_k)
    nq = t_q // block_q
    nk = t_k // block_k
    has_dlse = dlse is not None

    kspec = pl.BlockSpec((1, block_k, width), lambda i, kb, jq: kix(i, kb))
    qspec = pl.BlockSpec((1, block_q, width), lambda i, kb, jq: qix(i, jq))
    qstat = pl.BlockSpec((S, block_q, 1), lambda i, kb, jq: (i, jq, 0))
    in_specs = [kspec, kspec, qspec, qspec, qspec, qstat]
    args = [k, v, q, do, o, lse]
    if has_dlse:
        in_specs.append(qstat)
        args.append(dlse)
    if n_head is None:
        dqp_spec = pl.BlockSpec((1, 1, block_q, width),
                                lambda i, kb, jq: (kb, i, jq, 0))
        dqp_shape = jax.ShapeDtypeStruct((nk, bh, t_q, width), q.dtype)
    else:
        n_slices = n_head // S
        dqp_spec = pl.BlockSpec(
            (1, 1, block_q, width),
            lambda i, kb, jq: (kb, i // n_slices, jq, i % n_slices))
        dqp_shape = jax.ShapeDtypeStruct((nk,) + q.shape, q.dtype)
    dq_part, dk, dv = pl.pallas_call(
        functools.partial(_bwd_fused_kernel, sm_scale=sm_scale,
                          causal=causal, block_q=block_q, block_k=block_k,
                          nq=nq, has_dlse=has_dlse, sub_heads=S),
        grid=(bh, nk, nq),
        in_specs=in_specs,
        out_specs=[dqp_spec, kspec, kspec],
        out_shape=[
            dqp_shape,
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((S, block_k, d_sub), jnp.float32),
                        pltpu.VMEM((S, block_k, d_sub), jnp.float32),
                        pltpu.VMEM((S, block_q, d_sub), jnp.float32)],
        interpret=interpret,
    )(*args)
    dq = jnp.sum(dq_part.astype(jnp.float32), axis=0).astype(q.dtype)
    return dq, dk, dv


def _flash_bwd(q, k, v, o, lse, do, sm_scale, causal, block_q, block_k,
               interpret, dlse=None, n_head=None, sub_heads=1):
    """Pallas backward.  Short/medium t: one fused kernel (s recomputed
    once per block pair, dq as per-k-block partials).  Long t (partials
    over budget): dq kernel (q-major) + dk/dv kernel (k-major), both with
    causal block skip; O(block^2) VMEM.  ``lse`` and the optional ``dlse``
    (the cotangent of the returned lse, for callers that consume it —
    ring-attention merges) arrive in the narrow [b*h, t_q, 1] residual
    layout in ALL q/k/v layouts (packed mode keeps lse row-major by
    (b, h) — see the forward's lse note)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    S = sub_heads
    bh, t_q, t_k, width, qix, kix = _packed_geom(q, k, n_head, S)
    d_sub = width // S
    block_q = _pick_block(t_q, block_q)
    block_k = _pick_block(t_k, block_k)
    nq = t_q // block_q
    nk = t_k // block_k
    has_dlse = dlse is not None

    part_bytes = nk * bh * t_q * width * q.dtype.itemsize
    if part_bytes <= FUSED_BWD_PARTIAL_BYTES:
        return _flash_bwd_fused(q, k, v, o, lse, do, sm_scale, causal,
                                block_q, block_k, interpret, dlse=dlse,
                                n_head=n_head, sub_heads=S)

    qspec = pl.BlockSpec((1, block_q, width), lambda i, j, kb: qix(i, j))
    kspec = pl.BlockSpec((1, block_k, width), lambda i, j, kb: kix(i, kb))
    qstat = pl.BlockSpec((S, block_q, 1), lambda i, j, kb: (i, j, 0))
    dq_in_specs = [qspec, kspec, kspec, qspec, qspec, qstat]
    dq_args = [q, k, v, do, o, lse]
    if has_dlse:
        dq_in_specs.append(qstat)
        dq_args.append(dlse)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, nk=nk,
                          has_dlse=has_dlse, sub_heads=S),
        grid=(bh, nq, nk),
        in_specs=dq_in_specs,
        out_specs=[qspec],
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype)],
        scratch_shapes=[pltpu.VMEM((S, block_q, d_sub), jnp.float32),
                        pltpu.VMEM((S, block_q, LSE_LANES), jnp.float32)],
        interpret=interpret,
    )(*dq_args)[0]

    kspec2 = pl.BlockSpec((1, block_k, width), lambda i, kb, jq: kix(i, kb))
    qspec2 = pl.BlockSpec((1, block_q, width), lambda i, kb, jq: qix(i, jq))
    qstat2 = pl.BlockSpec((S, block_q, 1), lambda i, kb, jq: (i, jq, 0))
    dkv_in_specs = [kspec2, kspec2, qspec2, qspec2, qspec2, qstat2]
    dkv_args = [k, v, q, do, o, lse]
    if has_dlse:
        dkv_in_specs.append(qstat2)
        dkv_args.append(dlse)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale,
                          causal=causal, block_q=block_q, block_k=block_k,
                          nq=nq, has_dlse=has_dlse, sub_heads=S),
        grid=(bh, nk, nq),
        in_specs=dkv_in_specs,
        out_specs=[kspec2, kspec2],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((S, block_k, d_sub), jnp.float32),
                        pltpu.VMEM((S, block_k, d_sub), jnp.float32)],
        interpret=interpret,
    )(*dkv_args)
    return dq, dk, dv


def _sub_heads_for(n_head, q):
    """The sub_heads (S) the kernels run for this call: the geometry
    decision of ``packed_sub_heads``, with unsupported widths falling back
    to S=1 (reachable only in interpret mode — the public API rejects
    them on hardware)."""
    if n_head is None:
        return 1
    d = q.shape[-1] // n_head
    return packed_sub_heads(n_head, d) or 1


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_core(q, k, v, sm_scale, causal, block_q, block_k, interpret,
                n_head=None):
    o, _ = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret,
                      n_head=n_head, sub_heads=_sub_heads_for(n_head, q))
    return o


def _flash_core_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret,
                    n_head=None):
    o, lse = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k,
                        interpret, n_head=n_head,
                        sub_heads=_sub_heads_for(n_head, q))
    # FLASH_BWD_RESIDUALS contract: tag the kernel-owned residuals (o is
    # ALSO the primal output — one tagged value, saved once) so a
    # name-policy checkpoint (memory_optimize(policy="offload")) keeps
    # them instead of re-running the forward kernel in the backward pass.
    # Outside a name-policy region the tag is an identity no-op.
    o = checkpoint_name(o, KERNEL_RESIDUAL_TAG)
    lse = checkpoint_name(lse, KERNEL_RESIDUAL_TAG)
    return o, (q, k, v, o, lse)


def _flash_core_bwd(sm_scale, causal, block_q, block_k, interpret, n_head,
                    res, do):
    q, k, v, o, lse = res
    return _flash_bwd(q, k, v, o, lse[:, :, None], do, sm_scale, causal,
                      block_q, block_k, interpret, n_head=n_head,
                      sub_heads=_sub_heads_for(n_head, q))


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def _resolve_backend(backend):
    """One selection path for every flash entry point: the kernel
    registry's resolution (explicit arg > per-op env > global env >
    auto; docs/kernels.md).  Returns ``(name, impl)``.  The old ad-hoc
    per-platform fallback — ``interpret = jax.default_backend() !=
    "tpu"`` at each call site — is now the ``pallas_tpu`` backend's own
    interpret default behind this path."""
    from ..kernels import resolve  # late: kernels imports this module

    kernel = resolve("flash_attention", backend)
    return kernel.backend, kernel.impl


def _pallas_flash_attention(q, k, v, causal=False, sm_scale=None,
                            block_q=1024, block_k=1024, interpret=None):
    """The Mosaic (``pallas_tpu``) flash attention: q [b, t_q, h, d],
    k/v [b, t_k, h, d] -> [b, t_q, h, d].  Differentiable (custom VJP).
    ``interpret=None`` auto-selects Pallas interpreter mode off-TPU so
    the same kernel logic runs in CPU tests."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, t_q, h, d = q.shape
    t_k = k.shape[1]
    sm_scale = d ** -0.5 if sm_scale is None else sm_scale

    def pack(x, t):
        return jnp.swapaxes(x, 1, 2).reshape(b * h, t, x.shape[-1])

    o = _flash_core(
        pack(q, t_q), pack(k, t_k), pack(v, t_k),
        float(sm_scale), bool(causal), int(block_q), int(block_k),
        bool(interpret), None,
    )
    return jnp.swapaxes(o.reshape(b, h, t_q, d), 1, 2)


def flash_attention(q, k, v, causal=False, sm_scale=None, block_q=1024,
                    block_k=1024, interpret=None, backend=None):
    """Fused attention, routed through the kernel registry
    (docs/kernels.md): ``backend`` picks pallas_tpu | triton | xla_ref
    explicitly, None resolves env overrides then the platform's auto
    order.  q [b, t_q, h, d], k/v [b, t_k, h, d] -> [b, t_q, h, d];
    differentiable through every backend (each carries the same
    custom-VJP residual contract)."""
    name, impl = _resolve_backend(backend)
    if name == "pallas_tpu":
        return _pallas_flash_attention(q, k, v, causal=causal,
                                       sm_scale=sm_scale, block_q=block_q,
                                       block_k=block_k,
                                       interpret=interpret)
    return impl.call(q, k, v, causal=causal, sm_scale=sm_scale,
                     block_q=block_q, block_k=block_k,
                     interpret=interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_core_lse(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    o, lse = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k,
                        interpret)
    return o, lse


def _flash_core_lse_fwd(q, k, v, sm_scale, causal, block_q, block_k,
                        interpret):
    o, lse = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k,
                        interpret)
    # same FLASH_BWD_RESIDUALS tagging as _flash_core_fwd (o and lse are
    # both primal outputs here — still one tagged value each)
    o = checkpoint_name(o, KERNEL_RESIDUAL_TAG)
    lse = checkpoint_name(lse, KERNEL_RESIDUAL_TAG)
    return (o, lse), (q, k, v, o, lse)


def _flash_core_lse_bwd(sm_scale, causal, block_q, block_k, interpret,
                        res, cts):
    q, k, v, o, lse = res
    do, dlse = cts
    return _flash_bwd(q, k, v, o, lse[:, :, None], do, sm_scale, causal,
                      block_q, block_k, interpret,
                      dlse=dlse.astype(jnp.float32)[:, :, None])


_flash_core_lse.defvjp(_flash_core_lse_fwd, _flash_core_lse_bwd)


def flash_attention_with_lse(q, k, v, causal=False, sm_scale=None,
                             block_q=1024, block_k=1024, interpret=None,
                             backend=None):
    """flash_attention that ALSO returns the per-row logsumexp
    (o [b, t, h, d], lse [b, h, t]) — the building block for composing
    partial attentions with online-softmax merges (ring attention).
    Fully differentiable including through lse; registry-routed like
    ``flash_attention``."""
    name, impl = _resolve_backend(backend)
    if name != "pallas_tpu":
        return impl.call_with_lse(q, k, v, causal=causal,
                                  sm_scale=sm_scale, block_q=block_q,
                                  block_k=block_k, interpret=interpret)
    return _pallas_flash_attention_with_lse(
        q, k, v, causal=causal, sm_scale=sm_scale, block_q=block_q,
        block_k=block_k, interpret=interpret)


def _pallas_flash_attention_with_lse(q, k, v, causal=False, sm_scale=None,
                                     block_q=1024, block_k=1024,
                                     interpret=None):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, t_q, h, d = q.shape
    t_k = k.shape[1]
    sm_scale = d ** -0.5 if sm_scale is None else sm_scale

    def pack(x, t):
        return jnp.swapaxes(x, 1, 2).reshape(b * h, t, x.shape[-1])

    o, lse = _flash_core_lse(
        pack(q, t_q), pack(k, t_k), pack(v, t_k),
        float(sm_scale), bool(causal), int(block_q), int(block_k),
        bool(interpret),
    )
    return (jnp.swapaxes(o.reshape(b, h, t_q, d), 1, 2),
            lse.reshape(b, h, t_q))



def flash_attention_packed(q, k, v, n_head, causal=False, sm_scale=None,
                           block_q=1024, block_k=1024, interpret=None,
                           backend=None):
    """Fused attention on the RAW projection layout: q/k/v [b, t, h*d]
    (heads concatenated in the feature dim, exactly what the QKV matmuls
    emit) -> o [b, t, h*d] (exactly what the output projection consumes).

    Numerically identical to ``flash_attention`` on the reshaped 4-D view,
    but the [b,t,h,d]<->[b*h,t,d] pack/unpack transposes — 23 ms/step on
    the GPT flagship, 8% of device time (RESULTS.md round 4) — never
    exist: each 128-lane slice is selected by the kernels' block index
    maps.  Supported geometries (``packed_sub_heads``): ``d_head % 128 ==
    0`` (one head per slice), ``d_head == 64`` with even ``n_head`` (TWO
    heads per slice — the kernels run two independent softmax states over
    the 64-lane halves, so d_head-64 models dodge the transpose tax too),
    or ``n_head == 1``.  Other widths raise; callers use
    ``flash_attention``.  Registry-routed: the triton/xla_ref backends
    are shape-complete here (their head split is a reshape, not a
    Mosaic lane slice), so every head width works off the TPU path."""
    name, impl = _resolve_backend(backend)
    if name != "pallas_tpu":
        return impl.call_packed(q, k, v, n_head, causal=causal,
                                sm_scale=sm_scale, block_q=block_q,
                                block_k=block_k, interpret=interpret)
    return _pallas_flash_attention_packed(
        q, k, v, n_head, causal=causal, sm_scale=sm_scale,
        block_q=block_q, block_k=block_k, interpret=interpret)


def _pallas_flash_attention_packed(q, k, v, n_head, causal=False,
                                   sm_scale=None, block_q=1024,
                                   block_k=1024, interpret=None):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, t_q, hd = q.shape
    if hd % n_head:
        raise ValueError(f"feature dim {hd} not divisible by n_head {n_head}")
    d = hd // n_head
    if packed_sub_heads(n_head, d) is None and not interpret:
        # interpret mode has no Mosaic tiling rules — CPU tests exercise
        # small head widths through the identical code path
        raise ValueError(
            f"flash_attention_packed needs d_head % 128 == 0 or d_head == "
            f"64 with even n_head (lane-aligned or paired head slices), "
            f"got d_head={d}, n_head={n_head}; use flash_attention for "
            f"other head widths")
    sm_scale = d ** -0.5 if sm_scale is None else sm_scale
    return _flash_core(
        q, k, v, float(sm_scale), bool(causal), int(block_q), int(block_k),
        bool(interpret), int(n_head))


def attention_reference(q, k, v, causal=False, sm_scale=None):
    """Dense reference implementation (for tests and tiny shapes)."""
    d = q.shape[-1]
    sm_scale = d ** -0.5 if sm_scale is None else sm_scale
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * sm_scale
    if causal:
        t_q, t_k = logits.shape[-2:]
        mask = jnp.arange(t_q)[:, None] >= jnp.arange(t_k)[None, :]
        logits = jnp.where(mask, logits, NEG_INF)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


# -- op registration ---------------------------------------------------------
from ..core.registry import register_op


@register_op("flash_attention")
def flash_attention_op(Q, K, V, causal=False, sm_scale=0.0, block_q=1024,
                       block_k=1024, backend="", **_):
    scale = None if not sm_scale else float(sm_scale)
    return {"Out": flash_attention(Q, K, V, causal=causal, sm_scale=scale,
                                   block_q=int(block_q),
                                   block_k=int(block_k),
                                   backend=backend or None)}


def _tp_axis(_ctx):
    """(mesh, tp_size) when the executor runs under a mesh with a 'tp'
    axis — the signal for the ops to enter their shard_map paths."""
    mesh = getattr(getattr(_ctx, "executor", None), "mesh", None)
    if mesh is None or "tp" not in mesh.axis_names:
        return None, 1
    return mesh, int(mesh.shape["tp"])


@register_op("flash_attention_packed")
def flash_attention_packed_op(Q, K, V, n_head=None, causal=False,
                              sm_scale=0.0, block_q=1024, block_k=1024,
                              backend="", _ctx=None, **_):
    if n_head is None:
        # no safe default: 1 would silently softmax across the whole
        # concatenated h*d feature dim as a single head
        raise ValueError("flash_attention_packed op requires the n_head attr")
    n_head = int(n_head)
    block_q, block_k = int(block_q), int(block_k)
    scale = None if not sm_scale else float(sm_scale)
    backend = backend or None
    mesh, tp = _tp_axis(_ctx)
    if tp > 1 and n_head % tp == 0:
        # Head-sharded tensor parallelism: the packed feature dim IS the
        # head dim, so a 'tp' shard of [b, t, h*d] holds h/tp whole
        # heads and attention needs NO cross-shard communication — each
        # shard runs the kernel on its local heads (the shard_map-over-
        # heads recipe; GSPMD cannot partition an opaque custom call, so
        # without this it would all-gather the tp-sharded activations).
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        db = "dp" if "dp" in mesh.axis_names else None
        spec = P(db, None, "tp")
        local_heads = n_head // tp
        d_head = Q.shape[-1] // n_head

        def local(q, k, v):
            if packed_sub_heads(local_heads, d_head) is None:
                # the GLOBAL head count packs but the per-shard count
                # does not (e.g. d_head=64, n_head=6, tp=2 -> 3 local
                # heads can't pair): run the shard through the 4-D
                # kernel — transposes on the local shard beat a trace
                # error
                b, t, hd = q.shape
                r4 = lambda x: x.reshape(b, t, local_heads, d_head)
                o = flash_attention(
                    r4(q), r4(k), r4(v), causal=causal, sm_scale=scale,
                    block_q=block_q, block_k=block_k, backend=backend)
                return o.reshape(b, t, hd)
            return flash_attention_packed(
                q, k, v, local_heads, causal=causal, sm_scale=scale,
                block_q=block_q, block_k=block_k, backend=backend)

        out = shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                        out_specs=spec, check_rep=False)(Q, K, V)
        return {"Out": out}
    return {"Out": flash_attention_packed(
        Q, K, V, n_head, causal=causal, sm_scale=scale,
        block_q=block_q, block_k=block_k, backend=backend)}


# -- kernel-registry registration (docs/kernels.md) --------------------------
# The Mosaic kernels above ARE the "pallas_tpu" backend of the
# flash_attention op class: native on TPU, interpret mode off-TPU (the
# CPU test path — the availability reason annotates it).
from ..kernels.registry import (
    pallas_tpu_availability as _pallas_tpu_availability,
    register_kernel as _register_kernel)


class _FlashPallasTpu:
    call = staticmethod(_pallas_flash_attention)
    call_with_lse = staticmethod(_pallas_flash_attention_with_lse)
    call_packed = staticmethod(_pallas_flash_attention_packed)


_register_kernel("flash_attention", "pallas_tpu", _FlashPallasTpu,
                 available=_pallas_tpu_availability)
