"""Beam-level training: cross_entropy_over_beam.

Reference: ``paddle/gserver/layers/CrossEntropyOverBeam.{h,cpp}`` (DSL
surface ``trainer_config_helpers/layers.py cross_entropy_over_beam`` /
``BeamInput``) — the learning-to-search cost.  Per sequence, a beam
search produced E expansions; expansion ``e`` holds the candidate
scores of every surviving prefix (rows), the top-k candidate ids
selected per row (-1 padded), and the gold candidate id.  The cost
enumerates every complete candidate path through the expansions, sums
the selected scores along each path, softmaxes over all paths, and
takes the NLL of the gold path; if the gold falls off the beam at step
t, the cost is computed over the beam at step t with the gold path
appended as an extra candidate (``CrossEntropyOverBeam.cpp:19-163``).

The reference constructs paths with per-sequence dynamic loops on CPU
("the process of constructing beams is not friendly to GPU" —
CrossEntropyOverBeam.h:110).  The TPU version is static-shape: each
expansion is dense-padded (scores [b, R, L], ids [b, R, B], gold [b]);
rows of expansion e+1 correspond to the valid (non -1) candidates of
expansion e in row-major order; the fall-off step is data-dependent,
handled by ``lax.switch`` over the E possible stopping points; the path
table is the full R*B slot grid (+1 gold-extra slot) with invalid slots
masked to -inf inside the softmax.  Everything is gathers and masks, so
the whole cost is differentiable and jits."""

import jax
import jax.numpy as jnp

from ..core.registry import register_op


def _as_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _one_sample_loss(scores, ids, gold):
    """Loss for ONE sequence.  scores[e] [R_e, L_e] f32, ids[e] [R_e, B_e]
    int32 (-1 padded), gold[e] scalar int32.  Returns scalar f32."""
    E = len(ids)

    # --- track the gold prefix through the expansions -------------------
    # goldRow[e]: which row of expansion e the gold prefix sits in (= the
    # rank of the gold candidate among the valid candidates of e-1);
    # goldCol[e]: the gold id's position within that row's beam, -1 when
    # the gold fell off (reference calValidExpandStep).
    gold_rows, gold_cols = [], []
    gold_row = jnp.int32(0)
    all_found = jnp.bool_(True)
    valid_cnt = jnp.int32(0)
    for e in range(E):
        if e:
            prev = ids[e - 1].reshape(-1)
            upto = (gold_rows[e - 1] * ids[e - 1].shape[1]
                    + gold_cols[e - 1])
            pos = jnp.arange(prev.shape[0])
            gold_row = jnp.sum(
                ((prev != -1) & (pos < upto)).astype(jnp.int32))
        row_vals = ids[e][jnp.clip(gold_row, 0, ids[e].shape[0] - 1)]
        match = row_vals == gold[e]
        found = jnp.any(match)
        gold_col = jnp.where(found, jnp.argmax(match).astype(jnp.int32),
                             jnp.int32(-1))
        gold_rows.append(gold_row)
        gold_cols.append(gold_col)
        # the step where the gold falls off still counts as a valid
        # expansion (the cost is computed over the beam at that step)
        valid_cnt = jnp.where(all_found, jnp.int32(e + 1), valid_cnt)
        all_found = jnp.logical_and(all_found, found)

    gold_rows = jnp.stack(gold_rows)
    gold_cols = jnp.stack(gold_cols)

    def make_branch(t):
        # cost over the beam at (static) expansion t
        def branch(_):
            R, B = ids[t].shape
            P = R * B
            flat = ids[t].reshape(-1)
            validm = flat != -1
            rowidx = jnp.arange(P) // B
            Lt = scores[t].shape[1]
            path_score = scores[t][rowidx, jnp.clip(flat, 0, Lt - 1)]
            path_score = jnp.where(validm, path_score, 0.0)
            parent = rowidx
            # backtrack: row r of expansion e+1 is the r-th valid
            # candidate of expansion e (row-major)
            for e in range(t - 1, -1, -1):
                Re, Be = ids[e].shape
                flat_e = ids[e].reshape(-1)
                vm_e = flat_e != -1
                rank = jnp.cumsum(vm_e.astype(jnp.int32)) - 1
                rows_next = ids[e + 1].shape[0]
                origin = jnp.zeros((rows_next,), jnp.int32)
                origin = origin.at[
                    jnp.where(vm_e, rank, rows_next)
                ].set(jnp.arange(Re * Be, dtype=jnp.int32), mode="drop")
                slot = origin[jnp.clip(parent, 0, rows_next - 1)]
                row_e = slot // Be
                id_e = flat_e[slot]
                Le = scores[e].shape[1]
                path_score = path_score + jnp.where(
                    validm,
                    scores[e][row_e, jnp.clip(id_e, 0, Le - 1)], 0.0)
                parent = row_e

            # the gold path: one of the real slots when it survived, an
            # appended extra path when it fell off at step t
            fell = gold_cols[t] == -1
            gold_extra = jnp.float32(0.0)
            for e in range(t + 1):
                Le = scores[e].shape[1]
                gold_extra = gold_extra + scores[e][
                    jnp.clip(gold_rows[e], 0, scores[e].shape[0] - 1),
                    jnp.clip(gold[e], 0, Le - 1)]
            total = jnp.where(validm, path_score, -jnp.inf)
            total = jnp.concatenate(
                [total, jnp.where(fell, gold_extra, -jnp.inf)[None]])
            gold_pos = jnp.where(
                fell, jnp.int32(P), gold_rows[t] * B + gold_cols[t])
            return (jax.scipy.special.logsumexp(total)
                    - total[gold_pos]).astype(jnp.float32)

        return branch

    t_idx = jnp.clip(valid_cnt - 1, 0, E - 1)
    return jax.lax.switch(t_idx, [make_branch(t) for t in range(E)],
                          jnp.float32(0.0))


def cross_entropy_over_beam_fn(scores, ids, gold):
    """Batched beam cross entropy.  scores: list of E arrays [b, R_e, L_e];
    ids: list of E int arrays [b, R_e, B_e] (-1 padded); gold: list of E
    int arrays [b].  Returns [b] f32 losses."""
    scores = [s.astype(jnp.float32) for s in _as_list(scores)]
    ids = [(i[:, None, :] if i.ndim == 2 else i).astype(jnp.int32)
           for i in _as_list(ids)]
    gold = [g.reshape(g.shape[0]).astype(jnp.int32)
            for g in _as_list(gold)]
    return jax.vmap(_one_sample_loss)(scores, ids, gold)


@register_op("cross_entropy_over_beam")
def cross_entropy_over_beam_op(Scores, Ids, Gold, **_):
    scores = _as_list(Scores)
    ids3 = []
    for i in _as_list(Ids):
        if i.ndim == 2:  # [b, B] single-row expansion
            i = i[:, None, :]
        ids3.append(i)
    scores3 = []
    for s in scores:
        if s.ndim == 2:
            s = s[:, None, :]
        scores3.append(s)
    loss = cross_entropy_over_beam_fn(scores3, ids3, _as_list(Gold))
    return {"Out": loss[:, None]}
