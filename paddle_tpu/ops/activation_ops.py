"""Activation ops.

Reference: ``paddle/operators/activation_op.{cc,cu}`` — ~20 activations via
functor templates, each with a hand-written gradient functor.  Here each is
one jnp expression; gradients come from JAX AD and XLA fuses them into
neighbouring matmuls (the reference needed separate kernel launches).
"""

import jax
import jax.numpy as jnp

from ..core.registry import register_op


def _simple(name, fn):
    @register_op(name)
    def _op(X, **attrs):
        return {"Out": fn(X, **{k: v for k, v in attrs.items() if not k.startswith("_")})}

    _op.__name__ = name
    return _op


_simple("sigmoid", lambda X: jax.nn.sigmoid(X))
_simple("logsigmoid", lambda X: jax.nn.log_sigmoid(X))
_simple("exp", lambda X: jnp.exp(X))
_simple("relu", lambda X: jax.nn.relu(X))
_simple("tanh", lambda X: jnp.tanh(X))
_simple("tanh_shrink", lambda X: X - jnp.tanh(X))
_simple("sqrt", lambda X: jnp.sqrt(X))
_simple("abs", lambda X: jnp.abs(X))
_simple("ceil", lambda X: jnp.ceil(X))
_simple("floor", lambda X: jnp.floor(X))
_simple("round", lambda X: jnp.round(X))
_simple("reciprocal", lambda X: 1.0 / X)
_simple("log", lambda X: jnp.log(X))
_simple("square", lambda X: jnp.square(X))
_simple("softplus", lambda X: jax.nn.softplus(X))
# exact (erf) gelu, not the tanh approximation: the tanh form's backward
# is not reassociation-stable between unrolled and lax.scan execution on
# XLA:CPU (measured 1e-3-level grad drift), which would break the
# scan-remat engine's bit-exactness contract; erf is stable and matches
# the op test's own erf reference more closely anyway
_simple("gelu", lambda X: jax.nn.gelu(X, approximate=False))
_simple("softsign", lambda X: X / (1 + jnp.abs(X)))


@register_op("prelu")
def prelu(X, Alpha, **_):
    # reference prelu_op.cc:46: f(x) = alpha*x for x<0 else x; Alpha is a
    # learnable scalar (the reference op takes exactly one alpha; a
    # channel-wise variant would need explicit axis alignment, so reject
    # silently-misbroadcast shapes).
    if Alpha.size != 1:
        raise ValueError(
            f"prelu Alpha must be a single scalar, got shape {Alpha.shape}")
    return {"Out": jnp.where(X >= 0, X, Alpha.reshape(()) * X)}


@register_op("brelu")
def brelu(X, t_min=0.0, t_max=24.0, **_):
    return {"Out": jnp.clip(X, t_min, t_max)}


@register_op("leaky_relu")
def leaky_relu(X, alpha=0.02, **_):
    return {"Out": jnp.where(X > 0, X, alpha * X)}


@register_op("soft_relu")
def soft_relu(X, threshold=40.0, **_):
    t = jnp.clip(X, -threshold, threshold)
    return {"Out": jnp.log1p(jnp.exp(t))}


@register_op("elu")
def elu(X, alpha=1.0, **_):
    return {"Out": jax.nn.elu(X, alpha)}


@register_op("relu6")
def relu6(X, threshold=6.0, **_):
    return {"Out": jnp.clip(X, 0.0, threshold)}


@register_op("pow")
def pow_op(X, factor=1.0, **_):
    return {"Out": jnp.power(X, factor)}


@register_op("stanh")
def stanh(X, scale_a=2.0 / 3.0, scale_b=1.7159, **_):
    return {"Out": scale_b * jnp.tanh(scale_a * X)}


@register_op("hard_shrink")
def hard_shrink(X, threshold=0.5, **_):
    return {"Out": jnp.where(jnp.abs(X) > threshold, X, 0.0)}


@register_op("softshrink")
def softshrink(X, lambda_=0.5, **attrs):
    lam = attrs.get("lambda", lambda_)
    return {"Out": jnp.where(X > lam, X - lam, jnp.where(X < -lam, X + lam, 0.0))}


@register_op("thresholded_relu")
def thresholded_relu(X, threshold=1.0, **_):
    return {"Out": jnp.where(X > threshold, X, 0.0)}


@register_op("hard_sigmoid")
def hard_sigmoid(X, slope=0.2, offset=0.5, **_):
    return {"Out": jnp.clip(slope * X + offset, 0.0, 1.0)}


@register_op("swish")
def swish(X, beta=1.0, **_):
    return {"Out": X * jax.nn.sigmoid(beta * X)}


@register_op("softmax")
def softmax(X, **_):
    return {"Out": jax.nn.softmax(X, axis=-1)}


@register_op("log_softmax")
def log_softmax(X, **_):
    return {"Out": jax.nn.log_softmax(X, axis=-1)}
