"""IO-adjacent ops.

Reference: ``save_op.cc`` / ``load_op.cc`` serialize tensors from inside the
program; ``feed_op`` / ``fetch_op`` bridge the feed/fetch variables
(``feed_fetch_method.h``).  Host IO cannot live inside a compiled TPU
program, so save/load are *host-side* operations on the Scope (see
``paddle_tpu.io``); the ops below exist for program-parity and raise if a
program containing them is actually lowered — save_inference_model prunes
them out, matching the reference's inference_optimize flow.
"""

from ..core.registry import register_op


@register_op("save", raw=True)
def save(ctx, block, op, env):
    raise RuntimeError(
        "save_op cannot run inside a compiled program on TPU; use "
        "paddle_tpu.io.save_persistables/save_vars (host-side)"
    )


@register_op("load", raw=True)
def load(ctx, block, op, env):
    raise RuntimeError(
        "load_op cannot run inside a compiled program on TPU; use "
        "paddle_tpu.io.load_persistables (host-side)"
    )
