"""Conv / pool / normalization ops.

Reference: ``conv_op`` (im2col+gemm, ``conv_cudnn_op.cu.cc``), ``pool_op``,
``batch_norm_op`` (+cudnn), ``lrn_op``, ``spp_op``, ``unpool_op``,
``row_conv_op`` (DeepSpeech lookahead), ``im2sequence_op``.  On TPU a conv is
one ``lax.conv_general_dilated`` — XLA tiles it onto the MXU directly; the
whole im2col/cuDNN-algorithm-selection machinery disappears.  Layout stays
NCHW at the API (reference convention); XLA relayouts internally as needed.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op

_CONV_DN = ("NCHW", "OIHW", "NCHW")


# NOTE: no preferred_element_type on convs — jax's conv transpose (grad)
# rule mis-types the cotangent when output dtype != input dtype, and the TPU
# MXU accumulates bf16 convs in float32 natively anyway.


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v,) * n


@register_op("conv2d")
def conv2d(Input, Filter, strides=(1, 1), paddings=(0, 0), dilations=(1, 1), groups=1, **_):
    s, p, d = _pair(strides), _pair(paddings), _pair(dilations)
    out = jax.lax.conv_general_dilated(
        Input,
        Filter.astype(Input.dtype),
        window_strides=s,
        padding=[(p[0], p[0]), (p[1], p[1])],
        rhs_dilation=d,
        dimension_numbers=_CONV_DN,
        feature_group_count=groups,
    )
    return {"Output": out.astype(Input.dtype)}


@register_op("depthwise_conv2d")
def depthwise_conv2d(Input, Filter, strides=(1, 1), paddings=(0, 0), dilations=(1, 1), groups=None, **_):
    g = groups or Input.shape[1]
    return conv2d(
        Input=Input, Filter=Filter, strides=strides, paddings=paddings,
        dilations=dilations, groups=g,
    )


def _conv_transpose_nd(Input, Filter, strides, paddings, dilations, nd, dn):
    """Fractionally-strided conv: lhs_dilation by stride + spatially-flipped
    kernel, the gradient-of-conv formulation (reference
    conv_transpose_op.cc).  Filter layout is (C_in, C_out, *spatial)."""
    s = _pair(strides, nd)
    p = _pair(paddings, nd)
    d = _pair(dilations, nd)
    flip = (slice(None), slice(None)) + (slice(None, None, -1),) * nd
    w = jnp.swapaxes(Filter.astype(Input.dtype), 0, 1)[flip]
    # transpose-conv implicit padding on the dilated kernel extent
    pads = [(d[i] * (w.shape[2 + i] - 1) - p[i],) * 2 for i in range(nd)]
    out = jax.lax.conv_general_dilated(
        Input,
        w,
        window_strides=(1,) * nd,
        padding=pads,
        lhs_dilation=s,
        rhs_dilation=d,
        dimension_numbers=dn,
    )
    return out.astype(Input.dtype)


@register_op("conv2d_transpose")
def conv2d_transpose(Input, Filter, strides=(1, 1), paddings=(0, 0), dilations=(1, 1), **_):
    return {"Output": _conv_transpose_nd(Input, Filter, strides, paddings,
                                         dilations, 2, _CONV_DN)}


@register_op("conv3d_transpose")
def conv3d_transpose(Input, Filter, strides=(1, 1, 1), paddings=(0, 0, 0),
                     dilations=(1, 1, 1), **_):
    return {"Output": _conv_transpose_nd(
        Input, Filter, strides, paddings, dilations, 3,
        ("NCDHW", "OIDHW", "NCDHW"))}


@register_op("conv3d")
def conv3d(Input, Filter, strides=(1, 1, 1), paddings=(0, 0, 0), dilations=(1, 1, 1), groups=1, **_):
    s, p, d = _pair(strides, 3), _pair(paddings, 3), _pair(dilations, 3)
    out = jax.lax.conv_general_dilated(
        Input,
        Filter.astype(Input.dtype),
        window_strides=s,
        padding=[(pp, pp) for pp in p],
        rhs_dilation=d,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        feature_group_count=groups,
    )
    return {"Output": out.astype(Input.dtype)}


@register_op("conv_shift")
def conv_shift(X, Y, **_):
    """Circular correlation (conv_shift_op.cc): out[i,j] = sum_k x[i, (j+k-M/2) % W] y[i,k]."""
    b, w = X.shape
    m = Y.shape[1]
    half = m // 2
    idx = (jnp.arange(w)[:, None] + jnp.arange(m)[None, :] - half) % w
    gathered = X[:, idx]  # [b, w, m]
    return {"Out": jnp.einsum("bwm,bm->bw", gathered, Y)}


def _pool_nd(X, k, s, p, pooling_type, global_pooling, ceil_mode=False,
             exclusive=True):
    """Shared N-spatial-dim pooling core (NC + spatial layout)."""
    nd = X.ndim - 2
    if global_pooling:
        k = X.shape[2:]
        p = (0,) * nd
    window = (1, 1) + tuple(k)
    stride = (1, 1) + tuple(s)
    pads = ((0, 0), (0, 0)) + tuple((p[i], p[i]) for i in range(nd))
    if ceil_mode:
        hi = []
        for i in range(nd):
            size = X.shape[2 + i] + 2 * p[i] - k[i]
            rem = size % s[i]
            hi.append((s[i] - rem) % s[i] if rem else 0)
        pads = ((0, 0), (0, 0)) + tuple(
            (p[i], p[i] + hi[i]) for i in range(nd))
    # NOTE: init values must be Python scalars so JAX recognizes the monoid
    # and emits reduce_window_max/_sum primitives (which have linearization
    # rules); an Array init falls back to generic reduce_window, which
    # cannot be differentiated under jit.
    if pooling_type == "max":
        init = -np.inf if jnp.issubdtype(X.dtype, jnp.floating) else int(jnp.iinfo(X.dtype).min)
        return jax.lax.reduce_window(X, init, jax.lax.max, window, stride, pads)
    ones = jnp.ones_like(X)
    summed = jax.lax.reduce_window(X, 0.0, jax.lax.add, window, stride, pads)
    if exclusive:
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, stride, pads)
    else:
        counts = jnp.asarray(np.prod(k), X.dtype)
    return summed / counts


def _pool2d(X, ksize, strides, paddings, pooling_type, global_pooling,
            ceil_mode=False, exclusive=True):
    return _pool_nd(X, _pair(ksize), _pair(strides), _pair(paddings),
                    pooling_type, global_pooling, ceil_mode, exclusive)


@register_op("pool2d")
def pool2d(
    X,
    ksize=(2, 2),
    strides=(1, 1),
    paddings=(0, 0),
    pooling_type="max",
    global_pooling=False,
    ceil_mode=False,
    exclusive=True,
    **_,
):
    return {"Out": _pool2d(X, ksize, strides, paddings, pooling_type, global_pooling, ceil_mode, exclusive)}


@register_op("pool3d")
def pool3d(
    X,
    ksize=(2, 2, 2),
    strides=(1, 1, 1),
    paddings=(0, 0, 0),
    pooling_type="max",
    global_pooling=False,
    ceil_mode=False,
    exclusive=True,
    **_,
):
    # reference pool_op.cc:298 pool3d (NCDHW)
    return {"Out": _pool_nd(X, _pair(ksize, 3), _pair(strides, 3),
                            _pair(paddings, 3), pooling_type,
                            global_pooling, ceil_mode, exclusive)}


@register_op("max_pool2d_with_index", nondiff=True)
def max_pool2d_with_index(X, ksize=(2, 2), strides=(1, 1), paddings=(0, 0), global_pooling=False, **_):
    out = _pool2d(X, ksize, strides, paddings, "max", global_pooling)
    # indices: flat position within each feature map (reference pool_with_index_op)
    n, c, h, w = X.shape
    flat_idx = jnp.arange(h * w, dtype=jnp.float32).reshape(1, 1, h, w)
    flat_idx = jnp.broadcast_to(flat_idx, X.shape)
    k, s, p = _pair(ksize), _pair(strides), _pair(paddings)
    if global_pooling:
        k, p = (h, w), (0, 0)

    def sel(a, b):
        av, ai = a
        bv, bi = b
        take_b = bv > av
        return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)

    vals, idxs = jax.lax.reduce_window(
        (X, flat_idx),
        (jnp.asarray(-jnp.inf, X.dtype), jnp.asarray(-1.0, jnp.float32)),
        sel,
        (1, 1) + tuple(k),
        (1, 1) + tuple(s),
        ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])),
    )
    return {"Out": vals, "Mask": idxs.astype(jnp.int32)}


@register_op("unpool")
def unpool(X, Indices, ksize=(2, 2), strides=(2, 2), paddings=(0, 0),
           unpooling_type="max", output_size=None, **_):
    n, c, h, w = X.shape
    s, k, p = _pair(strides), _pair(ksize), _pair(paddings)
    if output_size is not None:
        # explicit original extent (the pooled-shape formula floors, so it
        # is not invertible when windows didn't tile exactly)
        oh, ow = output_size
    else:
        # invert the pooled-shape formula: Mask holds flat positions in the
        # ORIGINAL map, so the output must be that original extent
        oh = (h - 1) * s[0] + k[0] - 2 * p[0]
        ow = (w - 1) * s[1] + k[1] - 2 * p[1]
    flat = jnp.zeros((n, c, oh * ow), dtype=X.dtype)
    idx = Indices.reshape(n, c, -1).astype(jnp.int32)
    vals = X.reshape(n, c, -1)
    flat = jax.vmap(jax.vmap(lambda f, i, v: f.at[i].set(v)))(flat, idx, vals)
    return {"Out": flat.reshape(n, c, oh, ow)}


@register_op("spp")
def spp(X, pyramid_height=3, pooling_type="max", **_):
    """Spatial pyramid pooling (spp_op.cc): concat of pyramid_height levels."""
    n, c, h, w = X.shape
    outs = []
    for lvl in range(pyramid_height):
        bins = 2 ** lvl
        kh, kw = int(np.ceil(h / bins)), int(np.ceil(w / bins))
        sh, sw = kh, kw
        ph, pw = (kh * bins - h + 1) // 2, (kw * bins - w + 1) // 2
        o = _pool2d(X, (kh, kw), (sh, sw), (ph, pw), pooling_type, False, False, False)
        outs.append(o.reshape(n, -1))
    return {"Out": jnp.concatenate(outs, axis=1)}


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _bn_train(X, Scale, Bias, axes, epsilon):
    y, _ = _bn_train_fwd(X, Scale, Bias, axes, epsilon)
    return y


def _bn_stats(X, axes):
    """Per-channel mean/var with f32 accumulation; full-tensor reads stay
    in X.dtype (the reductions fuse over the bf16 tensor — no f32 copy).
    Centered two-pass form: E[x^2]-E[x]^2 cancels catastrophically in f32
    for large-mean channels (e.g. raw-pixel-scale inputs)."""
    n = 1
    for a in axes:
        n *= X.shape[a]
    mean = jnp.sum(X, axis=axes, dtype=jnp.float32) / n
    bs = _bshape(X, axes)
    centered = X.astype(jnp.float32) - mean.reshape(bs)
    var = jnp.sum(jnp.square(centered), axis=axes) / n
    return mean, var


def _bshape(X, axes):
    return [1 if i in axes else X.shape[i] for i in range(X.ndim)]


def _bn_train_fwd(X, Scale, Bias, axes, epsilon):
    # Per-channel coefficients in f32 (tiny); the full-tensor normalize is
    # ONE fused multiply-add in X.dtype.  Computing the full-tensor math in
    # f32 instead doubles HBM traffic on bf16 models (measured: the f32
    # variant put ResNet-50 bs128 at 53 GB accessed/step vs ~20 GB).
    mean, var = _bn_stats(X, axes)
    inv = jax.lax.rsqrt(var + epsilon)
    a = Scale.astype(jnp.float32) * inv
    b = Bias.astype(jnp.float32) - mean * a
    bs = _bshape(X, axes)
    y = X * a.reshape(bs).astype(X.dtype) + b.reshape(bs).astype(X.dtype)
    return y, (X, Scale, mean, inv)


def _bn_train_bwd(axes, epsilon, res, dY):
    # Textbook BN backward: f32 per-channel reductions, X.dtype elementwise.
    X, Scale, mean, inv = res
    bs = _bshape(X, axes)
    n = 1
    for a in axes:
        n *= X.shape[a]
    mean_c = mean.reshape(bs).astype(X.dtype)
    inv_c = inv.reshape(bs).astype(X.dtype)
    xhat = (X - mean_c) * inv_c
    sum_dy = jnp.sum(dY, axis=axes, dtype=jnp.float32)
    sum_dy_xhat = jnp.sum((dY * xhat).astype(jnp.float32), axis=axes)
    coef = (Scale.astype(jnp.float32) * inv).reshape(bs)
    dX = coef.astype(X.dtype) * (
        dY
        - (sum_dy / n).reshape(bs).astype(X.dtype)
        - xhat * (sum_dy_xhat / n).reshape(bs).astype(X.dtype)
    )
    return dX, sum_dy_xhat.astype(Scale.dtype), sum_dy.astype(Scale.dtype)


_bn_train.defvjp(_bn_train_fwd, _bn_train_bwd)


@register_op("batch_norm")
def batch_norm(
    X,
    Scale,
    Bias,
    Mean,
    Variance,
    momentum=0.9,
    epsilon=1e-5,
    is_test=False,
    data_layout="NCHW",
    **_,
):
    axes = tuple(i for i in range(X.ndim) if i != (1 if data_layout == "NCHW" else X.ndim - 1))
    bshape = _bshape(X, axes)

    if is_test:
        mean, var = Mean.astype(jnp.float32), Variance.astype(jnp.float32)
        inv = jax.lax.rsqrt(var + epsilon)
        a = Scale.astype(jnp.float32) * inv
        b = Bias.astype(jnp.float32) - mean * a
        y = X * a.reshape(bshape).astype(X.dtype) \
            + b.reshape(bshape).astype(X.dtype)
        return {
            "Y": y,
            "MeanOut": Mean,
            "VarianceOut": Variance,
            "SavedMean": Mean,
            "SavedVariance": Variance,
        }

    mean, var = _bn_stats(X, axes)
    mean_out = (momentum * Mean.astype(jnp.float32) + (1 - momentum) * mean).astype(Mean.dtype)
    var_out = (momentum * Variance.astype(jnp.float32) + (1 - momentum) * var).astype(Variance.dtype)
    # Stats are training bookkeeping, not part of the differentiated graph
    # (the reference's batch_norm_op.cc likewise treats them as buffers).
    y = _bn_train(X, Scale, Bias, axes, epsilon)
    return {
        "Y": y,
        "MeanOut": mean_out,
        "VarianceOut": var_out,
        "SavedMean": jax.lax.stop_gradient(mean),
        "SavedVariance": jax.lax.stop_gradient(var),
    }


@register_op("layer_norm")
def layer_norm(X, Scale=None, Bias=None, begin_norm_axis=1, epsilon=1e-5, **_):
    axes = tuple(range(begin_norm_axis, X.ndim))
    xf = X.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + epsilon)
    if Scale is not None:
        y = y * Scale.astype(jnp.float32)
    if Bias is not None:
        y = y + Bias.astype(jnp.float32)
    return {
        "Y": y.astype(X.dtype),
        "Mean": mean.reshape(X.shape[:begin_norm_axis]),
        "Variance": var.reshape(X.shape[:begin_norm_axis]),
    }


@register_op("lrn")
def lrn(X, n=5, k=2.0, alpha=1e-4, beta=0.75, **_):
    sq = jnp.square(X)
    half = n // 2
    padded = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    windows = sum(padded[:, i : i + X.shape[1]] for i in range(n))
    mid = k + alpha * windows
    return {"Out": X / jnp.power(mid, beta), "MidOut": mid}


@register_op("im2sequence")
def im2sequence(X, kernels=(1, 1), strides=(1, 1), paddings=(0, 0, 0, 0), **_):
    """Sliding-window patches → sequence (im2sequence_op.cc).  Output is
    [N, out_h*out_w, C*kh*kw] padded-dense (the reference emits LoD)."""
    n, c, h, w = X.shape
    kh, kw = _pair(kernels)
    sh, sw = _pair(strides)
    pu, pl, pd, pr = paddings if len(paddings) == 4 else (paddings[0], paddings[1], paddings[0], paddings[1])
    xp = jnp.pad(X, ((0, 0), (0, 0), (pu, pd), (pl, pr)))
    patches = jax.lax.conv_general_dilated_patches(
        xp, (kh, kw), (sh, sw), padding="VALID", dimension_numbers=_CONV_DN
    )  # [N, C*kh*kw, oh, ow]
    ckk = patches.shape[1]
    out = patches.reshape(n, ckk, -1).transpose(0, 2, 1)
    return {"Out": out}


@register_op("row_conv")
def row_conv(X, Filter, Length=None, **_):
    """Lookahead row convolution (row_conv_op.cc, DeepSpeech2).  X is padded
    dense [batch, time, dim]; Filter [future_context+1, dim]."""
    ctx_len, dim = Filter.shape
    b, t, d = X.shape
    out = jnp.zeros_like(X)
    xp = jnp.pad(X, ((0, 0), (0, ctx_len - 1), (0, 0)))
    out = sum(xp[:, i : i + t, :] * Filter[i][None, None, :] for i in range(ctx_len))
    if Length is not None:
        mask = (jnp.arange(t)[None, :] < Length[:, None])[..., None]
        out = jnp.where(mask, out, 0.0)
    return {"Out": out}


@register_op("bilinear_interp")
def bilinear_interp(X, out_h=0, out_w=0, **_):
    """Bilinear upsampling, align-corners convention of the reference
    (``paddle/gserver/layers/BilinearInterpLayer.cpp:1``: ratio =
    (in-1)/(out-1)).  X [N, C, H, W] -> [N, C, out_h, out_w]."""
    n, c, h, w = X.shape
    oh, ow = int(out_h), int(out_w)

    def axis_coords(in_dim, out_dim):
        if out_dim == 1 or in_dim == 1:
            return (jnp.zeros((out_dim,), jnp.float32),
                    jnp.zeros((out_dim,), jnp.int32),
                    jnp.zeros((out_dim,), jnp.int32))
        ratio = (in_dim - 1.0) / (out_dim - 1.0)
        src = jnp.arange(out_dim, dtype=jnp.float32) * ratio
        lo = jnp.clip(jnp.floor(src).astype(jnp.int32), 0, in_dim - 1)
        hi = jnp.clip(lo + 1, 0, in_dim - 1)
        return src - lo.astype(jnp.float32), lo, hi

    fy, y0, y1 = axis_coords(h, oh)
    fx, x0, x1 = axis_coords(w, ow)
    tl = X[:, :, y0][:, :, :, x0]
    tr = X[:, :, y0][:, :, :, x1]
    bl = X[:, :, y1][:, :, :, x0]
    br = X[:, :, y1][:, :, :, x1]
    fy = fy.reshape(1, 1, oh, 1).astype(X.dtype)
    fx = fx.reshape(1, 1, 1, ow).astype(X.dtype)
    top = tl * (1 - fx) + tr * fx
    bot = bl * (1 - fx) + br * fx
    return {"Out": top * (1 - fy) + bot * fy}
