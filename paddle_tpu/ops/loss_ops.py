"""Loss ops (reference group: cross_entropy_op, softmax_with_cross_entropy_op,
sigmoid_cross_entropy_with_logits_op, smooth_l1_loss_op, hinge/huber/log/rank/
margin_rank/modified_huber losses, nce_op)."""

import jax
import jax.numpy as jnp

from ..core.registry import register_op


def _squeeze_label(Label):
    if Label.ndim >= 2 and Label.shape[-1] == 1:
        return Label.reshape(Label.shape[:-1])
    return Label


@register_op("cross_entropy")
def cross_entropy(X, Label, soft_label=False, **_):
    xf = X.astype(jnp.float32)
    if soft_label:
        out = -jnp.sum(Label.astype(jnp.float32) * jnp.log(jnp.maximum(xf, 1e-20)), axis=-1, keepdims=True)
    else:
        lbl = _squeeze_label(Label).astype(jnp.int32)
        picked = jnp.take_along_axis(xf, lbl[..., None], axis=-1)
        out = -jnp.log(jnp.maximum(picked, 1e-20))
    return {"Y": out.astype(X.dtype)}


@register_op("softmax_with_cross_entropy")
def softmax_with_cross_entropy(Logits, Label, soft_label=False, **_):
    lf = Logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(lf, axis=-1)
    if soft_label:
        loss = -jnp.sum(Label.astype(jnp.float32) * logp, axis=-1, keepdims=True)
    else:
        lbl = _squeeze_label(Label).astype(jnp.int32)
        loss = -jnp.take_along_axis(logp, lbl[..., None], axis=-1)
    return {"Softmax": jnp.exp(logp).astype(Logits.dtype), "Loss": loss.astype(Logits.dtype)}


@register_op("sigmoid_cross_entropy_with_logits")
def sigmoid_cross_entropy_with_logits(X, Label, **_):
    x = X.astype(jnp.float32)
    z = Label.astype(jnp.float32)
    loss = jnp.maximum(x, 0) - x * z + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return {"Out": loss.astype(X.dtype)}


@register_op("smooth_l1_loss")
def smooth_l1_loss(X, Y, InsideWeight=None, OutsideWeight=None, sigma=1.0, **_):
    s2 = sigma * sigma
    d = X - Y
    if InsideWeight is not None:
        d = d * InsideWeight
    ad = jnp.abs(d)
    loss = jnp.where(ad < 1.0 / s2, 0.5 * s2 * d * d, ad - 0.5 / s2)
    if OutsideWeight is not None:
        loss = loss * OutsideWeight
    out = jnp.sum(loss.reshape(X.shape[0], -1), axis=1, keepdims=True)
    return {"Out": out, "Diff": d}


@register_op("hinge_loss")
def hinge_loss(Logits, Labels, **_):
    y = Labels.astype(Logits.dtype) * 2.0 - 1.0
    return {"Loss": jnp.maximum(1.0 - Logits * y, 0.0)}


@register_op("huber_loss")
def huber_loss(X, Y, delta=1.0, **_):
    r = Y - X
    ar = jnp.abs(r)
    loss = jnp.where(ar <= delta, 0.5 * r * r, delta * (ar - 0.5 * delta))
    return {"Out": loss, "Residual": r}


@register_op("log_loss")
def log_loss(Predicted, Labels, epsilon=1e-4, **_):
    p = Predicted
    l = Labels
    return {"Loss": -l * jnp.log(p + epsilon) - (1 - l) * jnp.log(1 - p + epsilon)}


@register_op("rank_loss")
def rank_loss(Label, Left, Right, **_):
    d = Left - Right
    return {"Out": jnp.log1p(jnp.exp(d)) - Label * d}


@register_op("margin_rank_loss")
def margin_rank_loss(Label, X1, X2, margin=0.0, **_):
    out = jnp.maximum(-Label * (X1 - X2) + margin, 0.0)
    return {"Out": out, "Activated": (out > 0).astype(X1.dtype)}


@register_op("modified_huber_loss")
def modified_huber_loss(X, Y, **_):
    # labels in {0,1} -> {-1,1}; modified_huber_loss_op.cc
    y = Y.astype(X.dtype) * 2.0 - 1.0
    z = X * y
    loss = jnp.where(z < -1.0, -4.0 * z, jnp.square(jnp.maximum(1.0 - z, 0.0)))
    return {"Out": loss, "IntermediateVal": z}


@register_op("nce", stateful_rng=True)
def nce(Input, Label, Weight, Bias=None, SampleWeight=None,
        num_neg_samples=10, num_total_classes=None, _key=None, **_):
    """Noise-contrastive estimation (nce_op.cc) with uniform negative
    sampling.  Input [b,d], Weight [classes,d], Label [b,1]."""
    b = Input.shape[0]
    total = num_total_classes or Weight.shape[0]
    lbl = _squeeze_label(Label).astype(jnp.int32)
    key = _key if _key is not None else jax.random.PRNGKey(0)
    neg = jax.random.randint(key, (b, num_neg_samples), 0, total)

    def logit(ids):
        w = Weight[ids]  # [..., d]
        out = jnp.sum(w * Input[:, None, :] if ids.ndim == 2 else w * Input, axis=-1)
        if Bias is not None:
            out = out + Bias[ids]
        return out

    pos_logit = logit(lbl[:, None])[:, 0]
    neg_logit = logit(neg)
    p_noise = 1.0 / total
    pos_p = jax.nn.sigmoid(pos_logit - jnp.log(num_neg_samples * p_noise))
    neg_p = jax.nn.sigmoid(neg_logit - jnp.log(num_neg_samples * p_noise))
    loss = -jnp.log(jnp.maximum(pos_p, 1e-20)) - jnp.sum(
        jnp.log(jnp.maximum(1 - neg_p, 1e-20)), axis=1
    )
    if SampleWeight is not None:
        loss = loss * SampleWeight.reshape(-1)
    return {"Cost": loss[:, None],
            "SampleLogits": jnp.concatenate([pos_logit[:, None], neg_logit], axis=1),
            "SampleLabels": jnp.concatenate([lbl[:, None], neg], axis=1)}


@register_op("hierarchical_sigmoid")
def hierarchical_sigmoid(X, W, Label, Bias=None, num_classes=2, **_):
    """Hierarchical sigmoid (tree softmax) over a complete binary tree —
    the large-vocab training capability of the reference's
    ``paddle/gserver/layers/HierarchicalSigmoidLayer.cpp:1`` (bit-code
    matrix ops in ``paddle/math/MatrixBitCode.cpp``).

    Bit-code convention (matches the reference's SimpleCode): for class c,
    ``code = c + num_classes``; path node d has row index
    ``(code >> (d+1)) - 1`` in ``W`` and target bit ``(code >> d) & 1``;
    the path length is ``floor(log2(code))``.  Cost per sample is
    ``sum_d softplus(pre_d) - bit_d * pre_d`` (softrelu-clipped like the
    reference), i.e. the exact NLL of the label's leaf.

    X [b,d]; W [num_classes-1, d]; Label [b,1]; Bias [num_classes-1].
    Returns Out [b,1] and PreOut [b, max_code_len].
    """
    b = X.shape[0]
    lbl = _squeeze_label(Label).astype(jnp.int32)
    code = lbl + num_classes
    max_len = max(1, (2 * num_classes - 1).bit_length() - 1)
    d_range = jnp.arange(max_len)
    # [b, max_len]
    shifted = code[:, None] >> (d_range[None, :] + 1)
    active = shifted > 0
    idx = jnp.maximum(shifted - 1, 0)
    bits = ((code[:, None] >> d_range[None, :]) & 1).astype(X.dtype)
    rows = W[idx]  # [b, max_len, d]
    pre = jnp.einsum("bld,bd->bl", rows, X)
    if Bias is not None:
        pre = pre + Bias.reshape(-1)[idx]
    # reference softrelu threshold 40: clip the VALUE but keep the
    # reference backward (sigmoid(clip(pre)) - bit), which is nonzero at
    # saturation — a plain clip would zero the gradient and strand
    # badly-wrong samples.
    pre = pre + jax.lax.stop_gradient(jnp.clip(pre, -40.0, 40.0) - pre)
    loss_terms = jnp.where(active, jax.nn.softplus(pre) - bits * pre, 0.0)
    out = jnp.sum(loss_terms, axis=1, keepdims=True)
    return {"Out": out, "PreOut": jnp.where(active, pre, 0.0)}
