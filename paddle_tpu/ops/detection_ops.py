"""Detection / vision ops (reference group: prior_box_op, iou_similarity_op,
bipartite_match_op, roi_pool_op, detection_output; plus crop/pad/multiplex in
tensor_ops).  Fixed-size masked forms of the reference's dynamically-sized
outputs (XLA static shapes)."""

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op


@register_op("prior_box", nondiff=True)
def prior_box(Input, Image, min_sizes=(), max_sizes=(), aspect_ratios=(1.0,),
              variances=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              step_w=0.0, step_h=0.0, offset=0.5, **_):
    """SSD prior boxes (prior_box_op.cc).  Returns Boxes [H, W, P, 4] and
    Variances broadcast to the same shape."""
    fh, fw = Input.shape[2], Input.shape[3]
    ih, iw = Image.shape[2], Image.shape[3]
    sw = step_w or iw / fw
    sh = step_h or ih / fh
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    boxes = []
    for ms in min_sizes:
        for ar in ars:
            bw = ms * np.sqrt(ar) / 2.0
            bh = ms / np.sqrt(ar) / 2.0
            boxes.append((bw, bh))
        # extra prior for sqrt(min*max), reference order: after ar==1
    for ms, mxs in zip(min_sizes, max_sizes or ()):
        s = np.sqrt(ms * mxs) / 2.0
        boxes.append((s, s))
    p = len(boxes)
    cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * sw
    cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * sh
    cxg, cyg = jnp.meshgrid(cx, cy)  # [H, W]
    wh = jnp.asarray(boxes, jnp.float32)  # [P, 2]
    x1 = (cxg[..., None] - wh[None, None, :, 0]) / iw
    y1 = (cyg[..., None] - wh[None, None, :, 1]) / ih
    x2 = (cxg[..., None] + wh[None, None, :, 0]) / iw
    y2 = (cyg[..., None] + wh[None, None, :, 1]) / ih
    out = jnp.stack([x1, y1, x2, y2], axis=-1)  # [H, W, P, 4]
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32), out.shape)
    return {"Boxes": out, "Variances": var}


def _iou(a, b):
    """a [n,4], b [m,4] -> [n,m] (xmin, ymin, xmax, ymax)."""
    area_a = jnp.maximum(a[:, 2] - a[:, 0], 0) * jnp.maximum(a[:, 3] - a[:, 1], 0)
    area_b = jnp.maximum(b[:, 2] - b[:, 0], 0) * jnp.maximum(b[:, 3] - b[:, 1], 0)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_a[:, None] + area_b[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


@register_op("iou_similarity")
def iou_similarity(X, Y, **_):
    return {"Out": _iou(X.reshape(-1, 4), Y.reshape(-1, 4))}


@register_op("bipartite_match", nondiff=True)
def bipartite_match(DistMat, **_):
    """Greedy bipartite matching (bipartite_match_op.cc): repeatedly pick
    the global max of the distance matrix, match that row/col pair."""
    dist = DistMat
    n, m = dist.shape

    def step(carry, _):
        d, row_of_col, dist_of_col = carry
        flat = jnp.argmax(d)
        r, c = flat // m, flat % m
        val = d[r, c]
        ok = val > 0
        row_of_col = jnp.where(ok, row_of_col.at[c].set(r), row_of_col)
        dist_of_col = jnp.where(ok, dist_of_col.at[c].set(val), dist_of_col)
        d = jnp.where(ok, d.at[r, :].set(-1.0).at[:, c].set(-1.0), d)
        return (d, row_of_col, dist_of_col), None

    init = (dist, jnp.full((m,), -1, jnp.int32), jnp.zeros((m,), dist.dtype))
    (_, row_of_col, dist_of_col), _ = jax.lax.scan(step, init, None, length=min(n, m))
    return {
        "ColToRowMatchIndices": row_of_col[None, :],
        "ColToRowMatchDist": dist_of_col[None, :],
    }


@register_op("roi_pool")
def roi_pool(X, ROIs, pooled_height=1, pooled_width=1, spatial_scale=1.0, **_):
    """ROI max pooling (roi_pool_op.cc).  ROIs [R, 5] = (batch_idx, x1, y1,
    x2, y2) in input coordinates."""
    n, c, h, w = X.shape
    r = ROIs.shape[0]

    def one_roi(roi):
        bi = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * spatial_scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * spatial_scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * spatial_scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        img = X[bi]  # [c, h, w]
        ys = jnp.arange(h)
        xs = jnp.arange(w)

        def cell(ph, pw):
            hstart = y1 + (ph * rh) // pooled_height
            hend = y1 + ((ph + 1) * rh + pooled_height - 1) // pooled_height
            wstart = x1 + (pw * rw) // pooled_width
            wend = x1 + ((pw + 1) * rw + pooled_width - 1) // pooled_width
            mask = (
                (ys[:, None] >= hstart) & (ys[:, None] < jnp.maximum(hend, hstart + 1))
                & (xs[None, :] >= wstart) & (xs[None, :] < jnp.maximum(wend, wstart + 1))
            )
            return jnp.max(jnp.where(mask[None], img, -jnp.inf), axis=(1, 2))

        grid = jnp.stack(
            [jnp.stack([cell(ph, pw) for pw in range(pooled_width)], -1)
             for ph in range(pooled_height)],
            -2,
        )  # [c, ph, pw]
        return grid

    out = jax.vmap(one_roi)(ROIs.astype(jnp.float32))
    return {"Out": out, "Argmax": jnp.zeros_like(out, jnp.int32)}


@register_op("detection_output", nondiff=True)
def detection_output(Loc, Conf, PriorBox, background_label=0,
                     nms_threshold=0.45, nms_top_k=400, keep_top_k=200,
                     score_threshold=0.01, **_):
    """SSD decode + per-class NMS, fixed-size masked output
    [keep_top_k, 6] = (label, score, x1, y1, x2, y2); empty slots label=-1."""
    # Loc [b, P*4] or [b, P, 4]; Conf [b, P, C]; PriorBox [P, 4] + var [P, 4]
    prior, var = PriorBox[..., :4], None
    if PriorBox.ndim == 3:  # [2, P, 4] boxes+variances stacked
        prior, var = PriorBox[0], PriorBox[1]
    b = Conf.shape[0]
    p = prior.shape[0]
    c = Conf.shape[-1]
    loc = Loc.reshape(b, p, 4)
    if var is None:
        # SAME fallback as multibox_loss: (0.1, 0.1, 0.2, 0.2) — training
        # and decoding must scale w/h offsets identically
        var = jnp.tile(jnp.asarray([0.1, 0.1, 0.2, 0.2], jnp.float32),
                       (p, 1))
    # decode center-size offsets
    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = (prior[:, 0] + prior[:, 2]) / 2
    pcy = (prior[:, 1] + prior[:, 3]) / 2
    dcx = var[:, 0] * loc[..., 0] * pw + pcx
    dcy = var[:, 1] * loc[..., 1] * ph + pcy
    dw = jnp.exp(var[:, 2] * loc[..., 2]) * pw
    dh = jnp.exp(var[:, 3] * loc[..., 3]) * ph
    boxes = jnp.stack(
        [dcx - dw / 2, dcy - dh / 2, dcx + dw / 2, dcy + dh / 2], axis=-1
    )  # [b, P, 4]

    def per_image(bx, cf):
        results = []
        for cls in range(c):
            if cls == background_label:
                continue
            scores = cf[:, cls]
            k = min(nms_top_k, p)
            top_s, top_i = jax.lax.top_k(scores, k)
            cand = bx[top_i]
            iou = _iou(cand, cand)

            def nms_step(keep, i):
                active = jnp.logical_and(jnp.arange(k) < i, keep)
                sup = jnp.any(jnp.logical_and(active, iou[i] > nms_threshold))
                ok = jnp.logical_and(~sup, top_s[i] > score_threshold)
                return keep.at[i].set(ok), None

            keep, _ = jax.lax.scan(nms_step, jnp.zeros((k,), jnp.bool_), jnp.arange(k))
            cls_col = jnp.full((k, 1), float(cls))
            entry = jnp.concatenate([cls_col, top_s[:, None], cand], axis=1)
            entry = jnp.where(keep[:, None], entry, jnp.full_like(entry, -1.0))
            results.append(entry)
        allr = jnp.concatenate(results, axis=0)
        order = jnp.argsort(-allr[:, 1])
        allr = allr[order][:keep_top_k]
        pad = keep_top_k - allr.shape[0]
        if pad > 0:
            allr = jnp.concatenate([allr, jnp.full((pad, 6), -1.0)], axis=0)
        return allr

    out = jax.vmap(per_image)(boxes, Conf)
    return {"Out": out}


@register_op("multibox_loss")
def multibox_loss(Loc, Conf, PriorBox, GtBox, GtLabel,
                  overlap_threshold=0.5, neg_pos_ratio=3.0,
                  background_label=0, **_):
    """SSD training loss (reference
    ``paddle/gserver/layers/MultiBoxLossLayer.cpp:1``): match priors to
    ground truth by IoU, smooth-L1 on the matched location offsets,
    softmax cross-entropy on class confidences with hard negative mining
    (negatives ranked by loss, kept up to neg_pos_ratio x positives).

    Loc [b, P, 4] (center-size offsets), Conf [b, P, C],
    PriorBox [P, 4] or [2, P, 4] (boxes + variances),
    GtBox [b, G, 4] corner form, GtLabel [b, G] int (< 0 = padding).
    Returns Loss [b, 1] (per-image loc+conf loss, normalized by positives).
    """
    prior, var = PriorBox, None
    if PriorBox.ndim == 3:
        prior, var = PriorBox[0], PriorBox[1]
    if var is None:
        var = jnp.full_like(prior, 0.1).at[:, 2:].set(0.2)
    b, p, _4 = Loc.shape
    g = GtBox.shape[1]
    c = Conf.shape[-1]

    valid_gt = GtLabel >= 0                                   # [b, G]
    # IoU prior x gt
    ax1, ay1, ax2, ay2 = [prior[:, i] for i in range(4)]
    area_p = (ax2 - ax1) * (ay2 - ay1)                        # [P]
    bx1, by1, bx2, by2 = [GtBox[..., i] for i in range(4)]    # [b, G]
    ix1 = jnp.maximum(ax1[None, :, None], bx1[:, None, :])
    iy1 = jnp.maximum(ay1[None, :, None], by1[:, None, :])
    ix2 = jnp.minimum(ax2[None, :, None], bx2[:, None, :])
    iy2 = jnp.minimum(ay2[None, :, None], by2[:, None, :])
    inter = jnp.clip(ix2 - ix1, 0) * jnp.clip(iy2 - iy1, 0)   # [b, P, G]
    area_g = ((bx2 - bx1) * (by2 - by1))[:, None, :]
    iou = inter / jnp.maximum(area_p[None, :, None] + area_g - inter, 1e-10)
    iou = jnp.where(valid_gt[:, None, :], iou, -1.0)

    best_gt = jnp.argmax(iou, axis=2)                         # [b, P]
    best_iou = jnp.max(iou, axis=2)
    matched = best_iou >= overlap_threshold                   # [b, P]
    # bipartite stage (reference MultiBoxLossLayer matchBBox): every valid
    # gt claims its best-overlap prior even below the threshold, so no
    # ground truth is left without a positive / loc signal
    bidx = jnp.arange(b)[:, None]
    gidx = jnp.broadcast_to(jnp.arange(g)[None, :], (b, g))
    best_prior = jnp.argmax(iou, axis=1)                      # [b, G]
    # padded gts (iou forced to -1) all argmax to prior 0 — route their
    # scatter writes to an out-of-bounds index so JAX drops them instead
    # of clobbering a real gt whose best prior is 0
    tgt_prior = jnp.where(valid_gt, best_prior, p)
    force = jnp.zeros((b, p), jnp.bool_).at[bidx, tgt_prior].set(True)
    forced_gt = jnp.zeros((b, p), best_gt.dtype).at[bidx, tgt_prior].set(gidx)
    best_gt = jnp.where(force, forced_gt, best_gt)
    matched = jnp.logical_or(matched, force)
    n_pos = jnp.sum(matched, axis=1)                          # [b]

    # encode matched gt as center-size offsets wrt the prior (SSD encode)
    mb = jnp.take_along_axis(GtBox, best_gt[..., None], axis=1)  # [b,P,4]
    pw, ph = ax2 - ax1, ay2 - ay1
    pcx, pcy = (ax1 + ax2) / 2, (ay1 + ay2) / 2
    gcx = (mb[..., 0] + mb[..., 2]) / 2
    gcy = (mb[..., 1] + mb[..., 3]) / 2
    gw = jnp.maximum(mb[..., 2] - mb[..., 0], 1e-10)
    gh = jnp.maximum(mb[..., 3] - mb[..., 1], 1e-10)
    t = jnp.stack([
        (gcx - pcx[None]) / pw[None] / var[:, 0][None],
        (gcy - pcy[None]) / ph[None] / var[:, 1][None],
        jnp.log(gw / pw[None]) / var[:, 2][None],
        jnp.log(gh / ph[None]) / var[:, 3][None],
    ], axis=-1)                                               # [b, P, 4]
    diff = Loc - jax.lax.stop_gradient(t)
    ad = jnp.abs(diff)
    smooth_l1 = jnp.where(ad < 1.0, 0.5 * ad * ad, ad - 0.5).sum(-1)
    loc_loss = jnp.sum(jnp.where(matched, smooth_l1, 0.0), axis=1)

    # conf loss: softmax CE against matched label (background if unmatched)
    tgt = jnp.where(
        matched,
        jnp.take_along_axis(GtLabel, best_gt, axis=1),
        background_label,
    )                                                         # [b, P]
    logp = jax.nn.log_softmax(Conf, axis=-1)
    ce = -jnp.take_along_axis(
        logp, tgt[..., None].astype(jnp.int32), axis=2)[..., 0]  # [b, P]

    # hard negative mining: keep top (ratio * n_pos) unmatched by CE
    neg_ce = jnp.where(matched, -jnp.inf, ce)
    order = jnp.argsort(-neg_ce, axis=1)
    rank = jnp.argsort(order, axis=1)                         # rank of each
    n_neg = jnp.minimum((neg_pos_ratio * n_pos).astype(jnp.int32),
                        p - n_pos)
    keep_neg = jnp.logical_and(~matched, rank < n_neg[:, None])
    conf_loss = jnp.sum(jnp.where(jnp.logical_or(matched, keep_neg),
                                  ce, 0.0), axis=1)

    denom = jnp.maximum(n_pos.astype(Loc.dtype), 1.0)
    return {"Loss": ((loc_loss + conf_loss) / denom)[:, None]}
