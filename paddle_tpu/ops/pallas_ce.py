"""Fused softmax-cross-entropy head as Pallas TPU kernels.

The reference composes the LM head from a projection plus
``softmax_with_cross_entropy`` (``paddle/operators/
softmax_with_cross_entropy_op.cc``), which materializes the full
``[tokens, vocab]`` logits — at the GPT flagship shape (32k tokens x 32k
vocab) that is ~2 GiB of bf16 logits plus the saved softmax, all HBM
traffic.  This kernel fuses projection -> log-softmax -> NLL the flash
way: the vocab axis is tiled, logit tiles live only in VMEM, an online
max/sum carries the softmax state across vocab tiles, and the label's
logit is picked up by an iota==label select in the visited tile.  HBM
residual is O(tokens) — one f32 lse per token, stored compactly (narrow
[n, 1] kernel output, squeezed to 1-D; same convention as
pallas_attention.py) — never O(tokens x vocab).

Backward mirrors flash: two Pallas kernels recompute the probability
tiles from the saved lse — dx (row-major grid, vocab innermost,
accumulating ``ds @ W^T`` in VMEM) and dW (vocab-major grid, rows
innermost, accumulating ``X^T @ ds``), with ``ds = (p - onehot) * g``.
MXU feeds stay in the input dtype (bf16 in = 2x the f32 MXU rate);
softmax state and accumulators are f32.

Layout: x [N, d] activations, w [d, v] head weight, labels [N] int.
Rows with out-of-range labels (e.g. ignore_index -1) produce a finite
garbage loss that callers mask out; their gradients vanish because the
masked loss contributes a zero cotangent.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

from jax.ad_checkpoint import checkpoint_name

from ..analysis.jaxpr_tools import KERNEL_RESIDUAL_TAG
from ..core.registry import register_op
from .pallas_attention import _pick_block

LANES = 128  # Mosaic min lane tile; per-row stats are lane-replicated


def _ce_fwd_kernel(x_ref, w_ref, y_ref, loss_ref, lse_ref,
                   m_scr, l_scr, pick_scr, *, block_v, nv):
    """One (row-block, vocab-block) grid cell; vocab is the innermost grid
    axis so online-softmax state carries across vocab tiles in VMEM."""
    import jax.experimental.pallas as pl

    jv = pl.program_id(1)

    @pl.when(jv == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr[...], -jnp.inf)
        l_scr[...] = jnp.zeros_like(l_scr[...])
        pick_scr[...] = jnp.zeros_like(pick_scr[...])

    x = x_ref[...]                      # [bn, d] input dtype
    w = w_ref[...]                      # [d, bv]
    s = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)          # [bn, bv] f32
    m_prev = m_scr[...]
    m2 = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m2)
    p = jnp.exp(s - m2[:, :1])
    l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
    m_scr[...] = m2
    y = y_ref[...]                      # [bn, 1] int32
    col = jv * block_v + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    pick_scr[...] += jnp.sum(
        jnp.where(col == y, s, 0.0), axis=-1, keepdims=True)

    @pl.when(jv == nv - 1)
    def _finalize():
        lse = m_scr[...] + jnp.log(l_scr[...])
        lse_ref[...] = lse[:, :1]
        loss_ref[...] = (lse - pick_scr[...])[:, :1]


def _ce_dx_kernel(x_ref, w_ref, y_ref, lse_ref, g_ref, dx_ref, dx_scr,
                  *, block_v, nv):
    """dx: grid (row-blocks, vocab-blocks), vocab innermost; recompute the
    probability tile from lse, ds = (p - onehot) * g, dx += ds @ W^T."""
    import jax.experimental.pallas as pl

    jv = pl.program_id(1)

    @pl.when(jv == 0)
    def _init():
        dx_scr[...] = jnp.zeros_like(dx_scr[...])

    x = x_ref[...]
    w = w_ref[...]
    s = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    p = jnp.exp(s - lse_ref[...][:, :1])
    col = jv * block_v + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    onehot = (col == y_ref[...]).astype(jnp.float32)
    ds = ((p - onehot) * g_ref[...][:, :1]).astype(w.dtype)
    dx_scr[...] += jax.lax.dot_general(
        ds, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(jv == nv - 1)
    def _finalize():
        dx_ref[...] = dx_scr[...].astype(dx_ref.dtype)


def _ce_dw_kernel(x_ref, w_ref, y_ref, lse_ref, g_ref, dw_ref, dw_scr,
                  *, block_v, nn):
    """dW: grid (vocab-blocks, row-blocks), rows innermost; dW += X^T @ ds
    accumulated across row tiles in VMEM."""
    import jax.experimental.pallas as pl

    jv = pl.program_id(0)
    jn = pl.program_id(1)

    @pl.when(jn == 0)
    def _init():
        dw_scr[...] = jnp.zeros_like(dw_scr[...])

    x = x_ref[...]
    w = w_ref[...]
    s = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    p = jnp.exp(s - lse_ref[...][:, :1])
    col = jv * s.shape[1] + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    onehot = (col == y_ref[...]).astype(jnp.float32)
    ds = ((p - onehot) * g_ref[...][:, :1]).astype(x.dtype)
    dw_scr[...] += jax.lax.dot_general(
        x, ds, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(jn == nn - 1)
    def _finalize():
        dw_ref[...] = dw_scr[...].astype(dw_ref.dtype)


def _ce_fwd(x, w, y, block_n, block_v, interpret):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, d = x.shape
    v = w.shape[1]
    bn = _pick_block(n, block_n)
    bv = _pick_block(v, block_v)
    nv = v // bv
    y2 = y.reshape(n, 1)

    loss, lse = pl.pallas_call(
        functools.partial(_ce_fwd_kernel, block_v=bv, nv=nv),
        grid=(n // bn, nv),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i, jv: (i, 0)),
            pl.BlockSpec((d, bv), lambda i, jv: (0, jv)),
            pl.BlockSpec((bn, 1), lambda i, jv: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, 1), lambda i, jv: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i, jv: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bn, LANES), jnp.float32),  # m
            pltpu.VMEM((bn, LANES), jnp.float32),  # l
            pltpu.VMEM((bn, LANES), jnp.float32),  # picked label logit
        ],
        interpret=interpret,
    )(x, w, y2)
    # squeeze to 1-D immediately: the [n, 1] kernel buffers get tile-
    # padded to 128 lanes by XLA's layout; the 1-D forms are compact
    return loss[:, 0], lse[:, 0]


def _ce_bwd(x, w, y, lse, g, block_n, block_v, interpret):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, d = x.shape
    v = w.shape[1]
    bn = _pick_block(n, block_n)
    bv = _pick_block(v, block_v)
    nn_ = n // bn
    nv = v // bv
    y2 = y.reshape(n, 1)
    lse = lse.reshape(n, 1)
    g2 = g.astype(jnp.float32).reshape(n, 1)

    xspec = pl.BlockSpec((bn, d), lambda i, jv: (i, 0))
    wspec = pl.BlockSpec((d, bv), lambda i, jv: (0, jv))
    rstat = pl.BlockSpec((bn, 1), lambda i, jv: (i, 0))
    dx = pl.pallas_call(
        functools.partial(_ce_dx_kernel, block_v=bv, nv=nv),
        grid=(nn_, nv),
        in_specs=[xspec, wspec, rstat, rstat, rstat],
        out_specs=[xspec],
        out_shape=[jax.ShapeDtypeStruct((n, d), x.dtype)],
        scratch_shapes=[pltpu.VMEM((bn, d), jnp.float32)],
        interpret=interpret,
    )(x, w, y2, lse, g2)[0]

    xspec2 = pl.BlockSpec((bn, d), lambda jv, jn: (jn, 0))
    wspec2 = pl.BlockSpec((d, bv), lambda jv, jn: (0, jv))
    rstat2 = pl.BlockSpec((bn, 1), lambda jv, jn: (jn, 0))
    dw = pl.pallas_call(
        functools.partial(_ce_dw_kernel, block_v=bv, nn=nn_),
        grid=(nv, nn_),
        in_specs=[xspec2, wspec2, rstat2, rstat2, rstat2],
        out_specs=[pl.BlockSpec((d, bv), lambda jv, jn: (0, jv))],
        out_shape=[jax.ShapeDtypeStruct((d, v), w.dtype)],
        scratch_shapes=[pltpu.VMEM((d, bv), jnp.float32)],
        interpret=interpret,
    )(x, w, y2, lse, g2)[0]
    return dx, dw


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ce_core(x, w, y, blocks, interpret):
    loss, _ = _ce_fwd(x, w, y, blocks[0], blocks[2], interpret)
    return loss


def _ce_core_fwd(x, w, y, blocks, interpret):
    loss, lse = _ce_fwd(x, w, y, blocks[0], blocks[2], interpret)
    # kernel-residual tag (see ops/pallas_attention.py): a name-policy
    # checkpoint saves the O(tokens) lse instead of re-running the
    # O(tokens x vocab) forward kernel in the backward pass
    lse = checkpoint_name(lse, KERNEL_RESIDUAL_TAG)
    return loss, (x, w, y, lse)


def _ce_core_bwd(blocks, interpret, res, g):
    x, w, y, lse = res
    dx, dw = _ce_bwd(x, w, y, lse, g, blocks[0], blocks[1], interpret)
    return dx, dw, np.zeros(y.shape, jax.dtypes.float0)


_ce_core.defvjp(_ce_core_fwd, _ce_core_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ce_core_lse(x, w, y, blocks, interpret):
    """Like ``_ce_core`` but also returns the per-row lse, DIFFERENTIABLE
    through both outputs — the building block for vocab-sharded tensor
    parallelism, where each shard's (loss_s, lse_s) pair is merged by a
    cross-shard logsumexp (same pattern as flash_attention_with_lse for
    ring attention)."""
    return _ce_fwd(x, w, y, blocks[0], blocks[2], interpret)


def _ce_core_lse_fwd(x, w, y, blocks, interpret):
    loss, lse = _ce_fwd(x, w, y, blocks[0], blocks[2], interpret)
    lse = checkpoint_name(lse, KERNEL_RESIDUAL_TAG)
    return (loss, lse), (x, w, y, lse)


def _ce_core_lse_bwd(blocks, interpret, res, cts):
    x, w, y, lse = res
    g, glse = cts
    # loss = lse - picked, so with an extra lse cotangent glse the total
    # logits cotangent is (g + glse)*(p - onehot) + glse*onehot: the
    # first term is exactly the existing backward kernels run with
    # g' = g + glse; the onehot term is a rank-1-per-row correction
    # (dx += glse * W[:, y],  dW[:, y] += glse * x) done in plain JAX.
    g = g.astype(jnp.float32)
    glse = glse.astype(jnp.float32)
    dx, dw = _ce_bwd(x, w, y, lse, g + glse, blocks[0], blocks[1],
                     interpret)
    yi = y.astype(jnp.int32)
    dx = dx + (glse[:, None] * w[:, yi].T).astype(dx.dtype)
    dw = dw + (jnp.zeros(dw.shape, jnp.float32)
               .at[:, yi].add(x.T.astype(jnp.float32) * glse[None, :])
               ).astype(dw.dtype)
    return dx, dw, np.zeros(y.shape, jax.dtypes.float0)


_ce_core_lse.defvjp(_ce_core_lse_fwd, _ce_core_lse_bwd)


def _resolve_backend(backend):
    """One selection path (the kernel registry, docs/kernels.md) for
    both CE entry points — replaces the per-call-site
    ``interpret = jax.default_backend() != "tpu"`` fallback."""
    from ..kernels import resolve  # late: kernels imports this module

    kernel = resolve("fused_ce", backend)
    return kernel.backend, kernel.impl


def fused_softmax_ce_head_with_lse(x, w, labels, block_n=512,
                                   block_v=1024, interpret=None,
                                   block_v_fwd=2048, backend=None):
    """``fused_softmax_ce_head`` that ALSO returns the per-position lse
    (both ``[...]`` f32), differentiable through both — callers compose
    partial losses across vocab shards with a logsumexp merge
    (parallelism: see the fused_softmax_ce_head op's tp path)."""
    lead = x.shape[:-1]
    d = x.shape[-1]
    n = 1
    for s in lead:
        n *= int(s)
    name, impl = _resolve_backend(backend)
    if name != "pallas_tpu":
        loss, lse = impl.call_with_lse(
            x.reshape(n, d), w, labels.reshape(n), block_n=block_n,
            block_v=block_v, block_v_fwd=block_v_fwd,
            interpret=interpret)
        return loss.reshape(lead), lse.reshape(lead)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bn, bv, bv_fwd = _auto_blocks(
        n, d, w.shape[1], x.dtype.itemsize, w.dtype.itemsize,
        int(block_n), int(block_v), int(block_v_fwd))
    loss, lse = _ce_core_lse(
        x.reshape(n, d), w, labels.reshape(n).astype(jnp.int32),
        (bn, bv, bv_fwd), bool(interpret))
    return loss.reshape(lead), lse.reshape(lead)


# per-kernel VMEM budget for the block chooser.  14 MB (of the 16 MB
# scoped limit) reproduces the hand-tuned flagship config exactly
# (bn=512, bv=1024, bv_fwd=2048 at d=768 bf16) while leaving headroom
# for Mosaic's own spills; larger d_model configs shrink to fit instead
# of dying in the Mosaic lowering with a raw VMEM-OOM.
VMEM_BUDGET = 14 << 20


def _vmem_est(kernel, bn, bv, d, ix, iw):
    """Rough per-grid-cell VMEM bytes: double-buffered input blocks +
    the f32 logits tile + kernel-specific accumulators/outputs."""
    inputs = 2 * (bn * d * ix + d * bv * iw)
    s_tile = bn * bv * 4
    if kernel == "fwd":
        extra = 3 * bn * LANES * 4
    elif kernel == "dx":
        extra = bn * d * 4 + bn * d * ix
    else:  # dw
        extra = d * bv * 4 + d * bv * iw
    return inputs + s_tile + extra


def _auto_blocks(n, d, v, ix, iw, block_n, block_v, block_v_fwd,
                 budget=None):
    """Shrink the (caller-capped) block sizes until every kernel's VMEM
    estimate fits the scoped budget.  Raises with an actionable message
    if even the minimum blocks cannot fit (enormous d_model)."""
    budget = budget or VMEM_BUDGET

    def fit(kernel, bn_cap, bv_cap):
        bn = _pick_block(n, bn_cap)
        bv_c = bv_cap
        while True:
            bv = _pick_block(v, bv_c)
            if _vmem_est(kernel, bn, bv, d, ix, iw) <= budget:
                return bn, bv
            if bv_c > 128:
                bv_c //= 2
                continue
            if bn > 8:
                bn = _pick_block(n, max(8, bn // 2))
                bv_c = bv_cap
                continue
            raise ValueError(
                f"fused_softmax_ce_head: no block config fits VMEM for "
                f"d_model={d}, vocab={v} ({kernel} kernel needs "
                f"{_vmem_est(kernel, bn, bv, d, ix, iw) >> 20} MB at the "
                f"minimum blocks, budget {budget >> 20} MB) — use the "
                f"unfused softmax_with_cross_entropy head for this shape")

    bn_f, bv_f = fit("fwd", block_n, block_v_fwd)
    bn_x, bv_x = fit("dx", block_n, block_v)
    bn_w, bv_w = fit("dw", block_n, block_v)
    # one bn for all kernels (the residual/stat blocks are shared)
    bn = min(bn_f, bn_x, bn_w)
    return bn, min(bv_x, bv_w), bv_f


def fused_softmax_ce_head(x, w, labels, block_n=512, block_v=1024,
                          interpret=None, block_v_fwd=2048, backend=None):
    """Fused projection + softmax cross-entropy: ``x [..., d]``,
    ``w [d, v]``, ``labels [...]`` int -> per-position NLL ``[...]`` f32,
    without ever materializing ``[..., v]`` logits in HBM (the xla_ref
    oracle backend materializes them — that is its point).
    Differentiable in x and w (custom VJP in every backend); routed
    through the kernel registry (docs/kernels.md) — ``backend`` picks
    pallas_tpu | triton | xla_ref explicitly, None resolves env
    overrides then the platform auto order.

    Block args are UPPER bounds: the chooser shrinks them per kernel to
    fit scoped VMEM (the forward fits a wider vocab block than the
    backward kernels, whose accumulators + second input block OOM at
    bv=2048/d=768; measured fwd 10.8 -> 9.7 ms at the flagship shape
    with the split sizes), so d_model >= 1024 configs work instead of
    hitting a raw Mosaic VMEM error."""
    lead = x.shape[:-1]
    d = x.shape[-1]
    n = 1
    for s in lead:
        n *= int(s)
    name, impl = _resolve_backend(backend)
    if name != "pallas_tpu":
        loss = impl.call(x.reshape(n, d), w, labels.reshape(n),
                         block_n=block_n, block_v=block_v,
                         block_v_fwd=block_v_fwd, interpret=interpret)
        return loss.reshape(lead)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bn, bv, bv_fwd = _auto_blocks(
        n, d, w.shape[1], x.dtype.itemsize, w.dtype.itemsize,
        int(block_n), int(block_v), int(block_v_fwd))
    loss = _ce_core(
        x.reshape(n, d), w, labels.reshape(n).astype(jnp.int32),
        (bn, bv, bv_fwd), bool(interpret))
    return loss.reshape(lead)


def fused_softmax_ce_head_reference(x, w, labels):
    """Dense reference (tests / tiny shapes): materializes logits."""
    logits = jnp.einsum("...d,dv->...v", x, w,
                        preferred_element_type=jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    lbl = labels.astype(jnp.int32)
    return -jnp.take_along_axis(logp, lbl[..., None], axis=-1)[..., 0]


@register_op("fused_softmax_ce_head")
def fused_softmax_ce_head_op(X, W, Label, block_n=512, block_v=1024,
                             block_v_fwd=2048, backend="", _ctx=None,
                             **_):
    backend = backend or None
    lbl = Label
    if lbl.ndim == X.ndim and lbl.shape[-1] == 1:
        lbl = lbl.reshape(lbl.shape[:-1])
    from .pallas_attention import _tp_axis

    mesh, tp = _tp_axis(_ctx)
    v = W.shape[1]
    if tp > 1 and v % tp == 0:
        # Vocab-sharded tensor parallelism: each shard runs the fused
        # kernel over its vocab slice (labels localized by the shard
        # offset) and the global softmax is recovered by a cross-shard
        # logsumexp merge — the same online-softmax algebra the kernel
        # uses across vocab TILES, lifted to mesh shards:
        #   lse  = logsumexp_tp(lse_s)
        #   loss = lse - psum(in_shard ? (lse_s - loss_s) : 0)
        # Differentiable end to end (loss_s/lse_s carry the kernel's
        # custom VJP; the merge is plain JAX).
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        db = "dp" if "dp" in mesh.axis_names else None
        xspec = P(*([db] + [None] * (X.ndim - 1)))
        lspec = P(*([db] + [None] * (lbl.ndim - 1)))

        def local(x, w, y):
            vs = w.shape[1]
            off = jax.lax.axis_index("tp") * vs
            y = y.astype(jnp.int32)
            in_s = ((y >= off) & (y < off + vs))
            y_loc = jnp.clip(y - off, 0, vs - 1)
            loss_s, lse_s = fused_softmax_ce_head_with_lse(
                x, w, y_loc, block_n=block_n, block_v=block_v,
                block_v_fwd=block_v_fwd, backend=backend)
            picked = jnp.where(in_s, lse_s - loss_s, 0.0)
            # the max shift is numerical stabilization only (it cancels
            # algebraically) — stop_gradient keeps the merge on psum's
            # differentiation path (pmax has no JVP rule)
            m = jax.lax.pmax(jax.lax.stop_gradient(lse_s), "tp")
            lse = jnp.log(jax.lax.psum(jnp.exp(lse_s - m), "tp")) + m
            return lse - jax.lax.psum(picked, "tp")

        loss = shard_map(
            local, mesh=mesh,
            in_specs=(xspec, P(None, "tp"), lspec),
            out_specs=lspec, check_rep=False)(X, W, lbl)
        return {"Loss": loss[..., None]}
    loss = fused_softmax_ce_head(X, W, lbl, block_n=block_n,
                                 block_v=block_v,
                                 block_v_fwd=block_v_fwd,
                                 backend=backend)
    return {"Loss": loss[..., None]}


# -- kernel-registry registration (docs/kernels.md) --------------------------
# The Mosaic kernels above ARE the "pallas_tpu" backend of the fused_ce
# op class; impl convention is 2-D (x [n, d], w [d, v], labels [n]).
from ..kernels.registry import (
    pallas_tpu_availability as _pallas_tpu_availability,
    register_kernel as _register_kernel)


def _pallas_ce(x, w, labels, block_n=None, block_v=None,
               block_v_fwd=None, interpret=None):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, d = x.shape
    bn, bv, bv_fwd = _auto_blocks(
        n, d, w.shape[1], x.dtype.itemsize, w.dtype.itemsize,
        int(block_n or 512), int(block_v or 1024),
        int(block_v_fwd or 2048))
    return _ce_core(x, w, labels.astype(jnp.int32), (bn, bv, bv_fwd),
                    bool(interpret))


def _pallas_ce_with_lse(x, w, labels, block_n=None, block_v=None,
                        block_v_fwd=None, interpret=None):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, d = x.shape
    bn, bv, bv_fwd = _auto_blocks(
        n, d, w.shape[1], x.dtype.itemsize, w.dtype.itemsize,
        int(block_n or 512), int(block_v or 1024),
        int(block_v_fwd or 2048))
    return _ce_core_lse(x, w, labels.astype(jnp.int32),
                        (bn, bv, bv_fwd), bool(interpret))


class _CePallasTpu:
    call = staticmethod(_pallas_ce)
    call_with_lse = staticmethod(_pallas_ce_with_lse)


_register_kernel("fused_ce", "pallas_tpu", _CePallasTpu,
                 available=_pallas_tpu_availability)
