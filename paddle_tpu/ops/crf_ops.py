"""Linear-chain CRF ops.

Reference: ``linear_chain_crf_op.{h,cc}`` (forward algorithm + analytic
gradient) and ``crf_decoding_op`` (Viterbi).  Transition parameter layout
follows the reference: [num_tags + 2, num_tags] where row 0 = start weights,
row 1 = end weights, rows 2.. = transition[i][j] from tag i to tag j.
Padded dense [b, T, num_tags] emissions + lengths replace LoD; both
recursions are ``lax.scan``s and the log-likelihood gradient comes from AD.
"""

import jax
import jax.numpy as jnp

from ..core.registry import register_op

_NEG_INF = -1e30


def _mask(Length, t):
    return jnp.arange(t)[None, :] < Length[:, None]


def crf_log_norm(emission, transition, lengths):
    b, t, n = emission.shape
    start, end, trans = transition[0], transition[1], transition[2:]
    alpha0 = start[None, :] + emission[:, 0, :]

    def step(alpha, tt):
        # logsumexp over previous tag
        scores = alpha[:, :, None] + trans[None, :, :] + emission[:, tt, None, :]
        new_alpha = jax.scipy.special.logsumexp(scores, axis=1)
        active = (tt < lengths)[:, None]
        return jnp.where(active, new_alpha, alpha), None

    alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, t))
    return jax.scipy.special.logsumexp(alpha + end[None, :], axis=1)


def crf_path_score(emission, transition, labels, lengths):
    b, t, n = emission.shape
    start, end, trans = transition[0], transition[1], transition[2:]
    lbl = labels.astype(jnp.int32)
    m = _mask(lengths, t).astype(jnp.float32)
    emit = jnp.take_along_axis(emission, lbl[..., None], axis=2).reshape(b, t)
    score = start[lbl[:, 0]] + emit[:, 0]
    tr = trans[lbl[:, :-1], lbl[:, 1:]]  # [b, t-1]
    score = score + jnp.sum((tr + emit[:, 1:]) * m[:, 1:], axis=1)
    last = jnp.maximum(lengths.astype(jnp.int32) - 1, 0)
    last_lbl = jnp.take_along_axis(lbl, last[:, None], axis=1).reshape(-1)
    return score + end[last_lbl]


@register_op("linear_chain_crf")
def linear_chain_crf(Emission, Transition, Label, Length=None, **_):
    b, t, n = Emission.shape
    lengths = (
        Length.astype(jnp.int32) if Length is not None else jnp.full((b,), t, jnp.int32)
    )
    lbl = Label.reshape(b, t) if Label.ndim == 3 else Label
    em = Emission.astype(jnp.float32)
    tr = Transition.astype(jnp.float32)
    log_z = crf_log_norm(em, tr, lengths)
    gold = crf_path_score(em, tr, lbl, lengths)
    nll = log_z - gold
    return {
        "LogLikelihood": nll[:, None].astype(Emission.dtype),
        "EmissionExps": jnp.exp(em - jnp.max(em, axis=-1, keepdims=True)),
        "TransitionExps": jnp.exp(tr - jnp.max(tr)),
        "Alpha": jnp.zeros_like(em),
    }


@register_op("crf_decoding", nondiff=True)
def crf_decoding(Emission, Transition, Label=None, Length=None, **_):
    """Viterbi decode.  With Label given, outputs per-token correctness mask
    (reference semantics for evaluation)."""
    b, t, n = Emission.shape
    lengths = (
        Length.astype(jnp.int32) if Length is not None else jnp.full((b,), t, jnp.int32)
    )
    em = Emission.astype(jnp.float32)
    start, end, trans = Transition[0], Transition[1], Transition[2:]
    delta0 = start[None, :] + em[:, 0, :]

    def fwd(delta, tt):
        scores = delta[:, :, None] + trans[None, :, :] + em[:, tt, None, :]
        best_prev = jnp.argmax(scores, axis=1)
        new_delta = jnp.max(scores, axis=1)
        active = (tt < lengths)[:, None]
        delta = jnp.where(active, new_delta, delta)
        return delta, jnp.where(active, best_prev, jnp.broadcast_to(jnp.arange(n)[None, :], (b, n)))

    delta, backptrs = jax.lax.scan(fwd, delta0, jnp.arange(1, t))  # backptrs [t-1, b, n]
    final = delta + end[None, :]
    last_tag = jnp.argmax(final, axis=1)  # [b]

    def back(tag, bp):
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1).reshape(-1)
        return prev, tag

    # scan emits the tag at t=T-1 first and carries the predecessor; the
    # final carry is the tag at t=0
    first_tag, tags_rev = jax.lax.scan(back, last_tag, backptrs[::-1])
    path = jnp.concatenate([first_tag[None, :], tags_rev[::-1]], axis=0).T  # [b, t]
    path = jnp.where(_mask(lengths, t), path, 0)
    if Label is not None:
        lbl = Label.reshape(b, t) if Label.ndim == 3 else Label
        correct = jnp.logical_and(path == lbl.astype(path.dtype), _mask(lengths, t))
        return {"ViterbiPath": correct.astype(jnp.int32)}
    return {"ViterbiPath": path.astype(jnp.int32)}
