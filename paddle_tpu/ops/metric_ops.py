"""Metric ops (reference: accuracy_op, auc_op, precision_recall_op,
positive_negative_pair_op, chunk_eval_op).  Metrics are part of the program
(SURVEY §5) — accumulator state lives in persistable variables so metric
updates fuse into the jitted step."""

import jax
import jax.numpy as jnp

from ..core.registry import register_op


@register_op("accuracy", nondiff=True)
def accuracy(Out, Indices, Label, **_):
    """Top-k accuracy (accuracy_op.cc): Indices [b, k] from top_k, Label
    [b, 1]."""
    lbl = Label.reshape(-1, 1).astype(Indices.dtype)
    correct = jnp.any(Indices == lbl, axis=1)
    num_correct = jnp.sum(correct.astype(jnp.float32))
    total = jnp.asarray(float(Indices.shape[0]), jnp.float32)
    return {
        "Accuracy": (num_correct / total).reshape(1),
        "Correct": num_correct.astype(jnp.int32).reshape(1),
        "Total": total.astype(jnp.int32).reshape(1),
    }


@register_op("auc", nondiff=True)
def auc(Out, Indices=None, Label=None, curve="ROC", num_thresholds=200, **_):
    """Approximate AUC via threshold buckets (auc_op.cc)."""
    pos_prob = Out[:, 1] if Out.ndim == 2 and Out.shape[1] >= 2 else Out.reshape(-1)
    lbl = Label.reshape(-1).astype(jnp.bool_)
    thresholds = jnp.linspace(0.0, 1.0, num_thresholds)
    pred_pos = pos_prob[None, :] >= thresholds[:, None]  # [T, b]
    tp = jnp.sum(jnp.logical_and(pred_pos, lbl[None, :]), axis=1).astype(jnp.float32)
    fp = jnp.sum(jnp.logical_and(pred_pos, ~lbl[None, :]), axis=1).astype(jnp.float32)
    pos = jnp.maximum(jnp.sum(lbl.astype(jnp.float32)), 1.0)
    neg = jnp.maximum(jnp.sum((~lbl).astype(jnp.float32)), 1.0)
    tpr = tp / pos
    fpr = fp / neg
    # integrate (thresholds descend fpr); trapezoid
    auc_val = -jnp.trapezoid(tpr, fpr)
    return {"AUC": auc_val.reshape(1)}


@register_op("precision_recall", nondiff=True)
def precision_recall(MaxProbs=None, Indices=None, Labels=None, Weights=None,
                     StatesInfo=None, class_number=2, **_):
    """Multi-class precision/recall (precision_recall_op.cc).  Maintains
    per-class [TP, FP, TN, FN] stats; returns batch + accumulated metrics."""
    pred = Indices.reshape(-1).astype(jnp.int32)
    lbl = Labels.reshape(-1).astype(jnp.int32)
    w = Weights.reshape(-1) if Weights is not None else jnp.ones_like(pred, jnp.float32)
    classes = jnp.arange(class_number)
    is_pred = pred[None, :] == classes[:, None]   # [C, b]
    is_lbl = lbl[None, :] == classes[:, None]
    tp = jnp.sum(jnp.where(jnp.logical_and(is_pred, is_lbl), w[None, :], 0.0), axis=1)
    fp = jnp.sum(jnp.where(jnp.logical_and(is_pred, ~is_lbl), w[None, :], 0.0), axis=1)
    fn = jnp.sum(jnp.where(jnp.logical_and(~is_pred, is_lbl), w[None, :], 0.0), axis=1)
    tn = jnp.sum(jnp.where(jnp.logical_and(~is_pred, ~is_lbl), w[None, :], 0.0), axis=1)
    batch_states = jnp.stack([tp, fp, tn, fn], axis=1)  # [C, 4]
    acc_states = batch_states + (StatesInfo if StatesInfo is not None else 0.0)

    def metrics(states):
        tp_, fp_, tn_, fn_ = states[:, 0], states[:, 1], states[:, 2], states[:, 3]
        prec = tp_ / jnp.maximum(tp_ + fp_, 1e-12)
        rec = tp_ / jnp.maximum(tp_ + fn_, 1e-12)
        f1 = 2 * prec * rec / jnp.maximum(prec + rec, 1e-12)
        # macro + micro averaged, as the reference outputs 6 numbers
        micro_p = jnp.sum(tp_) / jnp.maximum(jnp.sum(tp_ + fp_), 1e-12)
        micro_r = jnp.sum(tp_) / jnp.maximum(jnp.sum(tp_ + fn_), 1e-12)
        micro_f1 = 2 * micro_p * micro_r / jnp.maximum(micro_p + micro_r, 1e-12)
        return jnp.stack([jnp.mean(prec), jnp.mean(rec), jnp.mean(f1),
                          micro_p, micro_r, micro_f1])

    return {
        "BatchMetrics": metrics(batch_states),
        "AccumMetrics": metrics(acc_states),
        "AccumStatesInfo": acc_states,
    }


@register_op("positive_negative_pair", nondiff=True)
def positive_negative_pair(Score, Label, QueryID, AccumulatePositivePair=None,
                           AccumulateNegativePair=None, AccumulateNeutralPair=None, **_):
    """Ranking pair stats within each query (positive_negative_pair_op.cc)."""
    s = Score.reshape(-1)
    l = Label.reshape(-1).astype(jnp.float32)
    q = QueryID.reshape(-1)
    same_q = q[:, None] == q[None, :]
    upper = jnp.triu(jnp.ones_like(same_q), k=1)
    valid = jnp.logical_and(same_q, upper > 0)
    ds = s[:, None] - s[None, :]
    dl = l[:, None] - l[None, :]
    informative = jnp.logical_and(valid, dl != 0)
    pos = jnp.sum(jnp.where(jnp.logical_and(informative, ds * dl > 0), 1.0, 0.0))
    neg = jnp.sum(jnp.where(jnp.logical_and(informative, ds * dl < 0), 1.0, 0.0))
    neu = jnp.sum(jnp.where(jnp.logical_and(informative, ds == 0), 1.0, 0.0))
    if AccumulatePositivePair is not None:
        pos = pos + AccumulatePositivePair.reshape(())
        neg = neg + AccumulateNegativePair.reshape(())
        neu = neu + AccumulateNeutralPair.reshape(())
    return {
        "PositivePair": pos.reshape(1),
        "NegativePair": neg.reshape(1),
        "NeutralPair": neu.reshape(1),
    }


# chunk_eval scheme tables (chunk_eval_op.h:108-141): per-scheme
# (num_tag_types, tag_begin, tag_inside, tag_end, tag_single); -1 = unused.
_CHUNK_SCHEMES = {
    "IOB": (2, 0, 1, -1, -1),
    "IOE": (2, -1, 0, 1, -1),
    "IOBES": (4, 0, 1, 2, 3),
    "plain": (1, -1, -1, -1, -1),
}


@register_op("chunk_eval", nondiff=True)
def chunk_eval(Inference, Label, Length=None, num_chunk_types=1,
               chunk_scheme="IOB", excluded_chunk_types=(), **_):
    """Chunk-level precision/recall/F1 (chunk_eval_op.h:27-198), all four
    reference schemes (IOB/IOE/IOBES/plain) + excluded_chunk_types.

    Tag encoding follows the reference: ``tag = label % num_tag_types``,
    ``type = label // num_tag_types``; the "outside" label is
    ``num_chunk_types * num_tag_types``.  A chunk match requires an
    identical (begin, end, type) span.

    The reference walks each sequence with an ``in_chunk`` flag; here the
    begin/end predicates are evaluated position-wise (exact because a
    non-outside token is always inside a chunk under every scheme table:
    ChunkBegin fires whenever prev is outside and cur is not, so ChunkEnd
    — which needs a non-outside prev — can never fire on a closed chunk).
    """
    if chunk_scheme not in _CHUNK_SCHEMES:
        raise ValueError(f"unknown chunk scheme {chunk_scheme!r}")
    n_tags, t_beg, t_in, t_end, t_sin = _CHUNK_SCHEMES[chunk_scheme]
    other = num_chunk_types
    b, t = Inference.shape
    mask = (
        (jnp.arange(t)[None, :] < Length.reshape(-1, 1))
        if Length is not None
        else jnp.ones((b, t), jnp.bool_)
    )

    def analyze(labels):
        labels = labels.astype(jnp.int32)
        tag = labels % n_tags
        ctype = jnp.where(mask, labels // n_tags, other)
        # prev at position 0: type = other (chunk_eval_op.h:47 init)
        prev_tag = jnp.concatenate(
            [jnp.full((b, 1), -2, tag.dtype), tag[:, :-1]], axis=1)
        prev_type = jnp.concatenate(
            [jnp.full((b, 1), other, ctype.dtype), ctype[:, :-1]], axis=1)

        cur_out = ctype == other
        prev_out = prev_type == other
        diff_type = ctype != prev_type

        # ChunkBegin table (chunk_eval_op.h:93-104)
        tag_cond = jnp.zeros((b, t), jnp.bool_)
        if t_beg >= 0:
            tag_cond = jnp.logical_or(tag_cond, tag == t_beg)
        if t_sin >= 0:
            tag_cond = jnp.logical_or(tag_cond, tag == t_sin)
        prev_closed = jnp.zeros((b, t), jnp.bool_)
        if t_end >= 0:
            prev_closed = jnp.logical_or(prev_closed, prev_tag == t_end)
        if t_sin >= 0:
            prev_closed = jnp.logical_or(prev_closed, prev_tag == t_sin)
        if t_in >= 0:
            tag_cond = jnp.logical_or(
                tag_cond, jnp.logical_and(tag == t_in, prev_closed))
        if t_end >= 0:
            tag_cond = jnp.logical_or(
                tag_cond, jnp.logical_and(tag == t_end, prev_closed))
        begins = jnp.where(
            prev_out, ~cur_out,
            jnp.where(cur_out, False, jnp.logical_or(diff_type, tag_cond)))

        # ChunkEnd table (chunk_eval_op.h:80-91): a segment ends AT i-1
        # when this fires at i.
        end_tag_cond = jnp.zeros((b, t), jnp.bool_)
        restart = jnp.zeros((b, t), jnp.bool_)  # cur tag begins anew
        if t_beg >= 0:
            restart = jnp.logical_or(restart, tag == t_beg)
        if t_sin >= 0:
            restart = jnp.logical_or(restart, tag == t_sin)
        if t_beg >= 0:
            end_tag_cond = jnp.logical_or(
                end_tag_cond, jnp.logical_and(prev_tag == t_beg, restart))
        if t_in >= 0:
            end_tag_cond = jnp.logical_or(
                end_tag_cond, jnp.logical_and(prev_tag == t_in, restart))
        if t_end >= 0:
            end_tag_cond = jnp.logical_or(end_tag_cond, prev_tag == t_end)
        if t_sin >= 0:
            end_tag_cond = jnp.logical_or(end_tag_cond, prev_tag == t_sin)
        closes = jnp.where(
            prev_out, False,
            jnp.where(cur_out, True, jnp.logical_or(diff_type, end_tag_cond)))

        # end_marker[i]: a segment's last token is i — ChunkEnd fires at
        # i+1, or i is the final (valid) token of a still-open chunk.
        nxt_closes = jnp.concatenate(
            [closes[:, 1:], jnp.ones((b, 1), jnp.bool_)], axis=1)
        end_marker = jnp.logical_and(~cur_out, nxt_closes)
        return begins, end_marker, ctype

    inf_s, inf_e, inf_t = analyze(Inference)
    lab_s, lab_e, lab_t = analyze(Label)

    idx = jnp.arange(t)[None, :]

    def chunk_end(ends):
        # for each position, the nearest segment-final index at/after it
        INF = t + 1
        end_pos = jnp.where(ends, idx, INF)
        return jnp.flip(jax.lax.cummin(jnp.flip(end_pos, axis=1), axis=1),
                        axis=1)

    inf_end = chunk_end(inf_e)
    lab_end = chunk_end(lab_e)

    def counted(starts, ctype):
        ok = starts
        for ex in excluded_chunk_types:
            ok = jnp.logical_and(ok, ctype != ex)
        return ok

    inf_ok = counted(inf_s, inf_t)
    lab_ok = counted(lab_s, lab_t)
    num_inf = jnp.sum(jnp.where(inf_ok, 1.0, 0.0))
    num_lab = jnp.sum(jnp.where(lab_ok, 1.0, 0.0))
    match = jnp.logical_and(
        jnp.logical_and(inf_ok, lab_ok),
        jnp.logical_and(inf_end == lab_end, inf_t == lab_t),
    )
    num_correct = jnp.sum(jnp.where(match, 1.0, 0.0))
    # zero-denominator convention of the reference (chunk_eval_op.h:186-197)
    precision = jnp.where(num_inf > 0, num_correct / jnp.maximum(num_inf, 1.0), 0.0)
    recall = jnp.where(num_lab > 0, num_correct / jnp.maximum(num_lab, 1.0), 0.0)
    f1 = jnp.where(
        num_correct > 0,
        2 * precision * recall / jnp.maximum(precision + recall, 1e-12), 0.0)
    return {
        "Precision": precision.reshape(1),
        "Recall": recall.reshape(1),
        "F1-Score": f1.reshape(1),
        "NumInferChunks": num_inf.astype(jnp.int32).reshape(1),
        "NumLabelChunks": num_lab.astype(jnp.int32).reshape(1),
        "NumCorrectChunks": num_correct.astype(jnp.int32).reshape(1),
    }
