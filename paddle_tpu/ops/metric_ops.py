"""Metric ops (reference: accuracy_op, auc_op, precision_recall_op,
positive_negative_pair_op, chunk_eval_op).  Metrics are part of the program
(SURVEY §5) — accumulator state lives in persistable variables so metric
updates fuse into the jitted step."""

import jax
import jax.numpy as jnp

from ..core.registry import register_op


@register_op("accuracy", nondiff=True)
def accuracy(Out, Indices, Label, **_):
    """Top-k accuracy (accuracy_op.cc): Indices [b, k] from top_k, Label
    [b, 1]."""
    lbl = Label.reshape(-1, 1).astype(Indices.dtype)
    correct = jnp.any(Indices == lbl, axis=1)
    num_correct = jnp.sum(correct.astype(jnp.float32))
    total = jnp.asarray(float(Indices.shape[0]), jnp.float32)
    return {
        "Accuracy": (num_correct / total).reshape(1),
        "Correct": num_correct.astype(jnp.int32).reshape(1),
        "Total": total.astype(jnp.int32).reshape(1),
    }


@register_op("auc", nondiff=True)
def auc(Out, Indices=None, Label=None, curve="ROC", num_thresholds=200, **_):
    """Approximate AUC via threshold buckets (auc_op.cc)."""
    pos_prob = Out[:, 1] if Out.ndim == 2 and Out.shape[1] >= 2 else Out.reshape(-1)
    lbl = Label.reshape(-1).astype(jnp.bool_)
    thresholds = jnp.linspace(0.0, 1.0, num_thresholds)
    pred_pos = pos_prob[None, :] >= thresholds[:, None]  # [T, b]
    tp = jnp.sum(jnp.logical_and(pred_pos, lbl[None, :]), axis=1).astype(jnp.float32)
    fp = jnp.sum(jnp.logical_and(pred_pos, ~lbl[None, :]), axis=1).astype(jnp.float32)
    pos = jnp.maximum(jnp.sum(lbl.astype(jnp.float32)), 1.0)
    neg = jnp.maximum(jnp.sum((~lbl).astype(jnp.float32)), 1.0)
    tpr = tp / pos
    fpr = fp / neg
    # integrate (thresholds descend fpr); trapezoid
    auc_val = -jnp.trapezoid(tpr, fpr)
    return {"AUC": auc_val.reshape(1)}


@register_op("precision_recall", nondiff=True)
def precision_recall(MaxProbs=None, Indices=None, Labels=None, Weights=None,
                     StatesInfo=None, class_number=2, **_):
    """Multi-class precision/recall (precision_recall_op.cc).  Maintains
    per-class [TP, FP, TN, FN] stats; returns batch + accumulated metrics."""
    pred = Indices.reshape(-1).astype(jnp.int32)
    lbl = Labels.reshape(-1).astype(jnp.int32)
    w = Weights.reshape(-1) if Weights is not None else jnp.ones_like(pred, jnp.float32)
    classes = jnp.arange(class_number)
    is_pred = pred[None, :] == classes[:, None]   # [C, b]
    is_lbl = lbl[None, :] == classes[:, None]
    tp = jnp.sum(jnp.where(jnp.logical_and(is_pred, is_lbl), w[None, :], 0.0), axis=1)
    fp = jnp.sum(jnp.where(jnp.logical_and(is_pred, ~is_lbl), w[None, :], 0.0), axis=1)
    fn = jnp.sum(jnp.where(jnp.logical_and(~is_pred, is_lbl), w[None, :], 0.0), axis=1)
    tn = jnp.sum(jnp.where(jnp.logical_and(~is_pred, ~is_lbl), w[None, :], 0.0), axis=1)
    batch_states = jnp.stack([tp, fp, tn, fn], axis=1)  # [C, 4]
    acc_states = batch_states + (StatesInfo if StatesInfo is not None else 0.0)

    def metrics(states):
        tp_, fp_, tn_, fn_ = states[:, 0], states[:, 1], states[:, 2], states[:, 3]
        prec = tp_ / jnp.maximum(tp_ + fp_, 1e-12)
        rec = tp_ / jnp.maximum(tp_ + fn_, 1e-12)
        f1 = 2 * prec * rec / jnp.maximum(prec + rec, 1e-12)
        # macro + micro averaged, as the reference outputs 6 numbers
        micro_p = jnp.sum(tp_) / jnp.maximum(jnp.sum(tp_ + fp_), 1e-12)
        micro_r = jnp.sum(tp_) / jnp.maximum(jnp.sum(tp_ + fn_), 1e-12)
        micro_f1 = 2 * micro_p * micro_r / jnp.maximum(micro_p + micro_r, 1e-12)
        return jnp.stack([jnp.mean(prec), jnp.mean(rec), jnp.mean(f1),
                          micro_p, micro_r, micro_f1])

    return {
        "BatchMetrics": metrics(batch_states),
        "AccumMetrics": metrics(acc_states),
        "AccumStatesInfo": acc_states,
    }


@register_op("positive_negative_pair", nondiff=True)
def positive_negative_pair(Score, Label, QueryID, AccumulatePositivePair=None,
                           AccumulateNegativePair=None, AccumulateNeutralPair=None, **_):
    """Ranking pair stats within each query (positive_negative_pair_op.cc)."""
    s = Score.reshape(-1)
    l = Label.reshape(-1).astype(jnp.float32)
    q = QueryID.reshape(-1)
    same_q = q[:, None] == q[None, :]
    upper = jnp.triu(jnp.ones_like(same_q), k=1)
    valid = jnp.logical_and(same_q, upper > 0)
    ds = s[:, None] - s[None, :]
    dl = l[:, None] - l[None, :]
    informative = jnp.logical_and(valid, dl != 0)
    pos = jnp.sum(jnp.where(jnp.logical_and(informative, ds * dl > 0), 1.0, 0.0))
    neg = jnp.sum(jnp.where(jnp.logical_and(informative, ds * dl < 0), 1.0, 0.0))
    neu = jnp.sum(jnp.where(jnp.logical_and(informative, ds == 0), 1.0, 0.0))
    if AccumulatePositivePair is not None:
        pos = pos + AccumulatePositivePair.reshape(())
        neg = neg + AccumulateNegativePair.reshape(())
        neu = neu + AccumulateNeutralPair.reshape(())
    return {
        "PositivePair": pos.reshape(1),
        "NegativePair": neg.reshape(1),
        "NeutralPair": neu.reshape(1),
    }


@register_op("chunk_eval", nondiff=True)
def chunk_eval(Inference, Label, Length=None, num_chunk_types=1,
               chunk_scheme="IOB", **_):
    """Chunk-level precision/recall/F1 (chunk_eval_op.cc), IOB scheme.

    Tag encoding follows the reference: for IOB, tag = chunk_type * 2
    (B-) or chunk_type * 2 + 1 (I-); the "outside" tag is num_chunk_types*2.
    A chunk match requires identical (begin, end, type) spans.
    """
    if chunk_scheme != "IOB":
        raise NotImplementedError("only IOB chunk_scheme is implemented")
    b, t = Inference.shape
    mask = (
        (jnp.arange(t)[None, :] < Length[:, None])
        if Length is not None
        else jnp.ones((b, t), jnp.bool_)
    )

    def spans(tags):
        """begin[i]: a chunk starts at i; type[i]: its chunk type."""
        outside = num_chunk_types * 2
        valid = jnp.logical_and(tags < outside, mask)
        is_b = jnp.logical_and(valid, tags % 2 == 0)
        is_i = jnp.logical_and(valid, tags % 2 == 1)
        ctype = tags // 2
        prev_valid = jnp.concatenate([jnp.zeros((b, 1), jnp.bool_), valid[:, :-1]], axis=1)
        prev_type = jnp.concatenate([jnp.full((b, 1), -1, ctype.dtype), ctype[:, :-1]], axis=1)
        # I- starts a chunk if previous token wasn't inside same-type chunk
        starts = jnp.logical_or(
            is_b, jnp.logical_and(is_i, jnp.logical_or(~prev_valid, prev_type != ctype))
        )
        nxt_valid = jnp.concatenate([valid[:, 1:], jnp.zeros((b, 1), jnp.bool_)], axis=1)
        nxt_type = jnp.concatenate([ctype[:, 1:], jnp.full((b, 1), -1, ctype.dtype)], axis=1)
        nxt_tags = jnp.concatenate([tags[:, 1:], jnp.full((b, 1), outside, tags.dtype)], axis=1)
        # chunk ends at i if next token is not an I- of same type
        cont = jnp.logical_and(
            jnp.logical_and(nxt_valid, nxt_tags % 2 == 1), nxt_type == ctype
        )
        ends = jnp.logical_and(valid, ~cont)
        return starts, ends, ctype, valid

    inf_s, inf_e, inf_t, inf_v = spans(Inference.astype(jnp.int32))
    lab_s, lab_e, lab_t, lab_v = spans(Label.astype(jnp.int32))

    # identify chunks by their start index; a chunk is (start, end, type).
    # end index for a chunk starting at i = next end position >= i.
    idx = jnp.arange(t)[None, :]

    def chunk_end(ends):
        # for each position, the nearest end at or after it
        INF = t + 1
        end_pos = jnp.where(ends, idx, INF)
        rev_cummin = jnp.flip(jax.lax.cummin(jnp.flip(end_pos, axis=1), axis=1), axis=1)
        return rev_cummin

    inf_end = chunk_end(inf_e)
    lab_end = chunk_end(lab_e)
    num_inf = jnp.sum(jnp.where(inf_s, 1.0, 0.0))
    num_lab = jnp.sum(jnp.where(lab_s, 1.0, 0.0))
    match = jnp.logical_and(
        jnp.logical_and(inf_s, lab_s),
        jnp.logical_and(inf_end == lab_end, inf_t == lab_t),
    )
    num_correct = jnp.sum(jnp.where(match, 1.0, 0.0))
    precision = num_correct / jnp.maximum(num_inf, 1e-12)
    recall = num_correct / jnp.maximum(num_lab, 1e-12)
    f1 = 2 * precision * recall / jnp.maximum(precision + recall, 1e-12)
    return {
        "Precision": precision.reshape(1),
        "Recall": recall.reshape(1),
        "F1-Score": f1.reshape(1),
        "NumInferChunks": num_inf.astype(jnp.int32).reshape(1),
        "NumLabelChunks": num_lab.astype(jnp.int32).reshape(1),
        "NumCorrectChunks": num_correct.astype(jnp.int32).reshape(1),
    }
