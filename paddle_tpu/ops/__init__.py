"""The operator library — pure JAX implementations, one per reference op.

Grouped exactly as the reference groups ``paddle/operators/`` (~150 ops, see
SURVEY.md §2.2).  Every function here is trace-time code: it runs once under
``jax.jit`` tracing and returns jax arrays; XLA does the fusing, tiling and
device placement that the reference implements by hand in CUDA kernels and
``paddle/operators/math/`` functors.
"""

from ..core.registry import registered_ops, get_op_impl

from . import math_ops
from . import activation_ops
from . import tensor_ops
from . import random_ops
from . import nn_ops
from . import loss_ops
from . import sequence_ops
from . import rnn_ops
from . import optimizer_ops
from . import control_flow_ops
from . import beam_search_ops
from . import beam_ce_ops
from . import metric_ops
from . import detection_ops
from . import ctc_ops
from . import crf_ops
from . import io_ops
from . import pallas_attention
from . import pallas_ce
