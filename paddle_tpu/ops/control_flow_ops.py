"""Control flow — compare/logical ops and block-structured control ops.

Reference: ``while_op.cc``, ``conditional_block_op.cc``, ``compare_op``,
``logical_op``, the LoDTensorArray op family, ``parallel_do_op.cc``.  The
reference interprets sub-blocks by re-entering the Executor with STEP_SCOPES
(executor.cc:118); here sub-blocks lower to ``lax.while_loop`` /
``lax.cond`` / ``lax.scan`` — traced once, compiled into the same XLA
computation, with static shapes throughout.  Tensor "arrays" (the
LoDTensorArray analog) are preallocated [max_len, ...] buffers written with
``.at[i].set`` — dynamic append is not an XLA concept.
"""

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from ..core.executor import run_block_ops


def _register_cmp(name, fn):
    @register_op(name)
    def _op(X, Y, **_):
        return {"Out": fn(X, Y)}

    _op.__name__ = name


_register_cmp("less_than", jnp.less)
_register_cmp("less_equal", jnp.less_equal)
_register_cmp("greater_than", jnp.greater)
_register_cmp("greater_equal", jnp.greater_equal)
_register_cmp("equal", jnp.equal)
_register_cmp("not_equal", jnp.not_equal)


@register_op("logical_and")
def logical_and(X, Y, **_):
    return {"Out": jnp.logical_and(X, Y)}


@register_op("logical_or")
def logical_or(X, Y, **_):
    return {"Out": jnp.logical_or(X, Y)}


@register_op("logical_xor")
def logical_xor(X, Y, **_):
    return {"Out": jnp.logical_xor(X, Y)}


@register_op("logical_not")
def logical_not(X, **_):
    return {"Out": jnp.logical_not(X)}


# ---------------------------------------------------------------------------
# Tensor array ops (LoDTensorArray analog; lod_tensor_array.h,
# tensor_array_read_write_op.cc) — Array is a preallocated [max_len, ...]
# buffer; I is a scalar int index.
# ---------------------------------------------------------------------------
@register_op("array_write")
def array_write(X, I, Array, **_):
    i = jnp.asarray(I).reshape(()).astype(jnp.int32)
    return {"Out": Array.at[i].set(X)}


@register_op("array_read")
def array_read(Array, I, **_):
    i = jnp.asarray(I).reshape(()).astype(jnp.int32)
    return {"Out": jax.lax.dynamic_index_in_dim(Array, i, axis=0, keepdims=False)}


@register_op("array_length")
def array_length(Array, **_):
    # static capacity; the dynamic "filled" length is tracked by the loop
    # counter variable in while-programs (max_sequence_len analog).
    return {"Out": jnp.asarray([Array.shape[0]], dtype=jnp.int32)}


# ---------------------------------------------------------------------------
# Structured control-flow ops.  Raw lowerings: they receive (ctx, block, op,
# env) and splice the sub-block in.
# ---------------------------------------------------------------------------
def _sub_block_writes(program, block_idx):
    blk = program.block(block_idx)
    written = []
    for op in blk.ops:
        for n in op.output_names():
            if n not in written:
                written.append(n)
        sub = op.attrs.get("sub_block")
        if sub is not None:
            for n in _sub_block_writes(program, sub):
                if n not in written:
                    written.append(n)
    return written


@register_op("while", raw=True)
def while_op(ctx, block, op, env):
    """Lower a while sub-block to lax.while_loop.

    Carried state = condition var + every var the sub-block writes that
    already exists in the enclosing env (same contract as the reference
    while_op's step-scope promotion).  All carried vars must keep their
    shape/dtype across iterations (XLA requirement — the reference enforced
    nothing and paid with dynamic reallocation)."""
    program = ctx.program
    sub_idx = op.attrs["sub_block"]
    cond_name = op.inputs["Condition"][0]
    written = _sub_block_writes(program, sub_idx)
    carried = [n for n in written if n in env]
    if cond_name not in carried:
        carried.insert(0, cond_name)
    sub_blk = program.block(sub_idx)

    def cond_fun(carry):
        return jnp.asarray(carry[cond_name]).reshape(()).astype(jnp.bool_)

    def body_fun(carry):
        local = dict(env)
        local.update(carry)
        run_block_ops(ctx, sub_blk, sub_blk.ops, local)
        return {n: local[n] for n in carried}

    init = {n: env[n] for n in carried}
    final = jax.lax.while_loop(cond_fun, body_fun, init)
    env.update(final)


@register_op("conditional_block", raw=True)
def conditional_block(ctx, block, op, env):
    """lax.cond over a sub-block.  Outputs must be written by the sub-block;
    the false branch passes through their current env values (which must
    exist — declare defaults with fill_constant first)."""
    program = ctx.program
    sub_idx = op.attrs["sub_block"]
    cond_name = op.inputs["Cond"][0]
    out_names = op.outputs.get("Out", [])
    sub_blk = program.block(sub_idx)

    def true_fn(operands):
        local = dict(operands)
        run_block_ops(ctx, sub_blk, sub_blk.ops, local)
        return tuple(local[n] for n in out_names)

    def false_fn(operands):
        return tuple(operands[n] for n in out_names)

    pred = jnp.asarray(env[cond_name]).reshape(()).astype(jnp.bool_)
    operands = dict(env)
    outs = jax.lax.cond(pred, true_fn, false_fn, operands)
    env.update(zip(out_names, outs))


@register_op("scan_block", raw=True)
def scan_block(ctx, block, op, env):
    """Structured dynamic-RNN op (the TPU-native recurrent_op): scan the
    sub-block over the time axis of the sequence inputs.

    inputs:  X (list: sequence tensors [b, t, ...] scanned per step as
             [b, ...]), Init (list: loop-carried states)
    outputs: Out (list: per-step stacked outputs [b, t, ...]),
             FinalStates (list: final carried states)
    attrs:   sub_block, x_names (names the per-step slices take inside the
             sub-block), state_names (carried var names, updated by the
             block writing the same name), out_names (per-step outputs to
             stack), reverse (bool), length_name (optional: an env var
             [b] of per-sample sequence lengths — carried states FREEZE
             on steps at/after a sample's length, the LoD semantics where
             padded steps do not exist; reference recurrent_op expands
             exactly len steps per sample).
    """
    program = ctx.program
    sub_blk = program.block(op.attrs["sub_block"])
    x_outer = op.inputs.get("X", [])
    init_outer = op.inputs.get("Init", [])
    x_names = op.attrs.get("x_names", [])
    state_names = op.attrs.get("state_names", [])
    out_names = op.attrs.get("out_names", [])
    reverse = op.attrs.get("reverse", False)
    length_name = op.attrs.get("length_name")

    xs = {inner: jnp.swapaxes(env[outer], 0, 1) for inner, outer in zip(x_names, x_outer)}
    if reverse:
        xs = {k: v[::-1] for k, v in xs.items()}
    init = {n: env[o] for n, o in zip(state_names, init_outer)}
    t_axis = next(iter(xs.values())).shape[0]
    steps = jnp.arange(t_axis)
    if reverse:
        steps = steps[::-1]  # step i processes original index t-1-i

    def step(carry, inp):
        x_slice, idx = inp
        local = dict(env)
        local.update(carry)
        local.update(x_slice)
        run_block_ops(ctx, sub_blk, sub_blk.ops, local)
        if length_name is not None:
            valid = idx < env[length_name]  # [b]
            for n in state_names:
                new, old = local[n], carry[n]
                m = valid.reshape((-1,) + (1,) * (new.ndim - 1)).astype(
                    new.dtype)
                local[n] = m * new + (1 - m) * old
        new_carry = {n: local[n] for n in state_names}
        ys = tuple(local[n] for n in out_names)
        return new_carry, ys

    final, stacked = jax.lax.scan(step, init, (xs, steps))
    outs = []
    for y in stacked:
        y = jnp.swapaxes(y, 0, 1)
        outs.append(y[:, ::-1] if reverse else y)
    if "Out" in op.outputs:
        for name, val in zip(op.outputs["Out"], outs):
            env[name] = val
    if "FinalStates" in op.outputs:
        for name, sname in zip(op.outputs["FinalStates"], state_names):
            env[name] = final[sname]


@register_op("parallel_do", raw=True)
def parallel_do(ctx, block, op, env):
    """Reference parallel_do_op.cc scattered inputs over PLACE_LIST with a
    thread pool and summed grads.  On TPU data parallelism is mesh sharding
    (paddle_tpu.parallel) — XLA partitions the *same* program.  This op
    therefore lowers to plain inline execution of its sub-block; the batch
    dimension's sharding annotation does the parallel part."""
    program = ctx.program
    sub_blk = program.block(op.attrs["sub_block"])
    run_block_ops(ctx, sub_blk, sub_blk.ops, env)


@register_op("feed", raw=True)
def feed(ctx, block, op, env):
    pass  # feeds are jit arguments; nothing to do


@register_op("fetch", raw=True)
def fetch(ctx, block, op, env):
    pass  # fetches are jit outputs


@register_op("print", raw=True)
def print_op(ctx, block, op, env):
    """FLAGS-controlled debug print (print_op.cc) via jax.debug.print —
    works inside compiled programs, unlike the reference's host-side loop."""
    name = op.inputs["In"][0]
    msg = op.attrs.get("message", "")
    jax.debug.print(msg + " {name} = {x}", name=name, x=env[name])
    if "Out" in op.outputs:
        env[op.outputs["Out"][0]] = env[name]
