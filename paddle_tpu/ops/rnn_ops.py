"""Recurrent ops — LSTM / GRU families.

Reference: ``lstm_op``, ``lstmp_op``, ``gru_op``, ``lstm_unit_op``,
``gru_unit_op`` batched via ``math/sequence2batch`` (reorder ragged
sequences into per-timestep dense batches) and fused CUDA cell kernels
(``hl_cuda_lstm.cu``, ``math/detail/lstm_kernel.h``).

TPU-native form: the batch is already padded dense [b, t, ...], so the
sequence2batch machinery vanishes — a single ``lax.scan`` over time runs the
cell; XLA unrolls the gate algebra onto MXU matmuls (the hidden-to-gates
GEMM dominates) and masking freezes finished rows.  Gate order convention:
i, f, c(candidate), o — gradients are consistent by construction (jax AD).
"""

import jax
import jax.numpy as jnp

from ..core.registry import register_op

# Time-scan unroll factor: 4 is ~25% faster on v5e than no unroll (fewer
# sequential-loop bubbles); 8 regresses (measured on the LSTM bench,
# bs64 h512 t100: 7.8ms vs 10.3 at 1, 12.1 at 8).
_SCAN_UNROLL = 4
from .sequence_ops import time_mask


def _act(name):
    return {
        "sigmoid": jax.nn.sigmoid,
        "tanh": jnp.tanh,
        "relu": jax.nn.relu,
        "identity": lambda x: x,
        "linear": lambda x: x,
    }[name]


def lstm_cell(x_gates, h, c, weight_hh, bias=None, peephole=None,
              gate_act="sigmoid", cell_act="tanh", cand_act="tanh"):
    """One LSTM step. x_gates [b, 4d] (input already projected), h/c [b, d],
    weight_hh [d, 4d]; peephole (wi, wf, wo) each [d] or None."""
    d = h.shape[-1]
    acc = jnp.float32 if h.dtype in (jnp.bfloat16, jnp.float16) else None
    gates = x_gates + jnp.dot(h, weight_hh.astype(h.dtype), preferred_element_type=acc).astype(h.dtype)
    if bias is not None:
        gates = gates + bias.astype(h.dtype)
    gi, gf, gc, go = jnp.split(gates, 4, axis=-1)
    ga, ca = _act(gate_act), _act(cand_act)
    if peephole is not None:
        wi, wf, wo = peephole
        i = ga(gi + wi * c)
        f = ga(gf + wf * c)
    else:
        i = ga(gi)
        f = ga(gf)
    c_new = f * c + i * ca(gc)
    if peephole is not None:
        o = ga(go + peephole[2] * c_new)
    else:
        o = ga(go)
    h_new = o * _act(cell_act)(c_new)
    return h_new, c_new


@register_op("lstm")
def lstm(
    Input, Weight, Bias=None, H0=None, C0=None, Length=None,
    use_peepholes=False, is_reverse=False,
    gate_activation="sigmoid", cell_activation="tanh", candidate_activation="tanh",
    **_,
):
    """Full-sequence LSTM (lstm_op.cc).  Input [b, t, 4d] (pre-projected,
    as in the reference where the input GEMM is a separate fc), Weight
    [d, 4d] recurrent weights, Bias [4d] or [7d] with peepholes."""
    b, t, d4 = Input.shape
    d = d4 // 4
    h0 = H0 if H0 is not None else jnp.zeros((b, d), Input.dtype)
    c0 = C0 if C0 is not None else jnp.zeros((b, d), Input.dtype)
    peep = None
    bias = None
    if Bias is not None:
        if use_peepholes and Bias.shape[-1] == 7 * d:
            bias = Bias[..., : 4 * d].reshape(4 * d)
            wi, wf, wo = (Bias[..., 4 * d : 5 * d].reshape(d),
                          Bias[..., 5 * d : 6 * d].reshape(d),
                          Bias[..., 6 * d :].reshape(d))
            peep = (wi, wf, wo)
        else:
            bias = Bias.reshape(-1)

    mask = time_mask(Length, t, Input.dtype) if Length is not None else jnp.ones((b, t), Input.dtype)
    xs = jnp.swapaxes(Input, 0, 1)  # [t, b, 4d]
    ms = jnp.swapaxes(mask, 0, 1)[..., None]  # [t, b, 1]
    if is_reverse:
        xs, ms = xs[::-1], ms[::-1]

    def step(carry, xm):
        h, c = carry
        x, m = xm
        h_new, c_new = lstm_cell(
            x, h, c, Weight, bias, peep,
            gate_activation, cell_activation, candidate_activation,
        )
        h = m * h_new + (1 - m) * h
        c = m * c_new + (1 - m) * c
        return (h, c), (h, c)

    (_, _), (hs, cs) = jax.lax.scan(step, (h0, c0), (xs, ms),
                                    unroll=_SCAN_UNROLL)
    if is_reverse:
        hs, cs = hs[::-1], cs[::-1]
    return {
        "Hidden": jnp.swapaxes(hs, 0, 1),
        "Cell": jnp.swapaxes(cs, 0, 1),
    }


@register_op("lstmp")
def lstmp(
    Input, Weight, ProjWeight, Bias=None, H0=None, C0=None, Length=None,
    use_peepholes=False, is_reverse=False,
    gate_activation="sigmoid", cell_activation="tanh",
    candidate_activation="tanh", proj_activation="identity", **_,
):
    """LSTM with recurrent projection (lstmp_op.cc): hidden state projected
    to lower dim before recurrence.  Weight [p, 4d], ProjWeight [d, p]."""
    b, t, d4 = Input.shape
    d = d4 // 4
    p = ProjWeight.shape[1]
    h0 = H0 if H0 is not None else jnp.zeros((b, p), Input.dtype)
    c0 = C0 if C0 is not None else jnp.zeros((b, d), Input.dtype)
    bias = Bias.reshape(-1)[: 4 * d] if Bias is not None else None
    peep = None
    if Bias is not None and use_peepholes and Bias.reshape(-1).shape[0] == 7 * d:
        fb = Bias.reshape(-1)
        peep = (fb[4 * d : 5 * d], fb[5 * d : 6 * d], fb[6 * d :])
    pact = _act(proj_activation)

    mask = time_mask(Length, t, Input.dtype) if Length is not None else jnp.ones((b, t), Input.dtype)
    xs = jnp.swapaxes(Input, 0, 1)
    ms = jnp.swapaxes(mask, 0, 1)[..., None]
    if is_reverse:
        xs, ms = xs[::-1], ms[::-1]

    def step(carry, xm):
        r, c = carry
        x, m = xm
        h_new, c_new = lstm_cell(
            x, r, c, Weight, bias, peep,
            gate_activation, cell_activation, candidate_activation,
        )
        r_new = pact(jnp.dot(h_new, ProjWeight.astype(h_new.dtype)))
        r = m * r_new + (1 - m) * r
        c = m * c_new + (1 - m) * c
        return (r, c), r

    (_, _), rs = jax.lax.scan(step, (h0, c0), (xs, ms), unroll=_SCAN_UNROLL)
    if is_reverse:
        rs = rs[::-1]
    return {"Projection": jnp.swapaxes(rs, 0, 1)}


def gru_cell(x_gates, h, weight_hh, bias=None, gate_act="sigmoid", cand_act="tanh"):
    """x_gates [b, 3d] (order u, r, c), weight_hh [d, 3d] (u,r parts) with
    candidate part [d, d] at the tail — matches reference gru layout where
    candidate uses (r*h) @ W_c."""
    d = h.shape[-1]
    acc = jnp.float32 if h.dtype in (jnp.bfloat16, jnp.float16) else None
    w_ur = weight_hh[:, : 2 * d]
    w_c = weight_hh[:, 2 * d :]
    g = x_gates
    if bias is not None:
        g = g + bias.astype(h.dtype)
    g_ur = g[..., : 2 * d] + jnp.dot(h, w_ur.astype(h.dtype), preferred_element_type=acc).astype(h.dtype)
    ga, ca = _act(gate_act), _act(cand_act)
    u = ga(g_ur[..., :d])
    r = ga(g_ur[..., d:])
    c = ca(g[..., 2 * d :] + jnp.dot(r * h, w_c.astype(h.dtype), preferred_element_type=acc).astype(h.dtype))
    return u * h + (1 - u) * c


@register_op("gru")
def gru(
    Input, Weight, Bias=None, H0=None, Length=None, is_reverse=False,
    gate_activation="sigmoid", activation="tanh", **_,
):
    """Full-sequence GRU (gru_op.cc). Input [b, t, 3d], Weight [d, 3d]."""
    b, t, d3 = Input.shape
    d = d3 // 3
    h0 = H0 if H0 is not None else jnp.zeros((b, d), Input.dtype)
    bias = Bias.reshape(-1) if Bias is not None else None

    mask = time_mask(Length, t, Input.dtype) if Length is not None else jnp.ones((b, t), Input.dtype)
    xs = jnp.swapaxes(Input, 0, 1)
    ms = jnp.swapaxes(mask, 0, 1)[..., None]
    if is_reverse:
        xs, ms = xs[::-1], ms[::-1]

    def step(h, xm):
        x, m = xm
        h_new = gru_cell(x, h, Weight, bias, gate_activation, activation)
        h = m * h_new + (1 - m) * h
        return h, h

    _, hs = jax.lax.scan(step, h0, (xs, ms), unroll=_SCAN_UNROLL)
    if is_reverse:
        hs = hs[::-1]
    return {"Hidden": jnp.swapaxes(hs, 0, 1)}


@register_op("lstm_unit")
def lstm_unit(X, C_prev, forget_bias=0.0, **_):
    """Single fused LSTM cell step (lstm_unit_op.cc): X [b, 4d] packed
    gates, gate order i, f, c, o with tanh/sigmoid activations."""
    d = C_prev.shape[-1]
    gi, gf, gc, go = jnp.split(X, 4, axis=-1)
    i = jax.nn.sigmoid(gi)
    f = jax.nn.sigmoid(gf + forget_bias)
    c = f * C_prev + i * jnp.tanh(gc)
    h = jax.nn.sigmoid(go) * jnp.tanh(c)
    return {"C": c, "H": h}


@register_op("gru_unit")
def gru_unit(Input, HiddenPrev, Weight, Bias=None,
             gate_activation="sigmoid", activation="tanh", **_):
    """Single GRU step (gru_unit_op.cc)."""
    h = gru_cell(
        Input if Bias is None else Input + 0.0,  # bias added inside cell
        HiddenPrev, Weight, Bias, gate_activation, activation,
    )
    return {"Hidden": h}


@register_op("nested_rnn")
def nested_rnn(Input, Weight, Bias=None, H0=None, Length=None,
               SubLength=None, gate_activation="sigmoid",
               activation="tanh", **_):
    """Hierarchical (2-level) GRU over a nested batch (the reference's
    hierarchical-RNN capability: an outer recurrent group over
    SUB-sequences whose inner group's memory boots from the outer memory
    — ``gserver/tests/sequence_nest_rnn.conf`` /
    ``RecurrentGradientMachine`` nested expansion).

    Input [b, s, t, 3d] (pre-projected gru gates), Weight [d, 3d];
    Length [b] = sub-seqs per sample, SubLength [b, s] = items per
    sub-seq.  The inner GRU runs over each sub-sequence's items booted
    from the outer state; the outer state advances to the inner RNN's
    hidden at that sub-sequence's last valid item.  Because the state
    threads across sub-sequence boundaries, a nested run over a split
    sequence equals a flat GRU over its concatenation — the reference's
    test_RecurrentGradientMachine equivalence, pinned in tests.

    Returns Hidden [b, s, t, d] (inner hiddens; padded positions hold
    the carried state) and OuterHidden [b, s, d] (state after each
    sub-sequence)."""
    b, s, t, d3 = Input.shape
    d = d3 // 3
    h0 = H0 if H0 is not None else jnp.zeros((b, d), Input.dtype)
    bias = Bias.reshape(-1) if Bias is not None else None
    if Length is None:
        Length = jnp.full((b,), s, jnp.int32)
    if SubLength is None:
        SubLength = jnp.full((b, s), t, jnp.int32)
    outer_mask = (jnp.arange(s)[None, :] < Length[:, None])  # [b, s]
    sub = jnp.where(outer_mask, SubLength, 0)

    xs = jnp.swapaxes(Input, 0, 1)        # [s, b, t, 3d]
    subs = jnp.swapaxes(sub, 0, 1)        # [s, b]

    def outer_step(h, inp):
        x_sent, slen = inp                 # [b, t, 3d], [b]
        m = time_mask(slen, t, Input.dtype)[..., None]  # [b, t, 1]

        def inner_step(hh, xm):
            x, mm = xm
            h_new = gru_cell(x, hh, Weight, bias, gate_activation,
                             activation)
            hh = mm * h_new + (1 - mm) * hh
            return hh, hh

        h_last, hs = jax.lax.scan(
            inner_step, h, (jnp.swapaxes(x_sent, 0, 1),
                            jnp.swapaxes(m, 0, 1)),
            unroll=_SCAN_UNROLL)
        return h_last, (h_last, jnp.swapaxes(hs, 0, 1))

    _, (outer_hs, inner_hs) = jax.lax.scan(outer_step, h0, (xs, subs))
    return {"Hidden": jnp.swapaxes(inner_hs, 0, 1),
            "OuterHidden": jnp.swapaxes(outer_hs, 0, 1)}
