"""Beam search ops.

Reference: ``beam_search_op.cc`` / ``beam_search_decode_op.cc`` operate on
LoD beams with *dynamic* widths (finished beams shrink the LoD).  Dynamic
widths don't compile on TPU, so the TPU-native design is the standard
fixed-width masked beam (SURVEY §7 "hard parts"): beams keep constant width
k, finished hypotheses are frozen by masking (their score stops changing and
they only expand with end_id).  beam_search_decode backtracks parent
pointers stored per step — the functional analog of the reference's
SentenceVector tree walk.
"""

import jax
import jax.numpy as jnp

from ..core.registry import register_op


@register_op("beam_search", nondiff=True)
def beam_search(PreIds, PreScores, Scores, beam_size=4, end_id=1, **_):
    """One beam-search expansion step.

    PreIds [b, k] int64, PreScores [b, k] accumulated log-probs, Scores
    [b, k, V] step log-probs.  Returns SelectedIds [b, k], SelectedScores
    [b, k], ParentIdx [b, k] (which source beam each selected hypothesis
    extends).
    """
    b, k, v = Scores.shape
    finished = PreIds == end_id
    # finished beams: only continuation is end_id at zero added cost
    step_scores = jnp.where(
        finished[..., None],
        jnp.where(jnp.arange(v)[None, None, :] == end_id, 0.0, -1e38),
        Scores,
    )
    total = PreScores[..., None] + step_scores  # [b, k, V]
    flat = total.reshape(b, k * v)
    top_scores, top_idx = jax.lax.top_k(flat, k)
    parent = (top_idx // v).astype(jnp.int32)
    ids = (top_idx % v).astype(jnp.int32)
    return {
        "SelectedIds": ids,
        "SelectedScores": top_scores,
        "ParentIdx": parent,
    }


@register_op("beam_search_decode", nondiff=True)
def beam_search_decode(Ids, ParentIdx, Scores=None, end_id=1, **_):
    """Backtrack stored beams into full sequences.

    Ids/ParentIdx [T, b, k] from stacking beam_search outputs per step.
    Returns SentenceIds [b, k, T] (right side padded with end_id after the
    first end token) and SentenceScores [b, k] (final accumulated scores).
    """
    t, b, k = Ids.shape

    def backtrack(carry, step):
        beam_idx = carry  # [b, k] which beam at step+1 each final slot maps to
        ids_t, parent_t = step
        tok = jnp.take_along_axis(ids_t, beam_idx, axis=1)
        prev = jnp.take_along_axis(parent_t, beam_idx, axis=1)
        return prev, tok

    init = jnp.broadcast_to(jnp.arange(k, dtype=jnp.int32)[None, :], (b, k))
    _, toks = jax.lax.scan(
        backtrack, init, (Ids[::-1], ParentIdx[::-1])
    )  # [T, b, k] reversed
    sent = jnp.transpose(toks[::-1], (1, 2, 0))  # [b, k, T]
    # freeze everything after the first end_id to end_id
    is_end = sent == end_id
    seen_end = jnp.cumsum(is_end.astype(jnp.int32), axis=-1) - is_end.astype(jnp.int32)
    sent = jnp.where(seen_end > 0, jnp.asarray(end_id, sent.dtype), sent)
    out = {"SentenceIds": sent}
    if Scores is not None:
        out["SentenceScores"] = Scores[-1] if Scores.ndim == 3 else Scores
    return out


@register_op("beam_init", nondiff=True)
def beam_init(Ref, beam_size=4, bos_id=0, **_):
    """Initial beam state for a [b]-batched decode (batch taken from
    Ref's leading dim): Ids [b, k] = bos, Scores [b, k] = [0, -inf...]
    so the first expansion draws k distinct tokens from beam 0 only —
    the reference RecurrentGradientMachine's generation bootstrap
    (RecurrentGradientMachine.h:307 generateSequence)."""
    b = Ref.shape[0]
    k = int(beam_size)
    ids = jnp.full((b, k), int(bos_id), jnp.int32)
    scores = jnp.full((b, k), -1e38, jnp.float32).at[:, 0].set(0.0)
    return {"Ids": ids, "Scores": scores}


@register_op("beam_expand", nondiff=True)
def beam_expand(X, beam_size=4, **_):
    """Tile each sample's row beam_size times along axis 0:
    [b, ...] -> [b*k, ...] (the static-input expansion the reference
    performs when entering generation mode)."""
    return {"Out": jnp.repeat(X, int(beam_size), axis=0)}


@register_op("beam_gather", nondiff=True)
def beam_gather(X, Parent, **_):
    """Reorder per-beam state rows by the beam parents selected this
    step: X [b*k, ...], Parent [b, k] -> rows of X gathered so row
    (i*k + j) becomes X[i*k + Parent[i, j]] — the decoder-state
    shuffling the reference does when beams switch parents."""
    b, k = Parent.shape
    flat = (jnp.arange(b)[:, None] * k + Parent.astype(jnp.int32)).reshape(-1)
    return {"Out": jnp.take(X, flat, axis=0)}
