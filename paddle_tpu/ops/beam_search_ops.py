"""Beam search ops.

Reference: ``beam_search_op.cc`` / ``beam_search_decode_op.cc`` operate on
LoD beams with *dynamic* widths (finished beams shrink the LoD).  Dynamic
widths don't compile on TPU, so the TPU-native design is the standard
fixed-width masked beam (SURVEY §7 "hard parts"): beams keep constant width
k, finished hypotheses are frozen by masking (their score stops changing and
they only expand with end_id).  beam_search_decode backtracks parent
pointers stored per step — the functional analog of the reference's
SentenceVector tree walk.
"""

import jax
import jax.numpy as jnp

from ..core.registry import register_op


@register_op("beam_search", nondiff=True)
def beam_search(PreIds, PreScores, Scores, beam_size=4, end_id=1, **_):
    """One beam-search expansion step.

    PreIds [b, k] int64, PreScores [b, k] accumulated log-probs, Scores
    [b, k, V] step log-probs.  Returns SelectedIds [b, k], SelectedScores
    [b, k], ParentIdx [b, k] (which source beam each selected hypothesis
    extends).
    """
    b, k, v = Scores.shape
    finished = PreIds == end_id
    # finished beams: only continuation is end_id at zero added cost
    step_scores = jnp.where(
        finished[..., None],
        jnp.where(jnp.arange(v)[None, None, :] == end_id, 0.0, -1e38),
        Scores,
    )
    total = PreScores[..., None] + step_scores  # [b, k, V]
    flat = total.reshape(b, k * v)
    top_scores, top_idx = jax.lax.top_k(flat, k)
    parent = (top_idx // v).astype(jnp.int32)
    ids = (top_idx % v).astype(jnp.int32)
    return {
        "SelectedIds": ids,
        "SelectedScores": top_scores,
        "ParentIdx": parent,
    }


@register_op("beam_search_decode", nondiff=True)
def beam_search_decode(Ids, ParentIdx, Scores=None, end_id=1, **_):
    """Backtrack stored beams into full sequences.

    Ids/ParentIdx [T, b, k] from stacking beam_search outputs per step.
    Returns SentenceIds [b, k, T] (right side padded with end_id after the
    first end token) and SentenceScores [b, k] (final accumulated scores).
    """
    t, b, k = Ids.shape

    def backtrack(carry, step):
        beam_idx = carry  # [b, k] which beam at step+1 each final slot maps to
        ids_t, parent_t = step
        tok = jnp.take_along_axis(ids_t, beam_idx, axis=1)
        prev = jnp.take_along_axis(parent_t, beam_idx, axis=1)
        return prev, tok

    init = jnp.broadcast_to(jnp.arange(k, dtype=jnp.int32)[None, :], (b, k))
    _, toks = jax.lax.scan(
        backtrack, init, (Ids[::-1], ParentIdx[::-1])
    )  # [T, b, k] reversed
    sent = jnp.transpose(toks[::-1], (1, 2, 0))  # [b, k, T]
    # freeze everything after the first end_id to end_id
    is_end = sent == end_id
    seen_end = jnp.cumsum(is_end.astype(jnp.int32), axis=-1) - is_end.astype(jnp.int32)
    sent = jnp.where(seen_end > 0, jnp.asarray(end_id, sent.dtype), sent)
    out = {"SentenceIds": sent}
    if Scores is not None:
        out["SentenceScores"] = Scores[-1] if Scores.ndim == 3 else Scores
    return out
