"""Learning-rate schedules as program state.

Reference: the legacy LearningRateScheduler family
(paddle/parameter/LearningRateScheduler.cpp: poly/exp/discexp/linear) and
the pserver-side lr policies (paddle/optimizer/lr_policy.h).  Each schedule
here maintains a persistable step counter incremented inside the program and
computes the decayed LR as an ordinary (jitted) op chain; pass the returned
Variable as an optimizer's learning_rate."""

from .layers.layer_helper import LayerHelper
from . import initializer as init_mod

__all__ = [
    "exponential_decay",
    "natural_exp_decay",
    "inverse_time_decay",
    "polynomial_decay",
]


def _global_step(helper):
    step = helper.create_global_variable(
        shape=[1], dtype="float32", name=f"{helper.name}.step",
        initializer=init_mod.Constant(0.0),
    )
    op = helper.append_op(
        type="increment", inputs={"X": [step.name]}, outputs={"Out": [step.name]},
        attrs={"step": 1.0},
    )
    # training-state write: clone(for_test=True) must strip it, else every
    # eval batch advances the schedule
    op.role = "optimize"
    return step


def _tmp(helper):
    return helper.create_tmp_variable("float32", [1], stop_gradient=True)


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    """lr * decay_rate ^ (step / decay_steps)"""
    helper = LayerHelper("exponential_decay")
    step = _global_step(helper)
    div = _tmp(helper)
    helper.append_op(
        type="scale", inputs={"X": [step.name]}, outputs={"Out": [div.name]},
        attrs={"scale": 1.0 / decay_steps},
    )
    if staircase:
        helper.append_op(type="floor", inputs={"X": [div.name]}, outputs={"Out": [div.name]})
    base = _tmp(helper)
    helper.append_op(
        type="fill_constant", outputs={"Out": [base.name]},
        attrs={"shape": [1], "dtype": "float32", "value": float(decay_rate)},
    )
    powed = _tmp(helper)
    helper.append_op(
        type="elementwise_pow", inputs={"X": [base.name], "Y": [div.name]},
        outputs={"Out": [powed.name]},
    )
    lr = _tmp(helper)
    helper.append_op(
        type="scale", inputs={"X": [powed.name]}, outputs={"Out": [lr.name]},
        attrs={"scale": float(learning_rate)},
    )
    return lr


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    """lr * exp(-decay_rate * step / decay_steps)"""
    helper = LayerHelper("natural_exp_decay")
    step = _global_step(helper)
    div = _tmp(helper)
    helper.append_op(
        type="scale", inputs={"X": [step.name]}, outputs={"Out": [div.name]},
        attrs={"scale": 1.0 / decay_steps},
    )
    if staircase:
        helper.append_op(type="floor", inputs={"X": [div.name]}, outputs={"Out": [div.name]})
    scaled = _tmp(helper)
    helper.append_op(
        type="scale", inputs={"X": [div.name]}, outputs={"Out": [scaled.name]},
        attrs={"scale": -float(decay_rate)},
    )
    e = _tmp(helper)
    helper.append_op(type="exp", inputs={"X": [scaled.name]}, outputs={"Out": [e.name]})
    lr = _tmp(helper)
    helper.append_op(
        type="scale", inputs={"X": [e.name]}, outputs={"Out": [lr.name]},
        attrs={"scale": float(learning_rate)},
    )
    return lr


def inverse_time_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    """lr / (1 + decay_rate * step / decay_steps)"""
    helper = LayerHelper("inverse_time_decay")
    step = _global_step(helper)
    div = _tmp(helper)
    helper.append_op(
        type="scale", inputs={"X": [step.name]}, outputs={"Out": [div.name]},
        attrs={"scale": float(decay_rate) / decay_steps},
    )
    if staircase:
        helper.append_op(type="floor", inputs={"X": [div.name]}, outputs={"Out": [div.name]})
    denom = _tmp(helper)
    helper.append_op(
        type="scale", inputs={"X": [div.name]}, outputs={"Out": [denom.name]},
        attrs={"scale": 1.0, "bias": 1.0},
    )
    recip = _tmp(helper)
    helper.append_op(type="reciprocal", inputs={"X": [denom.name]}, outputs={"Out": [recip.name]})
    lr = _tmp(helper)
    helper.append_op(
        type="scale", inputs={"X": [recip.name]}, outputs={"Out": [lr.name]},
        attrs={"scale": float(learning_rate)},
    )
    return lr


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=1e-4,
                     power=1.0, cycle=False):
    """(lr - end_lr) * (1 - min(step, decay_steps)/decay_steps)^power + end_lr"""
    helper = LayerHelper("polynomial_decay")
    step = _global_step(helper)
    capped = _tmp(helper)
    helper.append_op(
        type="clip", inputs={"X": [step.name]}, outputs={"Out": [capped.name]},
        attrs={"min": 0.0, "max": float(decay_steps)},
    )
    frac = _tmp(helper)
    helper.append_op(
        type="scale", inputs={"X": [capped.name]}, outputs={"Out": [frac.name]},
        attrs={"scale": -1.0 / decay_steps, "bias": 1.0},
    )
    powed = _tmp(helper)
    helper.append_op(
        type="pow", inputs={"X": [frac.name]}, outputs={"Out": [powed.name]},
        attrs={"factor": float(power)},
    )
    lr = _tmp(helper)
    helper.append_op(
        type="scale", inputs={"X": [powed.name]}, outputs={"Out": [lr.name]},
        attrs={"scale": float(learning_rate - end_learning_rate),
               "bias": float(end_learning_rate)},
    )
    return lr
