"""Graphviz dump of a Program (reference: fluid/net_drawer.py — draw the op
graph for debugging; also utils/make_model_diagram.py for v1 configs).
Pure-text DOT output; no graphviz dependency required to generate."""

from .core.program import Parameter

__all__ = ["draw_graph", "save_dot"]

_OP_STYLE = 'shape=box, style="rounded,filled", fillcolor="#e8f0fe"'
_VAR_STYLE = 'shape=ellipse, fillcolor="#fef7e0", style=filled'
_PARAM_STYLE = 'shape=ellipse, fillcolor="#e6f4ea", style=filled'
_DATA_STYLE = 'shape=ellipse, fillcolor="#fce8e6", style=filled'


def _q(s):
    return '"' + str(s).replace('"', '\\"') + '"'


def draw_graph(program, block_idx=0, max_label=40):
    """Return a DOT string of one block's op/var graph."""
    block = program.block(block_idx)
    lines = [
        "digraph Program {",
        "  rankdir=TB;",
        "  node [fontsize=10];",
    ]
    seen_vars = set()

    def var_node(name):
        if name in seen_vars:
            return
        seen_vars.add(name)
        var = block._find_var(name)
        if isinstance(var, Parameter):
            style = _PARAM_STYLE
        elif var is not None and var.is_data:
            style = _DATA_STYLE
        else:
            style = _VAR_STYLE
        label = name
        if var is not None and var.shape:
            label += f"\\n{list(var.shape)}"
        lines.append(f"  {_q('var_' + name)} [label={_q(label)}, {style}];")

    for i, op in enumerate(block.ops):
        marker = "*" if block.backward_index == i else ""
        op_id = f"op_{block_idx}_{i}"
        attrs = ", ".join(
            f"{k}={v}" for k, v in op.attrs.items()
            if not isinstance(v, (list, tuple)) or len(str(v)) < 12
        )[:max_label]
        label = f"{i}{marker}: {op.type}" + (f"\\n{attrs}" if attrs else "")
        lines.append(f"  {_q(op_id)} [label={_q(label)}, {_OP_STYLE}];")
        for n in op.input_names():
            var_node(n)
            lines.append(f"  {_q('var_' + n)} -> {_q(op_id)};")
        for n in op.output_names():
            var_node(n)
            lines.append(f"  {_q(op_id)} -> {_q('var_' + n)};")
    lines.append("}")
    return "\n".join(lines)


def save_dot(program, path, block_idx=0):
    dot = draw_graph(program, block_idx)
    with open(path, "w") as f:
        f.write(dot)
    return path
