"""Gradient / error clipping (reference: fluid/clip.py — ErrorClipByValue,
GradientClipByValue, GradientClipByNorm, GradientClipByGlobalNorm)."""

from .core import unique_name
from .core.program import Variable


def _tmp_like(block, ref, tag):
    v = Variable(
        block, name=unique_name.generate(f"{ref.name}.{tag}"),
        shape=ref.shape, dtype=ref.dtype, stop_gradient=True,
    )
    block.vars[v.name] = v
    return v


class BaseGradientClipAttr:
    def _append_clip_op(self, block, grad):
        raise NotImplementedError


class NullGradientClipAttr(BaseGradientClipAttr):
    def _append_clip_op(self, block, grad):
        return grad


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def _append_clip_op(self, block, grad):
        block.append_op(
            type="clip", inputs={"X": [grad.name]}, outputs={"Out": [grad.name]},
            attrs={"min": float(self.min), "max": float(self.max)},
        )
        return grad


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def _append_clip_op(self, block, grad):
        block.append_op(
            type="clip_by_norm", inputs={"X": [grad.name]},
            outputs={"Out": [grad.name]}, attrs={"max_norm": float(self.clip_norm)},
        )
        return grad


class GradientClipByGlobalNorm:
    """Global-norm clipping across a parameter group; applied in one pass by
    append_gradient_clip_ops (needs all grads together)."""

    def __init__(self, clip_norm):
        self.clip_norm = clip_norm


def append_gradient_clip_ops(param_grads, global_clip=None):
    """Apply per-param gradient_clip_attr, or a GradientClipByGlobalNorm over
    the whole list."""
    if isinstance(global_clip, GradientClipByGlobalNorm):
        block = param_grads[0][0].block
        # global_norm = sqrt(sum over params of sum(g^2))
        sq_norms = []
        for p, g in param_grads:
            sq = _tmp_like(block, g, "sq")
            block.append_op(
                type="squared_l2_norm", inputs={"X": [g.name]},
                outputs={"Out": [sq.name]},
            )
            sq.shape = (1,)
            sq_norms.append(sq)
        total = _tmp_like(block, sq_norms[0], "global_sq")
        total.shape = (1,)
        block.append_op(
            type="sum", inputs={"X": [v.name for v in sq_norms]},
            outputs={"Out": [total.name]},
        )
        gnorm = _tmp_like(block, total, "global_norm")
        block.append_op(type="sqrt", inputs={"X": [total.name]}, outputs={"Out": [gnorm.name]})
        # factor = clip_norm / max(global_norm, clip_norm)
        cn = _tmp_like(block, gnorm, "clip_norm")
        block.append_op(
            type="fill_constant", outputs={"Out": [cn.name]},
            attrs={"shape": [1], "dtype": gnorm.dtype.name,
                   "value": float(global_clip.clip_norm)},
        )
        maxed = _tmp_like(block, gnorm, "maxed")
        block.append_op(
            type="elementwise_max", inputs={"X": [gnorm.name], "Y": [cn.name]},
            outputs={"Out": [maxed.name]},
        )
        factor = _tmp_like(block, gnorm, "factor")
        block.append_op(
            type="elementwise_div", inputs={"X": [cn.name], "Y": [maxed.name]},
            outputs={"Out": [factor.name]},
        )
        for p, g in param_grads:
            block.append_op(
                type="elementwise_mul", inputs={"X": [g.name], "Y": [factor.name]},
                outputs={"Out": [g.name]}, attrs={"axis": 0},
            )
        return param_grads

    result = []
    for p, g in param_grads:
        clip_attr = getattr(p, "gradient_clip_attr", None)
        if clip_attr is not None:
            g = clip_attr._append_clip_op(p.block, g)
        result.append((p, g))
    return result


class ErrorClipByValue:
    """Clip the backpropagated error at a variable to [min, max]
    (reference fluid/clip.py:37 ErrorClipByValue)."""

    def __init__(self, max, min=None):
        if min is None and max <= 0:
            raise ValueError(
                f"ErrorClipByValue needs max > 0 when min is omitted "
                f"(got max={max}); the range is [-max, max]")
        self.max = float(max)
        self.min = float(min) if min is not None else None


def error_clip_callback(var, clip_attr):
    """Apply an ErrorClipByValue to ``var``: rewrites the program so the
    gradient flowing back through ``var`` is clipped, leaving the forward
    value unchanged.

    The reference rewrites the grad-op list (clip.py error_clip_callback);
    here gradients come from tracing, so the rewrite inserts an identity
    op with a clipped-cotangent custom VJP right after ``var``'s producer
    and points all later consumers at it.
    """
    from .core.program import OpDesc

    block = var.block
    producer = None
    for i, op in enumerate(block.ops):
        if var.name in op.output_names():
            producer = i
    if producer is None:
        raise ValueError(f"{var.name!r} has no producing op in its block")
    clipped = _tmp_like(block, var, "error_clip")
    clipped.stop_gradient = False
    for op in block.ops[producer + 1:]:
        for slot, names in op.inputs.items():
            op.inputs[slot] = [
                clipped.name if n == var.name else n for n in names
            ]
    attrs = {"max": clip_attr.max}
    if clip_attr.min is not None:
        attrs["min"] = clip_attr.min
    pos = producer + 1
    block.ops.insert(
        pos,
        OpDesc("error_clip", {"X": [var.name]}, {"Out": [clipped.name]},
               attrs),
    )
    # keep the forward/backward split (and any remat segment indices)
    # pointing at the same ops after the insert
    if block.backward_index is not None and pos <= block.backward_index:
        block.backward_index += 1
    segs = getattr(block.program, "_remat_segments", None)
    if segs:
        block.program._remat_segments = [
            (seg[0] + (pos <= seg[0]), seg[1] + (pos <= seg[1]), *seg[2:])
            for seg in segs
        ]
    block.program._bump_version()
    return clipped
