"""Unique name generator (reference: python/paddle/v2/fluid/framework.py
``unique_name`` and the v1 config_parser name mangling)."""

import contextlib
import threading

_lock = threading.Lock()
_counters = {}
_prefix_stack = []


def generate(key):
    with _lock:
        idx = _counters.get(key, 0)
        _counters[key] = idx + 1
    prefix = "/".join(_prefix_stack)
    name = f"{key}_{idx}"
    return f"{prefix}/{name}" if prefix else name


@contextlib.contextmanager
def guard(prefix=None):
    """Scope generated names (and reset counters inside tests)."""
    global _counters
    if prefix is not None:
        _prefix_stack.append(prefix)
        try:
            yield
        finally:
            _prefix_stack.pop()
    else:
        saved = dict(_counters)
        try:
            yield
        finally:
            with _lock:
                _counters = saved


def reset():
    with _lock:
        _counters.clear()
