"""Backward-pass memory audit: prove scan locality at the jaxpr/HLO level.

The BENCH_r05 failure mode was invisible to the compile-succeeds check:
every ``memory_optimize`` policy *compiled* at t=16k, but the flagship
step died at runtime with ~20 per-layer ``bf16[6,16384,768]`` HLO temps
coexisting at the flash-attention backward ``pallas_call``s — per-layer
backward residuals alive across the whole layer stack instead of one
layer at a time.  This module makes that property checkable without an
accelerator:

* ``jaxpr_report`` walks the step's jaxpr and reports every
  ``pallas_call`` with its scan-nesting depth — the locality invariant is
  "every flash call sits INSIDE a ``lax.scan`` body and none of its
  operands/results carries a leading layer-count axis" (a ``[L, t, d]``
  pallas operand means the per-layer kernel calls were stacked/hoisted
  out of the loop, exactly the r05 shape);
* ``audit_program`` lowers a Program through the Executor, builds the
  report, and (CPU included — ``CompiledMemoryStats`` works on every
  backend) attaches ``temp_bytes`` / ``hbm_high_water_bytes`` from
  ``compiled.memory_analysis()`` plus optimized-HLO shape probes.

The checkpoint-name tags shared by the kernels (``ops/pallas_attention``,
``ops/pallas_ce``) and the Executor's offload scan body live here:
under ``memory_optimize(policy="offload")`` each wrapped sub-segment's
``jax.checkpoint`` carries a name policy that streams ``BLOCK_INPUT_TAG``
values (the per-layer residual-stream inputs) to pinned host memory and
keeps ``KERNEL_RESIDUAL_TAG`` values (custom-VJP kernel residuals) in
device memory.
"""

import re

import numpy as np

__all__ = [
    "KERNEL_RESIDUAL_TAG", "BLOCK_INPUT_TAG",
    "jaxpr_report", "audit_program", "compiled_memory_stats",
]

# Residuals a custom-VJP kernel saves for its own backward (the flash
# contract is exactly (q, k, v, o, lse); the fused CE head's is its lse).
# Tagged INSIDE the kernels' fwd rules so a name-policy checkpoint keeps
# them instead of re-running the kernel in the backward pass.
KERNEL_RESIDUAL_TAG = "pt_kernel_res"

# The per-layer block input (the residual stream entering each scanned
# layer) — the one stacked [L, b, t, d] residual the offload policy
# moves to pinned host memory on the forward scan and prefetches back
# during the backward scan.
BLOCK_INPUT_TAG = "pt_blk_in"


def _jaxpr_types():
    """(ClosedJaxpr, Jaxpr) from the supported ``jax.extend.core``
    location, falling back to the legacy ``jax.core`` aliases on older
    releases."""
    try:
        from jax.extend import core as _jex_core

        return _jex_core.ClosedJaxpr, _jex_core.Jaxpr
    except (ImportError, AttributeError):
        import jax

        return jax.core.ClosedJaxpr, jax.core.Jaxpr


def _sub_jaxprs(eqn):
    closed_t, jaxpr_t = _jaxpr_types()
    for v in eqn.params.values():
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for x in vs:
            if isinstance(x, closed_t):
                yield x.jaxpr
            elif isinstance(x, jaxpr_t):
                yield x


def _aval_bytes(aval):
    try:
        return int(np.prod(aval.shape) * np.dtype(aval.dtype).itemsize)
    except Exception:
        return 0


def jaxpr_report(jaxpr, layer_count=None):
    """Walk a (Closed)Jaxpr and report kernel-call scan locality.

    Returns a dict:

    * ``pallas_calls``: one entry per ``pallas_call`` eqn —
      ``{"scan_depth", "shapes"}`` (operand+result shapes);
    * ``pallas_total`` / ``pallas_outside_scan``: counts (a backward
      whose flash calls were unrolled per layer shows up here as
      ``pallas_outside_scan > 0`` and ``pallas_total`` scaling with L);
    * ``scan_lengths``: the ``length`` of every scan eqn;
    * ``layer_stacked_pallas``: pallas operand/result shapes whose
      LEADING dim equals ``layer_count`` — the hoisted-out-of-the-loop
      form that exhausted HBM in BENCH_r05 (must be empty);
    * ``residual_stacks``: outputs of layer-count-length scans with a
      leading ``layer_count`` axis (the EXPECTED per-layer saved
      residuals), largest first, as ``{"shape", "dtype", "bytes"}``.
    """
    closed_t, _ = _jaxpr_types()
    if isinstance(jaxpr, closed_t):
        jaxpr = jaxpr.jaxpr
    report = {
        "pallas_calls": [],
        "pallas_total": 0,
        "pallas_outside_scan": 0,
        "scan_lengths": [],
        "layer_stacked_pallas": [],
        "residual_stacks": [],
    }

    def walk(jx, depth):
        for eqn in jx.eqns:
            name = eqn.primitive.name
            if name == "pallas_call":
                shapes = [tuple(v.aval.shape)
                          for v in list(eqn.invars) + list(eqn.outvars)
                          if hasattr(v, "aval")
                          and hasattr(v.aval, "shape")]
                report["pallas_calls"].append(
                    {"scan_depth": depth, "shapes": shapes})
                report["pallas_total"] += 1
                if depth == 0:
                    report["pallas_outside_scan"] += 1
                if layer_count:
                    report["layer_stacked_pallas"] += [
                        s for s in shapes
                        if len(s) >= 2 and s[0] == layer_count]
            if name == "scan":
                length = eqn.params.get("length")
                report["scan_lengths"].append(length)
                if layer_count and length == layer_count:
                    for v in eqn.outvars:
                        aval = getattr(v, "aval", None)
                        shape = getattr(aval, "shape", ())
                        if len(shape) >= 1 and shape[0] == layer_count:
                            report["residual_stacks"].append({
                                "shape": tuple(shape),
                                "dtype": str(aval.dtype),
                                "bytes": _aval_bytes(aval),
                            })
            next_depth = depth + (1 if name in ("scan", "while") else 0)
            for sub in _sub_jaxprs(eqn):
                walk(sub, next_depth)

    walk(jaxpr, 0)
    report["residual_stacks"].sort(key=lambda r: -r["bytes"])
    return report


def compiled_memory_stats(compiled):
    """``compiled.memory_analysis()`` flattened into the fields the rest
    of the stack reports: ``temp_bytes``, ``argument_bytes``,
    ``output_bytes``, and ``hbm_high_water_bytes`` (XLA's own
    liveness-aware peak when the backend reports one, else
    argument+output+temp minus donation aliasing).  ``{}`` when the
    backend has no memory analysis."""
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return {}
    if mem is None:
        return {}
    temp = int(getattr(mem, "temp_size_in_bytes", 0) or 0)
    arg = int(getattr(mem, "argument_size_in_bytes", 0) or 0)
    out = int(getattr(mem, "output_size_in_bytes", 0) or 0)
    alias = int(getattr(mem, "alias_size_in_bytes", 0) or 0)
    peak = int(getattr(mem, "peak_memory_in_bytes", 0) or 0)
    high = peak if peak else max(0, arg + out + temp - alias)
    return {
        "temp_bytes": temp,
        "argument_bytes": arg,
        "output_bytes": out,
        "hbm_high_water_bytes": high,
    }


def _shape_pattern(shape):
    return re.compile(r"\[" + ",".join(str(int(s)) for s in shape) + r"\]")


def audit_program(program, feed, fetch_list, scope=None, layer_count=None,
                  compile_stats=True, absent_shapes=()):
    """Lower ``program`` through a fresh Executor, trace the full step
    (forward+backward+optimizer) and return ``jaxpr_report`` extended
    with compile-time memory figures.

    ``absent_shapes``: iterable of shape tuples that must NOT appear in
    the optimized HLO text (e.g. ``(num_layers, t, d_model)`` — the
    BENCH_r05 failure shape); hit counts land in
    ``report["absent_shape_hits"]``.

    The scope must already hold the program's parameters (run the
    startup program into it first).  CPU-safe: used by the tier-1
    regression test and ``python -m paddle_tpu --memory-selftest``.
    """
    import jax

    from .executor import Executor

    exe = Executor()
    (program, scope, feed_names, fetch_names, feed_vals, state_names,
     state, _sig) = exe._prepare(program, feed, fetch_list, scope)
    step, _persist = exe.lower(program, feed_names, fetch_names, state_names)
    # one trace serves both the jaxpr walk and (via .lower) the compile
    traced = jax.jit(step).trace(state, *feed_vals)
    report = jaxpr_report(traced.jaxpr, layer_count=layer_count)
    report["scan_remat_plan"] = list(getattr(exe, "last_remat_plan", []) or [])
    if compile_stats:
        compiled = traced.lower().compile()
        report.update(compiled_memory_stats(compiled))
        if absent_shapes:
            try:
                text = compiled.as_text()
            except Exception:
                text = ""
            report["absent_shape_hits"] = {
                tuple(s): len(_shape_pattern(s).findall(text))
                for s in absent_shapes
            }
    return report
