"""Backward-pass memory audit: prove scan locality at the jaxpr/HLO level.

The BENCH_r05 failure mode was invisible to the compile-succeeds check:
every ``memory_optimize`` policy *compiled* at t=16k, but the flagship
step died at runtime with ~20 per-layer ``bf16[6,16384,768]`` HLO temps
coexisting at the flash-attention backward ``pallas_call``s — per-layer
backward residuals alive across the whole layer stack instead of one
layer at a time.  This module makes that property checkable without an
accelerator:

* ``jaxpr_report`` walks the step's jaxpr and reports every
  ``pallas_call`` with its scan-nesting depth — the locality invariant is
  "every flash call sits INSIDE a ``lax.scan`` body and none of its
  operands/results carries a leading layer-count axis" (a ``[L, t, d]``
  pallas operand means the per-layer kernel calls were stacked/hoisted
  out of the loop, exactly the r05 shape);
* ``audit_program`` lowers a Program through the Executor, builds the
  report, and (CPU included — ``CompiledMemoryStats`` works on every
  backend) attaches ``temp_bytes`` / ``hbm_high_water_bytes`` from
  ``compiled.memory_analysis()`` plus optimized-HLO shape probes.

The checkpoint-name tags shared by the kernels (``ops/pallas_attention``,
``ops/pallas_ce``) and the Executor's offload scan body live here:
under ``memory_optimize(policy="offload")`` each wrapped sub-segment's
``jax.checkpoint`` carries a name policy that streams ``BLOCK_INPUT_TAG``
values (the per-layer residual-stream inputs) to pinned host memory and
keeps ``KERNEL_RESIDUAL_TAG`` values (custom-VJP kernel residuals) in
device memory.
"""

import re

import numpy as np

__all__ = [
    "KERNEL_RESIDUAL_TAG", "BLOCK_INPUT_TAG",
    "jaxpr_report", "audit_program", "compiled_memory_stats",
    "hlo_comm_report", "comm_report", "REDUCE_COLLECTIVES",
]

# Residuals a custom-VJP kernel saves for its own backward (the flash
# contract is exactly (q, k, v, o, lse); the fused CE head's is its lse).
# Tagged INSIDE the kernels' fwd rules so a name-policy checkpoint keeps
# them instead of re-running the kernel in the backward pass.
KERNEL_RESIDUAL_TAG = "pt_kernel_res"

# The per-layer block input (the residual stream entering each scanned
# layer) — the one stacked [L, b, t, d] residual the offload policy
# moves to pinned host memory on the forward scan and prefetches back
# during the backward scan.
BLOCK_INPUT_TAG = "pt_blk_in"


def _jaxpr_types():
    """(ClosedJaxpr, Jaxpr) from the supported ``jax.extend.core``
    location, falling back to the legacy ``jax.core`` aliases on older
    releases."""
    try:
        from jax.extend import core as _jex_core

        return _jex_core.ClosedJaxpr, _jex_core.Jaxpr
    except (ImportError, AttributeError):
        import jax

        return jax.core.ClosedJaxpr, jax.core.Jaxpr


def _sub_jaxprs(eqn):
    closed_t, jaxpr_t = _jaxpr_types()
    for v in eqn.params.values():
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for x in vs:
            if isinstance(x, closed_t):
                yield x.jaxpr
            elif isinstance(x, jaxpr_t):
                yield x


def _aval_bytes(aval):
    try:
        return int(np.prod(aval.shape) * np.dtype(aval.dtype).itemsize)
    except Exception:
        return 0


def jaxpr_report(jaxpr, layer_count=None):
    """Walk a (Closed)Jaxpr and report kernel-call scan locality.

    Returns a dict:

    * ``pallas_calls``: one entry per ``pallas_call`` eqn —
      ``{"scan_depth", "shapes"}`` (operand+result shapes);
    * ``pallas_total`` / ``pallas_outside_scan``: counts (a backward
      whose flash calls were unrolled per layer shows up here as
      ``pallas_outside_scan > 0`` and ``pallas_total`` scaling with L);
    * ``scan_lengths``: the ``length`` of every scan eqn;
    * ``layer_stacked_pallas``: pallas operand/result shapes whose
      LEADING dim equals ``layer_count`` — the hoisted-out-of-the-loop
      form that exhausted HBM in BENCH_r05 (must be empty);
    * ``residual_stacks``: outputs of layer-count-length scans with a
      leading ``layer_count`` axis (the EXPECTED per-layer saved
      residuals), largest first, as ``{"shape", "dtype", "bytes"}``.
    """
    closed_t, _ = _jaxpr_types()
    if isinstance(jaxpr, closed_t):
        jaxpr = jaxpr.jaxpr
    report = {
        "pallas_calls": [],
        "pallas_total": 0,
        "pallas_outside_scan": 0,
        "scan_lengths": [],
        "layer_stacked_pallas": [],
        "residual_stacks": [],
    }

    def walk(jx, depth):
        for eqn in jx.eqns:
            name = eqn.primitive.name
            if name == "pallas_call":
                shapes = [tuple(v.aval.shape)
                          for v in list(eqn.invars) + list(eqn.outvars)
                          if hasattr(v, "aval")
                          and hasattr(v.aval, "shape")]
                report["pallas_calls"].append(
                    {"scan_depth": depth, "shapes": shapes})
                report["pallas_total"] += 1
                if depth == 0:
                    report["pallas_outside_scan"] += 1
                if layer_count:
                    report["layer_stacked_pallas"] += [
                        s for s in shapes
                        if len(s) >= 2 and s[0] == layer_count]
            if name == "scan":
                length = eqn.params.get("length")
                report["scan_lengths"].append(length)
                if layer_count and length == layer_count:
                    for v in eqn.outvars:
                        aval = getattr(v, "aval", None)
                        shape = getattr(aval, "shape", ())
                        if len(shape) >= 1 and shape[0] == layer_count:
                            report["residual_stacks"].append({
                                "shape": tuple(shape),
                                "dtype": str(aval.dtype),
                                "bytes": _aval_bytes(aval),
                            })
            next_depth = depth + (1 if name in ("scan", "while") else 0)
            for sub in _sub_jaxprs(eqn):
                walk(sub, next_depth)

    walk(jaxpr, 0)
    report["residual_stacks"].sort(key=lambda r: -r["bytes"])
    return report


def compiled_memory_stats(compiled):
    """``compiled.memory_analysis()`` flattened into the fields the rest
    of the stack reports: ``temp_bytes``, ``argument_bytes``,
    ``output_bytes``, and ``hbm_high_water_bytes`` (XLA's own
    liveness-aware peak when the backend reports one, else
    argument+output+temp minus donation aliasing).  ``{}`` when the
    backend has no memory analysis."""
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return {}
    if mem is None:
        return {}
    temp = int(getattr(mem, "temp_size_in_bytes", 0) or 0)
    arg = int(getattr(mem, "argument_size_in_bytes", 0) or 0)
    out = int(getattr(mem, "output_size_in_bytes", 0) or 0)
    alias = int(getattr(mem, "alias_size_in_bytes", 0) or 0)
    peak = int(getattr(mem, "peak_memory_in_bytes", 0) or 0)
    high = peak if peak else max(0, arg + out + temp - alias)
    return {
        "temp_bytes": temp,
        "argument_bytes": arg,
        "output_bytes": out,
        "hbm_high_water_bytes": high,
    }


def _shape_pattern(shape):
    return re.compile(r"\[" + ",".join(str(int(s)) for s in shape) + r"\]")


# ---------------------------------------------------------------------------
# Cross-chip communication audit: the comm analogue of the scan-locality
# walk above.  GSPMD *inserts* the collectives at compile time, so the
# jaxpr never shows them — the only place the "one gradient reduction per
# optimizer step" invariant is checkable is the partitioned optimized HLO.
# The load-bearing classification is LOOP MEMBERSHIP: a reduce op inside a
# while body executes once per loop iteration (the per-microbatch
# gradient all-reduce of a naive accumulation loop), one at top level
# executes once per step.  Static op counts alone cannot tell the two
# apart.

# collectives that REDUCE across chips (gradient aggregation); gathers /
# permutes move activations and are reported separately
REDUCE_COLLECTIVES = ("all-reduce", "reduce-scatter")
_GATHER_COLLECTIVES = ("all-gather", "collective-permute", "all-to-all",
                       "collective-broadcast")
_ALL_COLLECTIVES = REDUCE_COLLECTIVES + _GATHER_COLLECTIVES

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}

_COMP_RE = re.compile(r"^(?:ENTRY\s+)?%([\w.\-]+)\s*\(")
_CALL_RE = re.compile(
    r"(?:body|condition|to_apply|calls)=%?([\w.\-]+)")
_BRANCH_RE = re.compile(r"branch_computations=\{([^}]*)\}")
# lhs shapes may be a tuple — async ``-start`` forms return
# ``(operand..., result...)`` — so the shape-list class admits parens
_COLL_RE = re.compile(
    r"=\s*(\(?[\w\[\]{},:*/() ]*?)\s*"
    r"\b(" + "|".join(_ALL_COLLECTIVES) + r")((?:-start)?)[.\d]*\(")
_SHAPE_RE = re.compile(r"(\w+)\[([0-9,]*)\]")


def _shape_bytes_list(text):
    sizes = []
    for dtype, dims in _SHAPE_RE.findall(text):
        if dtype not in _DTYPE_BYTES:
            continue  # token[] etc.
        numel = 1
        for d in dims.split(","):
            if d:
                numel *= int(d)
        sizes.append(numel * _DTYPE_BYTES[dtype])
    return sizes


def _collective_bytes(shape_text, is_start):
    """Output bytes of one collective.  Async ``-start`` forms return an
    ``(operands..., results...)`` tuple — counting the whole tuple would
    double the figure the moment latency hiding rewrites the op, so take
    the result half (the last shape when the split is uneven, e.g.
    all-gather-start's small operand / big result)."""
    sizes = _shape_bytes_list(shape_text)
    if is_start and len(sizes) > 1:
        if len(sizes) % 2 == 0:
            return sum(sizes[len(sizes) // 2:])
        return sizes[-1]
    return sum(sizes)


def hlo_comm_report(text):
    """Parse optimized (post-SPMD) HLO text and report every cross-chip
    collective: static counts and output bytes per kind, split by whether
    the op sits inside a while-loop body (directly, or in a computation a
    loop body calls).  Keys:

    * ``collective_ops``: ``{kind: count}`` (async ``-start`` forms count
      once — and contribute their RESULT bytes only, not the whole
      operand+result tuple — ``-done`` not at all);
    * ``collective_count`` / ``collective_bytes``: totals;
    * ``reduce_ops`` / ``reduce_bytes``: the REDUCE class (all-reduce +
      reduce-scatter) — gradient aggregation;
    * ``reduce_ops_in_loop`` / ``reduce_bytes_in_loop``: reduce ops that
      execute once per loop iteration.  The comm-aware accumulation
      invariant is exactly ``reduce_ops_in_loop == 0``: every gradient is
      cross-chip-reduced once per optimizer step, at the boundary;
    * ``collectives_in_loop`` / ``collective_bytes_in_loop``: all kinds
      (attention-internal gathers land here — reported, not gated).
    """
    bodies = set(re.findall(r"body=%?([\w.\-]+)", text))
    bodies |= set(re.findall(r"condition=%?([\w.\-]+)", text))

    # one-level call graph so a collective inside a computation CALLED
    # from a while body still counts as in-loop
    edges = {}
    cur = None
    colls = []  # (kind, bytes, computation)
    for line in text.splitlines():
        m = _COMP_RE.match(line)
        if m:
            cur = m.group(1)
        head = line.split(" metadata=", 1)[0]
        for ref in _CALL_RE.findall(head):
            edges.setdefault(cur, set()).add(ref)
        for grp in _BRANCH_RE.findall(head):
            for ref in grp.split(","):
                edges.setdefault(cur, set()).add(
                    ref.strip().lstrip("%"))
        cm = _COLL_RE.search(head)
        if cm:
            colls.append((cm.group(2),
                          _collective_bytes(cm.group(1),
                                            bool(cm.group(3))),
                          cur))

    in_loop = set()
    frontier = list(bodies)
    while frontier:
        c = frontier.pop()
        if c in in_loop:
            continue
        in_loop.add(c)
        frontier.extend(edges.get(c, ()))

    report = {
        "collective_ops": {},
        "collective_count": 0, "collective_bytes": 0,
        "reduce_ops": 0, "reduce_bytes": 0,
        "reduce_ops_in_loop": 0, "reduce_bytes_in_loop": 0,
        "collectives_in_loop": 0, "collective_bytes_in_loop": 0,
    }
    for kind, nbytes, comp in colls:
        report["collective_ops"][kind] = (
            report["collective_ops"].get(kind, 0) + 1)
        report["collective_count"] += 1
        report["collective_bytes"] += nbytes
        looped = comp in in_loop
        if looped:
            report["collectives_in_loop"] += 1
            report["collective_bytes_in_loop"] += nbytes
        if kind in REDUCE_COLLECTIVES:
            report["reduce_ops"] += 1
            report["reduce_bytes"] += nbytes
            if looped:
                report["reduce_ops_in_loop"] += 1
                report["reduce_bytes_in_loop"] += nbytes
    return report


def comm_report(compiled):
    """``hlo_comm_report`` over a compiled executable's optimized HLO;
    ``{}`` when the backend cannot render it."""
    try:
        text = compiled.as_text()
    except Exception:
        return {}
    if not text:
        return {}
    return hlo_comm_report(text)


def audit_program(program, feed, fetch_list, scope=None, layer_count=None,
                  compile_stats=True, absent_shapes=()):
    """Lower ``program`` through a fresh Executor, trace the full step
    (forward+backward+optimizer) and return ``jaxpr_report`` extended
    with compile-time memory figures.

    ``absent_shapes``: iterable of shape tuples that must NOT appear in
    the optimized HLO text (e.g. ``(num_layers, t, d_model)`` — the
    BENCH_r05 failure shape); hit counts land in
    ``report["absent_shape_hits"]``.

    The scope must already hold the program's parameters (run the
    startup program into it first).  CPU-safe: used by the tier-1
    regression test and ``python -m paddle_tpu --memory-selftest``.
    """
    import jax

    from .executor import Executor

    exe = Executor()
    (program, scope, feed_names, fetch_names, feed_vals, state_names,
     state, _sig) = exe._prepare(program, feed, fetch_list, scope)
    step, _persist = exe.lower(program, feed_names, fetch_names, state_names)
    # one trace serves both the jaxpr walk and (via .lower) the compile
    traced = jax.jit(step).trace(state, *feed_vals)
    report = jaxpr_report(traced.jaxpr, layer_count=layer_count)
    report["scan_remat_plan"] = list(getattr(exe, "last_remat_plan", []) or [])
    if compile_stats:
        compiled = traced.lower().compile()
        report.update(compiled_memory_stats(compiled))
        if absent_shapes:
            try:
                text = compiled.as_text()
            except Exception:
                text = ""
            report["absent_shape_hits"] = {
                tuple(s): len(_shape_pattern(s).findall(text))
                for s in absent_shapes
            }
    return report
