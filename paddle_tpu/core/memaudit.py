"""DEPRECATED shim — the memory/comm audits moved to
``paddle_tpu.analysis`` (the static-analysis pass framework).

PR 4 built the scan-locality audit here and PR 5 added the cross-chip
comm audit; the analysis engine (``paddle_tpu/analysis/``) generalized
both into registered lint checks over three artifact levels.  Every
public name this module used to export keeps working:

* ``KERNEL_RESIDUAL_TAG`` / ``BLOCK_INPUT_TAG``  -> ``analysis.jaxpr_tools``
* ``jaxpr_report``                               -> ``analysis.jaxpr_tools``
* ``hlo_comm_report`` / ``comm_report``          -> ``analysis.hlo_tools``
* ``compiled_memory_stats``                      -> ``analysis.hlo_tools``
* ``audit_program``                              -> ``analysis.audit_program``
* ``REDUCE_COLLECTIVES``                         -> ``analysis.hlo_tools``

New code should import from ``paddle_tpu.analysis`` directly; these
wrappers emit a ``DeprecationWarning`` once per function and delegate.
"""

import functools
import warnings

from ..analysis.jaxpr_tools import (  # noqa: F401 — compat re-exports
    BLOCK_INPUT_TAG,
    KERNEL_RESIDUAL_TAG,
)
from ..analysis.hlo_tools import REDUCE_COLLECTIVES  # noqa: F401
from ..analysis import jaxpr_tools as _jaxpr_tools
from ..analysis import hlo_tools as _hlo_tools

__all__ = [
    "KERNEL_RESIDUAL_TAG", "BLOCK_INPUT_TAG",
    "jaxpr_report", "audit_program", "compiled_memory_stats",
    "hlo_comm_report", "comm_report", "REDUCE_COLLECTIVES",
]

_warned = set()


def _shim(name, target_name, fn):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if name not in _warned:
            _warned.add(name)
            warnings.warn(
                f"paddle_tpu.core.memaudit.{name} is deprecated; use "
                f"paddle_tpu.analysis.{target_name}",
                DeprecationWarning, stacklevel=2)
        return fn(*args, **kwargs)

    return wrapper


jaxpr_report = _shim("jaxpr_report", "jaxpr_report",
                     _jaxpr_tools.jaxpr_report)
hlo_comm_report = _shim("hlo_comm_report", "hlo_comm_report",
                        _hlo_tools.hlo_comm_report)
comm_report = _shim("comm_report", "comm_report", _hlo_tools.comm_report)
compiled_memory_stats = _shim("compiled_memory_stats",
                              "compiled_memory_stats",
                              _hlo_tools.compiled_memory_stats)


def audit_program(program, feed, fetch_list, scope=None, layer_count=None,
                  compile_stats=True, absent_shapes=()):
    """Deprecated: use ``paddle_tpu.analysis.audit_program``."""
    if "audit_program" not in _warned:
        _warned.add("audit_program")
        warnings.warn(
            "paddle_tpu.core.memaudit.audit_program is deprecated; use "
            "paddle_tpu.analysis.audit_program",
            DeprecationWarning, stacklevel=2)
    from ..analysis import audit_program as _audit

    return _audit(program, feed, fetch_list, scope=scope,
                  layer_count=layer_count, compile_stats=compile_stats,
                  absent_shapes=absent_shapes)
