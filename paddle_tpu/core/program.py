"""Program / Block / Variable / OpDesc — program-as-data.

Mirrors the *capability* of the reference Fluid IR:

* ``ProgramDesc{repeated BlockDesc}``   (reference framework.proto:148)
* ``BlockDesc{idx, parent_idx, vars, ops}``           (framework.proto:138)
* ``OpDesc{type, inputs, outputs, attrs}``            (framework.proto:35)
* Python mirrors ``Variable/Operator/Block/Program``  (fluid/framework.py:125,350,621,789)

but is designed for XLA: a Program is a *trace recipe*.  The Executor walks a
block once at compile time, calls each op's pure-JAX implementation, and jits
the whole thing.  Control-flow ops hold sub-blocks (the reference stores a
BLOCK attribute, framework.proto:29) which lower to ``lax.scan`` /
``lax.while_loop`` / ``lax.cond`` — compiler-friendly structured control flow
instead of interpreter re-entry with STEP_SCOPES.

Variable-length sequences: a Variable may carry ``lod_level > 0``.  Instead of
LoD offset vectors riding on the tensor (lod_tensor.h:58) the convention is a
shadow int32 variable ``<name>@LENGTH`` of shape [batch] (padded dense data +
explicit lengths = the static-shape form XLA wants).  ``Block.length_var``
creates/finds it; the DataFeeder fills both from ragged Python lists.
"""

import collections
import itertools
import contextlib
import copy

import numpy as np

from . import unique_name
from .dtypes import convert_dtype

LENGTH_SUFFIX = "@LENGTH"
SUBLENGTH_SUFFIX = "@SUBLENGTH"
GRAD_SUFFIX = "@GRAD"
# sparse input slots feed as two shadow arrays (ids + weights) — the
# no-densify path for reference sparse_binary/float_vector inputs
IDS_SUFFIX = "@IDS"
VALS_SUFFIX = "@VALS"


class Variable:
    """A named, statically-shaped tensor slot in a Block."""

    def __init__(
        self,
        block,
        name=None,
        shape=None,
        dtype="float32",
        lod_level=0,
        persistable=False,
        stop_gradient=False,
        is_data=False,
        initializer=None,
    ):
        self.block = block
        self.name = name or unique_name.generate("tmp")
        self.shape = tuple(int(s) if s is not None and s >= 0 else -1 for s in (shape or ()))
        self.dtype = convert_dtype(dtype)
        self.lod_level = lod_level
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.initializer = initializer
        # Optional jax.sharding.PartitionSpec set by the parallel layer.
        self.partition_spec = None

    @property
    def program(self):
        return self.block.program

    def grad_name(self):
        return self.name + GRAD_SUFFIX

    def length_var(self):
        """The shadow sequence-length variable (lod replacement)."""
        return self.block.length_var(self)

    def sub_length_var(self):
        """The shadow inner-level length variable (2-level lod)."""
        return self.block.sub_length_var(self)

    def __repr__(self):
        return (
            f"Variable(name={self.name!r}, shape={self.shape}, "
            f"dtype={self.dtype.name}, lod_level={self.lod_level}, "
            f"persistable={self.persistable})"
        )

    # Arithmetic sugar (fluid gained this later; users expect it).
    def _binary(self, other, op):
        from .. import layers

        if not isinstance(other, Variable):
            other = layers.fill_constant(
                shape=[1], dtype=self.dtype, value=float(other)
            )
        return getattr(layers, op)(self, other)

    def __add__(self, other):
        return self._binary(other, "elementwise_add")

    def __sub__(self, other):
        return self._binary(other, "elementwise_sub")

    def __mul__(self, other):
        return self._binary(other, "elementwise_mul")

    def __truediv__(self, other):
        return self._binary(other, "elementwise_div")

    __radd__ = __add__
    __rmul__ = __mul__

    def __rtruediv__(self, other):
        from .. import layers

        const = layers.fill_constant(
            shape=[1], dtype=self.dtype, value=float(other)
        )
        return layers.elementwise_div(const, self)

    def __rsub__(self, other):
        from .. import layers

        return layers.scale(self, scale=-1.0, bias=float(other))

    def __neg__(self):
        from .. import layers

        return layers.scale(self, scale=-1.0)


class Parameter(Variable):
    """A trainable persistable variable (fluid/framework.py:931)."""

    def __init__(self, block, **kwargs):
        self.trainable = kwargs.pop("trainable", True)
        self.regularizer = kwargs.pop("regularizer", None)
        self.gradient_clip_attr = kwargs.pop("gradient_clip_attr", None)
        self.optimize_attr = kwargs.pop("optimize_attr", {"learning_rate": 1.0})
        kwargs.setdefault("persistable", True)
        super().__init__(block, **kwargs)


class OpDesc:
    """One operator invocation: type + named input/output var lists + attrs.

    ``inputs`` / ``outputs`` map slot name -> list of variable names
    (duplicable slots, e.g. ``sum``'s X, hold several; reference
    OpProto.Var.duplicable, framework.proto:70).
    """

    def __init__(self, op_type, inputs=None, outputs=None, attrs=None):
        self.type = op_type
        self.inputs = {k: list(v) for k, v in (inputs or {}).items()}
        self.outputs = {k: list(v) for k, v in (outputs or {}).items()}
        self.attrs = dict(attrs or {})
        # "forward" | "optimize": optimize-role ops (param/state updates
        # appended by minimize, ModelAverage, ...) are stripped by
        # clone(for_test=True); position alone can't distinguish them
        # from eval-only ops appended after minimize.
        self.role = "forward"

    def input_names(self):
        return [n for vs in self.inputs.values() for n in vs]

    def output_names(self):
        return [n for vs in self.outputs.values() for n in vs]

    def __repr__(self):
        ins = {k: v for k, v in self.inputs.items()}
        outs = {k: v for k, v in self.outputs.items()}
        return f"Op({self.type}, in={ins}, out={outs}, attrs={list(self.attrs)})"


def _as_name_list(v):
    if v is None:
        return []
    if isinstance(v, (list, tuple)):
        return [x.name if isinstance(x, Variable) else str(x) for x in v]
    return [v.name if isinstance(v, Variable) else str(v)]


class Block:
    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars = collections.OrderedDict()
        self.ops = []
        # Index into ``ops`` where the backward pass conceptually sits: ops
        # before it are the forward program, ops at/after it run with
        # ``<param>@GRAD`` variables available (optimizer/regularizer/clip
        # ops).  None until append_backward marks it.
        self.backward_index = None

    @property
    def parent(self):
        return self.program.blocks[self.parent_idx] if self.parent_idx >= 0 else None

    def create_var(self, **kwargs):
        var = Variable(self, **kwargs)
        if var.name in self.vars:
            raise ValueError(f"variable {var.name!r} already exists in block {self.idx}")
        self.vars[var.name] = var
        return var

    def create_parameter(self, **kwargs):
        # Parameters always live in the top-level (global) block, like the
        # reference where sub-block programs reference outer-scope params.
        gb = self.program.global_block()
        param = Parameter(gb, **kwargs)
        if param.name in gb.vars:
            raise ValueError(f"parameter {param.name!r} already exists")
        gb.vars[param.name] = param
        return param

    def var(self, name):
        v = self._find_var(name)
        if v is None:
            raise KeyError(f"variable {name!r} not found in block {self.idx}")
        return v

    def _find_var(self, name):
        blk = self
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = blk.parent
        return None

    def has_var(self, name):
        return self._find_var(name) is not None

    def length_var(self, var):
        """Create/find the shadow ``<name>@LENGTH`` int32 [batch] variable."""
        name = var.name + LENGTH_SUFFIX
        existing = self._find_var(name)
        if existing is not None:
            return existing
        batch = var.shape[0] if var.shape else -1
        owner = var.block
        lv = Variable(
            owner, name=name, shape=(batch,), dtype="int32", is_data=var.is_data,
            stop_gradient=True,
        )
        owner.vars[name] = lv
        return lv

    def sub_length_var(self, var):
        """Create/find the shadow ``<name>@SUBLENGTH`` int32 [batch, s]
        variable — the INNER level's per-sub-sequence lengths of a
        2-level (nested) sequence batch [b, s, t, ...] (reference
        ``Argument.subSequenceStartPositions``, Argument.h:84-86;
        ``lod_tensor.h:58``'s second LoD level)."""
        name = var.name + SUBLENGTH_SUFFIX
        existing = self._find_var(name)
        if existing is not None:
            return existing
        batch = var.shape[0] if var.shape else -1
        s = var.shape[1] if len(var.shape) > 1 else -1
        owner = var.block
        lv = Variable(
            owner, name=name, shape=(batch, s), dtype="int32",
            is_data=var.is_data, stop_gradient=True,
        )
        owner.vars[name] = lv
        return lv

    def append_op(self, type, inputs=None, outputs=None, attrs=None):
        inputs = {k: _as_name_list(v) for k, v in (inputs or {}).items() if v is not None}
        outputs = {k: _as_name_list(v) for k, v in (outputs or {}).items() if v is not None}
        op = OpDesc(type, inputs, outputs, attrs)
        self.ops.append(op)
        self.program._bump_version()
        return op

    def prepend_op(self, type, inputs=None, outputs=None, attrs=None):
        inputs = {k: _as_name_list(v) for k, v in (inputs or {}).items() if v is not None}
        outputs = {k: _as_name_list(v) for k, v in (outputs or {}).items() if v is not None}
        op = OpDesc(type, inputs, outputs, attrs)
        self.ops.insert(0, op)
        if self.backward_index is not None:
            self.backward_index += 1
        self.program._bump_version()
        return op

    def all_parameters(self):
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    def __repr__(self):
        return f"Block(idx={self.idx}, ops={len(self.ops)}, vars={len(self.vars)})"


_program_serial = itertools.count()


class Program:
    """A list of blocks; block 0 is the global block (framework.py:789)."""

    def __init__(self):
        # unique across the process lifetime — id() can be reused after GC,
        # which would poison the Executor's compile cache
        self._serial = next(_program_serial)
        self.blocks = [Block(self, 0)]
        self.current_block_idx = 0
        self.random_seed = 0
        self._version = 0
        self._seed_counter = 0
        # Filled by append_backward: {block_idx: {"loss": name,
        #   "params": [names], "grad_map": {pname: gname}}}
        self._backward_info = {}

    # -- structure ---------------------------------------------------------
    def global_block(self):
        return self.blocks[0]

    def block(self, idx):
        return self.blocks[idx]

    def current_block(self):
        return self.blocks[self.current_block_idx]

    def create_block(self, parent_idx=None):
        parent = self.current_block_idx if parent_idx is None else parent_idx
        blk = Block(self, len(self.blocks), parent_idx=parent)
        self.blocks.append(blk)
        self.current_block_idx = blk.idx
        return blk

    def rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    def _bump_version(self):
        self._version += 1

    def next_seed(self):
        """Deterministic per-op seed stream for random ops."""
        self._seed_counter += 1
        return (self.random_seed, self._seed_counter)

    # -- queries -----------------------------------------------------------
    def list_vars(self):
        for blk in self.blocks:
            yield from blk.vars.values()

    def all_parameters(self):
        return self.global_block().all_parameters()

    def persistable_vars(self):
        return [v for v in self.global_block().vars.values() if v.persistable]

    # -- transformations ---------------------------------------------------
    def clone(self, for_test=False):
        """Deep copy; ``for_test=True`` flips ``is_test`` attrs AND strips
        everything from the backward marker on (grad, optimizer, and any
        later state-update ops) so evaluating the clone cannot mutate
        parameters (the analog of the reference's inference_optimize,
        pybind.cc:299)."""
        p = copy.deepcopy(self)  # fresh _serial via __setstate__
        if for_test:
            for blk in p.blocks:
                blk.ops = [op for op in blk.ops
                           if getattr(op, "role", "forward") != "optimize"]
                blk.backward_index = None
                for op in blk.ops:
                    if "is_test" in op.attrs:
                        op.attrs["is_test"] = True
            p._backward_info = {}
        return p

    def __setstate__(self, state):
        # fresh identity on deepcopy/unpickle: the Executor caches compiled
        # steps keyed on (_serial, _version); a copy must never collide.
        self.__dict__.update(state)
        self._serial = next(_program_serial)

    def prune(self, targets):
        """Backward-slice the global block to ops needed for ``targets``
        (reference framework/prune.cc, pybind.cc:289)."""
        from .ir import prune_program

        return prune_program(self, targets)

    def to_string(self, throw_on_error=False):
        lines = []
        for blk in self.blocks:
            lines.append(f"// block {blk.idx} (parent {blk.parent_idx})")
            for v in blk.vars.values():
                kind = "param" if isinstance(v, Parameter) else (
                    "data" if v.is_data else "var")
                lines.append(
                    f"  {kind} {v.name}: {v.dtype.name}{list(v.shape)}"
                    + (f" lod={v.lod_level}" if v.lod_level else "")
                    + (" persistable" if v.persistable else "")
                )
            for i, op in enumerate(blk.ops):
                marker = " // <-- backward" if blk.backward_index == i else ""
                lines.append(f"  {i}: {op}{marker}")
        return "\n".join(lines)

    __str__ = to_string


# ---------------------------------------------------------------------------
# Default program registry (fluid/framework.py default_main_program pattern)
# ---------------------------------------------------------------------------
_main_program = Program()
_startup_program = Program()


def default_main_program():
    return _main_program


def default_startup_program():
    return _startup_program


def switch_main_program(program):
    global _main_program
    prev, _main_program = _main_program, program
    return prev


def switch_startup_program(program):
    global _startup_program
    prev, _startup_program = _startup_program, program
    return prev


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    prev_main = switch_main_program(main_program)
    prev_startup = None
    if startup_program is not None:
        prev_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(prev_main)
        if prev_startup is not None:
            switch_startup_program(prev_startup)


@contextlib.contextmanager
def name_scope(prefix):
    with unique_name.guard(prefix):
        yield
