"""Scope — name -> device array environment.

Reference: ``paddle/framework/scope.h`` (Scope = name->Variable map with a
parent chain; executor creates a local scope per run).  Here a Scope holds
the *persistable* state between Executor runs: parameters, optimizer moments,
batch-norm stats, metric accumulators and the RNG key — all jax.Arrays living
on device.  Non-persistable intermediates never materialize: they are fused
away inside the jitted step.
"""

import contextlib

import jax
import numpy as np

RNG_VAR = "@RNG@"
# the step's global gradient norm, emitted by the Executor alongside the
# state (training-dynamics telemetry: trainer.grad_norm gauge, JSONL,
# flight-recorder NaN window); like @RNG@ it is scope state, not a
# Program variable
GRAD_NORM_VAR = "@GRAD_NORM@"


class Scope:
    def __init__(self, parent=None):
        self._vars = {}
        self.parent = parent

    def find_var(self, name):
        s = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s.parent
        return None

    def var_names(self):
        return list(self._vars)

    def get(self, name):
        v = self.find_var(name)
        if v is None:
            raise KeyError(f"variable {name!r} not found in scope")
        return v

    def set(self, name, value):
        self._vars[name] = value

    def update(self, mapping):
        self._vars.update(mapping)

    def delete(self, name):
        self._vars.pop(name, None)

    def numpy(self, name):
        return np.asarray(self.get(name))

    def new_scope(self):
        return Scope(parent=self)

    def ensure_rng(self, seed=0):
        if self.find_var(RNG_VAR) is None:
            self.set(RNG_VAR, jax.random.PRNGKey(seed))


_global_scope = Scope()
_scope_stack = [_global_scope]


def global_scope():
    return _scope_stack[-1]


@contextlib.contextmanager
def scope_guard(scope):
    _scope_stack.append(scope)
    try:
        yield scope
    finally:
        _scope_stack.pop()
