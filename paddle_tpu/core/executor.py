"""Executor — lowers a Program to one jitted pure function and runs it.

The reference Executor (``paddle/framework/executor.cc:79``) is a sequential
per-op interpreter: every step it re-creates operators, re-runs InferShape,
picks kernels and enqueues them one by one.  That design is wrong for TPU:
XLA wants the *whole* step as a single traced computation so it can fuse
elementwise chains into matmuls, overlap transfers, and tile onto the MXU.

So this Executor walks the block ONCE (at compile time), calling each op's
pure-JAX implementation to build a function

    step(state, *feed) -> (state', fetches)

where ``state`` is the dict of persistable arrays (parameters, optimizer
moments, BN stats, metric accumulators, RNG key) and jits it with donated
state buffers (in-place parameter updates at the XLA level).  Autodiff: if
``append_backward`` marked the block, the forward prefix is differentiated
with ``jax.grad`` and ``<param>@GRAD`` values are injected into the
environment before the remaining (optimizer) ops run — the functional analog
of the reference's MakeBlockBackward-generated gradient ops
(``backward.cc:415``).

Compiled steps are cached keyed on (program identity+version, feed signature,
fetch list, available state) — the analog of the reference caching nothing
and paying interpreter overhead per op per step.
"""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name

from ..observability import metrics as _obs
from ..analysis.jaxpr_tools import BLOCK_INPUT_TAG, KERNEL_RESIDUAL_TAG
from .program import Program, Parameter, default_main_program, GRAD_SUFFIX
from .registry import get_op_impl
from .scope import Scope, global_scope, GRAD_NORM_VAR, RNG_VAR
from .place import CPUPlace, TPUPlace

_pinned_host_cache = []


def _pinned_host_available():
    """True when device 0 exposes a ``pinned_host`` memory space (TPU/GPU
    with memories enabled) — the offload policy's transfer target.  A
    positive/negative ANSWER is cached per process; a transient probe
    failure (backend not yet initialized) is NOT cached, so a later call
    can still discover the memory space instead of silently pinning the
    process to the degraded "save" mode."""
    if not _pinned_host_cache:
        try:
            mems = jax.devices()[0].addressable_memories()
        except Exception:
            return False  # transient: do not cache
        _pinned_host_cache.append(
            any(m.kind == "pinned_host" for m in mems))
    return _pinned_host_cache[0]


def _offload_mode(program):
    """How the scan body should run an offload-marked program:
    ``"host"`` — stream block inputs to pinned host memory; ``"save"`` —
    same name-policy checkpoint structure with block inputs left in
    device memory (backends without a pinned_host space, e.g. CPU —
    keeps the structure testable off-accelerator); ``"off"`` — not an
    offload program, or killed via ``PADDLE_TPU_OFFLOAD=0`` (falls back
    to plain selective execution)."""
    if not getattr(program, "_offload", False):
        return "off"
    if os.environ.get("PADDLE_TPU_OFFLOAD", "1").lower() in (
            "0", "", "false"):
        return "off"
    return "host" if _pinned_host_available() else "save"


def _offload_ckpt_policy(mode):
    """The name-based checkpoint policy for a wrapped sub-segment under
    the offload policy: kernel residuals (should a kernel ever land
    inside a wrapped segment) stay in device memory; block inputs are
    offloaded (mode "host") or saved in place (mode "save"); everything
    untagged rematerializes, exactly like a default ``jax.checkpoint``."""
    cp = jax.checkpoint_policies
    if mode == "host":
        return cp.save_and_offload_only_these_names(
            names_which_can_be_saved=[KERNEL_RESIDUAL_TAG],
            names_which_can_be_offloaded=[BLOCK_INPUT_TAG],
            offload_src="device", offload_dst="pinned_host")
    return cp.save_only_these_names(
        KERNEL_RESIDUAL_TAG, BLOCK_INPUT_TAG)


def _grad_norm_enabled():
    """Training-dynamics telemetry kill switch: ``PADDLE_TPU_GRADNORM=0``
    drops the global grad-norm output from the step entirely (the scope
    never grows the ``@GRAD_NORM@`` entry and the compiled step is
    byte-identical to the pre-telemetry spelling)."""
    return os.environ.get("PADDLE_TPU_GRADNORM", "1").lower() not in (
        "0", "", "false", "off", "no")


def _emits_grad_norm(program):
    """True when the step function for ``program`` will emit the
    ``@GRAD_NORM@`` state entry: a marked backward exists and the kill
    switch is on.  ``_prepare`` (carry structure), ``compile_shardings``
    (pytree match) and ``lower`` (the emission itself) must all agree —
    this predicate is the single source of that decision."""
    if not _grad_norm_enabled():
        return False
    block = program.global_block()
    return (block.backward_index is not None
            and program._backward_info.get(0) is not None)


def _scan_strict():
    """PADDLE_TPU_SCAN_REMAT=strict: a uniform group that fails to scan
    RAISES (with the classification error) instead of silently falling
    back to the barrier spelling — the guard for capacity configs where
    an unrolled backward means a runtime HBM OOM (BENCH_r05)."""
    return os.environ.get("PADDLE_TPU_SCAN_REMAT", "").lower() == "strict"


def _tag_named(v, tag):
    """checkpoint_name for inexact arrays; anything else passes through
    (names on integer/key values are pointless and some backends reject
    them)."""
    try:
        if jnp.issubdtype(jnp.result_type(v), jnp.inexact):
            return checkpoint_name(v, tag)
    except TypeError:
        pass
    return v


def _fsdp_fwd_pin(sharding, site="fsdp"):
    """Forward-only sharding constraint: the primal is pinned to
    ``sharding``, the cotangent passes through UNPINNED.  Both FSDP
    pins use it — the at-rest stack pin (``P(None, *spec)``: at-rest
    bytes divide by the fsdp degree) and the in-body per-layer gather
    (the fsdp-free spec: GSPMD emits the all-gather inside the loop
    body and XLA frees the gathered copy when the iteration's uses
    finish).

    ``site`` names the blessed constraint-placement site: the pin is
    applied under a ``pt_pin[site]`` named scope, which (a) marks it
    blessed for the ``jaxpr.constraint-placement`` check — any in-scan
    constraint WITHOUT the marker is an error — and (b) rides the HLO
    ``op_name`` metadata so the CommPlan extractor attributes the
    collectives GSPMD derives from this pin back to the site
    (docs/analysis.md "Communication contracts").

    Why not a plain ``with_sharding_constraint``?  It transposes to
    itself, constraining the BACKWARD too — the gather's transpose
    forces every per-layer dW to full replication inside the backward
    scan, and the stack pin's transpose (or any sharded dW constraint)
    makes GSPMD feature-shard the saved residuals, turning the in-body
    LN/softmax reductions into partial sums plus in-loop all-reduces
    (measured: 19-49 in-loop reduce ops on the dp2 x fsdp4 mesh,
    depending on spelling).  Left free, the dW values stay replicated
    over fsdp all the way to the optimizer boundary, where the
    elementwise update against the fsdp-sharded moments reads them
    shard-locally (a free slice, outside every loop)."""

    scope = f"pt_pin[{site}]"

    @jax.custom_vjp
    def pin(x):
        with jax.named_scope(scope):
            return jax.lax.with_sharding_constraint(x, sharding)

    def pin_fwd(x):
        with jax.named_scope(scope):
            return jax.lax.with_sharding_constraint(x, sharding), None

    def pin_bwd(_, ct):
        return (ct,)

    pin.defvjp(pin_fwd, pin_bwd)
    return pin


def _accum_carry_spec(lead):
    """The accumulation carry's pin spec: the GROUP axis shards over
    plain ``dp`` and nothing else (docs/parallel.md constraint-placement
    rule 3 — an fsdp-composed carry makes GSPMD feature-shard the saved
    residuals into in-loop partial sums).  Module-level so the sharding
    selftest can plant the composed-spelling defect and prove the
    ``jaxpr.constraint-placement`` check catches it."""
    from jax.sharding import PartitionSpec

    return PartitionSpec(*([None] * lead + ["dp"]))


def _ensure_barrier_batch_rule():
    """``jax.lax.optimization_barrier`` has no batching rule in this jax
    (0.4.x) — vmapping a barrier-remat segment (the comm-aware
    accumulation loop vmaps the microbatch forward+backward over device
    groups) dies with NotImplementedError and silently forfeits local
    accumulation.  The barrier is identity per operand, so the rule is
    the trivial pass-through; upstream jax added exactly this later.
    Registered once, only if absent."""
    try:
        from jax._src.lax import lax as _llax
        from jax.interpreters import batching

        prim = getattr(_llax, "optimization_barrier_p", None)
        if prim is not None and prim not in batching.primitive_batchers:
            def _rule(args, dims, **params):
                return prim.bind(*args, **params), dims

            batching.primitive_batchers[prim] = _rule
    except Exception:  # noqa: BLE001 — newer jax ships its own rule
        pass


_ensure_barrier_batch_rule()


def _remat_segment(seg_fn, env, param_names=()):
    """``jax.checkpoint``-equivalent for one forward segment whose backward
    recompute is made DATA-DEPENDENT on the incoming cotangents via
    ``optimization_barrier``.

    This is the FALLBACK path for non-uniform segments.  Plain
    ``jax.checkpoint`` on a flat (unrolled) layer stack lets XLA's
    scheduler hoist every segment's rematted forward to the start of the
    backward — all layers' recomputed activations end up live at once and
    remat saves nothing (measured: GPT t=16k bs8 sat at 22.6 GB with the
    OOM dump showing 10+ rematted 768 MB FFN tiles alive together).
    ``lax.scan`` over layers is the canonical fix — the scan-remat engine
    (``_run_fwd``'s ``_try_scan_group``) runs structurally repeated
    segments exactly that way, with weights stacked along the scan axis —
    but a Program's non-repeating segments (prologue/epilogue, irregular
    nets) still need serialization; the barrier gives it — segment k's
    recompute cannot start until segment k+1's backward has produced k's
    output cotangents."""

    def _inexact(x):
        try:
            return jnp.issubdtype(jnp.result_type(x), jnp.inexact)
        except TypeError:
            return False

    @jax.custom_vjp
    def run(env):
        return seg_fn(env)

    def run_fwd(env):
        return seg_fn(env), env

    def run_bwd(env, ct):
        fkeys = sorted(k for k, v in env.items() if _inexact(v))
        ckeys = sorted(k for k, v in ct.items() if _inexact(v))
        env_f, ct_f = jax.lax.optimization_barrier(
            ([env[k] for k in fkeys], [ct[k] for k in ckeys]))
        env2 = dict(env)
        env2.update(zip(fkeys, env_f))
        ct2 = dict(ct)
        ct2.update(zip(ckeys, ct_f))
        _, vjp_fn = jax.vjp(seg_fn, env2)
        (denv,) = vjp_fn(ct2)
        # Tie the outgoing activation cotangents to this segment's weight
        # gradients with a REAL data dependency.  Without it XLA defers
        # every segment's dW matmuls (nothing consumes dW until the
        # optimizer at the very end), keeping their big recomputed
        # operands alive across the whole backward — measured as 12+
        # concurrent 768 MB tiles on GPT t=16k bs8, which nullified remat
        # entirely.  (A multi-operand optimization_barrier did NOT stop
        # the deferral.)  `tie = s - s` is exactly 0.0 for finite grads
        # but not constant-foldable for floats, so the residual-stream
        # cotangent that unblocks the previous segment's backward now
        # requires every dW of this segment to be finished.
        pkeys = [k for k in param_names if k in denv
                 and _inexact(denv[k])]
        if pkeys:
            s = sum(jnp.sum(denv[k].astype(jnp.float32)) for k in pkeys)
            tie = s - s
            denv = dict(denv)
            for k, v in denv.items():
                if k not in param_names and _inexact(v):
                    denv[k] = v + tie.astype(v.dtype)
        return (denv,)

    run.defvjp(run_fwd, run_bwd)
    return run(env)


def _scan_groups_for(program, segments):
    """Uniform (scan-able) groups among the program's remat segments,
    cached on the program keyed by (version, segment list).  Only groups
    whose period contains at least one WRAPPED segment qualify — the scan
    engine exists to give remat O(1)-per-layer temps; pure saved runs gain
    nothing from restructuring.  ``PADDLE_TPU_SCAN_REMAT=0`` disables the
    engine entirely (every wrapped segment falls back to the barrier)."""
    if os.environ.get("PADDLE_TPU_SCAN_REMAT", "1").lower() in (
            "0", "", "false"):
        return []
    key = (program._version, tuple(tuple(s) for s in segments))
    cached = getattr(program, "_scan_group_cache", None)
    if cached is not None and cached[0] == key:
        return cached[1]
    from .ir import find_uniform_groups

    groups = []
    for g in find_uniform_groups(program, segments):
        period = segments[g["start"]:g["start"] + g["period"]]
        if any((s[2] if len(s) > 2 else True) for s in period):
            groups.append(g)
    program._scan_group_cache = (key, groups)
    return groups


def _rng_op_count(ops):
    """Stateful random-op instances in an op run — each draws one key from
    the LoweringCtx counter, so the scan body must advance the counter by
    this much per iteration to reproduce the unrolled key stream."""
    n = 0
    for op in ops:
        impl = get_op_impl(op.type)
        if impl.stateful_rng and "_key" not in op.attrs:
            n += 1
    return n


def _rng_op_count_deep(program, ops, seen=None):
    """_rng_op_count including sub-blocks (control-flow bodies)."""
    seen = set() if seen is None else seen
    n = _rng_op_count(ops)
    for op in ops:
        sub = op.attrs.get("sub_block")
        if sub is not None and sub not in seen:
            seen.add(sub)
            n += _rng_op_count_deep(program, program.block(sub).ops, seen)
    return n


class LoweringCtx:
    """Passed to raw (control-flow) op implementations so they can lower
    sub-blocks with the same machinery."""

    def __init__(self, executor, program, step_key):
        self.executor = executor
        self.program = program
        self.step_key = step_key
        self._op_counter = 0

    def next_op_key(self):
        """A fresh deterministic PRNG key for one random-op instance."""
        self._op_counter += 1
        return jax.random.fold_in(self.step_key, self._op_counter)

    def run_ops(self, block, ops, env):
        run_block_ops(self, block, ops, env)

    def run_block(self, block_idx, env):
        blk = self.program.block(block_idx)
        run_block_ops(self, blk, blk.ops, env)


def _check_fetches(program, fetch_names):
    """Fail fast with a useful message when a fetch var is not in the
    program — usually the default program is not the one the model was
    built in (missing program= argument / program_guard)."""
    known = {n for blk in program.blocks for n in blk.vars}
    missing = [n for n in fetch_names if n not in known]
    if missing:
        raise ValueError(
            f"fetch var(s) {missing} not found in the program "
            f"({len(program.global_block().ops)} ops); pass the program "
            f"the model was built in (program= argument or program_guard)"
        )


def _gather_input(env, block, name, inside_grad_prefix):
    val = env[name]
    if inside_grad_prefix:
        var = block._find_var(name)
        if var is not None and var.stop_gradient and not isinstance(var, Parameter):
            val = jax.lax.stop_gradient(val)
    return val


def _activation_shard_specs(program):
    """Sharding annotations on non-persistable INTERMEDIATES
    (``parallel.shard_activation``): ``{var_name: PartitionSpec}``,
    cached on the program per version.  Parameters, data feeds and
    persistables are excluded — they have their own sharding paths
    (``compile_shardings``, the boundary pin)."""
    cached = getattr(program, "_act_shard_cache", None)
    if cached is not None and cached[0] == program._version:
        return cached[1]
    specs = {}
    for blk in program.blocks:
        for n, var in blk.vars.items():
            if var.persistable or getattr(var, "is_data", False) \
                    or isinstance(var, Parameter):
                continue
            spec = getattr(var, "partition_spec", None)
            if spec is not None:
                specs[n] = spec
    program._act_shard_cache = (program._version, specs)
    return specs


def _apply_activation_spec(ctx, name, spec, val):
    """Pin one annotated intermediate to its ``partition_spec``.  Always
    called inside the ``pt_shard[var]`` named scope (see
    ``run_block_ops``): the scope wraps BOTH the producing op's lowering
    and this pin, because the SPMD partitioner absorbs the constraint
    custom-call itself — the reshard collectives it inserts inherit the
    surrounding ops' metadata, and that metadata is what lets the
    CommPlan extractor attribute them back to the variable
    (``hlo.accidental-reshard``, ``CommContract.forbid_reshard``)."""
    try:
        if len(spec) > np.ndim(val):
            return val
        from jax.sharding import NamedSharding

        return jax.lax.with_sharding_constraint(
            val, NamedSharding(ctx.executor.mesh, spec))
    except Exception:  # noqa: BLE001 — an unplaceable annotation must
        return val     # not kill the trace; spec-conflict lint names it


def run_block_ops(ctx, block, ops, env, inside_grad_prefix=False):
    """Trace-time evaluation of a list of OpDescs over a name->array env."""
    act_specs = (
        _activation_shard_specs(ctx.program)
        if ctx.program is not None
        and getattr(ctx.executor, "mesh", None) is not None else {})
    for op in ops:
        impl = get_op_impl(op.type)
        if impl.raw:
            impl.fn(ctx, block, op, env)
            if act_specs:
                # raw (control-flow) ops write env themselves — apply
                # any annotated output's pin here so shard_activation
                # on a while/scan-block output is never a silent no-op
                for names in op.outputs.values():
                    for n in names:
                        if n in act_specs and n in env:
                            with jax.named_scope(f"pt_shard[{n}]"):
                                env[n] = _apply_activation_spec(
                                    ctx, n, act_specs[n], env[n])
            continue
        force_stop = inside_grad_prefix and impl.nondiff
        ins = {}
        for slot, names in op.inputs.items():
            if not names:
                continue
            vals = [
                _gather_input(env, block, n, inside_grad_prefix) for n in names
            ]
            if force_stop:
                vals = [jax.lax.stop_gradient(v) for v in vals]
            ins[slot] = vals if len(names) > 1 else vals[0]
        attrs = dict(op.attrs)
        if impl.stateful_rng and "_key" not in attrs:
            attrs["_key"] = ctx.next_op_key()
        pin_names = ()
        if act_specs:
            pin_names = tuple(
                n for names in op.outputs.values() for n in names
                if n in act_specs)
        try:
            if pin_names:
                # the pt_shard[vars] scope wraps the WHOLE lowering of
                # the producing op (not just the constraint): GSPMD
                # attaches its reshard collectives to these ops'
                # metadata, which is the provenance the comm analyzer
                # attributes reshards by.  ALL annotated outputs join
                # the scope name — provenance matching is a regex
                # search, so a forbid_reshard pattern on any of them
                # still fires.
                with jax.named_scope(
                        f"pt_shard[{','.join(pin_names)}]"):
                    outs = impl.call(ins, attrs, ctx)
            else:
                outs = impl.call(ins, attrs, ctx)
        except Exception as e:
            raise RuntimeError(f"error lowering {op}: {e}") from e
        outs = outs or {}
        for slot, names in op.outputs.items():
            if slot not in outs:
                continue
            vals = outs[slot]
            if not isinstance(vals, (list, tuple)):
                vals = [vals]
            if len(vals) != len(names):
                raise RuntimeError(
                    f"op {op.type}: output slot {slot} produced {len(vals)} "
                    f"values for {len(names)} variables"
                )
            for n, v in zip(names, vals):
                if n in act_specs:
                    with jax.named_scope(f"pt_shard[{n}]"):
                        v = _apply_activation_spec(
                            ctx, n, act_specs[n], v)
                env[n] = v


class Executor:
    """Executor(place) — place may be CPUPlace(), TPUPlace(), or None (JAX
    default backend).  Optionally bound to a ``jax.sharding.Mesh`` for
    multi-device SPMD execution (see paddle_tpu.parallel)."""

    def __init__(self, place=None, mesh=None, donate_state=True):
        self.place = place
        self.mesh = mesh
        self.donate_state = donate_state
        self._multiproc = mesh is not None and any(
            d.process_index != jax.process_index()
            for d in mesh.devices.flat
        )
        self._cache = {}
        # Telemetry of the most recent run()/run_steps(): compile_seconds,
        # static flops / bytes_accessed from XLA cost analysis, cache_hit,
        # and (mesh runs) the cross-chip collective accounting from the
        # compiled HLO.  The Trainer reads this to report achieved MFU
        # per step.
        self.last_step_cost = None
        # Most recent compile's gradient-accumulation comm plan
        # ({"mode": "local"|"reduce_each", ...}) — the accumulation
        # analogue of last_remat_plan.  None when the step has no accum.
        self.last_accum_plan = None
        # Most recent compile's per-op-class attribution table
        # (observability.attribution: flops/bytes/roofline-ms per class,
        # coverage vs cost_analysis, tune-style workload key).  None
        # until a compile runs with PADDLE_TPU_ATTR on.
        self.last_attribution = None
        # Most recent mesh compile's structured CommPlan
        # (analysis.comm.CommPlan: per-collective kind / mesh axes /
        # bytes / loop membership / phase / provenance) — what
        # CommContracts and comm_diff consume.  None off-mesh.
        self.last_comm_plan = None

    def _fsdp_active(self, program):
        """True when the scan-remat body should gather FSDP-sharded
        per-layer weights in-loop: an ``fsdp`` mesh axis of size > 1,
        the ``PADDLE_TPU_FSDP`` kill switch on, and the program not
        opted out (``program._fsdp = False`` — the autotuner's
        gather-vs-replicate schedule dimension,
        ``memory_optimize(policy="auto")``)."""
        from ..parallel.api import _fsdp_enabled
        from ..parallel.mesh import axis_size

        if self.mesh is None or not _fsdp_enabled():
            return False
        if getattr(program, "_fsdp", True) is False:
            return False
        return axis_size(self.mesh, "fsdp") > 1

    def _rng_invariant_ctx(self):
        """Sharding-invariant RNG for compiles on an ``fsdp`` mesh.

        The legacy (non-partitionable) threefry lowering produces
        DIFFERENT values when a random op's output is sharded — an
        FSDP-sharded weight would be *initialized differently* than its
        replicated spelling, breaking the bit-exactness contract the
        kill switches are gated on.  The partitionable lowering derives
        each element from its global counter regardless of
        partitioning, so values never depend on the layout.  Scoped to
        meshes WITH an fsdp axis (the only place random outputs shard)
        and deliberately independent of ``PADDLE_TPU_FSDP`` — both
        spellings of the bit-exactness comparison must lower the same
        way; everything off the fsdp mesh keeps the legacy stream
        (tests pin scan-vs-unrolled dropout bit-exactness on it)."""
        import contextlib

        from ..parallel.mesh import axis_size

        if axis_size(self.mesh, "fsdp") > 1:
            try:
                from jax._src.config import threefry_partitionable

                return threefry_partitionable(True)
            except Exception:  # noqa: BLE001 — newer jax: already on
                pass
        return contextlib.nullcontext()

    def _aot_compile(self, jitted, args, label, program=None,
                     fetch_names=()):
        """Explicit ``lower().compile()`` instead of first-call jit, so
        compile time and the executable's static cost model are
        observable: increments ``executor.compile_count``, observes
        ``executor.compile_seconds``, and extracts flops/bytes from
        ``compiled.cost_analysis()`` (the reference has no analog — its
        interpreter never compiles; here the cost model is what turns
        step wall-time into achieved MFU).  When ``program`` is given,
        the static-analysis engine's program- and hlo-level checks run
        over the compile artifacts (no extra trace/compile) and their
        findings summarize into the cost dict (``lint_findings`` /
        ``lint_errors`` / ``lint_checks`` — PADDLE_TPU_LINT=0 disables).
        Returns ``(fn, cost)``."""
        reg = _obs.get_registry()
        # kernel-registry recording: resolutions happen at trace time
        # (inside .lower()), so resetting here scopes the snapshot to
        # THIS compile — last_step_cost["kernel_backends"] then says
        # which kernel backend each op class of this executable runs
        # (docs/kernels.md; the attribution workload key carries the
        # flash choice as its |kb= token)
        from ..kernels import registry as _kreg

        _kreg.reset_selected()
        t0 = time.perf_counter()
        with self._rng_invariant_ctx():
            compiled = jitted.lower(*args).compile()
        dt = time.perf_counter() - t0
        kernel_backends = _kreg.selected_backends()
        reg.counter(
            "executor.compile_count",
            help="programs compiled (jit cache misses)").inc()
        reg.histogram("executor.compile_seconds").observe(dt)
        cost = {"label": label, "compile_seconds": dt,
                "flops": None, "bytes_accessed": None}
        if kernel_backends:
            cost["kernel_backends"] = kernel_backends
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            if ca:
                f = ca.get("flops")
                b = ca.get("bytes accessed")
                cost["flops"] = float(f) if f else None
                cost["bytes_accessed"] = float(b) if b else None
        except Exception:
            pass  # some backends/plugins don't implement cost analysis
        from ..analysis.hlo_tools import compiled_memory_stats

        memstats = compiled_memory_stats(compiled)
        if memstats:
            # hbm_high_water_bytes: XLA's liveness-aware peak when the
            # backend reports one, else argument+output+temp minus
            # donation aliasing; temp_bytes: HLO temps alone (the figure
            # the remat policies move).  Both land in last_step_cost (the
            # bench/trainer JSON channel) and the registry.
            temp = memstats["temp_bytes"]
            high = memstats["hbm_high_water_bytes"]
            peak = high or (memstats["output_bytes"] + temp)
            if peak:
                cost["compiled_peak_bytes"] = int(peak)
                reg.gauge("executor.compiled_peak_bytes").set_max(peak)
            cost["temp_bytes"] = temp
            cost["hbm_high_water_bytes"] = high
            reg.gauge(
                "executor.temp_bytes",
                help="HLO temp bytes of the largest compiled step",
            ).set_max(temp)
            reg.gauge(
                "executor.hbm_high_water_bytes",
                help="compiled-step HBM high-water (memory_analysis)",
            ).set_max(high)
        comm = None
        comm_plan = None
        if self.mesh is not None:
            # cross-chip communication accounting
            # (analysis.hlo_tools.hlo_comm_report): static collective op
            # counts/bytes of the compiled step, with the load-bearing
            # loop split — a reduce op inside a while body pays once per
            # microbatch, one outside pays once per step.  Lands in
            # last_step_cost (bench/trainer JSON channel) and the
            # registry, mirroring the hbm_high_water plumbing.  The
            # same HLO text also yields the structured CommPlan
            # (analysis.comm): per-collective mesh axes, phase and
            # provenance — exe.last_comm_plan carries the full plan,
            # the cost dict its compact per-bucket summary.
            from ..analysis.comm import extract_comm_plan

            try:
                hlo_text = compiled.as_text() or ""
            except Exception:  # noqa: BLE001 — backend can't render
                hlo_text = ""
            comm_plan = extract_comm_plan(
                hlo_text, mesh=self.mesh, label=label)
            # the scalar report derives from the plan: ONE parse of the
            # (potentially huge) HLO text serves both shapes
            comm = comm_plan.comm_report() if hlo_text else {}
            self.last_comm_plan = comm_plan
            if len(comm_plan):
                cost["comm_plan"] = comm_plan.summary()
            if comm:
                cost["collective_count"] = comm["collective_count"]
                cost["collective_bytes"] = comm["collective_bytes"]
                # per-kind counts under a DISTINCT key: "collective_ops"
                # stays the scalar count everywhere scalar-valued (the
                # executor.collective_ops gauge, trainer JSONL)
                cost["collective_op_kinds"] = dict(comm["collective_ops"])
                cost["reduce_ops"] = comm["reduce_ops"]
                cost["reduce_bytes"] = comm["reduce_bytes"]
                if label.startswith("scan"):
                    # run_steps fuses N optimizer steps into ONE while
                    # loop: the per-step boundary reduction is
                    # structurally "in loop" there, so the
                    # one-reduce-per-step invariant does not apply —
                    # emit None rather than a false regression signal
                    cost["reduce_ops_in_loop"] = None
                    cost["collectives_in_loop"] = None
                else:
                    cost["reduce_ops_in_loop"] = comm["reduce_ops_in_loop"]
                    cost["collectives_in_loop"] = comm[
                        "collectives_in_loop"]
                reg.gauge(
                    "executor.collective_ops",
                    help="collective ops in the largest compiled step",
                ).set_max(comm["collective_count"])
                reg.gauge(
                    "executor.collective_bytes",
                    help="static collective bytes of the largest "
                         "compiled step",
                ).set_max(comm["collective_bytes"])
        if self.last_accum_plan is not None:
            cost["accum_comm"] = dict(self.last_accum_plan)
        try:
            # autotune traffic snapshot (tune.cache_hits/misses/searches)
            # — how a trainer JSONL/bench row shows whether this compile
            # ran on tuned or default schedules (docs/autotune.md)
            from ..tune import tune_stats

            ts = tune_stats()
            if ts:
                cost["tune"] = ts
        except Exception:  # noqa: BLE001 — telemetry must never block
            pass
        try:
            # per-op-class performance attribution of this executable
            # (observability/attribution.py): which classes own the
            # milliseconds, coverage vs the cost_analysis figure above,
            # and the tune-style workload key the corpus joins on.  The
            # full table lands on exe.last_attribution; the compact
            # top-op summary rides the cost dict into trainer JSONL and
            # bench rows.  PADDLE_TPU_ATTR=0 skips the walk.
            from ..observability import attribution as _attr

            if _attr.attribution_enabled():
                att = _attr.attribute_compiled(
                    compiled, cost=cost, program=program)
                if att:
                    self.last_attribution = att
                    cost["attribution"] = _attr.summarize(att)
        except Exception:  # noqa: BLE001 — telemetry must never block
            pass
        try:
            # learned cost model status (tune/costmodel.py): whether the
            # attribution estimates above came from the FITTED
            # coefficients or the analytic defaults — rides into trainer
            # JSONL and flight bundles so a corpus row says which model
            # produced its est_ms
            from ..tune.costmodel import model_status

            cost["costmodel"] = model_status()
        except Exception:  # noqa: BLE001 — telemetry must never block
            pass
        from ..analysis import compile_findings, lint_enabled

        if program is not None and lint_enabled():
            # fold the static-analysis findings of this compile into the
            # cost dict (and thence the trainer JSONL): program-level
            # checks over the IR, hlo-level checks over the artifacts
            # computed above.  run_steps fuses N optimizer steps into ONE
            # while loop, so in-loop collectives are expected there.
            try:
                findings = compile_findings(
                    program=program, fetch_names=fetch_names,
                    compiled=compiled, memstats=memstats or None,
                    comm=comm if self.mesh is not None else {},
                    in_loop_expected=label.startswith("scan"),
                    donate=self.donate_state,
                    kernel_backends=kernel_backends,
                    mesh=self.mesh, comm_plan=comm_plan, label=label)
            except Exception:  # noqa: BLE001 — lint must never block a run
                findings = []
            cost["lint_findings"] = len(findings)
            cost["lint_errors"] = sum(
                1 for f in findings if f.severity == "error")
            if findings:
                cost["lint_checks"] = sorted(
                    {f.check for f in findings})[:8]
            if any(f.check == "jaxpr.kernel-backend" for f in findings):
                # dedicated flag for the timed-run gates (bench,
                # kernels selftest): lint_checks caps at 8 names, so
                # membership there is not a reliable signal
                cost["interpret_in_timed_run"] = True
        return compiled, cost

    # ------------------------------------------------------------------
    def _prepare(self, program, feed, fetch_list, scope):
        """Shared run()/run_steps() prologue: resolve defaults, coerce
        feeds (device arrays stay on device), snapshot state, build the
        compile-cache signature."""
        program = program or default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []
        scope = scope or global_scope()
        scope.ensure_rng(program.random_seed)

        feed_names = sorted(feed.keys())
        fetch_names = [
            v.name if hasattr(v, "name") else str(v) for v in fetch_list
        ]
        block = program.global_block()
        multiproc = self._multiproc
        feed_vals = []
        for n in feed_names:
            val = feed[n]
            var = block._find_var(n)
            dtype = var.dtype if var is not None else None
            if isinstance(val, jax.Array):
                if multiproc and val.sharding.is_fully_addressable:
                    raise ValueError(
                        f"feed {n!r} is a process-local jax.Array but the "
                        f"mesh spans multiple processes; device_put it "
                        f"with the global NamedSharding (or feed numpy — "
                        f"each process's local batch shard)")
                # already device-resident (e.g. a prefetched pipeline) —
                # no host round-trip; coerce dtype on device if needed.
                if dtype is not None and val.dtype != dtype:
                    val = val.astype(dtype)
                feed_vals.append(val)
                continue
            val = np.asarray(val, dtype=dtype)
            if multiproc:
                # Multi-host mesh: each process feeds its LOCAL portion of
                # the batch (the reference's per-trainer data convention);
                # assemble the global jax.Array — jit rejects raw numpy
                # with cross-process shardings.
                from jax.sharding import NamedSharding, PartitionSpec
                from ..parallel.api import _spec_for

                spec = _spec_for(var, self.mesh) if var else PartitionSpec()
                val = jax.make_array_from_process_local_data(
                    NamedSharding(self.mesh, spec), val)
            feed_vals.append(val)

        state_names = tuple(
            sorted(
                v.name
                for v in program.persistable_vars()
                if scope.find_var(v.name) is not None
            )
        )
        state = {n: scope.get(n) for n in state_names}
        state[RNG_VAR] = scope.get(RNG_VAR)
        if _emits_grad_norm(program):
            # grad-norm is carried like @RNG@: output-only for run(),
            # but lax.scan (run_steps) needs carry-in == carry-out, so
            # the input state holds a (ignored) scalar slot too
            if scope.find_var(GRAD_NORM_VAR) is None:
                scope.set(GRAD_NORM_VAR, jnp.zeros((), jnp.float32))
            state[GRAD_NORM_VAR] = scope.get(GRAD_NORM_VAR)

        feed_sig = tuple(
            (n, v.shape, str(v.dtype)) for n, v in zip(feed_names, feed_vals)
        )
        return (program, scope, feed_names, fetch_names, feed_vals,
                state_names, state, feed_sig)

    def _finish(self, scope, new_state, fetch_names, fetches, return_numpy):
        """Shared run()/run_steps() postlude: debug flags, scope update."""
        from ..flags import FLAGS

        if FLAGS.check_nan_inf:
            # FLAGS_check_nan_inf analog (reference executor.cc:131): scan
            # everything the step produced.  Host-side sync — debug only.
            for name, arr in list(new_state.items()) + list(
                zip(fetch_names, fetches)
            ):
                a = np.asarray(arr)
                if np.issubdtype(a.dtype, np.floating) and not np.isfinite(a).all():
                    # make the abort observable (metrics + timeline),
                    # not just a propagating exception
                    _obs.get_registry().counter(
                        "executor.nan_trips",
                        help="NaN/Inf aborts caught by nan_guard / "
                             "check_nan_inf").inc()
                    from ..observability import trace as _trace

                    _trace.get_tracer().instant(
                        "nan_guard_trip", cat="executor", var=name)
                    # post-mortem: the flight bundle carries the recent
                    # step records (grad-norm window included) alongside
                    # the abort
                    from ..observability import flight as _flight

                    _flight.dump("nan_trip", var=name)
                    err = FloatingPointError(
                        f"NaN/Inf detected in {name!r} after step"
                    )
                    # already recorded here: nan_guard() must not count
                    # the same abort a second time on the way out
                    err._pt_nan_counted = True
                    raise err
        if FLAGS.do_memory_benchmark:
            total = sum(
                np.asarray(v).nbytes for v in new_state.values()
            )
            print(f"[memory] live state: {total / 1e6:.2f} MB "
                  f"({len(new_state)} vars)")
        scope.update(new_state)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return list(fetches)

    def _run_entry(self, program, feed_names, fetch_names, state_names,
                   state, feed_vals, feed_sig):
        """The single-step executable for this signature — ``(entry,
        cache_hit)`` — compiling (and caching) on miss.  Shared by
        ``run`` and ``compile_only`` so preflighting primes exactly the
        cache entry the real step will hit."""
        key = (
            program._serial,
            program._version,
            feed_sig,
            tuple(fetch_names),
            state_names,
        )
        reg = _obs.get_registry()
        entry = self._cache.get(key)
        if entry is not None:
            reg.counter("executor.cache_hits").inc()
            return entry, True
        reg.counter("executor.cache_misses").inc()
        _check_fetches(program, fetch_names)
        jitted = self._compile(
            program, feed_names, fetch_names, state_names)
        entry = self._aot_compile(
            jitted, (state,) + tuple(feed_vals),
            f"run:{program._serial}v{program._version}",
            program=program, fetch_names=tuple(fetch_names))
        self._cache[key] = entry
        return entry, False

    def run(
        self,
        program=None,
        feed=None,
        fetch_list=None,
        scope=None,
        return_numpy=True,
    ):
        (program, scope, feed_names, fetch_names, feed_vals, state_names,
         state, feed_sig) = self._prepare(program, feed, fetch_list, scope)
        entry, cache_hit = self._run_entry(
            program, feed_names, fetch_names, state_names, state,
            feed_vals, feed_sig)
        step, cost = entry
        self.last_step_cost = dict(cost, cache_hit=cache_hit)

        new_state, fetches = step(state, *feed_vals)
        return self._finish(scope, new_state, fetch_names, fetches,
                            return_numpy)

    # ------------------------------------------------------------------
    def compile_only(self, program=None, feed=None, fetch_list=None,
                     scope=None):
        """AOT-compile the step for this (program, feed, fetch) signature
        WITHOUT running it, priming the same cache ``run`` uses (the
        following ``run`` is a cache hit, not a second compile).  Returns
        a copy of the cost dict — compile_seconds, flops,
        ``hbm_high_water_bytes``, ``temp_bytes`` — so callers can
        preflight a capacity config against the chip's HBM before the
        first real step allocates (bench.py's flagship fallback uses
        this to turn a runtime allocator abort into a parseable
        per-section failure)."""
        (program, scope, feed_names, fetch_names, feed_vals, state_names,
         state, feed_sig) = self._prepare(program, feed, fetch_list, scope)
        entry, cache_hit = self._run_entry(
            program, feed_names, fetch_names, state_names, state,
            feed_vals, feed_sig)
        _, cost = entry
        self.last_step_cost = dict(cost, cache_hit=cache_hit)
        return dict(cost)

    # ------------------------------------------------------------------
    def run_steps(
        self,
        program=None,
        feed=None,
        fetch_list=None,
        steps=None,
        scope=None,
        return_numpy=True,
    ):
        """Run ``steps`` training steps as ONE jitted ``lax.scan`` — the
        whole inner loop compiles to a single XLA computation, so per-step
        host dispatch (the cost the reference pays per *op* in its
        interpreter loop, executor.cc:118) disappears entirely.

        ``feed`` values are STACKED along a leading steps axis
        ([steps, batch, ...]); ``steps`` defaults to that axis.  Fetches
        come back stacked ([steps, ...]).  State (parameters, RNG) carries
        through the scan exactly as across separate ``run`` calls.
        """
        (program, scope, feed_names, fetch_names, feed_vals, state_names,
         state, feed_sig) = self._prepare(program, feed, fetch_list, scope)
        if steps is None:
            if not feed_vals:
                raise ValueError("steps is required when there is no feed")
            steps = int(feed_vals[0].shape[0])
        for n, v in zip(feed_names, feed_vals):
            if v.shape[0] != steps:
                raise ValueError(
                    f"feed {n!r} leading (steps) axis {v.shape[0]} != "
                    f"{steps}; run_steps feeds are stacked [steps, ...]"
                )

        key = (
            "scan",
            steps,
            program._serial,
            program._version,
            feed_sig,
            tuple(fetch_names),
            state_names,
        )
        reg = _obs.get_registry()
        entry = self._cache.get(key)
        cache_hit = entry is not None
        if not cache_hit:
            reg.counter("executor.cache_misses").inc()
            _check_fetches(program, fetch_names)
            jitted = self._compile_scan(
                program, feed_names, fetch_names, state_names, steps
            )
            entry = self._aot_compile(
                jitted, (state,) + tuple(feed_vals),
                f"scan{steps}:{program._serial}v{program._version}",
                program=program, fetch_names=tuple(fetch_names))
            self._cache[key] = entry
        else:
            reg.counter("executor.cache_hits").inc()
        fn, cost = entry
        self.last_step_cost = dict(cost, cache_hit=cache_hit, steps=steps)

        new_state, fetches = fn(state, *feed_vals)
        return self._finish(scope, new_state, fetch_names, fetches,
                            return_numpy)

    def _compile_scan(self, program, feed_names, fetch_names, state_names,
                      steps):
        step, persist_out = self.lower(
            program, feed_names, fetch_names, state_names)
        # lax.scan requires carry-in == carry-out structure: every
        # persistable the step will emit must already be in the scope
        # (run() tolerates the step creating them; a scan cannot).
        extra = sorted(set(persist_out) - set(state_names))
        if extra:
            raise ValueError(
                f"run_steps needs persistable var(s) {extra} initialized "
                f"before the scan (run the startup program, or one "
                f"regular run() step, first)"
            )

        def multi(state, *stacked_feeds):
            def body(s, fs):
                return step(s, *fs)

            xs = tuple(stacked_feeds) if stacked_feeds else None
            new_state, fetches = jax.lax.scan(
                body, state, xs, length=steps)
            return new_state, fetches

        jit_kwargs = {}
        if self.donate_state:
            jit_kwargs["donate_argnums"] = 0
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            from ..parallel.api import compile_shardings

            in_sh, out_sh = compile_shardings(
                self.mesh, program, feed_names, fetch_names, state_names,
                out_state_names=persist_out,
                extra_state=((GRAD_NORM_VAR,)
                             if _emits_grad_norm(program) else ()),
            )
            state_sh, *feed_sh = in_sh
            # stacked feeds get an unsharded leading steps axis
            feed_sh = [
                NamedSharding(self.mesh, PartitionSpec(None, *s.spec))
                for s in feed_sh
            ]
            jit_kwargs["in_shardings"] = (state_sh, *feed_sh)
            jit_kwargs["out_shardings"] = out_sh
        return jax.jit(multi, **jit_kwargs)

    # ------------------------------------------------------------------
    def lower(self, program, feed_names, fetch_names, state_names):
        """Build the pure (unjitted) step function
        ``step(state, *feed) -> (new_state, fetches)`` for a program.
        Returns ``(step, persist_out)`` where persist_out names the state
        entries the step emits.  Exposed for embedding the framework in
        external jit pipelines (e.g. the driver's compile checks)."""
        self.last_accum_plan = None
        block = program.global_block()
        bw = block.backward_index
        info = program._backward_info.get(0)
        emit_grad_norm = _emits_grad_norm(program)
        # The state the step returns: persistables that are either already
        # live (passed in) or written by some op — static, so sharding
        # pytrees can be built to match.
        written = {
            n
            for blk in program.blocks
            for op in blk.ops
            for n in op.output_names()
        }
        persist_out = [
            v.name
            for v in program.persistable_vars()
            if v.name in written or v.name in state_names
        ]

        def step(state, *feed_vals):
            rng = state[RNG_VAR]
            step_key, next_key = jax.random.split(rng)
            ctx = LoweringCtx(self, program, step_key)
            env = dict(state)
            env.pop(GRAD_NORM_VAR, None)  # carried slot, never an input
            env.update(zip(feed_names, feed_vals))
            grad_norm_out = [None]

            if bw is None or info is None:
                run_block_ops(ctx, block, block.ops, env)
            else:
                param_names = [
                    n for n in info["params"] if n in env
                ]

                segments = getattr(program, "_remat_segments", None)

                # Only the values actually consumed after the backward
                # split may escape the differentiated forward as aux:
                # optimizer-op inputs, fetches, and persistables (BN
                # stats, metric accumulators).  Returning the whole env
                # would make every intermediate activation a computation
                # OUTPUT, forcing XLA to materialize all of them to HBM
                # (measured: 53 GB accessed/step on ResNet-50 bs128 vs
                # ~16 GB with the trimmed aux) and blocking fusion.
                aux_names = set(fetch_names) | set(persist_out)
                aux_names.add(info["loss"])
                for op_ in block.ops[bw:]:
                    for slot_names in op_.inputs.values():
                        aux_names.update(slot_names)

                def make_fwd(fctx):
                    """Differentiable forward bound to one LoweringCtx —
                    gradient accumulation builds one per microbatch so
                    random ops draw distinct keys."""

                    def fwd(tparams, env0):
                        return _run_fwd(fctx, tparams, env0)

                    return fwd

                def _run_fwd(fctx, tparams, env0):
                    e = dict(env0)
                    e.update(tparams)
                    if not segments:
                        run_block_ops(
                            fctx, block, block.ops[:bw], e,
                            inside_grad_prefix=True,
                        )
                    else:
                        # memory_optimize marked remat boundaries: run each
                        # wrapped forward segment under jax.checkpoint so
                        # backward recomputes activations instead of
                        # storing them; unwrapped segments (the selective
                        # policy's expensive ops — flash attention etc.)
                        # run plainly so their residuals stay saved.
                        #
                        # A wrapped segment may only return names consumed
                        # AFTER it (later forward ops, the loss, aux):
                        # returning everything it writes would thread every
                        # internal activation into the next segment's
                        # inputs, where jax.checkpoint saves it as a
                        # residual — remat would then recompute for zero
                        # memory saved (measured: t=16k bs8 GPT sat at
                        # 23.5 GB, OOM, regardless of policy).
                        def op_uses(op_, acc, seen):
                            for slot_names in op_.inputs.values():
                                acc.update(slot_names)
                            sub = op_.attrs.get("sub_block")
                            if sub is not None and sub not in seen:
                                seen.add(sub)
                                for sop in program.block(sub).ops:
                                    op_uses(sop, acc, seen)

                        needed_after = [set(aux_names)
                                        | {info["loss"]}]
                        for op_ in reversed(block.ops[:bw]):
                            nxt = set(needed_after[-1])
                            op_uses(op_, nxt, set())
                            needed_after.append(nxt)
                        needed_after.reverse()  # needed_after[i] = used
                        # by ops[i:] (+loss/aux); index bw == just aux

                        def _try_scan_group(group, use_fsdp=True):
                            """Run ``segments[i0 : i0 + P*G]`` — G
                            structurally identical periods of P segments
                            (one transformer layer each) — as ONE
                            ``lax.scan``: per-layer weights stack along the
                            scan axis (xs), the residual stream threads as
                            the carry, and wrapped sub-segments run under
                            plain ``jax.checkpoint`` INSIDE the scan body.
                            The scan structurally serializes backward
                            recompute (segment k's remat cannot start until
                            its iteration's cotangent arrives), so remat
                            temps are O(1) per layer — the compilable HLO
                            the barrier spelling could not guarantee at
                            t=16k.

                            FSDP rides the same structure: xs entries
                            whose parameter resolves an ``fsdp`` spec
                            stay SHARDED in the stacked at-rest form
                            (``P(None, *spec)`` — at-rest bytes divide
                            by the fsdp degree) and each layer's slice
                            is constrained to the fsdp-free spec INSIDE
                            the body, so GSPMD emits the all-gather in
                            the loop and frees the gathered slice after
                            its layer — live parameter bytes are O(one
                            layer), the PR-3 remat trick applied to
                            weights.  Returns False (caller falls back
                            to the per-segment barrier path) when the
                            group cannot be classified into
                            carry/xs/shared inputs or the scan fails to
                            trace; an fsdp-constrained trace failure
                            first retries WITHOUT the constraints
                            (``executor.fsdp_fallbacks``)."""
                            i0, P, G = (group["start"], group["period"],
                                        group["count"])
                            ext_maps = group["ext_maps"]
                            out_maps = group["out_maps"]
                            c0 = fctx._op_counter
                            reg = _obs.get_registry()
                            fsdp_gather = {}
                            try:
                                out0 = list(out_maps[0].keys())
                                out_sets = [set(m.values()) for m in out_maps]
                                written_any = set().union(*out_sets)
                                pre_w = [set()]
                                for k in range(G):
                                    pre_w.append(pre_w[-1] | out_sets[k])

                                # classify each canonical external input:
                                # carry (produced by the previous period),
                                # shared (same name+value every period), or
                                # xs (per-period values stacked on the scan
                                # axis — the per-layer weights)
                                carry_map, shared_names, xs_names = {}, [], []
                                for n in ext_maps[0]:
                                    vals = [ext_maps[k][n] for k in range(G)]
                                    m = vals[1] if G > 1 else None
                                    if (m in out_maps[0] and n in e and all(
                                            ext_maps[k][n]
                                            == out_maps[k - 1][m]
                                            for k in range(1, G))):
                                        carry_map[n] = m
                                    elif (all(v == n for v in vals)
                                          and n not in written_any
                                          and n in e):
                                        shared_names.append(n)
                                    elif all(vals[k] in e
                                             and vals[k] not in pre_w[k]
                                             for k in range(G)):
                                        xs_names.append(n)
                                    else:
                                        raise ValueError(
                                            f"unclassifiable input {n!r}")

                                # outputs escaping the group: final-period
                                # values come from the carry; anything else
                                # consumed after the group stacks as ys
                                t_end = segments[i0 + P * G - 1][1]
                                names_after = needed_after[t_end]
                                carry_vals = set(carry_map.values())
                                inv_carry = {m: n
                                             for n, m in carry_map.items()}
                                ys_names = set()
                                ys_writes = []   # (env_name, canonical, k)
                                carry_writes = {}  # env_name -> carry input
                                for m in out0:
                                    for k in range(G):
                                        on = out_maps[k][m]
                                        if on not in names_after:
                                            continue
                                        if k == G - 1 and m in inv_carry:
                                            carry_writes[on] = inv_carry[m]
                                        else:
                                            ys_names.add(m)
                                            ys_writes.append((on, m, k))

                                # per-sub-segment plan (canonical frame):
                                # ops, wrap flag, outputs needed later in
                                # the period, external uses, rng-op count
                                sub = []
                                for seg_ in segments[i0:i0 + P]:
                                    s_, t_ = seg_[0], seg_[1]
                                    wrap_ = (seg_[2] if len(seg_) > 2
                                             else True)
                                    sub.append([block.ops[s_:t_], wrap_])
                                needed_sub = [set(ys_names) | carry_vals]
                                for ops_j, _w in reversed(sub):
                                    nxt = set(needed_sub[0])
                                    for op_ in ops_j:
                                        op_uses(op_, nxt, set())
                                    needed_sub.insert(0, nxt)
                                plan_subs = []
                                nr = 0
                                for j, (ops_j, wrap_) in enumerate(sub):
                                    written_j = {
                                        n for op_ in ops_j
                                        for n in op_.output_names()}
                                    out_j = tuple(sorted(
                                        written_j & needed_sub[j + 1]))
                                    uses_j = set()
                                    for op_ in ops_j:
                                        op_uses(op_, uses_j, set())
                                    nr_j = _rng_op_count(ops_j)
                                    plan_subs.append(
                                        (ops_j, wrap_, out_j,
                                         tuple(sorted(uses_j)), nr, nr_j))
                                    nr += nr_j

                                shared_env = {n: e[n] for n in shared_names}
                                xs_stacked = {
                                    n: jnp.stack(
                                        [e[ext_maps[k][n]]
                                         for k in range(G)])
                                    for n in xs_names
                                }
                                if use_fsdp and self._fsdp_active(
                                        program):
                                    from jax.sharding import (
                                        NamedSharding as _NS,
                                        PartitionSpec as _PS)

                                    from ..parallel.api import \
                                        fsdp_spec_for

                                    for n in xs_names:
                                        v_ = block._find_var(n)
                                        spec = fsdp_spec_for(
                                            v_, self.mesh, block
                                        ) if v_ is not None else None
                                        if spec is None:
                                            continue
                                        gathered = _PS(*(
                                            (tuple(a for a in ent
                                                   if a != "fsdp")
                                             or None)
                                            if isinstance(ent, tuple)
                                            else (None if ent == "fsdp"
                                                  else ent)
                                            for ent in spec))
                                        # at rest: the stack stays
                                        # fsdp-sharded on the weight's
                                        # leading (non-scan) axis
                                        xs_stacked[n] = \
                                            _fsdp_fwd_pin(
                                                _NS(self.mesh,
                                                    _PS(None, *spec)),
                                                site=f"fsdp_stack:{n}")(
                                                xs_stacked[n])
                                        fsdp_gather[n] = \
                                            _fsdp_fwd_pin(
                                                _NS(self.mesh,
                                                    gathered),
                                                site=f"fsdp_gather:{n}")
                                carry0 = {n: e[n] for n in carry_map}
                                # offload ("host"/"save"): the ONE change
                                # vs plain selective execution is that
                                # each wrapped sub-segment's checkpoint
                                # gets a NAME policy and tags the
                                # block-input (carry) args it consumes
                                # BLOCK_INPUT_TAG inside the region — the
                                # segment's backward recompute then reads
                                # the carry from the saved named copy
                                # (pinned host memory in mode "host")
                                # instead of forcing the scan to stack it
                                # in HBM.  The recompute op graph is
                                # IDENTICAL to selective's (a default
                                # jax.checkpoint saves nothing internal
                                # either); only the residual's placement
                                # moves — which is why offload is
                                # bit-exact vs selective.
                                off_mode = _offload_mode(program)
                                ckpt_policy = (
                                    _offload_ckpt_policy(off_mode)
                                    if off_mode != "off" else None)

                                def body(carry, xs):
                                    k_idx, xvals = xs
                                    if fsdp_gather:
                                        # gather THIS layer's weight
                                        # slices to their fsdp-free
                                        # spec inside the loop body:
                                        # XLA frees them when the
                                        # iteration's uses finish, so
                                        # only one layer is ever live
                                        # gathered
                                        xvals = dict(xvals)
                                        for n_, g_ in \
                                                fsdp_gather.items():
                                            xvals[n_] = g_(xvals[n_])
                                    e2 = dict(shared_env)
                                    e2.update(carry)
                                    e2.update(xvals)
                                    base = (c0 + k_idx * nr) if nr else c0
                                    for (ops_j, wrap_, out_j, uses_j,
                                         off_j, _nr_j) in plan_subs:
                                        cj = base + off_j if nr else c0
                                        if not wrap_:
                                            fctx._op_counter = cj
                                            run_block_ops(
                                                fctx, block, ops_j, e2,
                                                inside_grad_prefix=True)
                                            continue
                                        tags = (
                                            frozenset(carry_map)
                                            & set(uses_j)
                                            if ckpt_policy is not None
                                            else frozenset())

                                        def seg_fn(env_in, _ops=ops_j,
                                                   _out=out_j, _c=cj,
                                                   _tags=tags):
                                            fctx._op_counter = _c
                                            e3 = dict(env_in)
                                            for tn in _tags:
                                                if tn in e3:
                                                    e3[tn] = _tag_named(
                                                        e3[tn],
                                                        BLOCK_INPUT_TAG)
                                            run_block_ops(
                                                fctx, block, _ops, e3,
                                                inside_grad_prefix=True)
                                            return {n: e3[n] for n in _out
                                                    if n in e3}

                                        env_sub = {u: e2[u] for u in uses_j
                                                   if u in e2}
                                        e2.update(jax.checkpoint(
                                            seg_fn,
                                            policy=ckpt_policy)(env_sub))
                                    new_carry = {
                                        n: e2[carry_map[n]]
                                        for n in carry_map}
                                    ys = {m: e2[m] for m in ys_names}
                                    return new_carry, ys

                                # named scope -> XLA op metadata: XPlane
                                # captures (profiler('dir') / Trainer
                                # trace_dir=) show this group as
                                # "scan_remat[i0+PxG]" so device timelines
                                # line up with the Program's layer
                                # structure
                                with jax.named_scope(
                                        f"scan_remat[{i0}+{P}x{G}]"):
                                    carry_f, ys = jax.lax.scan(
                                        body,
                                        carry0,
                                        (jnp.arange(G, dtype=jnp.int32),
                                         xs_stacked),
                                        length=G)
                                for on, m, k in sorted(ys_writes,
                                                       key=lambda w: w[2]):
                                    e[on] = ys[m][k]
                                for on, n in carry_writes.items():
                                    e[on] = carry_f[n]
                                fctx._op_counter = c0 + G * nr
                                reg.counter(
                                    "executor.scan_remat_groups",
                                    help="remat segment groups executed as "
                                         "lax.scan over layers").inc()
                                if fsdp_gather:
                                    reg.counter(
                                        "executor.fsdp_groups",
                                        help="scan groups whose stacked "
                                             "weights are fsdp-sharded "
                                             "with in-loop gathers").inc()
                                plan_log.append(
                                    {"start": i0, "period": P, "count": G,
                                     "carry": sorted(carry_map),
                                     "xs": len(xs_names),
                                     "shared": len(shared_names),
                                     "fsdp": len(fsdp_gather),
                                     "offload": off_mode})
                                return True
                            except Exception as exc:
                                # classification/trace failure: restore the
                                # rng counter and run the group segment by
                                # segment through the barrier fallback —
                                # with the REASON recorded (a silent
                                # fallback at a capacity config is a
                                # runtime OOM waiting to happen: BENCH_r05)
                                fctx._op_counter = c0
                                reason = " ".join(
                                    f"{type(exc).__name__}: {exc}"
                                    .split())[:200]
                                if fsdp_gather:
                                    # the fsdp constraints are the only
                                    # delta vs the proven scan spelling:
                                    # drop them and keep the scan before
                                    # surrendering to the barrier path
                                    reg.counter(
                                        "executor.fsdp_fallbacks",
                                        help="scan groups whose fsdp "
                                             "constraints failed to trace "
                                             "(retried replicated)").inc()
                                    plan_log.append(
                                        {"start": i0, "period": P,
                                         "count": G,
                                         "fsdp_fallback": reason})
                                    return _try_scan_group(
                                        group, use_fsdp=False)
                                reg.counter(
                                    "executor.scan_remat_fallbacks",
                                    help="segment groups that fell back to "
                                         "the barrier spelling").inc()
                                plan_log.append(
                                    {"start": i0, "period": P, "count": G,
                                     "fallback": reason})
                                if _scan_strict():
                                    raise RuntimeError(
                                        f"PADDLE_TPU_SCAN_REMAT=strict: "
                                        f"uniform group at segment {i0} "
                                        f"(period {P} x {G}) failed to "
                                        f"scan: {reason}") from exc
                                return False

                        groups = _scan_groups_for(program, segments)
                        by_start = {g["start"]: g for g in groups}
                        plan_log = []
                        self.last_remat_plan = plan_log
                        si = 0
                        while si < len(segments):
                            g = by_start.get(si)
                            if g is not None and _try_scan_group(g):
                                si += g["period"] * g["count"]
                                continue
                            seg = segments[si]
                            si += 1
                            s, t = seg[0], seg[1]
                            wrap = seg[2] if len(seg) > 2 else True
                            seg_ops = block.ops[s:t]
                            if not wrap:
                                run_block_ops(
                                    fctx, block, seg_ops, e,
                                    inside_grad_prefix=True,
                                )
                                continue
                            written = {
                                n for op in seg_ops for n in op.output_names()
                            }
                            out_names = tuple(sorted(
                                written & needed_after[t]))

                            # checkpoint may trace seg_fn more than once;
                            # pin the random-op key counter to the segment
                            # start so fwd and remat derive identical keys
                            c0 = fctx._op_counter

                            def seg_fn(env_in, _ops=seg_ops, _out=out_names,
                                       _c0=c0):
                                fctx._op_counter = _c0
                                e2 = dict(env_in)
                                run_block_ops(
                                    fctx, block, _ops, e2,
                                    inside_grad_prefix=True,
                                )
                                return {n: e2[n] for n in _out if n in e2}

                            seg_uses = set()
                            for op_ in seg_ops:
                                op_uses(op_, seg_uses, set())
                            env_sub = {
                                k: e[k] for k in sorted(seg_uses) if k in e
                            }
                            outs = _remat_segment(
                                seg_fn, env_sub,
                                param_names=frozenset(param_names))
                            e.update(outs)
                    loss = e[info["loss"]]
                    aux = {n: e[n] for n in aux_names if n in e}
                    return jnp.sum(loss), aux

                tparams = {n: env[n] for n in param_names}
                if self.mesh is not None and self._fsdp_active(program):
                    # prologue/epilogue FSDP (shard_fsdp's fsdp_axes
                    # tagging: embedding tables, the LM head): the
                    # at-rest value is fsdp x tp sharded on its leading
                    # dim, but compute must see the EXPLICIT-spec
                    # (gathered) weight — the leading dim is the
                    # lookup/contraction axis, and letting GSPMD keep
                    # the shard turns the embedding lookup and the
                    # head matmul into partial sums plus per-microbatch
                    # in-loop all-reduces (measured: 26 in-loop reduce
                    # ops on dp2 x fsdp4 at accum=4).  The forward-only
                    # pin here sits OUTSIDE the accumulation loop, so
                    # the all-gather runs once per step (overlappable
                    # via PADDLE_TPU_COMM_OVERLAP) and the cotangent
                    # passes through unpinned — dW stays fsdp-replicated
                    # to the boundary exactly like the scan weights'.
                    from jax.sharding import (
                        NamedSharding as _NS, PartitionSpec as _PP)
                    from ..parallel.api import fsdp_spec_for

                    for n in param_names:
                        var = block._find_var(n)
                        if (var is None
                                or not getattr(var, "fsdp_axes", None)
                                or fsdp_spec_for(
                                    var, self.mesh, block) is None):
                            continue
                        gathered = (getattr(var, "partition_spec", None)
                                    or _PP())
                        tparams[n] = _fsdp_fwd_pin(
                            _NS(self.mesh, gathered),
                            site=f"fsdp_prologue_gather:{n}")(
                            tparams[n])
                accum = int(getattr(program, "_grad_accum", 1) or 1)
                if accum <= 1:
                    grads, aux = jax.grad(make_fwd(ctx), has_aux=True)(
                        tparams, env)
                    env.update(aux)
                else:
                    grads, aux = self._accum_grads(
                        program, block, ctx, env, tparams, make_fwd,
                        feed_names, persist_out, accum, step_key, bw)
                    env.update(aux)
                if emit_grad_norm:
                    # global grad norm BEFORE the boundary pin reads the
                    # same values either way; computing it from the dict
                    # here (one f32 sum-of-squares per param + one sqrt)
                    # keeps it a pure extra output — nothing feeds back
                    # into the update math, so every bit-exactness
                    # contract is untouched
                    parts = [jnp.sum(jnp.square(g.astype(jnp.float32)))
                             for g in grads.values()]
                    grad_norm_out[0] = (jnp.sqrt(sum(parts)) if parts
                                        else jnp.zeros((), jnp.float32))
                if self.mesh is not None:
                    # Pin each gradient at the backward/optimizer boundary
                    # to its PARAMETER's sharding (replicated under plain
                    # dp, the tp spec for tp-sharded params).  ZeRO-1
                    # shards optimizer STATE, not gradients — without this
                    # pin the sharded-moment annotations propagate back
                    # through the grads into the whole backward pass,
                    # repartitioning it (measured: extra in-loop
                    # collectives in the attention scans and loss/params
                    # drifting from the replicated spelling).  With it the
                    # backward is bit-identical to ZeRO-off and only the
                    # update math reads the grad shard-locally.
                    from jax.sharding import (
                        NamedSharding, PartitionSpec as _P)
                    from ..parallel.api import grad_rs_spec_for

                    for n, g in grads.items():
                        var = block._find_var(n)
                        # the true-ZeRO-3 reduce-scatter spelling
                        # (docs/parallel.md rule 4): an fsdp-tagged
                        # parameter's gradient pins to the COMPOSED
                        # spec at the boundary, so GSPMD spells the
                        # cross-chip aggregation as reduce-scatter@fsdp
                        # and each chip receives only its shard.  The
                        # scatter happens ONCE, here — the carry stays
                        # plain P('dp') and the backward cotangents
                        # stay unpinned, so the three PR-10 placement
                        # rules survive (zero3_grad_contract enforces
                        # the shape).  PADDLE_TPU_ZERO3_RS=0 (or any
                        # fsdp_spec_for fallback) restores the
                        # replicated-grad spelling below, bit-exact.
                        rs = grad_rs_spec_for(var, self.mesh, block)
                        if rs is not None:
                            with jax.named_scope(
                                    f"pt_pin[grad_rs_boundary:{n}]"):
                                env[n + GRAD_SUFFIX] = (
                                    jax.lax.with_sharding_constraint(
                                        g, NamedSharding(self.mesh, rs)))
                            continue
                        # the replicated-grad reference spelling: the
                        # EXPLICIT spec, never fsdp-composed — the
                        # gradient stays replicated over fsdp to the
                        # boundary, where the elementwise update
                        # against the fsdp-sharded moments reads it
                        # shard-locally (a free slice, outside every
                        # loop); sharding_report accounts grads at
                        # whichever spec this pin resolves to
                        spec = (getattr(var, "partition_spec", None)
                                if var is not None else None) or _P()
                        with jax.named_scope(
                                f"pt_pin[grad_boundary:{n}]"):
                            env[n + GRAD_SUFFIX] = (
                                jax.lax.with_sharding_constraint(
                                    g, NamedSharding(self.mesh, spec)))
                else:
                    for n, g in grads.items():
                        env[n + GRAD_SUFFIX] = g
                run_block_ops(ctx, block, block.ops[bw:], env)

            new_state = {n: env[n] for n in persist_out}
            new_state[RNG_VAR] = next_key
            if emit_grad_norm:
                new_state[GRAD_NORM_VAR] = (
                    grad_norm_out[0]
                    if grad_norm_out[0] is not None
                    else jnp.zeros((), jnp.float32))
            fetches = tuple(env[n] for n in fetch_names)
            return new_state, fetches

        return step, persist_out

    def _accum_comm_mode(self, program, block, bw, mbs, carry_persist,
                         ndp):
        """Pick the accumulation-loop communication spelling:
        ``("local", None)`` — accumulate per-device partial gradients in a
        dp-sharded carry and cross-chip-reduce ONCE at the optimizer
        boundary; ``("reduce_each", reason)`` — the reference spelling
        whose per-microbatch gradients are full cross-chip values (GSPMD
        reduces — or worse, gathers the batch and replicates compute —
        inside the loop body).  Local mode needs every condition below;
        the reason string lands in ``last_accum_plan`` so a silent
        de-optimization is observable (the scan-remat fallback
        discipline)."""
        if ndp <= 1:
            return "reduce_each", "no dp mesh axis"
        if os.environ.get("PADDLE_TPU_LOCAL_ACCUM", "1").lower() in (
                "0", "", "false"):
            return "reduce_each", "PADDLE_TPU_LOCAL_ACCUM=0"
        if not mbs:
            return "reduce_each", "no batch feeds to split"
        bad = sorted(n for n, mb in mbs.items() if mb % ndp)
        if bad:
            return "reduce_each", (
                f"microbatch not divisible by dp={ndp}: {bad}")
        unsharded = []
        for n in mbs:
            var = block._find_var(n)
            spec = getattr(var, "partition_spec", None) if var else None
            if spec is None or not len(spec) or spec[0] != "dp":
                unsharded.append(n)
        if unsharded:
            return "reduce_each", (
                f"feeds not dp-batch-sharded: {sorted(unsharded)}")
        if carry_persist:
            # BN stats / metric accumulators couple device groups across
            # the batch axis — vmapped lanes would each write their own
            return "reduce_each", (
                f"forward-written persistables: {carry_persist[:3]}")
        if _rng_op_count_deep(program, block.ops[:bw]):
            # the per-lane computation shares one op key under vmap, so
            # every device group would draw the SAME dropout mask —
            # valid dropout, but not the unsharded key stream
            return "reduce_each", "stateful rng ops in the forward"
        return "local", None

    def _accum_grads(self, program, block, ctx, env, tparams, make_fwd,
                     feed_names, persist_out, accum, step_key, bw):
        """Gradient accumulation (``pt.gradient_accumulation``): slice the
        feed batch into ``accum`` microbatches, run forward+backward per
        microbatch under ``lax.scan`` (activation memory scales with the
        microbatch), accumulate gradients in float32, and return the MEAN
        gradient — the big-batch average-loss gradient when microbatches
        weigh equally.  Forward-written persistables (BN stats, metric
        accumulators) thread through the scan carry so microbatch k+1 sees
        k's updates, exactly as consecutive small steps would.

        On a mesh with a dp axis the COMM-AWARE spelling
        (``_accum_grads_local``) is preferred: the reference spelling
        below makes every microbatch's gradient a full cross-chip value,
        so GSPMD either reduces inside the loop body (accum x the
        collective bytes) or — observed on the CPU SPMD partitioner —
        all-gathers the whole batch and REPLICATES the accumulation loop
        on every chip.  Eligibility and fallback reasons:
        ``_accum_comm_mode`` / ``last_accum_plan``."""
        mbs = {}
        for n in feed_names:
            if jnp.ndim(env[n]) == 0:
                continue  # 0-d feeds (scalars) pass through unsplit
            b0 = env[n].shape[0]
            if b0 % accum:
                raise ValueError(
                    f"gradient_accumulation(micro_steps={accum}): feed "
                    f"{n!r} leading dim {b0} is not divisible")
            mbs[n] = b0 // accum
        full_b = env[sorted(mbs)[0]].shape[0] if mbs else 0

        fwd_written = {
            n for op in block.ops[:bw] for n in op.output_names()
        }
        carry_persist = sorted(
            n for n in persist_out if n in fwd_written and n in env
        )
        # aux names the forward merely passes through (optimizer-op state
        # inputs: moments, beta pows, lr — and the params themselves):
        # their env values are already authoritative, and stacking them
        # per microbatch both wastes scan-ys memory and MISCLASSIFIES in
        # the reassembly when a state var's leading dim happens to equal
        # the feed batch (e.g. a [max_len, d] positional-embedding moment
        # at batch == max_len would be "batch-leading"-reshaped).
        passthrough = {
            v.name for v in program.persistable_vars()
            if v.name not in fwd_written
        } | set(tparams)

        from ..parallel.mesh import axis_size

        ndp = axis_size(self.mesh, "dp")
        reg = _obs.get_registry()
        mode, reason = self._accum_comm_mode(
            program, block, bw, mbs, carry_persist, ndp)
        self.last_accum_plan = {"mode": mode, "accum": accum, "dp": ndp}
        if reason:
            self.last_accum_plan["reason"] = reason
        if mode == "local":
            try:
                out = self._accum_grads_local(
                    program, block, env, tparams, make_fwd, accum,
                    step_key, bw, mbs, full_b, ndp, passthrough)
                reg.counter(
                    "executor.accum_local_steps",
                    help="steps compiled with boundary-reduced (local) "
                         "gradient accumulation").inc()
                return out
            except Exception as exc:  # trace failure: reference spelling
                reg.counter(
                    "executor.accum_local_fallbacks",
                    help="accum steps that fell back to per-microbatch "
                         "reduction").inc()
                why = " ".join(
                    f"{type(exc).__name__}: {exc}".split())[:200]
                self.last_accum_plan = {
                    "mode": "reduce_each", "accum": accum, "dp": ndp,
                    "reason": f"local spelling failed: {why}"}

        def one_micro(carry, i):
            gacc, persist = carry
            e0 = dict(env)
            e0.update(persist)
            for n, mb in mbs.items():
                e0[n] = jax.lax.dynamic_slice_in_dim(
                    env[n], i * mb, mb, 0)
            fctx = LoweringCtx(
                self, program, jax.random.fold_in(step_key, i + 1))
            g, aux = jax.grad(make_fwd(fctx), has_aux=True)(tparams, e0)
            gacc = jax.tree_util.tree_map(
                lambda a, gi: a + gi.astype(jnp.float32), gacc, g)
            new_persist = {n: aux[n] for n in carry_persist}
            # params and unwritten optimizer state sit in aux too
            # (optimizer-op inputs) but env already holds the exact
            # values; stacking them across the scan would cost
            # accum x state-bytes of HBM for nothing (see ``passthrough``)
            ys = {n: v for n, v in aux.items()
                  if n not in new_persist and n not in passthrough}
            return (gacc, new_persist), ys

        g0 = jax.tree_util.tree_map(
            lambda p: jnp.zeros(jnp.shape(p), jnp.float32), tparams)
        p0 = {n: env[n] for n in carry_persist}
        (gsum, persist_f), ys = jax.lax.scan(
            one_micro, (g0, p0), jnp.arange(accum))
        grads = {
            n: (gsum[n] / accum).astype(env[n].dtype) for n in gsum
        }
        aux = dict(persist_f)
        aux.update(self._reassemble_accum_aux(block, env, ys, full_b, bw))
        return grads, aux

    def _accum_grads_local(self, program, block, env, tparams, make_fwd,
                           accum, step_key, bw, mbs, full_b, ndp,
                           passthrough):
        """Comm-aware gradient accumulation: one cross-chip gradient
        reduction per OPTIMIZER step instead of one per microbatch.

        The batch is regrouped so each microbatch is the union of every
        device's k-th local slice: feed ``[B, ...]`` (dp-sharded) reshapes
        to ``[ndp, accum, B/(ndp*accum), ...]`` — a shard-local reshape —
        and transposes to scan xs ``[accum, ndp, mb_g, ...]`` with the
        GROUP axis sharded over dp.  The microbatch forward+backward runs
        ``jax.vmap``-ed over that group axis, so every lane's compute is
        resident on one chip and the loop body carries NO collectives
        (``memaudit.comm_report: reduce_ops_in_loop == 0`` — also killing
        the batch-axis gathers GSPMD otherwise inserts for in-loop
        dynamic slicing).  Per-lane gradients accumulate in a dp-sharded
        ``[ndp, ...]`` float32 carry (per-device bytes == one replicated
        gradient buffer); the single sum over the group axis at the
        boundary is where XLA emits the one cross-chip reduction, feeding
        the ZeRO-sharded optimizer update directly.

        Numerics: grads are the mean over (microbatch, group) lanes —
        exactly the reference spelling's mean-of-equal-weight-microbatch
        gradients, refined to device groups (the documented
        equal-weight-mean-loss contract of ``gradient_accumulation``);
        float summation ORDER differs, so vs dp=1 this is
        close-not-bit-identical, like any resharding."""
        from jax.sharding import NamedSharding

        mesh = self.mesh

        def dp_sharded(x, lead=0):
            # the blessed accum-carry pin (docs/parallel.md rule 3):
            # plain dp on the group axis, marked pt_pin[accum_carry] so
            # the constraint-placement check can verify BOTH the site
            # and the spec (an fsdp-composed carry is an error even
            # when marked)
            with jax.named_scope("pt_pin[accum_carry]"):
                return jax.lax.with_sharding_constraint(
                    x, NamedSharding(mesh, _accum_carry_spec(lead)))

        xs_feeds = {}
        for n, mb in mbs.items():
            v = env[n]
            mb_g = mb // ndp
            g = dp_sharded(jnp.reshape(
                v, (ndp, accum, mb_g) + tuple(v.shape[1:])))
            xs_feeds[n] = dp_sharded(jnp.moveaxis(g, 0, 1), lead=1)

        def one_micro(gacc, xs):
            i, feeds_k = xs
            fctx = LoweringCtx(
                self, program, jax.random.fold_in(step_key, i + 1))
            fwd = make_fwd(fctx)

            def lane(feeds_lane):
                e0 = dict(env)
                e0.update(feeds_lane)
                return jax.grad(fwd, has_aux=True)(tparams, e0)

            g, aux = jax.vmap(lane)(feeds_k)
            # the [ndp, ...] f32 carry shards ONLY its group axis over
            # dp; an FSDP weight's dW deliberately stays replicated
            # over fsdp through the loops (an fsdp-sharded constraint
            # here makes GSPMD feature-shard the saved residuals,
            # turning in-body LN/softmax reductions into in-loop
            # all-reduces) — the optimizer-boundary pin reshards it
            # once, outside every loop
            gacc = jax.tree_util.tree_map(
                lambda a, gi: dp_sharded(a + gi.astype(jnp.float32)),
                gacc, g)
            # params/unwritten optimizer state sit in aux (optimizer-op
            # inputs) but env already holds them — stacking
            # [accum, ndp, ...] copies would burn accum x ndp x
            # state-bytes of scan-ys for nothing
            ys = {n: v for n, v in aux.items() if n not in passthrough}
            return gacc, ys

        g0 = jax.tree_util.tree_map(
            lambda p: dp_sharded(
                jnp.zeros((ndp,) + tuple(jnp.shape(p)), jnp.float32)),
            tparams)
        gacc, ys = jax.lax.scan(
            one_micro, g0, (jnp.arange(accum), xs_feeds))
        from ..parallel.api import grad_rs_spec_for

        def _finalize(n):
            return (jnp.sum(gacc[n], axis=0) / (ndp * accum)).astype(
                env[n].dtype)

        grads = {}
        for n in gacc:
            var = block._find_var(n)
            # the grad-RS provenance scope: this per-param sum over the
            # dp-sharded group axis is WHERE the one cross-chip
            # gradient reduction materializes, and under the
            # reduce-scatter spelling its operand is already the
            # fsdp-shard (GSPMD pushes the boundary pin's slice into
            # the carry — slice-before-reduce, valid because dW is
            # fsdp-replicated).  Scoping the sum per param threads
            # ``pt_pin[grad_rs_boundary:<param>]`` into the derived
            # all-reduce's op_name, which is what lets the CommPlan
            # extractor canonicalize it to a logical reduce-scatter
            # with per-grad attribution (analysis/comm/plan.py).
            if (var is not None
                    and grad_rs_spec_for(var, self.mesh, block)
                    is not None):
                with jax.named_scope(f"pt_pin[grad_rs_boundary:{n}]"):
                    grads[n] = _finalize(n)
            else:
                grads[n] = _finalize(n)
        return grads, self._reassemble_accum_aux(
            block, env, ys, full_b, bw, local_ndp=ndp)

    def _reassemble_accum_aux(self, block, env, ys, full_b, bw,
                              local_ndp=0):
        """Reassemble scan-stacked aux fetches back to their big-batch
        values.  ``ys`` entries carry a leading ``[accum, ...]`` axis —
        or ``[accum, ndp, ...]`` when ``local_ndp`` is set (the
        comm-aware path's vmapped device groups)."""
        producer = {}
        for op in block.ops[:bw]:
            for out_n in op.output_names():
                producer[out_n] = op

        def _static_batch_leading(name):
            var = block._find_var(name)
            vshape = tuple(var.shape) if var is not None else ()
            return len(vshape) >= 1 and (
                vshape[0] == -1 or (full_b and vshape[0] == full_b))

        aux = {}

        # additive combiners through which batch-sum-ness propagates
        # linearly: sum(microbatch values) reassembles the big-batch value
        # (layers.sums appends op type "sum", so no "sums" entry exists)
        _ADDITIVE = {"elementwise_add", "elementwise_sub", "sum", "scale"}
        _bs_memo = {}
        _bs_cap_hits = [0]

        def _is_batch_sum(name, _depth=0):
            """Transitive classification: True when the fetch is a pure
            batch-reduction sum (directly a reduce_sum over batch data, or
            an additive composite of such), so the big-batch value is the
            SUM of the microbatch values.  A composite mixing sum-like and
            non-sum-like terms has no exact reassembly — raise rather than
            silently return 1/accum of the truth.  Memoized per var name:
            a shared-subexpression additive DAG (x = x + x doubling) is
            linear work, not exponential.  A result whose subtree hit the
            depth cap is conservative-for-this-path, not a property of
            the var — it must NOT be memoized, or a later shallower query
            would read the poisoned value (the cap-hit counter detects
            taint anywhere in the subtree, short-circuiting included)."""
            if _depth > 64:
                _bs_cap_hits[0] += 1
                return False  # depth-capped: conservative
            if name in _bs_memo:
                return _bs_memo[name]
            before = _bs_cap_hits[0]
            r = _is_batch_sum_uncached(name, _depth)
            if _bs_cap_hits[0] == before:
                _bs_memo[name] = r
            return r

        def _is_batch_sum_uncached(name, _depth):
            op = producer.get(name)
            if op is None:
                return False
            ins = [i_n for ns_ in op.inputs.values() for i_n in ns_]
            if op.type == "reduce_sum":
                return any(_static_batch_leading(i) for i in ins) or all(
                    _is_batch_sum(i, _depth + 1) for i in ins)
            if op.type in _ADDITIVE:
                flags = [_is_batch_sum(i, _depth + 1) for i in ins]
                if op.type == "scale" and any(flags) and (
                        float(op.attrs.get("bias", 0.0)) != 0.0):
                    # X*s + b over a batch sum: summing microbatch
                    # values would inflate the bias term accum-fold
                    raise ValueError(
                        f"gradient_accumulation cannot reassemble fetch "
                        f"{name!r}: scale with a nonzero bias over a "
                        f"batch-sum term; apply the bias on the host")
                if any(flags) and not all(flags):
                    raise ValueError(
                        f"gradient_accumulation cannot reassemble fetch "
                        f"{name!r}: it mixes batch-sum terms with "
                        f"non-sum terms (op {op.type!r}); fetch the "
                        f"parts separately and combine on the host")
                return all(flags) and bool(flags)
            return False

        lead = 2 if local_ndp else 1
        for n, y in ys.items():
            # classify by the var's STATIC leading dim, not the runtime
            # shape (a [1]-shaped mean fetch with microbatch 1 must not be
            # mistaken for batch data): -1 or the full feed batch means
            # batch-leading -> microbatch results concatenate back.
            if y.ndim >= lead + 1 and _static_batch_leading(n):
                if local_ndp:
                    # [accum, ndp, mb_g, ...] -> device-major, then
                    # microbatch, then row: the exact original global
                    # batch order (each device's shard was split into
                    # accum contiguous slices)
                    aux[n] = jnp.moveaxis(y, 0, 1).reshape(
                        (-1,) + y.shape[3:])
                else:
                    aux[n] = y.reshape((-1,) + y.shape[2:])
                continue
            axes = tuple(range(lead))
            if _is_batch_sum(n):
                # a reduction OVER the batch: the big-batch sum is the
                # sum of the microbatch (x group) sums.  (reduce_sum of
                # batch-independent tensors — weight norms — is
                # microbatch-invariant and falls through to the mean,
                # which is then exact.)
                aux[n] = jnp.sum(y, axis=axes)
            elif jnp.issubdtype(y.dtype, jnp.inexact):
                # scalar metrics (avg loss): mean of equal-weight
                # microbatch (x group) averages == the big-batch average
                aux[n] = jnp.mean(y, axis=axes)
            else:
                aux[n] = y[(-1,) * lead] if local_ndp else y[-1]
        return aux

    def _compile(self, program, feed_names, fetch_names, state_names):
        step, persist_out = self.lower(
            program, feed_names, fetch_names, state_names)
        jit_kwargs = {}
        if self.donate_state:
            jit_kwargs["donate_argnums"] = 0
        if self.mesh is not None:
            from ..parallel.api import compile_shardings

            in_shardings, out_shardings = compile_shardings(
                self.mesh, program, feed_names, fetch_names, state_names,
                out_state_names=persist_out,
                extra_state=((GRAD_NORM_VAR,)
                             if _emits_grad_norm(program) else ()),
            )
            # NamedShardings carry the mesh, so no ambient mesh context is
            # needed around the jitted call.
            jit_kwargs["in_shardings"] = in_shardings
            jit_kwargs["out_shardings"] = out_shardings
        return jax.jit(step, **jit_kwargs)
