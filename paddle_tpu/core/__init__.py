from .dtypes import convert_dtype
from .place import CPUPlace, TPUPlace, Place, is_compiled_with_tpu
from . import unique_name
from .program import (
    Variable,
    Parameter,
    OpDesc,
    Block,
    Program,
    default_main_program,
    default_startup_program,
    program_guard,
    switch_main_program,
    switch_startup_program,
    name_scope,
)
from .registry import OpImpl, register_op, get_op_impl, registered_ops
from .scope import Scope, global_scope, scope_guard
from .executor import Executor
from . import ir
