"""Program transformations: pruning / inference conversion / structural
segment matching.

Reference: ``paddle/framework/prune.{h,cc}`` + ``pybind.cc:289 m.def("prune")``
and ``inference_optimize`` (pybind.cc:299).  Used by save_inference_model to
slice a training program down to the feed->fetch subgraph.

The structural-matching half (``match_op_run`` / ``detect_repeated_run`` /
``find_uniform_groups``) serves the scan-based remat engine: a Program is an
unrolled op list, but a transformer's N blocks are N structurally identical
op runs differing only in variable names.  Matching recovers that repetition
so the Executor can run the repeats as ONE ``lax.scan`` body with weights
stacked along the scan axis (the ``jax.checkpoint``-friendly form whose
backward has O(1)-per-layer remat temps) instead of N unrolled
barrier-serialized segments.
"""

import copy


def sub_block_names(program, block_idx, seen=None):
    """``(reads, writes)`` of every op anywhere under a sub-block,
    recursing into nested control-flow ops (while containing scan_block,
    etc.).  The single traversal the pruner and the static-analysis
    engine's program-level checks both rely on — one definition of "what
    a control-flow op touches"."""
    seen = set() if seen is None else seen
    if block_idx in seen:
        return set(), set()
    seen.add(block_idx)
    reads, writes = set(), set()
    for op in program.block(block_idx).ops:
        reads |= set(op.input_names())
        writes |= set(op.output_names())
        nested = op.attrs.get("sub_block")
        if nested is not None:
            r, w = sub_block_names(program, nested, seen)
            reads |= r
            writes |= w
    return reads, writes


def prune_program(program, targets):
    """Return a deep-copied program whose global block keeps only ops needed
    (transitively) to compute ``targets`` (Variables or names)."""
    target_names = {t.name if hasattr(t, "name") else str(t) for t in targets}
    pruned = copy.deepcopy(program)
    block = pruned.global_block()

    needed = set(target_names)
    kept = []
    for op in reversed(block.ops):
        produced = set(op.output_names())
        if produced & needed:
            kept.append(op)
            needed |= set(op.input_names())
            # control-flow ops pull in their (possibly nested) sub-block reads
            sub_idx = op.attrs.get("sub_block")
            if sub_idx is not None:
                needed |= sub_block_names(pruned, sub_idx)[0]
    kept.reverse()
    block.ops = kept
    block.backward_index = None
    pruned._backward_info = {}

    referenced = set(needed) | target_names
    for blk in pruned.blocks:
        for op in blk.ops:
            referenced |= set(op.input_names()) | set(op.output_names())
    block.vars = type(block.vars)(
        (n, v) for n, v in block.vars.items() if n in referenced
    )
    return pruned


# ---------------------------------------------------------------------------
# Structural matching of repeated op runs (the scan-remat front end)
# ---------------------------------------------------------------------------

def _op_impl_or_none(op_type):
    from .registry import get_op_impl

    try:
        return get_op_impl(op_type)
    except Exception:
        return None


def match_op_run(program, ops_a, ops_b):
    """Structural match of two op runs within one block.

    Returns ``(ext_map, out_map)`` when ``ops_b`` is the same op sequence as
    ``ops_a`` under a consistent variable renaming, else ``None``:

    - ``ext_map``: names read-before-written in A -> the corresponding name
      in B (the run's external inputs: carried activations, per-layer
      parameters, shared constants);
    - ``out_map``: names written by A -> the final corresponding written
      name in B (assignment semantics: last write wins, like the env).

    Bails (``None``) on raw/control-flow ops (sub-blocks are whole-program
    machinery, not repeatable straight-line structure), attr mismatches, or
    static shape/dtype mismatches of paired external inputs (stacking along
    a scan axis needs uniform operands).
    """
    if len(ops_a) != len(ops_b):
        return None
    block = program.global_block()
    ext, ext_rev, cur = {}, {}, {}

    def pair_input(na, nb):
        if na in cur:
            return cur[na] == nb
        if na in ext:
            return ext[na] == nb
        if nb in ext_rev:
            return False  # two canonical inputs collapsing onto one name
        va, vb = block._find_var(na), block._find_var(nb)
        if va is not None and vb is not None:
            if tuple(va.shape) != tuple(vb.shape) or va.dtype != vb.dtype:
                return False
        ext[na] = nb
        ext_rev[nb] = na
        return True

    for oa, ob in zip(ops_a, ops_b):
        if oa.type != ob.type:
            return None
        impl = _op_impl_or_none(oa.type)
        if impl is None or impl.raw:
            return None
        if "sub_block" in oa.attrs or "sub_block" in ob.attrs:
            return None
        if oa.attrs != ob.attrs:
            return None
        if set(oa.inputs) != set(ob.inputs) or set(oa.outputs) != set(ob.outputs):
            return None
        for slot in oa.inputs:
            nas, nbs = oa.inputs[slot], ob.inputs[slot]
            if len(nas) != len(nbs):
                return None
            for na, nb in zip(nas, nbs):
                if not pair_input(na, nb):
                    return None
        for slot in oa.outputs:
            nas, nbs = oa.outputs[slot], ob.outputs[slot]
            if len(nas) != len(nbs):
                return None
            for na, nb in zip(nas, nbs):
                cur[na] = nb
    return ext, cur


def detect_repeated_run(program, start, end, min_period=2, max_prologue=96):
    """Find the dominant periodic tiling of ``block.ops[start:end]``.

    Returns ``(s0, period, count)`` — ``count`` structurally identical
    (``match_op_run``) runs of ``period`` ops beginning at op ``s0`` — or
    ``None`` when nothing repeats at least twice.  Maximizes covered ops
    (``period * count``); the op-TYPE sequence prefilters candidates so the
    expensive structural check only runs on plausible periods.
    """
    ops = program.global_block().ops[start:end]
    n = len(ops)
    types = [op.type for op in ops]
    best = None  # (coverage, s0, period, count)
    # work budget: the (offset x period) scan is O(n^2) slice compares on
    # a repetition-free program — cap total compared elements so a huge
    # irregular net falls through to the caller's sqrt-N path in bounded
    # time instead of stalling memory_optimize for seconds
    budget = 2_000_000
    for off in range(0, min(max_prologue, n)):
        limit = (n - off) // 2
        p = min_period
        while p <= limit and budget > 0:
            budget -= p
            if types[off:off + p] == types[off + p:off + 2 * p]:
                base = ops[off:off + p]
                count = 1
                while off + (count + 1) * p <= n:
                    m = match_op_run(
                        program, base,
                        ops[off + count * p:off + (count + 1) * p])
                    if m is None:
                        break
                    count += 1
                if count >= 2:
                    coverage = p * count
                    if best is None or coverage > best[0]:
                        best = (coverage, start + off, p, count)
                    # a longer period at the same offset cannot beat
                    # full-coverage; keep scanning only if partial
                    if coverage >= n - off:
                        break
            p += 1
        if best is not None and best[0] >= n - off:
            break
        if budget <= 0:
            break
    if best is None:
        return None
    return best[1], best[2], best[3]


def find_uniform_groups(program, segments, min_repeat=2, max_period=24):
    """Group consecutive remat segments into scan-able uniform runs.

    ``segments`` is the transpiler's ``[(start, end, wrapped), ...]`` tiling
    of the forward prefix.  A group is ``segments[i : i + count*period]``
    where each period of ``period`` consecutive segments structurally
    repeats the first (same op structure via ``match_op_run``, same wrap
    flags) — e.g. one transformer layer under the selective policy is a
    ``[wrapped cheap-run, unwrapped kernel, ...]`` period.

    Returns a list of dicts ``{"start", "period", "count", "ext_maps",
    "out_maps"}`` (maps indexed by repeat k; k=0 is the identity over the
    canonical names).  Groups are disjoint, greedy left-to-right.
    """
    block = program.global_block()
    groups = []
    nseg = len(segments)
    i = 0
    while i < nseg:
        best = None  # (coverage_segments, period, count, ext_maps, out_maps)
        for p in range(1, min(max_period, (nseg - i) // 2) + 1):
            # wrap-flag pattern must repeat before paying for matching
            flags0 = [bool(s[2]) if len(s) > 2 else True
                      for s in segments[i:i + p]]
            base_ops = [op for (s, t, *_) in segments[i:i + p]
                        for op in block.ops[s:t]]
            if not base_ops:
                continue
            # identity maps for k=0
            m0 = match_op_run(program, base_ops, base_ops)
            if m0 is None:
                continue
            ext_maps, out_maps = [m0[0]], [m0[1]]
            count = 1
            while i + (count + 1) * p <= nseg:
                nxt = segments[i + count * p:i + (count + 1) * p]
                flags = [bool(s[2]) if len(s) > 2 else True for s in nxt]
                if flags != flags0:
                    break
                nxt_ops = [op for (s, t, *_) in nxt
                           for op in block.ops[s:t]]
                m = match_op_run(program, base_ops, nxt_ops)
                if m is None:
                    break
                ext_maps.append(m[0])
                out_maps.append(m[1])
                count += 1
            if count >= min_repeat:
                coverage = p * count
                if best is None or coverage > best[0]:
                    best = (coverage, p, count, ext_maps, out_maps)
        if best is not None:
            _, p, count, ext_maps, out_maps = best
            groups.append({"start": i, "period": p, "count": count,
                           "ext_maps": ext_maps, "out_maps": out_maps})
            i += p * count
        else:
            i += 1
    return groups
