"""Program transformations: pruning / inference conversion.

Reference: ``paddle/framework/prune.{h,cc}`` + ``pybind.cc:289 m.def("prune")``
and ``inference_optimize`` (pybind.cc:299).  Used by save_inference_model to
slice a training program down to the feed->fetch subgraph.
"""

import copy


def prune_program(program, targets):
    """Return a deep-copied program whose global block keeps only ops needed
    (transitively) to compute ``targets`` (Variables or names)."""
    target_names = {t.name if hasattr(t, "name") else str(t) for t in targets}
    pruned = copy.deepcopy(program)
    block = pruned.global_block()

    def sub_block_reads(block_idx, seen=None):
        """All names read anywhere under a sub-block, recursing into nested
        control-flow ops (while containing scan_block, etc.)."""
        seen = seen if seen is not None else set()
        if block_idx in seen:
            return set()
        seen.add(block_idx)
        reads = set()
        for sop in pruned.block(block_idx).ops:
            reads |= set(sop.input_names())
            nested = sop.attrs.get("sub_block")
            if nested is not None:
                reads |= sub_block_reads(nested, seen)
        return reads

    needed = set(target_names)
    kept = []
    for op in reversed(block.ops):
        produced = set(op.output_names())
        if produced & needed:
            kept.append(op)
            needed |= set(op.input_names())
            # control-flow ops pull in their (possibly nested) sub-block reads
            sub_idx = op.attrs.get("sub_block")
            if sub_idx is not None:
                needed |= sub_block_reads(sub_idx)
    kept.reverse()
    block.ops = kept
    block.backward_index = None
    pruned._backward_info = {}

    referenced = set(needed) | target_names
    for blk in pruned.blocks:
        for op in blk.ops:
            referenced |= set(op.input_names()) | set(op.output_names())
    block.vars = type(block.vars)(
        (n, v) for n, v in block.vars.items() if n in referenced
    )
    return pruned
