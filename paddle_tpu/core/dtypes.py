"""Dtype handling.

The reference enumerates dtypes in ``paddle/framework/framework.proto:91``
(VarType.Type: BOOL..FP64) and converts at kernel-dispatch time
(``paddle/framework/data_type_transform.cc``).  Here dtypes are plain numpy /
jax dtypes; bfloat16 is first-class because it is the MXU-native type.
"""

import jax.numpy as jnp
import numpy as np

_DTYPE_MAP = {
    "bool": jnp.bool_,
    "int8": jnp.int8,
    "uint8": jnp.uint8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    # "int64" maps to int32: TPU has no native int64 and JAX truncates it
    # without x64 mode anyway.  The reference uses int64 for ids/labels
    # (VarType.INT64); int32 covers every vocab/label size it supports.
    "int64": jnp.int32,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float64": jnp.float64,
    # reference spelling (VarType enum names, framework.proto:91)
    "fp16": jnp.float16,
    "fp32": jnp.float32,
    "fp64": jnp.float64,
}


def convert_dtype(dtype):
    """Accept a string / numpy dtype / jax dtype; return a canonical numpy dtype."""
    if dtype is None:
        return np.dtype("float32")
    if isinstance(dtype, str):
        if dtype not in _DTYPE_MAP:
            raise ValueError(f"unknown dtype {dtype!r}")
        return np.dtype(_DTYPE_MAP[dtype])
    return np.dtype(dtype)


def is_floating(dtype):
    return np.issubdtype(convert_dtype(dtype), np.floating) or convert_dtype(
        dtype
    ) == np.dtype(jnp.bfloat16)
