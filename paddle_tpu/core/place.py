"""Places — device tags.

Reference: ``paddle/platform/place.h:24,34,53`` defines CPUPlace / CUDAPlace
and a boost::variant Place consumed by kernel dispatch.  On TPU the analog is
a jax.Device (or a Mesh of them); Places here are thin selectors the Executor
resolves against ``jax.devices()``.
"""

import jax


class Place:
    def __eq__(self, other):
        return type(self) is type(other) and self.__dict__ == other.__dict__

    def __hash__(self):
        return hash((type(self).__name__, tuple(sorted(self.__dict__.items()))))

    def __repr__(self):
        args = ", ".join(f"{k}={v}" for k, v in self.__dict__.items())
        return f"{type(self).__name__}({args})"


class CPUPlace(Place):
    def get_device(self):
        return jax.devices("cpu")[0]


class TPUPlace(Place):
    """device_id indexes into the local TPU devices, like CUDAPlace(dev_id)."""

    def __init__(self, device_id=0):
        self.device_id = device_id

    def get_device(self):
        devs = _accelerator_devices()
        return devs[self.device_id]


def _accelerator_devices():
    devs = jax.devices()
    accel = [d for d in devs if d.platform != "cpu"]
    return accel or devs


def is_compiled_with_tpu():
    """Analog of core.is_compiled_with_cuda() used to gate device tests."""
    try:
        return any(d.platform != "cpu" for d in jax.devices())
    except RuntimeError:
        return False
