"""Op registry.

Reference: ``paddle/framework/op_registry.h:148 REGISTER_OP`` plus a kernel
map keyed by (dtype, place, layout, library) — ``operator.h:368``.  On TPU
there is exactly one "kernel" per op: a pure JAX function.  XLA handles dtype
specialization, layout and fusion, so the whole OpKernelType dispatch /
data-transform machinery (operator.cc:460-536) disappears by design.

Implementations are plain functions whose parameters are the op's input slot
names (capitalized, fluid convention: X, Y, Input, Filter, ...) plus attrs as
keyword arguments; they return ``{slot: array-or-list}``:

    @register_op("elementwise_add")
    def elementwise_add(X, Y, axis=-1, **_):
        return {"Out": X + Y}

Control-flow / meta ops register with ``raw=True`` and receive the lowering
context instead (they splice sub-blocks into lax.scan / while_loop / cond).
"""

import inspect

_REGISTRY = {}


class OpImpl:
    def __init__(self, op_type, fn, raw=False, stateful_rng=False,
                 nondiff=False):
        self.type = op_type
        self.fn = fn
        self.raw = raw
        self.stateful_rng = stateful_rng
        # nondiff: op has no linearization rule (integer outputs, argsort-
        # style selection, DP recursions over ints).  The executor
        # stop_gradients its inputs inside the grad prefix so linearization
        # treats it as a constant computation.
        self.nondiff = nondiff
        if not raw:
            sig = inspect.signature(fn)
            self.params = set(sig.parameters)
            self.has_var_kw = any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in sig.parameters.values()
            )
            self.wants_ctx = "_ctx" in self.params

    def call(self, ins, attrs, ctx):
        kwargs = dict(ins)
        for k, v in attrs.items():
            if self.has_var_kw or k in self.params:
                kwargs[k] = v
        if self.wants_ctx:
            kwargs["_ctx"] = ctx
        return self.fn(**kwargs)


def register_op(op_type, raw=False, stateful_rng=False, nondiff=False):
    def deco(fn):
        if op_type in _REGISTRY:
            raise ValueError(f"op {op_type!r} registered twice")
        _REGISTRY[op_type] = OpImpl(
            op_type, fn, raw=raw, stateful_rng=stateful_rng, nondiff=nondiff
        )
        return fn

    return deco


def get_op_impl(op_type):
    impl = _REGISTRY.get(op_type)
    if impl is None:
        raise KeyError(f"no implementation registered for op {op_type!r}")
    return impl


def registered_ops():
    return sorted(_REGISTRY)
