"""Host-side composable metrics (the v2 ``event_handler`` statistics helpers
+ the evaluators-as-ops pattern, SURVEY §5 observability).  These accumulate
on the host from fetched values; the in-program accumulating evaluators live
in paddle_tpu.evaluator."""

import numpy as np


class MetricBase:
    def __init__(self, name):
        self._name = name

    def reset(self):
        raise NotImplementedError

    def update(self, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(MetricBase):
    def __init__(self, name="accuracy"):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1.0):
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        return self.value / max(self.weight, 1e-12)


class EditDistance(MetricBase):
    def __init__(self, name="edit_distance"):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total_distance = 0.0
        self.seq_num = 0

    def update(self, distances, seq_num):
        self.total_distance += float(np.sum(distances))
        self.seq_num += int(seq_num)

    def eval(self):
        return self.total_distance / max(self.seq_num, 1)


class CompositeMetric(MetricBase):
    def __init__(self, name="composite"):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, **kwargs):
        for m in self._metrics:
            m.update(**kwargs)

    def eval(self):
        return [m.eval() for m in self._metrics]
