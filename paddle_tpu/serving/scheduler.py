"""SLO-aware goodput scheduling — the CONTROL half of the serving SLO
loop (ROADMAP item 1c; PR 11 shipped the measurement half).

The PR-2 admission policy was FIFO: maximize raw tok/s, serve every
request in arrival order no matter how late.  Under bursty load that is
exactly wrong for goodput — tokens delivered WITHIN each request's
TTFT/e2e budget: FIFO burns whole decode chunks finishing requests that
blew their deadline minutes ago while requests that could still make
theirs age out in the queue behind them.  This module closes the loop
with three decisions per free slot, fed by the engine's own measured
latency telemetry:

* **predict** — :class:`TtftPredictor` keeps EMAs of per-bucket prefill
  wall time and per-chunk decode wall time (the histograms
  ``serving.queue_wait`` / ``serving.prefill_seconds`` /
  ``serving.decode_chunk`` already observe; the predictor is the same
  stream folded to a point estimate).  Predicted TTFT of a queued
  request = time already queued + its bucket's prefill estimate;
  minimum service time adds the decode chunks its token budget needs.
* **shed** — a request whose age plus OPTIMISTIC minimum service time
  already exceeds its e2e budget cannot meet it under any schedule;
  serving it would burn capacity that on-time requests need.  It is
  failed immediately (``SheddedRequest``, ``serving.shed_total``).
  Optimism is deliberate: the bound only sheds provably-doomed work,
  never a request a lucky schedule could still save.
* **reorder** — among admissible requests, pop the one with the least
  TTFT slack (budget minus predicted TTFT): earliest-deadline-first
  over the deadline actually contracted.  Requests with no TTFT budget
  sort FIFO behind budgeted ones.

``FifoScheduler`` keeps the PR-2 behavior verbatim — it is the
benchmark baseline (``benchmarks/serving.py`` runs both policies under
the same shared-prefix Poisson load and gates SLO goodput > FIFO
goodput) and the compatibility spelling (``ServingEngine`` with no
budgets behaves identically under either).
"""

import math

__all__ = ["SheddedRequest", "TtftPredictor", "FifoScheduler",
           "SloScheduler", "make_scheduler"]


class SheddedRequest(RuntimeError):
    """The scheduler refused a request that could no longer meet its
    end-to-end budget (``Request.shed`` is True; ``result()`` raises
    this)."""


class TtftPredictor:
    """Point estimates of the engine's service-time components, fed by
    the driver thread after every measured prefill / decode chunk.

    EMA with a fast alpha: serving latencies are regime-y (compile
    storms, co-tenant noise) and an old regime's tail should wash out
    within a few observations.  ``ready`` stays False until at least
    one decode chunk AND one prefill have been observed — a cold
    predictor must never shed (the optimistic-bound contract degrades
    to "never doomed", not to garbage estimates)."""

    def __init__(self, alpha=0.3):
        self.alpha = float(alpha)
        self._prefill = {}      # suffix bucket -> EMA seconds
        self._chunk = None      # EMA seconds per decode-chunk call
        self._chunk_steps = 1

    def _fold(self, old, v):
        return v if old is None else old + self.alpha * (v - old)

    def observe_prefill(self, bucket, seconds):
        self._prefill[bucket] = self._fold(
            self._prefill.get(bucket), float(seconds))

    def observe_chunk(self, seconds, steps):
        self._chunk = self._fold(self._chunk, float(seconds))
        self._chunk_steps = max(1, int(steps))

    @property
    def ready(self):
        return self._chunk is not None and bool(self._prefill)

    def prefill_s(self, bucket):
        """Prefill estimate for a bucket; an unseen bucket scales the
        nearest observed one by the bucket ratio (prefill wall is
        linear in scanned tokens)."""
        if bucket in self._prefill:
            return self._prefill[bucket]
        if not self._prefill:
            return 0.0
        ref = min(self._prefill, key=lambda b: abs(b - bucket))
        return self._prefill[ref] * (bucket / ref)

    def decode_s(self, new_tokens):
        """OPTIMISTIC decode time for ``new_tokens`` greedy tokens: the
        chunk calls needed at the measured per-chunk wall, assuming the
        request rides every chunk from admission (no queueing ahead of
        it).  One token rode the prefill already."""
        if self._chunk is None:
            return 0.0
        calls = math.ceil(max(0, new_tokens - 1) / self._chunk_steps)
        return calls * self._chunk

    def predicted_ttft(self, req, bucket, now):
        """Queue age so far + the bucket's prefill estimate — the TTFT
        this request lands at if admitted right now."""
        return (now - req.submit_t) + self.prefill_s(bucket)

    def min_service_s(self, bucket, new_tokens):
        return self.prefill_s(bucket) + self.decode_s(new_tokens)


class FifoScheduler:
    """The PR-2 policy: strict arrival order, never sheds."""

    name = "fifo"

    def pick(self, queue, now, bucket_of):
        """Pop the next request to admit.  Returns ``(req_or_None,
        shed_list)``; FIFO never sheds."""
        return (queue.popleft() if queue else None), []


class SloScheduler:
    """Admit by least TTFT slack, shed what cannot meet its e2e budget.

    ``queue`` is the engine's deque, mutated under the engine's queue
    lock; ``bucket_of(req)`` maps a request to its (conservative,
    reuse-blind) prefill bucket.  ``budgets`` is any object with
    ``ttft_slo_s``/``e2e_slo_s`` attributes (the engine passes itself,
    so budgets mutated after construction — the bench/test pattern —
    are honored live); per-request budgets win over those defaults."""

    name = "slo"

    def __init__(self, predictor, budgets):
        self.predictor = predictor
        self.budgets = budgets

    def _budgets(self, req):
        ttft = getattr(req, "ttft_slo_s", None)
        e2e = getattr(req, "e2e_slo_s", None)
        return (ttft if ttft is not None else self.budgets.ttft_slo_s,
                e2e if e2e is not None else self.budgets.e2e_slo_s)

    def pick(self, queue, now, bucket_of):
        """One admission decision: remove and return the least-slack
        admissible request, plus the list of requests shed as provably
        unable to meet their e2e budget (removed from the queue; the
        engine fails them).  A cold predictor sheds nothing and
        degrades to FIFO order."""
        if not queue:
            return None, []
        pred = self.predictor
        shed, keep = [], []
        for req in queue:
            # one budget resolution + one trie-probing bucket estimate
            # per request — pick() runs under the engine's queue lock,
            # so the per-request work here gates concurrent submits
            ttft_b, e2e_b = self._budgets(req)
            bucket = bucket_of(req)
            if (e2e_b is not None and pred.ready
                    and getattr(req, "sheddable", True)
                    and (now - req.submit_t) + pred.min_service_s(
                        bucket, req.max_new) > e2e_b):
                shed.append(req)
            else:
                keep.append((req, ttft_b, bucket))
        choice = None
        if keep:
            def slack(item):
                i, (req, ttft_b, bucket) = item
                if ttft_b is None or not pred.ready:
                    # unbudgeted requests keep FIFO order BEHIND every
                    # budgeted one (inf slack, arrival index tiebreak)
                    return (math.inf, i)
                return (ttft_b - pred.predicted_ttft(req, bucket, now), i)

            _, (choice, _, _) = min(enumerate(keep), key=slack)
        queue.clear()
        queue.extend(r for (r, _, _) in keep if r is not choice)
        return choice, shed


def make_scheduler(kind, predictor, budgets):
    """Factory for ``ServingEngine(scheduler=...)``: "slo" (default) or
    "fifo" (the PR-2 baseline policy).  ``budgets`` supplies the
    engine-level ``ttft_slo_s``/``e2e_slo_s`` defaults (read live)."""
    kind = (kind or "slo").lower()
    if kind == "fifo":
        return FifoScheduler()
    if kind == "slo":
        return SloScheduler(predictor, budgets)
    raise ValueError(f"unknown scheduler {kind!r} (use 'slo' or 'fifo')")
