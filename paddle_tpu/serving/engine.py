"""Continuous-batching serving engine — slot-scheduled multi-request
decode over the flagship transformer's KV-cache serving path.

``models/transformer.py generate`` turned decode into a single jitted
scan, but it serves exactly one request per call: chip utilization
collapses under real traffic (many concurrent, variable-length
requests).  Decode is HBM-bandwidth-bound on WEIGHT reads, so batching
``S`` requests into one step re-reads the same weights for ``S`` tokens
— nearly free throughput.  The engine keeps one fixed-capacity batched
decode step saturated across many requests:

* **Slot pool** — the batched KV cache has ``max_slots`` rows; each row
  holds one active sequence with its own length (``pos``).  A slot is
  freed the moment its request hits EOS or its token budget, and the
  row is fully overwritten by the next prefill (stale K/V is never
  attended: decode writes position ``pos`` before masking ``<= pos``).
* **Continuous batching** — queued requests are admitted into free
  slots BETWEEN decode chunks, not at batch boundaries: a long request
  never holds the batch hostage, a short one never waits for stragglers.
* **Bucketed prefill** — prompts pad to the nearest power-of-two bucket
  so the compile cache is bounded by the bucket set (TVM-style static
  shape buckets), never by the request count: total executables =
  ``len(used prefill buckets) + 1`` decode chunk.
* **Chunked decode** — ``decode_chunk`` steps run per device call
  (one ``lax.scan``), amortizing dispatch + host sync over
  ``chunk × active_slots`` tokens.  EOS is detected on the host after
  the chunk; a slot finishing mid-chunk wastes at most ``chunk - 1``
  garbage steps (discarded, never surfaced).

Greedy decode through the engine is token-identical to running each
request alone through ``transformer.generate`` (same per-row math; see
``batched_decode``).  Telemetry flows through the global observability
registry under ``serving.*`` (queue depth, slot occupancy, admitted /
completed / token counters, TTFT + per-step + e2e histograms, tok/s
gauge, compile counters) — plus the TTFT decomposition pair
``serving.queue_wait`` (submit -> admission pop) and
``serving.decode_chunk`` (per chunk call), the measurement SLO-aware
admission needs.  With tracing enabled (``observability.trace``,
default on) every finished request also lays a span tree on its own
timeline lane — submit -> queue -> prefill(bucket) -> per-decode-chunk
-> evict — exported to Chrome-trace via ``trace.save(path)``.
"""

import collections
import threading
import time

import numpy as np

from ..observability import flight as _flight
from ..observability import metrics as _obs
from ..observability import trace as _trace
from . import batched_decode as _bd

__all__ = ["Request", "ServingEngine"]


class Request:
    """One submitted generation request and its (eventual) result.

    ``tokens`` holds only GENERATED tokens (EOS included when hit);
    ``result()`` returns prompt + generated as one int32 array.  Handles
    are thread-safe: ``wait``/``result`` may be called from any thread
    while the engine runs in another.  If the engine aborts (a device
    error mid-serve), the handle completes with ``error`` set and
    ``result()`` re-raises it instead of hanging waiters forever.
    """

    __slots__ = ("rid", "prompt", "max_new", "eos_id", "tokens",
                 "submit_t", "first_token_t", "finish_t", "error",
                 "admit_t", "prefill_t0", "prefill_t1", "bucket",
                 "chunks", "slo_ok", "_done")

    def __init__(self, rid, prompt, max_new, eos_id):
        self.rid = rid
        self.prompt = prompt
        self.max_new = max_new
        self.eos_id = eos_id
        self.tokens = []
        self.submit_t = time.perf_counter()
        self.first_token_t = None
        self.finish_t = None
        self.error = None
        # span-tree timestamps (observability.trace): queue pop, prefill
        # window, prefill bucket, and the decode-chunk windows this
        # request was live for — the request's timeline lane is emitted
        # from these when it finishes
        self.admit_t = None
        self.prefill_t0 = None
        self.prefill_t1 = None
        self.bucket = None
        self.chunks = []
        # SLO verdict at finish: True (met), False (violated), or None
        # (the engine has no SLO budgets configured)
        self.slo_ok = None
        self._done = threading.Event()

    @property
    def done(self):
        return self._done.is_set()

    def wait(self, timeout=None):
        return self._done.wait(timeout)

    def result(self, timeout=None):
        if not self._done.wait(timeout):
            raise TimeoutError(f"request {self.rid} not finished")
        if self.error is not None:
            raise RuntimeError(
                f"request {self.rid} failed: engine aborted") \
                from self.error
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)])

    @property
    def ttft(self):
        """Submit -> first generated token, seconds (None until then)."""
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.submit_t

    @property
    def e2e(self):
        """Submit -> finished, seconds (None until finished)."""
        if self.finish_t is None:
            return None
        return self.finish_t - self.submit_t


class ServingEngine:
    """Slot-scheduled continuous-batching front-end over the batched
    decode kernels.

    params   name->array dict with the Program's parameter names (e.g.
             ``transformer.extract_params()``); cast once to
             ``compute_dtype`` (default: the dtype the block/lm_head
             matmul weights imply — bf16-trained weights serve in bf16).
    max_len  per-slot KV-cache capacity; every request needs
             ``len(prompt) + max_new_tokens <= max_len``.
    max_slots     concurrent sequences in the batched step.
    decode_chunk  decode steps fused per device call.
    min_bucket    smallest prefill bucket; prompts pad to the nearest
             power-of-two multiple of it (compile-count bound).
    eos_id   default EOS token id (per-request override in ``submit``).
    ttft_slo_s / e2e_slo_s   per-request latency budgets (seconds).
             When set, every finished request is judged at finish time
             (``Request.slo_ok``): a breach counts
             ``serving.slo_violations`` and its tokens are EXCLUDED
             from the ``serving.goodput_tok_s`` gauge — throughput the
             users actually experienced within budget, the
             goodput-under-SLO measurement ROADMAP item 1(c) schedules
             against (tok/s alone rewards serving nobody on time).

    Drive it synchronously (``generate_many`` / ``step`` +
    ``results``) or from a background thread (``start``/``stop``) with
    producers calling ``submit`` concurrently.
    """

    def __init__(self, params, n_layer, n_head, d_model, max_len=128,
                 max_slots=8, decode_chunk=4, min_bucket=8, eos_id=None,
                 compute_dtype=None, eps=1e-5, donate=True,
                 registry=None, ttft_slo_s=None, e2e_slo_s=None):
        import jax
        import jax.numpy as jnp

        from ..models.transformer import infer_compute_dtype

        if d_model % n_head:
            raise ValueError(f"d_model {d_model} % n_head {n_head} != 0")
        if max_slots < 1 or decode_chunk < 1 or min_bucket < 1:
            raise ValueError("max_slots, decode_chunk and min_bucket "
                             "must all be >= 1")
        self.n_layer, self.n_head, self.d_model = n_layer, n_head, d_model
        self.max_len, self.max_slots = int(max_len), int(max_slots)
        self.decode_chunk = int(decode_chunk)
        self.min_bucket = int(min_bucket)
        self.eos_id = eos_id
        self._eps = eps
        self._donate = donate
        if ttft_slo_s is not None and ttft_slo_s <= 0:
            raise ValueError(f"ttft_slo_s must be > 0: {ttft_slo_s}")
        if e2e_slo_s is not None and e2e_slo_s <= 0:
            raise ValueError(f"e2e_slo_s must be > 0: {e2e_slo_s}")
        self.ttft_slo_s = ttft_slo_s
        self.e2e_slo_s = e2e_slo_s
        self._good_tokens = 0       # tokens of SLO-met completions
        self._first_submit_t = None  # goodput window opens here
        if compute_dtype is None:
            compute_dtype = infer_compute_dtype(params)
        self.compute_dtype = jnp.dtype(compute_dtype)
        table_len = np.asarray(params["pos_emb.w.w"]).shape[0]
        if self.max_len > table_len:
            raise ValueError(
                f"max_len {self.max_len} exceeds the trained position-"
                f"embedding table ({table_len} positions)")
        self._p = jax.device_put(
            {k: jnp.asarray(v, self.compute_dtype)
             for k, v in params.items()})
        dh = d_model // n_head
        self._ck = tuple(
            jnp.zeros((self.max_slots, self.max_len, n_head, dh),
                      self.compute_dtype) for _ in range(n_layer))
        self._cv = tuple(
            jnp.zeros((self.max_slots, self.max_len, n_head, dh),
                      self.compute_dtype) for _ in range(n_layer))
        self._last = jnp.zeros((self.max_slots,), jnp.int32)
        self._pos = jnp.zeros((self.max_slots,), jnp.int32)

        self._slots = [None] * self.max_slots     # Request or None
        self._free = list(range(self.max_slots))  # LIFO free list
        self._queue = collections.deque()
        self._completed = collections.deque()
        self._qlock = threading.Lock()    # queue/completed/counters
        self._dlock = threading.RLock()   # the device state (one driver)
        self._next_rid = 0
        self._prefill_fns = {}            # bucket -> compiled callable
        self._decode_fn = None
        self._thread = None
        self._stop = threading.Event()
        self._error = None                # fatal error: engine is dead
        self._inflight = 0                # popped from queue, not yet
                                          # slotted (visible to idle)
        self._req_lane_ends = []          # trace lane i -> last finish_t

        self._reg = registry or _obs.get_registry()
        self._reg.gauge("serving.slots_total").set(self.max_slots)
        self._reg.gauge("serving.slots_active").set(0)
        self._reg.gauge("serving.queue_depth").set(0)

    @property
    def _tracer(self):
        # resolved per call, not bound at construction, so a tracer
        # installed via trace.set_tracer() after the engine exists (the
        # test pattern) still receives the request span trees
        return _trace.get_tracer()

    # -- request intake ---------------------------------------------------
    def submit(self, prompt, max_new_tokens=16, eos_id=None):
        """Queue one request; returns its ``Request`` handle.  Thread-safe
        (producers may submit while the engine decodes)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        p_len = prompt.shape[0]
        if p_len < 1:
            raise ValueError("empty prompt")
        max_new = int(max_new_tokens)
        if max_new < 1:
            raise ValueError(f"max_new_tokens must be >= 1: {max_new}")
        if p_len + max_new > self.max_len:
            raise ValueError(
                f"prompt ({p_len}) + max_new_tokens ({max_new}) exceeds "
                f"the slot KV capacity max_len={self.max_len}")
        t = self._thread
        if t is not None and not t.is_alive() and not self._stop.is_set():
            # started driver died (supervision normally aborts first,
            # which the _error check below catches; this closes the
            # window where the thread is gone but the abort hasn't
            # landed) — never queue onto a dead driver
            raise RuntimeError(
                "serving driver thread is dead") from self._error
        with self._qlock:
            # _error is set under _qlock in _abort, so checking it here
            # closes the submit-after-abort window (a request appended
            # after the abort drained the queue would hang forever)
            if self._error is not None:
                raise RuntimeError(
                    "serving engine aborted") from self._error
            rid = self._next_rid
            self._next_rid += 1
            req = Request(rid, prompt,  max_new,
                          self.eos_id if eos_id is None else eos_id)
            if self._first_submit_t is None:
                self._first_submit_t = req.submit_t
            self._queue.append(req)
            self._reg.gauge("serving.queue_depth").set(len(self._queue))
        return req

    def results(self, block=False, timeout=None):
        """Drain finished requests (FIFO completion order; aborted
        requests surface here too, with ``error`` set).  With
        ``block=True``, waits up to ``timeout`` seconds for at least one
        (``timeout=0`` = poll once; ``None`` = wait indefinitely)."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            with self._qlock:
                out = list(self._completed)
                self._completed.clear()
            if out or not block:
                return out
            if deadline is not None and time.monotonic() >= deadline:
                return []
            time.sleep(0.001)

    # -- scheduler --------------------------------------------------------
    @property
    def active_slots(self):
        return self.max_slots - len(self._free)

    @property
    def idle(self):
        with self._qlock:
            pending = bool(self._queue) or self._inflight > 0
        return not pending and self.active_slots == 0

    def step(self):
        """One scheduler iteration: admit queued requests into free slots
        (bucketed prefill), then run one batched decode chunk.  Returns
        the number of requests finished this iteration.

        A device error mid-step leaves the donated caches unusable, so
        it is fatal: the engine aborts — every queued and in-flight
        request completes with ``error`` set (waiters wake instead of
        hanging) and further ``submit``/``step`` calls raise."""
        if self._error is not None:
            raise RuntimeError("serving engine aborted") from self._error
        with self._dlock:
            try:
                finished = self._admit()
                if self.active_slots:
                    finished += self._decode()
            except Exception as e:
                self._abort(e)
                raise
        return finished

    def _abort(self, exc):
        """Fail every pending request and mark the engine dead."""
        with self._qlock:
            self._error = exc
            self._inflight = 0
            pending = list(self._queue)
            self._queue.clear()
            for s, req in enumerate(self._slots):
                if req is not None:
                    pending.append(req)
                    self._slots[s] = None
            self._free = list(range(self.max_slots))
            for req in pending:
                req.error = exc
                req.finish_t = time.perf_counter()
                self._completed.append(req)
            self._reg.gauge("serving.queue_depth").set(0)
            self._reg.gauge("serving.slots_active").set(0)
            self._reg.counter("serving.aborted").inc(len(pending))
        for req in pending:
            req._done.set()
        # post-mortem: the abort (device error mid-step or driver
        # death) dumps the flight bundle — recent spans carry the
        # request/decode timeline that led here
        _flight.dump("serving_abort",
                     error=f"{type(exc).__name__}: {exc}"[:300],
                     failed_requests=len(pending))

    def run_until_idle(self):
        """Drive ``step`` until the queue and every slot are empty."""
        n = 0
        while not self.idle:
            n += self.step()
        return n

    def generate_many(self, prompts, max_new_tokens=16, eos_id=None):
        """Synchronous batch front-end: submit every prompt, run to
        completion, return one prompt+generated int32 array per prompt
        (order preserved).  ``max_new_tokens`` may be a scalar or a
        per-prompt sequence."""
        if np.ndim(max_new_tokens) == 0:
            max_new_tokens = [max_new_tokens] * len(prompts)
        if len(max_new_tokens) != len(prompts):
            raise ValueError(
                f"max_new_tokens has {len(max_new_tokens)} entries for "
                f"{len(prompts)} prompts")
        reqs = [self.submit(p, m, eos_id)
                for p, m in zip(prompts, max_new_tokens)]
        self.run_until_idle()
        # drain OWN handles from the completion queue (a concurrent
        # submit()+results() producer must still see its completions)
        mine = {id(r) for r in reqs}
        with self._qlock:
            kept = [r for r in self._completed if id(r) not in mine]
            self._completed.clear()
            self._completed.extend(kept)
        return [r.result(timeout=0) for r in reqs]

    # -- background driver ------------------------------------------------
    def start(self):
        """Run the scheduler loop on a daemon thread until ``stop()``.

        The driver is SUPERVISED: if the thread dies for ANY reason —
        not just a device error ``step()`` already turns into an abort,
        but any exception escaping the loop itself (``BaseException``
        included) — every queued and in-flight request is failed with
        the captured exception, so ``Request.result(timeout=None)``
        wakes instead of hanging forever and later ``submit()`` calls
        raise immediately."""
        if self._thread is not None:
            raise RuntimeError("engine already started")
        self._stop.clear()

        def loop():
            try:
                while not self._stop.is_set():
                    if self.idle:
                        time.sleep(0.001)
                        continue
                    self.step()
            except BaseException as e:  # noqa: BLE001 — supervision:
                # the driver is dying; step() aborts on Exception itself
                # (self._error set), anything else must not strand the
                # pending requests behind a silently-dead thread
                if self._error is None:
                    self._abort(e)
                self._reg.counter(
                    "serving.driver_deaths",
                    help="serving driver threads that died (requests "
                         "failed over, not stranded)").inc()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="pt-serving-engine")
        self._thread.start()

    def driver_alive(self):
        """True while the background driver thread is running."""
        t = self._thread
        return t is not None and t.is_alive()

    def stop(self, drain=True):
        """Stop the background loop (``drain=True`` serves out queued and
        active work first; a dead or aborted driver ends the drain
        immediately — its pending requests are already failed)."""
        if self._thread is None:
            return
        if drain:
            while not self.idle:
                if self._error is not None or not self._thread.is_alive():
                    break  # nothing will ever drain the rest
                time.sleep(0.001)
        self._stop.set()
        self._thread.join()
        self._thread = None

    # -- internals --------------------------------------------------------
    def _aot_with_mem_telemetry(self, fn, label):
        """Wrap a jitted entry point so its FIRST call compiles AOT
        (``lower().compile()`` — the same single compile the lazy jit
        path would do) and the executable's ``memory_analysis()`` lands
        in the ``serving.hbm_high_water_bytes`` / ``serving.temp_bytes``
        gauges; later calls reuse the executable.  Every call site feeds
        fixed shapes (bucketed prefill, the decode chunk), so the AOT
        executable serves all of them.  Backends without AOT fall back
        to the plain jit callable."""
        from ..analysis import compiled_memory_stats

        box = {}

        def call(*args):
            c = box.get("c")
            if c is None:
                try:
                    c = fn.lower(*args).compile()
                except Exception:
                    box["c"] = fn  # no AOT on this backend: plain jit
                    return fn(*args)
                box["c"] = c
                stats = compiled_memory_stats(c)
                if stats:
                    self._reg.gauge(
                        "serving.hbm_high_water_bytes", label=label,
                        help="compiled-executable HBM high-water "
                             "(memory_analysis)",
                    ).set_max(stats["hbm_high_water_bytes"])
                    self._reg.gauge(
                        "serving.temp_bytes", label=label,
                        help="compiled-executable HLO temp bytes",
                    ).set_max(stats["temp_bytes"])
            return c(*args)

        def cache_size():
            # executable count, same contract as jit's _cache_size():
            # the compile-bound tests assert exactly one per entry point
            c = box.get("c")
            if c is None:
                return 0
            if c is fn:
                return fn._cache_size()
            return 1

        call._cache_size = cache_size
        return call

    def bucket_for(self, p_len):
        """Prefill bucket for a prompt length: the smallest power-of-two
        multiple of ``min_bucket`` that covers it, capped at
        ``max_len``."""
        b = self.min_bucket
        while b < p_len:
            b *= 2
        return min(b, self.max_len)

    def _prefill_fn(self, bucket):
        fn = self._prefill_fns.get(bucket)
        if fn is None:
            fn = self._aot_with_mem_telemetry(
                _bd.make_prefill(self.n_layer, self.n_head, self.d_model,
                                 bucket, self.max_len, eps=self._eps,
                                 donate=self._donate),
                label=f"prefill_{bucket}")
            self._prefill_fns[bucket] = fn
            self._reg.counter(
                "serving.prefill_compiles",
                help="prefill executables built (one per shape bucket)",
            ).inc()
        return fn

    def _decode(self):
        if self._decode_fn is None:
            self._decode_fn = self._aot_with_mem_telemetry(
                _bd.make_decode_chunk(
                    self.n_layer, self.n_head, self.d_model,
                    self.decode_chunk, eps=self._eps, donate=self._donate),
                label="decode")
            self._reg.counter(
                "serving.decode_compiles",
                help="decode-chunk executables built (one per engine)",
            ).inc()
        t0 = time.perf_counter()
        self._ck, self._cv, self._last, self._pos, toks = self._decode_fn(
            self._p, self._ck, self._cv, self._last, self._pos)
        toks = np.asarray(toks)  # host sync: [chunk, S]
        t1 = time.perf_counter()
        wall = t1 - t0
        self._reg.histogram("serving.step_seconds").observe(
            wall / self.decode_chunk)
        # per-chunk-call latency (ISSUE 7 TTFT/TPOT decomposition) + the
        # driver-thread timeline span; every live request also records
        # this window for its own lane (emitted at finish)
        self._reg.histogram("serving.decode_chunk").observe(wall)
        tracer = self._tracer
        tracer.add_span("serving.decode_chunk", t0, t1,
                        cat="serving", steps=self.decode_chunk,
                        active=self.active_slots)
        if tracer.enabled:
            # per-request chunk windows feed only the finish-time lane
            # emission, which is skipped when tracing is off — don't
            # grow the lists on the disabled hot path
            for req in self._slots:
                if req is not None:
                    req.chunks.append((t0, t1))
        emitted = 0
        finished = 0
        now = time.perf_counter()
        for j in range(self.decode_chunk):
            for s, req in enumerate(self._slots):
                if req is None:
                    continue
                tok = int(toks[j, s])
                req.tokens.append(tok)
                emitted += 1
                if ((req.eos_id is not None and tok == req.eos_id)
                        or len(req.tokens) >= req.max_new):
                    self._slots[s] = None
                    self._free.append(s)
                    self._finish(req, now)
                    finished += 1
        self._reg.counter("serving.tokens").inc(emitted)
        if wall > 0:
            self._reg.gauge("serving.tok_s").set(emitted / wall)
        self._reg.gauge("serving.slots_active").set(self.active_slots)
        return finished

    def _admit(self):
        """Move queued requests into free slots (continuous batching:
        runs between decode chunks).  Returns requests finished AT
        prefill (immediate EOS / max_new == 1)."""
        import jax.numpy as jnp

        finished = 0
        while self._free:
            with self._qlock:
                if not self._queue:
                    break
                req = self._queue.popleft()
                # in-flight until slotted/finished, so idle never reads
                # True while an admission is mid-prefill
                self._inflight += 1
                self._reg.gauge("serving.queue_depth").set(
                    len(self._queue))
            # queue-wait: submit -> popped for admission.  With the
            # prefill window below this decomposes TTFT into queue time
            # vs prefill compute — the measurement SLO-aware admission
            # (ROADMAP item 3) schedules against.
            req.admit_t = time.perf_counter()
            self._reg.histogram("serving.queue_wait").observe(
                req.admit_t - req.submit_t)
            try:
                slot = self._free.pop()
                p_len = req.prompt.shape[0]
                bucket = self.bucket_for(p_len)
                req.bucket = bucket
                fn = self._prefill_fn(bucket)
                padded = np.zeros(bucket, np.int32)
                padded[:p_len] = req.prompt
                t_p0 = time.perf_counter()
                (self._ck, self._cv, self._last, self._pos,
                 first) = fn(self._p, self._ck, self._cv, self._last,
                             self._pos, np.int32(slot),
                             jnp.asarray(padded), np.int32(p_len))
                first = int(np.asarray(first))  # host sync
                now = time.perf_counter()
                req.prefill_t0, req.prefill_t1 = t_p0, now
                self._reg.histogram("serving.prefill_seconds").observe(
                    now - t_p0)
                self._tracer.add_span("serving.prefill", t_p0, now,
                                      cat="serving", rid=req.rid,
                                      bucket=bucket, slot=slot)
                req.first_token_t = now
                req.tokens.append(first)
                self._reg.counter("serving.admitted").inc()
                self._reg.counter("serving.tokens").inc()
                self._reg.histogram("serving.ttft_seconds").observe(
                    now - req.submit_t)
                if ((req.eos_id is not None and first == req.eos_id)
                        or req.max_new == 1):
                    self._free.append(slot)
                    self._finish(req, now)
                    finished += 1
                else:
                    self._slots[slot] = req
                with self._qlock:
                    self._inflight -= 1
            except Exception:
                # put the victim back where _abort (called by step) can
                # see and fail it with everything else
                with self._qlock:
                    self._queue.appendleft(req)
                    self._inflight -= 1
                raise
        self._reg.gauge("serving.slots_active").set(self.active_slots)
        return finished

    def _finish(self, req, now):
        req.finish_t = now
        self._reg.counter("serving.completed").inc()
        self._reg.histogram("serving.e2e_seconds").observe(req.e2e)
        self._judge_slo(req, now)
        self._emit_request_trace(req)
        with self._qlock:
            self._completed.append(req)
        req._done.set()

    def reset_slo_accounting(self):
        """Re-open the goodput window and zero the violation counter —
        benchmarks call this after their warm pass so compile-time TTFT
        breaches don't charge the timed run."""
        with self._qlock:
            self._good_tokens = 0
            self._first_submit_t = None
        c = self._reg.get("serving.slo_violations")
        if c is not None:
            c.reset()
        g = self._reg.get("serving.goodput_tok_s")
        if g is not None:
            g.reset()

    def _judge_slo(self, req, now):
        """SLO verdict at completion: a TTFT or e2e budget breach counts
        ``serving.slo_violations``; tokens of SLO-met requests feed the
        ``serving.goodput_tok_s`` gauge (good tokens over the window
        since the first submit — what the fleet delivered WITHIN budget,
        not what it merely emitted)."""
        if self.ttft_slo_s is None and self.e2e_slo_s is None:
            return
        ok = True
        if self.ttft_slo_s is not None and (
                req.ttft is None or req.ttft > self.ttft_slo_s):
            ok = False
        if self.e2e_slo_s is not None and (
                req.e2e is None or req.e2e > self.e2e_slo_s):
            ok = False
        req.slo_ok = ok
        if not ok:
            self._reg.counter(
                "serving.slo_violations",
                help="completed requests that breached their TTFT/e2e "
                     "SLO budget").inc()
        # _good_tokens/_first_submit_t are shared with submit() and
        # reset_slo_accounting() (which zeroes them under _qlock from
        # the caller's thread while the driver finishes requests) — the
        # read-modify-write must hold the same lock or a reset can lose
        # or resurrect warm-pass tokens
        with self._qlock:
            if ok:
                self._good_tokens += len(req.tokens)
            good, t0 = self._good_tokens, self._first_submit_t
        window = now - t0 if t0 is not None else 0.0
        if window > 0:
            self._reg.gauge(
                "serving.goodput_tok_s",
                help="tokens/sec from SLO-met requests since the first "
                     "submit (goodput under SLO, ROADMAP 1c)",
            ).set(good / window)

    def _emit_request_trace(self, req):
        """Lay the finished request's span tree on its own timeline lane:
        ``serving.request`` (submit -> finish) containing
        ``serving.req.queue`` / ``serving.req.prefill`` / one
        ``serving.req.decode_chunk`` per chunk the request was live for,
        closed by a zero-duration ``serving.req.evict`` marker.  These
        lane spans RE-present intervals the dedicated histograms
        (``serving.queue_wait`` / ``prefill_seconds`` /
        ``decode_chunk`` / ``e2e_seconds``) and the driver-thread
        operational spans already observed — one decode chunk is shared
        by every live request — so they are timeline-only
        (``timer=False``): folding them into ``host_timer.`` would
        multi-count the same wall seconds in the aggregate view."""
        tr = self._tracer
        if not tr.enabled or req.error is not None or req.admit_t is None:
            return
        lane = f"serving req {self._req_lane(req)}"
        tr.add_span("serving.request", req.submit_t, req.finish_t,
                    cat="serving", lane=lane, timer=False, rid=req.rid,
                    prompt_len=int(req.prompt.shape[0]),
                    tokens=len(req.tokens))
        tr.add_span("serving.req.queue", req.submit_t, req.admit_t,
                    cat="serving", lane=lane, timer=False, rid=req.rid)
        if req.prefill_t0 is not None:
            tr.add_span("serving.req.prefill", req.prefill_t0,
                        req.prefill_t1, cat="serving", lane=lane,
                        timer=False, rid=req.rid, bucket=req.bucket)
        for c0, c1 in req.chunks:
            tr.add_span("serving.req.decode_chunk", c0, c1,
                        cat="serving", lane=lane, timer=False,
                        rid=req.rid)
        tr.add_span("serving.req.evict", req.finish_t, req.finish_t,
                    cat="serving", lane=lane, timer=False, rid=req.rid)

    def _req_lane(self, req):
        """Pick a timeline lane whose previous occupant finished before
        this request was submitted, so overlapping requests NEVER share
        a lane (Chrome/Perfetto derive nesting purely from ts/dur
        containment within a tid — two live requests on one lane would
        render as one false tree).  Lanes are reused once free, keeping
        the lane count at the peak request concurrency; only past 64
        simultaneously-live requests does reuse fall back to the
        least-recently-freed lane.  Driver-thread only (called from
        ``_finish``), so no lock."""
        ends = self._req_lane_ends
        for i, end in enumerate(ends):
            if end <= req.submit_t:
                ends[i] = req.finish_t
                return i
        if len(ends) < 64:
            ends.append(req.finish_t)
            return len(ends) - 1
        i = min(range(len(ends)), key=ends.__getitem__)
        ends[i] = req.finish_t
        return i

    def stats(self):
        """Snapshot of the engine's ``serving.*`` metrics."""
        return self._reg.snapshot(prefix="serving.")
