"""Continuous-batching serving engine — slot-scheduled multi-request
decode over a PAGED, prefix-shared KV cache with SLO-aware goodput
scheduling.

``models/transformer.py generate`` turned decode into a single jitted
scan, but it serves exactly one request per call: chip utilization
collapses under real traffic (many concurrent, variable-length
requests).  Decode is HBM-bandwidth-bound on WEIGHT reads, so batching
``S`` requests into one step re-reads the same weights for ``S`` tokens
— nearly free throughput.  The engine keeps one fixed-capacity batched
decode step saturated across many requests:

* **Paged slot pool** — KV lives in a physical block pool
  (``serving.kvcache``): fixed-size blocks of ``block_tokens``
  positions, indexed per slot by a block table the compiled step
  gathers through.  A slot is a chain of blocks, not a contiguous row;
  blocks are reference-counted and returned to the pool the moment
  nothing uses them.
* **Prefix reuse** — identical prompt prefixes (system prompts,
  few-shot templates — the dominant production traffic shape) map
  through a trie to SHARED block chains: a request whose prefix is
  cached skips that portion of prefill entirely (full blocks shared by
  refcount; a divergence inside a cached block forks it copy-on-write).
  ``serving.prefix_hit_rate`` / ``serving.cow_copies`` /
  ``serving.blocks_in_use`` expose it live.
* **Continuous batching** — queued requests are admitted into free
  slots BETWEEN decode chunks, not at batch boundaries: a long request
  never holds the batch hostage, a short one never waits for stragglers.
* **Bucketed prefill** — the NON-CACHED prompt suffix pads to the
  nearest power-of-two bucket, so the compile cache is bounded by the
  bucket set (TVM-style static shape buckets), never by the request
  count: total executables = ``len(used prefill buckets) + 1`` decode
  chunk — the copy-on-write fork rides inside the prefill executable.
* **Chunked decode** — ``decode_chunk`` steps run per device call
  (one ``lax.scan``), amortizing dispatch + host sync.  EOS is detected
  on the host after the chunk.
* **SLO-aware scheduling** — the CONTROL half of the goodput loop
  (``serving.scheduler``; PR 11 shipped the measurement half): the
  queue is admitted by least predicted-TTFT slack and requests that
  provably cannot meet their e2e budget are SHED immediately
  (``serving.shed_total``) instead of burning decode capacity on
  tokens nobody receives on time.  ``scheduler="fifo"`` keeps the PR-2
  policy as the benchmark baseline.

Greedy decode through the engine is token-identical to running each
request alone through ``transformer.generate`` — prefix reuse on or off
(same per-row math; see ``batched_decode``).  Telemetry flows through
the global observability registry under ``serving.*``; with tracing
enabled every finished request lays a span tree on its own timeline
lane (submit -> queue -> prefill(bucket, prefix_hit) -> per-decode-
chunk -> evict) exported to Chrome-trace via ``trace.save(path)``.
"""

import collections
import threading
import time

import numpy as np

from ..observability import flight as _flight
from ..observability import metrics as _obs
from ..observability import trace as _trace
from ..resilience import faults as _faults
from . import batched_decode as _bd
from . import kvcache as _kv
from . import scheduler as _sched
from . import speculative as _spec

__all__ = ["Request", "ServingEngine"]


class Request:
    """One submitted generation request and its (eventual) result.

    ``tokens`` holds only GENERATED tokens (EOS included when hit);
    ``result()`` returns prompt + generated as one int32 array.  Handles
    are thread-safe: ``wait``/``result`` may be called from any thread
    while the engine runs in another.  If the engine aborts (a device
    error mid-serve), the handle completes with ``error`` set and
    ``result()`` re-raises it instead of hanging waiters forever; a
    request the SLO scheduler sheds completes with ``shed`` True and a
    ``SheddedRequest`` error.
    """

    __slots__ = ("rid", "prompt", "max_new", "eos_id", "tokens",
                 "submit_t", "first_token_t", "finish_t", "error",
                 "admit_t", "prefill_t0", "prefill_t1", "bucket",
                 "chunks", "slo_ok", "ttft_slo_s", "e2e_slo_s",
                 "shed", "sheddable", "prefix_hit",
                 "spec_proposed", "spec_accepted", "_done")

    def __init__(self, rid, prompt, max_new, eos_id,
                 ttft_slo_s=None, e2e_slo_s=None, sheddable=True):
        self.rid = rid
        self.prompt = prompt
        self.max_new = max_new
        self.eos_id = eos_id
        self.tokens = []
        self.submit_t = time.perf_counter()
        self.first_token_t = None
        self.finish_t = None
        self.error = None
        # span-tree timestamps (observability.trace): queue pop, prefill
        # window, prefill bucket, and the decode-chunk windows this
        # request was live for — the request's timeline lane is emitted
        # from these when it finishes
        self.admit_t = None
        self.prefill_t0 = None
        self.prefill_t1 = None
        self.bucket = None
        self.chunks = []
        # SLO verdict at finish: True (met), False (violated/shed), or
        # None (no SLO budgets configured); per-request budgets override
        # the engine-level defaults
        self.slo_ok = None
        self.ttft_slo_s = ttft_slo_s
        self.e2e_slo_s = e2e_slo_s
        self.shed = False
        # False exempts the request from scheduler shedding (it is
        # still judged against its budgets at finish) — the synchronous
        # generate_many front-end uses this: its caller waits for every
        # result, so refusing one only destroys output
        self.sheddable = sheddable
        # prompt tokens whose prefill was skipped via the prefix trie
        self.prefix_hit = 0
        # speculative accounting (0 when the engine has no draft):
        # draft tokens proposed for / accepted by this request
        self.spec_proposed = 0
        self.spec_accepted = 0
        self._done = threading.Event()

    @property
    def done(self):
        return self._done.is_set()

    def wait(self, timeout=None):
        return self._done.wait(timeout)

    def result(self, timeout=None):
        if not self._done.wait(timeout):
            raise TimeoutError(f"request {self.rid} not finished")
        if self.error is not None:
            if isinstance(self.error, _sched.SheddedRequest):
                raise self.error
            # the cause names what actually happened — an engine abort,
            # an injected slot death (engine still serving), a driver
            # death — don't claim more than "this request failed"
            raise RuntimeError(
                f"request {self.rid} failed: "
                f"{type(self.error).__name__}: {self.error}") \
                from self.error
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)])

    @property
    def ttft(self):
        """Submit -> first generated token, seconds (None until then)."""
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.submit_t

    @property
    def e2e(self):
        """Submit -> finished, seconds (None until finished)."""
        if self.finish_t is None:
            return None
        return self.finish_t - self.submit_t


class ServingEngine:
    """Slot-scheduled continuous-batching front-end over the paged
    batched decode kernels.

    params   name->array dict with the Program's parameter names (e.g.
             ``transformer.extract_params()``); cast once to
             ``compute_dtype`` (default: the dtype the block/lm_head
             matmul weights imply — bf16-trained weights serve in bf16).
    max_len  per-slot logical KV capacity; every request needs
             ``len(prompt) + max_new_tokens <= max_len``.
    max_slots     concurrent sequences in the batched step.
    decode_chunk  decode steps fused per device call.  ``None`` (the
             default) consults the autotune cache (workload key
             ``op=serving_decode``, docs/autotune.md) and falls back
             to 4 on a miss; an explicit value always wins.
    min_bucket    smallest prefill bucket; prompt SUFFIXES (after prefix
             reuse) pad to the nearest power-of-two multiple of it
             (compile-count bound).  ``None`` consults the same tuned
             entry; miss falls back to 8.
    block_tokens  tokens per physical KV block (paging granularity —
             also the prefix-sharing granularity: only whole blocks are
             shared, a partial overlap forks copy-on-write).
    cache_blocks  prefix-cache capacity budget: blocks the trie may
             keep alive beyond live requests (LRU-evicted under
             pressure).  Default ``2 * ceil(max_len / block_tokens)``.
    prefix_reuse  False disables the trie (every request pays full
             prefill — the PR-2 spelling; bit-exactness is gated in
             BOTH modes).
    scheduler  "slo" (default: least-TTFT-slack admission + e2e-doomed
             shedding; with no budgets configured it degrades to FIFO
             order) or "fifo" (the PR-2 baseline policy).
    eos_id   default EOS token id (per-request override in ``submit``).
    draft_params  parameter dict of a small DRAFT model (same
             ``transformer.build`` family: identical vocab / d_model /
             head geometry, fewer layers — e.g.
             ``speculative.depth_draft``).  When given (and
             ``PADDLE_TPU_SPEC`` is not off), decode runs SPECULATIVE
             rounds: the draft proposes ``spec_k`` tokens per slot into
             scratch block chains, one target verify forward scores the
             whole window, greedy acceptance commits the agreeing
             prefix + bonus token — TOKEN-EXACT vs plain greedy decode
             (docs/serving.md "Speculative decoding").  Geometry
             mismatches raise at construction.
    draft_n_layer / draft_n_head  the draft's depth / head count
             (default: inferred depth / the target's ``n_head``; a
             differing head count is rejected — the draft shares the
             target's paged pool arrays).
    spec_k   draft tokens proposed per round.  ``None`` consults the
             tuned ``op=spec_decode`` entry (docs/autotune.md) and
             falls back to 4; an explicit value always wins.
    ttft_slo_s / e2e_slo_s   per-request latency budgets (seconds),
             overridable per request in ``submit``.  When set, every
             finished request is judged at finish time
             (``Request.slo_ok``): a breach counts
             ``serving.slo_violations`` and its tokens are EXCLUDED
             from the ``serving.goodput_tok_s`` gauge — and the SLO
             scheduler admits/sheds against the same budgets, so
             goodput (not raw tok/s) is what the engine maximizes.

    Drive it synchronously (``generate_many`` / ``step`` +
    ``results``) or from a background thread (``start``/``stop``) with
    producers calling ``submit`` concurrently.
    """

    def __init__(self, params, n_layer, n_head, d_model, max_len=128,
                 max_slots=8, decode_chunk=None, min_bucket=None,
                 eos_id=None, compute_dtype=None, eps=1e-5, donate=True,
                 registry=None, ttft_slo_s=None, e2e_slo_s=None,
                 block_tokens=16, cache_blocks=None, prefix_reuse=True,
                 scheduler="slo", draft_params=None, draft_n_layer=None,
                 draft_n_head=None, spec_k=None):
        import jax
        import jax.numpy as jnp

        from ..models.transformer import infer_compute_dtype

        if d_model % n_head:
            raise ValueError(f"d_model {d_model} % n_head {n_head} != 0")
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1: {max_slots}")
        if block_tokens < 1:
            raise ValueError(f"block_tokens must be >= 1: {block_tokens}")
        self.n_layer, self.n_head, self.d_model = n_layer, n_head, d_model
        self.max_len, self.max_slots = int(max_len), int(max_slots)
        self.eos_id = eos_id
        self._eps = eps
        self._donate = donate
        if ttft_slo_s is not None and ttft_slo_s <= 0:
            raise ValueError(f"ttft_slo_s must be > 0: {ttft_slo_s}")
        if e2e_slo_s is not None and e2e_slo_s <= 0:
            raise ValueError(f"e2e_slo_s must be > 0: {e2e_slo_s}")
        self.ttft_slo_s = ttft_slo_s
        self.e2e_slo_s = e2e_slo_s
        self._good_tokens = 0       # tokens of SLO-met completions
        self._first_submit_t = None  # goodput window opens here
        if compute_dtype is None:
            compute_dtype = infer_compute_dtype(params)
        self.compute_dtype = jnp.dtype(compute_dtype)
        # decode chunk / bucket geometry: explicit args win; defaults
        # consult the tuned op=serving_decode entry (docs/autotune.md)
        if decode_chunk is None or min_bucket is None:
            cfg = self._tuned_geometry()
            if decode_chunk is None:
                decode_chunk = int(cfg.get("chunk", 4))
            if min_bucket is None:
                min_bucket = int(cfg.get("min_bucket", 8))
        if decode_chunk < 1 or min_bucket < 1:
            raise ValueError("decode_chunk and min_bucket must be >= 1")
        self.decode_chunk = int(decode_chunk)
        self.min_bucket = int(min_bucket)
        table_len = np.asarray(params["pos_emb.w.w"]).shape[0]
        if self.max_len > table_len:
            raise ValueError(
                f"max_len {self.max_len} exceeds the trained position-"
                f"embedding table ({table_len} positions)")
        self._p = jax.device_put(
            {k: jnp.asarray(v, self.compute_dtype)
             for k, v in params.items()})

        # -- speculative decoding (serving.speculative): with a draft
        # model and the PADDLE_TPU_SPEC switch on, decode runs
        # propose/verify/commit rounds.  Off (or no draft): none of
        # this exists — no validation, no extra pool blocks, no draft
        # executables — bit-identical to the plain engine.
        spec_on = draft_params is not None and _spec.spec_enabled()
        if spec_on:
            draft_n_layer = _spec.validate_draft(
                params, draft_params, n_layer, n_head, d_model,
                self.max_len, draft_n_layer=draft_n_layer,
                draft_n_head=draft_n_head)
            if spec_k is None:
                spec_k = int(self._tuned_spec().get(
                    "k", _spec.DEFAULT_SPEC_K))
        self.spec_k = int(spec_k) if spec_on else None

        # -- paged KV state (kvcache.py): pool arrays + host accounting
        self.block_tokens = int(block_tokens)
        self.blocks_per_slot = -(-self.max_len // self.block_tokens)
        if cache_blocks is None:
            cache_blocks = 2 * self.blocks_per_slot if prefix_reuse else 0
        if cache_blocks < 0:
            raise ValueError(f"cache_blocks must be >= 0: {cache_blocks}")
        self.cache_blocks = int(cache_blocks)
        # trash block + every slot's worst-case chain + the cache
        # budget: admission can ALWAYS allocate a full chain once the
        # trie evicts its unreferenced tail (kvcache.py invariants).
        # Speculative mode reserves a second worst-case chain per slot
        # for the draft's scratch blocks, so a propose round can never
        # starve admission.
        num_blocks = (1 + self.max_slots * self.blocks_per_slot
                      + self.cache_blocks)
        if spec_on:
            num_blocks += self.max_slots * self.blocks_per_slot
        self.kv_pool = _kv.BlockPool(num_blocks, self.block_tokens)
        self.prefix_trie = (_kv.PrefixTrie(self.kv_pool, self.cache_blocks)
                            if prefix_reuse else None)
        self.prefix_reuse = bool(prefix_reuse)
        dh = d_model // n_head
        self._pk = tuple(
            jnp.zeros((num_blocks, self.block_tokens, n_head, dh),
                      self.compute_dtype) for _ in range(n_layer))
        self._pv = tuple(
            jnp.zeros((num_blocks, self.block_tokens, n_head, dh),
                      self.compute_dtype) for _ in range(n_layer))
        self._last = jnp.zeros((self.max_slots,), jnp.int32)
        self._pos = jnp.zeros((self.max_slots,), jnp.int32)
        # host-side block table: unused entries -> trash block 0
        self._table = np.zeros((self.max_slots, self.blocks_per_slot),
                               np.int32)
        self._slot_blocks = [None] * self.max_slots  # bids a slot holds
        self._spec = (_spec.SpecState(self, draft_params, draft_n_layer,
                                      spec_k) if spec_on else None)

        self._slots = [None] * self.max_slots     # Request or None
        self._free = list(range(self.max_slots))  # LIFO free list
        self._queue = collections.deque()
        self._completed = collections.deque()
        self._qlock = threading.Lock()    # queue/completed/counters
        self._dlock = threading.RLock()   # the device state (one driver)
        self._next_rid = 0
        self._prefill_fns = {}            # suffix bucket -> compiled fn
        self._decode_fn = None
        # entry-point label -> the kernel-backend selections the kernel
        # registry recorded while that executable traced (so operators
        # can see WHICH attention spelling each compile used — paged
        # kernel vs the PADDLE_TPU_PAGED_ATTN=0 gather fallback)
        self.kernel_backends = {}
        self._thread = None
        self._stop = threading.Event()
        self._error = None                # fatal error: engine is dead
        self._inflight = 0                # popped from queue, not yet
                                          # slotted (visible to idle)
        self._req_lane_ends = []          # trace lane i -> last finish_t
        # the SLO control loop: measured-latency predictor + scheduler
        self.predictor = _sched.TtftPredictor()
        self._sched = _sched.make_scheduler(scheduler, self.predictor,
                                            budgets=self)
        # prefix-hit accounting window (reset with the goodput window)
        self._hit_tokens = 0
        self._prompt_tokens = 0

        self._reg = registry or _obs.get_registry()
        self._reg.gauge("serving.slots_total").set(self.max_slots)
        self._reg.gauge("serving.slots_active").set(0)
        self._reg.gauge("serving.queue_depth").set(0)
        self._reg.gauge(
            "serving.kv_blocks_total",
            help="physical KV blocks in the paged pool (excl. trash)",
        ).set(num_blocks - 1)
        self._reg.gauge("serving.blocks_in_use").set(0)

    def _tuned_geometry(self):
        """The tuned ``op=serving_decode`` config for this engine's
        shape, or {} (defaults apply).  Never raises — serving must
        construct even when the tune package is unhappy."""
        try:
            from .. import tune

            return tune.serving_decode_config(
                self.max_len, self.d_model // self.n_head, self.n_head,
                self.compute_dtype) or {}
        except Exception:  # noqa: BLE001 — lookup is best-effort
            return {}

    def _tuned_spec(self):
        """The tuned ``op=spec_decode`` config (the draft window ``k``)
        for this engine's shape, or {} — same never-raises contract as
        :meth:`_tuned_geometry`."""
        try:
            from .. import tune

            return tune.spec_decode_config(
                self.max_len, self.d_model // self.n_head, self.n_head,
                self.compute_dtype) or {}
        except Exception:  # noqa: BLE001 — lookup is best-effort
            return {}

    @property
    def _tracer(self):
        # resolved per call, not bound at construction, so a tracer
        # installed via trace.set_tracer() after the engine exists (the
        # test pattern) still receives the request span trees
        return _trace.get_tracer()

    # -- request intake ---------------------------------------------------
    def submit(self, prompt, max_new_tokens=16, eos_id=None,
               ttft_slo_s=None, e2e_slo_s=None, sheddable=True):
        """Queue one request; returns its ``Request`` handle.  Thread-safe
        (producers may submit while the engine decodes).  Per-request
        ``ttft_slo_s``/``e2e_slo_s`` budgets override the engine
        defaults for both the SLO verdict and the scheduler;
        ``sheddable=False`` exempts the request from scheduler shedding
        (it is still judged at finish)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        p_len = prompt.shape[0]
        if p_len < 1:
            raise ValueError("empty prompt")
        max_new = int(max_new_tokens)
        if max_new < 1:
            raise ValueError(f"max_new_tokens must be >= 1: {max_new}")
        if p_len + max_new > self.max_len:
            raise ValueError(
                f"prompt ({p_len}) + max_new_tokens ({max_new}) exceeds "
                f"the slot KV capacity max_len={self.max_len}")
        t = self._thread
        if t is not None and not t.is_alive() and not self._stop.is_set():
            # started driver died (supervision normally aborts first,
            # which the _error check below catches; this closes the
            # window where the thread is gone but the abort hasn't
            # landed) — never queue onto a dead driver
            raise RuntimeError(
                "serving driver thread is dead") from self._error
        with self._qlock:
            # _error is set under _qlock in _abort, so checking it here
            # closes the submit-after-abort window (a request appended
            # after the abort drained the queue would hang forever)
            if self._error is not None:
                raise RuntimeError(
                    "serving engine aborted") from self._error
            rid = self._next_rid
            self._next_rid += 1
            req = Request(rid, prompt, max_new,
                          self.eos_id if eos_id is None else eos_id,
                          ttft_slo_s=ttft_slo_s, e2e_slo_s=e2e_slo_s,
                          sheddable=sheddable)
            if self._first_submit_t is None:
                self._first_submit_t = req.submit_t
            self._queue.append(req)
            self._reg.gauge("serving.queue_depth").set(len(self._queue))
        return req

    def results(self, block=False, timeout=None):
        """Drain finished requests (FIFO completion order; aborted and
        shed requests surface here too, with ``error`` set).  With
        ``block=True``, waits up to ``timeout`` seconds for at least one
        (``timeout=0`` = poll once; ``None`` = wait indefinitely)."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            with self._qlock:
                out = list(self._completed)
                self._completed.clear()
            if out or not block:
                return out
            if deadline is not None and time.monotonic() >= deadline:
                return []
            time.sleep(0.001)

    # -- scheduler --------------------------------------------------------
    @property
    def active_slots(self):
        return self.max_slots - len(self._free)

    @property
    def idle(self):
        with self._qlock:
            pending = bool(self._queue) or self._inflight > 0
        return not pending and self.active_slots == 0

    def step(self):
        """One scheduler iteration: admit queued requests into free slots
        (scheduler-ordered, bucketed suffix prefill), then run one
        batched decode chunk.  Returns the number of requests finished
        this iteration (shed requests count — they completed, with
        ``error`` set).

        A device error mid-step leaves the donated pool unusable, so
        it is fatal: the engine aborts — every queued and in-flight
        request completes with ``error`` set (waiters wake instead of
        hanging) and further ``submit``/``step`` calls raise."""
        if self._error is not None:
            raise RuntimeError("serving engine aborted") from self._error
        with self._dlock:
            try:
                finished = self._admit()
                if self.active_slots:
                    finished += self._decode()
            except Exception as e:
                self._abort(e)
                raise
        return finished

    def _abort(self, exc):
        """Fail every pending request and mark the engine dead."""
        with self._qlock:
            self._error = exc
            self._inflight = 0
            pending = list(self._queue)
            self._queue.clear()
            for s, req in enumerate(self._slots):
                if req is not None:
                    pending.append(req)
                    self._slots[s] = None
                for b in self._slot_blocks[s] or ():
                    self.kv_pool.deref(b)
                self._slot_blocks[s] = None
                if self._spec is not None:
                    self._spec.release(self, s)
            self._table[:] = 0
            self._free = list(range(self.max_slots))
            for req in pending:
                req.error = exc
                req.finish_t = time.perf_counter()
                self._completed.append(req)
            self._reg.gauge("serving.queue_depth").set(0)
            self._reg.gauge("serving.slots_active").set(0)
            self._reg.counter("serving.aborted").inc(len(pending))
        for req in pending:
            req._done.set()
        # post-mortem: the abort (device error mid-step or driver
        # death) dumps the flight bundle — recent spans carry the
        # request/decode timeline that led here
        _flight.dump("serving_abort",
                     error=f"{type(exc).__name__}: {exc}"[:300],
                     failed_requests=len(pending))

    def run_until_idle(self):
        """Drive ``step`` until the queue and every slot are empty."""
        n = 0
        while not self.idle:
            n += self.step()
        return n

    def generate_many(self, prompts, max_new_tokens=16, eos_id=None):
        """Synchronous batch front-end: submit every prompt, run to
        completion, return one prompt+generated int32 array per prompt
        (order preserved).  ``max_new_tokens`` may be a scalar or a
        per-prompt sequence."""
        if np.ndim(max_new_tokens) == 0:
            max_new_tokens = [max_new_tokens] * len(prompts)
        if len(max_new_tokens) != len(prompts):
            raise ValueError(
                f"max_new_tokens has {len(max_new_tokens)} entries for "
                f"{len(prompts)} prompts")
        # unsheddable: this caller waits for EVERY result, so a
        # deadline shed could only destroy the batch's other outputs —
        # budgets still judge each request at finish (slo_ok)
        reqs = [self.submit(p, m, eos_id, sheddable=False)
                for p, m in zip(prompts, max_new_tokens)]
        self.run_until_idle()
        # drain OWN handles from the completion queue (a concurrent
        # submit()+results() producer must still see its completions)
        mine = {id(r) for r in reqs}
        with self._qlock:
            kept = [r for r in self._completed if id(r) not in mine]
            self._completed.clear()
            self._completed.extend(kept)
        return [r.result(timeout=0) for r in reqs]

    # -- background driver ------------------------------------------------
    def start(self):
        """Run the scheduler loop on a daemon thread until ``stop()``.

        The driver is SUPERVISED: if the thread dies for ANY reason —
        not just a device error ``step()`` already turns into an abort,
        but any exception escaping the loop itself (``BaseException``
        included) — every queued and in-flight request is failed with
        the captured exception, so ``Request.result(timeout=None)``
        wakes instead of hanging forever and later ``submit()`` calls
        raise immediately."""
        if self._thread is not None:
            raise RuntimeError("engine already started")
        self._stop.clear()

        def loop():
            try:
                while not self._stop.is_set():
                    if self.idle:
                        time.sleep(0.001)
                        continue
                    self.step()
            except BaseException as e:  # noqa: BLE001 — supervision:
                # the driver is dying; step() aborts on Exception itself
                # (self._error set), anything else must not strand the
                # pending requests behind a silently-dead thread
                if self._error is None:
                    self._abort(e)
                self._reg.counter(
                    "serving.driver_deaths",
                    help="serving driver threads that died (requests "
                         "failed over, not stranded)").inc()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="pt-serving-engine")
        self._thread.start()

    def driver_alive(self):
        """True while the background driver thread is running."""
        t = self._thread
        return t is not None and t.is_alive()

    def stop(self, drain=True):
        """Stop the background loop (``drain=True`` serves out queued and
        active work first; a dead or aborted driver ends the drain
        immediately — its pending requests are already failed)."""
        if self._thread is None:
            return
        if drain:
            while not self.idle:
                if self._error is not None or not self._thread.is_alive():
                    break  # nothing will ever drain the rest
                time.sleep(0.001)
        self._stop.set()
        self._thread.join()
        self._thread = None

    # -- internals --------------------------------------------------------
    def _aot_with_mem_telemetry(self, fn, label):
        """Wrap a jitted entry point so its FIRST call compiles AOT
        (``lower().compile()`` — the same single compile the lazy jit
        path would do) and the executable's ``memory_analysis()`` lands
        in the ``serving.hbm_high_water_bytes`` / ``serving.temp_bytes``
        gauges; later calls reuse the executable.  Every call site feeds
        fixed shapes (bucketed prefill, the decode chunk), so the AOT
        executable serves all of them.  Backends without AOT fall back
        to the plain jit callable."""
        from .. import kernels as _kernels
        from ..analysis.hlo_tools import compiled_memory_stats

        box = {}

        def prepare(*args):
            # compile (once) SEPARABLY from execution: call sites that
            # time their call and feed the wall into the scheduler's
            # latency predictor invoke this first, outside the timed
            # window — an EMA seeded with a one-time compile wall would
            # shed every arrival against a regime that no longer exists
            if box.get("c") is not None:
                return
            _kernels.reset_selected()
            try:
                c = fn.lower(*args).compile()
            except Exception:
                box["c"] = fn  # no AOT on this backend: plain jit
                return
            finally:
                # which kernel spelling this executable traced with —
                # per entry point, so operators can tell a paged-kernel
                # compile from a PADDLE_TPU_PAGED_ATTN=0 gather compile
                sel = _kernels.selected_backends()
                if sel:
                    self.kernel_backends[label] = sel
            box["c"] = c
            if _bd._paged_attn_on() and "paged_attention" in sel:
                self._reg.counter(
                    "serving.paged_attn_compiles",
                    help="serving executables compiled through the "
                         "paged_attention kernel (vs the "
                         "PADDLE_TPU_PAGED_ATTN=0 gather spelling)",
                ).inc()
            stats = compiled_memory_stats(c)
            if stats:
                self._reg.gauge(
                    "serving.hbm_high_water_bytes", label=label,
                    help="compiled-executable HBM high-water "
                         "(memory_analysis)",
                ).set_max(stats["hbm_high_water_bytes"])
                self._reg.gauge(
                    "serving.temp_bytes", label=label,
                    help="compiled-executable HLO temp bytes",
                ).set_max(stats["temp_bytes"])

        def call(*args):
            prepare(*args)
            return box["c"](*args)

        def cache_size():
            # executable count, same contract as jit's _cache_size():
            # the compile-bound tests assert exactly one per entry point
            c = box.get("c")
            if c is None:
                return 0
            if c is fn:
                return fn._cache_size()
            return 1

        call._cache_size = cache_size
        call.prepare = prepare
        return call

    def bucket_for(self, p_len):
        """Prefill bucket for a (suffix) length: the smallest
        power-of-two multiple of ``min_bucket`` that covers it, capped
        at ``max_len``."""
        b = self.min_bucket
        while b < p_len:
            b *= 2
        return min(b, self.max_len)

    def _prefill_fn(self, bucket):
        fn = self._prefill_fns.get(bucket)
        if fn is None:
            fn = self._aot_with_mem_telemetry(
                _bd.make_prefill(self.n_layer, self.n_head, self.d_model,
                                 bucket, eps=self._eps,
                                 donate=self._donate),
                label=f"prefill_{bucket}")
            self._prefill_fns[bucket] = fn
            self._reg.counter(
                "serving.prefill_compiles",
                help="prefill executables built (one per shape bucket)",
            ).inc()
        return fn

    def _release_slot(self, slot):
        """Return a slot and every KV block it references to the pool
        (shared blocks just drop one ref; private ones free).  The
        single reclamation path — eviction, immediate-EOS, slot death
        and abort all route here or mirror it exactly, so the fault
        test's no-leak invariant has one owner."""
        for b in self._slot_blocks[slot] or ():
            self.kv_pool.deref(b)
        self._slot_blocks[slot] = None
        self._table[slot] = 0
        if self._spec is not None:
            # the slot's draft scratch chain obeys the same discipline
            self._spec.release(self, slot)
        self._slots[slot] = None
        self._free.append(slot)
        if self.prefix_trie is not None:
            # blocks this slot shared with the trie are now trie-only:
            # re-apply the cache capacity budget
            self.prefix_trie.enforce_budget()
        self._reg.gauge("serving.blocks_in_use").set(
            self.kv_pool.blocks_in_use)

    def _decode(self):
        if self._spec is not None:
            return self._spec_decode()
        if self._decode_fn is None:
            self._decode_fn = self._aot_with_mem_telemetry(
                _bd.make_decode_chunk(
                    self.n_layer, self.n_head, self.d_model,
                    self.decode_chunk, eps=self._eps, donate=self._donate),
                label="decode")
            self._reg.counter(
                "serving.decode_compiles",
                help="decode-chunk executables built (one per engine)",
            ).inc()
        import jax.numpy as jnp

        # fault injection point (PADDLE_TPU_FAULT=slot_death:n): the
        # n-th decode chunk kills one active request mid-decode — its
        # slot and KV blocks must be reclaimed and the driver survive
        if _faults.maybe_fault("serving.decode") == "slot_death":
            self._kill_one_slot()
            if not self.active_slots:
                return 0
        # one-time AOT compile lands here, outside the timed window the
        # predictor consumes
        tbl = jnp.asarray(self._table)
        self._decode_fn.prepare(self._p, self._pk, self._pv, self._last,
                                self._pos, tbl)
        t0 = time.perf_counter()
        (self._pk, self._pv, self._last, self._pos,
         toks) = self._decode_fn(self._p, self._pk, self._pv, self._last,
                                 self._pos, tbl)
        toks = np.asarray(toks)  # host sync: [chunk, S]
        t1 = time.perf_counter()
        wall = t1 - t0
        self._reg.histogram("serving.step_seconds").observe(
            wall / self.decode_chunk)
        # per-chunk-call latency (ISSUE 7 TTFT/TPOT decomposition) + the
        # driver-thread timeline span; every live request also records
        # this window for its own lane (emitted at finish)
        self._reg.histogram("serving.decode_chunk").observe(wall)
        self.predictor.observe_chunk(wall, self.decode_chunk)
        tracer = self._tracer
        tracer.add_span("serving.decode_chunk", t0, t1,
                        cat="serving", steps=self.decode_chunk,
                        active=self.active_slots)
        if tracer.enabled:
            # per-request chunk windows feed only the finish-time lane
            # emission, which is skipped when tracing is off — don't
            # grow the lists on the disabled hot path
            for req in self._slots:
                if req is not None:
                    req.chunks.append((t0, t1))
        emitted = 0
        finished = 0
        now = time.perf_counter()
        for j in range(self.decode_chunk):
            for s, req in enumerate(self._slots):
                if req is None:
                    continue
                tok = int(toks[j, s])
                req.tokens.append(tok)
                emitted += 1
                if ((req.eos_id is not None and tok == req.eos_id)
                        or len(req.tokens) >= req.max_new):
                    self._release_slot(s)
                    self._finish(req, now)
                    finished += 1
        self._reg.counter("serving.tokens").inc(emitted)
        if wall > 0:
            self._reg.gauge("serving.tok_s").set(emitted / wall)
        self._reg.gauge("serving.slots_active").set(self.active_slots)
        return finished

    def _spec_decode(self):
        """One speculative round (serving.speculative): the draft
        proposes ``spec_k`` tokens per slot into scratch chains, ONE
        target verify forward scores every slot's ``k + 1``-token
        window, greedy acceptance commits the agreeing prefix plus the
        bonus token (token-exact vs plain greedy by induction), and
        scratch blocks past the committed frontier roll back to the
        pool.  At least one token commits per live slot per round, so
        progress is guaranteed even under a hostile draft."""
        import jax.numpy as jnp

        sp = self._spec
        k = sp.k
        B = self.block_tokens
        S = self.max_slots
        # per-slot committed frontier, rebuilt from host truth each
        # round: last committed token + its position + the last
        # position the slot may ever write (the verify write limit)
        last_h = np.zeros(S, np.int32)
        pos_h = np.zeros(S, np.int32)
        limit_h = np.full(S, -1, np.int32)
        for s, req in enumerate(self._slots):
            if req is None:
                continue
            p_len = req.prompt.shape[0]
            last_h[s] = req.tokens[-1]
            pos_h[s] = p_len + len(req.tokens) - 1
            limit_h[s] = p_len + req.max_new - 1
            hi = min(pos_h[s] + k, limit_h[s])
            sp.ensure_chain(self, s, int(hi) // B + 1)
        # the draft-chunk / verify-window one-time AOT compiles land
        # here, outside the timed window the predictor consumes (same
        # contract as the plain decode chunk); lowering needs only
        # shapes, so the verify prepares against a placeholder window
        nl = sp.n_layer
        sp.chunk_fn(self).prepare(
            sp.p, self._pk[:nl], self._pv[:nl], jnp.asarray(last_h),
            jnp.asarray(pos_h), jnp.asarray(sp.table))
        sp.verify_fn(self).prepare(
            self._p, self._pk, self._pv,
            jnp.asarray(np.zeros((S, k + 1), np.int32)),
            jnp.asarray(pos_h), jnp.asarray(limit_h),
            jnp.asarray(self._table))
        t0 = time.perf_counter()
        drafts = sp.propose(self, last_h, pos_h)       # [k, S] host
        t_d = time.perf_counter()
        self._reg.gauge(
            "serving.spec_draft_ms",
            help="draft propose wall time per speculative round (ms)",
        ).set((t_d - t0) * 1000.0)
        # fault injection point (PADDLE_TPU_FAULT=slot_death:n): in
        # speculative mode the decode-point death fires MID-VERIFY —
        # between propose and commit, the widest window of in-flight
        # scratch state.  The killed slot's real AND draft chains are
        # reclaimed (_release_slot), its table rows zero, and the
        # verify below runs with its write limit dropped to -1, so the
        # dead slot scatters only into the trash block.
        if _faults.maybe_fault("serving.decode") == "slot_death":
            self._kill_one_slot()
            if not self.active_slots:
                return 0
        for s in range(S):
            if self._slots[s] is None:
                limit_h[s] = -1
        U = np.zeros((S, k + 1), np.int32)
        U[:, 0] = last_h
        U[:, 1:] = drafts.T
        (self._pk, self._pv, greedy) = sp.verify_fn(self)(
            self._p, self._pk, self._pv, jnp.asarray(U),
            jnp.asarray(pos_h), jnp.asarray(limit_h),
            jnp.asarray(self._table))
        greedy = np.asarray(greedy)                    # host sync [S, k+1]
        t1 = time.perf_counter()
        wall = t1 - t0
        active = self.active_slots
        tracer = self._tracer
        if tracer.enabled:
            for req in self._slots:
                if req is not None:
                    req.chunks.append((t0, t1))
        emitted = 0
        finished = 0
        round_acc = 0
        now = time.perf_counter()
        for s, req in enumerate(self._slots):
            if req is None:
                continue
            remaining = req.max_new - len(req.tokens)
            commit, n_matched = _spec.accept_greedy(
                drafts[:, s], greedy[s], remaining)
            done = False
            appended = 0
            for tok in commit:
                req.tokens.append(tok)
                emitted += 1
                appended += 1
                if ((req.eos_id is not None and tok == req.eos_id)
                        or len(req.tokens) >= req.max_new):
                    done = True
                    break
            acc = min(n_matched, appended)
            # acceptance is judged over the draft tokens that COULD
            # have committed (the request's remaining window), not the
            # full k — end-of-request rounds would otherwise dilute the
            # rate and make the predictor's steps-per-round estimate,
            # and the reported draft quality, look worse than they are
            eff = min(k, max(0, remaining - 1))
            sp.proposed += eff
            sp.accepted += acc
            round_acc += acc
            req.spec_proposed += eff
            req.spec_accepted += acc
            if done:
                self._release_slot(s)
                self._finish(req, now)
                finished += 1
            else:
                # the draft KV is valid through the new frontier - 1;
                # scratch blocks past it held rejected-token state
                pos2 = req.prompt.shape[0] + len(req.tokens) - 1
                sp.rollback(self, s, (int(pos2) - 1) // B + 1)
        self._reg.counter("serving.tokens").inc(emitted)
        if wall > 0:
            self._reg.gauge("serving.tok_s").set(emitted / wall)
        if sp.proposed:
            self._reg.gauge(
                "serving.spec_accept_rate",
                help="draft tokens accepted / proposed since the last "
                     "accounting reset",
            ).set(sp.accepted / sp.proposed)
        self._reg.histogram("serving.decode_chunk").observe(wall)
        self._reg.histogram("serving.step_seconds").observe(
            wall / (k + 1))
        if active:
            # steps-per-round for the predictor: the steady-state
            # expectation 1 + accept_rate * k, not this round's
            # emitted/active — single rounds are noisy (slots finishing
            # mid-window report 1-2 tokens) and the predictor keeps
            # only the LAST steps value, so a noisy round would swing
            # predicted decode time by k x and mis-shed arrivals
            if sp.proposed:
                steps = 1 + k * (sp.accepted / sp.proposed)
            else:
                steps = emitted / active
            self.predictor.observe_chunk(wall, max(1, int(round(steps))))
        tracer.add_span("serving.spec_round", t0, t1, cat="serving",
                        k=k, active=active, emitted=emitted,
                        accepted=round_acc)
        self._reg.gauge("serving.blocks_in_use").set(
            self.kv_pool.blocks_in_use)
        self._reg.gauge("serving.slots_active").set(self.active_slots)
        return finished

    def _kill_one_slot(self):
        """Injected mid-decode slot death: fail the first active
        request, reclaim its slot and KV blocks (the no-leak
        regression), keep the driver alive."""
        for s, req in enumerate(self._slots):
            if req is None:
                continue
            req.error = RuntimeError(
                f"injected slot death (PADDLE_TPU_FAULT) — request "
                f"{req.rid} died in slot {s} mid-decode")
            req.finish_t = time.perf_counter()
            self._release_slot(s)
            self._reg.counter(
                "serving.slot_deaths",
                help="requests killed by injected mid-decode slot "
                     "death (blocks + slot reclaimed)").inc()
            self._reg.gauge("serving.slots_active").set(self.active_slots)
            with self._qlock:
                self._completed.append(req)
            req._done.set()
            return True
        return False

    def _sched_bucket(self, req):
        """The scheduler's prefill-bucket estimate for a queued request
        — REUSE-AWARE via a non-mutating trie probe (``peek_hit``
        touches no LRU clock), so a mostly-cached long prompt is costed
        at its real suffix bucket and never shed on the strength of a
        full prefill it would not pay.  The probe can only overestimate
        the eventual hit if the chain is evicted before admission —
        which under-sheds, the safe direction for the optimistic-bound
        contract."""
        p_len = req.prompt.shape[0]
        hit = 0
        if self.prefix_trie is not None:
            hit = self.prefix_trie.peek_hit(req.prompt, p_len - 1)
        return self.bucket_for(p_len - hit)

    def _admit(self):
        """Move queued requests into free slots (continuous batching:
        runs between decode chunks), in SCHEDULER order — the SLO
        scheduler pops by least TTFT slack and sheds e2e-doomed
        requests.  Returns requests finished AT admission (immediate
        EOS / max_new == 1 / shed)."""
        finished = 0
        while self._free:
            now = time.perf_counter()
            with self._qlock:
                if not self._queue:
                    break
                req, shed = self._sched.pick(self._queue, now,
                                             self._sched_bucket)
                if req is not None:
                    # in-flight until slotted/finished, so idle never
                    # reads True while an admission is mid-prefill
                    self._inflight += 1
                self._reg.gauge("serving.queue_depth").set(
                    len(self._queue))
            for victim in shed:
                self._shed(victim)
                finished += 1
            if req is None:
                if shed:
                    continue  # more queue may be schedulable next pass
                break
            # queue-wait: submit -> popped for admission.  With the
            # prefill window below this decomposes TTFT into queue time
            # vs prefill compute — the measurement the SLO-aware
            # admission schedules against.  Observed AFTER the
            # admission sticks: a PoolExhausted re-queue clears
            # admit_t, so a victim's wait is counted once, at its
            # final (successful) admission.
            req.admit_t = time.perf_counter()
            slot = self._free.pop()
            try:
                finished += self._prefill_into(slot, req)
                self._reg.histogram("serving.queue_wait").observe(
                    req.admit_t - req.submit_t)
                with self._qlock:
                    self._inflight -= 1
            except _kv.PoolExhausted:
                # every evictable cached chain is already gone and the
                # live slots hold the rest: back off until decode frees
                # blocks (put the victim back at the FRONT — it keeps
                # its place)
                self._free.append(slot)
                with self._qlock:
                    self._queue.appendleft(req)
                    self._inflight -= 1
                req.admit_t = None
                if self.active_slots == 0:
                    raise  # nothing will ever free blocks: fatal
                break
            except Exception:
                # put the victim back where _abort (called by step) can
                # see and fail it with everything else
                self._free.append(slot)
                with self._qlock:
                    self._queue.appendleft(req)
                    self._inflight -= 1
                raise
        self._reg.gauge("serving.slots_active").set(self.active_slots)
        return finished

    def _prefill_into(self, slot, req):
        """Admit one request into ``slot``: match the prefix trie,
        reference shared blocks, allocate the private tail (LRU-evicting
        cached chains under pressure), run the bucketed SUFFIX prefill
        (with the copy-on-write fork folded in), then register the
        prompt's full blocks in the trie.  Returns 1 when the request
        finished at prefill (immediate EOS / max_new == 1), else 0."""
        import jax.numpy as jnp

        pool, trie = self.kv_pool, self.prefix_trie
        p_len = req.prompt.shape[0]
        n_total = -(-(p_len + req.max_new) // self.block_tokens)
        shared, cow, hit = [], None, 0
        if trie is not None:
            shared, cow, hit = trie.match(req.prompt, p_len - 1)
        # hold every matched block across the eviction/alloc window so
        # LRU pressure can never free a chain we are about to attend
        hold = list(shared) + ([cow[0]] if cow else [])
        for b in hold:
            pool.ref(b)
        need = n_total - len(shared)
        try:
            if need > pool.free_blocks and trie is not None:
                trie.evict_lru(need - pool.free_blocks)
            priv = pool.alloc(need)
        except _kv.PoolExhausted:
            for b in hold:
                pool.deref(b)
            raise
        row = np.zeros(self.blocks_per_slot, np.int32)
        row[:len(shared)] = shared
        row[len(shared):n_total] = priv
        cow_src = cow_dst = 0
        if cow is not None:
            # fork the partially-matched cached block copy-on-write:
            # the fork target is the first private block (logical block
            # len(shared)); the copy itself rides inside the prefill
            # executable, so CoW costs zero extra compiles
            cow_src, cow_dst = cow[0], priv[0]
            self._reg.counter(
                "serving.cow_copies",
                help="prefix-cache blocks forked copy-on-write").inc()
        start = int(hit)
        suffix = p_len - start
        bucket = self.bucket_for(suffix)
        req.bucket = bucket
        req.prefix_hit = start
        fn = self._prefill_fn(bucket)
        padded = np.zeros(bucket, np.int32)
        padded[:suffix] = req.prompt[start:]
        # this bucket's one-time AOT compile lands here, outside the
        # timed window the predictor consumes
        fn.prepare(self._p, self._pk, self._pv, self._last, self._pos,
                   np.int32(slot), jnp.asarray(row), jnp.asarray(padded),
                   np.int32(start), np.int32(suffix), np.int32(cow_src),
                   np.int32(cow_dst))
        t_p0 = time.perf_counter()
        (self._pk, self._pv, self._last, self._pos,
         first) = fn(self._p, self._pk, self._pv, self._last, self._pos,
                     np.int32(slot), jnp.asarray(row),
                     jnp.asarray(padded), np.int32(start),
                     np.int32(suffix), np.int32(cow_src),
                     np.int32(cow_dst))
        first = int(np.asarray(first))  # host sync
        now = time.perf_counter()
        # the CoW source was held only for the copy window
        if cow is not None:
            pool.deref(cow[0])
        self._table[slot] = row
        self._slot_blocks[slot] = list(shared) + list(priv)
        if self._spec is not None:
            # draft prefill: scan the FULL prompt through the draft
            # into the slot's scratch chain so the first propose round
            # has a complete draft KV.  Runs before any request-state
            # mutation below so an (unlikely — the pool reserves a
            # draft chain per slot) PoolExhausted re-queues cleanly.
            try:
                self._spec.prefill(self, slot, req)
            except _kv.PoolExhausted:
                for b in self._slot_blocks[slot] or ():
                    pool.deref(b)
                self._slot_blocks[slot] = None
                self._table[slot] = 0
                self._spec.release(self, slot)
                if trie is not None:
                    trie.enforce_budget()
                self._reg.gauge("serving.blocks_in_use").set(
                    pool.blocks_in_use)
                raise
        if trie is not None:
            # register the prompt's FULL blocks (shared ones are
            # already cached and skipped; our private full blocks
            # become reusable by the next identical prefix)
            trie.insert(req.prompt, [int(b) for b in row[:p_len
                                                         // self.block_tokens]])
        req.prefill_t0, req.prefill_t1 = t_p0, now
        self._reg.histogram("serving.prefill_seconds").observe(now - t_p0)
        self.predictor.observe_prefill(bucket, now - t_p0)
        self._tracer.add_span("serving.prefill", t_p0, now,
                              cat="serving", rid=req.rid,
                              bucket=bucket, slot=slot,
                              prefix_hit=start)
        req.first_token_t = now
        req.tokens.append(first)
        self._reg.counter("serving.admitted").inc()
        self._reg.counter("serving.tokens").inc()
        self._reg.counter(
            "serving.prefill_tokens",
            help="prompt-suffix tokens actually scanned by prefill "
                 "(bucket-padded; prefix hits subtract from this)",
        ).inc(bucket)
        self._reg.histogram("serving.ttft_seconds").observe(
            now - req.submit_t)
        with self._qlock:
            self._hit_tokens += start
            self._prompt_tokens += p_len
            hit_rate = (self._hit_tokens / self._prompt_tokens
                        if self._prompt_tokens else 0.0)
        self._reg.counter(
            "serving.prefix_hit_tokens",
            help="prompt tokens served from the prefix cache "
                 "(prefill skipped)").inc(start)
        self._reg.gauge(
            "serving.prefix_hit_rate",
            help="cumulative prefix-cache hit rate over prompt tokens "
                 "(since the last accounting reset)").set(hit_rate)
        self._reg.gauge("serving.blocks_in_use").set(pool.blocks_in_use)
        if ((req.eos_id is not None and first == req.eos_id)
                or req.max_new == 1):
            self._release_slot(slot)
            # _release_slot re-appended the slot; the caller's _free
            # bookkeeping is already consistent (slot was popped there)
            self._finish(req, now)
            return 1
        self._slots[slot] = req
        return 0

    def _shed(self, req):
        """Fail a request the scheduler refused (cannot meet its e2e
        budget): it completes immediately with ``shed`` True and a
        ``SheddedRequest`` error — capacity goes to requests that can
        still meet their deadlines."""
        now = time.perf_counter()
        req.shed = True
        req.slo_ok = False
        req.error = _sched.SheddedRequest(
            f"request {req.rid} shed after {now - req.submit_t:.3f}s in "
            f"queue: predicted completion exceeds its e2e budget")
        req.finish_t = now
        self._reg.counter(
            "serving.shed_total",
            help="requests shed by the SLO scheduler (could no longer "
                 "meet their e2e budget)").inc()
        self._tracer.instant("serving.shed", cat="serving", rid=req.rid)
        with self._qlock:
            self._completed.append(req)
        req._done.set()

    def _finish(self, req, now):
        req.finish_t = now
        self._reg.counter("serving.completed").inc()
        self._reg.histogram("serving.e2e_seconds").observe(req.e2e)
        self._judge_slo(req, now)
        self._emit_request_trace(req)
        with self._qlock:
            self._completed.append(req)
        req._done.set()

    def reset_slo_accounting(self):
        """Re-open the goodput window and zero the violation/shed
        counters and the prefix-hit window — benchmarks call this after
        their warm pass so compile-time TTFT breaches (and warm-pass
        trie traffic) don't charge the timed run.  The window ORIGIN is
        re-armed too: the next ``submit`` starts a fresh
        since-first-submit window, so a warm pass can never deflate the
        timed run's ``serving.goodput_tok_s`` denominator."""
        with self._qlock:
            self._good_tokens = 0
            self._first_submit_t = None
            self._hit_tokens = 0
            self._prompt_tokens = 0
            if self._spec is not None:
                self._spec.proposed = 0
                self._spec.accepted = 0
        for nm in ("serving.slo_violations", "serving.goodput_tok_s",
                   "serving.shed_total", "serving.prefix_hit_rate",
                   "serving.prefix_hit_tokens", "serving.prefill_tokens",
                   "serving.cow_copies", "serving.spec_accept_rate",
                   "serving.spec_draft_ms",
                   "serving.spec_rollback_blocks"):
            m = self._reg.get(nm)
            if m is not None:
                m.reset()

    def _judge_slo(self, req, now):
        """SLO verdict at completion: a TTFT or e2e budget breach counts
        ``serving.slo_violations``; tokens of SLO-met requests feed the
        ``serving.goodput_tok_s`` gauge (good tokens over the window
        since the first submit — what the fleet delivered WITHIN budget,
        not what it merely emitted).  Per-request budgets win over the
        engine defaults."""
        ttft_b = (req.ttft_slo_s if req.ttft_slo_s is not None
                  else self.ttft_slo_s)
        e2e_b = (req.e2e_slo_s if req.e2e_slo_s is not None
                 else self.e2e_slo_s)
        if ttft_b is None and e2e_b is None:
            return
        ok = True
        if ttft_b is not None and (req.ttft is None or req.ttft > ttft_b):
            ok = False
        if e2e_b is not None and (req.e2e is None or req.e2e > e2e_b):
            ok = False
        req.slo_ok = ok
        if not ok:
            self._reg.counter(
                "serving.slo_violations",
                help="completed requests that breached their TTFT/e2e "
                     "SLO budget").inc()
        # _good_tokens/_first_submit_t are shared with submit() and
        # reset_slo_accounting() (which zeroes them under _qlock from
        # the caller's thread while the driver finishes requests) — the
        # read-modify-write must hold the same lock or a reset can lose
        # or resurrect warm-pass tokens
        with self._qlock:
            if ok:
                self._good_tokens += len(req.tokens)
            good, t0 = self._good_tokens, self._first_submit_t
        window = now - t0 if t0 is not None else 0.0
        if window > 0:
            self._reg.gauge(
                "serving.goodput_tok_s",
                help="tokens/sec from SLO-met requests since the first "
                     "submit (goodput under SLO, ROADMAP 1c)",
            ).set(good / window)

    def _emit_request_trace(self, req):
        """Lay the finished request's span tree on its own timeline lane:
        ``serving.request`` (submit -> finish) containing
        ``serving.req.queue`` / ``serving.req.prefill`` / one
        ``serving.req.decode_chunk`` per chunk the request was live for,
        closed by a zero-duration ``serving.req.evict`` marker.  These
        lane spans RE-present intervals the dedicated histograms
        (``serving.queue_wait`` / ``prefill_seconds`` /
        ``decode_chunk`` / ``e2e_seconds``) and the driver-thread
        operational spans already observed — one decode chunk is shared
        by every live request — so they are timeline-only
        (``timer=False``): folding them into ``host_timer.`` would
        multi-count the same wall seconds in the aggregate view."""
        tr = self._tracer
        if not tr.enabled or req.error is not None or req.admit_t is None:
            return
        lane = f"serving req {self._req_lane(req)}"
        spec_attrs = ({"spec_proposed": req.spec_proposed,
                       "spec_accepted": req.spec_accepted}
                      if req.spec_proposed else {})
        tr.add_span("serving.request", req.submit_t, req.finish_t,
                    cat="serving", lane=lane, timer=False, rid=req.rid,
                    prompt_len=int(req.prompt.shape[0]),
                    tokens=len(req.tokens),
                    prefix_hit=req.prefix_hit, **spec_attrs)
        tr.add_span("serving.req.queue", req.submit_t, req.admit_t,
                    cat="serving", lane=lane, timer=False, rid=req.rid)
        if req.prefill_t0 is not None:
            tr.add_span("serving.req.prefill", req.prefill_t0,
                        req.prefill_t1, cat="serving", lane=lane,
                        timer=False, rid=req.rid, bucket=req.bucket,
                        prefix_hit=req.prefix_hit)
        for c0, c1 in req.chunks:
            tr.add_span("serving.req.decode_chunk", c0, c1,
                        cat="serving", lane=lane, timer=False,
                        rid=req.rid)
        tr.add_span("serving.req.evict", req.finish_t, req.finish_t,
                    cat="serving", lane=lane, timer=False, rid=req.rid)

    def _req_lane(self, req):
        """Pick a timeline lane whose previous occupant finished before
        this request was submitted, so overlapping requests NEVER share
        a lane (Chrome/Perfetto derive nesting purely from ts/dur
        containment within a tid — two live requests on one lane would
        render as one false tree).  Lanes are reused once free, keeping
        the lane count at the peak request concurrency; only past 64
        simultaneously-live requests does reuse fall back to the
        least-recently-freed lane.  Driver-thread only (called from
        ``_finish``), so no lock."""
        ends = self._req_lane_ends
        for i, end in enumerate(ends):
            if end <= req.submit_t:
                ends[i] = req.finish_t
                return i
        if len(ends) < 64:
            ends.append(req.finish_t)
            return len(ends) - 1
        i = min(range(len(ends)), key=ends.__getitem__)
        ends[i] = req.finish_t
        return i

    def stats(self):
        """Snapshot of the engine's ``serving.*`` metrics."""
        return self._reg.snapshot(prefix="serving.")
