"""Paged KV cache — a physical block pool plus a reference-counted
prefix trie, the serving memory subsystem under ``ServingEngine``.

The PR-2 engine gave every slot a contiguous ``[max_len, h, dh]`` cache
row: capacity was ``max_slots x max_len`` whether a request used 8
tokens or 500, and two requests sharing a system prompt paid full
prefill twice.  This module splits the cache TVM-style into a logical
and a physical layer (PAPERS.md — portable schedule over a tuned
layout):

* **Physical** — ``BlockPool``: ``num_blocks`` fixed-size blocks of
  ``block_tokens`` token positions each, one pool per layer
  (``[num_blocks, block_tokens, n_head, d_head]`` device arrays managed
  by the engine; this class owns the host-side accounting — free list
  and per-block reference counts).  Physical block id 0 is the
  **trash block**: never allocated, permanently referenced, the safe
  landing zone every unused block-table entry points at (overrun decode
  steps write garbage there; no live slot ever attends it).
* **Logical** — each slot's sequence is a chain of block ids in a
  per-slot block table row; position ``t`` lives at
  ``(table[t // B], t % B)``.  Decode gathers K/V through the table
  inside the compiled step (``batched_decode``), so the executable
  count stays ``used_buckets + 1`` — the table is data, not shape.
* **Prefix reuse** — ``PrefixTrie``: a trie over FULL-block token
  chunks.  A request whose prompt starts with an already-cached chain
  shares those physical blocks (refcount, zero copy, zero prefill
  compute for the shared span); a prompt that diverges INSIDE a cached
  block forks it copy-on-write (one private block copy, the shared
  tokens still skipped).  Blocks are freed when their refcount hits
  zero; cached chains nobody references are evicted LRU under an
  explicit capacity budget.

Refcount invariants (pinned by ``tests/test_kvcache.py``):

- a block referenced by ``k`` slots and present in the trie has
  refcount ``k + 1``; a trie-only block has refcount 1; refcount 0
  means the block is on the free list — exactly one of these states
  holds for every non-trash block at every driver-thread quiescent
  point;
- the trie never holds a block the pool considers free, and eviction
  only ever touches refcount-1 (trie-only) leaf nodes, so a chain
  shared with a live slot can never be yanked out from under it;
- ``alloc`` after ``evict_lru`` always succeeds when the engine uses
  the default pool sizing (``max_slots`` full chains + the cache
  budget + trash), because slot-held blocks are bounded by the slot
  count.

Why full-block granularity is bit-exact: KV at position ``t`` is a
deterministic function of the token prefix ``tokens[:t+1]`` alone
(absolute position embeddings, greedy decode, no dropout).  A trie node
at depth ``d`` is keyed by the exact ``(d+1) * block_tokens``-token
prefix that produced its block, so a match guarantees the cached bytes
equal what prefill would recompute — the engine's served-equals-
single-stream identity survives reuse (the acceptance gate).
"""

import numpy as np

__all__ = ["BlockPool", "PoolExhausted", "PrefixTrie"]


class PoolExhausted(RuntimeError):
    """Not enough free blocks to satisfy an allocation (after LRU
    eviction of every unreferenced cached chain)."""


class BlockPool:
    """Host-side accounting for the physical block pool: free list +
    per-block refcounts.  Block 0 is the trash block — permanently
    referenced, never handed out, the target of every unused block-table
    entry."""

    TRASH = 0

    def __init__(self, num_blocks, block_tokens):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (trash + one real): {num_blocks}")
        if block_tokens < 1:
            raise ValueError(f"block_tokens must be >= 1: {block_tokens}")
        self.num_blocks = int(num_blocks)
        self.block_tokens = int(block_tokens)
        self._ref = np.zeros(self.num_blocks, np.int64)
        self._ref[self.TRASH] = 1  # pinned forever
        # LIFO free list: recently-freed blocks are re-handed first
        # (their pool rows are hot)
        self._free = list(range(self.num_blocks - 1, self.TRASH, -1))

    # -- accounting views ------------------------------------------------
    @property
    def free_blocks(self):
        return len(self._free)

    @property
    def blocks_in_use(self):
        """Non-trash blocks currently referenced (slots and/or trie)."""
        return self.num_blocks - 1 - len(self._free)

    def refcount(self, bid):
        return int(self._ref[bid])

    # -- lifecycle -------------------------------------------------------
    def alloc(self, n):
        """``n`` fresh blocks at refcount 1, or :class:`PoolExhausted`
        (nothing allocated on failure — all-or-nothing, so a failed
        admission never leaks a partial chain)."""
        if n > len(self._free):
            raise PoolExhausted(
                f"need {n} blocks, {len(self._free)} free "
                f"of {self.num_blocks - 1}")
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
        return out

    def ref(self, bid):
        """Add one reference to a live block (sharing an existing
        chain)."""
        if bid == self.TRASH:
            return
        if self._ref[bid] <= 0:
            raise ValueError(f"ref of free block {bid}")
        self._ref[bid] += 1

    def deref(self, bid):
        """Drop one reference; a block hitting zero returns to the free
        list immediately (no deferred sweep — the leak test is exact)."""
        if bid == self.TRASH:
            return
        if self._ref[bid] <= 0:
            raise ValueError(f"deref of free block {bid}")
        self._ref[bid] -= 1
        if self._ref[bid] == 0:
            self._free.append(bid)


class _Node:
    """One cached full block: the exact token chunk it encodes, the
    physical block id, children keyed by their chunk tuple, and the LRU
    clock."""

    __slots__ = ("chunk", "block", "children", "parent", "last_used")

    def __init__(self, chunk, block, parent):
        self.chunk = chunk          # tuple of block_tokens ints
        self.block = block          # physical block id
        self.children = {}          # chunk tuple -> _Node
        self.parent = parent        # _Node or the trie root sentinel
        self.last_used = 0


class PrefixTrie:
    """Prefix-reuse index: maps identical prompt prefixes to shared,
    reference-counted block chains.

    Edges are FULL ``block_tokens``-token chunks.  ``match`` walks exact
    chunk matches (share, refcount) and then finds the longest common
    prefix into one more cached chunk (copy-on-write fork material).
    ``insert`` registers a finished prefill's full prompt blocks.
    ``evict_lru``/``enforce_budget`` drop least-recently-used
    UNREFERENCED leaves (refcount 1 — held by nobody but the trie);
    chains shared with live slots are never evicted."""

    def __init__(self, pool, capacity_blocks):
        self.pool = pool
        self.capacity_blocks = int(capacity_blocks)
        self._root = _Node(None, None, None)
        self._clock = 0
        self._nodes = 0

    def __len__(self):
        return self._nodes

    def _tick(self):
        self._clock += 1
        return self._clock

    @staticmethod
    def _chunks(tokens, block_tokens):
        toks = [int(t) for t in tokens]
        return [tuple(toks[i:i + block_tokens])
                for i in range(0, len(toks) - block_tokens + 1,
                               block_tokens)]

    def match(self, tokens, limit):
        """Longest cached prefix of ``tokens`` usable within ``limit``
        tokens (the engine passes ``p_len - 1``: the last prompt
        position is always recomputed, so its logits exist).

        Returns ``(shared_bids, cow, hit_tokens)``:

        - ``shared_bids`` — block ids fully covered by the match, to be
          referenced as-is (the caller must ``pool.ref`` each);
        - ``cow`` — ``(src_bid, j)`` when the NEXT cached chunk agrees
          on its first ``j > 0`` tokens: fork material (copy the block,
          keep ``j`` positions) — or None;
        - ``hit_tokens`` — total prompt tokens whose prefill is skipped
          (``len(shared_bids) * B + j``).

        Touches every node on the path (LRU)."""
        B = self.pool.block_tokens
        toks = [int(t) for t in tokens]
        node = self._root
        shared = []
        i = 0
        now = self._tick()
        while (i + B <= limit and i + B <= len(toks)):
            child = node.children.get(tuple(toks[i:i + B]))
            if child is None:
                break
            child.last_used = now
            shared.append(child.block)
            node = child
            i += B
        # partial tail: the longest common prefix into one more cached
        # chunk, capped so the total stays within ``limit``
        cow = None
        tail = toks[i:min(len(toks), i + B)]
        room = limit - i
        best_j = 0
        best = None
        if tail and room > 0:
            for chunk, child in node.children.items():
                j = 0
                for a, b in zip(tail, chunk):
                    if a != b:
                        break
                    j += 1
                j = min(j, room)
                if j > best_j:
                    best_j, best = j, child
        if best is not None:
            best.last_used = now
            cow = (best.block, best_j)
        return shared, cow, len(shared) * B + best_j

    def peek_hit(self, tokens, limit):
        """Prompt tokens a :meth:`match` would serve from the cache,
        WITHOUT touching LRU clocks or returning block references — the
        scheduler's prediction probe (estimating a queued request's
        prefill must not distort eviction order)."""
        B = self.pool.block_tokens
        toks = [int(t) for t in tokens]
        node = self._root
        i = 0
        while i + B <= limit and i + B <= len(toks):
            child = node.children.get(tuple(toks[i:i + B]))
            if child is None:
                break
            node = child
            i += B
        tail = toks[i:min(len(toks), i + B)]
        room = limit - i
        best_j = 0
        if tail and room > 0:
            for chunk in node.children:
                j = 0
                for a, b in zip(tail, chunk):
                    if a != b:
                        break
                    j += 1
                best_j = max(best_j, min(j, room))
        return i + best_j

    def insert(self, tokens, block_ids):
        """Register a prompt's FULL blocks: ``block_ids[c]`` holds KV
        for ``tokens[c*B:(c+1)*B]``.  Only whole chunks are inserted
        (``len(block_ids)`` of them); chunks already cached are skipped
        (the caller's private duplicate stays private).  Each inserted
        block gains one trie reference.  Returns the number of blocks
        newly cached."""
        B = self.pool.block_tokens
        chunks = self._chunks(tokens, B)[:len(block_ids)]
        node = self._root
        now = self._tick()
        added = 0
        for chunk, bid in zip(chunks, block_ids):
            child = node.children.get(chunk)
            if child is None:
                self.pool.ref(bid)
                child = _Node(chunk, bid, node)
                node.children[chunk] = child
                self._nodes += 1
                added += 1
            child.last_used = now
            node = child
        if added:
            self.enforce_budget()
        return added

    # -- eviction --------------------------------------------------------
    def _evictable_leaves(self):
        """Leaves held by nobody but the trie (refcount exactly 1) —
        the only nodes LRU eviction may touch.  Depth-first walk; the
        trie is small (bounded by the capacity budget)."""
        out = []
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            elif self.pool.refcount(n.block) == 1:
                out.append(n)
        return out

    def _evict_node(self, node):
        del node.parent.children[node.chunk]
        self._nodes -= 1
        self.pool.deref(node.block)  # -> free list (refcount was 1)

    def evict_lru(self, need_blocks):
        """Free at least ``need_blocks`` blocks by evicting
        least-recently-used unreferenced leaves (a freed leaf may expose
        its parent as the next candidate — chains unwind tail-first).
        Returns the number of blocks actually freed (may be short when
        every cached chain is pinned by a live slot)."""
        freed = 0
        while freed < need_blocks:
            leaves = self._evictable_leaves()
            if not leaves:
                break
            victim = min(leaves, key=lambda n: n.last_used)
            self._evict_node(victim)
            freed += 1
        return freed

    def enforce_budget(self):
        """LRU-evict unreferenced cached blocks down to the capacity
        budget.  Only trie-ONLY blocks count against the budget (a
        block also referenced by a live slot is the slot's memory, not
        cache overhead) and only those are evictable."""
        while True:
            only = self._trie_only_count()
            if only <= self.capacity_blocks:
                return
            leaves = self._evictable_leaves()
            if not leaves:
                return
            self._evict_node(min(leaves, key=lambda n: n.last_used))

    def _trie_only_count(self):
        n = 0
        stack = list(self._root.children.values())
        while stack:
            nd = stack.pop()
            stack.extend(nd.children.values())
            if self.pool.refcount(nd.block) == 1:
                n += 1
        return n

    def clear(self):
        """Drop every cached chain (deref all trie-held blocks)."""
        stack = list(self._root.children.values())
        while stack:
            nd = stack.pop()
            stack.extend(nd.children.values())
            self.pool.deref(nd.block)
        self._root.children.clear()
        self._nodes = 0
