"""``python -m paddle_tpu --spec-selftest`` — speculative decoding's
CI gate, CPU-only (wired into tools/tier1.sh; docs/serving.md
"Speculative decoding").

The acceptance bar is the house bit-exactness contract applied to the
propose/verify/commit loop:

1. PARITY: a speculative engine (depth-pruned draft) emits TOKEN-EXACT
   output vs single-stream ``transformer.generate`` greedy — f32 and
   bf16, prefix reuse on and off, mixed prompt lengths.
2. SELF-DRAFT: with the draft = the target itself, the acceptance rate
   must be near 1 — an empirical probe that the parallel verify window
   is bit-consistent with the sequential step (any numeric drift
   between the two shows up as spurious rejections here).
3. ADVERSARIAL: a draft from a DIFFERENT random init (near-zero
   agreement) still yields exact output — acceptance only gates which
   target tokens commit per round, never what they are — and at least
   one token commits per round (progress under a hostile draft).
4. ZERO LEAK: after serving, ``blocks_in_use`` equals the plain
   engine's after the same workload — propose/rollback retains no
   scratch blocks.
5. KILL SWITCH: ``PADDLE_TPU_SPEC=0`` with a draft passed builds a
   bit-identical plain engine — same tokens, no draft executables, no
   spec metrics.
"""

import os

import numpy as np

__all__ = ["run_selftest"]

_TOY = dict(vocab=50, n_layer=2, n_head=2, d_model=32, max_len=64)


def _make_params(seed=7):
    import paddle_tpu as pt
    from paddle_tpu.models import transformer

    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    main.random_seed = seed
    with pt.program_guard(main, startup):
        transformer.build(
            vocab_size=_TOY["vocab"], n_layer=_TOY["n_layer"],
            n_head=_TOY["n_head"], d_model=_TOY["d_model"],
            max_len=_TOY["max_len"], dropout_rate=0.0, dtype="float32")
    pt.Executor().run(startup)
    return transformer.extract_params(program=main)


def _bf16(params):
    import jax.numpy as jnp

    return {k: (jnp.asarray(v, jnp.bfloat16)
                if (k.startswith("block") or k.startswith("lm_head"))
                and k.endswith(".w") else v)
            for k, v in params.items()}


def _engine(params, **kw):
    from .engine import ServingEngine

    kw.setdefault("max_len", _TOY["max_len"])
    kw.setdefault("max_slots", 4)
    kw.setdefault("decode_chunk", 4)
    kw.setdefault("min_bucket", 4)
    return ServingEngine(params, _TOY["n_layer"], _TOY["n_head"],
                         _TOY["d_model"], **kw)


def _ref_outputs(params, prompts, max_new):
    from paddle_tpu.models import transformer

    outs = []
    for p in prompts:
        toks, _ = transformer.generate(
            params, p[None], max_len=_TOY["max_len"],
            n_layer=_TOY["n_layer"], n_head=_TOY["n_head"],
            d_model=_TOY["d_model"], return_logits=False)
        outs.append(np.asarray(toks)[0][: len(p) + max_new])
    return outs


def run_selftest():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("PADDLE_TPU_SPEC", None)
    from paddle_tpu.observability import metrics as obs
    from .speculative import depth_draft

    failures = []

    def check(cond, what):
        (failures.append(what) if not cond else None)
        print(("  ok  " if cond else "  FAIL") + " " + what)

    params = _make_params(seed=7)
    draft = depth_draft(params, 1)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, _TOY["vocab"], (n,)).astype(np.int32)
               for n in (3, 5, 7, 4, 6, 2)]
    max_new = 14
    ref = _ref_outputs(params, prompts, max_new)

    # 1. parity: depth-pruned draft, f32, reuse on and off
    for reuse in (True, False):
        eng = _engine(params, prefix_reuse=reuse, draft_params=draft,
                      spec_k=3)
        outs = eng.generate_many(prompts, max_new)
        exact = all(np.array_equal(np.asarray(o), r)
                    for o, r in zip(outs, ref))
        check(exact, f"f32 parity vs transformer.generate "
                     f"(reuse={reuse})")
        check(eng._spec.proposed > 0,
              f"speculative rounds actually ran (reuse={reuse})")

    # bf16: spec engine vs plain engine, same cast weights (the plain
    # engine's own bf16 parity vs generate is pinned in test_serving)
    p16 = _bf16(params)
    eng16 = _engine(p16, prefix_reuse=True,
                    draft_params=depth_draft(p16, 1), spec_k=3)
    plain16 = _engine(p16, prefix_reuse=True)
    o16 = eng16.generate_many(prompts, max_new)
    q16 = plain16.generate_many(prompts, max_new)
    check(all(np.array_equal(np.asarray(a), np.asarray(b))
              for a, b in zip(o16, q16)),
          "bf16 parity: speculative == plain engine, bit-exact")

    # 2. self-draft: draft == target must accept nearly everything —
    # the empirical bit-consistency probe of the parallel verify window
    eng_self = _engine(params, prefix_reuse=False, draft_params=params,
                       spec_k=4)
    outs = eng_self.generate_many(prompts, max_new)
    check(all(np.array_equal(np.asarray(o), r)
              for o, r in zip(outs, ref)), "self-draft parity")
    sp = eng_self._spec
    rate = sp.accepted / sp.proposed if sp.proposed else 0.0
    check(rate >= 0.8,
          f"self-draft acceptance ~1 (verify window bit-consistent "
          f"with the sequential step): {rate:.3f}")

    # 3. adversarial draft: a different random init — near-zero
    # agreement, still token-exact, still >= 1 token per round
    adv = depth_draft(_make_params(seed=1234), 1)
    # small blocks so the frontier crosses block boundaries often —
    # the rollback path (scratch blocks returned after rejection) runs
    eng_adv = _engine(params, prefix_reuse=True, draft_params=adv,
                      spec_k=4, block_tokens=4)
    outs = eng_adv.generate_many(prompts, max_new)
    check(all(np.array_equal(np.asarray(o), r)
              for o, r in zip(outs, ref)),
          "adversarial-draft parity (low acceptance, exact output)")
    sp = eng_adv._spec
    adv_rate = sp.accepted / sp.proposed if sp.proposed else 1.0
    check(adv_rate < 0.5,
          f"adversarial draft really is adversarial: {adv_rate:.3f}")

    # 4. zero leak: spec engine retains exactly what the plain engine
    # retains after the same workload (reuse on: the trie's cached
    # chains; reuse off: nothing)
    plain = _engine(params, prefix_reuse=True, block_tokens=4)
    plain.generate_many(prompts, max_new)
    check(eng_adv.kv_pool.blocks_in_use == plain.kv_pool.blocks_in_use,
          f"zero scratch-block leak: spec in_use "
          f"{eng_adv.kv_pool.blocks_in_use} == plain "
          f"{plain.kv_pool.blocks_in_use}")
    eng_off = _engine(params, prefix_reuse=False, draft_params=draft,
                      spec_k=3)
    eng_off.generate_many(prompts, max_new)
    check(eng_off.kv_pool.blocks_in_use == 0,
          "zero blocks in use after serving (reuse off)")

    # spec metrics flow: executables counted before the registry is
    # cleared for the kill-switch probe below
    reg = obs.get_registry()
    check(reg.value("serving.spec_compiles") > 0,
          "serving.spec_compiles counted draft/verify executables")
    check(reg.value("serving.spec_rollback_blocks") > 0,
          "adversarial rejections rolled scratch blocks back")

    # 5. kill switch: PADDLE_TPU_SPEC=0 ignores the draft wholesale
    os.environ["PADDLE_TPU_SPEC"] = "0"
    try:
        obs.get_registry().clear(prefix="serving.")
        eng_k = _engine(params, prefix_reuse=True, draft_params=draft,
                        spec_k=3)
        outs_k = eng_k.generate_many(prompts, max_new)
        check(eng_k._spec is None,
              "kill switch: no speculative state constructed")
        check(all(np.array_equal(np.asarray(o), r)
                  for o, r in zip(outs_k, ref)),
              "kill switch: output bit-exact vs plain greedy")
        snap = eng_k.stats()
        check(not any(k.startswith("serving.spec_") for k in snap),
              "kill switch: no serving.spec_* metrics emitted")
    finally:
        os.environ.pop("PADDLE_TPU_SPEC", None)

    print("spec selftest " + ("FAILED" if failures else "PASSED"))
    return 1 if failures else 0
