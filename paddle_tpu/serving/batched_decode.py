"""Slot-batched KV-cache decode — the pure-JAX compute under
``paddle_tpu.serving``.

The batched cache holds ``max_slots`` independent sequences: tuples of
``n_layer`` ``[S, T, h, dh]`` arrays plus per-slot scalars (``last_tok``
[S] int32, ``pos`` [S] int32).  Slot rows never interact — every op here
is row-wise (matmuls, layer norm, per-slot causal attention, per-row
argmax), so slot ``s`` computes exactly what ``models/transformer.py
generate`` computes at position ``pos[s]`` and greedy decode is
token-identical to the single-stream path (the serving acceptance bar).

Three compiled entry points, built once per engine:

* ``make_decode_chunk`` — ONE executable for the whole engine lifetime:
  a ``lax.scan`` of ``chunk`` batched steps between host syncs, so the
  per-call dispatch+sync cost amortizes over ``chunk`` tokens for every
  active slot at once.
* ``make_prefill`` — one executable PER SHAPE BUCKET (prompt padded to a
  power-of-two length): scans the prompt through the same step math,
  building a fresh ``[T, h, dh]`` cache row, then writes the whole row
  into the batched cache at the target slot.  Compile count is bounded
  by the bucket set, never the request count.

Prefill deliberately reuses the single-token step (a scan over the
bucket) instead of a full-sequence teacher-forced matmul: the scan is
bit-identical to the reference decode (same per-row reduction shapes),
which is what makes the engine's outputs provably equal to running each
request alone.  Steps past the real prompt length process padding and
write garbage K/V at positions >= length — harmless by construction:
decode writes position ``pos`` BEFORE attending (mask ``<= pos``), so a
garbage position is always overwritten before it is ever attended.
"""

import jax
import jax.numpy as jnp

__all__ = ["batched_step_logits", "make_decode_chunk", "make_prefill"]


def _ln(x, scale, bias, eps):
    # statistics in f32 even under bf16 compute (mean/var cancellation) —
    # mirrors transformer.generate's ln exactly
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    xn = ((x32 - mu) / jnp.sqrt(var + eps)).astype(x.dtype)
    return xn * scale + bias


def batched_step_logits(p, tok, t, cache_k, cache_v, n_layer, n_head,
                        d_model, eps=1e-5):
    """One decode step for S independent slots.

    tok [S] int32 current tokens, t [S] int32 per-slot positions,
    cache_k/cache_v tuples of n_layer [S, T, h, dh].  Writes each slot's
    K/V at its own position ``t_s`` (clamped to the cache), attends over
    positions ``<= t_s``, and returns ``(logits [S, vocab] f32, cache_k',
    cache_v')``.
    """
    S = tok.shape[0]
    T = cache_k[0].shape[1]
    dh = d_model // n_head
    rows = jnp.arange(S)
    tw = jnp.clip(t, 0, T - 1)  # overrun slots write in-bounds garbage
    x = p["tok_emb.w"][tok] + p["pos_emb.w.w"][tw]          # [S, d]
    ck_out, cv_out = [], []
    for i in range(n_layer):
        w = lambda nm: p[f"block{i}_{nm}"]
        h = _ln(x, w("ln1.scale"), w("ln1.bias"), eps)
        q = h @ w("att_q.w") + w("att_q.b")
        k = h @ w("att_k.w") + w("att_k.b")
        v = h @ w("att_v.w") + w("att_v.b")
        qh = q.reshape(S, n_head, dh)
        kh = k.reshape(S, n_head, dh)
        vh = v.reshape(S, n_head, dh)
        # per-slot scatter: slot s writes at its own position t_s
        ck = cache_k[i].at[rows, tw].set(kh)
        cv = cache_v[i].at[rows, tw].set(vh)
        ck_out.append(ck)
        cv_out.append(cv)
        s = jnp.einsum("shd,sThd->shT", qh, ck,
                       preferred_element_type=jnp.float32)
        s = s / jnp.sqrt(float(dh))
        mask = jnp.arange(T)[None, None, :] <= t[:, None, None]
        s = jnp.where(mask, s, -1e30)
        a = jax.nn.softmax(s, axis=-1).astype(ck.dtype)
        ctx = jnp.einsum("shT,sThd->shd", a, cv).reshape(S, d_model)
        x = x + ctx @ w("att_out.w") + w("att_out.b")
        h2 = _ln(x, w("ln2.scale"), w("ln2.bias"), eps)
        # exact erf gelu, matching transformer.generate and the gelu op
        ff = jax.nn.gelu(h2 @ w("ffn1.w") + w("ffn1.b"), approximate=False)
        x = x + ff @ w("ffn2.w") + w("ffn2.b")
    x = _ln(x, p["ln_f.scale"], p["ln_f.bias"], eps)
    logits = jnp.matmul(x, p["lm_head.w"],
                        preferred_element_type=jnp.float32)
    return logits, tuple(ck_out), tuple(cv_out)


def make_decode_chunk(n_layer, n_head, d_model, chunk, eps=1e-5,
                      donate=True):
    """Build the batched decode executable: ``chunk`` greedy steps for
    every slot in one device call.

    ``fn(params, cache_k, cache_v, last_tok, pos) -> (cache_k', cache_v',
    last_tok', pos', toks [chunk, S] int32)`` — ``toks[j]`` is the token
    each slot emitted at its ``pos+j``'th position.  The caches and slot
    scalars are donated (updated in place on TPU); callers must replace
    their references with the outputs.
    """

    def decode_chunk(p, cache_k, cache_v, last_tok, pos):
        def body(carry, _):
            ck, cv, tok, t = carry
            logits, ck, cv = batched_step_logits(
                p, tok, t, ck, cv, n_layer, n_head, d_model, eps)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (ck, cv, nxt, t + 1), nxt

        (ck, cv, tok, t), toks = jax.lax.scan(
            body, (cache_k, cache_v, last_tok, pos), None, length=chunk)
        return ck, cv, tok, t, toks

    return jax.jit(decode_chunk,
                   donate_argnums=(1, 2, 3, 4) if donate else ())


def make_prefill(n_layer, n_head, d_model, bucket, max_len, eps=1e-5,
                 donate=True):
    """Build the prefill executable for one prompt-length bucket.

    ``fn(params, cache_k, cache_v, last_tok, pos, slot, prompt [bucket],
    length) -> (cache_k', cache_v', last_tok', pos', first_tok)`` —
    scans the padded prompt through the step math on a fresh zero cache
    row, writes the row into the batched cache at ``slot``, seeds the
    slot's ``last_tok`` with the first generated token (greedy argmax at
    the last real prompt position, ``length - 1``) and ``pos`` with
    ``length``.  ``first_tok`` is also returned as a scalar so the
    scheduler can report TTFT / detect an immediate EOS without pulling
    the whole slot state back.
    """
    dh = d_model // n_head

    def prefill(p, cache_k, cache_v, last_tok, pos, slot, prompt, length):
        dtype = cache_k[0].dtype
        row_k = tuple(jnp.zeros((1, max_len, n_head, dh), dtype)
                      for _ in range(n_layer))
        row_v = tuple(jnp.zeros((1, max_len, n_head, dh), dtype)
                      for _ in range(n_layer))

        def body(carry, t):
            ck, cv = carry
            tok = jax.lax.dynamic_slice_in_dim(prompt, t, 1)  # [1]
            logits, ck, cv = batched_step_logits(
                p, tok, t[None], ck, cv, n_layer, n_head, d_model, eps)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (ck, cv), nxt[0]

        (row_k, row_v), nxts = jax.lax.scan(
            body, (row_k, row_v), jnp.arange(bucket))
        first = jax.lax.dynamic_index_in_dim(nxts, length - 1,
                                             keepdims=False)
        cache_k = tuple(c.at[slot].set(r[0])
                        for c, r in zip(cache_k, row_k))
        cache_v = tuple(c.at[slot].set(r[0])
                        for c, r in zip(cache_v, row_v))
        last_tok = last_tok.at[slot].set(first)
        pos = pos.at[slot].set(length)
        return cache_k, cache_v, last_tok, pos, first

    return jax.jit(prefill, donate_argnums=(1, 2, 3, 4) if donate else ())
