"""Paged slot-batched KV-cache decode — the pure-JAX compute under
``paddle_tpu.serving``.

KV lives in a physical block pool: per layer one
``[num_blocks, block_tokens, h, dh]`` array, and each slot's logical
sequence is a chain of block ids in a per-slot BLOCK TABLE row
(``[max_slots, blocks_per_slot]`` int32, host-managed by
``serving.kvcache``).  Position ``t`` of slot ``s`` lives at
``(table[s, t // B], t % B)``.  Every compiled entry point writes and
gathers THROUGH the table, so:

* identical prompt prefixes can share physical blocks across slots
  (prefix reuse — the table is data, not shape, so sharing costs no
  recompile);
* the compiled-executable count keeps the PR-2 bound — ONE decode chunk
  plus one prefill per SUFFIX-length bucket (``used_buckets + 1``);
* unused table entries point at physical block 0, the trash block:
  overrun steps (a finished slot riding out the chunk, prefill bucket
  padding) write garbage there and nowhere else.

Four compiled entry points, built once per engine:

* ``make_decode_chunk`` — a ``lax.scan`` of ``chunk`` batched steps
  between host syncs; attention consumes the block table DIRECTLY
  through the ``paged_attention`` op class (online softmax block by
  block — the ``[S, T, h, dh]`` gathered view never materializes).
  ``PADDLE_TPU_PAGED_ATTN=0`` restores the ``decode_gather`` +
  dense-softmax spelling bit-exact (the kill switch / oracle path).
* ``make_prefill`` — one executable per SUFFIX bucket: scans the
  non-cached tail of the prompt (``tokens[start:start+length]`` padded
  to the bucket) through the same single-token step math, starting at
  runtime position ``start`` and attending the slot's cached blocks
  through the table.  A request whose prefix is fully cached scans only
  its last prompt token (the logits that seed decode are never cached).
  The optional copy-on-write fork (``cow_src -> cow_dst``) is folded
  into the SAME executable as a leading whole-block copy, so CoW adds
  no executable (``cow_src == cow_dst == 0`` is the no-op spelling —
  trash copied onto trash).
* ``make_verify_window`` — the speculative-decoding verify step
  (``serving.speculative``): ONE teacher-forced forward over a
  ``k + 1``-token window per slot (the slot's committed last token
  followed by its k draft proposals), scoring every window position in
  parallel through the same block-table gather.  The window rides the
  decode executable shape — the table is data — so speculative decode
  adds exactly one executable per engine, never one per ``k``.

Correctness discipline (unchanged from the contiguous engine): every op
is row-wise per slot, each step writes position ``t`` BEFORE attending
with mask ``<= t``, and garbage (trash-block content, bucket padding,
CoW tail beyond the shared span) is either overwritten before it is
ever attended or masked to exactly zero attention weight — so greedy
decode through the paged engine is bit-identical to single-stream
``transformer.generate``, prefix reuse on or off (the serving
acceptance bar, ``tests/test_serving.py`` / ``tests/test_kvcache.py``).
"""

import os

import jax
import jax.numpy as jnp

__all__ = ["paged_step_logits", "make_decode_chunk", "make_prefill",
           "make_verify_window"]


def _paged_attn_on():
    """The ``PADDLE_TPU_PAGED_ATTN`` kill switch (default ON).  Read at
    TRACE time, so an engine built under ``=0`` compiles the
    gather+dense-softmax spelling verbatim — bit-exact with the
    pre-paged-attention engine."""
    return os.environ.get("PADDLE_TPU_PAGED_ATTN", "1").lower() not in (
        "0", "", "false", "off", "no")


def _gather_kv(pool, table):
    """The block-table gather, routed through the kernel registry
    (``decode_gather`` op class, docs/kernels.md): the XLA
    advanced-indexing gather off-TPU, the scalar-prefetch Pallas kernel
    on TPU.  Bit-exact across backends — a gather moves bits.

    Since the ``paged_attention`` op class landed this is the
    KILL-SWITCH / ORACLE spelling, not the fast path: attention
    normally consumes the table directly (``_paged_attention`` below)
    and the ``[S, T, h, dh]`` view this gather materializes exists only
    under ``PADDLE_TPU_PAGED_ATTN=0`` (rollback) and in the reference
    suites that pin the paged kernels' numerics against it."""
    from ..kernels import resolve

    return resolve("decode_gather").impl.call(pool, table)


def _paged_attention(qh, pool_k, pool_v, table, pos):
    """One layer's attention THROUGH the block table: resolve the
    ``paged_attention`` op class (docs/kernels.md) and stream blocks
    with online softmax — ``qh [S, W, h, dh]``, ``pos [S, W]`` →
    ``[S, W, h, dh]``.  The tuned block-iteration geometry and backend
    come from the ``op=paged_attention`` cache entry when one exists
    (cached-mode lookup: a miss never compiles, an unavailable
    persisted backend degrades to auto)."""
    from .. import tune
    from ..kernels import resolve

    T = table.shape[1] * pool_k.shape[1]
    h, dh = qh.shape[-2], qh.shape[-1]
    try:
        cfg = tune.paged_attention_config(T, dh, h, str(qh.dtype)) or {}
    except Exception:  # noqa: BLE001 — tuning must never break decode
        cfg = {}
    try:
        ker = resolve("paged_attention", backend=cfg.get("backend"))
    except Exception:  # noqa: BLE001 — stale persisted backend -> auto
        ker = resolve("paged_attention")
    return ker.impl.call(qh, pool_k, pool_v, table, pos,
                         block_step=cfg.get("block_step"))


def _ln(x, scale, bias, eps):
    # statistics in f32 even under bf16 compute (mean/var cancellation) —
    # mirrors transformer.generate's ln exactly
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    xn = ((x32 - mu) / jnp.sqrt(var + eps)).astype(x.dtype)
    return xn * scale + bias


def paged_step_logits(p, tok, t, pool_k, pool_v, table, n_layer, n_head,
                      d_model, eps=1e-5):
    """One decode step for S independent slots through the block table.

    tok [S] int32 current tokens, t [S] int32 per-slot positions,
    pool_k/pool_v tuples of n_layer [num_blocks, B, h, dh], table
    [S, NB] int32 block ids (logical capacity T = NB * B).  Writes each
    slot's K/V at ``(table[s, t_s // B], t_s % B)`` (clamped — overrun
    slots land in whatever their last table entry maps to, by
    construction the trash block or an already-consumed position),
    attends over the gathered chain masked ``<= t_s``, and returns
    ``(logits [S, vocab] f32, pool_k', pool_v')``.
    """
    S = tok.shape[0]
    NB = table.shape[1]
    B = pool_k[0].shape[1]
    T = NB * B
    dh = d_model // n_head
    rows = jnp.arange(S)
    tw = jnp.clip(t, 0, T - 1)
    blk = table[rows, tw // B]      # [S] physical write block
    off = tw % B
    x = p["tok_emb.w"][tok] + p["pos_emb.w.w"][tw]          # [S, d]
    pk_out, pv_out = [], []
    for i in range(n_layer):
        w = lambda nm: p[f"block{i}_{nm}"]
        h = _ln(x, w("ln1.scale"), w("ln1.bias"), eps)
        q = h @ w("att_q.w") + w("att_q.b")
        k = h @ w("att_k.w") + w("att_k.b")
        v = h @ w("att_v.w") + w("att_v.b")
        qh = q.reshape(S, n_head, dh)
        kh = k.reshape(S, n_head, dh)
        vh = v.reshape(S, n_head, dh)
        # per-slot scatter through the table: slot s writes its own
        # (block, offset); distinct live slots own distinct blocks, so
        # the only possible collision is overrun garbage in the trash
        # block — content nobody ever attends
        pk = pool_k[i].at[blk, off].set(kh)
        pv = pool_v[i].at[blk, off].set(vh)
        pk_out.append(pk)
        pv_out.append(pv)
        if _paged_attn_on():
            # attend THROUGH the table: paged_attention streams blocks
            # with online softmax, the [S, T, h, dh] view never exists
            ctx = _paged_attention(
                qh[:, None], pk, pv, table,
                t[:, None])[:, 0].reshape(S, d_model)
        else:
            # kill-switch spelling (PADDLE_TPU_PAGED_ATTN=0): gather
            # each slot's logical view [S, T, h, dh] through the
            # registry-routed decode_gather kernel, dense softmax —
            # bit-exact with the pre-paged-attention engine
            ck = _gather_kv(pk, table)
            cv = _gather_kv(pv, table)
            s = jnp.einsum("shd,sThd->shT", qh, ck,
                           preferred_element_type=jnp.float32)
            s = s / jnp.sqrt(float(dh))
            mask = jnp.arange(T)[None, None, :] <= t[:, None, None]
            s = jnp.where(mask, s, -1e30)
            a = jax.nn.softmax(s, axis=-1).astype(ck.dtype)
            ctx = jnp.einsum("shT,sThd->shd", a, cv).reshape(S, d_model)
        x = x + ctx @ w("att_out.w") + w("att_out.b")
        h2 = _ln(x, w("ln2.scale"), w("ln2.bias"), eps)
        # exact erf gelu, matching transformer.generate and the gelu op
        ff = jax.nn.gelu(h2 @ w("ffn1.w") + w("ffn1.b"), approximate=False)
        x = x + ff @ w("ffn2.w") + w("ffn2.b")
    x = _ln(x, p["ln_f.scale"], p["ln_f.bias"], eps)
    logits = jnp.matmul(x, p["lm_head.w"],
                        preferred_element_type=jnp.float32)
    return logits, tuple(pk_out), tuple(pv_out)


def make_decode_chunk(n_layer, n_head, d_model, chunk, eps=1e-5,
                      donate=True):
    """Build the batched decode executable: ``chunk`` greedy steps for
    every slot in one device call.

    ``fn(params, pool_k, pool_v, last_tok, pos, table) -> (pool_k',
    pool_v', last_tok', pos', toks [chunk, S] int32)`` — ``toks[j]`` is
    the token each slot emitted at its ``pos+j``'th position.  The pool
    and slot scalars are donated (updated in place on TPU); the table is
    a small host-fed int32 array (data, not donated).  Callers must
    replace their references with the outputs.
    """

    def decode_chunk(p, pool_k, pool_v, last_tok, pos, table):
        def body(carry, _):
            pk, pv, tok, t = carry
            logits, pk, pv = paged_step_logits(
                p, tok, t, pk, pv, table, n_layer, n_head, d_model, eps)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (pk, pv, nxt, t + 1), nxt

        (pk, pv, tok, t), toks = jax.lax.scan(
            body, (pool_k, pool_v, last_tok, pos), None, length=chunk)
        return pk, pv, tok, t, toks

    return jax.jit(decode_chunk,
                   donate_argnums=(1, 2, 3, 4) if donate else ())


def make_verify_window(n_layer, n_head, d_model, k, eps=1e-5,
                       donate=True):
    """Build the speculative VERIFY executable: one teacher-forced
    target forward over a ``W = k + 1``-token window for every slot.

    ``fn(params, pool_k, pool_v, toks [S, W], pos [S], limit [S],
    table [S, NB]) -> (pool_k', pool_v', greedy [S, W] int32)`` —
    ``toks[s] = [last_s, d_1 .. d_k]`` (the committed last token
    followed by the slot's draft proposals), window position ``j``
    lives at logical position ``pos_s + j``, and ``greedy[s, j]`` is
    the target's argmax after consuming ``toks[s, j]`` there — exactly
    the token sequential greedy decode would emit after the prefix
    extended by ``toks[s, :j]``.  Scoring all W positions in ONE
    forward (each attends the cached chain plus the in-window
    positions ``<= pos_s + j``, all written before any gather) is the
    speculative win: the weights are read once for W tokens instead of
    W times.

    ``limit[s]`` is the last logical position slot ``s`` may ever
    legitimately write (``p_len + max_new - 1``; ``-1`` for a dead
    slot): window positions beyond it route their K/V writes to the
    trash block, so a window overhanging the end of a request — or a
    slot killed mid-round — can never scatter into a live block.
    Without this, two window positions clamped to the same table entry
    would race their ``.at[].set`` writes.  Greedy outputs at
    positions past ``limit`` are garbage; the host-side acceptance
    walk never commits them.
    """
    W = k + 1

    def verify(p, pool_k, pool_v, toks, pos, limit, table):
        S = toks.shape[0]
        NB = table.shape[1]
        B = pool_k[0].shape[1]
        T = NB * B
        dh = d_model // n_head
        rows = jnp.arange(S)
        P = pos[:, None] + jnp.arange(W)[None, :]            # [S, W]
        Pw = jnp.clip(P, 0, T - 1)
        writable = P <= limit[:, None]
        blk = jnp.where(writable, table[rows[:, None], Pw // B], 0)
        off = Pw % B
        x = p["tok_emb.w"][toks] + p["pos_emb.w.w"][Pw]      # [S, W, d]
        for i in range(n_layer):
            w = lambda nm: p[f"block{i}_{nm}"]
            h = _ln(x, w("ln1.scale"), w("ln1.bias"), eps)
            q = h @ w("att_q.w") + w("att_q.b")
            kk = h @ w("att_k.w") + w("att_k.b")
            v = h @ w("att_v.w") + w("att_v.b")
            qh = q.reshape(S, W, n_head, dh)
            kh = kk.reshape(S, W, n_head, dh)
            vh = v.reshape(S, W, n_head, dh)
            # all W writes land before the gather below — the same
            # write-before-attend discipline as the sequential step,
            # collapsed into one scatter (distinct live positions,
            # disjoint per-slot blocks, overruns trashed via `limit`)
            pk = pool_k[i].at[blk, off].set(kh)
            pv = pool_v[i].at[blk, off].set(vh)
            pool_k = pool_k[:i] + (pk,) + pool_k[i + 1:]
            pool_v = pool_v[:i] + (pv,) + pool_v[i + 1:]
            if _paged_attn_on():
                # window position j attends <= pos + j — the same
                # causal invariant, enforced per block inside the
                # paged_attention kernel instead of over a gathered view
                ctx = _paged_attention(qh, pk, pv, table, P).reshape(
                    S, W, d_model)
            else:
                ck = _gather_kv(pk, table)                   # [S, T, h, dh]
                cv = _gather_kv(pv, table)
                s = jnp.einsum("swhd,sThd->swhT", qh, ck,
                               preferred_element_type=jnp.float32)
                s = s / jnp.sqrt(float(dh))
                # one causal mask covers the cached chain AND the
                # in-window positions: window slot j attends <= pos + j
                mask = (jnp.arange(T)[None, None, None, :]
                        <= P[:, :, None, None])
                s = jnp.where(mask, s, -1e30)
                a = jax.nn.softmax(s, axis=-1).astype(ck.dtype)
                ctx = jnp.einsum("swhT,sThd->swhd", a, cv).reshape(
                    S, W, d_model)
            x = x + ctx @ w("att_out.w") + w("att_out.b")
            h2 = _ln(x, w("ln2.scale"), w("ln2.bias"), eps)
            ff = jax.nn.gelu(h2 @ w("ffn1.w") + w("ffn1.b"),
                             approximate=False)
            x = x + ff @ w("ffn2.w") + w("ffn2.b")
        x = _ln(x, p["ln_f.scale"], p["ln_f.bias"], eps)
        logits = jnp.matmul(x, p["lm_head.w"],
                            preferred_element_type=jnp.float32)
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return pool_k, pool_v, greedy

    return jax.jit(verify, donate_argnums=(1, 2) if donate else ())


def make_prefill(n_layer, n_head, d_model, bucket, eps=1e-5,
                 donate=True):
    """Build the prefill executable for one SUFFIX-length bucket.

    ``fn(params, pool_k, pool_v, last_tok, pos, slot, table_row [NB],
    toks [bucket], start, length, cow_src, cow_dst) -> (pool_k',
    pool_v', last_tok', pos', first_tok)`` — first copies block
    ``cow_src`` onto ``cow_dst`` whole (the copy-on-write fork; the
    no-fork spelling passes ``0, 0``, trash onto trash), then scans the
    padded prompt SUFFIX through the step math at positions ``start +
    i``, writing K/V through ``table_row`` and attending the slot's
    cached chain (positions ``< start`` were shared from the prefix
    trie and are read, never recomputed).  Seeds the slot's
    ``last_tok`` with the first generated token (greedy argmax at the
    last real prompt position, scan step ``length - 1``) and ``pos``
    with ``start + length``.  ``first_tok`` is also returned as a
    scalar so the scheduler can report TTFT / detect an immediate EOS
    without pulling slot state back.

    Steps past ``length`` process padding and write garbage at
    positions ``>= start + length`` — harmless by construction: each
    step writes BEFORE attending (mask ``<= t``), so the real steps
    never see padding writes, and decode overwrites position ``pos``
    before its first attend.
    """

    def prefill(p, pool_k, pool_v, last_tok, pos, slot, table_row,
                toks, start, length, cow_src, cow_dst):
        # copy-on-write fork: duplicate the whole source block; the
        # shared tokens are the live prefix, the tail is garbage the
        # suffix scan / decode overwrites before ever attending it
        pool_k = tuple(c.at[cow_dst].set(c[cow_src]) for c in pool_k)
        pool_v = tuple(c.at[cow_dst].set(c[cow_src]) for c in pool_v)

        def body(carry, i):
            pk, pv = carry
            tok = jax.lax.dynamic_slice_in_dim(toks, i, 1)  # [1]
            t = (start + i)[None]
            logits, pk, pv = paged_step_logits(
                p, tok, t, pk, pv, table_row[None], n_layer, n_head,
                d_model, eps)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (pk, pv), nxt[0]

        (pool_k, pool_v), nxts = jax.lax.scan(
            body, (pool_k, pool_v), jnp.arange(bucket))
        first = jax.lax.dynamic_index_in_dim(nxts, length - 1,
                                             keepdims=False)
        last_tok = last_tok.at[slot].set(first)
        pos = pos.at[slot].set(start + length)
        return pool_k, pool_v, last_tok, pos, first

    return jax.jit(prefill, donate_argnums=(1, 2, 3, 4) if donate else ())
