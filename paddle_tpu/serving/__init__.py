"""Continuous-batching serving — multi-request decode over a paged,
prefix-shared KV cache with SLO-aware goodput scheduling
(`docs/serving.md`).

``ServingEngine`` keeps one fixed-capacity batched decode step (compiled
once) saturated across many concurrent, variable-length requests: a
slot pool over a PAGED block KV cache (``kvcache``: fixed-size physical
blocks, per-slot block tables, reference-counted prefix reuse with
copy-on-write forks and LRU cache eviction), admission between decode
chunks (continuous batching) ordered by the SLO scheduler
(``scheduler``: least predicted-TTFT slack, e2e-doomed requests shed),
power-of-two shape-bucketed SUFFIX prefill so compile count is bounded
by the bucket set, and full ``serving.*`` telemetry through the
observability registry.
"""

from . import batched_decode, kvcache, scheduler, speculative
from .engine import Request, ServingEngine
from .kvcache import BlockPool, PoolExhausted, PrefixTrie
from .scheduler import (FifoScheduler, SheddedRequest, SloScheduler,
                        TtftPredictor)
from .speculative import depth_draft, spec_enabled

__all__ = [
    "Request", "ServingEngine", "batched_decode", "kvcache", "scheduler",
    "speculative", "depth_draft", "spec_enabled",
    "BlockPool", "PoolExhausted", "PrefixTrie",
    "FifoScheduler", "SheddedRequest", "SloScheduler", "TtftPredictor",
]
