"""Continuous-batching serving — multi-request decode over the flagship
transformer's KV-cache serving path (`docs/serving.md`).

``ServingEngine`` keeps one fixed-capacity batched decode step (compiled
once) saturated across many concurrent, variable-length requests: a slot
pool over the batched KV cache, admission between decode chunks
(continuous batching), power-of-two shape-bucketed prefill so compile
count is bounded by the bucket set, and full ``serving.*`` telemetry
through the observability registry.
"""

from . import batched_decode
from .engine import Request, ServingEngine

__all__ = ["Request", "ServingEngine", "batched_decode"]
