"""Speculative decoding over the paged serving engine — draft-model
propose, single-pass target verify, token-exact rollback
(docs/serving.md "Speculative decoding").

Plain decode emits one token per target forward, so decode wall time is
``max_new`` weight reads per request no matter how predictable the text
is.  Speculative decoding restructures the schedule, not the math:

* **Propose** — a small DRAFT model (the same ``transformer.build``
  family, depth-pruned: identical vocab / d_model / head geometry,
  fewer layers) runs ``k + 1`` cheap greedy steps per slot through the
  existing ``make_decode_chunk`` executable, writing its KV into
  SCRATCH block chains allocated from the same :class:`BlockPool` the
  real chains live in (disjoint block ids inside the same pool arrays —
  the block table is data, so the draft costs one executable, total).
* **Verify** — ONE target forward scores all ``k + 1`` window positions
  per slot (``batched_decode.make_verify_window``): the weights are
  read once for the whole window instead of once per token.  That
  parallel read amortization IS the win; everything else exists to make
  it lossless.
* **Accept** — greedy acceptance walks the longest prefix where the
  draft's proposal equals the target's argmax, then commits one BONUS
  token (the target's argmax at the first divergence).  Token-exactness
  is an induction, not a tolerance: position j's target argmax is
  computed from a prefix that is bit-identical to what sequential
  greedy decode would have consumed — the committed last token plus
  j already-verified proposals — so every committed token equals the
  sequential one, and at least one token commits per round.
* **Roll back** — scratch blocks past the new committed frontier are
  deref'd back to the pool (``serving.spec_rollback_blocks``); the
  rejected draft K/V beyond the frontier is dead data that the
  write-before-attend discipline overwrites before any future gather
  reads it, so rollback is pure host accounting — no device copy.
  Slot finish / death / abort release the whole scratch chain through
  the engine's ``_release_slot`` discipline: zero leaks, pinned by
  ``--spec-selftest`` and the fault-injection regression.

Kill switch: ``PADDLE_TPU_SPEC=0`` (or ``off``/``false``) makes the
engine ignore ``draft_params`` entirely — no validation, no extra pool
blocks, no draft executables — bit-identical to the plain engine.

The draft window ``k`` is a tuned dimension: ``tune.tune_spec_decode``
measures candidates end-to-end and persists the winner under the
workload key ``op=spec_decode`` (docs/autotune.md); the engine consults
the cache when constructed without an explicit ``spec_k``.
"""

import os

import numpy as np

from . import batched_decode as _bd

__all__ = ["DEFAULT_SPEC_K", "spec_enabled", "draft_depth",
           "depth_draft", "validate_draft", "accept_greedy",
           "SpecState"]

# hand-picked default draft window when neither the caller nor the
# tune cache (op=spec_decode) supplies one
DEFAULT_SPEC_K = 4


def spec_enabled():
    """The ``PADDLE_TPU_SPEC`` kill switch: False for ``0`` / ``off`` /
    ``false`` / ``no``; default True.  Read at engine construction —
    off means ``draft_params`` is ignored wholesale and the engine is
    bit-identical to one built without a draft."""
    v = os.environ.get("PADDLE_TPU_SPEC", "1").strip().lower()
    return v not in ("0", "off", "false", "no")


def draft_depth(params):
    """Number of transformer blocks a parameter dict carries (the
    ``block{i}_*`` naming of ``transformer.build``)."""
    depth = 0
    for k in params:
        if k.startswith("block") and "_" in k:
            head = k[len("block"):k.index("_")]
            if head.isdigit():
                depth = max(depth, int(head) + 1)
    return depth


def depth_draft(params, n_layers):
    """A depth-pruned draft from target params: the first ``n_layers``
    transformer blocks plus the shared embeddings / final LN / LM head.
    The cheapest honest draft in the ``transformer.build`` family —
    same vocab, same width, same head geometry by construction — used
    by the selftests and the serving benchmark."""
    if not 1 <= int(n_layers) <= draft_depth(params):
        raise ValueError(
            f"depth_draft: n_layers {n_layers} outside [1, "
            f"{draft_depth(params)}]")
    out = {}
    for k, v in params.items():
        if k.startswith("block") and "_" in k:
            head = k[len("block"):k.index("_")]
            if head.isdigit() and int(head) >= int(n_layers):
                continue
        out[k] = v
    return out


def validate_draft(params, draft_params, n_layer, n_head, d_model,
                   max_len, draft_n_layer=None, draft_n_head=None):
    """Geometry checks at engine construction — the draft shares the
    target's paged pool arrays and tokenizer, so mismatches must fail
    LOUDLY here, not as silent garbage tokens at serve time.  Returns
    the validated ``draft_n_layer``."""
    t_vocab = int(np.asarray(params["tok_emb.w"]).shape[0])
    d_vocab = int(np.asarray(draft_params["tok_emb.w"]).shape[0])
    if t_vocab != d_vocab:
        raise ValueError(
            f"speculative draft/target vocab mismatch: draft tok_emb "
            f"has {d_vocab} entries, target has {t_vocab} — the models "
            f"must share one tokenizer for acceptance to compare tokens")
    d_head_vocab = int(np.asarray(draft_params["lm_head.w"]).shape[1])
    if d_head_vocab != t_vocab:
        raise ValueError(
            f"speculative draft lm_head emits {d_head_vocab} logits, "
            f"target vocab is {t_vocab} — shared tokenizer required")
    d_width = int(np.asarray(draft_params["tok_emb.w"]).shape[1])
    if d_width != d_model:
        raise ValueError(
            f"speculative draft d_model {d_width} != target d_model "
            f"{d_model}: the draft writes its K/V into the target's "
            f"paged pool arrays, so the widths must match")
    dnh = n_head if draft_n_head is None else int(draft_n_head)
    if dnh != n_head:
        raise ValueError(
            f"speculative draft d_head {d_model // dnh} (n_head {dnh}) "
            f"!= target d_head {d_model // n_head} (n_head {n_head}): "
            f"the shared pool block shape is [B, n_head, d_head]")
    depth = draft_depth(draft_params)
    dnl = depth if draft_n_layer is None else int(draft_n_layer)
    if not 1 <= dnl <= depth:
        raise ValueError(
            f"speculative draft_n_layer {dnl} outside [1, {depth}] "
            f"(layers present in draft_params)")
    if dnl > n_layer:
        raise ValueError(
            f"speculative draft has {dnl} layers, target has {n_layer}: "
            f"the draft rides the first {n_layer} pool arrays, so it "
            f"cannot be deeper than the target")
    d_pos = int(np.asarray(draft_params["pos_emb.w.w"]).shape[0])
    if max_len > d_pos:
        raise ValueError(
            f"max_len {max_len} exceeds the draft's position-embedding "
            f"table ({d_pos} positions)")
    return dnl


def accept_greedy(drafts, target_greedy, max_commit):
    """The acceptance walk for one slot: ``drafts`` are the k proposed
    tokens, ``target_greedy`` the target's k+1 window argmaxes
    (``target_greedy[j]`` = greedy token after the prefix extended by
    ``drafts[:j]``).  Returns the committed tokens — the longest
    agreeing prefix plus the bonus token at the divergence — capped at
    ``max_commit``.  Returns ``(tokens, n_matched)`` — the committed
    tokens and how many draft proposals they contain.  Every returned
    token is bit-equal to what sequential greedy decode would emit
    (the induction in the module docstring), and at least one
    commits."""
    n = 0
    while (n < len(drafts) and n + 1 < max_commit
           and int(drafts[n]) == int(target_greedy[n])):
        n += 1
    commit = [int(t) for t in target_greedy[:n + 1]][:max_commit]
    return commit, min(n, len(commit))


class SpecState:
    """Per-engine speculative state: draft params on device, the draft
    scratch block table + chains, and the draft executables (one
    prefill per suffix bucket, one k+1-step propose chunk).  All block
    accounting flows through the engine's :class:`BlockPool`; the
    engine's ``_release_slot`` / ``_abort`` call :meth:`release` so the
    scratch chains obey the same zero-leak discipline as real chains."""

    def __init__(self, engine, draft_params, draft_n_layer, k):
        import jax
        import jax.numpy as jnp

        if int(k) < 1:
            raise ValueError(f"spec_k must be >= 1: {k}")
        self.k = int(k)
        self.n_layer = int(draft_n_layer)
        self.p = jax.device_put(
            {kk: jnp.asarray(v, engine.compute_dtype)
             for kk, v in draft_params.items()})
        self.table = np.zeros((engine.max_slots, engine.blocks_per_slot),
                              np.int32)
        self.chains = [None] * engine.max_slots
        self._prefill_fns = {}
        self._chunk_fn = None
        self._verify_fn = None
        # cumulative accept accounting for the serving.spec_accept_rate
        # gauge (reset with the goodput window)
        self.proposed = 0
        self.accepted = 0

    # -- executables ------------------------------------------------------
    def _compile_counter(self, engine):
        return engine._reg.counter(
            "serving.spec_compiles",
            help="speculative executables built (draft prefill buckets "
                 "+ draft chunk + verify window)")

    def chunk_fn(self, engine):
        """The draft PROPOSE executable: ``k + 1`` greedy draft steps
        (the extra step writes the k-th proposal's K/V, so a fully
        accepted round leaves the draft cache current)."""
        if self._chunk_fn is None:
            self._chunk_fn = engine._aot_with_mem_telemetry(
                _bd.make_decode_chunk(
                    self.n_layer, engine.n_head, engine.d_model,
                    self.k + 1, eps=engine._eps, donate=engine._donate),
                label="spec_draft")
            self._compile_counter(engine).inc()
        return self._chunk_fn

    def verify_fn(self, engine):
        if self._verify_fn is None:
            self._verify_fn = engine._aot_with_mem_telemetry(
                _bd.make_verify_window(
                    engine.n_layer, engine.n_head, engine.d_model,
                    self.k, eps=engine._eps, donate=engine._donate),
                label="spec_verify")
            self._compile_counter(engine).inc()
        return self._verify_fn

    def prefill_fn(self, engine, bucket):
        fn = self._prefill_fns.get(bucket)
        if fn is None:
            fn = engine._aot_with_mem_telemetry(
                _bd.make_prefill(self.n_layer, engine.n_head,
                                 engine.d_model, bucket, eps=engine._eps,
                                 donate=engine._donate),
                label=f"spec_prefill_{bucket}")
            self._prefill_fns[bucket] = fn
            self._compile_counter(engine).inc()
        return fn

    # -- scratch-chain accounting -----------------------------------------
    def ensure_chain(self, engine, slot, n_blocks):
        """Extend slot's scratch chain to ``n_blocks`` blocks (LRU-
        evicting cached prefix chains under pressure, like admission).
        The pool is sized so drafts always fit once trie-only chains
        are evicted."""
        chain = self.chains[slot] or []
        need = n_blocks - len(chain)
        if need <= 0:
            return
        pool, trie = engine.kv_pool, engine.prefix_trie
        if need > pool.free_blocks and trie is not None:
            trie.evict_lru(need - pool.free_blocks)
        fresh = pool.alloc(need)
        chain.extend(fresh)
        self.chains[slot] = chain
        self.table[slot, :len(chain)] = chain
        engine._reg.gauge("serving.blocks_in_use").set(
            pool.blocks_in_use)

    def rollback(self, engine, slot, keep_blocks):
        """Return scratch blocks past the committed frontier to the
        pool.  The draft K/V they held was computed from REJECTED
        tokens — dead data; the next round re-proposes from the
        committed frontier and rewrites every position it attends, so
        dropping the blocks is the entire rollback."""
        chain = self.chains[slot]
        if chain is None or len(chain) <= keep_blocks:
            return 0
        dropped = chain[keep_blocks:]
        del chain[keep_blocks:]
        for b in dropped:
            engine.kv_pool.deref(b)
        self.table[slot, len(chain):] = 0
        engine._reg.counter(
            "serving.spec_rollback_blocks",
            help="draft scratch blocks rolled back to the pool after "
                 "rejection").inc(len(dropped))
        return len(dropped)

    def release(self, engine, slot):
        """Drop slot's whole scratch chain — the ``_release_slot``
        discipline (finish, injected death, abort all land here)."""
        for b in self.chains[slot] or ():
            engine.kv_pool.deref(b)
        self.chains[slot] = None
        self.table[slot] = 0

    # -- draft forward passes ---------------------------------------------
    def prefill(self, engine, slot, req):
        """Run the draft over the full prompt into the scratch chain so
        the first propose round has a complete draft KV.  No prefix
        reuse on the draft side — scratch chains are private by
        definition.  The draft's own first-token prediction is
        discarded: the committed sequence is the TARGET's."""
        import jax.numpy as jnp

        p_len = req.prompt.shape[0]
        self.ensure_chain(engine, slot,
                          -(-p_len // engine.block_tokens))
        bucket = engine.bucket_for(p_len)
        padded = np.zeros(bucket, np.int32)
        padded[:p_len] = req.prompt
        fn = self.prefill_fn(engine, bucket)
        # the draft touches only the first draft_n_layer pool arrays;
        # the target's deeper layers pass around the call untouched.
        # last/pos are donated scratch in spec mode (the round rebuilds
        # both from host mirrors); the draft's writes to them are noise
        nl = self.n_layer
        (pk, pv, engine._last, engine._pos,
         _first) = fn(self.p, engine._pk[:nl], engine._pv[:nl],
                      engine._last, engine._pos, np.int32(slot),
                      jnp.asarray(self.table[slot]), jnp.asarray(padded),
                      np.int32(0), np.int32(p_len), np.int32(0),
                      np.int32(0))
        engine._pk = tuple(pk) + engine._pk[nl:]
        engine._pv = tuple(pv) + engine._pv[nl:]

    def propose(self, engine, last_h, pos_h):
        """One draft chunk: ``k + 1`` greedy steps per slot from the
        committed frontier.  Returns the proposals ``[k, S]`` (step j's
        output is the j+1'th draft token; the final step only exists to
        write the k-th proposal's K/V)."""
        import jax.numpy as jnp

        fn = self.chunk_fn(engine)
        nl = self.n_layer
        (pk, pv, engine._last, engine._pos,
         toks) = fn(self.p, engine._pk[:nl], engine._pv[:nl],
                    jnp.asarray(last_h), jnp.asarray(pos_h),
                    jnp.asarray(self.table))
        engine._pk = tuple(pk) + engine._pk[nl:]
        engine._pv = tuple(pv) + engine._pv[nl:]
        return np.asarray(toks)[:self.k]
