"""v1 compat — the long tail of the trainer_config_helpers surface.

Covers (reference: python/paddle/trainer_config_helpers/):
- layers.py: projections + operators for mixed_layer, recurrent_group +
  memory + step layers, and the remaining `*_layer` functions;
- activations.py / attrs.py / poolings.py: the full class lists;
- optimizers.py: the remaining optimizer classes;
- evaluators.py: evaluator constructors mapped to the in-program metric
  ops / host-side evaluator classes;
- networks.py: composed networks mapped to paddle_tpu.nets.

Everything here returns Program Variables (the repo-wide v1 divergence:
no proto LayerOutput pipeline).  Names whose reference semantics require
the v1 generation driver (beam_search over recurrent_group) raise with a
pointer to the native carrier; they are triaged in PARITY.md.
"""

import numpy as np

from .. import layers, nets as _nets, optimizer as _opt, evaluator as _eval
from ..layers import tensor as _tensor
from ..layers.layer_helper import LayerHelper
from ..core import unique_name
from . import v1 as _v1

__all__ = [
    # enums / support classes
    "LayerOutput", "LayerType", "AggregateLevel", "ExpandLevel",
    "layer_support", "StaticInput", "SubsequenceInput", "BaseGeneratedInput",
    "GeneratedInput", "BeamInput",
    # projections / operators
    "full_matrix_projection", "trans_full_matrix_projection",
    "table_projection", "identity_projection", "dotmul_projection",
    "scaling_projection", "context_projection", "conv_projection",
    "slice_projection", "dotmul_operator", "conv_operator", "mixed_layer",
    # recurrence
    "recurrent_group", "memory", "recurrent_layer", "lstm_step_layer",
    "gru_step_layer", "gru_step_naive_layer", "get_output_layer",
    "beam_search", "eos_layer", "maxid_layer", "sampling_id_layer",
    # remaining layers
    "repeat_layer", "seq_reshape_layer", "seq_concat_layer",
    "seq_slice_layer", "sub_seq_layer", "expand_layer",
    "l2_distance_layer", "power_layer", "interpolation_layer",
    "bilinear_interp_layer", "sum_to_one_norm_layer", "row_l2_norm_layer",
    "conv_shift_layer", "tensor_layer", "selective_fc_layer",
    "linear_comb_layer", "convex_comb_layer", "dot_prod_layer",
    "out_prod_layer", "print_layer", "printer_layer", "priorbox_layer",
    "cross_channel_norm_layer", "multibox_loss_layer",
    "detection_output_layer", "roi_pool_layer", "spp_layer", "pad_layer",
    "multiplex_layer", "row_conv_layer", "prelu_layer",
    "switch_order_layer", "gated_unit_layer", "crop_layer", "clip_layer",
    "kmax_seq_score_layer", "img_pool3d_layer", "img_conv3d_layer",
    "scale_shift_layer", "resize_layer", "scale_sub_region_layer",
    "factorization_machine", "maxout_layer", "block_expand_layer",
    "huber_classification_cost", "sub_nested_seq_layer",
    "cross_entropy_over_beam",
    # activations (completing the 18)
    "BaseActivation", "SequenceSoftmaxActivation", "SqrtActivation",
    "ReciprocalActivation", "SoftSignActivation",
    # attrs
    "HookAttr", "ParamAttr", "ExtraAttr", "ParameterAttribute",
    "ExtraLayerAttribute",
    # poolings (completing the 9)
    "BasePoolingType", "MaxWithMaskPooling", "CudnnMaxPooling",
    "CudnnAvgPooling", "CudnnAvgInclPadPooling", "SquareRootNPooling",
    # optimizers (completing the 13)
    "Optimizer", "BaseSGDOptimizer", "AdamaxOptimizer",
    "DecayedAdaGradOptimizer", "BaseRegularization", "ModelAverage",
    # evaluators (16)
    "evaluator_base", "classification_error_evaluator", "auc_evaluator",
    "pnpair_evaluator", "precision_recall_evaluator", "ctc_error_evaluator",
    "chunk_evaluator", "sum_evaluator", "column_sum_evaluator",
    "value_printer_evaluator", "gradient_printer_evaluator",
    "maxid_printer_evaluator", "maxframe_printer_evaluator",
    "seqtext_printer_evaluator", "classification_error_printer_evaluator",
    "detection_map_evaluator",
    # networks
    "sequence_conv_pool", "simple_img_conv_pool", "img_conv_bn_pool",
    "img_conv_group", "img_separable_conv", "lstmemory_group",
    "lstmemory_unit", "gru_group", "gru_unit", "simple_gru2",
    "bidirectional_gru", "bidirectional_lstm", "text_conv_pool",
    "simple_attention", "dot_product_attention", "multi_head_attention",
    "vgg_16_network", "small_vgg",
]


# --------------------------------------------------------- support classes
class LayerOutput:
    """In this rebuild layer functions return Program Variables directly;
    LayerOutput is kept as the nominal type for isinstance checks in
    ported configs (reference layers.py LayerOutput)."""

    def __new__(cls, *a, **k):
        raise TypeError(
            "LayerOutput is not constructed directly here — layer "
            "functions return Program Variables")


class LayerType:
    """Name constants (reference layers.py LayerType) — retained for
    config compatibility; the Program records op types instead."""
    DATA = "data"
    FC = "fc"
    CONV = "conv"
    POOL = "pool"
    BATCH_NORM = "batch_norm"
    LSTMEMORY = "lstmemory"
    GRUMEMORY = "grumemory"
    COST = "cost"

    @staticmethod
    def is_layer_type(_t):
        return True


class AggregateLevel:
    TO_NO_SEQUENCE = 0
    TO_SEQUENCE = 1
    EACH_TIMESTEP = 0
    EACH_SEQUENCE = 1


class ExpandLevel:
    FROM_NO_SEQUENCE = 0
    FROM_TIMESTEP = 0
    FROM_SEQUENCE = 1


def layer_support(*attrs):
    """Reference decorator validating ExtraLayerAttribute support — a
    no-op here (attributes map to jit-compiled behavior directly)."""
    def deco(fn):
        return fn
    return deco


class StaticInput:
    """Non-scanned input to recurrent_group: visible unsliced inside the
    step (reference StaticInput; carried by scan_block's closure env)."""

    def __init__(self, input, is_seq=False, size=None):
        self.input = input
        self.is_seq = is_seq
        self.size = size


class SubsequenceInput:
    """Nested (2-level LoD) sequence input to recurrent_group: the outer
    group iterates SUB-sequences — each step sees a 1-level padded
    sequence [b, t, d] whose ``@LENGTH`` is that sub-sequence's lengths
    (reference recurrent_group over subSequenceStartPositions,
    ``RecurrentGradientMachine`` nested expansion; nested configs
    ``gserver/tests/sequence_nest_rnn.conf``)."""

    def __init__(self, input):
        if getattr(input, "lod_level", 0) < 2:
            raise ValueError(
                "SubsequenceInput needs a nested (lod_level=2) sequence "
                "variable [b, s, t, ...]; declare it with "
                "layers.data(..., lod_level=2)")
        self.input = input


class BaseGeneratedInput:
    pass


class GeneratedInput(BaseGeneratedInput):
    """Generation-mode input to ``beam_search``: at each decode step the
    step function receives the EMBEDDING of the token each beam selected
    last step (reference ``trainer_config_helpers`` GeneratedInput +
    ``RecurrentGradientMachine.h:307-309`` generateSequence/beamSearch).
    ``embedding_name`` shares the trained token-embedding parameter."""

    def __init__(self, size, embedding_name=None, embedding_size=None,
                 **_):
        if not embedding_size:
            raise ValueError("GeneratedInput needs embedding_size")
        self.size = size
        self.embedding_name = embedding_name
        self.embedding_size = embedding_size


class BeamInput:
    """One beam expansion for cross_entropy_over_beam (reference
    ``trainer_config_helpers/layers.py:6362``): the candidate scores of
    every surviving prefix, the selected top-k candidate ids (-1
    padded; e.g. ``kmax_seq_score_layer`` output), and the gold id."""

    def __init__(self, candidate_scores=None, selected_candidates=None,
                 gold=None, **_):
        if candidate_scores is None or selected_candidates is None \
                or gold is None:
            raise ValueError(
                "BeamInput needs candidate_scores, selected_candidates "
                "and gold")
        self.candidate_scores = candidate_scores
        self.selected_candidates = selected_candidates
        self.gold = gold


# ------------------------------------------------------------- projections
class _Projection:
    def __init__(self, kind, input, **kw):
        self.kind = kind
        self.input = input
        self.kw = kw


def full_matrix_projection(input, size=0, param_attr=None, **_):
    return _Projection("full_matrix", input, size=size,
                       param_attr=param_attr)


def trans_full_matrix_projection(input, size=0, param_attr=None, **_):
    return _Projection("trans_full_matrix", input, size=size,
                       param_attr=param_attr)


def table_projection(input, size=0, param_attr=None, **_):
    return _Projection("table", input, size=size, param_attr=param_attr)


def identity_projection(input, offset=None, size=None, **_):
    return _Projection("identity", input, offset=offset, size=size)


def dotmul_projection(input, param_attr=None, **_):
    return _Projection("dotmul", input, param_attr=param_attr)


def scaling_projection(input, param_attr=None, **_):
    return _Projection("scaling", input, param_attr=param_attr)


def context_projection(input, context_len, context_start=None, **_):
    return _Projection("context", input, context_len=context_len,
                       context_start=context_start)


def conv_projection(input, filter_size, num_filters, num_channels=None,
                    stride=1, padding=0, param_attr=None, **_):
    return _Projection("conv", input, filter_size=filter_size,
                       num_filters=num_filters, stride=stride,
                       padding=padding, param_attr=param_attr)


def slice_projection(input, slices, **_):
    return _Projection("slice", input, slices=slices)


def dotmul_operator(a=None, b=None, scale=1.0, **_):
    return _Projection("dotmul_op", a, b=b, scale=scale)


def conv_operator(img=None, filter=None, filter_size=0, num_filters=0,
                  num_channels=None, stride=1, padding=0, **_):
    return _Projection("conv_op", img, filter=filter,
                       filter_size=filter_size, num_filters=num_filters,
                       stride=stride, padding=padding)


def _eval_projection(proj, size):
    """Lower one projection/operator to a Variable (mixed_layer's body)."""
    x = proj.input
    kw = proj.kw
    if proj.kind in ("full_matrix", "trans_full_matrix"):
        out_size = kw["size"] or size
        helper = LayerHelper("proj", name=None)
        in_dim = int(np.prod(x.shape[1:]))
        shape = ([out_size, in_dim] if proj.kind == "trans_full_matrix"
                 else [in_dim, out_size])
        w = helper.create_parameter(kw.get("param_attr"), shape=shape,
                                    dtype=x.dtype)
        return layers.matmul(x, w,
                             transpose_y=proj.kind == "trans_full_matrix")
    if proj.kind == "table":
        return layers.embedding(
            x, size=[_v1._vocab_of(x), kw["size"] or size],
            param_attr=kw.get("param_attr"))
    if proj.kind == "identity":
        if kw.get("offset") is None:
            return x
        off = kw["offset"]
        sz = kw.get("size") or size
        return _tensor.crop(x, shape=[-1, sz], offsets=[0, off])
    if proj.kind == "dotmul":
        helper = LayerHelper("dotmul_proj")
        w = helper.create_parameter(kw.get("param_attr"),
                                    shape=[x.shape[-1]], dtype=x.dtype)
        return layers.elementwise_mul(x, w)
    if proj.kind == "scaling":
        helper = LayerHelper("scaling_proj")
        w = helper.create_parameter(kw.get("param_attr"), shape=[1],
                                    dtype=x.dtype)
        return layers.elementwise_mul(x, w)
    if proj.kind == "context":
        return _context_window(x, kw["context_len"],
                               kw.get("context_start"))
    if proj.kind == "conv":
        return layers.conv2d(
            x, num_filters=kw["num_filters"],
            filter_size=kw["filter_size"], stride=kw["stride"],
            padding=kw["padding"], param_attr=kw.get("param_attr"),
            bias_attr=False)
    if proj.kind == "slice":
        parts = [
            _tensor.crop(x, shape=[-1, e - s], offsets=[0, s])
            for s, e in kw["slices"]
        ]
        return parts[0] if len(parts) == 1 else _tensor.concat(parts, axis=1)
    if proj.kind == "dotmul_op":
        return layers.scale(layers.elementwise_mul(x, kw["b"]),
                            scale=kw["scale"])
    if proj.kind == "conv_op":
        return layers.conv2d(
            x, num_filters=kw["num_filters"],
            filter_size=kw["filter_size"], stride=kw["stride"],
            padding=kw["padding"], bias_attr=False)
    raise ValueError(f"unknown projection {proj.kind}")


def _context_window(x, context_len, context_start=None):
    """Sliding context concat over the time axis (reference
    context_projection): [b, t, d] -> [b, t, context_len*d], zero-padded
    at the borders."""
    start = (-(context_len // 2)) if context_start is None else context_start
    shifted = [_shift_time(x, start + k) for k in range(context_len)]
    return _tensor.concat(shifted, axis=2)


def _shift_time(x, off):
    """x [b, t, ...] shifted by `off` timesteps (positive = look ahead),
    zero-filled."""
    t = x.shape[1]
    rest = list(x.shape[2:])
    if off == 0:
        return x
    if off > 0:
        body = _tensor.crop(x, shape=[-1, t - off] + rest,
                            offsets=[0, off] + [0] * len(rest))
        return _tensor.pad(body,
                           paddings=[0, 0, 0, off] + [0, 0] * len(rest))
    off = -off
    body = _tensor.crop(x, shape=[-1, t - off] + rest,
                        offsets=[0, 0] + [0] * len(rest))
    return _tensor.pad(body, paddings=[0, 0, off, 0] + [0, 0] * len(rest))


def mixed_layer(size=0, input=None, act=None, bias_attr=None, name=None,
                **_):
    """mixed_layer over projections/operators: evaluate each input and
    sum (reference MixedLayerType; += syntax folds to the input list)."""
    ins = input if isinstance(input, (list, tuple)) else [input]
    vals = []
    for p in ins:
        vals.append(_eval_projection(p, size)
                    if isinstance(p, _Projection) else p)
    out = vals[0]
    for v in vals[1:]:
        out = layers.elementwise_add(out, v)
    if bias_attr is not False:
        helper = LayerHelper("mixed", bias_attr=bias_attr)
        out = helper.append_bias_op(out, dim_start=len(out.shape) - 1)
    out = _v1._apply_act(out, _v1._act(act))
    _register_name(out, name)
    return out


# ------------------------------------------------------ recurrent machinery
_RNN_STACK = []


class _V1RnnCtx:
    def __init__(self, rnn, parent_block, sample_outer):
        self.rnn = rnn
        self.parent_block = parent_block
        self.sample_outer = sample_outer  # an outer seq var (batch ref)
        self.mems = []   # (mem_var, name)
        self.named = {}  # layer name -> var (registered inside the step)


def _register_name(var, name):
    if name and _RNN_STACK:
        _RNN_STACK[-1].named[name] = var
    return var


def memory(name=None, size=None, boot_layer=None, is_seq=False, **_):
    """v1 memory(): the loop-carried state, linked by NAME to the step
    layer that produces its next value (reference layers.py memory)."""
    if not _RNN_STACK:
        raise RuntimeError("memory() is only valid inside recurrent_group")
    ctx = _RNN_STACK[-1]
    if boot_layer is not None:
        init = boot_layer
        k = getattr(ctx, "beam_k", None)
        if k:
            # generation mode: the decode loop runs at the flattened
            # [b*k] beam batch, so a boot from encoder state [b, ...]
            # must expand to the beams like StaticInput contexts do
            ex = ctx.parent_block.create_var(
                name=unique_name.generate("beam_boot"),
                dtype=init.dtype,
                shape=[init.shape[0]] + list(init.shape[1:]))
            ctx.parent_block.append_op(
                type="beam_expand", inputs={"X": [init.name]},
                outputs={"Out": [ex.name]}, attrs={"beam_size": k})
            init = ex
    else:
        # zeros [batch, size] built in the PARENT block (the sub-block
        # cannot initialize its own carry)
        init = ctx.parent_block.create_var(
            name=unique_name.generate("rnn_boot"),
            dtype="float32", shape=[ctx.sample_outer.shape[0], size])
        ctx.parent_block.append_op(
            type="fill_constant_batch_size_like",
            inputs={"Input": [ctx.sample_outer.name]},
            outputs={"Out": [init.name]},
            attrs={"shape": (1, size), "dtype": "float32", "value": 0.0,
                   "input_dim_idx": 0, "output_dim_idx": 0},
        )
    mem = ctx.rnn.memory(init)
    ctx.mems.append((mem, name))
    return mem


def recurrent_group(step, input, reverse=False, name=None, **_):
    """Run `step` over each timestep of the sequence inputs (reference
    layers.py recurrent_group -> the scan_block op).  StaticInput wrappers
    pass through unsliced; memories link to same-named step layers.

    ``reverse=True`` (reference ``layers.py:347``): the step visits the
    sequence last-to-first and the outputs come back aligned with the
    INPUT order.  Implemented as length-aware rotation — reverse each
    sequence input (padding stays right-aligned so the group's
    padded-steps-don't-advance-memories masking is untouched), scan
    forward, reverse the outputs back."""
    from ..layers import control_flow as cf

    ins = input if isinstance(input, (list, tuple)) else [input]
    if reverse:
        def _rev(i):
            if isinstance(i, StaticInput):
                return i
            if isinstance(i, SubsequenceInput):
                return SubsequenceInput(layers.sequence_reverse(i.input))
            return layers.sequence_reverse(i)

        ins = [_rev(i) for i in ins]
    seq_ins = [i for i in ins if not isinstance(i, StaticInput)]
    if not seq_ins:
        raise ValueError("recurrent_group needs at least one sequence input")
    rnn = cf.StaticRNN(name=name)
    prog = rnn.helper.main_program
    parent = prog.current_block()
    first = seq_ins[0].input if isinstance(seq_ins[0], SubsequenceInput) \
        else seq_ins[0]
    ctx = _V1RnnCtx(rnn, parent, first)
    _RNN_STACK.append(ctx)
    try:
        with rnn.step():
            step_args = []
            for i in ins:
                if isinstance(i, StaticInput):
                    step_args.append(i.input)  # closure env: unsliced
                elif isinstance(i, SubsequenceInput):
                    # outer iteration over SUB-sequences: the slice
                    # [b, t, d] is itself a 1-level sequence whose
                    # lengths are this step's slice of @SUBLENGTH
                    x = i.input
                    inner = rnn.step_input(x)
                    inner_len = rnn.step_input(x.sub_length_var())
                    inner.lod_level = 1
                    rnn._sub.vars[inner.name + "@LENGTH"] = inner_len
                    step_args.append(inner)
                else:
                    step_args.append(rnn.step_input(i))
            # LoD semantics: padded steps don't advance memories
            if getattr(first, "lod_level", 0) > 0:
                rnn.set_sequence_length(first.length_var())
            outs = step(*step_args)
            outs = outs if isinstance(outs, (list, tuple)) else [outs]
            for mem, mname in ctx.mems:
                target = ctx.named.get(mname)
                if target is None and len(outs) == 1 and len(ctx.mems) == 1:
                    target = outs[0]  # single-memory convention
                if target is None:
                    raise ValueError(
                        f"memory(name={mname!r}) has no same-named step "
                        f"layer; give the producing layer name={mname!r}")
                rnn.update_memory(mem, target)
            for o in outs:
                rnn.step_output(o)
    finally:
        _RNN_STACK.pop()
    result = rnn()
    if getattr(first, "lod_level", 0) > 0:
        # outputs are sequences over the scanned input's lengths (outer
        # lengths for a nested group), so last_seq & friends index the
        # true last step, not the padded one
        out_len = first.length_var()
        for o in (result if isinstance(result, list) else [result]):
            o.lod_level = 1
            o.block.vars[o.name + "@LENGTH"] = out_len
    if reverse:
        # un-rotate so output position t corresponds to input position t
        if isinstance(result, list):
            result = [layers.sequence_reverse(o) for o in result]
        else:
            result = layers.sequence_reverse(result)
    return result


def recurrent_layer(input, act=None, bias_attr=None, param_attr=None,
                    name=None, **_):
    """Elman recurrence out_t = act(in_t + W out_{t-1}) (reference
    RecurrentLayer.cpp)."""
    size = input.shape[-1]

    def step(x_t):
        mem = memory(name="__rec_state", size=size)
        helper = LayerHelper("recurrent")
        w = helper.create_parameter(param_attr, shape=[size, size],
                                    dtype=input.dtype)
        nxt = layers.elementwise_add(x_t, layers.matmul(mem, w))
        nxt = _v1._apply_act(nxt, _v1._act(act, "tanh"))
        _register_name(nxt, "__rec_state")
        return nxt

    out = recurrent_group(step, input, name=name)
    return out


def lstm_step_layer(input, state, size, act=None, gate_act=None,
                    state_act=None, name=None, **_):
    """One LSTM step inside recurrent_group (reference LstmStepLayer):
    ``input`` is already projected to [b, 4*size]; ``state`` is the cell.
    Pure gate math — the recurrent projection lives in the group's
    mixed_layer, exactly the v1 contract.  The new cell is the auxiliary
    'state' output (get_output_layer)."""
    i, f, c_hat, o = _tensor.split(input, 4, dim=1)
    i = layers.sigmoid(i)
    f = layers.sigmoid(f)
    o = layers.sigmoid(o)
    c_hat = layers.tanh(c_hat)
    new_cell = layers.elementwise_add(
        layers.elementwise_mul(f, state),
        layers.elementwise_mul(i, c_hat))
    hidden = layers.elementwise_mul(o, layers.tanh(new_cell))
    hidden._v1_outputs = {"state": new_cell}
    _register_name(hidden, name)
    return hidden


def gru_step_layer(input, output_mem, size=None, act=None, gate_act=None,
                   name=None, **_):
    d = size or output_mem.shape[-1]
    out = layers.gru_unit(input, output_mem, size=3 * d)
    _register_name(out, name)
    return out


gru_step_naive_layer = gru_step_layer


def get_output_layer(input, arg_name, **_):
    """Select a named auxiliary output of a layer (reference
    GetOutputLayer — e.g. lstm 'state').  Layers here stash auxiliaries
    on the Variable (`_v1_outputs`)."""
    outs = getattr(input, "_v1_outputs", None)
    if outs and arg_name in outs:
        return outs[arg_name]
    raise ValueError(
        f"layer has no auxiliary output {arg_name!r}; available: "
        f"{sorted(outs) if outs else '(none)'}")


def beam_search(step, input, bos_id, eos_id, beam_size, max_length=100,
                name=None, **_):
    """The v1 GENERATION DRIVER: beam search over a recurrent step
    function (reference ``RecurrentGradientMachine.h:307-309``
    generateSequence/beamSearch — per-token dynamic net expansion with
    beam maintenance, exposed via ``api/SequenceGenerator.cpp``).

    TPU-native lowering: a fixed-length ``StaticRNN`` decode loop whose
    carried state is (current beam tokens [b, k], accumulated scores
    [b, k], the user step's memories).  Each tick embeds the beams' last
    tokens (the ``GeneratedInput`` contract), runs the user step on the
    flattened [b*k] batch (StaticInput contexts pre-expanded to beams),
    expands/selects with the fixed-width masked ``beam_search`` op, and
    REORDERS every user memory by the selected parents (``beam_gather``)
    — the decoder-state shuffling the reference performs on its
    dynamically expanded nets.  Parent pointers are stacked per step and
    backtracked once at the end (``beam_search_decode``).

    Returns the decoded token variable [b, beam_size, max_length]
    (everything after each hypothesis's first ``eos_id`` is padded with
    ``eos_id``); its ``_v1_outputs['scores']`` carries the final [b, k]
    log-prob scores (``get_output_layer``-accessible)."""
    from ..layers import control_flow as cf

    ins = input if isinstance(input, (list, tuple)) else [input]
    gens = [i for i in ins if isinstance(i, BaseGeneratedInput)]
    if len(gens) != 1:
        raise ValueError("beam_search needs exactly one GeneratedInput")
    g = gens[0]
    statics = [i for i in ins if isinstance(i, StaticInput)]
    if not statics:
        raise ValueError(
            "beam_search needs at least one StaticInput context (the "
            "encoded source) to size the decode batch")
    ref = statics[0].input
    k = int(beam_size)

    helper = LayerHelper("v1_beam_search", name=name)
    block = helper.main_program.current_block()

    emb_attr = (ParamAttr(name=g.embedding_name)
                if g.embedding_name else None)
    emb_w = helper.create_parameter(
        emb_attr, shape=[g.size, g.embedding_size], dtype="float32",
        suffix=None if g.embedding_name else "emb_w")

    # static contexts expand to the beam layout [b*k, ...] — INCLUDING
    # their sequence metadata, so masked sequence ops inside the step
    # (simple_attention etc.) still see lengths for ragged encoders
    expanded = {}
    for s in statics:
        ex = helper.create_tmp_variable(
            s.input.dtype, [s.input.shape[0]] + list(s.input.shape[1:]))
        helper.append_op(
            type="beam_expand", inputs={"X": [s.input.name]},
            outputs={"Out": [ex.name]}, attrs={"beam_size": k})
        if getattr(s.input, "lod_level", 0) > 0:
            exl = helper.create_tmp_variable(
                "int32", [s.input.shape[0]], stop_gradient=True)
            helper.append_op(
                type="beam_expand",
                inputs={"X": [s.input.length_var().name]},
                outputs={"Out": [exl.name]}, attrs={"beam_size": k})
            ex.lod_level = s.input.lod_level
            ex.block.vars[ex.name + "@LENGTH"] = exl
        expanded[id(s)] = ex

    ids0 = helper.create_tmp_variable("int32", [ref.shape[0], k],
                                      stop_gradient=True)
    scores0 = helper.create_tmp_variable("float32", [ref.shape[0], k],
                                         stop_gradient=True)
    helper.append_op(
        type="beam_init", inputs={"Ref": [ref.name]},
        outputs={"Ids": [ids0.name], "Scores": [scores0.name]},
        attrs={"beam_size": k, "bos_id": int(bos_id)})
    # dummy scanned input drives the fixed-length loop
    ticks = helper.create_tmp_variable("float32",
                                       [ref.shape[0], int(max_length)],
                                       stop_gradient=True)
    helper.append_op(
        type="fill_constant_batch_size_like", inputs={"Input": [ref.name]},
        outputs={"Out": [ticks.name]},
        attrs={"shape": (1, int(max_length)), "dtype": "float32",
               "value": 0.0, "input_dim_idx": 0, "output_dim_idx": 0})

    rnn = cf.StaticRNN(name=name)
    ctx = _V1RnnCtx(rnn, block, expanded[id(statics[0])])
    ctx.beam_k = k  # memory(boot_layer=...) must expand boots to beams
    _RNN_STACK.append(ctx)
    try:
        with rnn.step():
            rnn.step_input(ticks)
            cur_ids = rnn.memory(ids0)
            cur_scores = rnn.memory(scores0)
            sub = rnn._sub
            flat_ids = _tensor.reshape(cur_ids, [-1, 1])
            emb = sub.create_var(
                name=unique_name.generate("beam_emb"), dtype="float32",
                shape=[None, g.embedding_size])
            sub.append_op(
                type="lookup_table",
                inputs={"W": [emb_w.name], "Ids": [flat_ids.name]},
                outputs={"Out": [emb.name]}, attrs={"padding_idx": -1})
            emb.shape = (flat_ids.shape[0], g.embedding_size)

            step_args = []
            for i in ins:
                if isinstance(i, BaseGeneratedInput):
                    step_args.append(emb)
                elif isinstance(i, StaticInput):
                    step_args.append(expanded[id(i)])
                else:
                    raise ValueError(
                        "beam_search inputs must be GeneratedInput or "
                        "StaticInput")
            probs = step(*step_args)
            probs = probs if not isinstance(probs, (list, tuple)) \
                else probs[0]
            logp = layers.log(probs)
            logp3 = _tensor.reshape(logp, [-1, k, int(g.size)])
            sel_ids = sub.create_var(
                name=unique_name.generate("beam_ids"), dtype="int32",
                shape=list(cur_ids.shape))
            sel_scores = sub.create_var(
                name=unique_name.generate("beam_scores"), dtype="float32",
                shape=list(cur_scores.shape))
            parent = sub.create_var(
                name=unique_name.generate("beam_parent"), dtype="int32",
                shape=list(cur_ids.shape))
            sub.append_op(
                type="beam_search",
                inputs={"PreIds": [cur_ids.name],
                        "PreScores": [cur_scores.name],
                        "Scores": [logp3.name]},
                outputs={"SelectedIds": [sel_ids.name],
                         "SelectedScores": [sel_scores.name],
                         "ParentIdx": [parent.name]},
                attrs={"beam_size": k, "end_id": int(eos_id)})
            # user memories follow their selected parent beams; unlike
            # recurrent_group there is NO single-memory fallback — the
            # step's return value is the token distribution, never a
            # state, so an unnamed memory is always a config error
            for mem, mname in ctx.mems:
                target = ctx.named.get(mname)
                if target is None:
                    raise ValueError(
                        f"memory(name={mname!r}) inside beam_search has "
                        f"no same-named step layer; name the layer that "
                        f"produces the memory's next value")
                moved = sub.create_var(
                    name=unique_name.generate("beam_mem"),
                    dtype=target.dtype, shape=list(target.shape))
                sub.append_op(
                    type="beam_gather",
                    inputs={"X": [target.name], "Parent": [parent.name]},
                    outputs={"Out": [moved.name]})
                rnn.update_memory(mem, moved)
            rnn.update_memory(cur_ids, sel_ids)
            rnn.update_memory(cur_scores, sel_scores)
            rnn.step_output(sel_ids)
            rnn.step_output(parent)
            rnn.step_output(sel_scores)
    finally:
        _RNN_STACK.pop()
    ids_s, parent_s, scores_s = rnn()   # each [b, T, k]

    def _tbk(x):
        out = helper.create_tmp_variable(x.dtype, [x.shape[1], x.shape[0],
                                                   x.shape[2]])
        helper.append_op(type="transpose", inputs={"X": [x.name]},
                         outputs={"Out": [out.name]},
                         attrs={"axis": (1, 0, 2)})
        return out

    sent = helper.create_tmp_variable(
        "int32", [ref.shape[0], k, int(max_length)], stop_gradient=True)
    sent_scores = helper.create_tmp_variable(
        "float32", [ref.shape[0], k], stop_gradient=True)
    helper.append_op(
        type="beam_search_decode",
        inputs={"Ids": [_tbk(ids_s).name],
                "ParentIdx": [_tbk(parent_s).name],
                "Scores": [_tbk(scores_s).name]},
        outputs={"SentenceIds": [sent.name],
                 "SentenceScores": [sent_scores.name]},
        attrs={"end_id": int(eos_id)})
    sent._v1_outputs = {"scores": sent_scores}
    _register_name(sent, name)
    return sent


def eos_layer(input, eos_id, name=None, **_):
    """1.0 where the id equals eos_id (reference EosIdCheckLayer)."""
    const = _tensor.fill_constant_batch_size_like(
        input, shape=[1] * len(input.shape), dtype=input.dtype,
        value=float(eos_id))
    out = layers.equal(input, const)
    _register_name(out, name)
    return out


def maxid_layer(input, name=None, **_):
    out = _tensor.argmax(input, axis=-1)
    _register_name(out, name)
    return out


def sampling_id_layer(input, name=None, **_):
    helper = LayerHelper("sampling_id", name=name)
    out = helper.create_tmp_variable("int32", list(input.shape[:-1]),
                                     stop_gradient=True)
    helper.append_op(type="sampling_id", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]})
    _register_name(out, name)
    return out


# ---------------------------------------------------------- simple layers
def repeat_layer(input, num_repeats, **_):
    """Tile the feature vector num_repeats times (reference FeatureMapExpand
    / repeat_layer: out size = in size * num_repeats)."""
    return _tensor.expand(input, [1] * (len(input.shape) - 1) + [num_repeats])


def seq_reshape_layer(input, reshape_size, **_):
    return layers.sequence_reshape(input, new_dim=reshape_size)


def seq_concat_layer(a, b, **_):
    """Concatenate two sequences per batch item in time (reference
    SequenceConcatLayer; result lengths add)."""
    from ..layers.nn import _seq_inputs

    helper = LayerHelper("seq_concat")
    t_total = a.shape[1] + b.shape[1]
    out = helper.create_tmp_variable(
        a.dtype, [a.shape[0], t_total] + list(a.shape[2:]), lod_level=1)
    inputs = {"X": [a.name, b.name]}
    lens = []
    for v in (a, b):
        li = {}
        _seq_inputs(li, v)
        lens.extend(li.get("Length", []))
    if len(lens) == 2:
        inputs["Length"] = lens
    helper.append_op(
        type="sequence_concat", inputs=inputs,
        outputs={"Out": [out.name],
                 "OutLength": [out.length_var().name]},
        attrs={"axis": 1})
    return out


def seq_slice_layer(input, starts, ends, **_):
    """v1 contract: [starts, ends) positions -> lengths = ends - starts
    for the sequence_slice op (which takes Offset + SeqLength)."""
    helper = LayerHelper("seq_slice")
    lengths = layers.elementwise_sub(ends, starts)
    out = helper.create_tmp_variable(input.dtype, list(input.shape))
    ln = helper.create_tmp_variable("int32", [input.shape[0]],
                                    stop_gradient=True)
    helper.append_op(
        type="sequence_slice",
        inputs={"X": [input.name], "Offset": [starts.name],
                "SeqLength": [lengths.name]},
        outputs={"Out": [out.name], "OutLength": [ln.name]},
    )
    return out


def sub_seq_layer(input, offsets, sizes, **_):
    return seq_slice_layer(input, offsets, sizes)


def expand_layer(input, expand_as, expand_level=None, **_):
    return layers.sequence_expand(input, expand_as)


def l2_distance_layer(x, y, **_):
    d = layers.elementwise_sub(x, y)
    return layers.sqrt(layers.reduce_sum(layers.square(d), dim=1,
                                         keep_dim=True))


def power_layer(input, other=None, **_):
    """out = other ^ input-per-sample-exponent (reference PowerLayer: the
    FIRST input is the per-sample power [b,1], the second the data)."""
    if isinstance(input, (list, tuple)):
        p, x = input
    else:
        p, x = input, other
    return layers.elementwise_pow(x, p)


def interpolation_layer(input, weight=None, **_):
    """out = w*a + (1-w)*b, per-sample scalar w (reference
    InterpolationLayer; v1 passes [w, a, b] as inputs)."""
    if isinstance(input, (list, tuple)) and len(input) == 3:
        w, a, b = input
    else:
        w, (a, b) = weight, input
    wa = layers.elementwise_mul(a, w)
    one_minus = layers.scale(w, scale=-1.0, bias=1.0)
    wb = layers.elementwise_mul(b, one_minus)
    return layers.elementwise_add(wa, wb)


def bilinear_interp_layer(input, out_size_x, out_size_y, **_):
    helper = LayerHelper("bilinear_interp")
    b, c = input.shape[0], input.shape[1]
    out = helper.create_tmp_variable(
        input.dtype, [b, c, out_size_y, out_size_x])
    helper.append_op(
        type="bilinear_interp", inputs={"X": [input.name]},
        outputs={"Out": [out.name]},
        attrs={"out_h": out_size_y, "out_w": out_size_x},
    )
    return out


def sum_to_one_norm_layer(input, **_):
    s = layers.reduce_sum(input, dim=1, keep_dim=True)
    return layers.elementwise_div(input, s)


def row_l2_norm_layer(input, **_):
    return layers.l2_normalize(input, axis=1)


def conv_shift_layer(a, b, **_):
    helper = LayerHelper("conv_shift")
    out = helper.create_tmp_variable(a.dtype, list(a.shape))
    helper.append_op(type="conv_shift",
                     inputs={"X": [a.name], "Y": [b.name]},
                     outputs={"Out": [out.name]})
    return out


def tensor_layer(a, b, size, act=None, param_attr=None, bias_attr=None,
                 **_):
    out = layers.bilinear_tensor_product(a, b, size, param_attr=param_attr,
                                         bias_attr=bias_attr)
    return _v1._apply_act(out, _v1._act(act))


def selective_fc_layer(input, size, select=None, act=None, param_attr=None,
                       bias_attr=None, **_):
    out = layers.selective_fc(input, size=size, select=select,
                              param_attr=param_attr, bias_attr=bias_attr)
    return _v1._apply_act(out, _v1._act(act, "tanh"))


def linear_comb_layer(weights, vectors, size, **_):
    """out[b, d] = sum_j w[b, j] * v[b, j*d : (j+1)*d] (reference
    LinearCombLayer / convex_comb_layer)."""
    m = weights.shape[-1]
    v3 = _tensor.reshape(vectors, [vectors.shape[0], m, size])
    w3 = _tensor.reshape(weights, [weights.shape[0], m, 1])
    prod = layers.elementwise_mul(v3, w3)
    return layers.reduce_sum(prod, dim=1)


convex_comb_layer = linear_comb_layer


def dot_prod_layer(a, b, **_):
    helper = LayerHelper("dot")
    out = helper.create_tmp_variable(a.dtype, [a.shape[0], 1])
    helper.append_op(type="dot", inputs={"X": [a.name], "Y": [b.name]},
                     outputs={"Out": [out.name]})
    return out


def out_prod_layer(a, b, **_):
    a3 = _tensor.reshape(a, [a.shape[0], a.shape[-1], 1])
    b3 = _tensor.reshape(b, [b.shape[0], 1, b.shape[-1]])
    prod = layers.matmul(a3, b3)
    return _tensor.reshape(prod, [a.shape[0], a.shape[-1] * b.shape[-1]])


def print_layer(input, message="", **_):
    ins = input if isinstance(input, (list, tuple)) else [input]
    helper = LayerHelper("print")
    for v in ins:
        helper.append_op(type="print", inputs={"In": [v.name]},
                         outputs={}, attrs={"message": message})
    return ins[0] if len(ins) == 1 else list(ins)


printer_layer = print_layer


def priorbox_layer(input, image, min_size, max_size=(), aspect_ratio=(),
                   variance=(0.1, 0.1, 0.2, 0.2), **_):
    """Prior boxes flattened to the [2, P, 4] boxes+variances form every
    downstream consumer (multibox_loss_layer / detection_output_layer)
    expects."""
    from ..layers import detection as _det

    boxes, var = _det.prior_box(
        input, image, min_sizes=list(min_size),
        max_sizes=list(max_size or []),
        aspect_ratios=list(aspect_ratio) or [1.0],
        variances=list(variance))
    n = boxes.shape[0] * boxes.shape[1] * boxes.shape[2]
    return _tensor.concat([
        _tensor.reshape(_tensor.reshape(boxes, [n, 4]), [1, n, 4]),
        _tensor.reshape(_tensor.reshape(var, [n, 4]), [1, n, 4]),
    ], axis=0)


def cross_channel_norm_layer(input, **_):
    return layers.l2_normalize(input, axis=1)


def multibox_loss_layer(input_loc, input_conf, priorbox, label_box,
                        label_cls, overlap_threshold=0.5,
                        neg_pos_ratio=3.0, background_id=0, **_):
    from ..layers import detection as _det

    loss = _det.multibox_loss(
        input_loc, input_conf, priorbox, label_box, label_cls,
        overlap_threshold=overlap_threshold, neg_pos_ratio=neg_pos_ratio,
        background_label=background_id)
    return layers.mean(loss)


def detection_output_layer(input_loc, input_conf, priorbox,
                           nms_threshold=0.45, nms_top_k=400,
                           keep_top_k=200, confidence_threshold=0.01,
                           background_id=0, **_):
    from ..layers import detection as _det

    return _det.detection_output(
        input_loc, input_conf, priorbox, background_label=background_id,
        nms_threshold=nms_threshold, nms_top_k=nms_top_k,
        keep_top_k=keep_top_k, score_threshold=confidence_threshold)


def roi_pool_layer(input, rois, pooled_width, pooled_height,
                   spatial_scale=1.0, **_):
    helper = LayerHelper("roi_pool")
    c = input.shape[1]
    out = helper.create_tmp_variable(
        input.dtype, [rois.shape[0], c, pooled_height, pooled_width])
    argmax = helper.create_tmp_variable(
        "int64", [rois.shape[0], c, pooled_height, pooled_width],
        stop_gradient=True)
    helper.append_op(
        type="roi_pool",
        inputs={"X": [input.name], "ROIs": [rois.name]},
        outputs={"Out": [out.name], "Argmax": [argmax.name]},
        attrs={"pooled_height": pooled_height,
               "pooled_width": pooled_width,
               "spatial_scale": spatial_scale},
    )
    return out


def spp_layer(input, pyramid_height, pool_type=None, **_):
    helper = LayerHelper("spp")
    c = input.shape[1]
    n_bins = sum(4 ** i for i in range(pyramid_height))
    out = helper.create_tmp_variable(input.dtype,
                                     [input.shape[0], c * n_bins])
    helper.append_op(
        type="spp", inputs={"X": [input.name]},
        outputs={"Out": [out.name]},
        attrs={"pyramid_height": pyramid_height,
               "pooling_type": _v1._pool_name(pool_type)},
    )
    return out


def pad_layer(input, pad_c=(0, 0), pad_h=(0, 0), pad_w=(0, 0), **_):
    pads = [0, 0, pad_c[0], pad_c[1], pad_h[0], pad_h[1],
            pad_w[0], pad_w[1]]
    return _tensor.pad(input, paddings=pads)


def multiplex_layer(input, **_):
    index, *candidates = input
    return layers.multiplex(candidates, index)


def row_conv_layer(input, context_size, act=None, param_attr=None, **_):
    out = layers.row_conv(input, future_context_size=context_size - 1,
                          param_attr=param_attr)
    return _v1._apply_act(out, _v1._act(act))


def prelu_layer(input, param_attr=None, **_):
    return layers.prelu(input, param_attr=param_attr)


def switch_order_layer(input, reshape_axis=None, **_):
    """NCHW <-> NHWC flip (reference SwitchOrderLayer)."""
    perm = [0, 2, 3, 1] if reshape_axis in (None, 3) else [0, 3, 1, 2]
    return _tensor.transpose(input, perm)


def gated_unit_layer(input, size, act=None, gate_param_attr=None,
                     param_attr=None, **_):
    value = layers.fc(input, size, param_attr=param_attr)
    value = _v1._apply_act(value, _v1._act(act))
    gate = layers.fc(input, size, param_attr=gate_param_attr, act="sigmoid")
    return layers.elementwise_mul(value, gate)


def crop_layer(input, offset, shape=None, axis=2, **_):
    """v1 crop: offset/shape apply FROM `axis` (default 2 = spatial dims);
    leading dims pass through untouched."""
    nd = len(input.shape)
    full_off = [0] * axis + list(offset)
    full_shape = [-1] * axis + list(
        shape if shape is not None
        else [input.shape[axis + i] - o for i, o in enumerate(offset)])
    full_off += [0] * (nd - len(full_off))
    full_shape += [-1] * (nd - len(full_shape))
    return _tensor.crop(input, shape=full_shape, offsets=full_off)


def clip_layer(input, min, max, **_):
    return layers.clip(input, min=min, max=max)


def kmax_seq_score_layer(input, beam_size=1, **_):
    scores = input if len(input.shape) == 2 else \
        _tensor.reshape(input, [input.shape[0], -1])
    _vals, idx = layers.topk(scores, k=beam_size)
    return idx


def img_pool3d_layer(input, pool_size, stride=1, padding=0, pool_type=None,
                     **_):
    return layers.pool3d(input, pool_size=pool_size, pool_stride=stride,
                         pool_padding=padding,
                         pool_type=_v1._pool_name(pool_type))


def img_conv3d_layer(input, filter_size, num_filters, stride=1, padding=0,
                     act=None, param_attr=None, bias_attr=None, **_):
    return layers.conv3d(input, num_filters=num_filters,
                         filter_size=filter_size, stride=stride,
                         padding=padding, param_attr=param_attr,
                         bias_attr=bias_attr, act=_v1._act(act, "relu"))


def scale_shift_layer(input, param_attr=None, bias_attr=None, **_):
    helper = LayerHelper("scale_shift")
    w = helper.create_parameter(param_attr, shape=[1], dtype=input.dtype)
    out = layers.elementwise_mul(input, w)
    if bias_attr is not False:
        b = helper.create_parameter(
            _v1_param_attr_or_default(bias_attr), shape=[1],
            dtype=input.dtype, suffix="b")
        out = layers.elementwise_add(out, b)
    return out


def _v1_param_attr_or_default(attr):
    from ..param_attr import ParamAttr as _PA

    return _PA.to_attr(attr) or _PA()


def resize_layer(input, size, **_):
    return _tensor.reshape(input, [input.shape[0], size])


def scale_sub_region_layer(input, indices, value=1.0, **_):
    helper = LayerHelper("scale_sub_region")
    out = helper.create_tmp_variable(input.dtype, list(input.shape))
    helper.append_op(
        type="scale_sub_region",
        inputs={"X": [input.name], "Indices": [indices.name]},
        outputs={"Out": [out.name]}, attrs={"value": float(value)},
    )
    return out


def factorization_machine(input, factor_size, param_attr=None, **_):
    """Second-order FM interactions (reference FactorizationMachineLayer):
    0.5 * sum_f [ (x·V_f)^2 - (x^2)·(V_f^2) ]."""
    helper = LayerHelper("fm")
    d = input.shape[-1]
    v = helper.create_parameter(param_attr, shape=[d, factor_size],
                                dtype=input.dtype)
    xv = layers.matmul(input, v)
    sq_of_sum = layers.square(xv)
    x2 = layers.square(input)
    v2 = layers.square(v)
    sum_of_sq = layers.matmul(x2, v2)
    diff = layers.elementwise_sub(sq_of_sum, sum_of_sq)
    return layers.scale(layers.reduce_sum(diff, dim=1, keep_dim=True),
                        scale=0.5)


def maxout_layer(input, groups, **_):
    helper = LayerHelper("maxout")
    c = input.shape[1]
    out = helper.create_tmp_variable(
        input.dtype, [input.shape[0], c // groups] + list(input.shape[2:]))
    helper.append_op(type="maxout", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"groups": groups})
    return out


def block_expand_layer(input, block_x, block_y, stride_x=1, stride_y=1,
                       padding_x=0, padding_y=0, **_):
    return layers.im2sequence(
        input, filter_size=(block_y, block_x),
        stride=(stride_y, stride_x),
        padding=(padding_y, padding_x, padding_y, padding_x))


def huber_classification_cost(input, label, **_):
    """Two-class huber (reference HuberTwoClassification =
    modified_huber_loss_op semantics)."""
    helper = LayerHelper("mod_huber")
    out = helper.create_tmp_variable(input.dtype, list(input.shape))
    inter = helper.create_tmp_variable(input.dtype, list(input.shape),
                                       stop_gradient=True)
    helper.append_op(
        type="modified_huber_loss",
        inputs={"X": [input.name], "Y": [label.name]},
        outputs={"Out": [out.name], "IntermediateVal": [inter.name]},
    )
    return layers.mean(out)


def sub_nested_seq_layer(input, selected_indices, name=None, **_):
    """Select sub-sequences of a nested input by per-sample indices
    (reference SubNestedSequenceLayer.cpp) — lowers to the native
    sub_nested_seq op."""
    out = layers.sub_nested_seq(input, selected_indices)
    _register_name(out, name)
    return out


def cross_entropy_over_beam(input, name=None, **_):
    """Learning-to-search cost over beam expansions (reference
    ``gserver/layers/CrossEntropyOverBeam.cpp``, DSL
    ``trainer_config_helpers/layers.py:6386``): softmax over the summed
    scores of every complete candidate path through the expansions, NLL
    of the gold path; when the gold falls off the beam at step t the
    cost is over the beam at t with the gold appended as an extra path.
    Lowers to the native ``cross_entropy_over_beam`` op
    (``ops/beam_ce_ops.py``), which is the static-shape/jittable
    re-design of the reference's CPU-only per-sequence path loops."""
    if isinstance(input, BeamInput):
        input = [input]
    for ipt in input:
        if not isinstance(ipt, BeamInput):
            raise TypeError(
                "cross_entropy_over_beam input must be BeamInput objects")
    helper = LayerHelper("cross_entropy_over_beam", name=name)
    scores = [b.candidate_scores for b in input]
    ids = [b.selected_candidates for b in input]
    gold = [b.gold for b in input]
    batch = scores[0].shape[0]
    out = helper.create_tmp_variable("float32", [batch, 1])
    helper.append_op(
        type="cross_entropy_over_beam",
        inputs={"Scores": [s.name for s in scores],
                "Ids": [i.name for i in ids],
                "Gold": [g.name for g in gold]},
        outputs={"Out": [out.name]},
    )
    _register_name(out, name)
    return out


# ----------------------------------------------------- activations / attrs
class BaseActivation(_v1._Act):
    pass


SequenceSoftmaxActivation = _v1._act_cls("SequenceSoftmaxActivation",
                                         "sequence_softmax")
SqrtActivation = _v1._act_cls("SqrtActivation", "sqrt")
ReciprocalActivation = _v1._act_cls("ReciprocalActivation", "reciprocal")
SoftSignActivation = _v1._act_cls("SoftSignActivation", "softsign")


class HookAttr:
    """Parameter hooks (pruning etc.) — recorded, not executed; the
    reference applied them trainer-side."""

    def __init__(self, type=None, sparsity_ratio=None):
        self.type = type
        self.sparsity_ratio = sparsity_ratio


from ..param_attr import ParamAttr  # re-export: same role as v1 ParamAttr

ParameterAttribute = ParamAttr


class ExtraLayerAttribute:
    def __init__(self, error_clipping_threshold=None, drop_rate=None,
                 device=None):
        self.error_clipping_threshold = error_clipping_threshold
        self.drop_rate = drop_rate
        self.device = device


ExtraAttr = ExtraLayerAttribute


# ---------------------------------------------------------------- poolings
class BasePoolingType:
    name = None


class MaxWithMaskPooling(BasePoolingType):
    name = "max"


class CudnnMaxPooling(BasePoolingType):
    name = "max"


class CudnnAvgPooling(BasePoolingType):
    name = "avg"


class CudnnAvgInclPadPooling(BasePoolingType):
    name = "avg"


class SquareRootNPooling(BasePoolingType):
    name = "sqrt"


# -------------------------------------------------------------- optimizers
class Optimizer:
    pass


class BaseSGDOptimizer(Optimizer):
    pass


def AdamaxOptimizer(beta1=0.9, beta2=0.999):
    return ("adamax", {"beta1": beta1, "beta2": beta2})


def DecayedAdaGradOptimizer(rho=0.95, epsilon=1e-6):
    return ("decayed_adagrad", {"decay": rho, "epsilon": epsilon})


class BaseRegularization:
    pass


def ModelAverage(average_window, max_average_window=None,
                 average_decay=None, **_):
    """v1 windowed parameter averaging -> the EMA-based ModelAverage.
    A window covering a fraction w of recent steps corresponds roughly to
    decay = 1 - 1/(w * max_window) over max_average_window steps."""
    if average_decay is None:
        horizon = max(2.0, float(average_window)
                      * float(max_average_window or 10000))
        average_decay = 1.0 - 1.0 / horizon
    return _opt.ModelAverage(average_decay=average_decay)


# -------------------------------------------------------------- evaluators
def evaluator_base(input, type, label=None, weight=None, name=None,
                   top_k=None, chunk_scheme=None, num_chunk_types=None,
                   excluded_chunk_types=None, positive_label=None,
                   query_id=None, **_):
    """Generic evaluator dispatcher (reference
    ``trainer_config_helpers/evaluators.py:71``: every ``*_evaluator``
    funnels into evaluator_base with a ``type`` string).  Maps the type
    to the corresponding in-program metric layer; reference evaluators
    attached to the config proto, here they are ordinary fetchable
    metric variables."""
    t = str(type)
    if t in ("classification_error", "classification_error_printer"):
        return layers.accuracy(input=input, label=label,
                               k=top_k or 1)
    if t == "auc":
        return layers.auc(input=input, label=label)
    if t in ("chunk", "chunk_evaluator"):
        return layers.chunk_eval(
            input=input, label=label, chunk_scheme=chunk_scheme or "IOB",
            num_chunk_types=num_chunk_types or 1,
            excluded_chunk_types=excluded_chunk_types)
    if t in ("precision_recall", "precision_recall_evaluator"):
        return precision_recall_evaluator(input, label,
                                          positive_label=positive_label)
    if t in ("pnpair", "pnpair_evaluator"):
        if query_id is None:
            raise ValueError("pnpair evaluator needs query_id")
        return pnpair_evaluator(input, label, query_id, weight=weight)
    if t in ("sum", "sum_evaluator"):
        return sum_evaluator(input)
    if t in ("column_sum", "column_sum_evaluator", "last-column-sum"):
        return column_sum_evaluator(input)
    if t in ("ctc_edit_distance", "ctc_error", "ctc_error_evaluator"):
        return ctc_error_evaluator(input, label)
    if t in ("last-column-auc",):
        return layers.auc(input=input, label=label)
    if t in ("max_id_printer", "maxid_printer"):
        return maxid_printer_evaluator(input)
    if t in ("max_frame_printer", "maxframe_printer"):
        return maxframe_printer_evaluator(input)
    # printer family: evaluation-time inspection — fetch the value
    # itself.  Only types whose reference semantics ARE "print the
    # input" may fall through; gradient_printer attaches to gradients
    # (reference evaluators.py:630), which a fetch-the-input shim would
    # silently misrepresent — reject it instead.
    if t == "gradient_printer":
        raise ValueError(
            "gradient_printer attaches to parameter gradients; fetch "
            "<param>@GRAD explicitly instead of using the evaluator shim")
    if t in ("value_printer", "seq_text_printer"):
        return input
    raise ValueError(f"unknown evaluator type {type!r}")


def classification_error_evaluator(input, label, **_):
    return layers.accuracy(input=input, label=label)


def auc_evaluator(input, label, **_):
    return layers.auc(input=input, label=label)


def pnpair_evaluator(input, label, query_id, **_):
    helper = LayerHelper("pnpair")
    outs = {
        n: helper.create_tmp_variable("float32", [1], stop_gradient=True)
        for n in ("PositivePair", "NegativePair", "NeutralPair")
    }
    helper.append_op(
        type="positive_negative_pair",
        inputs={"Score": [input.name], "Label": [label.name],
                "QueryID": [query_id.name]},
        outputs={k: [v.name] for k, v in outs.items()},
    )
    return (outs["PositivePair"], outs["NegativePair"],
            outs["NeutralPair"])


def precision_recall_evaluator(input, label, positive_label=None, **_):
    helper = LayerHelper("precision_recall")
    idx = _tensor.argmax(input, axis=-1)
    batch = helper.create_tmp_variable("float32", [6], stop_gradient=True)
    accum = helper.create_tmp_variable("float32", [6], stop_gradient=True)
    states = helper.create_tmp_variable(
        "float32", [input.shape[-1], 4], stop_gradient=True)
    helper.append_op(
        type="precision_recall",
        inputs={"Indices": [idx.name], "Labels": [label.name]},
        outputs={"BatchMetrics": [batch.name],
                 "AccumMetrics": [accum.name],
                 "AccumStatesInfo": [states.name]},
        attrs={"class_number": input.shape[-1]},
    )
    return batch


def ctc_error_evaluator(input, label, **_):
    decoded = layers.ctc_greedy_decoder(input,
                                        blank=input.shape[-1] - 1)
    dist, _ = layers.edit_distance(decoded, label, normalized=True)
    return dist


def chunk_evaluator(input, label, chunk_scheme="IOB", num_chunk_types=1,
                    excluded_chunk_types=None, **_):
    return layers.chunk_eval(
        input, label, chunk_scheme=chunk_scheme,
        num_chunk_types=num_chunk_types,
        excluded_chunk_types=excluded_chunk_types)


def sum_evaluator(input, **_):
    return layers.reduce_sum(input)


def column_sum_evaluator(input, **_):
    return layers.reduce_sum(input, dim=0)


def value_printer_evaluator(input, **_):
    return print_layer(input, message="[value]")


def gradient_printer_evaluator(input, **_):
    # gradients are jax.grad internals here; print the forward value with
    # a marker (the reference printed param grads trainer-side)
    return print_layer(input, message="[grad-of]")


def maxid_printer_evaluator(input, **_):
    return print_layer(maxid_layer(input), message="[maxid]")


def maxframe_printer_evaluator(input, **_):
    return print_layer(maxid_layer(input), message="[maxframe]")


def seqtext_printer_evaluator(input, result_file=None, **_):
    return print_layer(input, message="[seqtext]")


def classification_error_printer_evaluator(input, label, **_):
    acc = layers.accuracy(input=input, label=label)
    return print_layer(acc, message="[classification_error]")


def detection_map_evaluator(input, label, overlap_threshold=0.5,
                            ap_type="integral", evaluate_difficult=False,
                            **_):
    """Host-side DetectionMAP (fetch detections, update per batch)."""
    return _eval.DetectionMAP(overlap_threshold=overlap_threshold,
                              ap_version=ap_type,
                              evaluate_difficult=evaluate_difficult)


# ---------------------------------------------------------------- networks
def sequence_conv_pool(input, context_len, hidden_size, **_):
    return _nets.sequence_conv_pool(input, num_filters=hidden_size,
                                    filter_size=context_len)


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         pool_stride=1, act=None, **_):
    return _nets.simple_img_conv_pool(
        input, num_filters=num_filters, filter_size=filter_size,
        pool_size=pool_size, pool_stride=pool_stride,
        act=_v1._act(act, "relu"))


def img_conv_bn_pool(input, filter_size, num_filters, pool_size,
                     pool_stride=1, act=None, **_):
    return _nets.img_conv_bn_pool(
        input, num_filters=num_filters, filter_size=filter_size,
        pool_size=pool_size, pool_stride=pool_stride,
        act=_v1._act(act, "relu"))


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, pool_stride=2,
                   conv_with_batchnorm=False, **_):
    return _nets.img_conv_group(
        input, conv_num_filter=conv_num_filter, pool_size=pool_size,
        conv_padding=conv_padding, conv_filter_size=conv_filter_size,
        conv_act=_v1._act(conv_act, "relu"), pool_stride=pool_stride,
        conv_with_batchnorm=conv_with_batchnorm)


def img_separable_conv(input, num_channels, num_out_channels, filter_size,
                       stride=1, padding=0, act=None, **_):
    return _nets.img_separable_conv(
        input, num_channels=num_channels,
        num_out_channels=num_out_channels, filter_size=filter_size,
        stride=stride, padding=padding, act=_v1._act(act, "relu"))


def lstmemory_unit(input, size, name=None, act=None, gate_act=None,
                   state_act=None, **_):
    """One LSTM step — call INSIDE recurrent_group with the step input
    (reference networks.py lstmemory_unit): projects [x_t, out_mem] to
    4*size gates, applies lstm_step_layer, links the cell memory."""
    name = name or unique_name.generate("lstm_unit")
    out_mem = memory(name=name, size=size)
    cell_mem = memory(name=name + "_cell", size=size)
    proj = mixed_layer(
        size=4 * size,
        input=[full_matrix_projection(input, 4 * size),
               full_matrix_projection(out_mem, 4 * size)])
    hidden = lstm_step_layer(proj, cell_mem, size=size, name=name)
    _register_name(get_output_layer(hidden, "state"), name + "_cell")
    return hidden


def lstmemory_group(input, size, reverse=False, **_):
    proj = layers.fc(input, size * 4, num_flatten_dims=2)
    layers.link_sequence(proj, input)
    hidden, _cell = layers.dynamic_lstm(proj, size=size * 4,
                                        is_reverse=reverse)
    return hidden


def gru_unit(input=None, size=None, name=None, **_):
    """One GRU step — call INSIDE recurrent_group with the step input
    (reference networks.py gru_unit): projects x_t to 3*size and applies
    gru_step_layer against the output memory."""
    name = name or unique_name.generate("gru_unit")
    mem = memory(name=name, size=size)
    proj = mixed_layer(
        size=3 * size,
        input=[full_matrix_projection(input, 3 * size)],
        bias_attr=False)
    out = gru_step_layer(proj, mem, size=size, name=name)
    return out


def gru_group(input, size, reverse=False, **_):
    proj = layers.fc(input, size * 3, num_flatten_dims=2)
    layers.link_sequence(proj, input)
    return layers.dynamic_gru(proj, size=size, is_reverse=reverse)


def simple_gru2(input, size, reverse=False, **_):
    return _v1.simple_gru(input, size, reverse=reverse)


def bidirectional_gru(input, size, return_concat=True, **_):
    return _nets.bidirectional_gru(input, size,
                                   return_concat=return_concat)


def bidirectional_lstm(input, size, return_concat=True, **_):
    return _nets.bidirectional_lstm(input, size,
                                    return_concat=return_concat)


def text_conv_pool(input, context_len, hidden_size, **_):
    return _nets.sequence_conv_pool(input, num_filters=hidden_size,
                                    filter_size=context_len)


def simple_attention(encoded_sequence, encoded_proj, decoder_state, **_):
    return _nets.simple_attention(encoded_sequence, encoded_proj,
                                  decoder_state,
                                  decoder_size=decoder_state.shape[-1])


def dot_product_attention(attended_sequence, attending_sequence=None,
                          transform_param_attr=None, **kw):
    q = kw.get("queries", attending_sequence)
    k = kw.get("keys", attended_sequence)
    v = kw.get("values", attended_sequence)
    return _nets.dot_product_attention(q, k, v)


def multi_head_attention(query, key, value, head_num, **_):
    return layers.multi_head_attention(query, key, value,
                                       d_model=query.shape[-1],
                                       n_head=head_num)


def vgg_16_network(input_image, num_channels, num_classes=1000, **_):
    """VGG-16 (reference networks.py vgg_16_network)."""
    tmp = _nets.img_conv_group(
        input_image, conv_num_filter=[64, 64], pool_size=2,
        conv_filter_size=3, conv_act="relu", pool_stride=2,
        conv_with_batchnorm=True)
    tmp = _nets.img_conv_group(
        tmp, conv_num_filter=[128, 128], pool_size=2, conv_filter_size=3,
        conv_act="relu", pool_stride=2, conv_with_batchnorm=True)
    tmp = _nets.img_conv_group(
        tmp, conv_num_filter=[256, 256, 256], pool_size=2,
        conv_filter_size=3, conv_act="relu", pool_stride=2,
        conv_with_batchnorm=True)
    tmp = _nets.img_conv_group(
        tmp, conv_num_filter=[512, 512, 512], pool_size=2,
        conv_filter_size=3, conv_act="relu", pool_stride=2,
        conv_with_batchnorm=True)
    tmp = _nets.img_conv_group(
        tmp, conv_num_filter=[512, 512, 512], pool_size=2,
        conv_filter_size=3, conv_act="relu", pool_stride=2,
        conv_with_batchnorm=True)
    tmp = layers.fc(tmp, 4096, act="relu")
    tmp = layers.dropout(tmp, dropout_prob=0.5)
    tmp = layers.fc(tmp, 4096, act="relu")
    tmp = layers.dropout(tmp, dropout_prob=0.5)
    return layers.fc(tmp, num_classes, act="softmax")


def small_vgg(input_image, num_channels, num_classes=10, **_):
    tmp = _nets.img_conv_group(
        input_image, conv_num_filter=[64, 64], pool_size=2,
        conv_filter_size=3, conv_act="relu", pool_stride=2,
        conv_with_batchnorm=True)
    tmp = _nets.img_conv_group(
        tmp, conv_num_filter=[128, 128], pool_size=2, conv_filter_size=3,
        conv_act="relu", pool_stride=2, conv_with_batchnorm=True)
    tmp = layers.dropout(tmp, dropout_prob=0.5)
    tmp = layers.fc(tmp, 512, act="relu")
    return layers.fc(tmp, num_classes, act="softmax")
